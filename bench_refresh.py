"""Refresh-vs-refactor benchmark: prints ONE JSON line, writes BENCH_REFRESH.json.

The ISSUE 2 claim measured, not asserted. Workload: a served system
drifts by a rank-k correction (A <- A + U V^H) before each solve — the
streaming/online traffic shape. Two ways to absorb each drift:

  refactor — materialize the drifted matrix and pay a full O(N^3)
             refactorization through the cached `FactorPlan` factor
             program, then solve (the only option before ISSUE 2).
  refresh  — `SolveSession.update(U, V, replace=True)`: O(N^2 k)
             Sherman-Morrison-Woodbury capacitance refresh against the
             resident base factors, then a corrected solve
             (`conflux_tpu.update`). Zero refactorizations, zero
             recompiles after the first round (asserted via the plan's
             trace counters).

Two legs ride by default: a single-system plan (N=1024, k=16 — the
ISSUE 2 acceptance shape) and a batched plan (B=32, N=256, k=16, the
bench_serve fleet shape; batched plans invert their triangular factors
at factor time, so the refactor leg pays that too — exactly what a
drifting fleet would pay). Headline value is refreshed drift+solve
rounds/s; `speedup_vs_refactor` is the ratio on identical work, and the
refreshed residuals are held within 10x of the full-refactor oracle's
(f32) — a throughput number from wrong answers is worthless.

`--smoke` shrinks to N=512, k=8, single leg, and exits nonzero unless
the refresh path actually beats the refactor path — the CI gate.

Runs on the CPU backend by default (reproducible anywhere, the tier-1
topology); amortization counters come from `profiler.serve_stats()`.
"""

import argparse
import json
import os
import time


def parse_args():
    ap = argparse.ArgumentParser("bench_refresh")
    ap.add_argument("-N", type=int, default=1024,
                    help="single-leg system size")
    ap.add_argument("-k", type=int, default=16, help="drift rank")
    ap.add_argument("-v", type=int, default=256,
                    help="single-leg tile size")
    ap.add_argument("--batch", type=int, default=32,
                    help="batched-leg fleet size (0 skips the leg)")
    ap.add_argument("--batch-n", type=int, default=256,
                    help="batched-leg system size")
    ap.add_argument("--batch-v", type=int, default=128,
                    help="batched-leg tile size")
    ap.add_argument("--rounds", type=int, default=8,
                    help="drift+solve rounds per workload")
    ap.add_argument("--reps", type=int, default=3,
                    help="timed repetitions per leg (mean reported)")
    ap.add_argument("--smoke", action="store_true",
                    help="CI gate: N=512 k=8 single leg, assert the "
                    "refresh path beats full refactor")
    ap.add_argument("--out", default=None,
                    help="JSON output path (default BENCH_REFRESH.json; "
                    "--smoke runs default to BENCH_REFRESH_smoke.json so "
                    "CI smoke numbers never clobber the committed "
                    "full-shape headline)")
    return ap.parse_args()


def main():
    args = parse_args()
    os.environ["JAX_PLATFORMS"] = "cpu"

    import numpy as np

    import jax
    import jax.numpy as jnp

    jax.config.update("jax_platforms", "cpu")

    from conflux_tpu import cache, profiler, serve
    from conflux_tpu.update import apply_update

    cache.enable_persistent_cache()
    profiler.clear()
    if args.out is None:
        args.out = ("BENCH_REFRESH_smoke.json" if args.smoke
                    else "BENCH_REFRESH.json")

    if args.smoke:
        args.N, args.k, args.v = 512, 8, 128
        args.batch, args.rounds, args.reps = 0, 4, 1

    rng = np.random.default_rng(0)

    def systems(shape_n, b=None):
        lead = () if b is None else (b,)
        A = (rng.standard_normal(lead + (shape_n, shape_n))
             / np.sqrt(shape_n)
             + 2.0 * np.eye(shape_n)).astype(np.float32)
        return A

    def drift(shape_n, k, b=None):
        lead = () if b is None else (b,)
        # scaled so the drifted systems stay well-conditioned (the same
        # matrix class as the base batch)
        U = (rng.standard_normal(lead + (shape_n, k))
             / np.sqrt(shape_n)).astype(np.float32)
        V = (rng.standard_normal(lead + (shape_n, k))
             / np.sqrt(shape_n)).astype(np.float32)
        return U, V

    def sync(x):
        return float(jnp.sum(x))

    def residuals(A_np, x, b_np):
        x64 = np.asarray(x, np.float64)
        A64, b64 = A_np.astype(np.float64), b_np.astype(np.float64)
        if A_np.ndim == 2:
            r = A64 @ x64 - b64
            return np.linalg.norm(r) / np.linalg.norm(b64)
        r = np.einsum("bij,bj->bi", A64, x64) - b64
        return float(np.max(np.linalg.norm(r, axis=1)
                            / np.linalg.norm(b64, axis=1)))

    apply_fn = jax.jit(apply_update)

    def run_leg(name, B, N, k, v):
        batched_leg = B > 0
        shape = (B, N, N) if batched_leg else (N, N)
        lead = B if batched_leg else None
        A = systems(N, lead)
        drifts = [drift(N, k, lead) for _ in range(args.rounds)]
        rhs = [rng.standard_normal(((B, N) if batched_leg else (N,)))
               .astype(np.float32) for _ in range(args.rounds)]
        Ad = jnp.asarray(A)
        drifts_d = [(jnp.asarray(U), jnp.asarray(V)) for U, V in drifts]
        rhs_d = [jnp.asarray(r) for r in rhs]

        plan = serve.FactorPlan.create(shape, jnp.float32, v=v)
        session = plan.factor(Ad)

        # ---- warm-up: compile both paths fully ----------------------- #
        session.update(*drifts_d[0], replace=True)
        sync(session.solve(rhs_d[0]))
        sync(plan.factor(apply_fn(Ad, *drifts_d[0])).solve(rhs_d[0]))
        traces = dict(plan.trace_counts)

        # ---- refresh leg: SMW update + corrected solve per round ----- #
        t_refresh = 0.0
        for _ in range(args.reps):
            t0 = time.perf_counter()
            for (U, V), bd in zip(drifts_d, rhs_d):
                session.update(U, V, replace=True)
                x_refresh = session.solve(bd)
            sync(x_refresh)
            t_refresh += time.perf_counter() - t0
        t_refresh /= args.reps
        assert plan.trace_counts == traces, \
            "refresh leg recompiled mid-workload"
        assert session.refactors == 0, "drift policy refactored in-bench"

        # ---- refactor leg: full factor per round through the plan ---- #
        t_refactor = 0.0
        for _ in range(args.reps):
            t0 = time.perf_counter()
            for (U, V), bd in zip(drifts_d, rhs_d):
                s = plan.factor(apply_fn(Ad, U, V))
                x_refactor = s.solve(bd)
            sync(x_refactor)
            t_refactor += time.perf_counter() - t0
        t_refactor /= args.reps
        assert plan.trace_counts == traces, \
            "refactor leg recompiled mid-workload"

        # ---- residual oracle: last round's drifted system ------------ #
        A_last = np.asarray(apply_fn(Ad, *drifts_d[-1]))
        res_refresh = residuals(A_last, x_refresh, rhs[-1])
        res_refactor = residuals(A_last, x_refactor, rhs[-1])
        bar = 10.0 * max(float(res_refactor), 1e-8)
        ok = bool(res_refresh <= bar)

        solves = args.rounds * (B if batched_leg else 1)
        return {
            "workload": (f"B={B or 1} N={N} k={k} v={v} "
                         f"rounds={args.rounds} f32"),
            "refresh_solves_per_s": round(solves / t_refresh, 2),
            "refactor_solves_per_s": round(solves / t_refactor, 2),
            "speedup_vs_refactor": round(t_refactor / t_refresh, 2),
            "refresh_round_ms": round(1e3 * t_refresh / args.rounds, 3),
            "refactor_round_ms": round(1e3 * t_refactor / args.rounds, 3),
            "residual_refresh": float(res_refresh),
            "residual_refactor_oracle": float(res_refactor),
            "residual_within_10x": ok,
        }

    legs = {"single": run_leg("single", 0, args.N, args.k, args.v)}
    if args.batch:
        legs["batched"] = run_leg("batched", args.batch, args.batch_n,
                                  args.k, args.batch_v)

    stats = profiler.serve_stats()
    out = {
        "metric": (f"refresh vs refactor N={args.N} k={args.k} "
                   f"({jax.devices()[0].platform} backend"
                   + (", smoke" if args.smoke else "") + ")"),
        "value": legs["single"]["speedup_vs_refactor"],
        "unit": "x refresh speedup over full refactor",
        **{f"{name}_{key}": val for name, leg in legs.items()
           for key, val in leg.items()},
        "serve_counters": {ph: stats[ph] for ph in profiler.SERVE_PHASES},
        "solves_per_factor": round(stats["solves_per_factor"], 2),
    }
    with open(args.out, "w") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    print(json.dumps(out))

    bad = [name for name, leg in legs.items()
           if not leg["residual_within_10x"]]
    if bad:
        raise SystemExit(
            f"refreshed residuals exceed 10x the refactor oracle: {bad}")
    if args.smoke and legs["single"]["speedup_vs_refactor"] <= 1.0:
        raise SystemExit(
            "smoke gate: refresh did not beat full refactor at "
            f"N={args.N}, k={args.k}")


if __name__ == "__main__":
    main()
