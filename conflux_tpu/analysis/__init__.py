"""conflint — the serve stack's static analysis layer (DESIGN.md §22).

The engine/serve/resilience/profiler modules are multithreaded and
their correctness rests on conventions nothing used to check: lock
guards, donation ownership, no-host-sync hot paths, future-resolution
ownership, bucket-keyed compilation, and BaseException discipline.
conflint mechanically re-proves them on every run:

    python -m conflux_tpu.analysis              # scan the repo, exit 1
    python -m conflux_tpu.analysis --json r.json  # + diffable report

`lockcheck` is the opt-in runtime half (lock-order cycles and
lock-held-across-dispatch): `scripts/soak.py --serve --lockcheck`.

Rules live in `conflux_tpu.analysis.rules`; this package never imports
jax, so the analyzer observes the tree without executing it.
"""

from conflux_tpu.analysis.core import (
    Finding,
    Report,
    RULE_IDS,
    run_paths,
    scan_source,
)
from conflux_tpu.analysis.rules import ALL_RULES

__all__ = ["Finding", "Report", "RULE_IDS", "ALL_RULES", "run_paths",
           "scan_source"]
