"""conflint rules: the serve stack's conventions as AST checks.

Each rule is grounded in a hazard class this repo has actually shipped
fixes for (CHANGES.md PRs 3-5); docs/DESIGN.md §22 carries the full
hazard → rule → example → fix/suppress table.

| rule          | enforces                                              |
|---------------|-------------------------------------------------------|
| CFX-LOCK      | `# guarded-by: L` attrs touched only under `with L`   |
| CFX-DONATE    | donated buffers never read after the donating dispatch|
| CFX-HOSTSYNC  | no host syncs inside `# hot-path` functions           |
| CFX-FUTURE    | `# futures-owner` except-edges resolve owned futures  |
| CFX-RECOMPILE | jit/bucket programs built once, at power-of-two keys  |
| CFX-EXCEPT    | InjectedKill (BaseException) reaches the watchdog     |

Every rule is conservative where static analysis runs out of road
(documented per rule); the runtime half (`analysis.lockcheck`) covers
the dynamic remainder (lock-order cycles, lock-held-across-dispatch).
"""

from __future__ import annotations

import ast

from conflux_tpu.analysis.core import Finding, SourceFile  # noqa: F401


def _is_pow2(n) -> bool:
    return isinstance(n, int) and not isinstance(n, bool) and n >= 1 \
        and not (n & (n - 1))


def _func_defs(tree):
    """Yield (node, class_name_or_None) once per def in the module
    (ast.walk visits methods again after their ClassDef — dedupe)."""
    seen: set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            for item in node.body:
                if isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    seen.add(id(item))
                    yield item, node.name
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and id(node) not in seen:
            yield node, None


def _self_attr(node) -> str | None:
    """'X' for an `self.X` attribute node, else None."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _call_name(call: ast.Call) -> str:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return ""


class Rule:
    id = "CFX-NONE"
    description = ""

    def check(self, sf: SourceFile, out: list) -> None:
        raise NotImplementedError


# --------------------------------------------------------------------- #
# CFX-LOCK — guarded attributes accessed only under their lock
# --------------------------------------------------------------------- #


class LockRule(Rule):
    """Attributes annotated `# guarded-by: L` on their initializing
    assignment must only be read/written inside `with self.L` (class
    attrs) / `with L` (module globals) — the discipline the engine's
    counters, the session's factor/drift state, and the profiler tables
    live by. `__init__` is exempt (construction happens-before
    publication); `# requires-lock: L` on a def marks helpers whose
    CALLERS hold the lock (trusted, not verified — keep such helpers
    private). Scope limit: only `self.`/module-global accesses are
    checked; cross-object accesses (`session._factors` from the engine)
    are the runtime harness's job."""

    id = "CFX-LOCK"
    description = "guarded-by attribute accessed outside its lock"

    def check(self, sf: SourceFile, out: list) -> None:
        # pass 1: collect guarded attrs per class, and module globals
        class_guards: dict[str, dict[str, str]] = {}
        mod_guards: dict[str, str] = {}
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef):
                guards: dict[str, str] = {}
                for sub in ast.walk(node):
                    if isinstance(sub, (ast.Assign, ast.AnnAssign)):
                        lock = sf.guard_on(sub)
                        if lock is None:
                            continue
                        targets = (sub.targets
                                   if isinstance(sub, ast.Assign)
                                   else [sub.target])
                        for t in targets:
                            attr = _self_attr(t)
                            if attr is not None:
                                guards[attr] = lock
                if guards:
                    class_guards[node.name] = guards
        for stmt in sf.tree.body:
            if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                lock = sf.guard_on(stmt)
                if lock is None:
                    continue
                targets = (stmt.targets if isinstance(stmt, ast.Assign)
                           else [stmt.target])
                for t in targets:
                    if isinstance(t, ast.Name):
                        mod_guards[t.id] = lock
        if not class_guards and not mod_guards:
            return

        # pass 2: walk every function with a held-locks context
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef):
                guards = class_guards.get(node.name)
                if not guards:
                    continue
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)) \
                            and item.name != "__init__":
                        self._walk(sf, out, item.body, guards, "self",
                                   sf.required_locks(item))
        if mod_guards:
            for stmt in sf.tree.body:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    self._walk(sf, out, stmt.body, mod_guards, None,
                               sf.required_locks(stmt))

    def _with_locks(self, stmt: ast.With, owner) -> set:
        got = set()
        for item in stmt.items:
            e = item.context_expr
            if owner == "self":
                attr = _self_attr(e)
                if attr is not None:
                    got.add(attr)
            elif isinstance(e, ast.Name):
                got.add(e.id)
        return got

    def _walk(self, sf, out, body, guards, owner, held) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # a closure may run on another thread — conservative:
                # it holds nothing (its own withs still count)
                self._walk(sf, out, stmt.body, guards, owner,
                           sf.required_locks(stmt))
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                inner = held | self._with_locks(stmt, owner)
                for item in stmt.items:
                    self._scan_expr(sf, out, item.context_expr, guards,
                                    owner, held)
                self._walk(sf, out, stmt.body, guards, owner, inner)
                continue
            # expressions of this statement (conditions included) run
            # under `held`; child statement lists recurse
            for field, value in ast.iter_fields(stmt):
                if isinstance(value, ast.expr):
                    self._scan_expr(sf, out, value, guards, owner, held)
                elif isinstance(value, list):
                    if value and isinstance(value[0], ast.stmt):
                        self._walk(sf, out, value, guards, owner, held)
                    else:
                        for v in value:
                            if isinstance(v, ast.expr):
                                self._scan_expr(sf, out, v, guards,
                                                owner, held)
                            elif isinstance(v, ast.excepthandler):
                                self._walk(sf, out, v.body, guards,
                                           owner, held)

    def _scan_expr(self, sf, out, expr, guards, owner, held) -> None:
        for node in ast.walk(expr):
            name = None
            if owner == "self":
                attr = _self_attr(node)
                if attr in guards:
                    name = attr
            elif isinstance(node, ast.Name) and node.id in guards:
                name = node.id
            if name is None:
                continue
            lock = guards[name]
            if lock in held:
                continue
            who = f"self.{name}" if owner == "self" else name
            lockname = f"self.{lock}" if owner == "self" else lock
            sf.emit(out, self.id, node.lineno,
                    f"{who} accessed outside 'with {lockname}' "
                    f"(declared '# guarded-by: {lock}')")


# --------------------------------------------------------------------- #
# CFX-DONATE — donated buffers are dead after the dispatch
# --------------------------------------------------------------------- #


class DonateRule(Rule):
    """A variable passed in a donated argument position must not be
    read again until reassigned: XLA reuses the buffer, so a later read
    observes garbage (jax raises only under strict checks, and the
    serve path runs none). Covers (a) `f = jax.jit(g, donate_argnums=
    (i,))` then `f(...)`, (b) the immediately-invoked form, and (c)
    the repo convention `plan._refresh_fn(kb, donate)(A0, ...)`, whose
    arg 0 is donated whenever the session owns the base (CHANGES PR 3:
    donate the session-OWNED superseded base, never the caller's
    array). Conservative: only Name / `self.X` arguments are tracked,
    linearly by line within one function."""

    id = "CFX-DONATE"
    description = "donated buffer referenced after the donating dispatch"

    def check(self, sf: SourceFile, out: list) -> None:
        for func, _cls in _func_defs(sf.tree):
            self._check_func(sf, out, func)

    @staticmethod
    def _jit_donated(call: ast.Call):
        """donate_argnums of a `jax.jit(...)` call, else None."""
        f = call.func
        is_jit = (isinstance(f, ast.Attribute) and f.attr == "jit") or \
                 (isinstance(f, ast.Name) and f.id == "jit")
        if not is_jit:
            return None
        for kw in call.keywords:
            if kw.arg == "donate_argnums":
                if isinstance(kw.value, (ast.Tuple, ast.List)):
                    idxs = [e.value for e in kw.value.elts
                            if isinstance(e, ast.Constant)]
                    return tuple(i for i in idxs if isinstance(i, int))
                if isinstance(kw.value, ast.Constant) and \
                        isinstance(kw.value.value, int):
                    return (kw.value.value,)
                return ()  # dynamic donate_argnums: can't resolve
        return None

    @staticmethod
    def _key(node):
        if isinstance(node, ast.Name):
            return ("name", node.id)
        attr = _self_attr(node)
        if attr is not None:
            return ("self", attr)
        return None

    def _check_func(self, sf, out, func) -> None:
        jit_fns: dict[str, tuple] = {}  # name -> donate_argnums
        events = []  # (end_line, key, desc)
        for node in ast.walk(func):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                d = self._jit_donated(node.value)
                if d and len(node.targets) == 1 and \
                        isinstance(node.targets[0], ast.Name):
                    jit_fns[node.targets[0].id] = d
            if not isinstance(node, ast.Call):
                continue
            donated_idx = None
            inner = node.func
            if isinstance(inner, ast.Name) and inner.id in jit_fns:
                donated_idx = jit_fns[inner.id]
            elif isinstance(inner, ast.Call):
                d = self._jit_donated(inner)
                if d:
                    donated_idx = d
                elif _call_name(inner) == "_refresh_fn":
                    kwargs = {kw.arg: kw.value for kw in inner.keywords}
                    dn = kwargs.get("donate",
                                    inner.args[1] if len(inner.args) > 1
                                    else None)
                    if not (isinstance(dn, ast.Constant)
                            and dn.value is False):
                        donated_idx = (0,)  # donated whenever truthy
            if not donated_idx:
                continue
            end = getattr(node, "end_lineno", node.lineno)
            for i in donated_idx:
                if i < len(node.args):
                    key = self._key(node.args[i])
                    if key is not None:
                        events.append((end, key,
                                       ast.unparse(node.args[i])))
        if not events:
            return
        # linear order by line: a Store to the key closes the window,
        # a Load inside it is a use-after-donate
        for end, key, desc in events:
            store_line = None
            for node in ast.walk(func):
                if self._key(node) != key:
                    continue
                if isinstance(node.ctx, ast.Store) and \
                        node.lineno > end:
                    if store_line is None or node.lineno < store_line:
                        store_line = node.lineno
            for node in ast.walk(func):
                if self._key(node) != key or \
                        not isinstance(node.ctx, ast.Load):
                    continue
                if node.lineno <= end:
                    continue
                if store_line is not None and node.lineno >= store_line:
                    continue
                sf.emit(out, self.id, node.lineno,
                        f"'{desc}' was donated to a dispatch on line "
                        f"{end} and read again before reassignment — "
                        "XLA owns that buffer now")


# --------------------------------------------------------------------- #
# CFX-HOSTSYNC — no host syncs on the dispatch hot path
# --------------------------------------------------------------------- #


class HostSyncRule(Rule):
    """Inside a `# hot-path` function, forbid the device round-trips
    that stall async dispatch (engine.py module docstring: only the
    drain thread blocks): `.block_until_ready()`, `.item()`,
    `np.asarray`/`np.array` (a d2h copy when handed a device value),
    and `float(...)`/`int(...)` of a call result (a scalar readback
    when the call is device-valued). Drain-side sites are allowlisted
    by NOT being marked; marked functions that legitimately touch host
    numpy carry an inline suppression naming why."""

    id = "CFX-HOSTSYNC"
    description = "host sync inside a # hot-path function"

    _NP_NAMES = {"np", "numpy"}
    _SYNC_ATTRS = {"block_until_ready", "item"}

    def check(self, sf: SourceFile, out: list) -> None:
        for func, _cls in _func_defs(sf.tree):
            if not sf.is_hot_path(func):
                continue
            for node in ast.walk(func):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                if isinstance(f, ast.Attribute):
                    if f.attr in self._SYNC_ATTRS:
                        sf.emit(out, self.id, node.lineno,
                                f".{f.attr}() blocks on device work "
                                f"inside hot-path '{func.name}'")
                    elif f.attr in ("asarray", "array") and \
                            isinstance(f.value, ast.Name) and \
                            f.value.id in self._NP_NAMES:
                        sf.emit(out, self.id, node.lineno,
                                f"np.{f.attr}() forces a device->host "
                                f"copy when handed a device value, "
                                f"inside hot-path '{func.name}'")
                elif isinstance(f, ast.Name) and \
                        f.id in ("float", "int") and node.args and \
                        isinstance(node.args[0], ast.Call):
                    sf.emit(out, self.id, node.lineno,
                            f"{f.id}(<call>) is a scalar readback "
                            f"(host sync) when the call is "
                            f"device-valued, inside hot-path "
                            f"'{func.name}'")


# --------------------------------------------------------------------- #
# CFX-FUTURE — exception edges must resolve owned futures
# --------------------------------------------------------------------- #


class FutureRule(Rule):
    """In a `# futures-owner` function (a worker body that owns request
    futures), an `except` edge must leave every owned future on a
    resolution path: the handler must resolve/fail/re-queue (a call to
    one of the RESOLVERS below), or re-raise so the worker wrapper's
    post-mortem (`_thread_died`) fails the pending set. Flagged:
    broad handlers (`Exception`/`BaseException`/bare) that do neither,
    and narrow handlers that silently swallow (`pass`-only body) — a
    narrow handler with real recovery logic is trusted. This is the
    static half of PR 4's resolution-ownership (`_live`) discipline."""

    id = "CFX-FUTURE"
    description = "exception edge can strand an owned future"

    RESOLVERS = {
        "set_result", "set_exception", "_fail", "_settle",
        "_settle_factor", "_redispatch_survivors",
        "_redispatch_factor_survivors", "_drain_redispatch",
        "_drain_factor_redispatch", "_solo_drain", "_solo_factor_drain",
        "_escalate_settle", "_thread_died", "_run_chunk",
        "_run_factor_chunk", "_drain_unhealthy", "_drain_factor",
    }
    _BROAD = {"Exception", "BaseException"}

    def _handler_types(self, h: ast.ExceptHandler) -> list:
        t = h.type
        if t is None:
            return []
        elts = t.elts if isinstance(t, ast.Tuple) else [t]
        names = []
        for e in elts:
            if isinstance(e, ast.Name):
                names.append(e.id)
            elif isinstance(e, ast.Attribute):
                names.append(e.attr)
        return names

    def check(self, sf: SourceFile, out: list) -> None:
        for func, _cls in _func_defs(sf.tree):
            if not sf.is_futures_owner(func):
                continue
            for node in ast.walk(func):
                if not isinstance(node, ast.ExceptHandler):
                    continue
                names = self._handler_types(node)
                broad = not names or any(n in self._BROAD
                                         for n in names)
                resolves = any(
                    isinstance(sub, ast.Call)
                    and _call_name(sub) in self.RESOLVERS
                    for sub in ast.walk(node))
                reraises = any(isinstance(sub, ast.Raise)
                               for sub in ast.walk(node))
                swallow = all(isinstance(s, ast.Pass)
                              for s in node.body)
                if resolves or reraises:
                    continue
                if broad:
                    sf.emit(out, self.id, node.lineno,
                            f"broad except in futures-owner "
                            f"'{func.name}' neither resolves owned "
                            "futures nor re-raises — pending requests "
                            "would hang forever")
                elif swallow:
                    sf.emit(out, self.id, node.lineno,
                            f"except {'/'.join(names) or '<bare>'} in "
                            f"futures-owner '{func.name}' swallows "
                            "silently (pass-only body) — if a future "
                            "was in flight it is stranded")


# --------------------------------------------------------------------- #
# CFX-RECOMPILE — programs built once, keyed at power-of-two buckets
# --------------------------------------------------------------------- #


class RecompileRule(Rule):
    """Three shapes of accidental recompilation (30-100 ms each on this
    CPU backend, invisible to the plan trace counters):
    (a) `jax.jit(...)` built inside a for/while body — a fresh program
    object (and trace) per iteration;
    (b) `jax.jit(f)(...)` immediately invoked — retraces every call;
    (c) a bucket-program getter (`_solve_fn`, `_stacked_factor_fn`,
    ...) fed a key that is provably not a power-of-two bucket: the
    memo caches key programs by exact value, so per-call-varying keys
    compile one program per distinct value. Accepted keys: pow2
    literals, `rank_bucket(...)` calls, names locally assigned from
    either, and `<staged buffer>.shape[i]` (stages pad to buckets).
    Unresolvable names (parameters, tuple unpacks) pass — the getters
    assert the pow2 contract at runtime."""

    id = "CFX-RECOMPILE"
    description = "per-call recompilation hazard"

    BUCKET_GETTERS = {
        "_solve_fn", "_stacked_solve_fn", "_stacked_factor_fn",
        "_factor_health_fn", "_solve_health_fn", "_refine_fn",
        "_update_fn",
    }

    def check(self, sf: SourceFile, out: list) -> None:
        for func, _cls in _func_defs(sf.tree):
            self._check_func(sf, out, func)

    @staticmethod
    def _is_jit(call: ast.Call) -> bool:
        f = call.func
        return (isinstance(f, ast.Attribute) and f.attr == "jit" and
                isinstance(f.value, ast.Name) and f.value.id == "jax")

    @staticmethod
    def _is_shape_sub(node) -> bool:
        return (isinstance(node, ast.Subscript)
                and isinstance(node.value, ast.Attribute)
                and node.value.attr == "shape")

    def _bucket_ok(self, func, call_line, arg) -> bool | None:
        """True = provably bucketed, False = provably not, None =
        unresolvable (conservative pass)."""
        if isinstance(arg, ast.Constant):
            return _is_pow2(arg.value)
        if isinstance(arg, ast.Call):
            return True if _call_name(arg) == "rank_bucket" else None
        if self._is_shape_sub(arg):
            return True
        if isinstance(arg, ast.Name):
            last = None
            for node in ast.walk(func):
                if isinstance(node, ast.Assign) and \
                        node.lineno < call_line and \
                        any(isinstance(t, ast.Name) and t.id == arg.id
                            for t in node.targets):
                    if last is None or node.lineno > last.lineno:
                        last = node
            if last is None:
                return None  # parameter / out-of-scope: trust runtime
            return self._bucket_ok(func, last.lineno, last.value)
        return None

    def _check_func(self, sf, out, func) -> None:
        # (a) jit under a loop
        def loops(body, in_loop):
            for stmt in body:
                here = in_loop or isinstance(stmt, (ast.For, ast.While))
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    continue  # a nested def delays execution
                if here:
                    for sub in ast.walk(stmt):
                        if isinstance(sub, ast.Call) and \
                                self._is_jit(sub):
                            sf.emit(out, self.id, sub.lineno,
                                    "jax.jit built inside a loop — a "
                                    "fresh program (and trace) per "
                                    "iteration; hoist and memoize")
                for _f, v in ast.iter_fields(stmt):
                    if isinstance(v, list) and v and \
                            isinstance(v[0], ast.stmt):
                        loops(v, here)
                    elif isinstance(v, list):
                        for h in v:
                            if isinstance(h, ast.excepthandler):
                                loops(h.body, here)

        loops(func.body, False)
        for node in ast.walk(func):
            if not isinstance(node, ast.Call):
                continue
            # (b) immediately-invoked jit
            if isinstance(node.func, ast.Call) and \
                    self._is_jit(node.func):
                sf.emit(out, self.id, node.lineno,
                        "jax.jit(f)(...) retraces on every call — "
                        "bind the jitted fn once and reuse it")
            # (c) bucket getters fed un-bucketed keys
            name = _call_name(node)
            if name in self.BUCKET_GETTERS and node.args:
                for arg in node.args:
                    ok = self._bucket_ok(func, node.lineno, arg)
                    if ok is False:
                        sf.emit(out, self.id, node.lineno,
                                f"{name}({ast.unparse(arg)}) — bucket "
                                "keys must be power-of-two (route "
                                "through update.rank_bucket), else "
                                "every distinct value compiles its "
                                "own program")


# --------------------------------------------------------------------- #
# CFX-EXCEPT — InjectedKill must reach the watchdog
# --------------------------------------------------------------------- #


class ExceptRule(Rule):
    """`InjectedKill` is a BaseException on purpose (PR 4): it must
    sail through per-item `except Exception` handling and out of the
    worker loop so the watchdog path runs. A bare `except:` or
    `except BaseException` swallows it — allowed only when the handler
    re-raises or IS the sanctioned post-mortem (calls `_thread_died`).
    Explicitly catching `InjectedKill` without re-raising is flagged
    for the same reason."""

    id = "CFX-EXCEPT"
    description = "BaseException/bare except defeats the watchdog"

    def check(self, sf: SourceFile, out: list) -> None:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            t = node.type
            elts = (t.elts if isinstance(t, ast.Tuple)
                    else [] if t is None else [t])
            names = [(e.id if isinstance(e, ast.Name) else
                      e.attr if isinstance(e, ast.Attribute) else "")
                     for e in elts]
            bare = t is None
            base = "BaseException" in names
            kill = "InjectedKill" in names
            if not (bare or base or kill):
                continue
            reraises = any(isinstance(sub, ast.Raise)
                           for sub in ast.walk(node))
            postmortem = any(
                isinstance(sub, ast.Call)
                and _call_name(sub) == "_thread_died"
                for sub in ast.walk(node))
            if reraises or postmortem:
                continue
            what = ("bare except:" if bare
                    else "except BaseException" if base
                    else "except InjectedKill")
            sf.emit(out, self.id, node.lineno,
                    f"{what} swallows InjectedKill (a BaseException) — "
                    "the watchdog never learns the worker died; "
                    "re-raise or route through _thread_died")


ALL_RULES = (LockRule(), DonateRule(), HostSyncRule(), FutureRule(),
             RecompileRule(), ExceptRule())
