"""Runtime lock-order / dispatch-discipline harness (conflint's
dynamic half; DESIGN.md §22).

Static CFX-LOCK proves guarded attributes are touched under their
lock, but two properties are only visible at runtime: the ORDER in
which threads nest different locks (an A->B edge in one thread and a
B->A edge in another is a potential deadlock even if the test run gets
lucky), and whether a no-dispatch lock (the engine's admission lock)
is ever held across a device dispatch (which would serialize the
double-buffered pipeline behind the GIL-released XLA call and can
deadlock against `on_full='block'` submitters).

`watch()` monkeypatches `threading.Lock`/`threading.RLock` so every
lock CREATED inside the context is wrapped with bookkeeping:

- each acquisition records held->acquired edges into a global
  lock-order graph; an edge that closes a cycle is reported as a
  potential deadlock with both lock names;
- locks created from the files in `forbid_dispatch_files` (default:
  the engine module) are marked no-dispatch; if one is held when a
  `serve.*` profiler region is entered (the dispatch sites), that is a
  violation. Session RLocks are deliberately NOT forbidden — holding
  the session lock across a dispatch is the §20 escalation design.

Locks created before the context (module-level registry locks) are
untouched; wrappers created inside keep working after exit, they just
stop reporting into a live state. Opt-in only: production code never
imports this module; `scripts/soak.py --lockcheck` and
tests/test_analysis.py do.
"""

from __future__ import annotations

import contextlib
import os
import sys
import threading
from _thread import allocate_lock, get_ident


class LockCheckState:
    """The shared books of one `watch()` session."""

    def __init__(self, forbid_dispatch_files=("engine.py",)):
        self.forbid_dispatch_files = tuple(forbid_dispatch_files)
        self._raw = allocate_lock()  # raw lock: never instrumented
        self._held: dict[int, list] = {}     # thread id -> wrapper stack
        self._adj: dict[int, set] = {}       # lock id -> successor ids
        self._names: dict[int, str] = {}
        self._edges: set = set()
        self._seen_dispatch: set = set()
        self.locks = 0
        self.acquisitions = 0
        self.stash_edges = 0
        self.violations: list[str] = []

    # -- bookkeeping (called by the wrappers) ------------------------- #

    def _register(self, wrapper) -> None:
        with self._raw:
            self.locks += 1
            self._names[id(wrapper)] = wrapper.name

    def _reachable(self, src: int, dst: int) -> bool:
        stack, seen = [src], set()
        while stack:
            n = stack.pop()
            if n == dst:
                return True
            if n in seen:
                continue
            seen.add(n)
            stack.extend(self._adj.get(n, ()))
        return False

    def note_acquire(self, wrapper, stash: bool = False) -> None:
        tid = get_ident()
        with self._raw:
            self.acquisitions += 1
            held = self._held.setdefault(tid, [])
            b = id(wrapper)
            if stash and held:
                # a victim-stash acquisition (tier._spill_batch): the
                # ONE deliberate session-lock -> session-lock nesting.
                # It is leaf-bounded — while holding the victim's lock
                # the spill path only ever takes the tier manager's
                # leaf lock, never blocks on another session or the
                # engine (phase 2 try-acquires), and a reviving
                # session is never a victim — so no realizable cycle
                # can pass through it (the lockdep 'nested' annotation,
                # applied by call site instead of at the call). Counted
                # but kept out of the order graph.
                self.stash_edges += 1
                held.append(wrapper)
                return
            for w in held:
                a = id(w)
                if a == b or (a, b) in self._edges:
                    continue
                # adding a->b: if b already reaches a, this edge closes
                # a cycle — two threads disagree on nesting order
                if self._reachable(b, a):
                    self.violations.append(
                        f"lock-order cycle: {w.name} -> {wrapper.name} "
                        f"while the reverse order exists elsewhere — "
                        "potential deadlock")
                self._edges.add((a, b))
                self._adj.setdefault(a, set()).add(b)
            held.append(wrapper)

    def note_release(self, wrapper) -> None:
        tid = get_ident()
        with self._raw:
            held = self._held.get(tid, [])
            for i in range(len(held) - 1, -1, -1):
                if held[i] is wrapper:
                    del held[i]
                    break

    def note_dispatch(self, region: str) -> None:
        """profiler.region hook: a `serve.*` region is a device
        dispatch site — no-dispatch locks must not be held here."""
        if not region.startswith("serve."):
            return
        tid = get_ident()
        with self._raw:
            for w in self._held.get(tid, ()):
                if not w.no_dispatch:
                    continue
                key = (id(w), region)
                if key in self._seen_dispatch:
                    continue
                self._seen_dispatch.add(key)
                self.violations.append(
                    f"no-dispatch lock {w.name} held across dispatch "
                    f"region '{region}' — the admission lock must "
                    "never cover device work")

    # -- public surface ------------------------------------------------ #

    def mark_no_dispatch(self, wrapper) -> None:
        """Explicitly forbid a wrapped lock across dispatch (tests)."""
        wrapper.no_dispatch = True

    def report(self) -> dict:
        with self._raw:
            return {"locks": self.locks,
                    "acquisitions": self.acquisitions,
                    "order_edges": len(self._edges),
                    "stash_edges": self.stash_edges,
                    "violations": list(self.violations)}


_STASH_SITES = (("tier.py", "_spill_batch"), ("tier.py", "_demote_one"))


def _is_stash_acquire() -> bool:
    """True when the acquisition call chain bottoms out in a blessed
    victim-stash site (see note_acquire). Walks past this module's own
    frames (wrapper acquire/__enter__)."""
    here = os.path.dirname(os.path.abspath(__file__))
    f = sys._getframe(1)
    for _ in range(6):
        if f is None:
            return False
        fn = os.path.abspath(f.f_code.co_filename)
        if os.path.dirname(fn) != here:
            return (os.path.basename(fn), f.f_code.co_name) \
                in _STASH_SITES
        f = f.f_back
    return False


class _LockWrap:
    """threading.Lock stand-in that reports into a LockCheckState."""

    _KIND = "Lock"

    def __init__(self, state, inner, name, no_dispatch):
        self._st = state
        self._inner = inner
        self.name = name
        self.no_dispatch = no_dispatch

    def acquire(self, blocking=True, timeout=-1):
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._st.note_acquire(self, stash=_is_stash_acquire())
        return ok

    def release(self):
        self._st.note_release(self)
        self._inner.release()

    def locked(self):
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()

    def __repr__(self):
        return f"<lockcheck {self._KIND} {self.name}>"


class _RLockWrap(_LockWrap):
    """threading.RLock stand-in: re-entrant acquisitions record one
    edge set (depth changes are invisible to lock ordering). Exposes
    the private Condition protocol (`_is_owned`/`_release_save`/
    `_acquire_restore`) by delegation, so `threading.Condition` built
    on a wrapped RLock waits correctly."""

    _KIND = "RLock"

    def acquire(self, blocking=True, timeout=-1):
        owned = self._inner._is_owned()
        ok = self._inner.acquire(blocking, timeout)
        if ok and not owned:
            self._st.note_acquire(self, stash=_is_stash_acquire())
        return ok

    def release(self):
        self._inner.release()
        if not self._inner._is_owned():
            self._st.note_release(self)

    def _is_owned(self):
        return self._inner._is_owned()

    def _release_save(self):
        # Condition.wait: the full release bypasses our books on
        # purpose — the thread sleeps, so it can add no false edges,
        # and _acquire_restore rebalances before it runs again
        return self._inner._release_save()

    def _acquire_restore(self, state):
        return self._inner._acquire_restore(state)


def _creation_site() -> tuple:
    """(filename, lineno) of the frame that called threading.Lock()."""
    f = sys._getframe(2)
    here = os.path.dirname(os.path.abspath(__file__))
    while f is not None and os.path.dirname(
            os.path.abspath(f.f_code.co_filename)) == here:
        f = f.f_back
    if f is None:
        return ("<unknown>", 0)
    return (f.f_code.co_filename, f.f_lineno)


def _should_wrap(fname: str) -> bool:
    """Instrument locks created by the code under contract — the
    conflux_tpu package, its tests/scripts, and the queue module the
    engine builds on. Locks born inside jax/XLA internals stay raw:
    their ordering is not our contract, and wrapping them would report
    cycles this repo cannot fix."""
    base = os.path.basename(fname)
    return ("conflux_tpu" in fname
            or base == "queue.py"
            or base.startswith("test_")
            or base == "soak.py"
            or (os.sep + "tests" + os.sep) in fname)


@contextlib.contextmanager
def watch(forbid_dispatch_files=("engine.py",)):
    """Instrument every lock created inside the context (by the files
    `_should_wrap` selects); yields the :class:`LockCheckState` whose
    `violations` the caller asserts empty. Nesting watch() contexts is
    not supported."""
    from conflux_tpu import profiler  # lazy: profiler imports jax

    state = LockCheckState(forbid_dispatch_files)
    orig_lock, orig_rlock = threading.Lock, threading.RLock

    def make(cls, factory):
        def build():
            fname, lineno = _creation_site()
            if not _should_wrap(fname):
                return factory()
            nd = (cls is _LockWrap and os.path.basename(fname)
                  in state.forbid_dispatch_files)
            w = cls(state, factory(),
                    f"{cls._KIND}@{os.path.basename(fname)}:{lineno}",
                    nd)
            state._register(w)
            return w

        return build

    threading.Lock = make(_LockWrap, orig_lock)
    threading.RLock = make(_RLockWrap, orig_rlock)
    prev_hook = profiler._dispatch_hook
    profiler._dispatch_hook = state.note_dispatch
    try:
        yield state
    finally:
        threading.Lock = orig_lock
        threading.RLock = orig_rlock
        profiler._dispatch_hook = prev_hook
