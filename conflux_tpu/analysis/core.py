"""conflint core: source model, annotations, suppressions, report.

The serve stack's correctness rests on conventions — lock-guarded
attributes, buffer-donation ownership, a no-host-sync rule on the
dispatch hot path, future-resolution ownership — that unit tests can
only spot-check. conflint turns each convention into a mechanical rule
over the AST (see `conflux_tpu.analysis.rules`) so the whole tree is
re-proved on every CI run.

This module is deliberately stdlib-only (ast + tokenize): the analyzer
must run in a bare CI step and must never import jax (importing the
package under analysis would skew what it measures).

Vocabulary (all machine-read from comments, all demonstrated in
`tests/test_analysis.py`):

- ``# guarded-by: _lock`` on an attribute's initializing assignment
  (or a module-global's) declares the lock that must be held at every
  later access. CFX-LOCK enforces it.
- ``# hot-path`` on (or directly above) a ``def`` marks a function on
  the dispatch hot path: CFX-HOSTSYNC forbids host syncs inside.
- ``# futures-owner`` marks a worker-body function that owns request
  futures: CFX-FUTURE forbids exception edges that strand them.
- ``# requires-lock: _lock`` on a ``def`` asserts the caller holds the
  lock (private helpers only called under it).
- ``# conflint: disable=RULE[,RULE] reason`` suppresses a finding on
  its own line (or, on a standalone comment line, on the next line).
  Suppressions are counted in the report — they are visible debt, not
  silence.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import os
import re
import tokenize

RULE_IDS = ("CFX-LOCK", "CFX-DONATE", "CFX-HOSTSYNC", "CFX-FUTURE",
            "CFX-RECOMPILE", "CFX-EXCEPT")

_SUPPRESS_RE = re.compile(
    r"#\s*conflint:\s*disable=([A-Za-z0-9_\-,]+)(?:\s+(.*))?")
_GUARD_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_]\w*)")
_REQ_LOCK_RE = re.compile(r"#\s*requires-lock:\s*([A-Za-z_]\w*)")
_HOT_RE = re.compile(r"#\s*hot-path\b")
_FUT_RE = re.compile(r"#\s*futures-owner\b")

# directories never worth scanning (vendored code, caches, VCS)
EXCLUDE_DIRS = {".git", "__pycache__", ".pytest_cache", ".mypy_cache",
                "libs", "data", "node_modules", ".venv", "venv",
                "build", "dist", ".claude", ".eggs"}


@dataclasses.dataclass
class Finding:
    """One rule hit. `suppressed` findings are reported (and counted)
    but do not fail the run; `reason` carries the suppression comment's
    justification text."""

    rule: str
    path: str
    line: int
    message: str
    suppressed: bool = False
    reason: str = ""

    def as_dict(self) -> dict:
        d = {"rule": self.rule, "path": self.path, "line": self.line,
             "message": self.message}
        if self.suppressed:
            d["reason"] = self.reason
        return d

    def __str__(self) -> str:
        tag = " (suppressed)" if self.suppressed else ""
        return f"{self.path}:{self.line}: {self.rule}{tag}: {self.message}"


class SourceFile:
    """One parsed source: AST + per-line comments + the machine-read
    annotation/suppression maps every rule shares."""

    def __init__(self, path: str, text: str):
        self.path = path
        self.text = text
        self.tree = ast.parse(text, filename=path)
        # line -> full comment text (tokenize sees only real comments,
        # never string literals — fixture snippets in tests stay inert)
        self.comments: dict[int, str] = {}
        # comment-only lines (annotation/suppression applies to the
        # NEXT line as well)
        self._own_line: set[int] = set()
        try:
            for tok in tokenize.generate_tokens(
                    io.StringIO(text).readline):
                if tok.type == tokenize.COMMENT:
                    ln = tok.start[0]
                    self.comments[ln] = tok.string
                    if text.splitlines()[ln - 1].lstrip().startswith("#"):
                        self._own_line.add(ln)
        except tokenize.TokenError:
            pass
        # line -> (set of suppressed rule ids, reason)
        self.suppressions: dict[int, tuple[set, str]] = {}
        self.suppressions_used: list[Finding] = []
        for ln, c in self.comments.items():
            m = _SUPPRESS_RE.search(c)
            if not m:
                continue
            rules = {r.strip().upper() for r in m.group(1).split(",")}
            reason = (m.group(2) or "").strip()
            entry = (rules, reason)
            self.suppressions[ln] = entry
            if ln in self._own_line:  # standalone comment covers next line
                self.suppressions.setdefault(ln + 1, entry)

    # -- annotation lookups ------------------------------------------- #

    def comment_at(self, *lines: int) -> str:
        return " ".join(self.comments.get(ln, "") for ln in lines)

    def guard_on(self, node: ast.stmt) -> str | None:
        """`# guarded-by: NAME` on any line the statement spans."""
        end = getattr(node, "end_lineno", node.lineno) or node.lineno
        for ln in range(node.lineno, end + 1):
            m = _GUARD_RE.search(self.comments.get(ln, ""))
            if m:
                return m.group(1)
        return None

    def _def_comment(self, node: ast.AST) -> str:
        return self.comment_at(node.lineno, node.lineno - 1)

    def is_hot_path(self, node: ast.AST) -> bool:
        return bool(_HOT_RE.search(self._def_comment(node)))

    def is_futures_owner(self, node: ast.AST) -> bool:
        return bool(_FUT_RE.search(self._def_comment(node)))

    def required_locks(self, node: ast.AST) -> set:
        m = _REQ_LOCK_RE.search(self._def_comment(node))
        return {m.group(1)} if m else set()

    # -- finding emission (suppression-aware) ------------------------- #

    def emit(self, out: list, rule: str, line: int, message: str) -> None:
        sup = self.suppressions.get(line)
        if sup is not None and (rule in sup[0] or "ALL" in sup[0]):
            out.append(Finding(rule, self.path, line, message,
                               suppressed=True, reason=sup[1]))
        else:
            out.append(Finding(rule, self.path, line, message))


def scan_source(text: str, path: str = "<string>",
                rules=None) -> list[Finding]:
    """Run the rules over one in-memory source (fixture tests' entry
    point). Returns every finding, suppressed ones included."""
    from conflux_tpu.analysis.rules import ALL_RULES

    sf = SourceFile(path, text)
    out: list[Finding] = []
    for rule in (ALL_RULES if rules is None else rules):
        rule.check(sf, out)
    return out


def iter_py_files(paths) -> list[str]:
    files: list[str] = []
    for p in paths:
        if os.path.isfile(p):
            files.append(p)
            continue
        for root, dirs, names in os.walk(p):
            dirs[:] = sorted(d for d in dirs if d not in EXCLUDE_DIRS)
            files.extend(os.path.join(root, n) for n in sorted(names)
                         if n.endswith(".py"))
    return files


@dataclasses.dataclass
class Report:
    """One conflint run over a file set. `findings` are live (fail the
    run), `suppressions` are acknowledged hits. `summary()` is the
    diffable trend surface (the `profiler.serve_stats()` shape): rules
    run, findings, suppressions, files scanned, per-rule counts."""

    files_scanned: int
    findings: list[Finding]
    suppressions: list[Finding]
    errors: list[str]

    @property
    def clean(self) -> bool:
        return not self.findings and not self.errors

    def summary(self) -> dict:
        by_rule = {r: {"findings": 0, "suppressions": 0}
                   for r in RULE_IDS}
        for f in self.findings:
            by_rule.setdefault(
                f.rule, {"findings": 0, "suppressions": 0})
            by_rule[f.rule]["findings"] += 1
        for f in self.suppressions:
            by_rule.setdefault(
                f.rule, {"findings": 0, "suppressions": 0})
            by_rule[f.rule]["suppressions"] += 1
        return {"rules_run": len(RULE_IDS),
                "files_scanned": self.files_scanned,
                "findings": len(self.findings),
                "suppressions": len(self.suppressions),
                "parse_errors": len(self.errors),
                "by_rule": by_rule}

    def as_dict(self) -> dict:
        return {"tool": "conflint", "version": 1,
                "summary": self.summary(),
                "findings": [f.as_dict() for f in self.findings],
                "suppressions": [f.as_dict() for f in self.suppressions],
                "parse_errors": self.errors}

    def to_json(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.as_dict(), f, indent=2, sort_keys=True)
            f.write("\n")


def run_paths(paths, rules=None) -> Report:
    """Scan every .py under `paths` and fold the findings into a
    :class:`Report`. Unparseable files are reported as errors (a file
    conflint cannot read is a finding, not a pass)."""
    from conflux_tpu.analysis.rules import ALL_RULES

    rules = ALL_RULES if rules is None else rules
    findings: list[Finding] = []
    suppressions: list[Finding] = []
    errors: list[str] = []
    files = iter_py_files(paths)
    for path in files:
        try:
            with open(path, encoding="utf-8") as f:
                text = f.read()
            sf = SourceFile(path, text)
        except (SyntaxError, UnicodeDecodeError) as e:
            errors.append(f"{path}: {e}")
            continue
        out: list[Finding] = []
        for rule in rules:
            rule.check(sf, out)
        for f in out:
            (suppressions if f.suppressed else findings).append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    suppressions.sort(key=lambda f: (f.path, f.line, f.rule))
    return Report(len(files), findings, suppressions, errors)
