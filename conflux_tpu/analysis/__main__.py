"""`python -m conflux_tpu.analysis` — run conflint over a tree.

Exit status: 0 when every finding is suppressed (or none), 1 when any
live finding (or parse error) remains — the CI contract. `--json`
writes the diffable report (summary: rules run, findings,
suppressions, files scanned — the serve_stats shape, so trends diff
across PRs)."""

from __future__ import annotations

import argparse
import sys

from conflux_tpu.analysis.core import run_paths


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m conflux_tpu.analysis",
        description="conflint: concurrency/donation/dispatch contract "
                    "checks for the conflux-tpu serve stack")
    ap.add_argument("paths", nargs="*", default=["."],
                    help="files/dirs to scan (default: .)")
    ap.add_argument("--json", metavar="PATH", default=None,
                    help="write the JSON report here")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="summary only (no per-finding lines)")
    args = ap.parse_args(argv)

    report = run_paths(args.paths or ["."])
    if not args.quiet:
        for f in report.findings:
            print(f)
        for f in report.suppressions:
            print(f)
        for e in report.errors:
            print(f"parse error: {e}")
    s = report.summary()
    print(f"conflint: {s['files_scanned']} files, {s['rules_run']} "
          f"rules, {s['findings']} finding(s), "
          f"{s['suppressions']} suppression(s)"
          + (f", {s['parse_errors']} parse error(s)"
             if report.errors else ""))
    if args.json:
        report.to_json(args.json)
        print(f"report written to {args.json}")
    return 0 if report.clean else 1


if __name__ == "__main__":
    sys.exit(main())
