"""Layout descriptors and redistribution — the COSTA role.

The reference delegates grid/layout redistribution to the vendored COSTA
library via its `conflux_layout` adapter (`src/conflux/lu/layout.cpp:31-135`):
a conflux tile distribution is described either as a ScaLAPACK-style
`block_cyclic_layout` or as a `custom_layout` with explicit per-tile owners,
and `costa::transform` moves data between any two such layouts.

Here a layout is a small descriptor over a host matrix, and `transform`
re-buckets tiles between two block-cyclic layouts (different tile sizes
and/or grids) in one vectorized pass. On device, resharding between meshes
is XLA's job (`jax.device_put` with a new NamedSharding) — this module is
the host-side half, used by the CLIs, the checkpoint layer, and the
ScaLAPACK-interop surface.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from conflux_tpu.geometry import Grid3


@dataclasses.dataclass(frozen=True)
class BlockCyclicLayout:
    """ScaLAPACK-descriptor-style block-cyclic layout over a (Prows, Pcols)
    grid (role of `costa::block_cyclic_layout` as used in `layout.cpp:63-113`).
    """

    M: int
    N: int
    vr: int  # row tile size
    vc: int  # col tile size
    Prows: int
    Pcols: int

    @classmethod
    def for_grid(cls, M: int, N: int, v: int, grid: Grid3) -> "BlockCyclicLayout":
        return cls(M=M, N=N, vr=v, vc=v, Prows=grid.Px, Pcols=grid.Py)

    def owner(self, ti: int, tj: int) -> tuple[int, int]:
        """Owning grid coordinate of tile (ti, tj) — the conflux
        owner-computes map (`layout.cpp:114-123`)."""
        return ti % self.Prows, tj % self.Pcols

    def tile_counts(self) -> tuple[int, int]:
        return -(-self.M // self.vr), -(-self.N // self.vc)

    def local_shape(self, p: int, q: int) -> tuple[int, int]:
        """Local buffer extent on grid coordinate (p, q), numroc-style
        (role of `examples/utils.hpp` local-size math)."""
        Mt, Nt = self.tile_counts()
        nrt = (Mt - p + self.Prows - 1) // self.Prows
        nct = (Nt - q + self.Pcols - 1) // self.Pcols
        last_r = self.M - (Mt - 1) * self.vr
        last_c = self.N - (Nt - 1) * self.vc
        rows = nrt * self.vr - (last_r != self.vr and self.owner(Mt - 1, 0)[0] == p) * (self.vr - last_r)
        cols = nct * self.vc - (last_c != self.vc and self.owner(0, Nt - 1)[1] == q) * (self.vc - last_c)
        return rows, cols

    def owner_map(self) -> np.ndarray:
        """(Mt, Nt, 2) explicit per-tile owner array — the
        `costa::custom_layout` form (`layout.cpp:114-135`)."""
        Mt, Nt = self.tile_counts()
        ti = np.arange(Mt)[:, None]
        tj = np.arange(Nt)[None, :]
        return np.stack(
            np.broadcast_arrays(ti % self.Prows, tj % self.Pcols), axis=-1
        )


@dataclasses.dataclass(frozen=True)
class CustomLayout:
    """Explicit per-tile owner layout — the `costa::custom_layout` role
    (`src/conflux/lu/layout.cpp:114-135`): uniform (vr, vc) tiles whose
    owners form an ARBITRARY (Mt, Nt, 2) array rather than the cyclic
    `(ti % Prows, tj % Pcols)` rule. conflux itself only ever builds the
    cyclic form, but COSTA accepts any owner array; this closes that
    last sliver of the adapter surface.

    Local storage convention: because an arbitrary owner set is not a
    product of row/col tile sets, a coordinate's tiles do not pack into
    one rectangle — storage is `{(p, q): {(ti, tj): tile}}` with each
    tile row-major and trailing tiles short, matching COSTA's
    block-pointer representation rather than ScaLAPACK's dense local
    matrix."""

    M: int
    N: int
    vr: int
    vc: int
    owners: tuple  # hashable (Mt, Nt, 2) owner entries; use .owner()

    @classmethod
    def from_owner_map(cls, M: int, N: int, vr: int, vc: int,
                       owners: np.ndarray) -> "CustomLayout":
        owners = np.asarray(owners, dtype=np.int64)
        Mt, Nt = -(-M // vr), -(-N // vc)
        if owners.shape != (Mt, Nt, 2):
            raise ValueError(
                f"owner map shape {owners.shape} != tile grid {(Mt, Nt, 2)}")
        if owners.min() < 0:
            raise ValueError("owner coordinates must be non-negative")
        return cls(M=M, N=N, vr=vr, vc=vc,
                   owners=tuple(map(tuple, owners.reshape(-1, 2).tolist())))

    def tile_counts(self) -> tuple[int, int]:
        return -(-self.M // self.vr), -(-self.N // self.vc)

    def owner(self, ti: int, tj: int) -> tuple[int, int]:
        _, Nt = self.tile_counts()
        return self.owners[ti * Nt + tj]

    def tile_shape(self, ti: int, tj: int) -> tuple[int, int]:
        return (min((ti + 1) * self.vr, self.M) - ti * self.vr,
                min((tj + 1) * self.vc, self.N) - tj * self.vc)

    def scatter(self, A: np.ndarray) -> dict:
        """Split a host matrix into the per-owner tile stores."""
        out: dict = {}
        Mt, Nt = self.tile_counts()
        for ti in range(Mt):
            for tj in range(Nt):
                r0, c0 = ti * self.vr, tj * self.vc
                h, w = self.tile_shape(ti, tj)
                out.setdefault(self.owner(ti, tj), {})[(ti, tj)] = (
                    A[r0 : r0 + h, c0 : c0 + w].copy())
        return out

    def gather(self, store: dict) -> np.ndarray:
        """Inverse of :meth:`scatter`."""
        some = next(iter(next(iter(store.values())).values()))
        A = np.zeros((self.M, self.N), some.dtype)
        Mt, Nt = self.tile_counts()
        for ti in range(Mt):
            for tj in range(Nt):
                tile = store[self.owner(ti, tj)][(ti, tj)]
                A[ti * self.vr : ti * self.vr + tile.shape[0],
                  tj * self.vc : tj * self.vc + tile.shape[1]] = tile
        return A


def _src_view(shards, src, r: int, r_end: int, c: int, c_end: int):
    """View of global region [r:r_end, c:c_end] — which must lie within
    ONE source tile — from either layout kind's storage."""
    sti, stj = r // src.vr, c // src.vc
    sp, sq = src.owner(sti, stj)
    if isinstance(src, CustomLayout):
        tile = shards[sp, sq][(sti, stj)]
        return tile[r - sti * src.vr : r_end - sti * src.vr,
                    c - stj * src.vc : c_end - stj * src.vc]
    sbuf = shards[sp][sq]
    sr = ((sti - sp) // src.Prows) * src.vr + (r - sti * src.vr)
    sc = ((stj - sq) // src.Pcols) * src.vc + (c - stj * src.vc)
    return sbuf[sr : sr + (r_end - r), sc : sc + (c_end - c)]


def _copy_region(shards, src, r0: int, r1: int, c0: int, c1: int,
                 out: np.ndarray, or0: int, oc0: int) -> None:
    """Walk the source tiles covering [r0:r1, c0:c1] and copy into
    out[or0.., oc0..] — the shared kernel of every transform direction."""
    r = r0
    while r < r1:
        r_end = min((r // src.vr + 1) * src.vr, r1)
        c = c0
        while c < c1:
            c_end = min((c // src.vc + 1) * src.vc, c1)
            out[or0 + (r - r0) : or0 + (r - r0) + (r_end - r),
                oc0 + (c - c0) : oc0 + (c - c0) + (c_end - c)] = (
                _src_view(shards, src, r, r_end, c, c_end))
            c = c_end
        r = r_end


def numroc(n: int, nb: int, iproc: int, isrcproc: int, nprocs: int) -> int:
    """NUMber of Rows Or Columns: ScaLAPACK's exact `numroc` formula
    (the reference links it via `examples/utils.hpp` local-size math).
    Rows/cols of a block-cyclically distributed dimension owned by
    process `iproc` when the first block lives on `isrcproc`."""
    mydist = (nprocs + iproc - isrcproc) % nprocs
    nblocks = n // nb
    num = (nblocks // nprocs) * nb
    extrablks = nblocks % nprocs
    if mydist < extrablks:
        num += nb
    elif mydist == extrablks:
        num += n % nb
    return num


def scalapack_desc(layout: BlockCyclicLayout, p: int = 0,
                   ctxt: int = 0) -> np.ndarray:
    """The 9-integer ScaLAPACK array descriptor for this layout, as a
    caller in process row p would pass to p?gemm/descinit_
    (`examples/conflux_miniapp.cpp:404-500` builds these for the pdgemm
    validation). Entries: [DTYPE_, CTXT_, M_, N_, MB_, NB_, RSRC_, CSRC_,
    LLD_]; LLD_ is the caller's local leading dimension (column-major,
    ScaLAPACK convention), i.e. its numroc row count — it depends only on
    the process ROW, so no column coordinate is taken.
    """
    lld = max(1, numroc(layout.M, layout.vr, p, 0, layout.Prows))
    return np.array(
        [1, ctxt, layout.M, layout.N, layout.vr, layout.vc, 0, 0, lld],
        dtype=np.int64,
    )


def indxg2p(ig: int, nb: int, isrcproc: int, nprocs: int) -> int:
    """Owning process coordinate of global index `ig` (0-based form of
    ScaLAPACK TOOLS `INDXG2P`, the coordinate half of the
    `examples/utils.hpp` glue)."""
    return (isrcproc + ig // nb) % nprocs


def indxg2l(ig: int, nb: int, nprocs: int) -> int:
    """Local index of global index `ig` on its owning process (0-based
    `INDXG2L`). Together with `indxg2p` this defines ScaLAPACK's local
    element placement; `to_scalapack` is verified against it."""
    return (ig // (nb * nprocs)) * nb + ig % nb


def to_scalapack(A: np.ndarray, layout: BlockCyclicLayout
                 ) -> tuple[list[list[np.ndarray]], list[list[np.ndarray]]]:
    """Distribute a host matrix into ScaLAPACK-convention local buffers.

    Returns (locals, descs): `locals[p][q]` is the column-major
    (Fortran-order) local matrix process (p, q) would pass to a p?gemm /
    p?getrf call, `descs[p][q]` its 9-integer array descriptor. The
    reference hands matrices to ScaLAPACK for its pdgemm-based validation
    (`examples/conflux_miniapp.cpp:404-500`); this is the equivalent
    export surface, so factors computed here can be consumed by an
    existing ScaLAPACK pipeline (and vice versa via `from_scalapack`).

    Element placement: ScaLAPACK's local matrix is the owned blocks
    packed densely in global order — the same index map as our row-major
    shard buffers — so the conversion is a memory-order change plus the
    descriptor, not a re-bucketing.
    """
    shards = scatter(A, layout)
    locals_ = [[np.asfortranarray(shards[p][q])
                for q in range(layout.Pcols)] for p in range(layout.Prows)]
    descs = [[scalapack_desc(layout, p=p) for _q in range(layout.Pcols)]
             for p in range(layout.Prows)]
    return locals_, descs


def from_scalapack(locals_: list[list[np.ndarray]],
                   layout: BlockCyclicLayout) -> np.ndarray:
    """Assemble a host matrix from ScaLAPACK-convention local buffers
    (inverse of :func:`to_scalapack`; accepts any memory order — gather's
    sliced reads are order-agnostic, so no copy is made)."""
    return gather(locals_, layout)


def scatter(A: np.ndarray, layout: BlockCyclicLayout) -> list[list[np.ndarray]]:
    """Split a global matrix into per-coordinate local buffers (tiles in
    local block-cyclic order, row-major within)."""
    return [
        [_gather_tiles(A, layout, p, q) for q in range(layout.Pcols)]
        for p in range(layout.Prows)
    ]


def _gather_tiles(A: np.ndarray, lay: BlockCyclicLayout, p: int, q: int) -> np.ndarray:
    Mt, Nt = lay.tile_counts()
    row_tiles = range(p, Mt, lay.Prows)
    col_tiles = range(q, Nt, lay.Pcols)
    if not len(row_tiles) or not len(col_tiles):
        # this coordinate owns no tiles (grid larger than the tile grid);
        # the empty buffer still carries the one-sided numroc extents so
        # ScaLAPACK consumers see shape[0] == LLD row count
        return np.zeros(lay.local_shape(p, q), A.dtype)
    blocks = [
        np.concatenate(
            [r[:, tj * lay.vc : min((tj + 1) * lay.vc, lay.N)] for tj in col_tiles],
            axis=1,
        )
        for r in (A[ti * lay.vr : min((ti + 1) * lay.vr, lay.M)] for ti in row_tiles)
    ]
    return np.concatenate(blocks, axis=0)


def gather(shards: list[list[np.ndarray]], layout: BlockCyclicLayout) -> np.ndarray:
    """Inverse of :func:`scatter`."""
    dtype = shards[0][0].dtype
    A = np.zeros((layout.M, layout.N), dtype=dtype)
    Mt, Nt = layout.tile_counts()
    for p in range(layout.Prows):
        for q in range(layout.Pcols):
            loc = shards[p][q]
            for li, ti in enumerate(range(p, Mt, layout.Prows)):
                r0, r1 = ti * layout.vr, min((ti + 1) * layout.vr, layout.M)
                for lj, tj in enumerate(range(q, Nt, layout.Pcols)):
                    c0, c1 = tj * layout.vc, min((tj + 1) * layout.vc, layout.N)
                    A[r0:r1, c0:c1] = loc[
                        li * layout.vr : li * layout.vr + (r1 - r0),
                        lj * layout.vc : lj * layout.vc + (c1 - c0),
                    ]
    return A


def transform(shards, src, dst):
    """Redistribute between layouts (the `costa::transform` role,
    `examples/conflux_miniapp.cpp:349-353`). Either side may be a
    :class:`BlockCyclicLayout` (list-of-lists local rectangles) or a
    :class:`CustomLayout` (per-owner tile stores); tile sizes and grids
    may differ; shapes must agree.

    Streams tile intersections directly from source local buffers into
    each destination local buffer — COSTA's whole reason to exist is
    moving between layouts *without* materializing the global matrix
    (`src/conflux/lu/layout.cpp:48`), so peak extra memory here is one
    destination-coordinate buffer (block-cyclic) or one tile (custom),
    never (M, N). Exception: for uniform-square-tile transforms below
    `_NATIVE_TRANSFORM_MAX_BYTES`, an OpenMP fast path trades ~2x the
    matrix of transient memory for one native pass (conflux_tpu.native);
    larger matrices keep the constant-memory walk.
    """
    if (src.M, src.N) != (dst.M, dst.N):
        raise ValueError(f"layout shapes differ: {(src.M, src.N)} vs {(dst.M, dst.N)}")
    if isinstance(dst, CustomLayout):
        fast = _native_bc_to_custom(shards, src, dst)
        if fast is not None:
            return fast
        dtype = _src_dtype(shards, src)
        out: dict = {}
        Mt, Nt = dst.tile_counts()
        for ti in range(Mt):
            for tj in range(Nt):
                h, w = dst.tile_shape(ti, tj)
                tile = np.zeros((h, w), dtype)
                _copy_region(shards, src, ti * dst.vr, ti * dst.vr + h,
                             tj * dst.vc, tj * dst.vc + w, tile, 0, 0)
                out.setdefault(dst.owner(ti, tj), {})[(ti, tj)] = tile
        return out
    fast = _native_custom_to_bc(shards, src, dst)
    if fast is not None:
        return fast
    return [
        [_build_local(shards, src, dst, p, q) for q in range(dst.Pcols)]
        for p in range(dst.Prows)
    ]


# above this source size the native fast paths (which stage ~2x the
# matrix of transient buffers) yield to the constant-extra-memory walk
_NATIVE_TRANSFORM_MAX_BYTES = 1 << 30


def _uniform_square_tiles(src, dst) -> bool:
    """One tile size throughout and exact tiling on both grids — the
    regime the native tile-pack kernel handles (conflux's own layouts;
    everything else falls back to the Python region walk)."""
    bc, cl = (src, dst) if isinstance(dst, CustomLayout) else (dst, src)
    v = bc.vr
    return (bc.vr == bc.vc == cl.vr == cl.vc
            and bc.M % (v * bc.Prows) == 0 and bc.N % (v * bc.Pcols) == 0)


def _native_bc_to_custom(shards, src, dst):
    """Native fast path: block-cyclic -> packed tiles (one OpenMP pass),
    then per-owner VIEWS of the packed buffer — owner-array-agnostic."""
    from conflux_tpu import native

    if not isinstance(src, BlockCyclicLayout) or not _uniform_square_tiles(src, dst):
        return None
    dtype = np.dtype(_src_dtype(shards, src))
    # probe everything cheap BEFORE staging O(M*N) buffers: a missing
    # .so or unsupported dtype must not double the fallback's cost
    if (not native.available() or not native._TILES_OK
            or dtype not in (np.float32, np.float64)
            or src.M * src.N * dtype.itemsize > _NATIVE_TRANSFORM_MAX_BYTES):
        return None
    stacked = np.stack([np.stack([np.ascontiguousarray(shards[p][q])
                                  for q in range(src.Pcols)])
                        for p in range(src.Prows)])
    tiles = native.bc_to_tiles(stacked, src.vr, src.Prows, src.Pcols)
    if tiles is None:
        return None
    Mt, Nt = dst.tile_counts()
    out: dict = {}
    for ti in range(Mt):
        for tj in range(Nt):
            out.setdefault(dst.owner(ti, tj), {})[(ti, tj)] = (
                tiles[ti * Nt + tj])
    return out


def _native_custom_to_bc(store, src, dst):
    """Native fast path for the reverse direction: pack the tile stores
    into global order, then one OpenMP pass into the block-cyclic
    buffer."""
    from conflux_tpu import native

    if not isinstance(src, CustomLayout) or not _uniform_square_tiles(src, dst):
        return None
    dtype = np.dtype(_src_dtype(store, src))
    if (not native.available() or not native._TILES_OK
            or dtype not in (np.float32, np.float64)
            or src.M * src.N * dtype.itemsize > _NATIVE_TRANSFORM_MAX_BYTES):
        return None
    Mt, Nt = src.tile_counts()
    v = src.vr
    tiles = np.empty((Mt * Nt, v, v), dtype)
    for ti in range(Mt):
        for tj in range(Nt):
            tiles[ti * Nt + tj] = store[src.owner(ti, tj)][(ti, tj)]
    out4 = native.tiles_to_bc(tiles, dst.M, dst.N, v, dst.Prows, dst.Pcols)
    if out4 is None:
        return None
    return [[out4[p, q] for q in range(dst.Pcols)]
            for p in range(dst.Prows)]


def _src_dtype(shards, src):
    if isinstance(src, CustomLayout):
        return next(iter(next(iter(shards.values())).values())).dtype
    return shards[0][0].dtype


def _build_local(shards, src, dst: BlockCyclicLayout, p: int,
                 q: int) -> np.ndarray:
    """One destination coordinate's local buffer, assembled from the source
    tiles intersecting each of its tiles. Short trailing tiles are safe on
    both sides: a block-cyclic owner's short tile is always its LAST local
    tile, so full-tile local offsets (li*vr, lj*vc) are exact."""
    Mt, Nt = dst.tile_counts()
    row_tiles = range(p, Mt, dst.Prows)
    col_tiles = range(q, Nt, dst.Pcols)
    dtype = _src_dtype(shards, src)
    if not len(row_tiles) or not len(col_tiles):
        # same one-sided numroc extents as scatter's empty shards
        return np.zeros(dst.local_shape(p, q), dtype)
    loc = np.zeros(dst.local_shape(p, q), dtype)
    for li, ti in enumerate(row_tiles):
        r0, r1 = ti * dst.vr, min((ti + 1) * dst.vr, dst.M)
        for lj, tj in enumerate(col_tiles):
            c0, c1 = tj * dst.vc, min((tj + 1) * dst.vc, dst.N)
            _copy_region(shards, src, r0, r1, c0, c1,
                         loc, li * dst.vr, lj * dst.vc)
    return loc
