"""Tiered session residency: device / host / disk, spill and revival.

A device holds a few thousand resident factor sets at N=256; the north
star ("millions of users") does not fit, and before this layer the
fleet's only behavior under memory pressure was an allocator OOM that
killed every session at once. :class:`ResidentSet` bounds the
device-resident fleet by session count and bytes and moves the overflow
down a three-tier ladder:

- **device** — a normal :class:`~conflux_tpu.serve.SolveSession`:
  factors, base matrix, Woodbury state and probe row resident, solves
  are substitution-only.
- **host** — the session's FULL state (factor pytree, A0, the Woodbury
  ``(Up, Vp, Y, Cinv)`` correction, the cached probe row ``wA``, and
  the drift bookkeeping) swapped out as numpy arrays. Eviction is
  batch-amortized: a spill batch stashes every victim's device arrays
  under its own session lock (cheap pointer swaps), then ONE
  ``jax.device_get`` moves the whole batch's pytrees across — one
  blocking sync per eviction wave, not one per session, and never more
  than one session lock held at a time.
- **disk** — cold host records demoted to the §11 checkpoint
  serialization (`conflux_tpu.io`'s headered binary format, one file
  per pytree leaf plus a JSON manifest with shapes/dtypes/CRCs). The
  same records back :func:`save_fleet`/:func:`load_fleet` — the engine
  checkpoint/restore surface — so a crashed or upgraded server restarts
  with its fleet intact instead of cold-start-storming the factor lane.

Revival is transparent: ``solve``/``update``/``refactor`` on a spilled
session fault it back in under the session RLock
(`SolveSession._ensure_resident` -> :meth:`ResidentSet.fault_in`),
choosing between

- **h2d restore** — implant the record's arrays back on device
  (bitwise: a d2h/h2d round trip and the io.py codec never touch
  payload bits, asserted in tests/test_tier.py). Batched restores
  (:meth:`revive_many`, the checkpoint warm-up) ride
  ``batched.stack_host_trees``: one transfer per leaf POSITION for a
  whole same-plan group instead of one per (session, leaf).
- **re-factorization** — when the spilled drift is past
  ``revive_refactor_rank`` the factors are stale anyway, so the drifted
  base ``A0 + U V^H`` is materialized host-side and refactored through
  PR 5's coalesced factor lane (``engine.submit_factor``): a
  thundering-herd revival coalesces into a few vmapped factor
  dispatches instead of serializing narrow ones. Engine worker threads
  (which must not block on their own lane) and engineless managers take
  the direct ``plan._factor_once`` path — the same program family,
  bitwise the same factors.

Robustness rails (DESIGN §20/§23): a revive-lane semaphore bounds
concurrent fault-ins, so a revival storm degrades to bounded latency
instead of device OOM (a timed-out acquisition raises structured
:class:`~conflux_tpu.resilience.SessionSpilled`, the record intact);
every disk record carries per-leaf CRCs and a corrupt one fails ONLY
its owning session with :class:`~conflux_tpu.resilience.RestoreCorrupt`
evidence; `FaultPlan` sites ``spill``/``revive``/``disk_write``/
``disk_read`` inject crashes, delays and byte corruption
deterministically (a spill crash leaves the session resident, a revive
crash leaves it fully spilled — fail-safe in both directions). Every
outcome lands in ``profiler.serve_stats()['tier']``.
"""

from __future__ import annotations

import heapq
import itertools
import json
import os
import shutil
import threading
import time
import zlib
from collections import deque
from typing import Any

import numpy as np

import jax
import jax.numpy as jnp

from conflux_tpu import io as cfio
from conflux_tpu import profiler, resilience
from conflux_tpu.resilience import (
    InjectedFault,
    RestoreCorrupt,
    SessionSpilled,
)

# --------------------------------------------------------------------------- #
# tier counters (merged into profiler.serve_stats()['tier'])
# --------------------------------------------------------------------------- #

_TIER_KEYS = (
    "spills_host",        # sessions spilled device -> host
    "spills_disk",        # host records demoted to the disk tier
    "revives_h2d",        # fault-ins restored host -> device
    "revives_disk",       # fault-ins that read the disk tier first
    "revives_refactor",   # fault-ins that re-factored (stale drift)
    "revive_rejects",     # revive-lane admission timeouts (backpressure)
    "spill_faults",       # injected/real spill failures (session stayed
                          # resident — fail-safe)
    "disk_write_faults",  # demotion failures (record stayed host-tier)
    "restore_corrupt",    # records that failed their CRC on read
    "disk_bytes_written",
    "disk_bytes_read",
    "checkpoints",        # save_fleet calls
    "restores",           # load_fleet calls
    "checkpoint_records_written",  # records freshly serialized (dirty)
    "checkpoint_records_carried",  # clean records carried/copied (§35)
)

_TIER_LOCK = threading.Lock()
_TIER: dict[str, int] = {k: 0 for k in _TIER_KEYS}  # guarded-by: _TIER_LOCK
# fault-in wall-clock window (seconds) — serve_stats reports p50/p95/p99
_FAULT_LAT: deque = deque(maxlen=8192)  # guarded-by: _TIER_LOCK
# live ResidentSets (weak — a manager dies with its owner) for the gauge
# half of tier_stats(): resident/host/disk population, byte high-waters
_SET_REFS: list = []  # guarded-by: _TIER_LOCK


def bump(key: str, n: int = 1) -> None:
    """Count one tier outcome (unknown keys appear lazily)."""
    with _TIER_LOCK:
        _TIER[key] = _TIER.get(key, 0) + n


def _note_latency(dt: float) -> None:
    with _TIER_LOCK:
        _FAULT_LAT.append(dt)


def clear_tier() -> None:
    """Reset the global tier counters + latency window (gauges live on
    the ResidentSets and survive, like engine counters)."""
    with _TIER_LOCK:
        for k in list(_TIER):
            _TIER[k] = 0
        _FAULT_LAT.clear()


def tier_stats() -> dict:
    """Counters + fault-in latency percentiles + gauges merged across
    live ResidentSets — the 'tier' sub-dict of
    `profiler.serve_stats()`."""
    from conflux_tpu.engine import _percentile

    with _TIER_LOCK:
        out: dict[str, Any] = dict(_TIER)
        lats = sorted(_FAULT_LAT)
        alive, dead = [], []
        for ref in _SET_REFS:
            rs = ref()
            (alive if rs is not None else dead).append(
                rs if rs is not None else ref)
        for ref in dead:
            _SET_REFS.remove(ref)
    for pct in (50, 95, 99):
        out[f"fault_in_p{pct}_ms"] = 1e3 * _percentile(lats, pct)
    gauges = {"managed_sessions": 0, "resident_sessions": 0,
              "host_sessions": 0, "disk_sessions": 0,
              "corrupt_sessions": 0, "device_bytes": 0,
              "device_bytes_high_water": 0, "resident_high_water": 0,
              "host_bytes": 0, "disk_bytes": 0}
    for rs in alive:  # each stats() takes only that manager's lock
        s = rs.stats()
        for k in gauges:
            if k in ("device_bytes_high_water", "resident_high_water"):
                gauges[k] = max(gauges[k], s[k])
            else:
                gauges[k] += s[k]
    out.update(gauges)
    return out


def _register_set(rs) -> None:
    import weakref

    ref = weakref.ref(rs)
    with _TIER_LOCK:
        _SET_REFS.append(ref)


# --------------------------------------------------------------------------- #
# leaf codec: any session pytree leaf <-> the io.py headered format
# --------------------------------------------------------------------------- #

# io.py stores float32/float64/int32 (§11's checkpoint dtypes). Every
# other leaf dtype the serve stack produces maps onto them losslessly:
# complex views as real pairs, int64/uint64/uint32 view as int32 words
# (bit-preserving), and the sub-32-bit floats widen exactly (bf16/f16 ->
# f32 is injective). 'enc' in the leaf meta names the inverse.
_IO_NATIVE = ("float32", "float64", "int32")
_VIEW_AS = {"complex64": "float32", "complex128": "float64",
            "int64": "int32", "uint64": "int32", "uint32": "int32"}
_CAST_AS = {"bfloat16": "float32", "float16": "float32", "bool": "int32"}


def _encode_leaf(a: np.ndarray) -> tuple[np.ndarray, dict]:
    """One host leaf -> (2D io.py-storable array, leaf meta). The
    encoding is bit-lossless: 'raw' stores as-is, 'view' reinterprets
    the bytes, 'cast' widens through an injective dtype map."""
    a = np.ascontiguousarray(a)
    name = a.dtype.name
    meta = {"shape": list(a.shape), "dtype": name}
    if name in _IO_NATIVE:
        enc, how = a, "raw"
    elif name in _VIEW_AS:
        enc, how = a.view(np.dtype(_VIEW_AS[name])), "view"
    elif name in _CAST_AS:
        enc, how = a.astype(np.dtype(_CAST_AS[name])), "cast"
    else:
        raise ValueError(
            f"tier codec cannot store dtype {name} (extend _VIEW_AS/"
            "_CAST_AS with a lossless mapping)")
    meta["enc"] = how
    return enc.reshape(1, enc.size), meta


def _decode_leaf(flat: np.ndarray, meta: dict) -> np.ndarray:
    """Inverse of :func:`_encode_leaf` — bitwise."""
    dt = jnp.dtype(meta["dtype"])  # resolves bfloat16 via jax/ml_dtypes
    how = meta["enc"]
    flat = flat.reshape(-1)
    if how == "view":
        flat = flat.view(dt)
    elif how == "cast":
        flat = flat.astype(dt)
    return flat.reshape(tuple(meta["shape"]))


# --------------------------------------------------------------------------- #
# session state <-> leaves dict (+ structural meta)
# --------------------------------------------------------------------------- #


def _extract_state(session) -> tuple[dict, dict]:
    """Read-only snapshot of a resident session's device state as
    ({leaf name: device array}, structural meta). Caller holds the
    session lock (`# requires-lock` discipline — tier code only calls
    this under `with session._lock`)."""
    leaves: dict[str, Any] = {}
    for i, f in enumerate(session._factors):
        leaves[f"f{i}"] = f
    leaves["A0"] = session._A0
    probe_parts = 0
    if session._probe is not None:
        if isinstance(session._probe, tuple):
            # QR least-squares probe: the (u, uA) pair (DESIGN §33) —
            # one leaf per part, shapes differ (M vs N)
            probe_parts = len(session._probe)
            for i, p in enumerate(session._probe):
                leaves[f"probe{i}"] = p
        else:
            leaves["probe"] = session._probe
    upd = session._upd
    if upd is not None:
        for k in ("Up", "Vp", "Y", "Cinv"):
            leaves[k] = upd[k]
    meta = {
        "n_factors": len(session._factors),
        "keep_A": session._A is not None,
        "has_probe": session._probe is not None,
        "probe_parts": probe_parts,
        "upd": (None if upd is None
                else {"k": int(upd["k"]), "kb": int(upd["kb"])}),
        "owns_base": bool(session._owns_base),
        "last_cond": session.last_cond,
        "precision": session._served_tier,
        "auto_rung": int(session._auto_rung),
        "counters": {"factorizations": session.factorizations,
                     "solves": session.solves,
                     "updates": session.updates,
                     "refactors": session.refactors},
    }
    return leaves, meta


def _implant(session, leaves: dict, meta: dict,
             counters: bool = False) -> None:
    """Install a state snapshot (device arrays) into `session` — the
    inverse of :func:`_extract_state`; caller holds the session lock.
    `counters=True` additionally restores the bookkeeping counters (the
    checkpoint-restore path; a same-process fault-in keeps the live
    ones — they were never cleared)."""
    session._factors = tuple(leaves[f"f{i}"]
                             for i in range(meta["n_factors"]))
    session._A0 = leaves["A0"]
    session._A = session._A0 if meta["keep_A"] else None
    pp = int(meta.get("probe_parts", 0) or 0)
    session._probe = (tuple(leaves[f"probe{i}"] for i in range(pp))
                      if pp else leaves.get("probe"))
    # served-tier identity survives spill/restore (.get: pre-§33
    # records carry neither key and restore as native sessions)
    session._served_tier = meta.get("precision")
    session._auto_rung = int(meta.get("auto_rung", 0) or 0)
    session._tier_factors = {}  # derived cross-tier cache: rebuilt lazily
    u = meta["upd"]
    session._upd = (None if u is None else
                    {"k": u["k"], "kb": u["kb"],
                     "Up": leaves["Up"], "Vp": leaves["Vp"],
                     "Y": leaves["Y"], "Cinv": leaves["Cinv"]})
    session._owns_base = meta["owns_base"]
    # the restored buffers are NEW device arrays: any gang slot written
    # from the pre-spill state is stale (spill released the slot, but a
    # version bump keeps the lazy re-sync honest on every implant path)
    session._gang_ver += 1
    if counters:
        c = meta["counters"]
        session.factorizations = c["factorizations"]
        session.solves = c["solves"]
        session.updates = c["updates"]
        session.refactors = c["refactors"]
        session.last_cond = meta["last_cond"]


# --------------------------------------------------------------------------- #
# disk records: one io.py file per leaf + a JSON manifest with CRCs
# --------------------------------------------------------------------------- #


def _write_record(dirpath: str, leaves: dict, meta: dict,
                  faults=None) -> int:
    """Serialize a host-tier state snapshot to `dirpath` (one
    `conflux_tpu.io` binary per leaf + manifest.json naming shapes,
    dtypes, encodings and CRC32s). Returns the bytes written. The
    'disk_write' fault site injects delay/crash before any byte lands
    and, with kind 'nan', corrupts the written record afterwards (the
    next read fails its CRC with :class:`RestoreCorrupt`)."""
    resilience.maybe_fault(faults, "disk_write")
    os.makedirs(dirpath, exist_ok=True)
    manifest: dict[str, Any] = {"format": 1, "meta": meta, "leaves": {}}
    total = 0
    for name, a in leaves.items():
        enc, lmeta = _encode_leaf(np.asarray(a))
        fname = f"{name}.bin"
        cfio.save_matrix(os.path.join(dirpath, fname), enc)
        lmeta["file"] = fname
        lmeta["crc"] = zlib.crc32(enc.tobytes()) & 0xFFFFFFFF
        manifest["leaves"][name] = lmeta
        total += enc.nbytes
    with open(os.path.join(dirpath, "manifest.json"), "w") as f:
        json.dump(manifest, f)
    if resilience.data_fault(faults, "disk_write", "nan") is not None:
        # corrupt the first leaf's payload IN the written file — the
        # deterministic stand-in for bit rot / a torn write; detection
        # happens at read time through the CRC
        first = sorted(manifest["leaves"])[0]
        fpath = os.path.join(dirpath, manifest["leaves"][first]["file"])
        with open(fpath, "r+b") as f:
            f.seek(24)  # just past the io.py header
            f.write(b"\xde\xad\xbe\xef")
    return total


def _read_record(dirpath: str, faults=None) -> tuple[dict, dict]:
    """Deserialize a disk record: (host leaves, meta). Integrity
    failures (missing/truncated files, CRC mismatch, undecodable
    manifest) raise :class:`RestoreCorrupt` with evidence — the caller
    fails ONLY the owning session."""
    resilience.maybe_fault(faults, "disk_read")
    mpath = os.path.join(dirpath, "manifest.json")
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        raise RestoreCorrupt(
            f"spill record manifest unreadable: {mpath!r} ({e})",
            {"path": dirpath}) from e
    leaves: dict[str, Any] = {}
    total = 0
    for name, lmeta in manifest["leaves"].items():
        fpath = os.path.join(dirpath, lmeta["file"])
        try:
            enc = cfio.load_matrix(fpath)
        except (OSError, ValueError) as e:
            raise RestoreCorrupt(
                f"spill record leaf unreadable: {fpath!r} ({e})",
                {"path": dirpath, "leaf": name}) from e
        crc = zlib.crc32(enc.tobytes()) & 0xFFFFFFFF
        if crc != lmeta["crc"]:
            raise RestoreCorrupt(
                f"spill record leaf {name!r} failed its integrity "
                f"check (crc {crc:#010x} != recorded "
                f"{lmeta['crc']:#010x}) — the record is corrupt and "
                "only this session fails",
                {"path": dirpath, "leaf": name,
                 "expected_crc": lmeta["crc"], "got_crc": crc})
        leaves[name] = _decode_leaf(enc, lmeta)
        total += enc.nbytes
    bump("disk_bytes_read", total)
    return leaves, manifest["meta"]


# --------------------------------------------------------------------------- #
# the spill record
# --------------------------------------------------------------------------- #


def _session_devkey(s):
    """Hashable device identity of a session's placement (None = the
    default device) — the per-device cap accounting key (DESIGN §25)."""
    d = getattr(s, "device", None)
    return None if d is None else (d.platform, d.id)


class _SpillRecord:
    """Where a non-resident session's state lives. `tier` walks
    'transit' (device arrays stashed, d2h pending — a racing fault-in
    reclaims them instantly) -> 'host' (numpy) -> 'disk' (path only).
    'corrupt' pins the RestoreCorrupt a failed read produced, so every
    later touch of this session re-raises the same structured error."""

    __slots__ = ("tier", "leaves", "meta", "path", "nbytes", "error")

    def __init__(self, tier, leaves, meta, path=None, nbytes=0,
                 error=None):
        self.tier = tier
        self.leaves = leaves
        self.meta = meta
        self.path = path
        self.nbytes = nbytes
        self.error = error


def _host_nbytes(leaves: dict) -> int:
    return sum(int(np.asarray(a).nbytes) for a in leaves.values())


def _leaves_to_device(session, leaves: dict) -> dict:
    """Host leaves -> device arrays on the session's placement. Mesh
    sessions re-scatter batch-sharded onto their plan's mesh
    (`batched.shard_host_tree` — every state leaf is batch-axis-leading,
    DESIGN §32); pinned sessions land on their device; unpinned ones on
    the default device (the pre-fleet path, byte-identical). A
    host->device transfer moves bytes, never computes — bitwise on
    every branch."""
    mesh = session.plan.mesh
    if mesh is not None:
        from conflux_tpu.batched import shard_host_tree

        return shard_host_tree(leaves, mesh)
    target = getattr(session, "device", None)
    if target is None:
        return {k: jnp.asarray(v) for k, v in leaves.items()}
    return {k: jax.device_put(v, target) for k, v in leaves.items()}


# --------------------------------------------------------------------------- #
# ResidentSet — the tier manager
# --------------------------------------------------------------------------- #


class ResidentSet:
    """Bounds device-resident sessions by count/bytes; spills overflow
    to host, demotes cold host records to disk, and revives on touch.

    Knobs:

    max_sessions / max_bytes: the device-tier caps. Eviction makes room
        BEFORE a fault-in implants, so the byte gauge's high-water never
        exceeds the cap (the working-set bench asserts it). None = that
        dimension unbounded.
    host_max_sessions / host_max_bytes: host-tier caps; overflow demotes
        the coldest records to `disk_dir` (demotion is skipped — host
        grows — when no disk_dir is configured).
    evict_batch: sessions spilled per eviction wave. Larger batches
        amortize the d2h better (ONE `jax.device_get` per wave) at the
        cost of briefly undershooting the resident set.
    max_concurrent_revives: the revive-lane admission bound — at most
        this many fault-ins materialize device state concurrently, so a
        thundering-herd revival degrades to bounded latency instead of
        transient device OOM. A fault-in that cannot acquire a slot
        within its caller's deadline fails with structured
        :class:`SessionSpilled` (record intact). Engine worker threads
        always pass a bounded wait (the requests' soonest deadline,
        else the engine's `revive_wait`), so a saturated lane degrades
        to structured failures and can never wedge the dispatcher
        behind a client-held slot. 0/None disables.
    revive_refactor_rank: spilled drift rank at which revival
        re-factorizes (through the engine's coalesced factor lane when
        one is attached) instead of restoring stale factors + a fat
        Woodbury correction. None (default) resolves past
        `DriftPolicy.resolved_max_rank` — i.e. never, since `update()`
        refactors beyond that rank anyway — keeping default revivals
        BITWISE; set it lower to trade bitwise restoration for cheaper
        revived solves on heavily drifted sessions.
    engine: the ServeEngine whose factor lane coalesces refactor-
        revivals (attached automatically by ``ServeEngine(residency=)``).
        Engine worker threads never block on their own lane — they take
        the direct factor path (same program family, same bits).
    fault_plan: consulted at the 'spill'/'revive'/'disk_write'/
        'disk_read' sites (falls back to the installed global plan).

    Lock order (enforced at runtime by `scripts/soak.py --lockcheck`):
    session RLock -> manager lock, never the reverse — the manager lock
    guards only registry/gauge state and is never held across a device
    dispatch or another session's lock.
    """

    def __init__(self, *, max_sessions: int | None = None,
                 max_bytes: int | None = None,
                 max_sessions_per_device: int | None = None,
                 max_bytes_per_device: int | None = None,
                 host_max_sessions: int | None = None,
                 host_max_bytes: int | None = None,
                 disk_dir: str | None = None,
                 evict_batch: int = 4,
                 max_concurrent_revives: int | None = 4,
                 revive_refactor_rank: int | None = None,
                 engine=None, fault_plan=None):
        if max_sessions is not None and max_sessions < 1:
            raise ValueError("max_sessions must be >= 1 (a zero-session "
                             "device tier cannot serve)")
        if max_sessions_per_device is not None \
                and max_sessions_per_device < 1:
            raise ValueError("max_sessions_per_device must be >= 1")
        if evict_batch < 1:
            raise ValueError("evict_batch must be >= 1")
        self.max_sessions = max_sessions
        self.max_bytes = max_bytes
        # per-DEVICE caps (DESIGN §25): on a mesh-sharded fleet the
        # global caps alone let one hot device's revival storm evict
        # sessions fleet-wide — victims are picked by LRU regardless of
        # where they live, so a cold device's residents pay for a hot
        # device's pressure AND the hot device still overshoots its own
        # HBM. With these set, each device's population/bytes are
        # bounded separately and victims for a device's overage come
        # from THAT device only. None (default) = global-only, the
        # pre-fleet behavior.
        self.max_sessions_per_device = max_sessions_per_device
        self.max_bytes_per_device = max_bytes_per_device
        self.host_max_sessions = host_max_sessions
        self.host_max_bytes = host_max_bytes
        self.disk_dir = disk_dir
        self.evict_batch = int(evict_batch)
        self.revive_refactor_rank = revive_refactor_rank
        self.engine = engine
        self._faults = fault_plan
        slots = max_concurrent_revives
        if slots and max_sessions is not None:
            # more in-flight revivals than resident slots could land
            # together and overshoot the cap even with eviction making
            # room first — the lane never needs to outnumber the tier
            slots = min(int(slots), int(max_sessions))
        self._revive_sem = (threading.BoundedSemaphore(int(slots))
                            if slots else None)
        self._lock = threading.Lock()
        self._sessions: dict[int, Any] = {}  # guarded-by: _lock
        # id -> resident|spilling|reviving|host|disk|corrupt. A session
        # mid-fault-in is 'reviving' and NEVER an eviction victim, so
        # two concurrent fault-ins can't pick each other (no
        # session-lock cycle); 'spilling' claims a victim so concurrent
        # enforcers don't double-spill it.
        self._state: dict[int, str] = {}     # guarded-by: _lock
        self._bytes: dict[int, int] = {}     # guarded-by: _lock
        # in-flight capacity claims {token: (bytes, sessions, devkey)}: a
        # fault-in/adopt registers its incoming footprint here BEFORE
        # making room, so two concurrent revivals each see the other's
        # reservation and the victim math never lets them land past
        # the caps together (the capacity race the tier chaos soak
        # caught: both sized their eviction against the same snapshot)
        self._claims: dict[int, tuple] = {}  # guarded-by: _lock
        self._claim_seq = itertools.count()
        # O(log F) hot paths (DESIGN §35): `_state` mutations route
        # through `_set_state`, which keeps these incremental views
        # coherent so no hot path ever scans the fleet under `_lock`:
        #  - `_state_counts`: population per state (stats/_resident_now)
        #  - `_claimed_n`/`_claimed_b` (+ per-device `_claims_dev`):
        #    running claim totals (victim math)
        #  - `_dev_res`: per-device resident [count, bytes] census
        #  - `_lru_dev`/`_lru_host`/`_lru_by_dev`: lazy-invalidation
        #    min-heap LRU orders, each a (heap, entry) pair. The heap
        #    holds (stamp, sid) hints; `entry[sid]` is the stamp of the
        #    sid's ONE canonical hint (popped hints that don't match it
        #    are discarded; canonical hints staler than the session's
        #    live `_tier_stamp` are re-pushed at the live stamp). Valid
        #    pops therefore come out in exactly the live-stamp order the
        #    retired full sort produced — victim sets are bitwise
        #    identical (tests/test_scale.py holds the oracle).
        self._state_counts: dict[str, int] = {}     # guarded-by: _lock
        self._claimed_n = 0                         # guarded-by: _lock
        self._claimed_b = 0                         # guarded-by: _lock
        self._claims_dev: dict[Any, list] = {}      # guarded-by: _lock
        self._dev_res: dict[Any, list] = {}         # guarded-by: _lock
        self._devkey: dict[int, Any] = {}           # guarded-by: _lock
        self._lru_dev: tuple[list, dict] = ([], {})   # guarded-by: _lock
        self._lru_host: tuple[list, dict] = ([], {})  # guarded-by: _lock
        self._lru_by_dev: dict[Any, tuple] = {}       # guarded-by: _lock
        # per-device / host-tier LRU maintenance is armed only when the
        # matching caps can ever consume it (heaps nobody pops would
        # grow with churn); arming later rebuilds in one O(F) pass
        self._per_dev_lru = (max_sessions_per_device is not None
                             or max_bytes_per_device is not None)
        self._host_lru = (disk_dir is not None
                          and (host_max_sessions is not None
                               or host_max_bytes is not None))
        # victim-pick implementation: 'heap' (O(victims·log F)) or
        # 'sort' (the retired full-sort — kept as the measured baseline
        # for scripts/replay.py's interleaved before/after legs)
        self._lru_impl = os.environ.get("CONFLUX_TIER_LRU", "heap")
        self._device_bytes = 0               # guarded-by: _lock
        self._device_hw = 0                  # guarded-by: _lock
        self._resident_hw = 0                # guarded-by: _lock
        self._host_bytes = 0                 # guarded-by: _lock
        self._disk_bytes = 0                 # guarded-by: _lock
        self._clock = itertools.count(1)
        self._disk_seq = itertools.count()
        _register_set(self)

    # -------------------------------------------------------------- #
    # registration + the LRU clock
    # -------------------------------------------------------------- #

    def _tick(self) -> int:
        return next(self._clock)

    # -------------------------------------------------------------- #
    # incremental bookkeeping (DESIGN §35): every `_state` mutation
    # goes through `_set_state`, every `_bytes` mutation through
    # `_set_bytes`, every `_claims` mutation through the `_claims_*`
    # helpers — that single-writer discipline is what lets the hot
    # paths read counts and LRU minima instead of scanning the fleet
    # -------------------------------------------------------------- #

    @staticmethod
    # requires-lock: _lock
    def _lru_push(dom: tuple, sid: int, stamp: int) -> None:
        """Install sid's canonical LRU hint at `stamp`. The heap keeps
        any superseded hint as garbage (discarded lazily on pop);
        compaction rebuilds from the canonical map when garbage
        outgrows the live population — amortized O(1), since regrowing
        past the bound takes at least that many pushes."""
        heap, entry = dom
        entry[sid] = stamp
        heapq.heappush(heap, (stamp, sid))
        if len(heap) > 2 * len(entry) + 64:
            heap[:] = [(st, d) for d, st in entry.items()]
            heapq.heapify(heap)

    @staticmethod
    # requires-lock: _lock
    def _lru_drop(dom: tuple, sid: int) -> None:
        dom[1].pop(sid, None)  # the heap hint dies lazily on pop

    # requires-lock: _lock
    def _lru_min(self, dom: tuple):
        """The live LRU minimum of one order domain as (sid, session),
        or None when the domain is empty. Pops discard non-canonical
        hints and refresh canonical-but-stale ones (a touch bumped the
        session's `_tier_stamp` since the hint was pushed) at the live
        stamp, so accepted minima come out in exactly live-stamp order
        — the order the retired full sort produced. The `_tier_stamp`
        read is as racy as the old sort's was: benign staleness by
        design."""
        heap, entry = dom
        while heap:
            stamp, sid = heap[0]
            if entry.get(sid) != stamp:
                heapq.heappop(heap)
                continue
            s = self._sessions.get(sid)
            if s is None:
                heapq.heappop(heap)
                entry.pop(sid, None)
                continue
            live = s._tier_stamp
            if live != stamp:
                heapq.heapreplace(heap, (live, sid))
                entry[sid] = live
                continue
            return sid, s
        return None

    # requires-lock: _lock
    def _dev_dom(self, devkey) -> tuple:
        dom = self._lru_by_dev.get(devkey)
        if dom is None:
            dom = ([], {})
            self._lru_by_dev[devkey] = dom
        return dom

    # requires-lock: _lock
    def _enable_per_dev_lru(self) -> None:
        """Arm per-device LRU maintenance after construction (the caps
        were set on a live manager): one O(F) rebuild from the resident
        census, then incremental forever."""
        self._per_dev_lru = True
        self._lru_by_dev.clear()
        for sid, dk in self._devkey.items():
            s = self._sessions.get(sid)
            if s is None:
                continue
            heap, entry = self._dev_dom(dk)
            entry[sid] = s._tier_stamp
            heap.append((s._tier_stamp, sid))
        for heap, _entry in self._lru_by_dev.values():
            heapq.heapify(heap)

    # requires-lock: _lock
    def _enable_host_lru(self) -> None:
        """Arm host-tier LRU maintenance after construction — same
        one-shot O(F) rebuild as `_enable_per_dev_lru`."""
        self._host_lru = True
        heap, entry = self._lru_host
        heap.clear()
        entry.clear()
        for sid, st in self._state.items():
            if st != "host":
                continue
            s = self._sessions.get(sid)
            if s is None:
                continue
            entry[sid] = s._tier_stamp
            heap.append((s._tier_stamp, sid))
        heapq.heapify(heap)

    # requires-lock: _lock
    def _set_state(self, sid: int, s, new: str) -> None:
        """The single writer for `_state[sid]`: transitions update the
        per-state counts, the per-device resident census and the LRU
        order domains in O(log F)."""
        old = self._state.get(sid)
        self._state[sid] = new
        if old == new:
            return
        cnt = self._state_counts
        if old is not None:
            cnt[old] = cnt.get(old, 1) - 1
        cnt[new] = cnt.get(new, 0) + 1
        if old == "resident":
            self._lru_drop(self._lru_dev, sid)
            dk = self._devkey.pop(sid, None)
            dom = self._lru_by_dev.get(dk)
            if dom is not None:
                self._lru_drop(dom, sid)
            d = self._dev_res.get(dk)
            if d is not None:
                d[0] -= 1
                d[1] -= self._bytes.get(sid, 0)
                if d[0] <= 0:
                    self._dev_res.pop(dk, None)
        elif old == "host":
            self._lru_drop(self._lru_host, sid)
        if new == "resident":
            stamp = s._tier_stamp
            self._lru_push(self._lru_dev, sid, stamp)
            dk = _session_devkey(s)
            self._devkey[sid] = dk
            if self._per_dev_lru:
                self._lru_push(self._dev_dom(dk), sid, stamp)
            d = self._dev_res.get(dk)
            if d is None:
                self._dev_res[dk] = [1, self._bytes.get(sid, 0)]
            else:
                d[0] += 1
                d[1] += self._bytes.get(sid, 0)
        elif new == "host" and self._host_lru:
            self._lru_push(self._lru_host, sid, s._tier_stamp)

    # requires-lock: _lock
    def _set_bytes(self, sid: int, nbytes: int) -> None:
        """The single writer for `_bytes[sid]` — keeps the per-device
        resident byte census true while a resident session's footprint
        changes (updates/refactors)."""
        old = self._bytes.get(sid, 0)
        self._bytes[sid] = nbytes
        if self._state.get(sid) == "resident":
            d = self._dev_res.get(self._devkey.get(sid))
            if d is not None:
                d[1] += nbytes - old

    # requires-lock: _lock
    def _claims_add(self, token: int, nbytes: int, count: int,
                    devkey) -> None:
        self._claims[token] = (int(nbytes), int(count), devkey)
        self._claimed_b += int(nbytes)
        self._claimed_n += int(count)
        d = self._claims_dev.get(devkey)
        if d is None:
            self._claims_dev[devkey] = [int(count), int(nbytes)]
        else:
            d[0] += int(count)
            d[1] += int(nbytes)

    # requires-lock: _lock
    def _claims_remove(self, token: int) -> None:
        c = self._claims.pop(token, None)
        if c is None:
            return
        cb, cn, dk = c
        self._claimed_b -= cb
        self._claimed_n -= cn
        d = self._claims_dev.get(dk)
        if d is not None:
            d[0] -= cn
            d[1] -= cb
            if d[0] <= 0 and d[1] <= 0:
                self._claims_dev.pop(dk, None)

    # requires-lock: _lock
    def _claim_retire_one(self, token: int, nbytes: int) -> None:
        """Retire one landed slot's share of a multi-session claim
        (`revive_many` chunks) — the last slot retires the claim."""
        cb, cn, dk = self._claims.get(token, (0, 0, None))
        if cn > 1:
            freed = min(cb, int(nbytes))
            self._claims[token] = (cb - freed, cn - 1, dk)
            self._claimed_b -= freed
            self._claimed_n -= 1
            d = self._claims_dev.get(dk)
            if d is not None:
                d[0] -= 1
                d[1] -= freed
        else:
            self._claims_remove(token)

    def adopt(self, *sessions) -> "ResidentSet":
        """Bring sessions under management (resident ones count against
        the caps immediately and may be evicted; already-spilled ones —
        the lazy checkpoint-restore path — register in their current
        tier). Mesh-sharded sessions tier like any other: spill gathers
        the sharded leaves to one CRC'd host record (`jax.device_get`
        assembles across the mesh), revival re-scatters them
        batch-sharded (`batched.shard_host_tree`) — bitwise both ways
        (DESIGN §32). Chainable."""
        for s in sessions:
            if s._residency is not None and s._residency is not self:
                raise ValueError("session is already managed by a "
                                 "different ResidentSet")
            sid = id(s)
            token = None
            with s._lock:
                s._residency = self
                s._tier_stamp = self._tick()
                # adoption changes persisted identity (manager, tier
                # registration): mark checkpoint-dirty (DESIGN §35)
                s._ckpt_ver += 1
                rec = s._spill
                nb = s.nbytes
                with self._lock:
                    fresh = sid not in self._sessions
                    self._sessions[sid] = s
                    if rec is None:
                        state = self._state.get(sid)
                        if fresh or state is None:
                            # register as 'reviving' + a capacity claim
                            # (exactly a landing fault-in's shape):
                            # concurrent victim math sees the incoming
                            # footprint but can never PICK the adoptee.
                            # The eviction wave itself runs only after
                            # this session lock is released — a
                            # blocking _spill_batch under the adoptee's
                            # lock let two concurrent adopts pick each
                            # other as victims and deadlock A-holds-sX-
                            # waits-sY / B-holds-sY-waits-sX, and let a
                            # re-adoption spill its own adoptee through
                            # the reentrant RLock (review-caught)
                            token = next(self._claim_seq)
                            self._claims_add(token, nb, 1,
                                             _session_devkey(s))
                            self._set_state(sid, s, "reviving")
                        elif state == "resident":
                            # re-adoption of a managed resident
                            # session: already counted — refresh the
                            # byte gauge; _enforce below re-applies
                            # the caps without holding this lock
                            self._device_bytes += \
                                nb - self._bytes.get(sid, 0)
                            self._set_bytes(sid, nb)
                            self._device_hw = max(self._device_hw,
                                                  self._device_bytes)
                        # 'spilling'/'reviving' in flight: the owning
                        # enforcer/fault-in lands the gauges
                    else:
                        self._set_state(sid, s, rec.tier
                                        if rec.tier in ("host", "disk",
                                                        "corrupt")
                                        else "host")
                        self._set_bytes(sid, rec.nbytes)
                        if fresh and rec.tier == "host":
                            self._host_bytes += rec.nbytes
                        elif fresh and rec.tier == "disk":
                            self._disk_bytes += rec.nbytes
            if token is not None:
                # session lock released: make room for the claim, then
                # land it — no session lock held across the spill wave
                try:
                    self._make_room(0, 0)
                finally:
                    with self._lock:
                        # atomic claim -> gauge transfer (see
                        # _fault_in_admitted). Even a failed eviction
                        # wave lands the gauges: the session IS
                        # device-resident, and _enforce below retries
                        # the caps
                        self._claims_remove(token)
                        if self._state.get(sid) == "reviving":
                            self._set_state(sid, s, "resident")
                            self._set_bytes(sid, nb)
                            self._device_bytes += nb
                            self._device_hw = max(self._device_hw,
                                                  self._device_bytes)
                            self._resident_hw = max(
                                self._resident_hw,
                                self._resident_now())
        self._enforce()
        return self

    def sessions(self) -> list:
        """Every managed session, in adoption order."""
        with self._lock:
            return list(self._sessions.values())

    def _note_bytes(self, session) -> None:
        """Refresh one resident session's byte gauge (called by the
        serve layer after updates/refactors change the footprint;
        caller holds the session lock, `nbytes` was computed under it)."""
        nb = session.nbytes
        sid = id(session)
        with self._lock:
            if self._state.get(sid) == "resident":
                self._device_bytes += nb - self._bytes.get(sid, 0)
                self._set_bytes(sid, nb)
                self._device_hw = max(self._device_hw,
                                      self._device_bytes)

    # -------------------------------------------------------------- #
    # spill: device -> host (batch-amortized d2h), host -> disk
    # -------------------------------------------------------------- #

    def spill(self, *sessions) -> int:
        """Explicitly spill sessions to the host tier (idle-set
        trimming; capacity eviction calls the same machinery). Returns
        how many actually moved."""
        victims = []
        with self._lock:
            for s in sessions:
                sid = id(s)
                if self._state.get(sid) == "resident":
                    self._set_state(sid, s, "spilling")
                    victims.append(s)
        return self._spill_batch(victims)

    def spill_lru(self, n: int) -> int:
        """Spill the n least-recently-used resident sessions —
        O(n·log F) off the LRU heap, not a fleet sort."""
        victims: list = []
        with self._lock:
            if self._lru_impl == "sort":
                resident = [s for sid, s in self._sessions.items()
                            if self._state.get(sid) == "resident"]
                resident.sort(key=lambda s: s._tier_stamp)
                for s in resident[:n]:
                    self._set_state(id(s), s, "spilling")
                    victims.append(s)
            else:
                while len(victims) < n:
                    nxt = self._lru_min(self._lru_dev)
                    if nxt is None:
                        break
                    sid, s = nxt
                    self._set_state(sid, s, "spilling")
                    victims.append(s)
        return self._spill_batch(victims)

    def _spill_batch(self, victims: list) -> int:
        """The two-phase batch spill. Phase 1, per victim under its own
        session lock: stash the device-array state in a 'transit'
        record and null the session's fields (pointer swaps, no device
        work). Phase 2, no session locks held: ONE `jax.device_get`
        moves every stashed pytree host-side, then each record flips to
        'host' under a brief re-acquire (skipping any a fault-in
        reclaimed mid-flight). One session lock at a time, one blocking
        sync per batch."""
        recs = []
        for s in victims:
            sid = id(s)
            with s._lock:
                if s._spill is not None:  # raced: already off-device
                    t = s._spill.tier
                    with self._lock:
                        if self._state.get(sid) == "spilling":
                            # a 'transit' record registers as host-tier
                            # (phase 2 pending elsewhere)
                            self._set_state(sid, s, t if t in (
                                "host", "disk", "corrupt") else "host")
                    continue
                try:
                    resilience.maybe_fault(self._faults, "spill")
                except InjectedFault:
                    bump("spill_faults")
                    with self._lock:  # fail-safe: stays resident
                        # the session keeps its OLD stamp — the heap
                        # re-admits it at that stamp, exactly where the
                        # full sort would have placed it
                        self._set_state(sid, s, "resident")
                    continue
                leaves, meta = _extract_state(s)
                rec = _SpillRecord("transit", leaves, meta)
                s._spill = rec
                s._factors = None
                s._A = None
                s._A0 = None
                s._probe = None
                s._upd = None
                s._tier_factors = {}  # derived: dropped, not spilled
                g = s._gang
                if g is not None:
                    # eviction frees the gang slot (DESIGN §26) —
                    # under THIS held session lock, the one legal
                    # session->gang lock order; revival re-adopts
                    # (grouped revivals straight into gang slots via
                    # engine._gang_readopt, singles at next dispatch)
                    g.release(s)
            with self._lock:
                if self._state.get(sid) == "spilling":
                    # guarded like the raced branch above: a fault-in
                    # that reclaimed the transit record mid-handoff
                    # already owns the state; clobbering it to 'host'
                    # would strand a resident session outside the LRU
                    self._set_state(sid, s, "host")
                self._device_bytes -= self._bytes.get(sid, 0)
            recs.append((s, rec))
        if not recs:
            return 0
        with profiler.region("serve.spill"):
            host = jax.device_get([rec.leaves for _s, rec in recs])
        moved = 0
        for (s, rec), hl in zip(recs, host):
            # try-acquire, never block: the lock holder is mid-touch,
            # and every touch path reclaims the transit record itself
            # (`_fault_in_admitted`'s transit branch), so skipping the
            # flip loses nothing — blocking here while holding a
            # revive-lane slot deadlocked against a client waiting on
            # that slot with this session's lock held (soak-caught)
            if not s._lock.acquire(timeout=0.05):
                continue
            try:
                if s._spill is not rec or rec.tier != "transit":
                    continue  # a fault-in reclaimed the transit record
                rec.leaves = hl
                rec.tier = "host"
                rec.nbytes = _host_nbytes(hl)
            finally:
                s._lock.release()
            with self._lock:
                self._set_bytes(id(s), rec.nbytes)
                self._host_bytes += rec.nbytes
            bump("spills_host")
            moved += 1
        self._demote_overflow()
        return moved

    def demote(self, *sessions) -> int:
        """Explicitly demote host-tier sessions to the disk tier."""
        return sum(self._demote_one(s) for s in sessions)

    def _demote_one(self, s) -> int:
        if self.disk_dir is None:
            raise ValueError("demotion needs a disk_dir")
        sid = id(s)
        # try-acquire, never block: demotion is best-effort
        # housekeeping, and a host-tier session's lock can be held by
        # a client waiting on the revive lane — blocking here from a
        # fault-in's _spill_batch (which holds its session lock AND a
        # lane slot) closed a cycle lockcheck caught. On contention the
        # host tier runs transiently over its cap until the next
        # enforce — the safe direction.
        if not s._lock.acquire(timeout=0.05):
            return 0
        try:
            rec = s._spill
            if rec is None or rec.tier != "host":
                return 0
            d = os.path.join(self.disk_dir,
                             f"sess-{sid:x}-{next(self._disk_seq)}")
            try:
                nbytes = _write_record(d, rec.leaves, rec.meta,
                                       self._faults)
            except InjectedFault:
                bump("disk_write_faults")
                shutil.rmtree(d, ignore_errors=True)
                return 0  # fail-safe: the record stays host-tier
            host_nb = rec.nbytes
            rec.tier = "disk"
            rec.path = d
            rec.leaves = None
            rec.nbytes = nbytes
        finally:
            s._lock.release()
        with self._lock:
            self._set_state(sid, s, "disk")
            self._host_bytes -= host_nb
            self._disk_bytes += nbytes
            self._set_bytes(sid, nbytes)
        bump("spills_disk")
        bump("disk_bytes_written", nbytes)
        return 1

    def _demote_overflow(self) -> None:
        if self.disk_dir is None:
            return
        while True:
            victims: list = []
            with self._lock:
                if not self._host_lru:
                    self._enable_host_lru()
                over = 0
                if self.host_max_sessions is not None:
                    over = max(over,
                               self._state_counts.get("host", 0)
                               - self.host_max_sessions)
                if self.host_max_bytes is not None \
                        and self._host_bytes > self.host_max_bytes:
                    over = max(over, 1)
                if over <= 0:
                    return
                heap, entry = self._lru_host
                while len(victims) < over:
                    nxt = self._lru_min(self._lru_host)
                    if nxt is None:
                        break
                    # pop the candidate off the order (demotion may
                    # fail — failures re-enter below, still host-tier)
                    heapq.heappop(heap)
                    entry.pop(nxt[0], None)
                    victims.append(nxt[1])
            if not victims:
                return
            moved = sum(self._demote_one(s) for s in victims)
            with self._lock:
                for s in victims:
                    sid = id(s)
                    if self._state.get(sid) == "host":
                        # demotion failed (fault / lock contention):
                        # the record stays host-tier, back into the LRU
                        # at its unchanged stamp
                        self._lru_push(self._lru_host, sid,
                                       s._tier_stamp)
            if moved == 0:
                return  # nothing demotable (faults): stop, don't spin

    # -------------------------------------------------------------- #
    # capacity enforcement
    # -------------------------------------------------------------- #

    # requires-lock: _lock
    def _resident_now(self) -> int:
        """Device-tier occupancy for the high-water gauge: 'resident'
        sessions plus every in-flight capacity claim. A 'reviving'
        session is represented by its claim alone (it holds no device
        state until it lands, and landing retires the claim
        atomically), and a 'spilling' victim is NOT counted — the
        claim that evicted it already owns its slot, so counting both
        would double-count one slot for the duration of the handoff
        (the accounted-byte gauge retires victims at stash time for
        the same reason)."""
        return self._state_counts.get("resident", 0) + self._claimed_n

    def _claim(self, nbytes: int, count: int, devkey=None) -> int:
        """Reserve incoming device capacity ahead of a fault-in/adopt.
        The reservation participates in every concurrent caller's
        victim math (`_pick_victims`) until released, so simultaneous
        revivals cannot each size their eviction against a snapshot
        blind to the other and land past the caps together. `devkey`
        attributes the incoming footprint to one device for the
        per-device caps. Returns the release token for
        :meth:`_unclaim`."""
        token = next(self._claim_seq)
        with self._lock:
            self._claims_add(token, nbytes, count, devkey)
        return token

    def _unclaim(self, token: int) -> None:
        """Release a capacity claim — called AFTER the landing bytes
        are registered in the gauges (a moment of double-count is
        harmless; a window counted by neither would re-open the race)
        or when the fault-in fails and nothing lands."""
        with self._lock:
            self._claims_remove(token)

    def _pick_victims(self, incoming_bytes: int,
                      incoming_count: int) -> list:
        """Under the manager lock, claim the LRU resident sessions that
        must spill to fit `incoming_count` sessions of `incoming_bytes`
        plus every in-flight capacity claim under the caps. A session
        mid-fault-in is 'reviving' (never 'resident'), so it is never
        picked — which is what keeps two concurrent fault-ins from
        deadlocking on each other's session locks.

        O(victims · log F) off the lazy-invalidation heaps (DESIGN
        §35) — the retired materialize-and-sort baseline survives as
        `_pick_victims_sorted` (CONFLUX_TIER_LRU=sort) for the replay
        bench's interleaved before/after legs; both produce the SAME
        victim set on the same trace (tests/test_scale.py)."""
        if self._lru_impl == "sort":
            return self._pick_victims_sorted(incoming_bytes,
                                             incoming_count)
        with self._lock:
            need_n = 0
            if self.max_sessions is not None:
                need_n = (self._state_counts.get("resident", 0)
                          + self._claimed_n + incoming_count
                          - self.max_sessions)
            need_b = 0
            if self.max_bytes is not None:
                need_b = (self._device_bytes + self._claimed_b
                          + incoming_bytes - self.max_bytes)
            victims: list = []
            freed = 0
            while len(victims) < need_n or freed < need_b:
                nxt = self._lru_min(self._lru_dev)
                if nxt is None:
                    break
                sid, s = nxt
                victims.append(s)
                freed += self._bytes.get(sid, 0)
                self._set_state(sid, s, "spilling")
            # round small count-pressure waves up to the amortization
            # batch (never byte-pressure ones: bytes freed beyond the
            # need would thrash)
            if victims and need_n > 0 and need_b <= 0:
                while len(victims) < self.evict_batch:
                    nxt = self._lru_min(self._lru_dev)
                    if nxt is None:
                        break
                    sid, s = nxt
                    victims.append(s)
                    self._set_state(sid, s, "spilling")
            # per-DEVICE caps (DESIGN §25): each device's overage is
            # relieved by victims living ON that device — LRU within
            # the device — so one hot device's pressure never evicts a
            # cold device's residents, and the hot device itself stays
            # under its own cap. Global victims were already marked
            # 'spilling' above, so the live census credits their relief
            # and the residual per-device need is census + claims − cap.
            if self.max_sessions_per_device is not None \
                    or self.max_bytes_per_device is not None:
                if not self._per_dev_lru:
                    self._enable_per_dev_lru()
                for dk in list(self._dev_res):
                    d = self._dev_res.get(dk)
                    if d is None:
                        continue
                    cl = self._claims_dev.get(dk, (0, 0))
                    need_n_d = need_b_d = 0
                    if self.max_sessions_per_device is not None:
                        need_n_d = (d[0] + cl[0]
                                    - self.max_sessions_per_device)
                    if self.max_bytes_per_device is not None:
                        need_b_d = (d[1] + cl[1]
                                    - self.max_bytes_per_device)
                    dom = self._lru_by_dev.get(dk)
                    while dom is not None \
                            and (need_n_d > 0 or need_b_d > 0):
                        nxt = self._lru_min(dom)
                        if nxt is None:
                            break
                        sid, s = nxt
                        victims.append(s)
                        need_n_d -= 1
                        need_b_d -= self._bytes.get(sid, 0)
                        self._set_state(sid, s, "spilling")
        return victims

    def _pick_victims_sorted(self, incoming_bytes: int,
                             incoming_count: int) -> list:
        """The pre-§35 victim picker: materialize and sort the ENTIRE
        resident list under the manager lock. Kept as the bench
        baseline and the equivalence oracle — same victim sets as the
        heap path, O(F log F) per pick."""
        with self._lock:
            resident = [(sid, s) for sid, s in self._sessions.items()
                        if self._state.get(sid) == "resident"]
            resident.sort(key=lambda e: e[1]._tier_stamp)
            claimed_b = claimed_n = 0
            for cb, cn, _dk in self._claims.values():
                claimed_b += cb
                claimed_n += cn
            need_n = 0
            if self.max_sessions is not None:
                need_n = (len(resident) + claimed_n + incoming_count
                          - self.max_sessions)
            need_b = 0
            if self.max_bytes is not None:
                need_b = (self._device_bytes + claimed_b
                          + incoming_bytes - self.max_bytes)
            victims = []
            freed = 0
            for sid, s in resident:
                if len(victims) >= need_n and freed >= need_b:
                    break
                victims.append(s)
                freed += self._bytes.get(sid, 0)
            if victims and need_n > 0 and need_b <= 0:
                for sid, s in resident[len(victims):]:
                    if len(victims) >= self.evict_batch:
                        break
                    victims.append(s)
            if self.max_sessions_per_device is not None \
                    or self.max_bytes_per_device is not None:
                picked = {id(s) for s in victims}
                by_dev: dict = {}
                for sid, s in resident:
                    by_dev.setdefault(_session_devkey(s),
                                      []).append((sid, s))
                cl_n: dict = {}
                cl_b: dict = {}
                for cb, cn, dk in self._claims.values():
                    cl_n[dk] = cl_n.get(dk, 0) + cn
                    cl_b[dk] = cl_b.get(dk, 0) + cb
                for dk, members in by_dev.items():
                    need_n_d = need_b_d = 0
                    if self.max_sessions_per_device is not None:
                        need_n_d = (len(members) + cl_n.get(dk, 0)
                                    - self.max_sessions_per_device)
                    if self.max_bytes_per_device is not None:
                        res_b = sum(self._bytes.get(sid, 0)
                                    for sid, _s in members)
                        need_b_d = (res_b + cl_b.get(dk, 0)
                                    - self.max_bytes_per_device)
                    taken = freed_d = 0
                    for sid, s in members:
                        if sid in picked:
                            taken += 1
                            freed_d += self._bytes.get(sid, 0)
                    for sid, s in members:  # members keep LRU order
                        if taken >= need_n_d and freed_d >= need_b_d:
                            break
                        if sid in picked:
                            continue
                        victims.append(s)
                        picked.add(sid)
                        taken += 1
                        freed_d += self._bytes.get(sid, 0)
            for s in victims:
                self._set_state(id(s), s, "spilling")
        return victims

    def _make_room(self, incoming_bytes: int,
                   incoming_count: int) -> None:
        victims = self._pick_victims(incoming_bytes, incoming_count)
        if victims:
            self._spill_batch(victims)

    def _enforce(self) -> None:
        self._make_room(0, 0)
        self._demote_overflow()

    # -------------------------------------------------------------- #
    # fault-in (revival)
    # -------------------------------------------------------------- #

    def _refactor_rank(self, session) -> int:
        if self.revive_refactor_rank is not None:
            return int(self.revive_refactor_rank)
        # "stale" by default means past the DriftPolicy refactor
        # trigger — update() refactors beyond resolved_max_rank, so a
        # spilled session can never carry more: default revivals are
        # always h2d (bitwise)
        return session.policy.resolved_max_rank(session.plan.N) + 1

    def fault_in(self, session, timeout: float | None = None) -> bool:
        """Revive a spilled session in place, under its RLock (the
        transparent-revival entry — `SolveSession._ensure_resident` and
        the engine's pre-dispatch hook land here). Returns True when a
        spill record was actually revived, False when the session was
        already resident (a no-op — e.g. a racing touch got there
        first), so batch callers (`revive_many`) count real work only.
        Atomic: the session is either fully revived or fully spilled
        with its record intact — never half-resident. `timeout` bounds
        BOTH waits a fault-in can block on — the session-lock acquire
        and the revive-lane admission slot (the engine passes the
        requests' soonest deadline); expiry raises
        :class:`SessionSpilled` and releases nothing but the caller's
        time.

        The lock acquire MUST honor the timeout for deadlock freedom,
        not just latency: a client-thread refactor-revival legitimately
        holds its session's RLock AND a revive-lane slot while blocking
        on the engine's factor lane, so a dispatcher that blocked
        unbounded here — on that SAME session's lock, or on the lane
        slot the client holds — would close the cycle (client waits on
        dispatcher, dispatcher waits on the client's lock/slot). Engine
        worker threads therefore NEVER wait unbounded: a `timeout=None`
        call from one is bounded by the engine's `revive_wait`, no
        matter which entry path led here (`_revive_for`'s pre-dispatch
        hook, or a session's own `_ensure_resident` after a concurrent
        eviction spilled it mid-dispatch). The bounded waits fail the
        request structurally and keep the dispatcher live to serve the
        factor batch that un-wedges the client."""
        t0 = time.perf_counter()
        if timeout is None:
            eng = self.engine
            if eng is not None and eng._is_worker_thread():
                timeout = eng.revive_wait
        if timeout is None:
            session._lock.acquire()
        elif not session._lock.acquire(timeout=max(0.0, timeout)):
            bump("revive_rejects")
            raise SessionSpilled(
                f"session busy: another thread held its lock past the "
                f"{timeout:.3f}s revive budget (likely a revival in "
                "flight) — the record is intact; retry shortly",
                retry_after=timeout)
        try:
            rec = session._spill
            if rec is None:
                return False
            if rec.tier == "corrupt":
                # re-raise a FRESH copy of the pinned error: the
                # instance is shared across every thread that touches
                # this session, and a raise mutates the exception's
                # traceback — concurrent raises of one object would
                # scribble on each other
                err = rec.error
                raise RestoreCorrupt(str(err),
                                     dict(err.evidence)) from err
            sid = id(session)
            if self._revive_sem is not None:
                ok = (self._revive_sem.acquire() if timeout is None
                      else self._revive_sem.acquire(timeout=timeout))
                if not ok:
                    bump("revive_rejects")
                    raise SessionSpilled(
                        f"revive lane saturated: no admission slot "
                        f"within {timeout:.3f}s — the session stays "
                        "spilled (record intact); retry after an "
                        "in-flight revival completes")
            try:
                with self._lock:
                    self._set_state(sid, session, "reviving")
                self._fault_in_admitted(session, rec, sid)
            except RestoreCorrupt as e:
                bump("restore_corrupt")
                tier0, nb0, path0 = rec.tier, rec.nbytes, rec.path
                rec.tier = "corrupt"
                rec.error = e
                rec.leaves = None
                rec.path = None
                rec.nbytes = 0
                if path0 is not None:
                    # a CRC failure is permanent — the record can
                    # never restore, so reclaim its disk space (the
                    # pinned error keeps the path as evidence)
                    shutil.rmtree(path0, ignore_errors=True)
                with self._lock:
                    self._set_state(sid, session, "corrupt")
                    # retire the dead record from the tier gauges:
                    # without this, _disk_bytes counted the removed
                    # record forever
                    if tier0 == "disk":
                        self._disk_bytes -= nb0
                    elif tier0 == "host":
                        self._host_bytes -= nb0
                    self._set_bytes(sid, 0)
                raise
            except BaseException:
                # injected/real revive failure: fully spilled, record
                # intact — the next touch retries
                with self._lock:
                    if self._state.get(sid) == "reviving":
                        self._set_state(sid, session, rec.tier
                                        if rec.tier in ("host", "disk")
                                        else "host")
                raise
            finally:
                if self._revive_sem is not None:
                    self._revive_sem.release()
            session._tier_stamp = self._tick()
        finally:
            session._lock.release()
        _note_latency(time.perf_counter() - t0)
        return True

    # requires-lock: session lock (held by fault_in)
    def _fault_in_admitted(self, session, rec, sid) -> None:
        resilience.maybe_fault(self._faults, "revive")
        with profiler.region("serve.revive"):
            if rec.tier == "transit":
                leaves, meta = rec.leaves, rec.meta
                from_disk = False
            elif rec.tier == "host":
                leaves, meta = rec.leaves, rec.meta
                from_disk = False
            else:  # disk
                leaves, meta = _read_record(rec.path, self._faults)
                from_disk = True
            u = meta["upd"]
            stale = (u is not None
                     and u["k"] >= self._refactor_rank(session))
            # reserve the incoming footprint BEFORE sizing eviction —
            # a concurrent fault-in's victim math must see it, or two
            # revivals each sized against the same snapshot could land
            # past the caps together
            incoming = (0 if rec.tier == "transit"
                        else _host_nbytes(leaves))
            token = self._claim(incoming, 1, _session_devkey(session))
            try:
                self._make_room(0, 0)
                if stale and rec.tier != "transit":
                    self._revive_refactor(session, leaves, meta)
                    bump("revives_refactor")
                elif rec.tier == "transit":
                    _implant(session, leaves, meta)
                    bump("revives_h2d")
                else:
                    # restores land on the session's placement: pinned
                    # device, plan mesh (batch-sharded re-scatter), or
                    # the default device — byte-for-byte on each branch
                    _implant(session, _leaves_to_device(session, leaves),
                             meta)
                    bump("revives_h2d")
                if from_disk:
                    bump("revives_disk")
                    if rec.path is not None:
                        shutil.rmtree(rec.path, ignore_errors=True)
                session._spill = None
                nb = session.nbytes
                with self._lock:
                    # atomic claim -> gauge transfer: the reservation
                    # retires in the same lock acquisition that counts
                    # the landed session, so no concurrent reader ever
                    # sees it twice (or not at all)
                    self._claims_remove(token)
                    self._set_state(sid, session, "resident")
                    if rec.tier == "host":
                        self._host_bytes -= rec.nbytes
                    elif rec.tier == "disk":
                        self._disk_bytes -= rec.nbytes
                    self._set_bytes(sid, nb)
                    self._device_bytes += nb
                    self._device_hw = max(self._device_hw,
                                          self._device_bytes)
                    self._resident_hw = max(self._resident_hw,
                                            self._resident_now())
            finally:
                self._unclaim(token)

    # requires-lock: session lock (held by fault_in)
    def _revive_refactor(self, session, leaves, meta) -> None:
        """The stale-drift revival path: materialize A1 = A0 + U V^H
        host-side and re-factor it — through the engine's coalesced
        factor lane when one is attached and the caller is not an
        engine worker (a worker blocking on its own lane would
        deadlock), else through the plan's cached bucket-1 factor
        program. The revived session absorbs the drift exactly like a
        DriftPolicy refactor: fresh base, no Woodbury state, counters
        bumped."""
        plan = session.plan
        A0 = np.asarray(leaves["A0"])
        u = meta["upd"]
        if u is not None:
            k = u["k"]
            Up = np.asarray(leaves["Up"])[..., :k]
            Vp = np.asarray(leaves["Vp"])[..., :k]
            Vh = np.conj(np.swapaxes(Vp, -1, -2))
            A1 = (A0 + Up @ Vh).astype(A0.dtype)
        else:
            A1 = A0
        eng = self.engine
        fresh = None
        tier = meta.get("precision")
        target = getattr(session, "device", None)
        # the lane path honors a pinned session's placement only when
        # the engine actually serves that device; otherwise the direct
        # path below factors in place (state stays on its device).
        # Tier-opened sessions skip the lane and re-factor directly at
        # their served tier — the coalesced lane would rebuild them
        # native (a silent precision change across a revive)
        servable = target is None or target in getattr(eng, "devices", ())
        if (eng is not None and tier is None
                and not eng._is_worker_thread() and servable):
            from conflux_tpu.engine import EngineClosed, EngineSaturated

            try:
                fresh = eng.factor(plan, A1, policy=session.policy,
                                   device=target)
            except (EngineClosed, EngineSaturated):
                fresh = None  # lane unavailable: direct path below
        if fresh is not None:
            session._factors = fresh._factors
            session._A0 = fresh._A0
            session._probe = fresh._probe
        else:
            target = getattr(session, "device", None)
            if plan.mesh is not None:
                from conflux_tpu.batched import _shard_batch

                (Ad,) = _shard_batch((jnp.asarray(A1),), plan.mesh)
            else:
                Ad = (jnp.asarray(A1) if target is None
                      else jax.device_put(A1, target))
            with profiler.region("serve.refactor"):
                session._factors = (
                    plan._factor_once(Ad) if tier is None
                    else plan._tier_factor_once(tier, Ad))
            session._A0 = Ad
            session._probe = None
        session._A = (session._A0
                      if (meta["keep_A"] or tier is not None) else None)
        session._upd = None
        session._owns_base = True
        session._served_tier = tier
        session._auto_rung = int(meta.get("auto_rung", 0) or 0)
        session._tier_factors = {}
        session.factorizations += 1
        session.refactors += 1

    def _group_chunks(self, recs: list) -> list:
        """Split a coalesced-revival group into chunks the device caps
        can hold: a whole chunk lands in ONE stacked h2d, so an
        unbounded group would overshoot `max_sessions`/`max_bytes` no
        matter how many victims spilled first (past one cap's worth
        there is nothing left to evict — the e2e drive caught a
        6-session group landing at cap 3). Reviving more than capacity
        is still allowed: later chunks evict earlier ones (LRU), the
        tail ends up resident. Oversized singletons land anyway — the
        `fault_in` semantics: eviction did its best, cap softly
        exceeded."""
        cap_n = self.max_sessions
        if self.max_sessions_per_device is not None:
            cap_n = (self.max_sessions_per_device if cap_n is None
                     else min(cap_n, self.max_sessions_per_device))
        cap_b = self.max_bytes
        if self.max_bytes_per_device is not None:
            cap_b = (self.max_bytes_per_device if cap_b is None
                     else min(cap_b, self.max_bytes_per_device))
        out: list = []
        cur: list = []
        cb = 0
        for s, rec in recs:
            over_n = (cap_n is not None and len(cur) >= cap_n)
            over_b = (cap_b is not None and cur
                      and cb + rec.nbytes > cap_b)
            if cur and (over_n or over_b):
                out.append(cur)
                cur, cb = [], 0
            cur.append((s, rec))
            cb += rec.nbytes
        if cur:
            out.append(cur)
        return out

    def revive_many(self, sessions, timeout: float | None = None) -> int:
        """Coalesced revival of a set of spilled sessions — the
        checkpoint warm-up / prefetch path. Same-plan, undrifted
        host-tier records restore through `batched.stack_host_trees`:
        their leaves numpy-stack (memcpy) and cross in ONE h2d per leaf
        position, then device-side slices implant per session (bitwise
        what per-session `fault_in` restores). Groups are chunked to
        the device caps first — a whole chunk lands at once, so an
        uncapped group would overshoot `max_sessions`/`max_bytes` with
        nothing left to evict; reviving more than capacity is allowed,
        later chunks LRU-evict earlier ones and the tail stays
        resident. Drifted, disk-tier or
        mismatched sessions fall back to `fault_in` individually.
        Returns how many sessions were ACTUALLY revived: no-ops (a
        record reclaimed by a racing direct revival) don't count, and
        revive-lane backpressure on one session/group skips it —
        record intact, `revive_rejects` bumped — instead of abandoning
        the rest, so a partially-saturated lane still makes progress
        (the corrupt-record path keeps raising: that session can never
        revive and the caller should hear it)."""
        from conflux_tpu.batched import stack_host_trees, unstack_tree

        groups: dict[tuple, list] = {}
        rest = []
        landed: list = []
        for s in sessions:
            with s._lock:
                rec = s._spill
                if rec is None:
                    continue
                if (rec.tier != "host" or rec.meta["upd"] is not None
                        or s.plan.mesh is not None):
                    # mesh sessions fault in individually: numpy-
                    # stacking adds a leading axis that would break the
                    # batch-axis-leading shard rule (DESIGN §32)
                    rest.append(s)
                    continue
                key = (id(s.plan), rec.meta["n_factors"],
                       rec.meta["has_probe"],
                       rec.meta.get("probe_parts", 0),
                       rec.meta.get("precision"),
                       rec.meta["keep_A"],
                       _session_devkey(s))
                groups.setdefault(key, []).append(s)
        n = 0
        for group in groups.values():
            if len(group) == 1:
                rest.append(group[0])
                continue
            t0 = time.perf_counter()
            if self._revive_sem is not None:
                ok = (self._revive_sem.acquire() if timeout is None
                      else self._revive_sem.acquire(timeout=timeout))
                if not ok:
                    # lane saturated for THIS group: its sessions stay
                    # spilled (records intact) and the remaining
                    # groups/rest still get their attempt — partial
                    # progress, reported through the return count
                    bump("revive_rejects")
                    continue
            try:
                recs = []
                for s in group:
                    with s._lock:
                        rec = s._spill
                        if rec is not None and rec.tier == "host":
                            recs.append((s, rec))
                if not recs:
                    continue
                # chunked to the device caps (`_group_chunks`): one
                # claim covers each chunk until every member lands (a
                # moment of claim+gauge double-count as slots settle
                # is harmless — the safe direction)
                for chunk in self._group_chunks(recs):
                    token = self._claim(
                        sum(rec.nbytes for _s, rec in chunk),
                        len(chunk), _session_devkey(chunk[0][0]))
                    try:
                        with profiler.region("serve.revive"):
                            self._make_room(0, 0)
                            stacked = stack_host_trees(
                                [rec.leaves for _s, rec in chunk])
                            target = getattr(chunk[0][0], "device",
                                             None)
                            if target is not None:
                                # the grouped h2d lands on the group's
                                # pinned device (groups are keyed by
                                # device, so the chunk is homogeneous)
                                stacked = {
                                    k: jax.device_put(v, target)
                                    for k, v in stacked.items()}
                            slots = unstack_tree(stacked, len(chunk))
                        for (s, rec), dev in zip(chunk, slots):
                            with s._lock:
                                if s._spill is not rec:
                                    continue  # raced a direct fault_in
                                _implant(s, dev, rec.meta)
                                s._spill = None
                                s._tier_stamp = self._tick()
                                nb = s.nbytes
                            sid = id(s)
                            with self._lock:
                                # retire this slot's share of the
                                # chunk claim in the same lock
                                # acquisition that counts it landed
                                self._claim_retire_one(token,
                                                       rec.nbytes)
                                self._set_state(sid, s, "resident")
                                self._host_bytes -= rec.nbytes
                                self._set_bytes(sid, nb)
                                self._device_bytes += nb
                                self._device_hw = max(self._device_hw,
                                                      self._device_bytes)
                                self._resident_hw = max(
                                    self._resident_hw,
                                    self._resident_now())
                            bump("revives_h2d")
                            _note_latency(time.perf_counter() - t0)
                            landed.append(s)
                            n += 1
                    finally:
                        self._unclaim(token)
            finally:
                if self._revive_sem is not None:
                    self._revive_sem.release()
        for s in rest:
            try:
                if self.fault_in(s, timeout=timeout):
                    landed.append(s)
                    n += 1
            except SessionSpilled:
                # per-session backpressure (lane slot or session lock
                # busy past the budget): this session stays spilled,
                # the rest still get their revival attempt
                continue
        eng = self.engine
        if landed and eng is not None \
                and hasattr(eng, "_gang_readopt"):
            # grouped revivals land straight into gang slots (DESIGN
            # §26): adopt the revived fleet eagerly so its first
            # window already dispatches stacked. Advisory; no session
            # lock is held here.
            eng._gang_readopt(landed)
        return n

    # -------------------------------------------------------------- #
    # observability
    # -------------------------------------------------------------- #

    def stats(self) -> dict:
        """Gauges: population per tier, byte totals, and the
        device-tier high-water marks the capacity bound is judged by
        (merged fleet-wide into `profiler.serve_stats()['tier']`)."""
        with self._lock:
            cnt = self._state_counts
            resident = (cnt.get("resident", 0) + cnt.get("spilling", 0)
                        + cnt.get("reviving", 0))
            return {
                "managed_sessions": len(self._sessions),
                "resident_sessions": resident,
                "host_sessions": cnt.get("host", 0),
                "disk_sessions": cnt.get("disk", 0),
                "corrupt_sessions": cnt.get("corrupt", 0),
                "device_bytes": self._device_bytes,
                "device_bytes_high_water": self._device_hw,
                "resident_high_water": self._resident_hw,
                "host_bytes": self._host_bytes,
                "disk_bytes": self._disk_bytes,
                "max_sessions": self.max_sessions,
                "max_bytes": self.max_bytes,
                "max_sessions_per_device": self.max_sessions_per_device,
                "max_bytes_per_device": self.max_bytes_per_device,
                "per_device": self._per_device_locked(),
            }

    # requires-lock: _lock
    def _per_device_locked(self) -> dict:
        """Resident population/bytes per device — the balance gauge the
        per-device caps are judged by (str devkey -> counts; 'None' is
        the default device). Served from the incremental census, not a
        fleet scan."""
        return {str(dk): {"sessions": d[0], "bytes": d[1]}
                for dk, d in self._dev_res.items() if d[0] > 0}


# --------------------------------------------------------------------------- #
# fleet checkpoint / restore (ServeEngine.checkpoint / .restore)
# --------------------------------------------------------------------------- #


def _plan_fields(plan) -> dict:
    # promoted to serve.plan_spec (the fabric shares the codec); these
    # names stay as the tier-local spelling
    from conflux_tpu.serve import plan_spec

    return plan_spec(plan)


def _plan_from_fields(d: dict):
    from conflux_tpu.serve import plan_from_spec

    return plan_from_spec(d)


def _policy_fields(policy) -> dict:
    return {"max_rank": policy.max_rank,
            "cond_limit": policy.cond_limit,
            "refine": policy.refine}


def _load_base_entries(base: str) -> dict:
    """Previous-generation fleet.json entries by name, or {} when the
    base is missing/unreadable (the caller then degrades to a full
    write — a broken base must never break the NEXT checkpoint)."""
    try:
        with open(os.path.join(base, "fleet.json")) as f:
            return {e["name"]: e for e in json.load(f)["sessions"]}
    except (OSError, ValueError, KeyError, TypeError):
        return {}


def save_fleet(path: str, sessions, names=None, *, base=None,
               gen=None, full=True) -> dict:
    """Serialize a fleet snapshot to `path`: one disk record per
    session (the spill serialization, CRCs and all) + fleet.json naming
    each session's record dir, plan key and drift policy. Works across
    tiers WITHOUT moving anything: resident sessions d2h their state,
    host records serialize directly, disk records re-read (the engine's
    `checkpoint()` provides the drain barrier that makes the snapshot
    consistent). Returns {name: record dir}.

    Incremental mode (DESIGN §35): with `base` (the previous
    generation's directory) a session whose `_ckpt_ver` dirty clock
    matches its base entry is CLEAN — its state is bitwise what the
    base already persists. With ``full=False`` the clean session's
    record is NOT rewritten; its fleet.json entry instead points at the
    existing record via a single-hop relative dir
    (``../fleet-NNNNNN/<name>``, re-based every generation so chains
    never deepen), making a delta generation O(dirty) in d2h/CRC/IO
    and O(fleet) only in cheap JSON. With ``full=True`` (compaction,
    and the only mode when `base` is None) every record lands locally —
    clean ones by a byte-identical file copy, no d2h — so the
    generation is self-contained and older generations can be pruned.
    Every entry carries ``ver`` (the dirty clock it persists) and
    ``gen`` (the generation whose WRITE produced the record bytes —
    compaction copies keep their original ``gen``, so replica pushes
    and fail-over staleness gates see through compaction instead of
    re-pushing an unchanged fleet). `gen` is this generation's number;
    None (standalone snapshots) stamps fresh records with 0."""
    os.makedirs(path, exist_ok=True)
    prev_map = _load_base_entries(base) if base is not None else {}
    this_gen = int(gen) if gen is not None else 0
    entries = []
    carried = 0
    for i, s in enumerate(sessions):
        name = names[i] if names is not None else f"s{i:04d}"
        sid = getattr(s, "sid", None)
        with s._lock:
            rec = s._spill
            if rec is not None and rec.tier == "corrupt":
                # corrupt: this session has no state (carrying a stale
                # base record would silently resurrect it). The pinned
                # instance is shared across threads (see fault_in's
                # corrupt branch)
                raise RestoreCorrupt(
                    str(rec.error),
                    dict(rec.error.evidence)) from rec.error
            ver = s._ckpt_ver
            prev = prev_map.get(name)
            clean = (prev is not None and prev.get("ver") == ver
                     and prev.get("sid") == sid)
            src = (os.path.normpath(os.path.join(base, prev["dir"]))
                   if clean else None)
            if clean and not os.path.isdir(src):
                clean = False  # base record gone: degrade to a write
            if clean and not full:
                # delta carry: reference the existing record, zero IO
                entries.append({
                    "name": name,
                    "dir": os.path.relpath(src, path),
                    "plan": _plan_fields(s.plan),
                    "nbytes": prev["nbytes"], "sid": sid,
                    "ver": ver, "gen": prev.get("gen", 0)})
                carried += 1
                continue
            if clean:
                # compaction: localize the record by a byte-identical
                # copy (no d2h, no CRC recompute); keep the original
                # write generation so standbys holding that push stay
                # provably current
                shutil.copytree(src, os.path.join(path, name))
                entries.append({
                    "name": name, "dir": name,
                    "plan": _plan_fields(s.plan),
                    "nbytes": prev["nbytes"], "sid": sid,
                    "ver": ver, "gen": prev.get("gen", 0)})
                carried += 1
                continue
            if rec is None:
                leaves, meta = _extract_state(s)
                leaves = jax.device_get(leaves)
            elif rec.tier == "transit":
                leaves, meta = jax.device_get(rec.leaves), rec.meta
            elif rec.tier == "host":
                leaves, meta = rec.leaves, rec.meta
            else:  # disk ("corrupt" raised above)
                leaves, meta = _read_record(rec.path)
            meta = dict(meta)
            meta["policy"] = _policy_fields(s.policy)
            meta["ckpt_ver"] = ver
            # the stable session id rides the checkpoint (placement
            # identity): a restored fleet re-pins deterministically
            # through engine.place_session. Devices themselves are NOT
            # persisted — the restoring process may have a different
            # device list
            if sid is not None:
                meta["sid"] = sid
            nbytes = _write_record(os.path.join(path, name), leaves,
                                   meta)
        entries.append({"name": name, "dir": name,
                        "plan": _plan_fields(s.plan), "nbytes": nbytes,
                        "sid": sid, "ver": ver, "gen": this_gen})
    doc = {"format": 2, "gen": this_gen, "carried": carried,
           "sessions": entries}
    if base is not None:
        doc["base"] = os.path.basename(os.path.normpath(base))
    with open(os.path.join(path, "fleet.json"), "w") as f:
        json.dump(doc, f, indent=1)
    bump("checkpoints")
    bump("checkpoint_records_carried", carried)
    bump("checkpoint_records_written", len(entries) - carried)
    return {e["name"]: e["dir"] for e in entries}


def load_fleet(path: str, *, residency: ResidentSet | None = None,
               names=None):
    """Rebuild a fleet from a :func:`save_fleet` snapshot. Plans are
    reconstructed from their exact keys; each session comes back with
    its counters, drift policy, Woodbury state and probe row, and
    solves BITWISE identically to its pre-checkpoint self (plain and
    checked paths — asserted in tests/test_tier.py and the CI
    round-trip job).

    With `residency=None` every session is restored device-resident
    (eager h2d — small fleets, tests). With a ResidentSet the sessions
    register in the HOST tier instead and fault in lazily on first
    touch — the scalable warm restart: restore cost is file reads, and
    traffic pulls in exactly the working set (capacity-bounded, revival
    storms coalescing through the usual lanes). Returns the sessions in
    checkpoint order. A corrupt record raises :class:`RestoreCorrupt`
    naming the session; pass over it by deleting its entry from
    fleet.json if partial restore is wanted.

    `names` restores a SUBSET of the snapshot (checkpoint-order
    preserved): the serve fabric's fail-over re-homes a dead host's
    sessions across several survivors, each adopting only the names the
    rendezvous hash assigns it (DESIGN §28). Unknown names raise
    KeyError — a fail-over must never silently under-restore."""
    from conflux_tpu.serve import SolveSession
    from conflux_tpu.update import DriftPolicy

    with open(os.path.join(path, "fleet.json")) as f:
        fleet = json.load(f)
    entries = fleet["sessions"]
    if names is not None:
        want = set(names)
        have = {e["name"] for e in entries}
        if not want <= have:
            raise KeyError(f"snapshot {path} has no session(s) "
                           f"{sorted(want - have)}")
        entries = [e for e in entries if e["name"] in want]
    sessions = []
    for e in entries:
        plan = _plan_from_fields(e["plan"])
        leaves, meta = _read_record(os.path.join(path, e["dir"]))
        pol = (DriftPolicy(**meta["policy"])
               if meta.get("policy") is not None else None)
        s = SolveSession(plan, None, None, None, pol,
                         sid=meta.get("sid"))
        rec = _SpillRecord("host", leaves, meta,
                           nbytes=_host_nbytes(leaves))
        with s._lock:
            c = meta["counters"]
            s.factorizations = c["factorizations"]
            s.solves = c["solves"]
            s.updates = c["updates"]
            s.refactors = c["refactors"]
            s.last_cond = meta["last_cond"]
            s._owns_base = meta["owns_base"]
            # resume the dirty clock where the record left it: the
            # restored session's first mutation makes it delta-dirty
            # again without a spurious full rewrite (DESIGN §35)
            s._ckpt_ver = int(meta.get("ckpt_ver", 0) or 0)
            s._factors = None
            s._spill = rec
        sessions.append(s)
    if residency is not None:
        residency.adopt(*sessions)
    else:
        for s in sessions:
            with s._lock:
                rec = s._spill
                _implant(s, _leaves_to_device(s, rec.leaves), rec.meta)
                s._spill = None
            bump("revives_h2d")
    bump("restores")
    return sessions
