"""Multi-tenant quality of service: SLO classes, weighted fair-share
admission, and per-tenant isolation (DESIGN §30).

Every request through the serve stack has been equal until now — one
global p99 SLO, one admission policy, per-session breakers as the only
isolation. Production traffic at the ROADMAP's scale is tiered: a
paying tenant's interactive solves must hold a tight latency SLO while
a bulk tenant's offline backfill floods the same engine. This module
is the policy layer that prices that difference on the EXISTING rails:

- :class:`QosClass` — the request tag. `engine.submit(session, b,
  qos=QosClass(tenant="gold", tier="latency", slo=0.025))` classifies
  the request; `qos=None` (the default everywhere) keeps the engine
  byte-identical to the pre-QoS stack — the same opt-in discipline as
  `health=None` and `controller=None`.
- :class:`FairShareLedger` — weighted fair-share admission. Each
  tenant's share of `max_pending` is its declared weight over the sum
  of weights; while the engine is CONTENDED (pending above the
  contention fraction) a tenant at/over its share is shed with a
  structured `resilience.TenantThrottled(retry_after=...)` instead of
  queueing in front of everyone else. Below contention admission is
  work-conserving — an idle engine serves the bulk tenant at full
  rate. The deficit-round-robin credit (one quantum distributed by
  weight as each slot frees) lets a throttled tenant's priority-0
  traffic keep admitting at exactly its weighted fraction of the
  measured drain, so "fair share" holds through sustained overload,
  not just at the shed edge.
- :class:`EngineQosState` — the engine-side container: interned
  classes, the ledger, per-class counters and latency rings, and the
  per-tier collect-delay overrides the controller steers. Created
  lazily on the FIRST classified submission; a `qos=None` engine
  never allocates it.

Priority-aware coalescing rides the existing `DeviceLane` window (no
per-class queues or threads): each queued request resolves a per-class
collect delay — `latency` ~0 (dispatch now), `throughput` the engine
window, `batch` a stretched window that pads buckets full — and the
lane's effective deadline is the MIN over the batch's members
(:func:`collect_delay`). A latency-class arrival therefore pulls the
whole window in; batch traffic alone pads it out.

Wire safety: classes cross the fabric's process boundary as plain
dicts (:meth:`QosClass.to_wire` / :func:`class_from_wire`), so
`ServeFabric.solve(..., qos=...)` carries the class to the owning
host's engine unchanged.

All mutable state in :class:`FairShareLedger` and
:class:`EngineQosState` is guarded by the OWNING ENGINE's `_lock` —
the ledger is consulted inside `ServeEngine._admit` and released in
the settle/fail paths, all already under that lock, so QoS adds zero
new locks (and zero new lock-order edges) to the engine's graph.
"""

from __future__ import annotations

import dataclasses
from collections import deque

# the three service tiers, orderd most to least latency-sensitive; the
# tier picks the request's default collect delay inside the lane window
TIERS = ("latency", "throughput", "batch")

# how far the batch tier stretches the engine's coalescing window by
# default (it exists to pad buckets full, not to answer fast); the
# controller's per-tier override and QosClass.collect_delay both trump
BATCH_STRETCH = 4.0

# bound every per-tier delay (override or stretched default) at the
# same ceiling the adaptive controller's envelope uses
MAX_TIER_DELAY = 0.032

# the canonical fleet request the ledger prices against: one solve
# (factor) of the (32, 256, 256) batched plan the serve docs/benches
# are written around. A request's admission cost is its flop volume
# over this reference, clamped at >= 1.0 so lightweight traffic keeps
# the historical one-slot accounting exactly.
REF_SOLVE_UNITS = 32 * 256 * 256
REF_FACTOR_UNITS = 32 * 256 ** 3


def request_cost(shape, width=None, factor=False) -> float:
    """Byte/flop-aware admission cost of one request, in units of the
    canonical fleet request (clamped >= 1.0).

    `shape` is the plan's key shape — (B, M, N) batched/mesh or (M, N)
    single, with M == N for the square kinds and M > N for tall QR
    least-squares plans (DESIGN §33); `width` the request's RHS width
    (solves); `factor=True` prices the O(M N^2) cold start instead of
    the O(M N w) substitution — both reduce exactly to the former
    N^3 / N^2 w pricing when the plan is square. This is what makes a
    large-N mesh session a HEAVYWEIGHT tenant in the
    :class:`FairShareLedger` (DESIGN §32): one N=4096 mesh solve
    occupies the slots its arithmetic actually displaces, so a flood of
    them sheds at the tenant's share line while lightweight interactive
    traffic keeps admitting — instead of both classes queueing as if
    every request were equal."""
    B = shape[0] if len(shape) == 3 else 1
    M = shape[-2]
    N = shape[-1]
    if factor:
        return max(1.0, B * float(M) * float(N) ** 2 / REF_FACTOR_UNITS)
    w = 1 if width is None else max(1, int(width))
    return max(1.0, B * float(M) * float(N) * w / REF_SOLVE_UNITS)


@dataclasses.dataclass(frozen=True)
class QosClass:
    """One request class: who (tenant), how urgent (tier + priority),
    and against what objective (slo).

    tenant: the isolation domain — quota ledgers, throttle attribution
        and the per-tenant counters all key on it.
    tier: 'latency' (near-zero collect delay), 'throughput' (the
        engine's window), or 'batch' (a stretched window that pads
        buckets full).
    priority: intra-tenant importance, smaller = more important.
        Priority-0 traffic may spend the tenant's deficit-round-robin
        credit while over share; background priorities shed at the
        share line exactly.
    slo: per-class latency objective in SECONDS (None = unmanaged).
        Drives the per-class controller targets and the attainment
        column in `stats()['qos']`.
    weight: the tenant's fair-share weight. A tenant's share of
        `max_pending` is weight over the sum of the weights of every
        tenant the engine has seen (latest declaration wins).
    collect_delay: explicit per-request collect-delay override in
        seconds (None = the tier default).
    """

    tenant: str = "default"
    tier: str = "throughput"
    priority: int = 0
    slo: float | None = None
    weight: float = 1.0
    collect_delay: float | None = None

    def __post_init__(self):
        if not self.tenant or not isinstance(self.tenant, str):
            raise ValueError("qos tenant must be a non-empty string")
        if "/" in self.tenant:
            raise ValueError("qos tenant must not contain '/' (it is "
                             "the tenant/tier key separator)")
        if self.tier not in TIERS:
            raise ValueError(f"qos tier must be one of {TIERS}, "
                             f"got {self.tier!r}")
        if self.slo is not None and not self.slo > 0:
            raise ValueError("qos slo must be > 0 seconds (or None)")
        if not self.weight > 0:
            raise ValueError("qos weight must be > 0")
        if self.collect_delay is not None and self.collect_delay < 0:
            raise ValueError("qos collect_delay must be >= 0 (or None)")

    @property
    def key(self) -> str:
        """The class identity for counters/windows: 'tenant/tier'."""
        return f"{self.tenant}/{self.tier}"

    def to_wire(self) -> dict:
        """A plain-dict encoding safe to pickle/JSON across the fabric
        RPC boundary."""
        return {"tenant": self.tenant, "tier": self.tier,
                "priority": self.priority, "slo": self.slo,
                "weight": self.weight,
                "collect_delay": self.collect_delay}


def class_from_wire(d) -> "QosClass | None":
    """Rebuild a :class:`QosClass` from :meth:`QosClass.to_wire` output
    (None passes through, so wire call sites need no gate)."""
    if d is None:
        return None
    if isinstance(d, QosClass):
        return d
    return QosClass(
        tenant=str(d.get("tenant", "default")),
        tier=str(d.get("tier", "throughput")),
        priority=int(d.get("priority", 0)),
        slo=d.get("slo"),
        weight=float(d.get("weight", 1.0)),
        collect_delay=d.get("collect_delay"))


def collect_delay(cls: "QosClass | None", engine_delay: float,
                  tier_delay: dict) -> float:
    """The class's collect delay inside the lane window.

    Resolution order: the request's own `collect_delay` override, then
    the controller-steered per-tier override (`tier_delay`), then the
    tier default — latency 0, throughput the engine window, batch the
    engine window stretched `BATCH_STRETCH`x (clamped). A `qos=None`
    request resolves to exactly `engine_delay`, the pre-QoS behavior.
    """
    if cls is None:
        return engine_delay
    if cls.collect_delay is not None:
        return min(cls.collect_delay, MAX_TIER_DELAY)
    o = tier_delay.get(cls.tier)
    if o is not None:
        return min(o, MAX_TIER_DELAY)
    if cls.tier == "latency":
        return 0.0
    if cls.tier == "batch":
        return min(engine_delay * BATCH_STRETCH, MAX_TIER_DELAY)
    return engine_delay


class FairShareLedger:
    """Weighted fair-share admission accounting for one engine.

    Every method REQUIRES the owning engine's `_lock` (the ledger is a
    passive structure consulted from `ServeEngine._admit` and released
    from `ServeEngine._take`, both already inside that lock): no lock
    of its own, no new lock-order edges.

    The model: tenant i declares weight w_i (latest declaration wins);
    its share of the admission bound is `w_i / sum(w) * max_pending`.
    While the engine is UNCONTENDED (total pending below `contention`
    x max_pending) every request admits — fair share must never
    throttle an engine with idle capacity. While contended, a tenant
    at/over its share is shed, EXCEPT that priority-0 requests may
    spend the tenant's deficit credit: each slot released distributes
    one quantum across tenants proportional to weight (capped at
    `deficit_cap` x share), so a flooded tenant's interactive traffic
    keeps admitting at its weighted fraction of the drain rate while
    its background tiers take the throttling.
    """

    def __init__(self, contention: float = 0.5,
                 deficit_cap: float = 0.25):
        if not 0 < contention <= 1:
            raise ValueError("contention must be in (0, 1]")
        self.contention = float(contention)   # under the engine lock
        self.deficit_cap = float(deficit_cap)
        self._weight: dict = {}    # tenant -> weight; under engine._lock
        self._pending: dict = {}   # tenant -> in-flight; under engine._lock
        self._deficit: dict = {}   # tenant -> credit; under engine._lock
        self._admitted: dict = {}  # tenant -> total; under engine._lock
        self._throttled: dict = {}  # tenant -> total; under engine._lock

    def note(self, cls: QosClass) -> None:
        """Fold the class's declared weight in (latest wins)."""
        self._weight[cls.tenant] = cls.weight
        self._pending.setdefault(cls.tenant, 0)

    def share(self, tenant: str, max_pending: int) -> float:
        total = sum(self._weight.values())
        if total <= 0:
            return float(max_pending)
        w = self._weight.get(tenant, 0.0)
        return max(1.0, w / total * max_pending)

    def frac(self, tenant: str) -> float:
        """The tenant's weight fraction (its share of the drain)."""
        total = sum(self._weight.values())
        w = self._weight.get(tenant, 0.0)
        return w / total if total > 0 else 1.0

    def try_admit(self, cls: QosClass, engine_pending: int,
                  max_pending: int, cost: float = 1.0) -> "float | None":
        """Admit (count the slot, return None) or throttle (return the
        tenant's over-share backlog for the retry hint). `cost` is the
        request's admission weight in slots (:func:`request_cost`) —
        the default 1.0 keeps the historical one-request-one-slot
        accounting bitwise."""
        self.note(cls)
        t = cls.tenant
        mine = self._pending.get(t, 0)
        share = self.share(t, max_pending)
        if engine_pending < self.contention * max_pending \
                or mine < share:
            self._pending[t] = mine + cost
            self._admitted[t] = self._admitted.get(t, 0) + 1
            return None
        # contended and at/over share: priority-0 may spend credit
        if cls.priority <= 0 and self._deficit.get(t, 0.0) >= cost:
            self._deficit[t] -= cost
            self._pending[t] = mine + cost
            self._admitted[t] = self._admitted.get(t, 0) + 1
            return None
        self._throttled[t] = self._throttled.get(t, 0) + 1
        return mine - share + cost

    def release(self, cls: QosClass, cost: float = 1.0) -> None:
        """One of the tenant's requests resolved: free its slot(s) and
        distribute the freed quantum by weight (the DRR refill — a
        heavyweight settle frees `cost` slots, so it refills `cost`
        quanta)."""
        t = cls.tenant
        self._pending[t] = max(0.0, self._pending.get(t, 0) - cost)
        total = sum(self._weight.values())
        if total <= 0:
            return
        for tt, w in self._weight.items():
            cap = self.deficit_cap * max(1.0, w / total * 64)
            d = self._deficit.get(tt, 0.0) + cost * w / total
            self._deficit[tt] = min(cap, d)

    def stats(self, max_pending: int) -> dict:
        """Per-tenant ledger rows (shares resolved at the current
        admission bound)."""
        return {t: {"weight": self._weight.get(t, 0.0),
                    "share": round(self.share(t, max_pending), 1),
                    "pending": round(self._pending.get(t, 0), 1),
                    "deficit": round(self._deficit.get(t, 0.0), 2),
                    "admitted": self._admitted.get(t, 0),
                    "throttled": self._throttled.get(t, 0)}
                for t in sorted(self._weight)}


class EngineQosState:
    """The engine-side QoS container, created lazily on the first
    classified submission (`ServeEngine._qos`); a `qos=None` engine
    never allocates one. Every mutable field is guarded by the OWNING
    ENGINE's `_lock` — see the module docstring for why that adds no
    lock-order edges."""

    def __init__(self, latency_window: int = 4096):
        self.ledger = FairShareLedger()
        self.classes: dict = {}     # key -> QosClass; under engine._lock
        self.tier_delay: dict = {}  # tier -> s override; under engine._lock
        self.requests: dict = {}    # key -> int; under engine._lock
        self.completed: dict = {}   # key -> int; under engine._lock
        self.failed: dict = {}      # key -> int; under engine._lock
        self.throttled: dict = {}   # key -> int; under engine._lock
        self.latencies: dict = {}   # key -> deque; under engine._lock
        self.lat_seq: dict = {}     # key -> int; under engine._lock
        self._window = int(latency_window)

    def intern(self, cls: QosClass) -> QosClass:
        """Register the class (latest declaration of a key wins — a
        tenant may re-declare weight/slo) and return it."""
        self.classes[cls.key] = cls
        self.ledger.note(cls)
        if cls.key not in self.latencies:
            self.latencies[cls.key] = deque(maxlen=self._window)
            self.lat_seq[cls.key] = 0
        return cls

    def record_admit(self, cls: QosClass) -> None:
        self.requests[cls.key] = self.requests.get(cls.key, 0) + 1

    def record_throttle(self, cls: QosClass) -> None:
        self.throttled[cls.key] = self.throttled.get(cls.key, 0) + 1

    def record_settle(self, cls: QosClass, latency_s: float,
                      cost: float = 1.0) -> None:
        k = cls.key
        self.completed[k] = self.completed.get(k, 0) + 1
        self.latencies[k].append(latency_s)
        self.lat_seq[k] += 1
        self.ledger.release(cls, cost)

    def record_fail(self, cls: QosClass, cost: float = 1.0) -> None:
        self.failed[cls.key] = self.failed.get(cls.key, 0) + 1
        self.ledger.release(cls, cost)

    def counters(self, max_pending: int) -> dict:
        """The sort-free counter rows for `engine.counters()['qos']`."""
        rows = {}
        for k, cls in self.classes.items():
            rows[k] = {
                "tenant": cls.tenant, "tier": cls.tier,
                "priority": cls.priority, "weight": cls.weight,
                "slo_ms": (None if cls.slo is None
                           else 1e3 * cls.slo),
                "requests": self.requests.get(k, 0),
                "completed": self.completed.get(k, 0),
                "failed": self.failed.get(k, 0),
                "throttled": self.throttled.get(k, 0),
            }
        return {"classes": rows,
                "tenants": self.ledger.stats(max_pending),
                "contention": self.ledger.contention,
                "tier_delay": dict(self.tier_delay)}

    def stats(self, max_pending: int) -> dict:
        """`counters()` plus per-class latency percentiles and SLO
        attainment over the rolling rings (the `stats()['qos']` shape).
        """
        from conflux_tpu.engine import _percentile

        out = self.counters(max_pending)
        for k, row in out["classes"].items():
            xs = sorted(self.latencies.get(k, ()))
            row["latency_samples"] = len(xs)
            for pct in (50, 95, 99):
                row[f"latency_p{pct}_ms"] = (
                    1e3 * _percentile(xs, pct) if xs else 0.0)
            cls = self.classes[k]
            if cls.slo is not None and xs:
                within = sum(1 for x in xs if x <= cls.slo)
                row["slo_attainment_pct"] = round(
                    100.0 * within / len(xs), 2)
        return out
