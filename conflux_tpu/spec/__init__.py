"""Executable algorithm specification in pure NumPy.

Role of the reference's `python/` prototype (`python/conflux.py:12-366`,
`python/pivoting.py`): a single-process simulation of every device's buffers
and every collective, used to develop and debug the algorithm without
hardware, with pluggable pivoting strategies.
"""

from conflux_tpu.spec.numpy_lu import simulate_lu, PIVOTING_STRATEGIES

__all__ = ["simulate_lu", "PIVOTING_STRATEGIES"]
