"""Pure-NumPy simulation of the distributed LU — the executable spec.

Simulates all Px*Py*Pz devices' local buffers in one process, with every
collective written out as an explicit sum/gather over the simulated device
array — exactly the technique the reference used to develop its algorithm
without a cluster (`python/conflux.py:40`: `A11Buff = np.zeros([P, Nl, Nl])`).

This spec mirrors `conflux_tpu.lu.distributed` step for step (z-partial
shards, value-level pivot masks, layer-0 factor writes), so tests can check
the shard_map implementation against it buffer-for-buffer. Pivoting is
pluggable (reference `python/pivoting.py:14-18`):

  'tournament' — local candidate LU + stacked election (the production path)
  'partial'    — global partial pivoting by |max| column scan (quality oracle)
  'none'       — no pivoting (only safe for diagonally dominant inputs)
"""

from __future__ import annotations

import numpy as np
import scipy.linalg

from conflux_tpu.geometry import Grid3, LUGeometry


def _lu_packed(A: np.ndarray):
    """Packed LU with row pivoting: returns (lu, perm) with A[perm] = L@U."""
    P, L, U = scipy.linalg.lu(A)
    # perm from P: A = P L U  =>  A[perm] = L U with perm = argmax over P^T
    perm = np.argmax(P.T, axis=1) if P.shape[0] else np.arange(0)
    lu = np.tril(L[:, : U.shape[0]], -1) + np.pad(
        U, ((0, L.shape[0] - U.shape[0]), (0, 0))
    )
    return lu, perm


def _select_tournament(cand: np.ndarray, gri_m: np.ndarray, Px: int, v: int):
    """Local LU picks v candidates per x-rank; one stacked LU elects winners."""
    blocks, gris = [], []
    for px in range(Px):
        _, perm_l = _lu_packed(cand[px])
        top = perm_l[:v]
        blocks.append(cand[px][top])
        gris.append(gri_m[px][top])
    stacked = np.concatenate(blocks, axis=0)
    sgri = np.concatenate(gris, axis=0)
    lu_f, perm_f = _lu_packed(stacked)
    return sgri[perm_f[:v]], lu_f[:v]


def _select_partial(cand: np.ndarray, gri_m: np.ndarray, Px: int, v: int):
    """Global partial pivoting: eliminate column by column over the full
    stacked candidate set (the quality oracle the tournament approximates)."""
    stacked = np.concatenate(list(cand), axis=0).copy()
    sgri = np.concatenate(list(gri_m), axis=0)
    m = stacked.shape[0]
    order = np.arange(m)
    for j in range(v):
        p = j + int(np.argmax(np.abs(stacked[j:, j])))
        stacked[[j, p]] = stacked[[p, j]]
        order[[j, p]] = order[[p, j]]
        piv = stacked[j, j]
        if piv != 0:
            stacked[j + 1 :, j] /= piv
            stacked[j + 1 :, j + 1 :] -= np.outer(stacked[j + 1 :, j], stacked[j, j + 1 :])
    return sgri[order[:v]], stacked[:v]


def _select_none(cand: np.ndarray, gri_m: np.ndarray, Px: int, v: int):
    """Take the v lowest-numbered active rows, in global row order."""
    sgri = np.concatenate(list(gri_m), axis=0)
    stacked = np.concatenate(list(cand), axis=0)
    order = np.argsort(sgri, kind="stable")[:v]
    lu, _ = _lu_packed_nopiv(stacked[order])
    return sgri[order], lu


def _lu_packed_nopiv(A: np.ndarray):
    lu = A.astype(float).copy()
    v = lu.shape[1]
    for j in range(min(v, lu.shape[0])):
        lu[j + 1 :, j] /= lu[j, j]
        lu[j + 1 :, j + 1 :] -= np.outer(lu[j + 1 :, j], lu[j, j + 1 :])
    return lu, np.arange(lu.shape[0])


PIVOTING_STRATEGIES = {
    "tournament": _select_tournament,
    "partial": _select_partial,
    "none": _select_none,
}


def simulate_lu(A: np.ndarray, grid: Grid3, v: int, pivoting: str = "tournament"):
    """Run the full distributed algorithm on simulated devices.

    Returns (LU (M, N) packed factors in original row order, pivots
    (n_steps, v) global rows in elimination order), matching the outputs of
    `conflux_tpu.lu.distributed.lu_factor_distributed` exactly.
    """
    select = PIVOTING_STRATEGIES[pivoting]
    geom = LUGeometry.create(A.shape[0], A.shape[1], v, grid)
    Px, Py, Pz = grid.Px, grid.Py, grid.Pz
    Ml, Nl = geom.Ml, geom.Nl
    nlayr = geom.nlayr

    # per-device state, indexed [x, y, z]
    shards = geom.scatter(A).astype(np.float64)
    Aloc = np.zeros((Px, Py, Pz, Ml, Nl))
    Aloc[:, :, 0] = shards  # data enters on layer z=0
    done = np.zeros((Px, Ml), bool)

    gri = geom.global_row_index()  # single source of truth for the row map
    ctile = np.stack(
        [(np.arange(Nl) // v) * Py + y for y in range(Py)]
    )

    pivots = np.zeros((geom.n_steps, v), np.int64)

    for k in range(geom.n_steps):
        yo, lj = k % Py, (k // Py) * v

        # panel = psum over (y, z) of the owner column        [collective]
        panel = Aloc[:, yo, :, :, lj : lj + v].sum(axis=1)  # (Px, Ml, v)

        # pivot selection over the x axis                     [collective]
        cand = np.where(done[:, :, None], 0.0, panel)
        gri_m = np.where(done, np.iinfo(np.int64).max, gri)
        gpiv, lu00 = select(cand, gri_m, Px, v)
        pivots[k] = gpiv
        U00 = np.triu(lu00)
        L00 = np.tril(lu00, -1) + np.eye(v)

        match = gri[:, :, None] == gpiv[None, None, :]  # (Px, Ml, v)
        is_piv = match.any(axis=2)
        done_new = done | is_piv

        # L10 for active rows (duplicated compute)
        act = np.where(done_new[:, :, None], 0.0, panel)
        # X U00 = act  =>  U00^T X^T = act^T
        L10 = scipy.linalg.solve_triangular(
            U00, act.reshape(-1, v).T, trans="T", lower=False
        ).T.reshape(Px, Ml, v)

        # pivot rows: gather + psum over (x, z)               [collective]
        Prows = np.zeros((Py, v, Nl))
        for x in range(Px):
            for q in range(v):
                hits = np.nonzero(match[x, :, q])[0]
                if hits.size:
                    Prows[:, q, :] += Aloc[x, :, :, hits[0], :].sum(axis=1)
        U01 = np.stack(
            [scipy.linalg.solve_triangular(L00, Prows[y], lower=True, unit_diagonal=True)
             for y in range(Py)]
        )  # (Py, v, Nl)

        # trailing update: each z layer applies its slab
        for x in range(Px):
            for y in range(Py):
                trail = ctile[y] > k
                for z in range(Pz):
                    s0, s1 = z * nlayr, min((z + 1) * nlayr, v)
                    upd = L10[x][:, s0:s1] @ U01[y][s0:s1, :]
                    Aloc[x, y, z][:, trail] -= upd[:, trail]

        # factor writes on layer 0; pivot rows zeroed elsewhere
        for x in range(Px):
            piv_rows = np.nonzero(is_piv[x])[0]
            pos = np.argmax(match[x][piv_rows], axis=1)
            for y in range(Py):
                trail = ctile[y] > k
                for z in range(Pz):
                    if z == 0:
                        Aloc[x, y, z][np.ix_(piv_rows, trail)] = U01[y][pos][:, trail]
                    else:
                        Aloc[x, y, z][np.ix_(piv_rows, trail)] = 0.0
            # panel column on the owner y
            for z in range(Pz):
                col = Aloc[x, yo, z][:, lj : lj + v]
                if z == 0:
                    col[piv_rows] = lu00[pos]
                    active = ~done_new[x]
                    col[active] = L10[x][active]
                else:
                    # pivot + active rows zeroed; earlier-done rows are
                    # already zero on z != 0 from their own step
                    col[~done[x]] = 0.0
                Aloc[x, yo, z][:, lj : lj + v] = col

        done = done_new

    LU = geom.gather(Aloc.sum(axis=2))
    return LU, pivots
