"""Pure-NumPy simulation of the distributed LU — the executable spec.

Simulates all Px*Py*Pz devices' local buffers in one process, with every
collective written out as an explicit sum/gather over the simulated device
array — exactly the technique the reference used to develop its algorithm
without a cluster (`python/conflux.py:40`: `A11Buff = np.zeros([P, Nl, Nl])`).

This spec mirrors `conflux_tpu.lu.distributed` step for step (z-partial
shards, value-level pivot masks, layer-0 factor writes), so tests can check
the shard_map implementation against it buffer-for-buffer. Pivoting is
pluggable (reference `python/pivoting.py:14-18`):

  'tournament' — local candidate LU + stacked election (the production path)
  'partial'    — global partial pivoting by |max| column scan (quality oracle)
  'none'       — no pivoting (only safe for diagonally dominant inputs)
"""

from __future__ import annotations

import numpy as np
import scipy.linalg

from conflux_tpu.geometry import Grid3, LUGeometry


def _lu_packed(A: np.ndarray):
    """Packed LU with row pivoting: returns (lu, perm) with A[perm] = L@U."""
    P, L, U = scipy.linalg.lu(A)
    # perm from P: A = P L U  =>  A[perm] = L U with perm = argmax over P^T
    perm = np.argmax(P.T, axis=1) if P.shape[0] else np.arange(0)
    lu = np.tril(L[:, : U.shape[0]], -1) + np.pad(
        U, ((0, L.shape[0] - U.shape[0]), (0, 0))
    )
    return lu, perm


_ID_SENTINEL = np.iinfo(np.int64).max


def _take_fill(a: np.ndarray, idx: np.ndarray, fill):
    """NumPy mirror of `jnp.take(..., mode='fill')`: out-of-range ids give
    `fill` instead of clamping (the implementation relies on this to keep
    tournament pad ids from aliasing real rows)."""
    out = np.full((len(idx),) + a.shape[1:], fill, dtype=a.dtype)
    ok = idx < a.shape[0]
    out[ok] = a[idx[ok]]
    return out


def _tournament_winners_np(panel: np.ndarray, v: int, chunk: int):
    """NumPy mirror of `ops/blas.tournament_winners`: chunked nomination +
    binary reduction tree of (2v, v) LUs. Same chunk rounding, same pad-id
    convention, same return contract (packed winner LU, winner row ids)."""
    m = panel.shape[0]
    c = min(chunk, -(-m // v) * v)
    c = max(v, c // v * v)
    nch = -(-m // c)
    mp = nch * c
    if mp != m:
        panel = np.pad(panel, ((0, mp - m), (0, 0)))
    cand = panel.reshape(nch, c, v)
    cid = np.arange(mp).reshape(nch, c)

    win, wid, lu0 = [], [], None
    for i in range(nch):
        lu_c, perm_c = _lu_packed(cand[i])
        if i == 0:
            lu0 = lu_c[:v]
        top = perm_c[:v]
        win.append(cand[i][top])
        wid.append(cid[i][top])
    win, wid = np.stack(win), np.stack(wid)

    n = 1 << (nch - 1).bit_length()
    if n != nch:
        win = np.pad(win, ((0, n - nch), (0, 0), (0, 0)))
        wid = np.pad(wid, ((0, n - nch), (0, 0)), constant_values=mp)
    if n == 1:
        return lu0, wid[0]

    lu_top = None
    while n > 1:
        stacked = win.reshape(n // 2, 2 * v, v)
        sid = wid.reshape(n // 2, 2 * v)
        lus, wins, wids = [], [], []
        for i in range(n // 2):
            lu_r, perm_r = _lu_packed(stacked[i])
            top = perm_r[:v]
            lus.append(lu_r[:v])
            wins.append(stacked[i][top])
            wids.append(sid[i][top])
        lu_top, win, wid = np.stack(lus), np.stack(wins), np.stack(wids)
        n //= 2
    return lu_top[0], wid[0]


def _select_tournament(cand: np.ndarray, gri_m: np.ndarray, Px: int, v: int,
                       chunk: int):
    """Chunked CALU: per-x-rank chunked nomination, then the same chunked
    reduction tree elects winners from the Px*v gathered nominees — mirrors
    the shard_map implementation's step-1 exactly (height-bounded LUs)."""
    noms, nids = [], []
    for px in range(Px):
        _, top = _tournament_winners_np(cand[px], v, chunk)
        noms.append(_take_fill(cand[px], top, 0.0))
        nids.append(_take_fill(gri_m[px], top, _ID_SENTINEL))
    stack = np.concatenate(noms, axis=0)
    sids = np.concatenate(nids, axis=0)
    lu00, wid = _tournament_winners_np(stack, v, chunk)
    gpiv = _take_fill(sids, wid, _ID_SENTINEL)
    return gpiv, lu00


def _select_partial(cand: np.ndarray, gri_m: np.ndarray, Px: int, v: int,
                    chunk: int):
    """Global partial pivoting: eliminate column by column over the full
    stacked candidate set (the quality oracle the tournament approximates)."""
    stacked = np.concatenate(list(cand), axis=0).copy()
    sgri = np.concatenate(list(gri_m), axis=0)
    m = stacked.shape[0]
    order = np.arange(m)
    for j in range(v):
        p = j + int(np.argmax(np.abs(stacked[j:, j])))
        stacked[[j, p]] = stacked[[p, j]]
        order[[j, p]] = order[[p, j]]
        piv = stacked[j, j]
        if piv != 0:
            stacked[j + 1 :, j] /= piv
            stacked[j + 1 :, j + 1 :] -= np.outer(stacked[j + 1 :, j], stacked[j, j + 1 :])
    return sgri[order[:v]], stacked[:v]


def _select_none(cand: np.ndarray, gri_m: np.ndarray, Px: int, v: int,
                 chunk: int):
    """Take the v lowest-numbered active rows, in global row order."""
    sgri = np.concatenate(list(gri_m), axis=0)
    stacked = np.concatenate(list(cand), axis=0)
    order = np.argsort(sgri, kind="stable")[:v]
    lu, _ = _lu_packed_nopiv(stacked[order])
    return sgri[order], lu


def _lu_packed_nopiv(A: np.ndarray):
    lu = A.astype(float).copy()
    v = lu.shape[1]
    for j in range(min(v, lu.shape[0])):
        lu[j + 1 :, j] /= lu[j, j]
        lu[j + 1 :, j + 1 :] -= np.outer(lu[j + 1 :, j], lu[j, j + 1 :])
    return lu, np.arange(lu.shape[0])


PIVOTING_STRATEGIES = {
    "tournament": _select_tournament,
    "partial": _select_partial,
    "none": _select_none,
}


def simulate_lu(A: np.ndarray, grid: Grid3, v: int, pivoting: str = "tournament",
                panel_chunk: int = 4096):
    """Run the full distributed algorithm on simulated devices.

    Returns (LU (M, N) packed factors in original row order, pivots
    (n_steps, v) global rows in elimination order), matching the outputs of
    `conflux_tpu.lu.distributed.lu_factor_distributed` exactly.
    `panel_chunk` defaults to the implementation's TPU VMEM-safe chunk
    (`ops/blas._PANEL_CHUNK`); pass the same value used there for
    buffer-exact cross-validation in the chunked regime.
    """
    select = PIVOTING_STRATEGIES[pivoting]
    geom = LUGeometry.create(A.shape[0], A.shape[1], v, grid)
    Px, Py, Pz = grid.Px, grid.Py, grid.Pz
    Ml, Nl = geom.Ml, geom.Nl
    nlayr = geom.nlayr

    # per-device state, indexed [x, y, z]
    shards = geom.scatter(A).astype(np.float64)
    Aloc = np.zeros((Px, Py, Pz, Ml, Nl))
    Aloc[:, :, 0] = shards  # data enters on layer z=0
    done = np.zeros((Px, Ml), bool)

    gri = geom.global_row_index()  # single source of truth for the row map
    ctile = np.stack(
        [(np.arange(Nl) // v) * Py + y for y in range(Py)]
    )

    pivots = np.zeros((geom.n_steps, v), np.int64)

    for k in range(geom.n_steps):
        yo, lj = k % Py, (k // Py) * v

        # panel = psum over (y, z) of the owner column        [collective]
        panel = Aloc[:, yo, :, :, lj : lj + v].sum(axis=1)  # (Px, Ml, v)

        # pivot selection over the x axis                     [collective]
        cand = np.where(done[:, :, None], 0.0, panel)
        gri_m = np.where(done, np.iinfo(np.int64).max, gri)
        gpiv, lu00 = select(cand, gri_m, Px, v, panel_chunk)
        pivots[k] = gpiv
        U00 = np.triu(lu00)
        L00 = np.tril(lu00, -1) + np.eye(v)

        match = gri[:, :, None] == gpiv[None, None, :]  # (Px, Ml, v)
        is_piv = match.any(axis=2)
        done_new = done | is_piv

        # L10 for active rows (duplicated compute)
        act = np.where(done_new[:, :, None], 0.0, panel)
        # X U00 = act  =>  U00^T X^T = act^T
        L10 = scipy.linalg.solve_triangular(
            U00, act.reshape(-1, v).T, trans="T", lower=False
        ).T.reshape(Px, Ml, v)

        # pivot rows: gather + psum over (x, z)               [collective]
        Prows = np.zeros((Py, v, Nl))
        for x in range(Px):
            for q in range(v):
                hits = np.nonzero(match[x, :, q])[0]
                if hits.size:
                    Prows[:, q, :] += Aloc[x, :, :, hits[0], :].sum(axis=1)
        U01 = np.stack(
            [scipy.linalg.solve_triangular(L00, Prows[y], lower=True, unit_diagonal=True)
             for y in range(Py)]
        )  # (Py, v, Nl)

        # trailing update: each z layer applies its slab
        for x in range(Px):
            for y in range(Py):
                trail = ctile[y] > k
                for z in range(Pz):
                    s0, s1 = z * nlayr, min((z + 1) * nlayr, v)
                    upd = L10[x][:, s0:s1] @ U01[y][s0:s1, :]
                    Aloc[x, y, z][:, trail] -= upd[:, trail]

        # factor writes on layer 0; pivot rows zeroed elsewhere
        for x in range(Px):
            piv_rows = np.nonzero(is_piv[x])[0]
            pos = np.argmax(match[x][piv_rows], axis=1)
            for y in range(Py):
                trail = ctile[y] > k
                for z in range(Pz):
                    if z == 0:
                        Aloc[x, y, z][np.ix_(piv_rows, trail)] = U01[y][pos][:, trail]
                    else:
                        Aloc[x, y, z][np.ix_(piv_rows, trail)] = 0.0
            # panel column on the owner y
            for z in range(Pz):
                col = Aloc[x, yo, z][:, lj : lj + v]
                if z == 0:
                    col[piv_rows] = lu00[pos]
                    active = ~done_new[x]
                    col[active] = L10[x][active]
                else:
                    # pivot + active rows zeroed; earlier-done rows are
                    # already zero on z != 0 from their own step
                    col[~done[x]] = 0.0
                Aloc[x, yo, z][:, lj : lj + v] = col

        done = done_new

    LU = geom.gather(Aloc.sum(axis=2))
    return LU, pivots
