"""Pure-NumPy simulation of the distributed LU — the executable spec.

Simulates all Px*Py*Pz devices' local buffers in one process, with every
collective written out as an explicit sum/gather over the simulated device
array — exactly the technique the reference used to develop its algorithm
without a cluster (`python/conflux.py:40`: `A11Buff = np.zeros([P, Nl, Nl])`).

This spec mirrors `conflux_tpu.lu.distributed` step for step (z-partial
shards, value-level pivot masks, layer-0 factor writes), so tests can check
the shard_map implementation against it buffer-for-buffer. Pivoting is
pluggable (reference `python/pivoting.py:14-18`):

  'tournament' — local candidate LU + stacked election (the production path)
  'partial'    — global partial pivoting by |max| column scan (quality oracle)
  'none'       — no pivoting (only safe for diagonally dominant inputs)
"""

from __future__ import annotations

import numpy as np
import scipy.linalg

from conflux_tpu.geometry import Grid3, LUGeometry


def _np_compute_dtype(dtype) -> np.dtype:
    """NumPy mirror of `blas.compute_dtype` (no jax import): panel math
    runs in f32 for narrow types, natively otherwise — the dtype the
    impl resolves its chunk ceilings with."""
    dtype = np.dtype(dtype)
    if dtype == np.float16 or dtype.name == "bfloat16":
        return np.dtype(np.float32)
    return dtype


def _lu_packed(A: np.ndarray):
    """Packed LU with row pivoting: returns (lu, perm) with A[perm] = L@U."""
    P, L, U = scipy.linalg.lu(A)
    # perm from P: A = P L U  =>  A[perm] = L U with perm = argmax over P^T
    perm = np.argmax(P.T, axis=1) if P.shape[0] else np.arange(0)
    lu = np.tril(L[:, : U.shape[0]], -1) + np.pad(
        U, ((0, L.shape[0] - U.shape[0]), (0, 0))
    )
    return lu, perm


_ID_SENTINEL = np.iinfo(np.int64).max


def _take_fill(a: np.ndarray, idx: np.ndarray, fill):
    """NumPy mirror of `jnp.take(..., mode='fill')`: out-of-range ids give
    `fill` instead of clamping (the implementation relies on this to keep
    tournament pad ids from aliasing real rows)."""
    out = np.full((len(idx),) + a.shape[1:], fill, dtype=a.dtype)
    ok = idx < a.shape[0]
    out[ok] = a[idx[ok]]
    return out


def _tournament_winners_np(panel: np.ndarray, v: int, chunk: int):
    """NumPy mirror of `ops/blas.tournament_winners`: chunked nomination +
    binary reduction tree of (2v, v) LUs. Same chunk rounding, same pad-id
    convention, same return contract (packed winner LU, winner row ids)."""
    m = panel.shape[0]
    c = min(chunk, -(-m // v) * v)
    c = max(v, c // v * v)
    nch = -(-m // c)
    mp = nch * c
    if mp != m:
        panel = np.pad(panel, ((0, mp - m), (0, 0)))
    cand = panel.reshape(nch, c, v)
    cid = np.arange(mp).reshape(nch, c)

    win, wid, lu0 = [], [], None
    for i in range(nch):
        lu_c, perm_c = _lu_packed(cand[i])
        if i == 0:
            lu0 = lu_c[:v]
        top = perm_c[:v]
        win.append(cand[i][top])
        wid.append(cid[i][top])
    win, wid = np.stack(win), np.stack(wid)

    n = 1 << (nch - 1).bit_length()
    if n != nch:
        win = np.pad(win, ((0, n - nch), (0, 0), (0, 0)))
        wid = np.pad(wid, ((0, n - nch), (0, 0)), constant_values=mp)
    if n == 1:
        return lu0, wid[0]

    lu_top = None
    while n > 1:
        stacked = win.reshape(n // 2, 2 * v, v)
        sid = wid.reshape(n // 2, 2 * v)
        lus, wins, wids = [], [], []
        for i in range(n // 2):
            lu_r, perm_r = _lu_packed(stacked[i])
            top = perm_r[:v]
            lus.append(lu_r[:v])
            wins.append(stacked[i][top])
            wids.append(sid[i][top])
        lu_top, win, wid = np.stack(lus), np.stack(wins), np.stack(wids)
        n //= 2
    return lu_top[0], wid[0]


def _select_tournament(cand: np.ndarray, gri_m: np.ndarray, Px: int, v: int,
                       chunk: int):
    """Chunked CALU: per-x-rank chunked nomination, then the same chunked
    reduction tree elects winners from the Px*v gathered nominees — mirrors
    the shard_map implementation's step-1 exactly (height-bounded LUs).
    With a single x-rank the nomination IS the election (the implementation
    skips the second tournament; so does the spec, keeping pivot order
    identical)."""
    if Px == 1:
        lu00, top = _tournament_winners_np(cand[0], v, chunk)
        return _take_fill(gri_m[0], top, _ID_SENTINEL), lu00
    noms, nids = [], []
    for px in range(Px):
        _, top = _tournament_winners_np(cand[px], v, chunk)
        noms.append(_take_fill(cand[px], top, 0.0))
        nids.append(_take_fill(gri_m[px], top, _ID_SENTINEL))
    stack = np.concatenate(noms, axis=0)
    sids = np.concatenate(nids, axis=0)
    # the implementation's election tournament is batched, so its chunk is
    # capped at the batched VMEM-safe bound; the helper is imported (not
    # duplicated) so retuning it cannot desynchronize spec and impl
    from conflux_tpu.ops import blas

    # pinned budget, NOT device detection: the spec is pure NumPy and a
    # simulation — its chunking must not depend on which host runs it.
    # dtype is a property of the INPUT (mirrors the impl's compute-dtype
    # resolution), so the spec stays host-independent AND synchronized.
    cap = blas.batched_call_rows(v, _np_compute_dtype(stack.dtype),
                                 budget=blas._SCOPED_VMEM_DEFAULT)
    lu00, wid = _tournament_winners_np(stack, v, min(chunk, cap))
    gpiv = _take_fill(sids, wid, _ID_SENTINEL)
    return gpiv, lu00


def _select_partial(cand: np.ndarray, gri_m: np.ndarray, Px: int, v: int,
                    chunk: int):
    """Global partial pivoting: eliminate column by column over the full
    stacked candidate set (the quality oracle the tournament approximates)."""
    stacked = np.concatenate(list(cand), axis=0).copy()
    sgri = np.concatenate(list(gri_m), axis=0)
    m = stacked.shape[0]
    order = np.arange(m)
    for j in range(v):
        p = j + int(np.argmax(np.abs(stacked[j:, j])))
        stacked[[j, p]] = stacked[[p, j]]
        order[[j, p]] = order[[p, j]]
        piv = stacked[j, j]
        if piv != 0:
            stacked[j + 1 :, j] /= piv
            stacked[j + 1 :, j + 1 :] -= np.outer(stacked[j + 1 :, j], stacked[j, j + 1 :])
    return sgri[order[:v]], stacked[:v]


def _select_none(cand: np.ndarray, gri_m: np.ndarray, Px: int, v: int,
                 chunk: int):
    """Take the v lowest-numbered active rows, in global row order."""
    sgri = np.concatenate(list(gri_m), axis=0)
    stacked = np.concatenate(list(cand), axis=0)
    order = np.argsort(sgri, kind="stable")[:v]
    lu, _ = _lu_packed_nopiv(stacked[order])
    return sgri[order], lu


def _lu_packed_nopiv(A: np.ndarray):
    lu = A.astype(float).copy()
    v = lu.shape[1]
    for j in range(min(v, lu.shape[0])):
        lu[j + 1 :, j] /= lu[j, j]
        lu[j + 1 :, j + 1 :] -= np.outer(lu[j + 1 :, j], lu[j, j + 1 :])
    return lu, np.arange(lu.shape[0])


PIVOTING_STRATEGIES = {
    "tournament": _select_tournament,
    "partial": _select_partial,
    "none": _select_none,
}


def simulate_lu(A: np.ndarray, grid: Grid3, v: int, pivoting: str = "tournament",
                panel_chunk: int | None = None):
    """Run the full distributed algorithm on simulated devices.

    Mirrors the implementation's LAPACK-order layout: rows live at their
    *currently-pivoted global position*; each step swaps the elected pivot
    rows into the step's diagonal block and the displaced occupants into
    the vacated slots (the implementation's value-level answer to the
    reference's `push_pivots_up` compaction, `conflux_opt.hpp:176-218`).

    Returns (LU (M, N) packed factors in original row order, pivots
    (n_steps, v) original global rows in elimination order), matching
    `conflux_tpu.lu.distributed.lu_factor_distributed` (whose shards come
    back pivoted; its `perm[:n_steps*v]` reshaped is this `pivots`).
    `panel_chunk` defaults to the implementation's default
    (`blas.single_call_rows(v)`); pass the same value used there
    for buffer-exact cross-validation in the chunked regime.

    Divergence caveat: the spec pins its chunk ceilings to the 32 MiB
    `blas._SCOPED_VMEM_DEFAULT` so the simulation is host-independent,
    while the implementation honors CONFLUX_TPU_SCOPED_VMEM_BYTES /
    `set_scoped_vmem_bytes` / the device-kind table. When such an
    override is active, default-chunk runs of the two can elect
    different pivots (different nomination brackets). For buffer-exact
    cross-validation either pass an explicit `panel_chunk` to BOTH, or
    assert `blas.scoped_vmem_bytes() == blas._SCOPED_VMEM_DEFAULT`
    first (the spec-vs-impl tests do).
    """
    if panel_chunk is None:
        from conflux_tpu.ops import blas

        # pinned budget (see _select_tournament): host-independent spec;
        # dtype from the input, mirroring lu_factor_distributed
        panel_chunk = blas.single_call_rows(
            v, _np_compute_dtype(np.asarray(A).dtype),
            budget=blas._SCOPED_VMEM_DEFAULT)
    select = PIVOTING_STRATEGIES[pivoting]
    geom = LUGeometry.create(A.shape[0], A.shape[1], v, grid)
    Px, Py, Pz = grid.Px, grid.Py, grid.Pz
    Ml, Nl = geom.Ml, geom.Nl
    nlayr = geom.nlayr

    # per-device state, indexed [x, y, z]
    shards = geom.scatter(A).astype(np.float64)
    Aloc = np.zeros((Px, Py, Pz, Ml, Nl))
    Aloc[:, :, 0] = shards  # data enters on layer z=0

    gp = geom.global_row_index()  # (Px, Ml): global POSITION of local rows
    orig = gp.copy()  # original row id currently at each position
    ctile = np.stack([(np.arange(Nl) // v) * Py + y for y in range(Py)])

    def loc(pos):
        """(x, local row) owning global position pos."""
        t = pos // v
        return t % Px, (t // Px) * v + pos % v

    pivots = np.zeros((geom.n_steps, v), np.int64)

    for k in range(geom.n_steps):
        yo, lj = k % Py, (k // Py) * v
        io, li = k % Px, (k // Px) * v

        # panel = psum over (y, z) of the owner column        [collective]
        panel = Aloc[:, yo, :, :, lj : lj + v].sum(axis=1)  # (Px, Ml, v)

        # pivot selection over the x axis                     [collective]
        live = gp >= k * v
        cand = np.where(live[:, :, None], panel, 0.0)
        pos_m = np.where(live, gp, _ID_SENTINEL)
        wpos, lu00 = select(cand, pos_m, Px, v, panel_chunk)
        U00 = np.triu(lu00)
        L00 = np.tril(lu00, -1) + np.eye(v)

        # swap bookkeeping: winners -> diagonal slots; displaced occupants
        # -> slots vacated by external winners (both ascending)
        slots = k * v + np.arange(v)
        occ_is_winner = np.isin(slots, wpos)
        ext = np.sort(wpos[wpos >= (k + 1) * v])
        disp = np.nonzero(~occ_is_winner)[0]
        assert len(ext) == len(disp)

        # winners' full rows + ids + panel rows (psum over (x, z))
        Prows = np.zeros((Py, v, Nl))
        worig = np.zeros(v, np.int64)
        for j, p in enumerate(wpos):
            xw, lw = loc(p)
            Prows[:, j, :] = Aloc[xw, :, :, lw, :].sum(axis=1)
            worig[j] = orig[xw, lw]
        pivots[k] = worig
        # displaced occupants' rows + ids
        Drows = Aloc[io, :, :, li : li + v, :].sum(axis=1)  # (Py, v, Nl)
        dorig = orig[io, li : li + v].copy()
        diag_panel = panel[io, li : li + v].copy()  # (v, v)

        # swap writes: vacated slots take the displaced rows (full value on
        # z0, zero elsewhere); diagonal rows are rewritten after the GEMM
        panel_post = panel.copy()
        for i, j in enumerate(disp):
            xd, ld = loc(ext[i])
            for y in range(Py):
                Aloc[xd, y, 0, ld] = Drows[y, j]
                Aloc[xd, y, 1:, ld] = 0.0
            orig[xd, ld] = dorig[j]
            panel_post[xd, ld] = diag_panel[j]
        orig[io, li : li + v] = worig

        # L10 on the live row suffix (duplicated compute)
        row_live = gp >= (k + 1) * v
        act = np.where(row_live[:, :, None], panel_post, 0.0)
        # X U00 = act  =>  U00^T X^T = act^T
        L10 = scipy.linalg.solve_triangular(
            U00, act.reshape(-1, v).T, trans="T", lower=False
        ).T.reshape(Px, Ml, v)

        U01 = np.stack(
            [scipy.linalg.solve_triangular(L00, Prows[y], lower=True, unit_diagonal=True)
             for y in range(Py)]
        )  # (Py, v, Nl)

        # trailing update on the (row-suffix x col-suffix) live block
        for x in range(Px):
            rl = row_live[x]
            for y in range(Py):
                trail = ctile[y] > k
                for z in range(Pz):
                    s0, s1 = z * nlayr, min((z + 1) * nlayr, v)
                    upd = L10[x][:, s0:s1] @ U01[y][s0:s1, :]
                    Aloc[x, y, z][np.ix_(rl, trail)] -= upd[np.ix_(rl, trail)]

        # factor writes on layer 0: diagonal rows keep the winners' frozen
        # L prefix (leading cols), take packed lu00 (panel tile) and U01
        # (trailing cols)
        for y in range(Py):
            trail = ctile[y] > k
            Aloc[io, y, 0, li : li + v] = np.where(trail[None, :], U01[y], Prows[y])
            Aloc[io, y, 1:, li : li + v] = 0.0
        # panel column on the owner y
        for x in range(Px):
            for z in range(Pz):
                col = Aloc[x, yo, z][:, lj : lj + v]
                if z == 0:
                    col[row_live[x]] = L10[x][row_live[x]]
                    if x == io:
                        col[li : li + v] = lu00
                else:
                    col[:] = 0.0
                Aloc[x, yo, z][:, lj : lj + v] = col

    LUp = geom.gather(Aloc.sum(axis=2))  # factors in pivoted order
    # permutation: original row id at each global position
    perm = np.empty(geom.M, np.int64)
    for x in range(Px):
        perm[gp[x]] = orig[x]
    LU = np.empty_like(LUp)
    LU[perm] = LUp  # original row order, matching the host wrapper
    return LU, pivots
