"""Tile-level compute kernels (the pluggable BLAS boundary).

TPU-native equivalent of the reference's BLAS shim (`src/conflux/lu/blas.cpp`,
CMake option CONFLUX_BLAS): a small registry of tile ops (gemm, trsm, panel
LU, potrf) with an XLA backend and, for the hot ops, Pallas TPU kernels.
"""

from conflux_tpu.ops.blas import (
    gemm,
    blocked_trsm,
    batched_lu_factor,
    batched_cholesky_factor,
    trsm_left_lower_unit,
    trsm_right_upper,
    panel_lu,
    potrf,
    set_backend,
    get_backend,
)

__all__ = [
    "gemm",
    "blocked_trsm",
    "batched_lu_factor",
    "batched_cholesky_factor",
    "trsm_left_lower_unit",
    "trsm_right_upper",
    "panel_lu",
    "potrf",
    "set_backend",
    "get_backend",
]
