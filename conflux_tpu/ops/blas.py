"""XLA tile kernels behind a swappable backend registry.

Plays the role of the reference's `cosma::gemm` CBLAS shim
(`src/conflux/lu/blas.cpp:15-123`) and its LAPACKE calls (`cblas_dtrsm`,
`cblas_dgemm`, `LAPACKE_dgetrf`, `LAPACKE_dpotrf` — `conflux_opt.hpp:1346,
1537,1626`, `Cholesky.cpp:188`): every tile-level flop in the framework goes
through these entry points, so a Pallas backend can be swapped in without
touching algorithm code. Backends: 'xla' (default — let the compiler tile
onto the MXU) and 'pallas' (hand kernels for the hot ops, see
conflux_tpu/ops/pallas_kernels.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

_BACKEND = "xla"
_VALID_BACKENDS = ("xla", "pallas")

# On TPU, float32 matmuls default to one bfloat16 MXU pass, which is far too
# coarse for factorization-grade accuracy (observed ~1e-2 LU residuals at
# N=1024). Dense linear algebra needs true float32 accumulation, so every
# matmul in this module pins HIGHEST precision; callers wanting the bf16 fast
# path opt in via set_matmul_precision.
_MATMUL_PRECISION = lax.Precision.HIGHEST


def set_matmul_precision(p) -> None:
    global _MATMUL_PRECISION
    _MATMUL_PRECISION = p


def matmul_precision():
    return _MATMUL_PRECISION


def set_backend(name: str) -> None:
    global _BACKEND
    if name not in _VALID_BACKENDS:
        raise ValueError(f"unknown backend {name!r}; valid: {_VALID_BACKENDS}")
    if name == "pallas":
        # fail here, not at first use inside a trace
        import importlib

        importlib.import_module("conflux_tpu.ops.pallas_kernels")
    _BACKEND = name


def get_backend() -> str:
    return _BACKEND


# --------------------------------------------------------------------------- #
# GEMM
# --------------------------------------------------------------------------- #


def gemm(a: jax.Array, b: jax.Array, c: jax.Array | None = None,
         alpha: float = 1.0, beta: float = 1.0,
         precision=None, backend: str | None = None) -> jax.Array:
    """alpha * a @ b (+ beta * c). The trailing-update hot op.

    On TPU the matmul runs on the MXU with float32 accumulation; XLA fuses
    the scale/add epilogue. Inputs keep their dtype (use bfloat16/float32
    for speed, float64 for the validation path).

    `precision` / `backend` default to the module-level settings **at trace
    time**; algorithm entry points resolve them outside jit and pass them as
    static arguments so they participate in the jit cache key.
    """
    backend = _BACKEND if backend is None else backend
    precision = _MATMUL_PRECISION if precision is None else precision
    if backend == "pallas":
        from conflux_tpu.ops import pallas_kernels

        out = pallas_kernels.gemm(a, b)
    else:
        out = jnp.matmul(
            a, b,
            preferred_element_type=_acc_dtype(a.dtype),
            precision=precision,
        )
        out = out.astype(a.dtype)
    if alpha != 1.0:
        out = alpha * out
    if c is not None:
        out = out + (beta * c if beta != 1.0 else c)
    return out


def _acc_dtype(dtype) -> jnp.dtype:
    """MXU accumulation dtype: float32 for narrow types, native otherwise."""
    if dtype in (jnp.bfloat16, jnp.float16):
        return jnp.float32
    return dtype


def compute_dtype(dtype):
    """Dtype for panel factorizations and triangular solves.

    bfloat16 storage uses float32 panel math (LU/potrf kernels have no bf16
    path, and panel accuracy sets the factorization's accuracy); the trailing
    GEMMs stay in the storage dtype so bf16 runs ride the fast MXU path.
    """
    return _acc_dtype(dtype)


# --------------------------------------------------------------------------- #
# Triangular solves
# --------------------------------------------------------------------------- #


def trsm_left_lower_unit(L: jax.Array, B: jax.Array) -> jax.Array:
    """Solve L X = B with L unit lower triangular (A01 panel update,
    reference `conflux_opt.hpp:1537-1551`)."""
    return lax.linalg.triangular_solve(
        L, B, left_side=True, lower=True, unit_diagonal=True
    )


def trsm_right_upper(U: jax.Array, B: jax.Array) -> jax.Array:
    """Solve X U = B with U upper triangular (A10 panel update,
    reference `conflux_opt.hpp:1346-1359`)."""
    return lax.linalg.triangular_solve(
        U, B, left_side=False, lower=False, unit_diagonal=False
    )


def trsm_right_lower_t(L: jax.Array, B: jax.Array) -> jax.Array:
    """Solve X L^T = B with L lower triangular (Cholesky A10 update,
    reference `Cholesky.cpp:218-319` dtrsm)."""
    return lax.linalg.triangular_solve(
        L, B, left_side=False, lower=True, transpose_a=True, unit_diagonal=False
    )


# --------------------------------------------------------------------------- #
# Panel factorizations
# --------------------------------------------------------------------------- #


def panel_lu(panel: jax.Array):
    """Partial-pivoted LU of an (m, v) panel.

    Returns (lu_packed, perm) where perm is a length-m row permutation such
    that panel[perm] == L @ U with L unit-lower (m, v) and U upper (v, v)
    packed into lu_packed. This is the local kernel inside tournament
    pivoting (role of `LUP`, reference `conflux_opt.hpp:143-166`).
    """
    lu_packed, _pivots, perm = lax.linalg.lu(panel)
    return lu_packed, perm


def unit_lower(lu00: jax.Array) -> jax.Array:
    """Extract the unit-lower L00 from a packed (v, v) LU diagonal block."""
    v = lu00.shape[0]
    return jnp.tril(lu00, -1) + jnp.eye(v, dtype=lu00.dtype)


def potrf(a: jax.Array) -> jax.Array:
    """Lower Cholesky factor of a v x v SPD tile (reference dpotrf,
    `Cholesky.cpp:188-194`)."""
    return lax.linalg.cholesky(a, symmetrize_input=False)
