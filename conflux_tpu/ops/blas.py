"""XLA tile kernels behind a swappable backend registry.

Plays the role of the reference's `cosma::gemm` CBLAS shim
(`src/conflux/lu/blas.cpp:15-123`) and its LAPACKE calls (`cblas_dtrsm`,
`cblas_dgemm`, `LAPACKE_dgetrf`, `LAPACKE_dpotrf` — `conflux_opt.hpp:1346,
1537,1626`, `Cholesky.cpp:188`): every tile-level flop in the framework goes
through these entry points, so a Pallas backend can be swapped in without
touching algorithm code. Backends: 'xla' (default — let the compiler tile
onto the MXU) and 'pallas' (hand kernels for the hot ops, see
conflux_tpu/ops/pallas_kernels.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

_BACKEND = "xla"
_VALID_BACKENDS = ("xla", "pallas")

# On TPU, float32 matmuls default to one bfloat16 MXU pass, which is far too
# coarse for factorization-grade accuracy (observed ~1e-2 LU residuals at
# N=1024). Dense linear algebra needs true float32 accumulation, so every
# matmul in this module pins HIGHEST precision; callers wanting the bf16 fast
# path opt in via set_matmul_precision.
_MATMUL_PRECISION = lax.Precision.HIGHEST


def set_matmul_precision(p) -> None:
    global _MATMUL_PRECISION
    _MATMUL_PRECISION = p


def matmul_precision():
    return _MATMUL_PRECISION


def set_backend(name: str) -> None:
    global _BACKEND
    if name not in _VALID_BACKENDS:
        raise ValueError(f"unknown backend {name!r}; valid: {_VALID_BACKENDS}")
    if name == "pallas":
        # fail here, not at first use inside a trace
        import importlib

        importlib.import_module("conflux_tpu.ops.pallas_kernels")
    _BACKEND = name


def get_backend() -> str:
    return _BACKEND


# --------------------------------------------------------------------------- #
# GEMM
# --------------------------------------------------------------------------- #


def gemm(a: jax.Array, b: jax.Array, c: jax.Array | None = None,
         alpha: float = 1.0, beta: float = 1.0,
         precision=None, backend: str | None = None) -> jax.Array:
    """alpha * a @ b (+ beta * c). The trailing-update hot op.

    On TPU the matmul runs on the MXU with float32 accumulation; XLA fuses
    the scale/add epilogue. Inputs keep their dtype (use bfloat16/float32
    for speed, float64 for the validation path).

    `precision` / `backend` default to the module-level settings **at trace
    time**; algorithm entry points resolve them outside jit and pass them as
    static arguments so they participate in the jit cache key.
    """
    backend = _BACKEND if backend is None else backend
    precision = _MATMUL_PRECISION if precision is None else precision
    if backend == "pallas":
        from conflux_tpu.ops import pallas_kernels

        out = pallas_kernels.gemm(a, b)
    else:
        out = jnp.matmul(
            a, b,
            preferred_element_type=_acc_dtype(a.dtype),
            precision=precision,
        )
        out = out.astype(a.dtype)
    if alpha != 1.0:
        out = alpha * out
    if c is not None:
        out = out + (beta * c if beta != 1.0 else c)
    return out


def _acc_dtype(dtype) -> jnp.dtype:
    """MXU accumulation dtype: float32 for narrow types, native otherwise."""
    if dtype in (jnp.bfloat16, jnp.float16):
        return jnp.float32
    return dtype


def compute_dtype(dtype):
    """Dtype for panel factorizations and triangular solves.

    bfloat16 storage uses float32 panel math (LU/potrf kernels have no bf16
    path, and panel accuracy sets the factorization's accuracy); the trailing
    GEMMs stay in the storage dtype so bf16 runs ride the fast MXU path.
    """
    return _acc_dtype(dtype)


# --------------------------------------------------------------------------- #
# Triangular solves
# --------------------------------------------------------------------------- #


def trsm_left_lower_unit(L: jax.Array, B: jax.Array) -> jax.Array:
    """Solve L X = B with L unit lower triangular (A01 panel update,
    reference `conflux_opt.hpp:1537-1551`)."""
    return lax.linalg.triangular_solve(
        L, B, left_side=True, lower=True, unit_diagonal=True
    )


def trsm_right_upper(U: jax.Array, B: jax.Array) -> jax.Array:
    """Solve X U = B with U upper triangular (A10 panel update,
    reference `conflux_opt.hpp:1346-1359`)."""
    return lax.linalg.triangular_solve(
        U, B, left_side=False, lower=False, unit_diagonal=False
    )


def trsm_right_lower_t(L: jax.Array, B: jax.Array) -> jax.Array:
    """Solve X L^T = B with L lower triangular (Cholesky A10 update,
    reference `Cholesky.cpp:218-319` dtrsm). For complex dtypes the
    transpose is Hermitian (X L^H = B) — the A = L L^H convention."""
    return lax.linalg.triangular_solve(
        L, B, left_side=False, lower=True, transpose_a=True,
        conjugate_a=jnp.issubdtype(L.dtype, jnp.complexfloating),
        unit_diagonal=False
    )


def trsm_left_upper(U: jax.Array, B: jax.Array) -> jax.Array:
    """Solve U X = B with U upper triangular (LU back-substitution)."""
    return lax.linalg.triangular_solve(
        U, B, left_side=True, lower=False, unit_diagonal=False
    )


def trsm_left_upper_t(U: jax.Array, B: jax.Array) -> jax.Array:
    """Solve U^T X = B with U upper triangular (transpose-system solve,
    the getrs 'T' path)."""
    return lax.linalg.triangular_solve(
        U, B, left_side=True, lower=False, transpose_a=True,
        unit_diagonal=False
    )


def trsm_left_lower_unit_t(L: jax.Array, B: jax.Array) -> jax.Array:
    """Solve L^T X = B with L unit lower triangular."""
    return lax.linalg.triangular_solve(
        L, B, left_side=True, lower=True, transpose_a=True,
        unit_diagonal=True
    )


def blocked_trsm(T: jax.Array, B: jax.Array, *, lower: bool = True,
                 unit_diagonal: bool = False, dinv=None,
                 block_size: int | None = None, precision=None,
                 backend: str | None = None) -> jax.Array:
    """Blocked batched triangular solve (DESIGN §27): diagonal-block
    inverses + trailing-panel GEMMs instead of XLA's serial-per-row
    batched TriangularSolve — the vmapped serving programs' fast
    substitution path (`conflux_tpu.ops.batched_trsm`). T is (n, n) or
    (B, n, n) (packed factors fine); `backend='pallas'` (or the module
    backend, resolved at trace time like :func:`gemm`) routes batched
    operands through the Pallas kernel, interpret mode off-TPU."""
    from conflux_tpu.ops import batched_trsm

    backend = _BACKEND if backend is None else backend
    precision = _MATMUL_PRECISION if precision is None else precision
    return batched_trsm.blocked_trsm(
        T, B, lower=lower, unit_diagonal=unit_diagonal, dinv=dinv,
        block_size=block_size, precision=precision, backend=backend)


def batched_lu_factor(A: jax.Array, *, probe_w=None,
                      backend: str | None = None):
    """Batched pivoted LU of (B, n, n) systems (DESIGN §29): the coalesced
    factor lane's kernel entry point. `backend='pallas'` (or the module
    backend, resolved at trace time like :func:`gemm`) runs the
    batch-blocked Pallas kernel with the batch axis in the grid —
    interpret mode off-TPU; 'xla' vmaps `lax.linalg.lu`. Returns
    `(LU, perm)`, or `(LU, perm, wA)` with the in-kernel Freivalds probe
    row `wA = w^T A` when `probe_w` is given."""
    backend = _BACKEND if backend is None else backend
    if backend == "pallas":
        from conflux_tpu.ops import pallas_factor

        return pallas_factor.pallas_lu_factor_batched(A, probe_w=probe_w)
    lu_packed, _piv, perm = jax.vmap(lax.linalg.lu)(A)
    if probe_w is None:
        return lu_packed, perm
    wa = jnp.matmul(probe_w[None, None, :], A,
                    preferred_element_type=_acc_dtype(A.dtype),
                    precision=lax.Precision.HIGHEST)[:, 0, :]
    return lu_packed, perm, wa


def batched_cholesky_factor(A: jax.Array, *, probe_w=None,
                            backend: str | None = None):
    """Batched lower-Cholesky of (B, n, n) SPD systems (DESIGN §29).
    Backend semantics match :func:`batched_lu_factor`. Returns `L`, or
    `(L, wA)` when `probe_w` is given."""
    backend = _BACKEND if backend is None else backend
    if backend == "pallas":
        from conflux_tpu.ops import pallas_factor

        return pallas_factor.pallas_cholesky_factor_batched(
            A, probe_w=probe_w)
    L = lax.linalg.cholesky(A, symmetrize_input=False)
    if probe_w is None:
        return L
    wa = jnp.matmul(probe_w[None, None, :], A,
                    preferred_element_type=_acc_dtype(A.dtype),
                    precision=lax.Precision.HIGHEST)[:, 0, :]
    return L, wa


def trsm_left_lower(L: jax.Array, B: jax.Array) -> jax.Array:
    """Solve L X = B with L lower triangular (Cholesky forward solve)."""
    return lax.linalg.triangular_solve(
        L, B, left_side=True, lower=True, unit_diagonal=False
    )


def trsm_left_lower_t(L: jax.Array, B: jax.Array) -> jax.Array:
    """Solve L^T X = B with L lower triangular (Cholesky back solve).
    For complex dtypes the transpose is Hermitian (L^H X = B)."""
    return lax.linalg.triangular_solve(
        L, B, left_side=True, lower=True, transpose_a=True,
        conjugate_a=jnp.issubdtype(L.dtype, jnp.complexfloating),
        unit_diagonal=False
    )


# --------------------------------------------------------------------------- #
# VMEM-derived call ceilings
# --------------------------------------------------------------------------- #

# XLA's TPU LU custom call stages its operand through scoped VMEM: on a v5e
# a single (8192, 1024) f32 call (32 MiB) compiles, (16384, 1024) (64 MiB)
# does not — an ELEMENT-COUNT wall, so the safe height scales as
# budget / (itemsize * v). The measured v5e values (8192 rows single-call,
# 4096 batched, at v=1024 f32) are pinned by tests; other generations get
# the same model with their own budget via the device-kind table or the
# explicit override. Overridable because no public API queries scoped VMEM.
_SCOPED_VMEM_BYTES = None  # explicit override (set_scoped_vmem_bytes)

# budget per device kind, bytes. Only v5e is measured; other rows inherit
# the conservative v5e figure until measured on hardware.
_SCOPED_VMEM_TABLE = {
    "v5 lite": 32 << 20,
    "v5e": 32 << 20,
}
_SCOPED_VMEM_DEFAULT = 32 << 20


def set_scoped_vmem_bytes(n: int | None) -> None:
    """Override the scoped-VMEM budget the chunk ceilings derive from
    (None restores device-kind detection). Use when a new TPU generation
    mis-sizes: the pinned table only knows measured hardware."""
    global _SCOPED_VMEM_BYTES
    if n is not None and n < (1 << 20):
        raise ValueError(f"implausible scoped VMEM budget {n} bytes")
    _SCOPED_VMEM_BYTES = n


def scoped_vmem_bytes() -> int:
    """The scoped-VMEM budget bounding a single LU/QR custom call's
    operand: override > $CONFLUX_TPU_SCOPED_VMEM_BYTES > device-kind
    table > conservative default. Device detection may initialize a
    backend; pure-host callers (e.g. the NumPy spec) pass an explicit
    `budget` to the ceiling helpers instead of reaching this."""
    if _SCOPED_VMEM_BYTES is not None:
        return _SCOPED_VMEM_BYTES
    import os

    env = os.environ.get("CONFLUX_TPU_SCOPED_VMEM_BYTES")
    if env:
        # same validation as set_scoped_vmem_bytes: a typo'd override on
        # the unmeasured generation the env var exists for must fail
        # loudly, not silently fall back to detection
        try:
            n = int(env)
        except ValueError:
            raise ValueError(
                f"CONFLUX_TPU_SCOPED_VMEM_BYTES={env!r} is not an "
                "integer byte count") from None
        if n < (1 << 20):
            raise ValueError(
                f"CONFLUX_TPU_SCOPED_VMEM_BYTES={env}: implausible "
                "scoped VMEM budget (< 1 MiB)")
        return n
    try:
        kind = jax.devices()[0].device_kind.lower()
        for key, budget in _SCOPED_VMEM_TABLE.items():
            if key in kind:
                return budget
    except Exception:
        pass
    return _SCOPED_VMEM_DEFAULT


def single_call_rows(v: int, dtype=jnp.float32, budget: int | None = None
                     ) -> int:
    """Max rows of ONE (m, v) LU/QR custom call that stays within the
    scoped-VMEM budget — tile-rounded, at least one tile. v5e pin:
    single_call_rows(1024) == 8192 (the measured default nomination
    chunk). `budget` bypasses device detection (pure-host callers)."""
    budget = scoped_vmem_bytes() if budget is None else budget
    elems = budget // jnp.dtype(dtype).itemsize
    return max(v, (elems // v) // v * v)


def batched_call_rows(v: int, dtype=jnp.float32, budget: int | None = None
                      ) -> int:
    """Max per-element rows of a BATCHED (b, m, v) custom call: the batch
    shares the scoped budget, so half the single-call height.

    v5e pin: batched_call_rows(1024) == 4096 — the measured-FASTEST chunk
    as well as the safe one. The model treats that optimum as an
    ELEMENT count (4 Mi elements), so other widths get equal-footprint
    (not equal-row) defaults — e.g. 16384 rows at v=256. Only v=1024 is
    hardware-measured; per-call `chunk=` arguments override everywhere
    if a width-specific tune disagrees."""
    budget = scoped_vmem_bytes() if budget is None else budget
    elems = budget // jnp.dtype(dtype).itemsize // 2
    return max(v, (elems // v) // v * v)


# --------------------------------------------------------------------------- #
# Panel factorizations
# --------------------------------------------------------------------------- #

# 'auto' uses plain partial pivoting for short panels and the tournament for
# tall ones; 'partial'/'tournament' force one path (tests and experiments).
_PANEL_ALGO = "auto"


def set_panel_algo(name: str) -> None:
    if name not in ("auto", "partial", "tournament", "pallas"):
        raise ValueError(f"unknown panel algo {name!r}")
    global _PANEL_ALGO
    _PANEL_ALGO = name


# VMEM ceiling of the Pallas elimination kernel: the (m, 128) block, the
# lane-padded (m, 1) masks/temporaries and the double-buffered outputs must
# stay under the 16 MiB scoped VMEM (m=8192 measured 3.8 MB over). This is
# a property of the KERNEL's scratch layout (128 lanes x 4 B x ~8 buffers
# -> 16 MiB / 4 KiB = 4096 rows), not of the LU custom call's budget —
# a module var (not derived per-call) so tests can shrink the ceiling.
_PALLAS_MAX_ROWS = 4096


def _pallas_panel_ok(dtype, m: int, v: int) -> bool:
    """Whether the Pallas elimination kernel can factor an (m, v) panel
    (off-TPU it runs in interpret mode, so no backend check here)."""
    return (jnp.dtype(dtype) == jnp.float32 and v % 128 == 0
            and m <= _PALLAS_MAX_ROWS)


def get_panel_algo() -> str:
    return _PANEL_ALGO


def panel_lu(panel: jax.Array, algo: str | None = None):
    """Pivoted LU of an (m, v) panel.

    Returns (lu_packed, perm) where perm is a length-m row permutation such
    that panel[perm] == L @ U with L unit-lower (m, v) and U upper (v, v)
    packed into lu_packed. This is the local kernel inside tournament
    pivoting (role of `LUP`, reference `conflux_opt.hpp:143-166`).

    Short panels use exact partial pivoting (`lax.linalg.lu`); tall panels
    use communication-avoiding tournament pivoting (:func:`panel_lu_tournament`),
    which bounds every LU call's height and keeps the MXU busy.

    `algo` defaults to the module setting **at trace time**; jitted callers
    must resolve it outside jit and pass it as a static argument (see
    `conflux_tpu/lu/single.py`) so it participates in the jit cache key.

    Tile-size ceiling on TPU: every LU call is at least v rows tall (the
    tournament's reduction rounds stack 2v), and XLA's LuDecompositionBlock
    custom call overflows its scoped VMEM at ~16384 rows — so v <= 4096 is
    the safe regime on TPU (v=1024 measured fastest anyway; see bench.py).
    """
    m, v = panel.shape
    algo = _resolve_panel_algo(
        panel.dtype, m, v, _PANEL_ALGO if algo is None else algo
    )
    if algo == "pallas":
        if m > _PALLAS_MAX_ROWS:  # too tall for VMEM: tournament over chunks
            return panel_lu_tournament(panel, chunk=_PALLAS_MAX_ROWS,
                                       use_pallas=True)
        return panel_lu_pallas(panel)
    if algo == "tournament":
        return panel_lu_tournament(panel)
    lu_packed, _pivots, perm = lax.linalg.lu(panel)
    return lu_packed, perm


def _resolve_panel_algo(dtype, m: int, v: int, algo: str) -> str:
    """Shared dispatch for :func:`panel_lu` / :func:`panel_winners`:
    validate, resolve 'auto', and gate the pallas kernel's eligibility."""
    if algo not in ("auto", "partial", "tournament", "pallas"):
        raise ValueError(f"unknown panel algo {algo!r}")
    if algo == "auto":
        # measured on v5e (m=4096, v=1024): XLA custom call 11.7 ms, pallas
        # masked elimination 17 ms (its per-step scalar reductions serialize
        # the pipeline) — so 'auto' prefers partial/tournament and 'pallas'
        # stays opt-in until the kernel wins. The threshold derives from
        # the COMPUTE dtype: a bf16 panel runs f32 panel math, so its
        # single exact-LU call is f32-sized
        cd = compute_dtype(dtype)
        algo = ("tournament" if m > 2 * max(batched_call_rows(v, cd), v)
                else "partial")
    if algo == "pallas" and not _pallas_panel_ok(dtype, min(m, _PALLAS_MAX_ROWS), v):
        raise ValueError(
            f"pallas panel kernel supports float32 with width a multiple "
            f"of 128, got {jnp.dtype(dtype)} ({m}, {v})"
        )
    return algo


def chunk_layout(m: int, v: int, chunk: int | None = None) -> tuple[int, int]:
    """(chunk height c, chunk count nch) used by :func:`tournament_winners`
    for an (m, v) panel — exposed so callers can build per-chunk liveness
    predicates with the same rounding. The default chunk is the batched
    VMEM-safe height for width v (the chunk round is a batched call)."""
    c = chunk if chunk is not None else batched_call_rows(v)
    c = min(c, -(-m // v) * v)  # never taller than the (tile-rounded) panel
    c = max(v, c // v * v)  # multiple of v, at least one tile tall
    return c, -(-m // c)


def tournament_winners(panel: jax.Array, chunk: int | None = None,
                       use_pallas: bool = False, chunk_live=None,
                       tree: str = "pairwise"):
    """Elect v pivot rows of an (m, v) panel by tournament (CALU).

    Single-device analogue of the reference's butterfly tournament
    (`tournament_rounds`, `conflux_opt.hpp:220-336`): rows are split into
    chunks, each chunk's local partial-pivoted LU nominates its top v rows,
    and a binary reduction tree of stacked (2v, v) LUs elects the winners.
    All LU calls are height-bounded (chunk or 2v rows) and the chunk round
    is batched, so this scales to arbitrarily tall panels.

    `tree` picks the reduction shape after nomination: 'pairwise' is the
    binary tree above (log2(nch) batched rounds); 'flat' stacks ALL
    nominees into one (nch*v, v) LU call — fewer sequential custom calls
    (each is latency-bound in its serial column sweep, so call count is
    the cost driver on TPU), at the price of a taller single call. 'flat'
    requires nch*v within the single-call VMEM-safe height (~8192 rows at
    v=1024 measured; the caller picks tree='flat' only when that holds).
    Both trees elect with identical tie-breaking semantics (zero pad rows
    lose every contest) but may order DIFFERENT winners for rank-deficient
    or tied inputs; at full rank the winner SET matches partial pivoting's
    growth properties either way (CALU's guarantee, not bitwise equality
    between trees).

    `chunk_live`, if given, is a (nch,)-shaped traced bool vector (see
    :func:`chunk_layout`): chunk i's LU is skipped via `lax.cond` when
    chunk_live[i] is False, nominating zero rows instead (which lose every
    contest). Callers whose dead rows form a prefix (the distributed LU's
    LAPACK-order layout) use this to shrink the election with the active
    window. With chunk_live, lu00 is only meaningful if the winners went
    through a live path (guaranteed when any live row exists and nch == 1,
    or via the reduction tree when nch > 1).

    Returns (lu00, gpiv): lu00 is the packed (v, v) LU of the winning rows in
    pivot order; gpiv gives their row indices in `panel`. Requires the panel
    to have full column rank: a rank-deficient panel can elect zero pad rows,
    whose out-of-range ids are dropped by the caller's scatter (the same
    panels break exact partial pivoting too — zero pivots).
    """
    m, v = panel.shape
    if m < v:
        raise ValueError(
            f"tournament_winners needs m >= v, got ({m}, {v}): a shorter "
            "panel would elect zero-pad rows with out-of-range ids even at "
            "full rank"
        )
    if tree not in ("pairwise", "flat"):
        raise ValueError(f"unknown tree {tree!r} (pairwise|flat)")
    c, nch = chunk_layout(m, v, chunk)
    mp = nch * c
    if mp != m:  # zero rows lose every pivot contest against real rows
        panel = jnp.pad(panel, ((0, mp - m), (0, 0)))
    ids = jnp.arange(mp, dtype=jnp.int32)

    cand = panel.reshape(nch, c, v)
    cid = ids.reshape(nch, c)
    if use_pallas and _pallas_panel_ok(panel.dtype, c, v):
        outs = [panel_lu_pallas(cand[i]) for i in range(nch)]
        perm_c = jnp.stack([o[1] for o in outs])
        lu0 = outs[0][0][:v]
    elif chunk_live is not None:

        def chunk_lu(ci):
            lu_i, _, perm_i = lax.linalg.lu(ci)
            return lu_i, perm_i

        def chunk_dead(ci):
            # zero nominees (lose every contest); identity order
            perm_i = jnp.arange(c, dtype=jnp.int32) + jnp.zeros_like(
                ci[:, 0], jnp.int32)
            return jnp.zeros_like(ci), perm_i

        outs = [lax.cond(chunk_live[i], chunk_lu, chunk_dead, cand[i])
                for i in range(nch)]
        perm_c = jnp.stack([o[1] for o in outs])
        lu0 = outs[0][0][:v]
    else:
        lu_c, _, perm_c = lax.linalg.lu(cand)  # batched (nch, c, v)
        lu0 = lu_c[0, :v]
    top = perm_c[:, :v]
    win = jnp.take_along_axis(cand, top[:, :, None], axis=1)  # (nch, v, v)
    wid = jnp.take_along_axis(cid, top, axis=1)

    if nch == 1:  # single chunk: its local LU already decided everything
        return lu0, wid[0]

    if tree == "flat":
        # one (nch*v, v) LU elects straight from all nominees: 1 sequential
        # custom call instead of log2(nch) tree rounds
        stack = win.reshape(nch * v, v)
        sid = wid.reshape(nch * v)
        lu_f, _, perm_f = lax.linalg.lu(stack)
        top = perm_f[:v]
        return lu_f[:v], jnp.take(sid, top, mode="fill", fill_value=mp)

    n = 1 << (nch - 1).bit_length()
    if n != nch:
        # pad blocks are all-zero rows with out-of-range ids: they lose every
        # contest against full-rank data, and if ever elected (rank-deficient
        # panel) their ids are dropped by the caller's scatter, not aliased
        # onto a real row
        win = jnp.pad(win, ((0, n - nch), (0, 0), (0, 0)))
        wid = jnp.pad(wid, ((0, n - nch), (0, 0)), constant_values=mp)

    lu_top = None
    while n > 1:
        stacked = win.reshape(n // 2, 2 * v, v)
        sid = wid.reshape(n // 2, 2 * v)
        if use_pallas and _pallas_panel_ok(panel.dtype, 2 * v, v):
            outs = [panel_lu_pallas(stacked[i]) for i in range(n // 2)]
            perm_r = jnp.stack([o[1] for o in outs])
            lu_top = jnp.stack([o[0][:v] for o in outs])
        else:
            lu_r, _, perm_r = lax.linalg.lu(stacked)  # batched (n/2, 2v, v)
            lu_top = lu_r[:, :v]
        top = perm_r[:, :v]
        win = jnp.take_along_axis(stacked, top[:, :, None], axis=1)
        wid = jnp.take_along_axis(sid, top, axis=1)
        n //= 2
    # final round's packed LU rows 0..v are exactly the winners, factored
    return lu_top[0], wid[0]


def panel_lu_pallas(panel: jax.Array):
    """Blocked panel LU with full-height partial pivoting, Pallas elimination.

    Same contract as :func:`panel_lu`. The (m, v) panel is factored in
    128-wide column blocks; each block is eliminated by the VMEM-resident
    Pallas kernel (`pallas_kernels.lu_block`) with *no row movement* — pivot
    rows keep their positions, an `alive` mask shrinks, and the inter-block
    update is a row-gathered TRSM plus one masked MXU GEMM. Rows are gathered
    into LAPACK order exactly once at the end. This sidesteps the serial
    row-swapping LU custom call entirely (VMEM bound: m <= 4096, see
    `pallas_kernels.lu_block`; taller panels go through
    `panel_lu(algo='pallas')`, which routes them to the tournament with
    pallas chunks).
    """
    from conflux_tpu.ops import pallas_kernels

    w = pallas_kernels._PANEL_W
    m, v = panel.shape
    if v % w:
        raise ValueError(f"panel width {v} not a multiple of {w}")
    A = panel
    alive = jnp.ones((m, 1), jnp.int8)
    pivs = []
    for off in range(0, v, w):
        blk = lax.dynamic_slice(A, (0, off), (m, w))
        out, alive_new, piv = pallas_kernels.lu_block(blk, alive)
        A = lax.dynamic_update_slice(A, out, (0, off))
        pivrows = piv[0]  # (w,) absolute row ids in pivot order
        pivs.append(pivrows)
        if off + w < v:
            # inter-block update on the trailing columns of the panel
            L00 = out[pivrows]  # (w, w) packed rows in pivot order
            rest = lax.dynamic_slice(A, (0, off + w), (m, v - off - w))
            U01 = trsm_left_lower_unit(unit_lower(L00), rest[pivrows])
            # multipliers of still-live rows only (pivot rows contribute 0)
            L10 = jnp.where(alive_new != 0, out, 0.0)
            rest = rest - jnp.matmul(
                L10, U01, precision=lax.Precision.HIGHEST,
                preferred_element_type=_acc_dtype(L10.dtype),
            ).astype(rest.dtype)
            rest = rest.at[pivrows].set(U01)
            A = lax.dynamic_update_slice(A, rest, (0, off + w))
        alive = alive_new
    gpiv = jnp.concatenate(pivs)  # (v,) rows in elimination order
    ids = jnp.arange(m, dtype=jnp.int32)
    is_piv = jnp.zeros((m,), bool).at[gpiv].set(True, mode="drop")
    pos = jnp.zeros((m,), jnp.int32).at[gpiv].set(
        jnp.arange(v, dtype=jnp.int32), mode="drop"
    )
    key = jnp.where(is_piv, pos, v + ids)
    perm = jnp.argsort(key)
    return A[perm], perm


def panel_winners(panel: jax.Array, algo: str = "auto"):
    """Elect the v pivot rows of an (m, v) panel and factor them.

    Returns (lu00, gpiv): the packed (v, v) LU of the winners in pivot order
    and their row positions in `panel`. This is the selection half of
    :func:`panel_lu` without the L10 solve or row reordering — callers that
    place rows themselves (see `conflux_tpu/lu/single.py`'s swap-minimal
    update) use this directly. For rank-deficient panels the tournament may
    report out-of-range pad ids in gpiv (see :func:`tournament_winners`);
    `permute.swap_minimal_perm` sanitizes them.
    """
    m, v = panel.shape
    algo = _resolve_panel_algo(panel.dtype, m, v, algo)
    if algo == "pallas":
        if m <= _PALLAS_MAX_ROWS:
            lu_packed, perm = panel_lu_pallas(panel)
            return lu_packed[:v], perm[:v]
        return tournament_winners(panel, chunk=_PALLAS_MAX_ROWS, use_pallas=True)
    if algo == "partial":
        lu_f, _, perm = lax.linalg.lu(panel)
        return lu_f[:v], perm[:v]
    return tournament_winners(panel)


def panel_lu_tournament(panel: jax.Array, chunk: int | None = None,
                        use_pallas: bool = False):
    """Tournament-pivoted (CALU) LU of a tall (m, v) panel.

    Same contract as :func:`panel_lu`. Pivot growth of CALU is bounded and
    in practice indistinguishable from partial pivoting (the reference ships
    the same trade, `python/pivoting.py` 'tournament' strategy); residuals are
    checked by the test suite, not assumed. `use_pallas` runs the chunk and
    reduction-tree factorizations through the Pallas elimination kernel
    instead of the XLA custom call.
    """
    m, v = panel.shape
    lu00, gpiv = tournament_winners(panel, chunk, use_pallas)
    ids = jnp.arange(m, dtype=jnp.int32)
    is_piv = jnp.zeros((m,), bool).at[gpiv].set(True, mode="drop")
    pos = jnp.zeros((m,), jnp.int32).at[gpiv].set(
        jnp.arange(v, dtype=jnp.int32), mode="drop"
    )
    # winners first (in pivot order), remaining rows after (in original order)
    key = jnp.where(is_piv, pos, v + ids)
    perm = jnp.argsort(key)
    rest = panel[perm[v:]]
    L10 = trsm_right_upper(jnp.triu(lu00), rest)
    return jnp.concatenate([lu00, L10], axis=0), perm


def unit_lower(lu00: jax.Array) -> jax.Array:
    """Extract the unit-lower L00 from a packed (v, v) LU diagonal block."""
    v = lu00.shape[0]
    return jnp.tril(lu00, -1) + jnp.eye(v, dtype=lu00.dtype)


def potrf(a: jax.Array) -> jax.Array:
    """Lower Cholesky factor of a v x v SPD tile (reference dpotrf,
    `Cholesky.cpp:188-194`)."""
    return lax.linalg.cholesky(a, symmetrize_input=False)
