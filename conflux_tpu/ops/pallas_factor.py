"""Batched Pallas factor kernels — the factor lane's batch-blocked core.

The serving stack's remaining vmap cliff (DESIGN §29): every coalesced
cold start, gang refactor and revival storm factors its stack through
`jax.vmap` over the SINGLE-system blocked bodies (`lu/single.py`,
`cholesky/single.py`), so the panel factorization — the latency-critical
serialized path, CONFLUX's core thesis — serializes per slot across the
batch. On TPU the batch axis belongs in the Pallas grid instead: grid
``(batch, panel-step)`` with the running matrix in persistent VMEM
scratch (the `_matmul_kernel` accumulator discipline), so every slot's
panel elimination is the same masked VPU program and the batch is pure
grid parallelism, not a vmapped loop.

Two kernels share the layout:

- :func:`pallas_lu_factor_batched` — partial-pivot LU. The elimination
  body is `pallas_kernels._lu_block_kernel`'s masked-winner pattern
  (rows never move; per column: masked-argmax pivot election, record,
  multipliers in place, rank-1 update) extended with the leading batch
  grid axis and FULL-width trailing updates: eliminating column j
  updates every trailing column of the live rows, so each pivot row
  already carries its finished U row when it is frozen — the blocked
  trailing update needs no in-kernel row gather and no triangular
  solve. The caller gathers rows into LAPACK order once, outside the
  kernel (one batched `take_along_axis`).
- :func:`pallas_cholesky_factor_batched` — the SPD counterpart, no
  pivot election; the trailing update keeps BOTH triangles of the
  running matrix symmetric so the update's row factor is a cheap
  sublane broadcast of row j (there is no (m, 1) -> (m, m) lane
  broadcast on the VPU — the column factor takes the roll-reduction
  tree, same as LU).

Mosaic constraints (documented at `_lu_block_kernel`) shape both
bodies: scalar-only `fori_loop` carries (the matrix mutates VMEM
scratch refs), masks cast to the accumulator dtype and combined
arithmetically (no i1 relayouts), lane broadcasts via the exact cyclic
roll-reduction tree (power-of-two width — the wrapper identity-pads N
up), pivot rows via dynamic sublane reads.

The factor epilogue fuses here too: the kernels accumulate the §21
Freivalds probe row ``wA = w^T A`` at step 0 while the pristine input
block is VMEM-resident (`probe_w=`), and the jitted wrappers the serve
layer traces (`FactorPlan._stacked_factor_body`) append the
``substitution='blocked'`` diagonal-block inverses and the probe solve
in the SAME program — a checked coalesced factor is one dispatch, with
no second factor-time pass re-reading A from HBM for the probe row.

Per-slot outputs are bitwise invariant to the batch size and the pad
contents — grid slots never interact — which preserves the bucket/pad
contract that gives bitwise parity between ``plan.factor`` (bucket 1)
and the coalesced factor lane. Off-TPU the kernels run in interpret
mode (the correctness-test path, like `pallas_blocked_trsm`); f64 is
interpret-only (Mosaic has no f64), and the VMEM working set bounds
the padded size at roughly Np <= 1024 on hardware (a handful of
(Np, Np) f32 arrays against the ~16 MB scoped VMEM).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from conflux_tpu.ops.batched_trsm import _pad_identity

_PANEL_W = 128  # elimination-step chunk == one lane tile (grid dim 1)


def _pow2(n: int) -> int:
    """Next power of two >= n — the kernel's padded width: the exact
    lane broadcast is a cyclic roll-reduction tree, which double-counts
    wrapped shifts unless the lane width is a power of two."""
    return 1 << (max(1, int(n)) - 1).bit_length()


def _check_batched_square(A) -> None:
    if A.ndim != 3 or A.shape[-1] != A.shape[-2]:
        raise ValueError(
            f"batched factor kernels take (B, N, N), got {A.shape}")


# --------------------------------------------------------------------------- #
# kernels: grid (batch, panel-step), persistent VMEM running matrix
# --------------------------------------------------------------------------- #


def _blu_kernel(a_ref, w_ref, o_ref, piv_ref, wa_ref, acc_ref, alive_ref,
                *, bw: int):
    """Batch-blocked partial-pivot LU, one (batch, panel-step) grid
    cell: eliminate columns [i*bw, (i+1)*bw) of this slot's running
    matrix (VMEM scratch, initialized from the input block at step 0).
    Masked-winner election per column; full-width rank-1 updates, so
    frozen pivot rows hold finished U rows in place."""
    i = pl.program_id(1)
    m = acc_ref.shape[0]

    @pl.when(i == 0)
    def _init():
        acc_ref[:] = a_ref[0].astype(acc_ref.dtype)
        alive_ref[:] = jnp.ones_like(alive_ref)
        # fused probe row: wA = w^T A off the pristine VMEM-resident
        # input block — no second factor-time pass re-reading A
        wa_ref[:] = jnp.dot(
            w_ref[:].astype(acc_ref.dtype), a_ref[0].astype(acc_ref.dtype),
            preferred_element_type=acc_ref.dtype).astype(wa_ref.dtype)

    rows = lax.broadcasted_iota(jnp.int32, (m, m), 0)
    cols = lax.broadcasted_iota(jnp.int32, (m, m), 1)
    colsb = lax.broadcasted_iota(jnp.int32, (1, 1, bw), 2)
    base = i * bw

    def body(jj, carry):
        j = base + jj
        A = acc_ref[:]
        alive_f = alive_ref[:]
        # lane-broadcast column j: one nonzero per row, so the cyclic
        # roll-reduction tree sum is EXACT (power-of-two m)
        colj = jnp.where(cols == j, A, 0.0)
        s = 1
        while s < m:
            colj = colj + pltpu.roll(colj, s, 1)
            s *= 2
        cand = jnp.abs(colj) * alive_f - (1.0 - alive_f)  # dead rows -> -1
        p = jnp.min(
            jnp.where(cand == jnp.max(cand), rows, m)).astype(jnp.int32)
        isp_f = (rows == p).astype(acc_ref.dtype)
        rowp_bc = jnp.broadcast_to(acc_ref[pl.ds(p, 1), :], (m, m))
        colmask_f = (cols == j).astype(acc_ref.dtype)
        gtmask_f = (cols > j).astype(acc_ref.dtype)
        pivval = jnp.sum(isp_f * colmask_f * A)
        live_f = alive_f * (1.0 - isp_f)
        lmul = colj / pivval * live_f  # multipliers; 0 on dead/pivot rows
        # FULL-width rank-1 update of live rows (every trailing column,
        # future panels included) — what lets frozen pivot rows carry
        # finished U rows with no in-kernel gather/trsm; multipliers
        # land in column j of the live rows
        A = A - gtmask_f * (lmul * rowp_bc)
        maskf = colmask_f * live_f
        A = A * (1.0 - maskf) + lmul * maskf
        acc_ref[:] = A
        alive_ref[:] = live_f
        piv_ref[:] = jnp.where(colsb == jj, p, piv_ref[:])
        return carry

    jax.lax.fori_loop(0, bw, body, 0)

    @pl.when(i == pl.num_programs(1) - 1)
    def _store():
        o_ref[0] = acc_ref[:].astype(o_ref.dtype)


def _bchol_kernel(a_ref, w_ref, o_ref, wa_ref, acc_ref, *, bw: int):
    """Batch-blocked Cholesky, one (batch, panel-step) grid cell: no
    pivot election; the trailing update keeps BOTH triangles of the
    running matrix symmetric, so the rank-1 row factor is row j itself
    (an exact sublane broadcast) while the column factor rides the
    roll-reduction tree."""
    i = pl.program_id(1)
    m = acc_ref.shape[0]

    @pl.when(i == 0)
    def _init():
        acc_ref[:] = a_ref[0].astype(acc_ref.dtype)
        wa_ref[:] = jnp.dot(
            w_ref[:].astype(acc_ref.dtype), a_ref[0].astype(acc_ref.dtype),
            preferred_element_type=acc_ref.dtype).astype(wa_ref.dtype)

    rows = lax.broadcasted_iota(jnp.int32, (m, m), 0)
    cols = lax.broadcasted_iota(jnp.int32, (m, m), 1)
    base = i * bw

    def body(jj, carry):
        j = base + jj
        A = acc_ref[:]
        eqrow_f = (rows == j).astype(acc_ref.dtype)
        eqcol_f = (cols == j).astype(acc_ref.dtype)
        gtrow_f = (rows > j).astype(acc_ref.dtype)
        gtcol_f = (cols > j).astype(acc_ref.dtype)
        colj = jnp.where(cols == j, A, 0.0)
        s = 1
        while s < m:
            colj = colj + pltpu.roll(colj, s, 1)
            s *= 2
        ajj = jnp.sum(colj * eqrow_f * eqcol_f)
        ljj = jnp.sqrt(ajj)
        rowj_bc = jnp.broadcast_to(acc_ref[pl.ds(j, 1), :], (m, m))
        # symmetric trailing update (both triangles stay current so
        # future steps' rowj_bc reads are valid)
        A = A - (gtrow_f * gtcol_f) * (colj * rowj_bc) / ajj
        # scale column j below (and on) the diagonal into L values
        sel = eqcol_f * (gtrow_f + eqrow_f)
        A = A * (1.0 - sel) + (colj / ljj) * sel
        acc_ref[:] = A
        return carry

    jax.lax.fori_loop(0, bw, body, 0)

    @pl.when(i == pl.num_programs(1) - 1)
    def _store():
        trilf = (rows >= cols).astype(acc_ref.dtype)
        o_ref[0] = (acc_ref[:] * trilf).astype(o_ref.dtype)


# --------------------------------------------------------------------------- #
# jitted pallas_call wrappers
# --------------------------------------------------------------------------- #


@functools.partial(jax.jit, static_argnames=("interpret",))
def _pallas_blu(A, w, interpret: bool):
    B, m, _ = A.shape
    bw = min(_PANEL_W, m)
    nsteps = m // bw
    acc_dt = jnp.promote_types(A.dtype, jnp.float32)
    kern = functools.partial(_blu_kernel, bw=bw)
    out, piv, wa = pl.pallas_call(
        kern,
        grid=(B, nsteps),
        in_specs=[
            pl.BlockSpec((1, m, m), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, m), lambda b, i: (0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, m, m), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, 1, bw), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, m), lambda b, i: (b, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((B, m, m), A.dtype),
            jax.ShapeDtypeStruct((B, nsteps, bw), jnp.int32),
            jax.ShapeDtypeStruct((B, m), acc_dt),
        ),
        scratch_shapes=[
            pltpu.VMEM((m, m), acc_dt),
            pltpu.VMEM((m, m), acc_dt),
        ],
        cost_estimate=pl.CostEstimate(
            flops=B * (2 * m * m * m // 3 + 2 * m * m),
            bytes_accessed=B * (2 * m * m + 2 * m) * A.dtype.itemsize,
            transcendentals=0,
        ),
        interpret=interpret,
    )(A, w)
    # rows into LAPACK order: position k's row is the step-k pivot
    # winner (square elimination freezes every row exactly once)
    gpiv = piv.reshape(B, m)
    LU = jnp.take_along_axis(out, gpiv[..., None], axis=1)
    return LU, gpiv, wa


@functools.partial(jax.jit, static_argnames=("interpret",))
def _pallas_bchol(A, w, interpret: bool):
    B, m, _ = A.shape
    bw = min(_PANEL_W, m)
    nsteps = m // bw
    acc_dt = jnp.promote_types(A.dtype, jnp.float32)
    kern = functools.partial(_bchol_kernel, bw=bw)
    return pl.pallas_call(
        kern,
        grid=(B, nsteps),
        in_specs=[
            pl.BlockSpec((1, m, m), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, m), lambda b, i: (0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, m, m), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, m), lambda b, i: (b, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((B, m, m), A.dtype),
            jax.ShapeDtypeStruct((B, m), acc_dt),
        ),
        scratch_shapes=[pltpu.VMEM((m, m), acc_dt)],
        cost_estimate=pl.CostEstimate(
            flops=B * (m * m * m // 3 + 2 * m * m),
            bytes_accessed=B * (2 * m * m + 2 * m) * A.dtype.itemsize,
            transcendentals=B * m,
        ),
        interpret=interpret,
    )(A, w)


def _pad_batch_floor(Ap):
    """Floor the kernel batch at 2 with one identity pad slot. The
    bucket/pad contract wants per-slot bits invariant to the batch
    size; on TPU one Mosaic body serves every grid size, but in
    interpret mode a trip-count-1 grid loop gets INLINED by XLA and
    fuses differently from the retained loop at trip >= 2 (measured:
    low-bit drift at B=1 only, B in [2, 32] all bitwise identical).
    One wasted identity factor at bucket 1 buys the contract back."""
    if Ap.shape[0] >= 2:
        return Ap
    eye = jnp.eye(Ap.shape[-1], dtype=Ap.dtype)
    return jnp.concatenate([Ap, eye[None]])


def _probe_input(probe_w, n: int, m: int, acc_dt):
    """The (1, m) probe-row input: the caller's w zero-extended over the
    identity tail, or all-zero when no probe is wanted (the kernel's
    elimination program is identical either way — the probe is one dot
    at step 0 whose output the caller then drops)."""
    w = jnp.zeros((1, m), acc_dt)
    if probe_w is not None:
        w = w.at[0, :n].set(jnp.asarray(probe_w).astype(acc_dt))
    return w


def pallas_lu_factor_batched(A, *, probe_w=None):
    """Pivoted LU of a (B, N, N) batch through the batch-blocked Pallas
    kernel: returns ``(LU, perm)`` — packed factors in LAPACK order and
    the permutation, with ``A[i][perm[i]] == L_i @ U_i`` (the
    `lu_factor_blocked` contract per slot). With ``probe_w`` (length-N
    probe vector) also returns ``wA`` (B, N) = ``w^T A_i`` accumulated
    in-kernel at step 0 — the §21 Freivalds probe rows, free with the
    factor. Ragged N identity-pads to the next power of two and slices
    back bitwise (pad slots/rows never couple into real ones). Runs in
    interpret mode off-TPU; f64 is interpret-only."""
    A = jnp.asarray(A)
    _check_batched_square(A)
    B, n = A.shape[0], A.shape[-1]
    m = _pow2(n)
    acc_dt = jnp.promote_types(A.dtype, jnp.float32)
    Ap = _pad_batch_floor(_pad_identity(A, m))
    w = _probe_input(probe_w, n, m, acc_dt)
    interpret = jax.default_backend() != "tpu"
    LU, perm, wa = _pallas_blu(Ap, w, interpret)
    LU, perm = LU[:B, :n, :n], perm[:B, :n]
    if probe_w is None:
        return LU, perm
    return LU, perm, wa[:B, :n]


def pallas_cholesky_factor_batched(A, *, probe_w=None):
    """Lower Cholesky factors of a (B, N, N) SPD batch through the
    batch-blocked Pallas kernel: returns L (B, N, N), strictly-upper
    parts zeroed (the `cholesky_blocked` contract per slot); with
    ``probe_w`` also the in-kernel probe rows wA (B, N). Ragged N
    identity-pads to the next power of two, bitwise. Interpret mode
    off-TPU; f64 interpret-only."""
    A = jnp.asarray(A)
    _check_batched_square(A)
    B, n = A.shape[0], A.shape[-1]
    m = _pow2(n)
    acc_dt = jnp.promote_types(A.dtype, jnp.float32)
    Ap = _pad_batch_floor(_pad_identity(A, m))
    w = _probe_input(probe_w, n, m, acc_dt)
    interpret = jax.default_backend() != "tpu"
    L, wa = _pallas_bchol(Ap, w, interpret)
    L = L[:B, :n, :n]
    if probe_w is None:
        return L
    return L, wa[:B, :n]
