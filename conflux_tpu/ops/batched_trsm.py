"""Blocked batched triangular solves — the vmapped substitution engine.

XLA's *batched* small-RHS TriangularSolve is the serving stack's oldest
measured cliff: it substitutes serially per row (~70x slower than GEMM
form at B=32, N=256 on CPU — DESIGN §17, re-measured in §26), which is
why every vmapped serving program was forced onto the ``'inv'``
substitution engine (explicit full triangular inverses, error growth ~
cond(L) cond(U)). The reference CONFLUX never pays that path either: its
communication-optimal flops come from *blocked* triangular updates whose
inner work is GEMM (`conflux_opt.hpp` trailing-matrix update).

This module is that cure, generalized to the batched layout (DESIGN
§27): split the triangular axis into ``bs``-wide blocks, invert ONLY the
(bs, bs) diagonal blocks (once, at factor time — O(N bs^2) work next to
the O(N^3) factorization, error growth ~ max cond of a diagonal block
instead of cond(L) cond(U)), and substitute block-by-block so each of
the O(N/bs) steps is one (bs, bs) GEMM against the diagonal inverse plus
one trailing-panel GEMM — all MXU/BLAS3-shaped, all trivially vmappable
over a batch/stack axis. N serial 1-column substitutions become
O(N/bs) batched GEMMs.

Two implementations share the contract:

- the portable pure-XLA path (:func:`blocked_solve` /
  :func:`blocked_trsm`) — an unrolled static block loop of jnp matmuls,
  safe inside jit/vmap, what the serve programs trace;
- a Pallas TPU kernel (:func:`pallas_blocked_trsm`) — grid over
  (batch, block step) with the running right-hand side held in a VMEM
  accumulator (the `_matmul_kernel` discipline from
  `pallas_kernels.py`), registered behind the `ops.blas` backend
  registry (``blas.blocked_trsm(..., backend='pallas')``) and running in
  interpret mode off-TPU so correctness tests cover it on CPU.

The final block step optionally fuses the §20/§21 Freivalds probe
epilogue (:func:`blocked_solve_probe`): as each solution block is
produced, the finite-check accumulator (sum of x) and the probe
projection (wA . x[:, 0]) accumulate in the same loop, so a checked
solve's verdict costs no separate pass over x after the substitution.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_HI = lax.Precision.HIGHEST


def default_block_size(n: int) -> int:
    """The deterministic block width for an (n, n) triangle: 32, shrunk
    to the next power of two >= n for tiny systems. Derived from n ONLY
    — the diagonal-inverse stack's shape is part of a blocked plan's
    factor pytree, so it must be reproducible across processes
    (checkpoint/restore bitwise contract, DESIGN §23). 32 keeps the
    diagonal inverses well-conditioned (a (32, 32) triangle, not the
    whole factor), the per-step GEMMs MXU-tileable, and the step count
    N/32 small enough that XLA-CPU's fixed per-op overhead stays
    amortized (8 steps at the production N=256)."""
    if n < 1:
        raise ValueError(f"triangular solve needs n >= 1, got {n}")
    return min(32, 1 << (int(n) - 1).bit_length())


def _nblocks(n: int, bs: int) -> int:
    return -(-n // bs)


def _pad_identity(T, np_: int):
    """Extend an (..., n, n) triangle to (..., np_, np_) with an
    identity tail: the pad rows solve to exactly zero against a zero
    RHS pad and contribute nothing to real rows (their off-diagonal
    couplings are zero), so padded answers slice back bitwise."""
    n = T.shape[-1]
    if np_ == n:
        return T
    pad = [(0, 0)] * (T.ndim - 2) + [(0, np_ - n), (0, np_ - n)]
    Tp = jnp.pad(T, pad)
    idx = jnp.arange(n, np_)
    return Tp.at[..., idx, idx].set(jnp.ones((), T.dtype))


def diag_block_inverses(T, *, lower: bool = True,
                        unit_diagonal: bool = False,
                        block_size: int | None = None):
    """Invert the (bs, bs) diagonal blocks of an (n, n) triangle —
    the factor-time half of the blocked engine. Returns an
    (nb, bs, bs) stack of triangular inverses (nb = ceil(n / bs), the
    tail block identity-extended when bs does not divide n).

    `T` may be a PACKED factor (garbage on the other triangle — e.g.
    the U values a packed LU carries above L's diagonal): the block is
    masked to its triangle before inversion, and `unit_diagonal=True`
    rebuilds the implicit unit diagonal. One batched (nb, bs, bs)
    TriangularSolve against the identity — a bs-wide RHS, nowhere near
    the small-RHS cliff — runs at factor time and is amortized into
    the session open exactly like the 'inv' engine's full inverses,
    at 1/nb-th the inversion flops and far better conditioning.
    Traceable (jit/vmap-safe)."""
    n = T.shape[-1]
    bs = default_block_size(n) if block_size is None else int(block_size)
    nb = _nblocks(n, bs)
    Tp = _pad_identity(T, nb * bs)
    D = jnp.stack([Tp[i * bs:(i + 1) * bs, i * bs:(i + 1) * bs]
                   for i in range(nb)])
    if unit_diagonal:
        strict = jnp.tril(D, -1) if lower else jnp.triu(D, 1)
        D = strict + jnp.eye(bs, dtype=D.dtype)
    else:
        D = jnp.tril(D) if lower else jnp.triu(D)
    eye = jnp.broadcast_to(jnp.eye(bs, dtype=D.dtype), D.shape)
    return lax.linalg.triangular_solve(D, eye, left_side=True,
                                       lower=lower)


def _blocked_core(T, dinv, b, lower: bool, precision,
                  wA=None, stats_dtype=None):
    """The 2D block-substitution loop: solve T x = b through the
    precomputed diagonal-block inverses. Per step: one (bs, bs) x
    (bs, k) GEMM against the diagonal inverse, one trailing-panel GEMM
    updating the not-yet-solved rows. The loop is unrolled over a
    STATIC block count, so under vmap every step is a batched GEMM —
    the whole point. Off-triangle panels of a packed factor are never
    read (a lower solve reads strictly-below-diagonal panels only, an
    upper solve strictly-above), so packed LU storage needs no masking
    here. With `wA` (the probe row, length n) the Freivalds epilogue
    accumulates sum(x) and wA . x[:, 0] per block IN the loop — see
    :func:`blocked_solve_probe`."""
    n = T.shape[-1]
    nb, bs = dinv.shape[0], dinv.shape[-1]
    np_ = nb * bs
    if np_ != n:
        T = _pad_identity(T, np_)
        b = jnp.pad(b, ((0, np_ - n), (0, 0)))
    dt = jnp.result_type(T.dtype, b.dtype)

    def mm(a, x):
        return jnp.matmul(a.astype(dt), x.astype(dt),
                          precision=precision)

    probe = wA is not None
    if probe:
        sdt = dt if stats_dtype is None else jnp.dtype(stats_dtype)
        wAp = jnp.pad(wA.astype(sdt), (0, np_ - wA.shape[-1]))
        xsum = jnp.zeros((), sdt)
        wAx = jnp.zeros((), sdt)
    xs = []
    rest = b
    order = range(nb) if lower else range(nb - 1, -1, -1)
    for i in order:
        if lower:
            ri, rest = rest[:bs], rest[bs:]
        else:
            m = rest.shape[0]
            ri, rest = rest[m - bs:], rest[:m - bs]
        xi = mm(dinv[i], ri)
        xs.append(xi)
        if probe:
            xc = xi.astype(sdt)
            xsum = xsum + jnp.sum(xc)
            wAx = wAx + jnp.sum(wAp[i * bs:(i + 1) * bs] * xc[:, 0])
        if rest.shape[0]:
            if lower:
                panel = T[(i + 1) * bs:, i * bs:(i + 1) * bs]
            else:
                panel = T[:i * bs, i * bs:(i + 1) * bs]
            rest = rest - mm(panel, xi)
    x = jnp.concatenate(xs if lower else xs[::-1], axis=0)[:n]
    if probe:
        return x, xsum, wAx
    return x


def blocked_solve(T, dinv, b, *, lower: bool = True, precision=None):
    """Per-system blocked substitution with PRECOMPUTED diagonal-block
    inverses (`dinv` from :func:`diag_block_inverses`, resident in a
    blocked plan's factor pytree) — the serve hot path's primitive.
    T is (n, n) (packed factors fine), b is (n, k); traceable and
    vmap-safe (vmapping yields exactly the batched GEMM schedule)."""
    return _blocked_core(T, dinv, b, lower,
                         _HI if precision is None else precision)


def blocked_solve_probe(T, dinv, b, wA, *, lower: bool = False,
                        precision=None, stats_dtype=None):
    """:func:`blocked_solve` with the Freivalds probe epilogue fused
    into the block loop: returns (x, xsum, wAx) where xsum = sum(x)
    (the finite-check accumulator — NaN/Inf anywhere in x poisons it)
    and wAx = wA . x[:, 0] (the probe projection), both accumulated in
    `stats_dtype` as each block of x is produced. A checked blocked
    solve's verdict (`update.health_verdict_from_stats`) is assembled
    from these plus two O(N) dots on b — no separate pass re-reading x
    after the substitution (DESIGN §27). Defaults to the BACK solve
    (`lower=False`): the final block step of every factorization's
    substitution chain, where x is final."""
    return _blocked_core(T, dinv, b, lower,
                         _HI if precision is None else precision,
                         wA=wA, stats_dtype=stats_dtype)


def blocked_trsm(T, b, *, lower: bool = True,
                 unit_diagonal: bool = False, dinv=None,
                 block_size: int | None = None, precision=None,
                 backend: str | None = None):
    """Solve T x = b for a triangle or a batch of triangles — the
    public blocked-trsm entry (also surfaced as `blas.blocked_trsm`,
    behind the backend registry).

    T is (n, n) or (B, n, n); b matches with an optional trailing RHS
    axis ((n,), (n, k), (B, n), (B, n, k)); x comes back in b's shape.
    `dinv` passes precomputed diagonal-block inverses (per system, or
    stacked (B, nb, bs, bs) for batched input) — computed here when
    omitted. `backend='pallas'` routes BATCHED input through the Pallas
    kernel (interpret mode off-TPU); everything else takes the
    pure-XLA block loop, vmapped over the batch axis."""
    T = jnp.asarray(T)
    b = jnp.asarray(b)
    if T.ndim not in (2, 3) or T.shape[-1] != T.shape[-2]:
        raise ValueError(f"T must be (n, n) or (B, n, n), got {T.shape}")
    batched = T.ndim == 3
    squeeze = b.ndim == T.ndim - 1
    if squeeze:
        b = b[..., None]
    if b.ndim != T.ndim or b.shape[:-1] != T.shape[:-1]:
        raise ValueError(f"rhs {b.shape} does not match T {T.shape}")
    precision = _HI if precision is None else precision
    if backend is None:
        from conflux_tpu.ops import blas

        backend = blas.get_backend()

    def one_dinv(t):
        return diag_block_inverses(t, lower=lower,
                                   unit_diagonal=unit_diagonal,
                                   block_size=block_size)

    if dinv is None:
        dinv = jax.vmap(one_dinv)(T) if batched else one_dinv(T)
    else:
        dinv = jnp.asarray(dinv)
    if not batched:
        x = _blocked_core(T, dinv, b, lower, precision)
        return x[..., 0] if squeeze else x
    if backend == "pallas":
        x = pallas_blocked_trsm(T, dinv, b, lower=lower)
    else:
        x = jax.vmap(lambda t, d, r: _blocked_core(t, d, r, lower,
                                                   precision))(T, dinv, b)
    return x[..., 0] if squeeze else x


# --------------------------------------------------------------------------- #
# Pallas TPU kernel: block over batch x block step, VMEM accumulator
# --------------------------------------------------------------------------- #
#
# Grid (B, nb), block-step dim innermost — TPU grids iterate sequentially
# with the rightmost dimension fastest, so for each batch element the nb
# block steps run in order against a persistent VMEM scratch holding the
# running right-hand side (initialized from b at step 0, the
# `_matmul_kernel` accumulator discipline). Per step the kernel brings in
# one (np, bs) column panel of T and one (bs, bs) diagonal inverse,
# produces one (bs, k) x block on the MXU, and downdates the
# not-yet-solved rows of the VMEM accumulator with one panel GEMM —
# masked arithmetically (f32 row-iota compare), never via i1 relayouts
# (the Mosaic constraint `_lu_block_kernel` documents). Lane alignment:
# production serve traffic pads RHS widths to power-of-two buckets
# already; tiny k runs fine in interpret mode (the off-TPU correctness
# path) and underfills lanes on real hardware — batch more RHS to fill.


def _btrsm_kernel(t_ref, d_ref, b_ref, o_ref, acc_ref, *, nb: int,
                  bs: int, lower: bool):
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        acc_ref[:] = b_ref[0].astype(acc_ref.dtype)

    # the block this step solves (index maps already brought in its
    # panel/dinv and mapped the output window)
    j = i if lower else nb - 1 - i
    ri = acc_ref[pl.ds(j * bs, bs), :]
    xi = jnp.dot(d_ref[0, 0].astype(acc_ref.dtype), ri,
                 preferred_element_type=acc_ref.dtype)
    o_ref[0] = xi.astype(o_ref.dtype)
    # downdate rows not yet solved: below the block for a lower solve,
    # above it for an upper one; the masked rows also null out the
    # packed factor's other-triangle garbage in the full column panel
    upd = jnp.dot(t_ref[0].astype(acc_ref.dtype), xi,
                  preferred_element_type=acc_ref.dtype)
    rows = lax.broadcasted_iota(jnp.int32, upd.shape, 0)
    if lower:
        maskf = (rows >= (j + 1) * bs).astype(acc_ref.dtype)
    else:
        maskf = (rows < j * bs).astype(acc_ref.dtype)
    acc_ref[:] = acc_ref[:] - maskf * upd


@functools.partial(jax.jit,
                   static_argnames=("lower", "interpret"))
def _pallas_btrsm(T, dinv, b, lower: bool, interpret: bool):
    B, np_, _ = T.shape
    nb, bs = dinv.shape[1], dinv.shape[-1]
    k = b.shape[-1]
    acc_dtype = jnp.promote_types(T.dtype, jnp.float32)
    kern = functools.partial(_btrsm_kernel, nb=nb, bs=bs, lower=lower)
    blk = (lambda bi, i: (bi, 0, i)) if lower \
        else (lambda bi, i: (bi, 0, nb - 1 - i))
    dblk = (lambda bi, i: (bi, i, 0, 0)) if lower \
        else (lambda bi, i: (bi, nb - 1 - i, 0, 0))
    oblk = (lambda bi, i: (bi, i, 0)) if lower \
        else (lambda bi, i: (bi, nb - 1 - i, 0))
    return pl.pallas_call(
        kern,
        grid=(B, nb),
        in_specs=[
            pl.BlockSpec((1, np_, bs), blk),
            pl.BlockSpec((1, 1, bs, bs), dblk),
            pl.BlockSpec((1, np_, k), lambda bi, i: (bi, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, bs, k), oblk),
        out_shape=jax.ShapeDtypeStruct((B, np_, k), b.dtype),
        scratch_shapes=[pltpu.VMEM((np_, k), acc_dtype)],
        cost_estimate=pl.CostEstimate(
            flops=B * (np_ * np_ * k + np_ * bs * k),
            bytes_accessed=B * (np_ * np_ + np_ * k * 2
                                + nb * bs * bs) * T.dtype.itemsize,
            transcendentals=0,
        ),
        interpret=interpret,
    )(T, dinv, b)


def pallas_blocked_trsm(T, dinv, b, *, lower: bool = True):
    """Batched blocked trsm through the Pallas kernel: T (B, n, n)
    (packed factors fine), dinv (B, nb, bs, bs) from
    :func:`diag_block_inverses` per system, b (B, n, k). Runs in
    interpret mode off-TPU (the correctness-test path, same as the §7
    kernels); on TPU the accumulator lives in VMEM and both per-step
    GEMMs hit the MXU. Returns x (B, n, k)."""
    T = jnp.asarray(T)
    dinv = jnp.asarray(dinv)
    b = jnp.asarray(b)
    n = T.shape[-1]
    nb, bs = dinv.shape[1], dinv.shape[-1]
    np_ = nb * bs
    if np_ != n:
        T = _pad_identity(T, np_)
        b = jnp.pad(b, ((0, 0), (0, np_ - n), (0, 0)))
    interpret = jax.default_backend() != "tpu"
    x = _pallas_btrsm(T, dinv, b, lower, interpret)
    return x[:, :n]
