"""Hand-written Pallas TPU kernels for the hot tile ops.

The trailing-matrix GEMM is where ~2/3 N^3 of the factorization's flops live
(reference `conflux_opt.hpp:1626-1634`); this module provides an MXU-tiled
Pallas implementation behind the `conflux_tpu.ops.blas` backend registry.
Off-TPU (CPU simulation in tests) the kernels run in interpret mode.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _matmul_kernel(a_ref, b_ref, o_ref, acc_ref):
    @pl.when(pl.program_id(2) == 0)
    def _zero():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += jnp.dot(
        a_ref[:], b_ref[:], preferred_element_type=jnp.float32
    )

    @pl.when(pl.program_id(2) == pl.num_programs(2) - 1)
    def _store():
        o_ref[:] = acc_ref[:].astype(o_ref.dtype)


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


@functools.partial(jax.jit, static_argnames=("bm", "bn", "bk", "interpret"))
def _gemm(a, b, bm: int, bn: int, bk: int, interpret: bool):
    M, K = a.shape
    K2, N = b.shape
    assert K == K2, (a.shape, b.shape)
    Mp, Np, Kp = _round_up(M, bm), _round_up(N, bn), _round_up(K, bk)
    if (Mp, Kp) != (M, K):
        a = jnp.pad(a, ((0, Mp - M), (0, Kp - K)))
    if (Kp, Np) != (K, N):
        b = jnp.pad(b, ((0, Kp - K), (0, Np - N)))
    out = pl.pallas_call(
        _matmul_kernel,
        grid=(Mp // bm, Np // bn, Kp // bk),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),
            pl.BlockSpec((bk, bn), lambda i, j, k: (k, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((Mp, Np), a.dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        cost_estimate=pl.CostEstimate(
            flops=2 * Mp * Np * Kp,
            bytes_accessed=(Mp * Kp + Kp * Np + Mp * Np) * a.dtype.itemsize,
            transcendentals=0,
        ),
        interpret=interpret,
    )(a, b)
    if (Mp, Np) != (M, N):
        out = out[:M, :N]
    return out


def gemm(a: jax.Array, b: jax.Array, *, bm: int = 256, bn: int = 256,
         bk: int = 512) -> jax.Array:
    """a @ b via an MXU-tiled Pallas kernel with float32 accumulation."""
    M, K = a.shape
    _, N = b.shape
    # clamp blocks for small operands; keep MXU/VPU-aligned minima
    bm = min(bm, _round_up(M, 128))
    bn = min(bn, _round_up(N, 128))
    bk = min(bk, _round_up(K, 128))
    interpret = jax.default_backend() != "tpu"
    return _gemm(a, b, bm, bn, bk, interpret)
