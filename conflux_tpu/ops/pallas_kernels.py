"""Hand-written Pallas TPU kernels for the hot tile ops.

The trailing-matrix GEMM is where ~2/3 N^3 of the factorization's flops live
(reference `conflux_opt.hpp:1626-1634`); this module provides an MXU-tiled
Pallas implementation behind the `conflux_tpu.ops.blas` backend registry.
Off-TPU (CPU simulation in tests) the kernels run in interpret mode.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _matmul_kernel(a_ref, b_ref, o_ref, acc_ref):
    @pl.when(pl.program_id(2) == 0)
    def _zero():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += jnp.dot(
        a_ref[:], b_ref[:], preferred_element_type=jnp.float32
    )

    @pl.when(pl.program_id(2) == pl.num_programs(2) - 1)
    def _store():
        o_ref[:] = acc_ref[:].astype(o_ref.dtype)


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


@functools.partial(jax.jit, static_argnames=("bm", "bn", "bk", "interpret"))
def _gemm(a, b, bm: int, bn: int, bk: int, interpret: bool):
    M, K = a.shape
    K2, N = b.shape
    assert K == K2, (a.shape, b.shape)
    Mp, Np, Kp = _round_up(M, bm), _round_up(N, bn), _round_up(K, bk)
    if (Mp, Kp) != (M, K):
        a = jnp.pad(a, ((0, Mp - M), (0, Kp - K)))
    if (Kp, Np) != (K, N):
        b = jnp.pad(b, ((0, Kp - K), (0, Np - N)))
    out = pl.pallas_call(
        _matmul_kernel,
        grid=(Mp // bm, Np // bn, Kp // bk),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),
            pl.BlockSpec((bk, bn), lambda i, j, k: (k, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((Mp, Np), a.dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        cost_estimate=pl.CostEstimate(
            flops=2 * Mp * Np * Kp,
            bytes_accessed=(Mp * Kp + Kp * Np + Mp * Np) * a.dtype.itemsize,
            transcendentals=0,
        ),
        interpret=interpret,
    )(a, b)
    if (Mp, Np) != (M, N):
        out = out[:M, :N]
    return out


def gemm(a: jax.Array, b: jax.Array, *, bm: int = 256, bn: int = 256,
         bk: int = 512) -> jax.Array:
    """a @ b via an MXU-tiled Pallas kernel with float32 accumulation."""
    M, K = a.shape
    _, N = b.shape
    # clamp blocks for small operands; keep MXU/VPU-aligned minima
    bm = min(bm, _round_up(M, 128))
    bn = min(bn, _round_up(N, 128))
    bk = min(bk, _round_up(K, 128))
    interpret = jax.default_backend() != "tpu"
    return _gemm(a, b, bm, bn, bk, interpret)


# --------------------------------------------------------------------------- #
# Panel LU: masked Gaussian elimination on a VMEM-resident column block
# --------------------------------------------------------------------------- #
#
# The role of the reference's per-rank `LAPACKE_dgetrf` panel kernel (`LUP`,
# `conflux_opt.hpp:143-166`), redesigned for the TPU vector unit: rows never
# move (XLA's LU custom call swaps rows serially per column and overflows its
# scoped VMEM on tall panels). Instead the whole (m, w) block lives in VMEM
# and each of the w elimination steps is a handful of full-array masked VPU
# ops: select pivot by masked argmax, record it, write multipliers in place,
# rank-1-update the live rows. Pivot rows keep their (now U-row) values in
# their original positions; `alive` marks rows not yet chosen. The caller
# gathers rows into LAPACK order once at the end of the full panel.

_PANEL_W = 128  # column-block width == one lane tile


def _lu_block_kernel(a_ref, alive_ref, out_ref, alive_out_ref, piv_ref):
    m, w = a_ref.shape
    rows = jax.lax.broadcasted_iota(jnp.int32, (m, w), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (m, w), 1)
    cols1 = jax.lax.broadcasted_iota(jnp.int32, (1, w), 1)

    out_ref[:] = a_ref[:]
    alive_out_ref[:] = alive_ref[:]
    piv_ref[:] = jnp.zeros((1, w), jnp.int32)

    # Mutate the output refs per step; the loop carry stays scalar (Mosaic
    # cannot legalize scf.for with large value carries). Two more Mosaic
    # constraints shape the body: there is no (m, 1) -> (m, w) lane
    # broadcast (the pivot column/row are spread with small MXU matmuls
    # instead), and boolean ops between lane-iota-derived masks (sublane-
    # replicated i1 layout) and data-derived masks trigger invalid i1
    # relayouts — so every mask is cast to f32 and combined arithmetically.
    def body(j, carry):
        A = out_ref[:]
        alive_f = (alive_out_ref[:] != 0).astype(jnp.float32)
        # broadcast column j across lanes with a roll-reduction tree: the
        # masked array has a single nonzero per row, so the cyclic tree sum
        # is EXACT in f32 (an MXU broadcast would truncate to bf16 passes)
        colj = jnp.where(cols == j, A, 0.0)
        s = 1
        while s < w:
            colj = colj + pltpu.roll(colj, s, 1)
            s *= 2
        cand = jnp.abs(colj) * alive_f - (1.0 - alive_f)  # dead rows -> -1
        # masked argmax as reductions to scalar (lowest row wins ties)
        p = jnp.min(jnp.where(cand == jnp.max(cand), rows, m)).astype(jnp.int32)
        isp_f = (rows == p).astype(jnp.float32)
        # pivot row: dynamic sublane read (supported, unlike lane indexing),
        # then an exact sublane broadcast
        rowp_bc = jnp.broadcast_to(out_ref[pl.ds(p, 1), :], (m, w))
        colmask_f = (cols == j).astype(jnp.float32)
        gtmask_f = (cols > j).astype(jnp.float32)
        pivval = jnp.sum(isp_f * colmask_f * A)
        live_f = alive_f * (1.0 - isp_f)
        l = colj / pivval * live_f  # (m, w) multipliers, 0 on dead/pivot rows
        # rank-1 update of live rows, trailing columns only; multipliers into
        # column j of live non-pivot rows
        A = A - gtmask_f * (l * rowp_bc)
        maskf = colmask_f * live_f
        A = A * (1.0 - maskf) + l * maskf
        out_ref[:] = A
        alive_out_ref[:] = live_f.astype(jnp.int8)
        piv_ref[:] = jnp.where(cols1 == j, p, piv_ref[:])
        return carry

    jax.lax.fori_loop(0, w, body, 0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _lu_block(a, alive, interpret: bool):
    m, w = a.shape
    return pl.pallas_call(
        _lu_block_kernel,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((m, w), a.dtype),
            jax.ShapeDtypeStruct((m, w), jnp.int8),
            jax.ShapeDtypeStruct((1, w), jnp.int32),
        ),
        interpret=interpret,
    )(a, alive)


# --------------------------------------------------------------------------- #
# Row scatter (REMOVED, round 4)
# --------------------------------------------------------------------------- #
#
# An experimental pipelined row-DMA scatter (HBM -> VMEM -> HBM with
# scalar-prefetched destination indices, in-place aliasing) lived here in
# rounds 3-4 as the `swap='dma'` alternative to XLA's serial per-row
# scatter loop (~10 ms/step at v=1024, N=32768). The pre-decided adoption
# criterion (docs/ROUND3.md #3) required a staged hardware A/B with a
# full-scale residual gate; the TPU tunnel never recovered to run it, so
# the kernel was deleted unadopted per VERDICT r3 item 3 ("no third
# state") — see docs/ROUND4.md. Git history (rounds 3-4) has the kernel,
# its bring-up protocol (scripts/swap_probe.py), and the lesson that
# direct HBM->HBM local DMA wedges the chip (local copies want a VMEM
# side).


def lu_block(a: jax.Array, alive: jax.Array):
    """Eliminate one (m, 128) column block in place (no row movement).

    `alive` is an (m, 1) mask of rows still eligible as pivots. Returns
    (out, alive_out, piv): `out` has U-row values sitting at the pivot rows'
    original positions and L multipliers at live rows; `piv` (1, 128) gives
    the chosen pivot row per elimination step. VMEM bound: the f32 block, the
    int8 mask and the (m, w) f32 temporaries must fit the 16 MB scoped VMEM
    — m <= 4096 is safe (m=8192 measured over the limit).
    """
    m, w = a.shape
    interpret = jax.default_backend() != "tpu"
    alive_mw = jnp.broadcast_to(alive.astype(jnp.int8), (m, w))
    out, alive_out, piv = _lu_block(a, alive_mw, interpret)
    return out, alive_out[:, :1].astype(jnp.int32), piv
