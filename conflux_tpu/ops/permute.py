"""Row-permutation and pivot-compaction ops.

TPU-native equivalent of the reference's OpenMP row-permutation machinery
(`src/conflux/lu/utils.hpp:12-160`: `permute_rows`, `inverse_permute_rows`,
`prepend_column`) and the pivot compaction kernel `push_pivots_up`
(`conflux_opt.hpp:176-218`). On TPU these are value-level gathers/scatters —
XLA turns them into HBM-bandwidth copies; no in-place threading needed.

The distributed LU itself never moves rows (it masks instead — SURVEY P6),
but these ops are part of the public API surface for users who want the
reference's explicit-permutation workflow, and they back the validation
path's factor reconstruction.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def permute_rows(A: jax.Array, perm: jax.Array) -> jax.Array:
    """out[i, :] = A[perm[i], :]  (reference `utils.hpp` permute_rows)."""
    return A[perm, :]


def inverse_permute_rows(A: jax.Array, perm: jax.Array) -> jax.Array:
    """out[perm[i], :] = A[i, :] — the inverse of :func:`permute_rows`."""
    inv = jnp.zeros_like(perm).at[perm].set(jnp.arange(perm.shape[0], dtype=perm.dtype))
    return A[inv, :]


def invert_permutation(perm: jax.Array) -> jax.Array:
    return jnp.zeros_like(perm).at[perm].set(jnp.arange(perm.shape[0], dtype=perm.dtype))


def prepend_column(A: jax.Array, col: jax.Array) -> jax.Array:
    """Glue an index column onto a candidate buffer (reference
    `utils.hpp:12-26` — used to carry global row ids through local LUs)."""
    return jnp.concatenate([col[:, None].astype(A.dtype), A], axis=1)


def swap_minimal_perm(gpiv: jax.Array, m: int) -> jax.Array:
    """Length-m permutation placing winner j at slot j with <= 2v moves.

    LAPACK's getrf reorders rows by pairwise swaps, so at most 2v rows change
    position; a compaction permutation ("winners, then the rest in order")
    moves O(m) rows and costs a full-matrix gather per superstep. This builds
    the swap-flavoured permutation instead: slots [0, v) take the winners in
    pivot order, top-slot occupants displaced by an incoming winner drop into
    the slots those winners vacated (in ascending order), and every other row
    stays put.

    gpiv entries outside [0, m) (tournament pad ids from a rank-deficient
    panel, see `blas.tournament_winners`) are replaced by the lowest unused
    row ids so the result is always a valid permutation — the factor values
    for such panels are garbage either way (zero pivots), but downstream
    gathers/scatters never alias rows.
    """
    v = gpiv.shape[0]
    pos = jnp.arange(m, dtype=gpiv.dtype)
    valid = (gpiv >= 0) & (gpiv < m)
    is_w = jnp.zeros((m,), bool).at[jnp.where(valid, gpiv, m)].set(
        valid, mode="drop"
    )
    # lowest unused rows, ascending, to stand in for invalid winner ids
    unused = jnp.sort(jnp.where(is_w, m, pos))
    bad_rank = jnp.cumsum(~valid) - 1
    gpiv = jnp.where(valid, gpiv, unused[jnp.clip(bad_rank, 0, m - 1)])
    is_w = jnp.zeros((m,), bool).at[gpiv].set(True, mode="drop")
    # non-winner rows currently sitting in the top v slots, ascending (padded
    # with m, which clip keeps in range; the pad entries are never selected
    # because #vacant-slots == #displaced-rows)
    disp = jnp.sort(jnp.where((pos < v) & ~is_w, pos, m))
    vac = (pos >= v) & is_w
    rank = jnp.cumsum(vac) - 1
    sperm = jnp.where(vac, disp[jnp.clip(rank, 0, m - 1)], pos)
    return sperm.at[:v].set(gpiv)


def push_pivots_up(A: jax.Array, pivot_mask: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Stable partition: rows with pivot_mask True move to the top, others
    keep their relative order below (the role of `push_pivots_up`,
    `conflux_opt.hpp:176-218`, as a value-level permutation).

    Returns (A_permuted, perm) with A_permuted = A[perm].
    """
    n = A.shape[0]
    idx = jnp.arange(n)
    # stable argsort of (not pivot) keeps pivots first, original order within
    perm = jnp.argsort(jnp.where(pivot_mask, idx, idx + n), stable=True)
    return A[perm, :], perm
