"""Serve-path resilience: health guards, escalation, quarantine, faults.

The serving stack (`FactorPlan`/`SolveSession`/`ServeEngine`) is fast but
trusting: one NaN/Inf RHS host-staged into a coalesced batch silently
corrupts every co-batched answer, an ill-conditioned SMW-drifted session
returns garbage with no residual check, a queued request has no deadline
(an abandoned `result(timeout)` still burns its `max_pending` slot), and
a dead dispatcher thread queues work forever. This module holds the
host-side resilience machinery the engine wires through those layers:

- :class:`HealthPolicy` — the knobs: RHS finite guards at admission and
  staging (blast-radius isolation: a poisoned request fails its OWN
  future, never the batch), the fused finite/spot-residual output check
  (`conflux_tpu.update.health_spot_check`, fused INTO the solve program
  so the clean path pays no extra dispatch), the escalation ladder
  budget, and the quarantine circuit breaker.

- :func:`escalate` — the ladder run when a dispatched solve fails its
  health check: (1) one forced refactorization through the plan's CACHED
  factor program (`SolveSession.refactor` — absorbs any SMW drift, the
  usual culprit), (2) one round of iterative refinement riding the
  resident factors (`SolveSession.refine_checked`), (3) a structured
  :class:`SolveUnhealthy` carrying the residual/cond evidence of every
  rung. Rare by construction, so it may block (the engine runs it on the
  drain thread).

- :class:`CircuitBreaker` — per-session quarantine: after
  `quarantine_after` consecutive ladder failures the session fast-fails
  (:class:`SessionQuarantined`) instead of burning whole batches on a
  sick system; after `quarantine_cooldown` seconds ONE probe request is
  let through (half-open) and a healthy answer closes the circuit.

- :class:`FaultPlan` — deterministic, seeded fault injection for tests
  and the chaos soak (`scripts/soak.py --serve`): NaN at staging,
  delay/crash/kill at the named engine sites (dispatch, drain, d2h,
  refresh), forced-unhealthy verdicts at the solve check. The engine and
  `SolveSession._refactor` consult the installed plan at each site;
  production code never pays more than a None check.

Every outcome — guard trips, isolations, retries, refactor/refine
escalations, evictions, quarantine transitions, watchdog trips, injected
faults — is counted here and surfaces through
`profiler.serve_stats()['health']` so reliability is one coherent,
observable surface next to the throughput counters.
"""

from __future__ import annotations

import cmath
import dataclasses
import math
import threading
import time

import numpy as np

# --------------------------------------------------------------------------- #
# structured failures
# --------------------------------------------------------------------------- #


class RhsNonFinite(ValueError):
    """A request's RHS carries NaN/Inf — rejected at admission or
    isolated at staging so it never contaminates a coalesced batch."""


class MeshPlanUnsupported(ValueError):
    """A mesh-sharded (batch-sharded) plan hit one of the GENUINE
    residue surfaces — operations whose semantics contradict sharded
    state, not missing plumbing (DESIGN §32). The serve stack itself
    (factor lane, coalescing, tiering, checkpoint, QoS, fabric) serves
    mesh plans directly; what remains is migration: pinning sharded
    state onto one device (``device=`` naming a device OUTSIDE the
    plan's mesh, ``to_device``) and restoring a sharded checkpoint on
    a host that lacks the mesh's devices (cross-host migration).
    Structured (a ValueError subclass, so legacy string-matching
    callers keep working) so callers can route programmatically: the
    fix is a topology fix — drop the pin or restore on a matching
    host — not a fallback code path. Every raise is counted in
    ``profiler.serve_stats()['health']['mesh_plan_unsupported']``
    (zero on a healthy mesh trace, asserted by ``bench_engine
    --mesh``). `surface` names the rejecting surface (e.g.
    'factor_lane', 'factor', 'to_device', 'plan_codec')."""

    def __init__(self, msg: str, surface: str = ""):
        super().__init__(msg)
        self.surface = surface
        bump("mesh_plan_unsupported")


class HostUnavailable(RuntimeError):
    """A fabric request targeted an engine host that cannot answer —
    its process died mid-flight, its heartbeat lease lapsed (suspect or
    dead), its circuit breaker is open after repeated transport
    failures, or its sessions are mid-fail-over onto survivors. The
    request NEVER hangs: in-flight futures on a declared-dead host fail
    with this error the moment the fabric declares it. `retry_after`
    rides the fabric's measured signals (the PR 8 pattern): during
    fail-over it is the measured per-session revival rate times the
    sessions still queued, otherwise the heartbeat/breaker window that
    must elapse before the host can be trusted again. `host` names the
    unavailable host id. Counted in
    ``profiler.serve_stats()['health']['host_unavailable']``."""

    def __init__(self, msg: str, retry_after: float = 0.0,
                 host: str | None = None):
        super().__init__(msg)
        self.retry_after = retry_after
        self.host = host
        bump("host_unavailable")


class FleetDegraded(RuntimeError):
    """Fabric admission refused: fewer than `min_live` engine hosts are
    alive, so the fabric is running in degraded mode — existing
    sessions on live hosts keep answering, but NEW session opens (and,
    below quorum, all traffic) are shed until capacity recovers.
    `retry_after` hints when the next heartbeat round could restore a
    suspect host or finish a fail-over; `live`/`total` carry the
    observed host census. Counted in
    ``profiler.serve_stats()['health']['fleet_degraded']``."""

    def __init__(self, msg: str, retry_after: float = 0.0,
                 live: int = 0, total: int = 0):
        super().__init__(msg)
        self.retry_after = retry_after
        self.live = live
        self.total = total
        bump("fleet_degraded")


class WireCorrupt(ConnectionError):
    """A shared-memory wire segment failed its integrity check
    (DESIGN §31): a reply/request record's generation tag does not
    match its descriptor (a SIGKILL mid-write left a torn record, or a
    stale descriptor points at a recycled slot), or the descriptor
    names bytes outside the segment (overrun). Deliberately a
    ConnectionError subclass — the payload channel to that host can no
    longer be trusted, so the front treats it exactly like a torn
    pipe: the host is declared structurally dead on the spot, every
    pending reply future fails instantly (never a hang), and fail-over
    revives its sessions from the last checkpoint. That condemnation
    applies to REPLY-side corruption (the front's decode); a corrupt
    REQUEST record detected worker-side instead fails only its own
    item — shipped back as a structured error the front rehydrates to
    this type — because the front wrote that record and its
    frame-mates validated fine, so the channel itself is still
    trusted. `kind` is one of 'torn_segment' | 'stale_generation' |
    'overrun'; `host` names the host whose wire tore. Counted in
    ``profiler.serve_stats()['health']['wire_corrupt']``."""

    def __init__(self, msg: str, kind: str = "torn_segment",
                 host: str | None = None):
        super().__init__(msg)
        self.kind = kind
        self.host = host
        bump("wire_corrupt")
        bump(f"wire_corrupt[{kind}]")


class TenantThrottled(RuntimeError):
    """Weighted fair-share admission shed this tenant's request: the
    engine is contended and the tenant is at/over its declared share of
    `max_pending` with no deficit credit left (DESIGN §30). The shed is
    a POLICY outcome, not a failure — other tenants' traffic (and the
    latency class in particular) is admitted untouched, which is the
    point. `retry_after` is sized from the tenant's weighted fraction
    of the engine's measured drain rate: by then roughly one of the
    tenant's own slots should have freed. `tenant`/`qos_class` carry
    the shed attribution (`qos_class` is the 'tenant/tier' key).
    Counted globally in
    ``profiler.serve_stats()['health']['tenant_throttled']`` and
    per class under ``tenant_throttled[<tenant>/<tier>]``."""

    def __init__(self, msg: str, retry_after: float = 0.0,
                 tenant: str | None = None,
                 qos_class: str | None = None):
        super().__init__(msg)
        self.retry_after = retry_after
        self.tenant = tenant
        self.qos_class = qos_class
        bump("tenant_throttled")
        if qos_class is not None:
            bump(f"tenant_throttled[{qos_class}]")


class DeadlineExceeded(TimeoutError):
    """The request's deadline passed while it was queued; its pending
    slot has been released (lazy eviction, `ServeEngine.submit`)."""


class SessionQuarantined(RuntimeError):
    """The session's circuit breaker is open after repeated escalation
    failures: fast-fail instead of burning another batch. `retry_after`
    hints when the half-open probe window opens."""

    def __init__(self, msg: str, retry_after: float = 0.0):
        super().__init__(msg)
        self.retry_after = retry_after


class SolveUnhealthy(RuntimeError):
    """A dispatched solve failed its health check and the whole
    escalation ladder (forced refactor, then iterative refinement) could
    not recover it. `evidence` carries the per-rung verdicts:
    {'rungs': [{'rung', 'finite', 'residual'}...], 'residual_limit',
    'cond', 'update_rank', 'refactors'}."""

    def __init__(self, msg: str, evidence: dict):
        super().__init__(msg)
        self.evidence = evidence


class SessionSpilled(RuntimeError):
    """A request touched a spilled (host/disk-tier) session whose
    revival could not run — the revive lane's admission timed out, the
    request's deadline expired while the session was faulting in, or no
    residency manager is attached. The session's spill record is INTACT
    and it stays fully spilled (never half-resident): a later request
    revives it normally. `retry_after` hints when a revive slot should
    free up (0.0 = unknown)."""

    def __init__(self, msg: str, retry_after: float = 0.0):
        super().__init__(msg)
        self.retry_after = retry_after


class RestoreCorrupt(RuntimeError):
    """A spill/checkpoint record failed its integrity check on read
    (CRC mismatch, truncated leaf, undecodable manifest). Blast radius
    is the OWNING session only: its requests fail with this error and
    every other session — co-batched or not — is untouched. `evidence`
    carries {'path', 'leaf', 'expected_crc', 'got_crc'} (fields absent
    when the manifest itself was unreadable)."""

    def __init__(self, msg: str, evidence: dict | None = None):
        super().__init__(msg)
        self.evidence = {} if evidence is None else evidence


class InjectedFault(RuntimeError):
    """Raised by a FaultPlan 'crash' spec at an instrumented site —
    never by production code. Engine per-item handling catches it like
    any other failure (the worker thread survives)."""


class InjectedKill(BaseException):
    """A FaultPlan 'kill' spec: simulates a worker thread dying.
    BaseException on purpose — it sails through the engine's per-item
    `except Exception` handling and out of the worker loop, exercising
    the watchdog path."""


# --------------------------------------------------------------------------- #
# health counters (merged into profiler.serve_stats()['health'])
# --------------------------------------------------------------------------- #

_HEALTH_KEYS = (
    "rhs_rejects",            # submit()-time finite-guard trips
    "staging_isolations",     # poisoned requests failed alone at staging
    "output_failures",        # dispatched solves that failed the check
    "gang_unhealthy_slots",   # gang-stacked slots failing their per-slot
                              # verdict (requests re-dispatched solo)
    "survivor_redispatches",  # innocent requests re-dispatched solo
    "factor_rejects",         # submit_factor()-time A finite-guard trips
    "factor_isolations",      # poisoned A matrices failed alone at staging
    "factor_unhealthy",       # coalesced factorizations failing the verdict
    "refactor_escalations",   # ladder rung 1 runs
    "refine_escalations",     # ladder rung 2 runs
    "unhealthy",              # SolveUnhealthy raised (ladder exhausted)
    "evictions",              # deadline evictions
    "cond_refactors",         # DriftPolicy cond-limit guard trips
    "quarantine_opened",
    "quarantine_probes",
    "quarantine_recoveries",
    "watchdog_trips",
    "lane_revives",           # per-lane watchdog trips that respawned a lane
    "mesh_plan_unsupported",  # MeshPlanUnsupported raised (mesh plan routed
                              # at an unsharded-only serving surface)
    # the multi-host serve fabric (DESIGN §28)
    "host_unavailable",       # HostUnavailable raised (dead/suspect host,
                              # open breaker, or mid-fail-over routing)
    "fleet_degraded",         # FleetDegraded raised (admission below the
                              # live-host quorum)
    "heartbeat_misses",       # heartbeat probes that timed out / errored
    "hosts_suspected",        # alive -> suspect transitions
    "hosts_died",             # suspect/alive -> dead declarations
    "host_failovers",         # fail-over drills run (one per dead host)
    "sessions_failed_over",   # sessions revived on survivors from the
                              # dead host's last checkpoint
    "sessions_migrated",      # live drain-barrier session hand-offs
    # the zero-copy shm wire (DESIGN §31)
    "wire_corrupt",           # WireCorrupt raised (torn/stale/overrun
                              # ring record — host declared dead)
    "wire_ring_full",         # shm ring allocations refused (backpressure
                              # shed with a measured-drain retry hint)
    "wire_pickle_fallbacks",  # payloads that rode the pickle wire because
                              # they did not fit / the ring was saturated
    # multi-tenant QoS (DESIGN §30): fair-share admission sheds. The
    # per-class attributions ride lazy keys — tenant_throttled[t/tier]
    # and engine_saturated[t/tier] — next to these global totals
    "tenant_throttled",       # TenantThrottled raised (over-share tenant
                              # shed while the engine was contended)
    "faults_injected",
)

_HEALTH_LOCK = threading.Lock()
_HEALTH: dict[str, int] = {k: 0 for k in _HEALTH_KEYS}  # guarded-by: _HEALTH_LOCK


def bump(key: str, n: int = 1) -> None:
    """Count one health outcome (unknown keys appear lazily)."""
    with _HEALTH_LOCK:
        _HEALTH[key] = _HEALTH.get(key, 0) + n


def health_stats() -> dict:
    """Snapshot of the resilience counters (profiler.serve_stats()
    exposes this as the 'health' sub-dict)."""
    with _HEALTH_LOCK:
        return dict(_HEALTH)


def clear_health() -> None:
    """Reset the counters (profiler.clear() calls this too)."""
    with _HEALTH_LOCK:
        for k in list(_HEALTH):
            _HEALTH[k] = 0


# --------------------------------------------------------------------------- #
# the policy
# --------------------------------------------------------------------------- #


@dataclasses.dataclass(frozen=True)
class HealthPolicy:
    """What the engine guards, and how hard it fights before giving up.

    check_rhs: finite-guard every request's RHS at `submit()` (raises
        :class:`RhsNonFinite` synchronously) and AGAIN at staging (a
        request poisoned after admission fails its own future and is
        excluded from the staged buffer — blast-radius isolation).
    check_output: run the fused finite/spot-residual check on every
        dispatched solve (`SolveSession.solve_checked`). The check rides
        the SAME compiled program as the solve — zero extra dispatches —
        and its verdict crosses to the host with the drain thread's
        existing copy.
    submit_guard_sample: elements of each request's RHS the submit-time
        guard scans (None = exact, every element). The default samples:
        the staging guard re-checks the whole coalesced buffer exactly
        (amortized to one summation per BATCH) and the device-side
        finite verdict is exact for free, so sampling at submit only
        moves where a sparse poison is reported, never whether.
    residual_limit: relative-residual trip wire for the spot check
        (column 0 of the staged buffer, the systemic sentinel — see
        `update.health_spot_check`). None resolves per dtype/N via
        :meth:`resolved_residual_limit`; for bf16 the resolved limit is
        so loose the finite check is effectively the only output guard.
    max_refactor_retries / max_refine_retries: escalation-ladder budget
        (rung 1: forced refactor through the cached factor program;
        rung 2: one iterative-refinement sweep each).
    quarantine_after: consecutive ladder failures before the session's
        circuit breaker opens (fast-fail with
        :class:`SessionQuarantined`).
    quarantine_cooldown: seconds the breaker stays open before admitting
        ONE half-open probe request.
    """

    check_rhs: bool = True
    check_output: bool = True
    submit_guard_sample: int | None = 4096
    residual_limit: float | None = None
    max_refactor_retries: int = 1
    max_refine_retries: int = 1
    quarantine_after: int = 3
    quarantine_cooldown: float = 5.0

    def resolved_residual_limit(self, dtype, n: int) -> float:
        """1e4 * eps(dtype) * sqrt(N): loose enough that the 'inv'
        substitution engine's cond(L)cond(U)-scaled residuals never trip
        it on healthy traffic, tight enough to catch the O(1) garbage an
        ill-conditioned SMW correction or corrupted factor produces."""
        if self.residual_limit is not None:
            return float(self.residual_limit)
        eps = float(np.finfo(np.dtype(dtype)).eps) \
            if np.dtype(dtype).kind in "fc" else 1e-7
        return 1e4 * eps * math.sqrt(max(1, n))


def rhs_finite(b2: np.ndarray, sample: int | None = None) -> bool:
    """Host-side finite guard. Exact mode (sample=None) is one
    vectorized native-dtype summation instead of `isfinite().all()`:
    NaN/Inf anywhere poisons the accumulator (opposite-sign infinities
    meet as NaN), there is no bool temporary, and a non-finite verdict
    is confirmed with the exact scan so (rare) accumulator overflow of
    legitimate huge-magnitude data can never cause a false reject.

    `sample=k` checks only the first k elements — the SUBMIT guard's
    mode: at production request sizes an exact per-request pass re-reads
    every byte a second time and alone eats most of the <5% clean-path
    overhead budget (BENCH_RESILIENCE.json). The sampled check still
    rejects wholesale-poisoned requests synchronously; anything that
    slips it is caught EXACTLY by the per-batch staging guard (one
    amortized summation of the coalesced buffer, culprits isolated to
    their own futures) and by the device-side finite verdict, which
    costs nothing extra. Detection is never lost — only the reporting
    point moves."""
    kind = b2.dtype.kind
    if kind not in "fc":
        return True
    v = b2 if sample is None else b2.ravel()[:sample]
    # one SIMD summation, read with C-level isfinite — no ufunc round
    # trips, no temporaries
    if kind == "f":
        if math.isfinite(v.sum()):
            return True
    elif cmath.isfinite(complex(v.sum())):
        return True
    # non-finite sum: real poison, or accumulator overflow — confirm
    # exactly, so the full scan only ever runs on suspicion
    with np.errstate(invalid="ignore", over="ignore"):
        return bool(np.isfinite(v).all())


# --------------------------------------------------------------------------- #
# circuit breaker (session quarantine)
# --------------------------------------------------------------------------- #


class CircuitBreaker:
    """Closed → (K consecutive failures) → open → (cooldown) → half-open
    probe → closed again on a healthy answer, re-open on a sick one.

    `clock` is injectable for deterministic tests. Thread-safe: `allow`
    consumes the single half-open probe slot atomically; a probe that
    never resolves (evicted, engine died) re-arms after another
    cooldown instead of wedging the breaker half-open forever.
    """

    def __init__(self, threshold: int = 3, cooldown: float = 5.0,
                 clock=time.monotonic):
        self.threshold = int(threshold)
        self.cooldown = float(cooldown)
        self._clock = clock
        self._lock = threading.Lock()
        self._failures = 0     # guarded-by: _lock
        self._state = "closed"  # guarded-by: _lock
        self._opened_at = 0.0  # guarded-by: _lock
        # clock() of the outstanding half-open probe
        self._probe_at = None  # guarded-by: _lock

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def allow(self) -> tuple[bool, float]:
        """(admit?, retry_after). Open circuits refuse until the cooldown
        elapses, then admit exactly one probe per cooldown window."""
        with self._lock:
            if self._state == "closed":
                return True, 0.0
            now = self._clock()
            since = now - (self._probe_at if self._state == "half-open"
                           else self._opened_at)
            if since >= self.cooldown:
                self._state = "half-open"
                self._probe_at = now
                bump("quarantine_probes")
                return True, 0.0
            return False, self.cooldown - since

    def record_success(self) -> None:
        with self._lock:
            if self._state != "closed":
                self._state = "closed"
                self._probe_at = None
                bump("quarantine_recoveries")
            self._failures = 0

    def record_failure(self) -> None:
        with self._lock:
            if self._state == "half-open":  # sick probe: straight back open
                self._state = "open"
                self._opened_at = self._clock()
                self._probe_at = None
                return
            self._failures += 1
            if self._state == "closed" and self._failures >= self.threshold:
                self._state = "open"
                self._opened_at = self._clock()
                bump("quarantine_opened")


_ATTACH_LOCK = threading.Lock()


def breaker_for(session, policy: HealthPolicy,
                clock=time.monotonic) -> CircuitBreaker:
    """Get-or-attach the session's breaker (sessions outlive engines, so
    the breaker lives on the session; first policy to touch it wins)."""
    br = session._breaker
    if br is None:
        with _ATTACH_LOCK:
            br = session._breaker
            if br is None:
                br = CircuitBreaker(policy.quarantine_after,
                                    policy.quarantine_cooldown, clock)
                session._breaker = br
    return br


# --------------------------------------------------------------------------- #
# deterministic fault injection
# --------------------------------------------------------------------------- #

FAULT_SITES = ("staging", "dispatch", "drain", "d2h", "solve", "refresh",
               "factor", "spill", "revive", "disk_write", "disk_read",
               "heartbeat", "route", "migrate", "host_kill",
               # the shm wire (DESIGN §31): alloc refusal + reader-side
               # integrity trips, injected in conflux_tpu/wire.py
               "ring_full", "torn_segment", "stale_generation",
               # the elastic fabric (DESIGN §34): 'replicate' fires on the
               # front's per-standby replica push (kinds 'crash'/'delay' —
               # a failed push leaves the standby one generation stale,
               # which the gen-coherence rule then refuses at fail-over;
               # the drain storm itself is exercised via 'migrate', whose
               # barrier remove_host rides unchanged).
               "replicate")
FAULT_KINDS = ("nan", "delay", "crash", "kill", "unhealthy")


@dataclasses.dataclass
class FaultSpec:
    """One injection rule. Sites: 'staging' (kind 'nan' poisons a
    request's staged RHS), 'dispatch'/'drain'/'d2h'/'refresh' (kinds
    'delay'/'crash'/'kill'), 'solve' (kind 'unhealthy' forces the health
    verdict false), 'factor' (the cold-start lane: kind 'nan' poisons a
    factor request's staged A matrix upstream of the staging guard,
    kind 'unhealthy' forces the post-factor verdict false). The tier
    layer (`conflux_tpu.tier`) adds 'spill'/'revive' (kinds
    'delay'/'crash'/'kill' — a crash at spill leaves the session
    resident, a crash at revive leaves it fully spilled, record intact)
    and 'disk_write'/'disk_read' ('delay'/'crash' plus, at disk_write,
    kind 'nan': corrupt the written record's bytes so the next revive
    fails its CRC with :class:`RestoreCorrupt`). 'crash'
    raises :class:`InjectedFault` where the
    engine's per-item handling catches it (survivor re-dispatch / batch
    failure, thread survives); 'kill' escapes the loop entirely so the
    watchdog path runs. `prob` draws from the plan's seeded stream;
    `count` bounds total injections (None = unlimited)."""

    site: str
    kind: str
    prob: float = 1.0
    delay_s: float = 0.0
    count: int | None = None
    # The fabric layer (`conflux_tpu.fabric`, DESIGN §28) adds
    # 'heartbeat' (kinds 'delay'/'crash' — a slow or failed probe, the
    # hysteresis driver), 'route' (kinds 'crash'/'delay' on the front's
    # per-request host call), 'migrate' (kinds 'crash'/'delay' at the
    # hand-off barrier: a crash before the target adopts leaves the
    # session intact on the source) and 'host_kill' (kind 'kill': the
    # whole engine host dies, exercising detection + fail-over).

    def __post_init__(self):
        if self.site not in FAULT_SITES:
            raise ValueError(f"unknown fault site {self.site!r} "
                             f"({'|'.join(FAULT_SITES)})")
        if self.kind not in FAULT_KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r} "
                             f"({'|'.join(FAULT_KINDS)})")


class FaultPlan:
    """A seeded set of :class:`FaultSpec` rules. `fire(site, kinds)`
    consults the rules in order and returns the first that triggers
    (consuming its budget); with `prob=1.0` / `count` specs the firing
    sequence is fully deterministic, which is what the regression tests
    pin. `injected` records every firing as {(site, kind): n}."""

    def __init__(self, specs, seed: int = 0):
        self.specs = [s if isinstance(s, FaultSpec) else FaultSpec(**s)
                      for s in specs]
        self._rng = np.random.default_rng(seed)
        self._lock = threading.Lock()
        self.injected: dict[tuple[str, str], int] = {}  # guarded-by: _lock

    def fire(self, site: str, kinds=None) -> FaultSpec | None:
        with self._lock:
            for s in self.specs:
                if s.site != site:
                    continue
                if kinds is not None and s.kind not in kinds:
                    continue
                if s.count is not None and s.count <= 0:
                    continue
                if s.prob < 1.0 and self._rng.random() >= s.prob:
                    continue
                if s.count is not None:
                    s.count -= 1
                key = (s.site, s.kind)
                self.injected[key] = self.injected.get(key, 0) + 1
                bump("faults_injected")
                return s
        return None


# one process-wide installed plan: sites outside the engine (the serve
# layer's refactor/refresh path) consult this; the engine prefers its own
# `fault_plan=` and falls back here
_ACTIVE_FAULTS: FaultPlan | None = None


def install_faults(plan: FaultPlan | None) -> None:
    """Install (or clear, with None) the process-wide fault plan."""
    global _ACTIVE_FAULTS
    _ACTIVE_FAULTS = plan


def active_faults() -> FaultPlan | None:
    return _ACTIVE_FAULTS


def maybe_fault(plan: FaultPlan | None, site: str) -> None:
    """Run the delay/crash/kill faults of `site` (engine plan first,
    then the installed one). No-op — one None check — without a plan."""
    p = plan if plan is not None else _ACTIVE_FAULTS
    if p is None:
        return
    s = p.fire(site, kinds=("delay", "crash", "kill"))
    if s is None:
        return
    if s.kind == "delay":
        time.sleep(s.delay_s)
        return
    if s.kind == "kill":
        raise InjectedKill(f"injected kill at {site}")
    raise InjectedFault(f"injected crash at {site}")


def data_fault(plan: FaultPlan | None, site: str, kind: str) -> FaultSpec | None:
    """Fire a data-shaped fault ('nan' at staging, 'unhealthy' at solve)
    without raising — the caller applies the corruption."""
    p = plan if plan is not None else _ACTIVE_FAULTS
    if p is None:
        return None
    return p.fire(site, kinds=(kind,))


# --------------------------------------------------------------------------- #
# the escalation ladder
# --------------------------------------------------------------------------- #


def evaluate(verdict, limit: float) -> tuple[bool, bool, float]:
    """Host-side read of a checked solve's (2,) verdict array
    [finite_flag, spot_residual]: (healthy, finite, residual)."""
    v = np.asarray(verdict)
    finite = bool(v[0] >= 0.5)
    res = float(v[1])
    return finite and res <= limit, finite, res


def evaluate_slots(verdict, limit: float) -> list[tuple[bool, bool, float]]:
    """Host-side read of a per-slot (2, S) verdict block — row 0 the
    per-slot finite flags, row 1 the per-slot probe residuals. Three
    device-side producers emit this contract and are indistinguishable
    here by design: the factor lane's checked program
    (`FactorPlan._factor_health_fn` — vmapped probe solve, or the §27
    fused stats epilogue, or the §29 Pallas factor kernel with the
    in-kernel probe row) and the gang's stacked solve verdicts
    (`update.health_spot_check_slots` / `health_verdict_from_stats_slots`).
    Returns one (healthy, finite, residual) triple per slot so the
    drain thread can settle the healthy sessions and isolate the sick
    ones individually (slot verdicts are independent by construction).
    A NaN residual (non-finite factors poison their own probe solve)
    compares unhealthy through the same `res <= limit` predicate
    `evaluate` uses; the slot sweep is vectorized — one bulk comparison,
    not S python reads — because a 32-wide factor drain runs this on
    every coalesced dispatch."""
    v = np.asarray(verdict)
    finite = v[0] >= 0.5
    res = v[1].astype(float)
    with np.errstate(invalid="ignore"):
        healthy = finite & (res <= limit)
    return [(bool(healthy[i]), bool(finite[i]), float(res[i]))
            for i in range(v.shape[-1])]


def escalate(session, buf, policy: HealthPolicy, limit: float,
             evidence0: dict | None = None, faults: FaultPlan | None = None):
    """Fight for one staged chunk `buf` (numpy, already bucket-width)
    whose first answer failed the health check. Returns the recovered
    HOST answer array; raises :class:`SolveUnhealthy` with the full
    per-rung evidence when the ladder is exhausted.

    Rung 1 (x max_refactor_retries): force one true refactorization
    through the plan's CACHED factor program — absorbs any accumulated
    SMW drift (the usual systemic culprit) — and re-solve checked.
    Rung 2 (x max_refine_retries): one iterative-refinement sweep
    against the refreshed base factors. Both rungs re-run the fused
    check; a finite=False answer skips refinement (NaN cannot be
    refined away). Runs under the session's lock so a concurrent
    dispatcher never observes half-swapped factors. Blocking is fine:
    this is the failure path.

    `evidence0` seeds the per-rung evidence chain: one dict (the
    failed dispatch) or a list of dicts (a precision ladder that
    already climbed, :func:`escalate_precision`).
    """
    if evidence0 is None:
        rungs: list[dict] = []
    elif isinstance(evidence0, dict):
        rungs = [dict(evidence0)]
    else:
        rungs = [dict(r) for r in evidence0]

    def check(verdict, rung):
        ok, finite, res = evaluate(verdict, limit)
        # the 'solve' fault site covers every health verdict, ladder
        # rungs included — how the chaos tests force a full-ladder loss
        if data_fault(faults, "solve", "unhealthy") is not None:
            ok = False
        rungs.append({"rung": rung, "finite": finite, "residual": res})
        return ok

    x = None
    with session._lock:
        for _ in range(policy.max_refactor_retries):
            bump("refactor_escalations")
            session.refactor()
            x, verdict = session.solve_checked(buf)
            if check(verdict, "refactor"):
                return np.asarray(x)
        for _ in range(policy.max_refine_retries):
            if x is None or not rungs[-1]["finite"]:
                break
            bump("refine_escalations")
            x, verdict = session.refine_checked(buf, x)
            if check(verdict, "refine"):
                return np.asarray(x)
    bump("unhealthy")
    evidence = {
        "rungs": rungs,
        "residual_limit": limit,
        "cond": session.last_cond,
        "update_rank": session.update_rank,
        "refactors": session.refactors,
    }
    raise SolveUnhealthy(
        f"solve unhealthy after {len(rungs)} rung(s): "
        + "; ".join(f"{r.get('rung', 'dispatch')}: finite={r['finite']} "
                    f"res={r['residual']:.3e}" for r in rungs)
        + f" (limit {limit:.3e})", evidence)


def escalate_precision(session, buf, precision, policy, limit,
                       evidence0: dict | None = None,
                       faults: FaultPlan | None = None):
    """The precision ladder's escalation rungs (DESIGN §33): fight for
    one staged chunk whose TIER-routed answer failed the §20 verdict by
    re-solving checked at each HIGHER served tier first — cheap rungs
    (a derived factor set + one substitution per tier, no refactor) —
    and only when the ladder tops out falling through to the native
    :func:`escalate` rungs (refactor + refine), carrying the
    accumulated per-rung evidence.

    'auto' requests additionally RATCHET the session's sticky rung
    (`SolveSession._auto_rung`), so a session that needed f32 once
    starts there on its next auto request instead of re-failing bf16.
    Explicit-tier requests climb without moving the rung (the caller
    asked for that tier; the ladder is the rescue, not the new
    default). `policy` may be None (an unguarded engine serving 'auto'
    traffic) — the native rungs then run under the default
    :class:`HealthPolicy`."""
    from conflux_tpu import serve

    rungs: list[dict] = [] if evidence0 is None else [dict(evidence0)]
    x = None
    with session._lock:
        tier = session._resolve_tier(precision)
        while tier is not None:
            nxt = serve.next_precision_tier(tier)
            if nxt is None:
                break
            bump("precision_escalations")
            session.precision_escalations += 1
            if precision == "auto":
                rung = serve.PRECISION_TIERS.index(nxt)
                if rung > session._auto_rung:
                    session._auto_rung = rung
                    # the persisted auto-rung changed: the session is
                    # checkpoint-dirty even though this is a solve path
                    session._ckpt_ver += 1
            x, verdict = session.solve_checked(buf, precision=nxt)
            ok, finite, res = evaluate(verdict, limit)
            if data_fault(faults, "solve", "unhealthy") is not None:
                ok = False
            rungs.append({"rung": f"precision:{nxt}", "finite": finite,
                          "residual": res})
            if ok:
                return np.asarray(x)
            tier = nxt
    return escalate(session, buf,
                    policy if policy is not None else HealthPolicy(),
                    limit, evidence0=rungs, faults=faults)
