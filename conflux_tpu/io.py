"""Matrix I/O and deterministic input generation.

Role of the reference's `CholeskyIO` (`src/conflux/cholesky/CholeskyIO.cpp`):
distributed SPD input generation (`:100-172` — identical seeded tile
everywhere plus diagonal dominance), file parse + tile scatter (`:185-375`),
and binary dump of matrices for debug verification (`:384-501`, MPI-IO).
The MPI-IO role is played by plain row-major binary files written from the
gathered host copy.
"""

from __future__ import annotations

import numpy as np

from conflux_tpu.geometry import CholeskyGeometry, LUGeometry


def _spd_base_tile(geom: CholeskyGeometry, seed: int, dtype) -> np.ndarray:
    rng = np.random.default_rng(seed)
    tile = rng.uniform(-1.0, 1.0, size=(geom.v, geom.v)).astype(dtype)
    return (tile + tile.T) / 2


def generate_spd_local(geom: CholeskyGeometry, px: int, py: int,
                       seed: int = 2020, dtype=np.float64) -> np.ndarray:
    """ONE device's (Ml, Nl) SPD shard, built tile-locally.

    Same scheme as the reference generator (`CholeskyIO.cpp:100-172`): every
    off-diagonal tile is the *same* seeded v x v symmetrized block (so any
    rank materializes its tiles without communication) and diagonal tiles
    get an N-scaled identity boost for positive definiteness. Peak memory
    is this shard plus one tile — the reference's ability to generate
    inputs far larger than any single rank's memory lives here (and in the
    streaming :func:`generate_spd_file`), not in the all-shards helpers.
    """
    N, v = geom.N, geom.v
    Px, Py = geom.grid.Px, geom.grid.Py
    sym = _spd_base_tile(geom, seed, dtype)
    boost = N * np.eye(v, dtype=dtype)
    loc = np.tile(sym, (geom.Mtl, geom.Ntl))
    # global-diagonal tiles owned here: i*Px+px == j*Py+py
    for i in range(geom.Mtl):
        gt = i * Px + px
        j, rem = divmod(gt - py, Py)
        if rem == 0 and 0 <= j < geom.Ntl:
            loc[i * v:(i + 1) * v, j * v:(j + 1) * v] += boost
    return loc


def generate_spd_shards(geom: CholeskyGeometry, seed: int = 2020,
                        dtype=np.float64) -> np.ndarray:
    """All shards (Px, Py, Ml, Nl) in `CholeskyGeometry.scatter` convention
    — a host-side convenience that necessarily holds N^2 elements; use
    :func:`generate_spd_local` per device coordinate to stay tile-local."""
    shards = np.empty((geom.grid.Px, geom.grid.Py, geom.Ml, geom.Nl), dtype)
    for px in range(geom.grid.Px):
        for py in range(geom.grid.Py):
            shards[px, py] = generate_spd_local(geom, px, py, seed, dtype)
    return shards


def generate_spd_tiles(geom: CholeskyGeometry, seed: int = 2020,
                       dtype=np.float64) -> np.ndarray:
    """Full (N, N) SPD input — the host-side convenience form of the same
    construction as :func:`generate_spd_local` (one tile of peak overhead;
    agreement with the shard path is asserted by the test suite)."""
    N, v = geom.N, geom.v
    A = np.tile(_spd_base_tile(geom, seed, dtype), (N // v, N // v))
    A[np.arange(N), np.arange(N)] += N
    return A


# Binary file format: int64 header (M, N, dtype code) + row-major data.
# The header helpers below are the single source of truth for the format.
# int32 is a first-class code so integer state (the LU row-origin map,
# `lu_factor_steps` checkpoints) round-trips exactly at any scale — a
# float32 detour would corrupt row ids above 2^24.
_HEADER_BYTES = 3 * 8
_DTYPES = [np.dtype(np.float32), np.dtype(np.float64), np.dtype(np.int32)]


def _write_header(f, M: int, N: int, dtype) -> None:
    dtype = np.dtype(dtype)
    if dtype not in _DTYPES:
        names = ", ".join(d.name for d in _DTYPES)
        raise ValueError(
            f"matrix files store {names} only, got {dtype.name}; "
            "cast narrow storage dtypes (e.g. bfloat16) to float32 first"
        )
    np.array([M, N, _DTYPES.index(dtype)], dtype=np.int64).tofile(f)


def _read_header(path: str) -> tuple[int, int, np.dtype]:
    import os

    with open(path, "rb") as f:
        header = np.fromfile(f, dtype=np.int64, count=3)
    if header.size != 3:
        raise ValueError(f"{path!r} is too short to hold a matrix header")
    M, N, code = (int(x) for x in header)
    size = os.path.getsize(path)
    if (M < 0 or N < 0 or not 0 <= code < len(_DTYPES)
            or size != _HEADER_BYTES + M * N * _DTYPES[code].itemsize):
        # A raw headerless dump (the reference's cholesky_helper format:
        # dim*dim doubles, no header) misparses its first doubles as header
        # fields; the size check catches the rare bit patterns that would
        # otherwise look valid.
        raise ValueError(
            f"{path!r} is not a conflux_tpu matrix file (header reads "
            f"M={M}, N={N}, dtype code={code}, file size {size}); raw "
            "headerless dumps (e.g. the reference cholesky_helper format) "
            "must be converted by prepending the int64 (M, N, dtype) header"
        )
    return M, N, _DTYPES[code]


def load_matrix_auto(path: str) -> np.ndarray:
    """Load a matrix from either format: the framework's headered file, or
    the reference cholesky_helper's raw headerless dump of dim*dim float64
    (`examples/cholesky_helper.cpp` writes these) — detected by exact file
    size. Raw float32 squares are accepted too. Ambiguity is impossible:
    a valid header demands size == 24 + M*N*itemsize, a raw square demands
    size == dim^2*itemsize, and the loader only falls back on rejection.
    """
    import math
    import os

    try:
        return load_matrix(path)
    except ValueError as header_err:
        size = os.path.getsize(path)
        for np_t in (np.float64, np.float32):
            n2, rem = divmod(size, np.dtype(np_t).itemsize)
            dim = math.isqrt(n2)
            if rem == 0 and dim * dim == n2 and dim > 0:
                return np.fromfile(path, dtype=np_t).reshape(dim, dim)
        raise ValueError(
            f"{path!r} is neither a conflux_tpu matrix file nor a raw "
            f"square float64/float32 dump ({size} bytes)"
        ) from header_err


def generate_spd_file(path: str, N: int, v: int = 256, seed: int = 7,
                      dtype=np.float64) -> None:
    """Stream a deterministic SPD matrix to disk one tile-strip at a time.

    The role of the reference's offline `cholesky_helper` generator for very
    large N (`examples/cholesky_helper.cpp`): the matrix never exists in
    RAM. Same construction as the in-memory generators (`CholeskyIO.cpp:
    100-172` scheme): one seeded symmetric v x v tile replicated everywhere
    plus an N-scaled diagonal boost.
    """
    if N % v:
        raise ValueError(f"N={N} must be a multiple of the tile size {v}")
    rng = np.random.default_rng(seed)
    tile = rng.uniform(-1.0, 1.0, size=(v, v)).astype(dtype)
    sym = ((tile + tile.T) / 2).astype(dtype)
    strip = np.tile(sym, (1, N // v))  # (v, N), identical for every tile row
    r = np.arange(v)
    with open(path, "wb") as f:
        _write_header(f, N, N, dtype)
        for ti in range(N // v):
            # boost this strip's diagonal in place, write, restore the saved
            # v entries (a strip copy would double peak RAM at very large N)
            saved = strip[r, ti * v + r].copy()
            strip[r, ti * v + r] += N
            strip.tofile(f)
            strip[r, ti * v + r] = saved


def save_matrix(path: str, A: np.ndarray) -> None:
    """Row-major binary dump. Same spirit as the reference's
    `data/output_N.bin` debug dumps."""
    A = np.ascontiguousarray(A)
    with open(path, "wb") as f:
        _write_header(f, A.shape[0], A.shape[1], A.dtype)
        A.tofile(f)


def load_matrix(path: str) -> np.ndarray:
    M, N, dtype = _read_header(path)
    with open(path, "rb") as f:
        f.seek(_HEADER_BYTES)
        A = np.fromfile(f, dtype=dtype).reshape(M, N)
    return A


def load_and_scatter(path: str, geom: LUGeometry | CholeskyGeometry) -> np.ndarray:
    """File parse + tile scatter (role of `CholeskyIO.cpp:185-375`)."""
    return geom.scatter(load_matrix(path))


def load_scattered(path: str, geom: LUGeometry | CholeskyGeometry) -> np.ndarray:
    """Stream a matrix file straight into (Px, Py, Ml, Nl) shards.

    Unlike :func:`load_and_scatter` the global matrix is never materialized:
    the native mmap engine (or an `np.memmap` fallback working one tile row
    at a time) reads tiles in place, so matrices larger than host RAM flow
    through the page cache -- the role of the reference's collective MPI-IO
    reads (`CholeskyIO.cpp:185-375`). The file's padded shape must match the
    geometry's (M, N).
    """
    M, N, dtype = _read_header(path)
    gM = getattr(geom, "M", geom.N)
    gN = geom.N
    if (M, N) != (gM, gN):
        raise ValueError(f"file is {M}x{N}, geometry needs {gM}x{gN}")
    from conflux_tpu import native

    Px, Py, v = geom.grid.Px, geom.grid.Py, geom.v
    fast = native.file_scatter(path, _HEADER_BYTES, gM, gN, v, Px, Py, dtype)
    if fast is not None:
        return fast
    A = np.memmap(path, dtype=dtype, mode="r", offset=_HEADER_BYTES,
                  shape=(gM, gN))
    Ml, Nl, Ntl = gM // Px, gN // Py, gN // (v * Py)
    shards = np.empty((Px, Py, Ml, Nl), dtype=dtype)
    for ti in range(gM // v):  # one (v, N) strip resident at a time
        px, lt = ti % Px, ti // Px
        strip = np.asarray(A[ti * v : (ti + 1) * v]).reshape(v, Ntl, Py, v)
        shards[px, :, lt * v : (lt + 1) * v] = (
            strip.transpose(2, 0, 1, 3).reshape(Py, v, Nl)
        )
    return shards


def save_scattered(path: str, shards: np.ndarray,
                   geom: LUGeometry | CholeskyGeometry) -> None:
    """Inverse of :func:`load_scattered`: stream shards to a matrix file
    (role of the reference's MPI-IO dumps, `CholeskyIO.cpp:384-501`)."""
    shards = np.asarray(shards)
    gM = getattr(geom, "M", geom.N)
    gN = geom.N
    Px, Py, v = geom.grid.Px, geom.grid.Py, geom.v
    if shards.shape != (Px, Py, gM // Px, gN // Py):
        raise ValueError(f"shards shape {shards.shape} does not match "
                         f"geometry ({Px}, {Py}, {gM // Px}, {gN // Py})")
    with open(path, "wb") as f:
        _write_header(f, gM, gN, shards.dtype)
    from conflux_tpu import native

    if native.file_gather(path, shards, _HEADER_BYTES, v, Px, Py):
        return
    with open(path, "r+b") as f:  # grow to full size for the memmap
        f.truncate(_HEADER_BYTES + gM * gN * shards.dtype.itemsize)
    A = np.memmap(path, dtype=shards.dtype, mode="r+", offset=_HEADER_BYTES,
                  shape=(gM, gN))
    Nl, Ntl = gN // Py, gN // (v * Py)
    for ti in range(gM // v):  # one (v, N) strip written at a time
        px, lt = ti % Px, ti // Px
        strip = shards[px, :, lt * v : (lt + 1) * v]  # (Py, v, Nl)
        A[ti * v : (ti + 1) * v] = (
            strip.reshape(Py, v, Ntl, v).transpose(1, 2, 0, 3).reshape(v, gN)
        )
    A.flush()
