"""Matrix I/O and deterministic input generation.

Role of the reference's `CholeskyIO` (`src/conflux/cholesky/CholeskyIO.cpp`):
distributed SPD input generation (`:100-172` — identical seeded tile
everywhere plus diagonal dominance), file parse + tile scatter (`:185-375`),
and binary dump of matrices for debug verification (`:384-501`, MPI-IO).
The MPI-IO role is played by plain row-major binary files written from the
gathered host copy.
"""

from __future__ import annotations

import numpy as np

from conflux_tpu.geometry import CholeskyGeometry, LUGeometry


def generate_spd_tiles(geom: CholeskyGeometry, seed: int = 2020,
                       dtype=np.float64) -> np.ndarray:
    """Distributed-convention SPD input, built tile-locally.

    Same scheme as the reference generator (`CholeskyIO.cpp:100-172`): every
    off-diagonal tile is the *same* seeded v x v block (so any rank can
    materialize its tiles without communication), the matrix is symmetrized,
    and the diagonal gets an N-scaled boost for positive definiteness.
    Returns the full (N, N) matrix; use `geom.scatter` for shards.
    """
    N, v = geom.N, geom.v
    rng = np.random.default_rng(seed)
    tile = rng.uniform(-1.0, 1.0, size=(v, v)).astype(dtype)
    sym = (tile + tile.T) / 2
    A = np.tile(sym, (N // v, N // v))
    A[np.arange(N), np.arange(N)] += N
    return A


def save_matrix(path: str, A: np.ndarray) -> None:
    """Row-major binary dump: int64 header (M, N, dtype code) + data.
    Same spirit as the reference's `data/output_N.bin` debug dumps."""
    A = np.ascontiguousarray(A)
    code = {np.dtype(np.float32): 0, np.dtype(np.float64): 1}[A.dtype]
    with open(path, "wb") as f:
        np.array([A.shape[0], A.shape[1], code], dtype=np.int64).tofile(f)
        A.tofile(f)


def load_matrix(path: str) -> np.ndarray:
    with open(path, "rb") as f:
        M, N, code = np.fromfile(f, dtype=np.int64, count=3)
        dtype = [np.float32, np.float64][int(code)]
        A = np.fromfile(f, dtype=dtype).reshape(int(M), int(N))
    return A


def load_and_scatter(path: str, geom: LUGeometry | CholeskyGeometry) -> np.ndarray:
    """File parse + tile scatter (role of `CholeskyIO.cpp:185-375`)."""
    return geom.scatter(load_matrix(path))
