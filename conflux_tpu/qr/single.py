"""Single-device QR: height-bounded TSQR tree + blocked panel factorization.

QR is the third member of the communication-optimal dense-factorization
family this framework covers (LU `lu/`, Cholesky `cholesky/`). The
reference library stops at LU/Cholesky; its panel machinery, though, is
exactly a tall-skinny reduction over stacked candidate blocks
(`src/conflux/lu/conflux_opt.hpp:220-336` reduces (2v, v) stacks down a
butterfly), and TSQR is the same tree shape with QR as the combiner — so
the framework's chunked-tree utilities carry over directly.

TPU-first design notes:
 - every `jnp.linalg.qr` call is height-bounded by `chunk` (the QR
   custom call shares the scoped-VMEM ceiling the LU call has,
   `ops/blas.py`); tall panels go through a recursive chunked tree that
   only ever factors (chunk, n) and (levels * n, n) stacks;
 - Q is never built by the tree. The tree yields a backward-stable R;
   Q comes from `A @ R^{-1}` (TRSM) followed by a second tree pass on Q
   itself (the CholeskyQR2 refinement recipe, with the QR tree instead
   of a Gram/Cholesky first pass). Two passes give eps-grade
   orthogonality even for badly conditioned A, while keeping all the
   O(M n^2) flops in MXU-friendly GEMM/TRSM form instead of Householder
   applications;
 - the blocked square factorization is block-Gram-Schmidt over v-wide
   panels (panel TSQR + GEMM trailing update), the same owner-computes
   superstep shape as the LU loop.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from conflux_tpu.ops import blas


def _tree_r(panel: jax.Array, chunk: int) -> jax.Array:
    """Upper-triangular R of a tall panel via a chunked QR reduction tree.

    Only the R factors move up the tree (the TSQR half that parallel
    QR needs); heights are bounded by max(chunk, 2n) at every level.
    Rows are zero-padded to a whole number of chunks — zero rows do not
    change R.
    """
    m, n = panel.shape
    ch = max(min(chunk, m), 2 * n)
    while True:
        nch = -(-m // ch)
        if nch * ch != m:
            panel = jnp.pad(panel, ((0, nch * ch - m), (0, 0)))
        if nch == 1:
            return jnp.linalg.qr(panel, mode="r")[:n]
        rs = jnp.linalg.qr(panel.reshape(nch, ch, n), mode="r")[:, :n]
        panel = rs.reshape(nch * n, n)
        m = nch * n
        if m <= ch:
            return jnp.linalg.qr(panel, mode="r")[:n]


def _positive_diag(Q: jax.Array, R: jax.Array):
    """Normalize so diag(R) is real and >= 0 — the unique QR
    normalization (makes results deterministic across chunkings/grids
    and comparable to LAPACK's convention up to its own signs). For
    complex dtypes the correction is the diagonal's conjugate phase
    (|d|/d), the unitary generalization of the real sign flip."""
    d = jnp.diagonal(R)
    if jnp.issubdtype(R.dtype, jnp.complexfloating):
        mag = jnp.abs(d)
        s = jnp.where(mag > 0, jnp.conj(d) / jnp.where(mag > 0, mag, 1.0),
                      jnp.ones((), R.dtype))
    else:
        s = jnp.where(d < 0, -1.0, 1.0).astype(R.dtype)
    return Q * jnp.conj(s)[None, :], R * s[:, None]


def tall_qr(panel: jax.Array, chunk: int | None = None, passes: int = 2):
    """(Q, R) of a tall-skinny panel (m >= n) — tree R + refined Q.

    Pass 1: R1 = tree_r(A), Q1 = A R1^{-1}. Pass 2 (default): R2 =
    tree_r(Q1), Q = Q1 R2^{-1}, R = R2 R1 — orthogonality lands at
    eps-scale independent of cond(A) (the CholeskyQR2 argument: Q1 is
    already well-conditioned, so the second pass is numerically exact).
    """
    m, n = panel.shape
    if m < n:
        raise ValueError(f"tall_qr needs m >= n, got {panel.shape}")
    # the chunk round is a batched QR call: default to the batched
    # VMEM-safe height for this width in the COMPUTE dtype (bf16 panels
    # run f32 math; v5e pin: 4096 at n=1024 f32)
    if chunk is None:
        chunk = blas.batched_call_rows(n, blas.compute_dtype(panel.dtype))
    cdtype = blas.compute_dtype(panel.dtype)
    prec = blas.matmul_precision()
    A = panel.astype(cdtype)
    R = None
    for _ in range(max(1, passes)):
        Ri = _tree_r(A, chunk)
        A = blas.trsm_right_upper(Ri, A)
        R = Ri if R is None else jnp.matmul(Ri, R, precision=prec)
    Q, R = _positive_diag(A, R)
    return Q.astype(panel.dtype), R.astype(panel.dtype)


@functools.partial(jax.jit, static_argnums=(1, 2, 3))
def _qr_blocked(A, v: int, chunk: int, passes: int):
    M, N = A.shape
    cdtype = blas.compute_dtype(A.dtype)
    Ac = A.astype(cdtype)
    prec = blas.matmul_precision()
    Q = jnp.zeros((M, N), cdtype)
    R = jnp.zeros((N, N), cdtype)
    for j0 in range(0, N, v):
        j1 = min(j0 + v, N)
        Qp, Rp = tall_qr(Ac[:, j0:j1], chunk=chunk, passes=passes)
        Qp, Rp = Qp.astype(cdtype), Rp.astype(cdtype)
        R = lax.dynamic_update_slice(R, Rp, (j0, j0))
        if j1 < N:
            C = jnp.matmul(Qp.conj().T, Ac[:, j1:], precision=prec)
            R = lax.dynamic_update_slice(R, C, (j0, j1))
            Ac = lax.dynamic_update_slice(
                Ac, Ac[:, j1:] - jnp.matmul(Qp, C, precision=prec), (0, j1))
        Q = lax.dynamic_update_slice(Q, Qp, (0, j0))
    return Q, R


def qr_factor_blocked(A: jax.Array, v: int = 256, chunk: int | None = None,
                      passes: int = 2):
    """Blocked (Q, R) of an (M, N) matrix, M >= N.

    Block Gram-Schmidt over v-wide panels: each panel is factored by
    `tall_qr` (two-pass tree, so panel Qs are orthogonal to eps), then
    the trailing columns get the rank-v update `A -= Qp (Qp^T A)` — one
    (M, v) x (v, N-j) GEMM pair per superstep, the same flop layout as
    the LU trailing update. Returns thin Q (M, N) and R (N, N) with
    diag(R) >= 0.
    """
    M, N = A.shape
    if M < N:
        raise ValueError(f"qr_factor_blocked needs M >= N, got {A.shape}")
    if chunk is None:
        chunk = blas.batched_call_rows(min(v, N),
                                       blas.compute_dtype(A.dtype))
    Q, R = _qr_blocked(A, min(v, N), chunk, passes)
    return Q.astype(A.dtype), jnp.triu(R).astype(A.dtype)
