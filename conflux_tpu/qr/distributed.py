"""Distributed tall-skinny QR over the mesh x axis (TSQR / CholeskyQR2).

The communication-optimal QR member of the family: rows are block-
distributed over AXIS_X (no pivoting, so no cyclic interleave is needed),
and only (n, n) R factors ever cross the interconnect — the same
"reduce small blocks, keep the tall data local" pattern as the
reference's tournament panel reduction (`conflux_opt.hpp:220-336`),
with QR as the combiner instead of pivoted LU.

Two elections are offered:

 - `tsqr_distributed`: local chunked QR tree -> `all_gather` of the
   (n, n) local Rs over 'x' -> replicated tree reduction (every device
   computes the same global R, so no broadcast is needed — the same
   replicated-election trick the LU loop uses); Q by TRSM + a second
   pass. Robust at any conditioning.
 - `cholesky_qr2_distributed`: G = psum(A_loc^T A_loc) over 'x',
   R = chol(G)^T, Q = A R^{-1}, twice. One (n, n) psum per pass and
   pure GEMM/TRSM otherwise — the fastest MXU form, valid while
   cond(A)^2 stays below 1/eps of the compute dtype (the classical
   CholeskyQR2 regime); the Gram matrix is accumulated in f32-or-wider
   regardless of storage dtype.

Both return (Q_shards, R) with R replicated and diag(R) >= 0; results
are bitwise-identical across Px by construction of the replicated
reduction.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from jax import lax

from conflux_tpu.geometry import LUGeometry, ragged_segments
from conflux_tpu.ops import blas
from conflux_tpu.parallel.mesh import (
    AXIS_X,
    AXIS_Y,
    AXIS_Z,
    butterfly_allreduce,
    lookup_mesh,
    make_mesh,
    mesh_cache_key,
    pvary,
    shard_map,
    replicate,
)
from conflux_tpu.qr.single import _positive_diag, _tree_r


def _two_pass_tsqr(A, Px: int, chunk: int, passes: int, prec,
                   tree: str = "gather"):
    """Replicated TSQR election: local chunked tree, then a cross-x
    reduction of the (n, n) R factors; Q by TRSM, refined over `passes`
    sweeps; positive-diagonal normalized. Shared by the tall-skinny
    entry points and the block-cyclic loop's panel step.

    tree='gather' (default): one all_gather + replicated tree — a single
    optimized collective. tree='butterfly': the canonical TSQR hypercube
    — log2(Px) `ppermute` rounds each QR-reducing a pair-ordered
    (2n, n) stack, only n rows per round; pair ordering by the lower
    x-coordinate keeps every device's reduction bit-identical, so the
    result is replicated without a broadcast. Any Px: non-power-of-two
    axes fold their overflow ranks in/out with two extra ppermute
    rounds (see `butterfly_allreduce`)."""
    n = A.shape[1]
    R = None
    for _ in range(max(1, passes)):
        Ri = _tree_r(A, chunk)
        if tree == "butterfly":
            # ZERO-FILL CONTRACT (butterfly_allreduce): on odd-Px folds
            # the off-subcube lanes reduce ppermute's zero fill; the
            # reducer must stay total on an all-zero stack. _tree_r of
            # zeros is R=0 (geqrf of 0: finite, no NaN/Inf), and the
            # garbage is discarded by the coordinate selects — never
            # branch on the received values here (tests/test_ops.py
            # pins this with the real reducer at odd Px).
            (Ri,) = butterfly_allreduce(
                (Ri,), Px, AXIS_X,
                lambda top, bot: (_tree_r(
                    jnp.concatenate([top[0], bot[0]], axis=0), chunk),))
        elif Px > 1:
            allr = lax.all_gather(Ri, AXIS_X).reshape(Px * n, n)
            Ri = _tree_r(allr, chunk)
        A = blas.trsm_right_upper(Ri, A)
        R = Ri if R is None else jnp.matmul(Ri, R, precision=prec)
    return _positive_diag(A, R)


@functools.lru_cache(maxsize=32)
def _build(mesh_key, algo: str, shape, dtype_name: str, chunk: int,
           passes: int, tree: str = "gather"):
    mesh = lookup_mesh(mesh_key)
    Px = mesh.shape[AXIS_X]
    Ml, n = shape
    dtype = jnp.dtype(dtype_name)
    prec = blas.matmul_precision()

    def device_fn(blk):
        A = blk[0].astype(blas.compute_dtype(dtype))
        if algo == "tsqr":
            Q, R = _two_pass_tsqr(A, Px, chunk, passes, prec, tree=tree)
        else:  # cholesky: Gram psum + potrf election per pass
            R = None
            for _ in range(max(1, passes)):
                G = jax.lax.psum(
                    jnp.matmul(A.conj().T, A, precision=prec), AXIS_X)
                # G = L L^H (Hermitian), so the upper factor is L^H
                Ri = blas.potrf(G).conj().T
                A = blas.trsm_right_upper(Ri, A)
                R = Ri if R is None else jnp.matmul(Ri, R, precision=prec)
            Q, R = _positive_diag(A, R)
        # R is identical on every device already (replicated reduction /
        # psum'd Gram); re-establish replication for the out_spec, same
        # as the LU loop's perm output (complex-safe helper)
        R = replicate(R, tuple(mesh.axis_names))
        return Q.astype(dtype)[None], R.astype(dtype)

    fn = shard_map(device_fn, mesh=mesh,
                       in_specs=P(AXIS_X, None, None),
                       out_specs=(P(AXIS_X, None, None), P()))
    return jax.jit(fn)


def _factor(shards, mesh, algo: str, chunk: int | None, passes: int,
            tree: str = "gather"):
    shards = jnp.asarray(shards)
    if shards.ndim != 3:
        raise ValueError(
            f"expected (Px, Ml, n) row-block shards, got {shards.shape}")
    Px, Ml, n = shards.shape
    if Px != mesh.shape[AXIS_X]:
        raise ValueError(
            f"shards leading dim {Px} != mesh x extent {mesh.shape[AXIS_X]}")
    if Px * Ml < n:
        raise ValueError(f"need M = {Px * Ml} >= n = {n}")
    if chunk is None:
        chunk = blas.batched_call_rows(
            n, blas.compute_dtype(shards.dtype))
    if tree not in ("gather", "butterfly"):
        raise ValueError(f"unknown tree {tree!r} (gather|butterfly)")
    fn = _build(mesh_cache_key(mesh), algo, (Ml, n), shards.dtype.name,
                chunk, passes, tree)
    return fn(shards)


def tsqr_distributed(shards, mesh, chunk: int | None = None,
                     passes: int = 2, tree: str = "gather"):
    """(Q_shards, R) of an x-sharded (Px, Ml, n) tall matrix via the QR
    reduction tree. Every QR call is height-bounded by
    max(chunk, 2n, Px*n-tree levels); robust at any conditioning.
    tree='butterfly' selects the log-depth ppermute hypercube reduction
    (any Px — odd axes fold their overflow ranks with two extra rounds;
    see `_two_pass_tsqr`)."""
    return _factor(shards, mesh, "tsqr", chunk, passes, tree)


def cholesky_qr2_distributed(shards, mesh, passes: int = 2):
    """(Q_shards, R) via Gram-matrix CholeskyQR with `passes` refinement
    sweeps — one (n, n) psum per pass, everything else GEMM/TRSM.
    Requires cond(A)^2 * eps < 1 (use `tsqr_distributed` otherwise)."""
    return _factor(shards, mesh, "cholesky", None, passes)


def qr_distributed_host(A: np.ndarray, Px: int, mesh=None,
                        algo: str = "tsqr", chunk: int | None = None,
                        passes: int = 2):
    """Host convenience: block-row scatter, factor on the mesh, return
    (Q (M, n), R (n, n)). M is zero-padded up to a multiple of Px (zero
    rows leave R unchanged; the pad rows of Q are dropped)."""
    from conflux_tpu.geometry import Grid3

    M, n = A.shape
    if M < n:
        # the padded row count could pass _factor's check while the true
        # matrix is rank-deficient-by-shape -> silently non-orthogonal Q
        raise ValueError(f"need M >= n, got {A.shape}")
    Ml = -(-M // Px)
    if mesh is None:
        mesh = make_mesh(Grid3(Px, 1, 1))
    Ap = np.zeros((Px * Ml, n), A.dtype)
    Ap[:M] = A
    shards = Ap.reshape(Px, Ml, n)
    if algo == "tsqr":
        Qs, R = tsqr_distributed(shards, mesh, chunk=chunk, passes=passes)
    elif algo == "cholesky":
        Qs, R = cholesky_qr2_distributed(shards, mesh, passes=passes)
    else:
        raise ValueError(f"unknown algo {algo!r} (tsqr|cholesky)")
    Q = np.asarray(Qs).reshape(Px * Ml, n)[:M]
    return Q, np.asarray(R)


# --------------------------------------------------------------------------- #
# General (block-cyclic) distributed QR — the CAQR role
# --------------------------------------------------------------------------- #


@functools.lru_cache(maxsize=32)
def _build_full(geom, mesh_key, precision, backend: str, chunk: int,
                donate: bool = False, resumable: bool = False,
                csegs: int = 8, lookahead: bool = False):
    """Blocked distributed QR over the full (x, y, z) mesh.

    The general-matrix companion of `tsqr_distributed`, in the same design
    language as the LU/Cholesky superstep loops (one jitted shard_map +
    fori_loop, block-cyclic shards, z-partial-sum invariant):

     - column panel k: psum over ('y','z') -> replicated (Ml, v) panel;
     - BCGS2 re-projection: one more sweep of P -= Q_done (Q_done^T P)
       against the already-computed Q columns (the right-looking trailing
       update below is the first sweep), which is what keeps global
       orthogonality at eps without a second full factorization pass;
       the correction W rides into R's rows;
     - panel factorization: the two-pass TSQR election of
       `tsqr_distributed` (local chunked tree + all_gather of (v, v) Rs
       over 'x' + replicated tree reduction — no pivoting, so unlike LU
       no ids travel with the candidates);
     - trailing update: C = psum_{x,z}(Qp^T A) then A -= Qp C, with Qp
       split into nlayr = v/Pz z-slabs so the layers share the GEMM flops
       exactly like the LU/Cholesky 2.5D scheme; columns retire left to
       right (rows never retire — Q is full height), so only column
       segmentation is needed;
     - R is block-cyclic over its own (N, N) geometry — nothing
       replicated at scale: the panel's (v, v) R block lands on its
       (x, y) owner, C lands in R's row-tile k, and W is redistributed
       from column-owners to R's row-owners by a masked gather + psum
       over 'y' (the transpose-exchange idiom of the Cholesky loop's
       L10^T scatter).

    Q comes back thin (M, N) in A's layout; A = Q R with diag(R) >= 0.
    Rank-deficient panels leave their block's columns/rows unspecified
    (same contract as the LU loop's degenerate supersteps).

    lookahead=True selects the software-pipelined loop (the LU/Cholesky
    body_la pattern, P8): step k+1's panel reduce, BCGS2 re-projection
    and TSQR election are computed at the END of step k from (a) the
    pre-update matrix with ONLY the Q-column write applied — value-
    identical at every done column to the post-step matrix, but with no
    dataflow edge from the trailing segment GEMMs — and (b) a panel-slab
    GEMM mirroring the segment update's z-slab operands at width v.
    Value-equivalent to the plain loop; bitwise-verified on the CPU
    backend only — the slab GEMM is a width-v slice of work the plain
    loop computes at segment width, and TPU kernel accumulation order is
    shape-dependent (same caveat as the LU block update), so the TPU
    result may differ in final bits. XLA's scheduler can overlap the
    election collectives (panel psum, W/D psums, TSQR all_gather) with
    the trailing update on a mesh. Cost: one redundant (Ml, v)-slab GEMM
    per superstep.
    """
    mesh = lookup_mesh(mesh_key)
    v = geom.v
    Px, Py, Pz = geom.grid.Px, geom.grid.Py, geom.grid.Pz
    Ml, Nl = geom.Ml, geom.Nl
    if geom.M < geom.N:
        raise ValueError(f"distributed QR needs M >= N, got {geom.M}x{geom.N}")
    nlayr = -(-v // Pz)
    v_pad = Pz * nlayr
    n_steps = geom.Nt
    # R's own block-cyclic geometry over (N, N): local row count per
    # x-rank, padded so every x-rank holds whole tiles (r_geometry pads
    # the global row count the same way; pad tiles are never written)
    Nlr = (geom.Nt // Px + (1 if geom.Nt % Px else 0)) * v
    # trailing-update column segmentation (`csegs` segments): the QR
    # loop's analogue of the LU/Cholesky segs knob (rows are never
    # segmented here — every local row participates in every panel)
    col_segs = ragged_segments(geom.Ntl, v, csegs)

    def _vary(val):
        # mark a literal as varying over every mesh axis so lax.cond
        # branch output types match the mask-dependent compute branches
        # (identity on old jax, where check_rep handles this — see pvary)
        return pvary(val, (AXIS_X, AXIS_Y, AXIS_Z))

    def device_fn(blk, rblk=None, k0=0, k_end=n_steps):
        x = lax.axis_index(AXIS_X)
        y = lax.axis_index(AXIS_Y)
        z = lax.axis_index(AXIS_Z)
        dtype = blk.dtype
        cdtype = blas.compute_dtype(dtype)
        prec = precision

        # z-partial invariant (same line as the LU loop): data enters on
        # z == 0; a resumed z-replicated state round-trips through it
        Aloc = jnp.where(z == 0, blk[0, 0], jnp.zeros((), dtype))
        if rblk is None:
            # R starts as a literal zero block: mark it varying over the
            # mesh axes so the fori_loop carry type matches the body
            Rloc = _vary(jnp.zeros((Nlr, Nl), dtype))
        else:
            # R is only ever written on layer 0; restore that invariant
            Rloc = jnp.where(z == 0, rblk[0, 0], jnp.zeros((), dtype))

        lc = jnp.arange(Nl, dtype=jnp.int32)
        ctile = (lc // v) * Py + y  # global col-tile id per local col
        # R-local rows -> global R row ids (for the W transpose-exchange)
        lrr = jnp.arange(Nlr, dtype=jnp.int32)
        grow_r = ((lrr // v) * Px + x) * v + (lrr % v)
        # source local column (on the y owner) holding R row g
        wsrc_y = (grow_r // v) % Py
        wsrc_col = ((grow_r // v) // Py) * v + grow_r % v

        def tsqr_panel(P_):
            """Two-pass replicated TSQR election on the (Ml, v) panel."""
            return _two_pass_tsqr(P_, Px, chunk, 2, prec)

        def panel_reduce(Asrc, k):
            """Column panel k: one psum over ('y','z')."""
            i0 = jnp.zeros((), jnp.int32)
            lj = jnp.asarray((k // Py) * v, jnp.int32)  # k may be a py int
            panel_loc = lax.dynamic_slice(Asrc, (i0, lj), (Ml, v))
            return lax.psum(
                jnp.where(y == k % Py, panel_loc, jnp.zeros((), dtype)),
                (AXIS_Y, AXIS_Z)).astype(cdtype)

        def reproject(Asrc, P_, k):
            """BCGS2 re-projection of panel P_ against the finished Q
            columns of Asrc (tiles < k). Returns (W, P_reprojected);
            `Asrc` is the loop matrix in body, or the Q-write-only
            matrix A_q in the lookahead carry computation."""
            z0 = z == 0
            col_done = ctile < k

            def seg_c_done(clo):
                return (clo // v) * Py + y < k

            # W = Q_done^T P, (Nl, v), rows indexed by my local cols;
            # Q columns live on layer 0 only
            wparts = []
            for clo, chi in col_segs:
                dm = col_done[clo:chi]
                wparts.append(lax.cond(
                    seg_c_done(clo),
                    lambda a, m: jnp.matmul(
                        jnp.where(m[:, None],
                                  a.conj().T.astype(cdtype), 0.0),
                        P_, precision=prec),
                    # pvary matches the compute branch's varying
                    # axes (a: x/z, m: y) for the cond output type
                    lambda a, m: _vary(jnp.zeros((a.shape[1], v),
                                                 cdtype)),
                    jnp.where(z0, lax.slice(
                        Asrc, (0, clo), (Ml, chi)), jnp.zeros((), dtype)),
                    dm,
                ))
            W = lax.psum(
                jnp.concatenate(wparts, axis=0) if len(wparts) > 1
                else wparts[0],
                (AXIS_X, AXIS_Z))  # (Nl, v) replicated over x, z
            # P -= Q_done W: per-segment local partials (NO
            # collective inside the cond — divergent predicates across
            # y would deadlock a psum), one unconditional psum over 'y'
            # (columns are y-partitioned; rows stay local to x) + 'z'
            # (Q lives on layer 0) at the end
            Dacc = _vary(jnp.zeros((Ml, v), cdtype))
            for clo, chi in col_segs:
                dm = col_done[clo:chi]

                def proj(acc, clo=clo, chi=chi, dm=dm):
                    Qseg = jnp.where(
                        dm[:, None].T & z0,
                        lax.slice(Asrc, (0, clo), (Ml, chi)).astype(cdtype),
                        0.0)
                    return acc + jnp.matmul(Qseg, W[clo:chi],
                                            precision=prec)

                Dacc = lax.cond(seg_c_done(clo), proj,
                                lambda acc: acc, Dacc)
            return W, P_ - lax.psum(Dacc, (AXIS_Y, AXIS_Z))

        def elect(Asrc, k):
            """panel reduce + BCGS2 re-projection + TSQR election: the
            whole per-step panel pipeline (everything the lookahead
            carries ahead)."""
            with jax.named_scope("qr_panel_reduce"):
                P_ = panel_reduce(Asrc, k)
            with jax.named_scope("qr_reproject"):
                W, P_ = reproject(Asrc, P_, k)
            with jax.named_scope("qr_panel_tsqr"):
                Qp, Rp = tsqr_panel(P_)
            return W, Qp, Rp

        def body_core(k, Aloc, Rloc, W, Qp, Rp):
            i0 = jnp.zeros((), jnp.int32)
            z0 = z == 0
            yo = k % Py
            xo = k % Px
            lj = ((k // Py) * v).astype(jnp.int32)
            lir = ((k // Px) * v).astype(jnp.int32)  # R-local row slab
            col_live = ctile > k

            def seg_c_live(chi):
                return ((chi - 1) // v) * Py + y > k

            # ---- trailing projection C = Qp^T A (first GS sweep) ------- #
            with jax.named_scope("qr_trailing_c"):
                cparts = []
                for clo, chi in col_segs:
                    lm = col_live[clo:chi]
                    cparts.append(lax.cond(
                        seg_c_live(chi),
                        lambda a, m: jnp.matmul(
                            Qp.conj().T,
                            jnp.where(m[None, :], a.astype(cdtype), 0.0),
                            precision=prec),
                        lambda a, m: _vary(jnp.zeros((v, a.shape[1]),
                                                           cdtype)),
                        lax.slice(Aloc, (0, clo), (Ml, chi)), lm,
                    ))
                C = lax.psum(
                    jnp.concatenate(cparts, axis=1) if len(cparts) > 1
                    else cparts[0],
                    (AXIS_X, AXIS_Z))  # (v, Nl)

            # ---- trailing update A -= Qp C on this layer's z-slab ------ #
            Qpp = jnp.pad(Qp.astype(dtype), ((0, 0), (0, v_pad - v)))
            Cp = jnp.pad(C.astype(dtype), ((0, v_pad - v), (0, 0)))
            zoff = (z * nlayr).astype(jnp.int32)
            Qps = lax.dynamic_slice(Qpp, (i0, zoff), (Ml, nlayr))
            Cs = lax.dynamic_slice(Cp, (zoff, i0), (nlayr, Nl))
            with jax.named_scope("qr_trailing_update"):
                Anew = Aloc
                for clo, chi in col_segs:
                    lm = col_live[clo:chi]

                    def seg_update(A, clo=clo, chi=chi, lm=lm):
                        a_seg = lax.slice(A, (0, clo), (Ml, chi))
                        upd = blas.gemm(Qps, Cs[:, clo:chi],
                                        precision=prec, backend=backend)
                        new = a_seg - jnp.where(lm[None, :], upd,
                                                jnp.zeros((), dtype))
                        return lax.dynamic_update_slice(A, new, (0, clo))

                    Anew = lax.cond(seg_c_live(chi), seg_update,
                                    lambda A: A, Anew)

            # ---- Q panel write (z0, column owner) ---------------------- #
            with jax.named_scope("qr_writes"):
                qcol = jnp.where(z0, Qp.astype(dtype), jnp.zeros((), dtype))
                Anew = jnp.where(
                    y == yo, lax.dynamic_update_slice(Anew, qcol, (i0, lj)),
                    Anew)

                # R writes: C into row-tile k (live cols), Rp into the
                # diagonal block, W into column-panel k (done rows)
                rrow_cur = lax.dynamic_slice(Rloc, (lir, i0), (v, Nl))
                rrow_new = jnp.where(
                    col_live[None, :] & z0, C.astype(dtype), rrow_cur)
                rrow_new = jnp.where(
                    (y == yo) & z0,
                    lax.dynamic_update_slice(rrow_new, Rp.astype(dtype),
                                             (i0, lj)),
                    rrow_new)
                Rnew = jnp.where(
                    x == xo, lax.dynamic_update_slice(Rloc, rrow_new,
                                                      (lir, i0)),
                    Rloc)
                # W transpose-exchange: my R rows' corrections live on the
                # y-rank owning that global column; gather + psum over 'y'
                Wr = lax.psum(
                    jnp.where((wsrc_y == y)[:, None]
                              & (grow_r < k * v)[:, None],
                              jnp.take(W, jnp.minimum(wsrc_col, Nl - 1),
                                       axis=0, mode="clip"),
                              jnp.zeros((), cdtype)),
                    AXIS_Y)  # (Nlr, v) complete on every y
                wcol = lax.dynamic_slice(Rnew, (i0, lj), (Nlr, v))
                wcol = wcol + jnp.where(
                    (y == yo) & z0, Wr.astype(dtype), jnp.zeros((), dtype))
                Rnew = lax.dynamic_update_slice(Rnew, wcol, (i0, lj))
            art = dict(Qps=Qps, Cs=Cs, qcol=qcol, lj=lj, yo=yo)
            return Anew, Rnew, art

        def body(k, carry):
            Aloc, Rloc = carry
            W, Qp, Rp = elect(Aloc, k)
            Anew, Rnew, _ = body_core(k, Aloc, Rloc, W, Qp, Rp)
            return Anew, Rnew

        def body_la(k, carry):
            # software-pipelined body: this step's election arrives in
            # the carry; the next step's election is computed from
            # sources with no dataflow edge to the trailing GEMMs so
            # XLA can overlap its collectives with them on a mesh.
            Aloc, Rloc, W, Qp, Rp = carry
            Anew, Rnew, art = body_core(k, Aloc, Rloc, W, Qp, Rp)
            kn = k + 1
            i0 = jnp.zeros((), jnp.int32)

            def compute_next(_):
                # A_q: pre-update matrix + ONLY the Q-column write —
                # value-identical to Anew at every done column (the
                # trailing update touches live columns only; tile k's
                # column is neither: it is overwritten by qcol), but
                # dataflow-independent of the segment GEMMs
                A_q = jnp.where(
                    y == art["yo"],
                    lax.dynamic_update_slice(Aloc, art["qcol"],
                                             (i0, art["lj"])),
                    Aloc)
                # panel slab of tile kn, updated by a GEMM over the same
                # z-slab operands (Qps/Cs) as the segment update — value-
                # equivalent; bitwise only where kernel accumulation is
                # shape-independent (CPU yes; TPU unverified, the slab is
                # width v vs the segment's chi-clo)
                with jax.named_scope("qr_panel_reduce"):
                    lj1 = ((kn // Py) * v).astype(jnp.int32)
                    slab = lax.dynamic_slice(Aloc, (i0, lj1), (Ml, v))
                    upd = blas.gemm(
                        art["Qps"],
                        lax.dynamic_slice(art["Cs"], (i0, lj1),
                                          (nlayr, v)),
                        precision=prec, backend=backend)
                    slab = slab - upd  # tile kn is fully live at step k
                    P_n = lax.psum(
                        jnp.where(y == kn % Py, slab,
                                  jnp.zeros((), dtype)),
                        (AXIS_Y, AXIS_Z)).astype(cdtype)
                with jax.named_scope("qr_reproject"):
                    W_n, P_n = reproject(A_q, P_n, kn)
                with jax.named_scope("qr_panel_tsqr"):
                    Qp_n, Rp_n = tsqr_panel(P_n)
                return W_n, Qp_n, Rp_n

            # the last iteration has no next panel: skip the dangling
            # election (a whole superstep's collectives + TSQR)
            W_n, Qp_n, Rp_n = lax.cond(
                kn < k_end, compute_next, lambda _: (W, Qp, Rp), 0)
            return Anew, Rnew, W_n, Qp_n, Rp_n

        if lookahead:
            W0, Qp0, Rp0 = elect(Aloc, k0)
            Aloc, Rloc, _, _, _ = lax.fori_loop(
                k0, k_end, body_la, (Aloc, Rloc, W0, Qp0, Rp0))
        else:
            Aloc, Rloc = lax.fori_loop(k0, k_end, body, (Aloc, Rloc))
        Qout = lax.psum(Aloc, AXIS_Z)
        Rout = lax.psum(Rloc, AXIS_Z)
        return Qout[None, None], Rout[None, None]

    shard_spec = P(AXIS_X, AXIS_Y, None, None)
    if resumable:
        in_specs = (shard_spec, shard_spec, P(), P())
    else:
        in_specs = shard_spec
    fn = shard_map(device_fn, mesh=mesh, in_specs=in_specs,
                       out_specs=(shard_spec, shard_spec))
    # resumable mode donates the O(N^2) R state too — unlike LU's O(M)
    # orig map, holding input and output R simultaneously is matrix-sized
    donate_args = ((0, 1) if resumable else (0,)) if donate else ()
    return jax.jit(fn, donate_argnums=donate_args)


def build_program(geom, mesh, precision=None, backend: str | None = None,
                  chunk: int | None = None, donate: bool = False,
                  resumable: bool = False, csegs: int = 8,
                  lookahead: bool = False, dtype=None):
    """The jitted block-cyclic QR program itself (cached per config) —
    the single point resolving trace-time defaults, mirroring
    `lu.distributed.build_program`. Direct use is for callers needing
    the compile artifacts (the miniapp's --profile phase table); such
    callers should pass the input `dtype` they will run with so the
    chunk default resolves with its compute dtype (f64 halves the safe
    TSQR call height) and the built program matches the one the entry
    points cache and time."""
    precision = blas.matmul_precision() if precision is None else precision
    backend = blas.get_backend() if backend is None else backend
    if chunk is None:
        cdtype = blas.compute_dtype(jnp.dtype(dtype)) if dtype is not None \
            else jnp.float32
        chunk = blas.batched_call_rows(geom.v, cdtype)
    if donate and next(iter(mesh.devices.flat)).platform == "cpu":
        donate = False
    if csegs < 1:
        raise ValueError(
            f"csegs must be a positive segment count, got {csegs} "
            "(non-positive counts would silently skip trailing updates)")
    return _build_full(geom, mesh_cache_key(mesh), precision, backend,
                       chunk, donate, resumable, csegs, lookahead)


def qr_factor_distributed(shards, geom, mesh, precision=None,
                          backend: str | None = None,
                          chunk: int | None = None, donate: bool = False,
                          csegs: int = 8, lookahead: bool = False):
    """Blocked QR of block-cyclic (Px, Py, Ml, Nl) shards on the mesh.

    Returns (Q_shards, R_shards): Q thin (M, N) in A's layout, R upper-
    triangular (N, N) block-cyclic over its own geometry (gather it with
    `r_geometry(geom)`). See `_build_full` for the algorithm;
    `lookahead=True` software-pipelines the loop (P8 — next panel's
    election overlaps the trailing update on a mesh; value-equivalent
    results, bitwise-verified on the CPU backend only — see
    `_build_full`'s shape-dependent-accumulation caveat)."""
    from conflux_tpu.geometry import check_shards

    shards = jnp.asarray(shards)
    check_shards(shards, geom)
    # default chunk resolves inside build_program from the compute dtype
    fn = build_program(geom, mesh, precision=precision, backend=backend,
                       chunk=chunk, donate=donate, csegs=csegs,
                       lookahead=lookahead, dtype=shards.dtype)
    return fn(shards)


def qr_factor_steps(shards, geom, mesh, k0: int, k1: int, R=None,
                    precision=None, backend: str | None = None,
                    chunk: int | None = None, donate: bool = False):
    """Factor column panels [k0, k1) only — checkpoint/restart for the QR
    loop (the `lu_factor_steps`/`cholesky_factor_steps` counterpart).

    State = (shards, R): after k panels, columns with tile id < k hold
    finished Q columns, the rest the projected trailing matrix, and R
    holds its first k tile-rows — all plain saveable arrays. Pass R=None
    only when k0 == 0; feed each call's outputs to the next. The step
    bounds are traced scalars: one compiled program serves every segment.
    Same 2.5D caveat as the LU form: the checkpoint consolidates
    z-partial sums, so Pz > 1 resumes are numerically equivalent rather
    than bit-identical; Pz == 1 round-trips exactly."""
    if not (0 <= k0 < k1 <= geom.Nt):
        raise ValueError(f"step range [{k0}, {k1}) outside [0, {geom.Nt})")
    # the default chunk resolves inside build_program with the same
    # compute dtype as qr_factor_distributed's: a resumed run must chunk
    # its panel TSQR like the run it resumes
    if R is None:
        if k0 != 0:
            raise ValueError("resuming at k0 > 0 requires the R state "
                             "returned by the previous qr_factor_steps call")
        # r_geometry's local row count IS the kernel's padded Nlr — one
        # source of truth for the padding rule
        R = jnp.zeros(
            (geom.grid.Px, geom.grid.Py, r_geometry(geom).Ml, geom.Nl),
            jnp.asarray(shards).dtype)
    fn = build_program(geom, mesh, precision=precision, backend=backend,
                       chunk=chunk, donate=donate, resumable=True,
                       dtype=jnp.asarray(shards).dtype)
    return fn(jnp.asarray(shards), jnp.asarray(R), jnp.int32(k0),
              jnp.int32(k1))


def r_geometry(geom):
    """The (N, N) block-cyclic geometry R comes back in."""
    return LUGeometry.create(geom.N, geom.N, geom.v, geom.grid)


def qr_blocked_distributed_host(A: np.ndarray, grid, v: int, mesh=None,
                                precision=None, backend: str | None = None,
                                chunk: int | None = None):
    """Host convenience: scatter, factor, gather. Returns (Q (M, N),
    R (N, N), geom) for the ORIGINAL shape.

    Non-grid-multiple sizes are handled by block-diagonal identity
    extension: QR(blockdiag(A, I)) == blockdiag(Q, I) blockdiag(R, I)
    exactly, so the padded problem's leading (M, N) / (N, N) blocks ARE
    the answer (zero-column padding would instead feed singular panels
    into the TRSM recovery and NaN the trailing matrix). The identity
    lives in padded rows x padded columns, so rows are padded at least
    as far as columns."""
    M, N = A.shape
    if M < N:
        raise ValueError(f"distributed QR needs M >= N, got {A.shape}")
    geom = LUGeometry.create(M, N, v, grid)
    col_pad = geom.N - N
    if geom.M - M < col_pad:
        # need one identity row per pad column: grow the row padding
        geom = LUGeometry.create(M + col_pad, N, v, grid)
    if (geom.M, geom.N) != A.shape:
        Ap = np.zeros((geom.M, geom.N), A.dtype)
        Ap[:M, :N] = A
        Ap[np.arange(M, M + col_pad), np.arange(N, geom.N)] = 1
        A = Ap
    if mesh is None:
        mesh = make_mesh(geom.grid)
    Qs, Rs = qr_factor_distributed(
        jnp.asarray(geom.scatter(A)), geom, mesh, precision=precision,
        backend=backend, chunk=chunk)
    Q = geom.gather(np.asarray(Qs))[:M, :N]
    # r_geometry pads R's rows to a tile multiple of Px; the pad tiles
    # are never written, so slicing restores the (N, N) contract
    R = r_geometry(geom).gather(np.asarray(Rs))[:N, :N]
    return Q, np.triu(R), geom
