"""Distributed tall-skinny QR over the mesh x axis (TSQR / CholeskyQR2).

The communication-optimal QR member of the family: rows are block-
distributed over AXIS_X (no pivoting, so no cyclic interleave is needed),
and only (n, n) R factors ever cross the interconnect — the same
"reduce small blocks, keep the tall data local" pattern as the
reference's tournament panel reduction (`conflux_opt.hpp:220-336`),
with QR as the combiner instead of pivoted LU.

Two elections are offered:

 - `tsqr_distributed`: local chunked QR tree -> `all_gather` of the
   (n, n) local Rs over 'x' -> replicated tree reduction (every device
   computes the same global R, so no broadcast is needed — the same
   replicated-election trick the LU loop uses); Q by TRSM + a second
   pass. Robust at any conditioning.
 - `cholesky_qr2_distributed`: G = psum(A_loc^T A_loc) over 'x',
   R = chol(G)^T, Q = A R^{-1}, twice. One (n, n) psum per pass and
   pure GEMM/TRSM otherwise — the fastest MXU form, valid while
   cond(A)^2 stays below 1/eps of the compute dtype (the classical
   CholeskyQR2 regime); the Gram matrix is accumulated in f32-or-wider
   regardless of storage dtype.

Both return (Q_shards, R) with R replicated and diag(R) >= 0; results
are bitwise-identical across Px by construction of the replicated
reduction.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from conflux_tpu.ops import blas
from conflux_tpu.parallel.mesh import (
    AXIS_X,
    lookup_mesh,
    make_mesh,
    mesh_cache_key,
)
from conflux_tpu.qr.single import _positive_diag, _tree_r


@functools.lru_cache(maxsize=32)
def _build(mesh_key, algo: str, shape, dtype_name: str, chunk: int,
           passes: int):
    mesh = lookup_mesh(mesh_key)
    Px = mesh.shape[AXIS_X]
    Ml, n = shape
    dtype = jnp.dtype(dtype_name)
    prec = blas.matmul_precision()

    def device_fn(blk):
        A = blk[0].astype(blas.compute_dtype(dtype))
        R = None
        for _ in range(max(1, passes)):
            if algo == "tsqr":
                r_loc = _tree_r(A, chunk)
                allr = jax.lax.all_gather(r_loc, AXIS_X)  # (Px, n, n)
                # replicated reduction: every device factors the same
                # stack, so R needs no broadcast
                Ri = _tree_r(allr.reshape(Px * n, n), chunk)
            else:  # cholesky
                G = jax.lax.psum(
                    jnp.matmul(A.T, A, precision=prec), AXIS_X)
                Ri = blas.potrf(G).T
            A = blas.trsm_right_upper(Ri, A)
            R = Ri if R is None else jnp.matmul(Ri, R, precision=prec)
        Q, R = _positive_diag(A, R)
        # R is identical on every device already (replicated reduction /
        # psum'd Gram); pmax re-establishes replication for the out_spec,
        # same as the LU loop's perm output
        R = jax.lax.pmax(R, tuple(mesh.axis_names))
        return Q.astype(dtype)[None], R.astype(dtype)

    fn = jax.shard_map(device_fn, mesh=mesh,
                       in_specs=P(AXIS_X, None, None),
                       out_specs=(P(AXIS_X, None, None), P()))
    return jax.jit(fn)


def _factor(shards, mesh, algo: str, chunk: int | None, passes: int):
    shards = jnp.asarray(shards)
    if shards.ndim != 3:
        raise ValueError(
            f"expected (Px, Ml, n) row-block shards, got {shards.shape}")
    Px, Ml, n = shards.shape
    if Px != mesh.shape[AXIS_X]:
        raise ValueError(
            f"shards leading dim {Px} != mesh x extent {mesh.shape[AXIS_X]}")
    if Px * Ml < n:
        raise ValueError(f"need M = {Px * Ml} >= n = {n}")
    chunk = blas._PANEL_CHUNK if chunk is None else chunk
    fn = _build(mesh_cache_key(mesh), algo, (Ml, n), shards.dtype.name,
                chunk, passes)
    return fn(shards)


def tsqr_distributed(shards, mesh, chunk: int | None = None,
                     passes: int = 2):
    """(Q_shards, R) of an x-sharded (Px, Ml, n) tall matrix via the QR
    reduction tree. Every QR call is height-bounded by
    max(chunk, 2n, Px*n-tree levels); robust at any conditioning."""
    return _factor(shards, mesh, "tsqr", chunk, passes)


def cholesky_qr2_distributed(shards, mesh, passes: int = 2):
    """(Q_shards, R) via Gram-matrix CholeskyQR with `passes` refinement
    sweeps — one (n, n) psum per pass, everything else GEMM/TRSM.
    Requires cond(A)^2 * eps < 1 (use `tsqr_distributed` otherwise)."""
    return _factor(shards, mesh, "cholesky", None, passes)


def qr_distributed_host(A: np.ndarray, Px: int, mesh=None,
                        algo: str = "tsqr", chunk: int | None = None,
                        passes: int = 2):
    """Host convenience: block-row scatter, factor on the mesh, return
    (Q (M, n), R (n, n)). M is zero-padded up to a multiple of Px (zero
    rows leave R unchanged; the pad rows of Q are dropped)."""
    from conflux_tpu.geometry import Grid3

    M, n = A.shape
    if M < n:
        # the padded row count could pass _factor's check while the true
        # matrix is rank-deficient-by-shape -> silently non-orthogonal Q
        raise ValueError(f"need M >= n, got {A.shape}")
    Ml = -(-M // Px)
    if mesh is None:
        mesh = make_mesh(Grid3(Px, 1, 1))
    Ap = np.zeros((Px * Ml, n), A.dtype)
    Ap[:M] = A
    shards = Ap.reshape(Px, Ml, n)
    if algo == "tsqr":
        Qs, R = tsqr_distributed(shards, mesh, chunk=chunk, passes=passes)
    elif algo == "cholesky":
        Qs, R = cholesky_qr2_distributed(shards, mesh, passes=passes)
    else:
        raise ValueError(f"unknown algo {algo!r} (tsqr|cholesky)")
    Q = np.asarray(Qs).reshape(Px * Ml, n)[:M]
    return Q, np.asarray(R)
