"""Communication-optimal QR (TSQR tree + CholeskyQR2) — see single.py
and distributed.py module docstrings."""

from conflux_tpu.qr.distributed import (
    cholesky_qr2_distributed,
    qr_blocked_distributed_host,
    qr_distributed_host,
    qr_factor_distributed,
    qr_factor_steps,
    r_geometry,
    tsqr_distributed,
)
from conflux_tpu.qr.single import qr_factor_blocked, tall_qr

__all__ = [
    "cholesky_qr2_distributed",
    "qr_blocked_distributed_host",
    "qr_distributed_host",
    "qr_factor_blocked",
    "qr_factor_distributed",
    "qr_factor_steps",
    "r_geometry",
    "tall_qr",
    "tsqr_distributed",
]
