"""Distributed Cholesky factorization — the CONFCHOX side."""

from conflux_tpu.cholesky.single import cholesky_blocked

__all__ = ["cholesky_blocked"]
