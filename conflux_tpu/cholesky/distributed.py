"""Distributed 2.5D Cholesky over the (x, y, z) mesh.

TPU-native re-design of the reference's CONFCHOX driver
(`Cholesky.cpp:743-784` phases: choleskyA00 -> updateComputeA10 ->
computeA11 -> reduceA11 -> scatterA11). Same design language as
`conflux_tpu.lu.distributed`, minus pivoting:

 - block-cyclic (x, y) tile shards holding z-partial sums; the true matrix
   is the sum over 'z'; factors are written on layer z==0 only;
 - panel column k: one `psum` over ('y','z') (reference reduceA11 +
   scatterA11 rolled into a single collective);
 - diagonal tile broadcast (reference's shrinking-bcast-comm machinery,
   `Processor.cpp:131-250`): a masked `psum` over 'x' — fixed mesh
   collectives make the ladder of communicators unnecessary (SURVEY P7);
 - L10^T redistribution row-owners -> column-owners (reference's
   MPI_SUBTILE Isend mesh, `Cholesky.cpp:459-480`): a masked-gather `psum`
   over 'x' delivering exactly the rows each device's columns need;
 - trailing update: each z layer multiplies its nlayr-wide slab of the
   panel (reference's subtile split `l = v/Pz`), sharing the syrk flops
   across layers; `MPI_Waitany`-driven overlap (reference
   `Cholesky.cpp:487-550`) is the XLA latency-hiding scheduler's job, not
   ours.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from conflux_tpu.geometry import CholeskyGeometry, Grid3, ragged_segments
from conflux_tpu.ops import blas
from conflux_tpu.parallel.mesh import (
    AXIS_X,
    AXIS_Y,
    AXIS_Z,
    lookup_mesh,
    make_mesh,
    mesh_cache_key,
    shard_map,
)


@functools.lru_cache(maxsize=32)
def _build(geom: CholeskyGeometry, mesh_key, precision, backend: str,
           donate: bool = False, resumable: bool = False,
           lookahead: bool = False, segs: tuple = (8, 8)):
    mesh = lookup_mesh(mesh_key)
    v = geom.v
    Px, Py, Pz = geom.grid.Px, geom.grid.Py, geom.grid.Pz
    Ml, Nl = geom.Ml, geom.Nl
    nlayr = geom.nlayr
    n_steps = geom.Kappa
    v_pad = Pz * nlayr

    # trailing-update segmentation (same idea as lu.distributed): both the
    # live rows (rtile > k) and live columns (ctile > k) are contiguous
    # local suffixes under the block-cyclic map, so ceil-divide each axis
    # into ragged segments and skip dead (row, col) blocks with lax.cond —
    # GEMM work stays near the true N^3/3P instead of the 3x a
    # full-local-shape masked update would spend. Segments whose tiles lie
    # ENTIRELY above the diagonal are skipped too: the factorization never
    # reads the strict upper triangle (future panels mask rows above their
    # diagonal), so updating it is pure waste — segment-level triangle
    # skipping approaches the reference's lower-triangle-only owner set
    # (`Cholesky.cpp:333-355`) as the segmentation refines; mixed segments
    # still update their (unread, unspecified) upper elements.
    row_bounds = ragged_segments(Ml // v, v, segs[0])
    col_bounds = ragged_segments(Nl // v, v, segs[1])

    def device_fn(blk, k0=0, k_end=n_steps):
        x = lax.axis_index(AXIS_X)
        y = lax.axis_index(AXIS_Y)
        z = lax.axis_index(AXIS_Z)
        dtype = blk.dtype

        Aloc = jnp.where(z == 0, blk[0, 0], jnp.zeros((), dtype))

        lr = jnp.arange(Ml, dtype=jnp.int32)
        rtile = (lr // v) * Px + x  # global row-tile id per local row
        lc = jnp.arange(Nl, dtype=jnp.int32)
        ctile = (lc // v) * Py + y  # global col-tile id per local col
        # my local columns' global row coordinates (for the L10^T exchange):
        # column with global index g corresponds to row g, owned by x-rank
        # (g // v) % Px at local row ((g // v) // Px) * v + g % v
        gcol = ctile * v + (lc % v)
        col_owner_x = (gcol // v) % Px
        col_local_row = ((gcol // v) // Px) * v + gcol % v

        cdtype = blas.compute_dtype(dtype)

        def panel_reduce(Aloc, k):
            """Panel column k: z-reduce + y-broadcast (reference
            reduceA11 + scatterA11 rolled into one collective); panel
            math runs in the compute dtype (f32 when storage is bf16)."""
            yo = k % Py
            lj = jnp.asarray((k // Py) * v, jnp.int32)  # k may be a py int
            panel_loc = lax.dynamic_slice(
                Aloc, (jnp.zeros((), jnp.int32), lj), (Ml, v))
            return lax.psum(
                jnp.where(y == yo, panel_loc, jnp.zeros((), dtype)),
                (AXIS_Y, AXIS_Z),
            ).astype(cdtype)

        def body_core(k, Aloc, panel):
            i0 = jnp.zeros((), jnp.int32)
            xo = (k % Px).astype(jnp.int32)  # diag tile row owner
            yo = (k % Py).astype(jnp.int32)  # panel column owner
            lj = ((k // Py) * v).astype(jnp.int32)
            ldiag = ((k // Px) * v).astype(jnp.int32)

            # ---- diagonal tile: x-broadcast + potrf ----------------------- #
            with jax.named_scope("choleskyA00"):
                diag_slice = lax.dynamic_slice(panel, (ldiag, i0), (v, v))
                Akk = lax.psum(
                    jnp.where(x == xo, diag_slice, jnp.zeros((), cdtype)), AXIS_X
                )
                L00 = blas.potrf(Akk)

            # ---- L10 for rows below the diagonal (row-segmented) ---------- #
            # segment liveness as scalar tile-index compares (liveness is
            # monotone in the local tile index; see lu.distributed)
            def seg_r_live(rhi):
                return ((rhi - 1) // v) * Px + x > k

            def seg_c_live(chi):
                return ((chi - 1) // v) * Py + y > k

            with jax.named_scope("updateA10"):
                below = rtile > k
                pieces = []
                for rlo, rhi in row_bounds:
                    rm = below[rlo:rhi]
                    pieces.append(lax.cond(
                        seg_r_live(rhi),
                        lambda p, m: blas.trsm_right_lower_t(
                            L00, jnp.where(m[:, None], p,
                                           jnp.zeros((), cdtype))),
                        lambda p, m: jnp.zeros_like(p),
                        panel[rlo:rhi], rm,
                    ))
                L10 = (jnp.concatenate(pieces, axis=0)
                       if len(pieces) > 1 else pieces[0])  # (Ml, v)

            # ---- L10^T redistribution to column owners over 'x' ----------- #
            # row g of the global panel -> every device whose columns include
            # g; diag-tile columns take L00 rows
            with jax.named_scope("scatterA11"):
                from_L10 = jnp.where(
                    (col_owner_x == x)[:, None], L10[col_local_row],
                    jnp.zeros((), cdtype)
                )
                Lc = lax.psum(from_L10, AXIS_X)  # (Nl, v) = L10 rows for my cols
                diag_cols = ctile == k
                L00_rows = L00[gcol % v]  # (Nl, v), valid where diag_cols
                Lc = jnp.where(diag_cols[:, None], L00_rows, Lc)

            # ---- trailing syrk-style update on this layer's slab ---------- #
            # GEMM rides the storage dtype (bf16 fast path when selected)
            L10p = jnp.pad(L10.astype(dtype), ((0, 0), (0, v_pad - v)))
            Lcp = jnp.pad(Lc.astype(dtype), ((0, 0), (0, v_pad - v)))
            zoff = (z * nlayr).astype(jnp.int32)
            L10s = lax.dynamic_slice(L10p, (i0, zoff), (Ml, nlayr))
            Lcs = lax.dynamic_slice(Lcp, (i0, zoff), (Nl, nlayr))
            col_trail = ctile > k

            # (reference computeA11 phase) — in-place cond'd DUS per live
            # segment; a slice->concat formulation materializes the full
            # local matrix every step (measured ~26 ms/step of pure copies
            # in the LU loop at N=32768 before the same change)
            with jax.named_scope("computeA11"):
                Anew = Aloc
                for rlo, rhi in row_bounds:
                    rm = below[rlo:rhi]
                    for clo, chi in col_bounds:
                        cm = col_trail[clo:chi]

                        def seg_update(A, rlo=rlo, rhi=rhi, clo=clo, chi=chi,
                                       rm=rm, cm=cm):
                            a_seg = lax.slice(A, (rlo, clo), (rhi, chi))
                            # conj().T = herk for complex dtypes (no-op
                            # conj on real, folded by XLA)
                            upd = blas.gemm(L10s[rlo:rhi],
                                            Lcs[clo:chi].conj().T,
                                            precision=precision,
                                            backend=backend)
                            keep = rm[:, None] & cm[None, :]
                            new = a_seg - jnp.where(keep, upd,
                                                    jnp.zeros((), dtype))
                            return lax.dynamic_update_slice(A, new,
                                                            (rlo, clo))

                        # touches_lower: the segment's last row tile
                        # reaches (or passes) its first column tile —
                        # false means every tile is strictly upper and
                        # the segment's content is never read again
                        touches_lower = (
                            ((rhi - 1) // v) * Px + x
                            >= (clo // v) * Py + y)
                        Anew = lax.cond(
                            seg_r_live(rhi) & seg_c_live(chi)
                            & touches_lower,
                            seg_update, lambda A: A, Anew)

            # ---- factor writes: panel column on layer z==0 ---------------- #
            on_diag = rtile == k
            L00_local = jnp.where(
                z == 0, jnp.tril(L00)[lr % v].astype(dtype), jnp.zeros((), dtype)
            )
            pcol_cur = lax.dynamic_slice(Anew, (i0, lj), (Ml, v))
            pcol_new = jnp.where(
                on_diag[:, None],
                L00_local,
                jnp.where(below[:, None],
                          jnp.where(z == 0, L10.astype(dtype), jnp.zeros((), dtype)),
                          pcol_cur),
            )
            Anew = jnp.where(
                y == yo,
                lax.dynamic_update_slice(Anew, pcol_new, (i0, lj)),
                Anew,
            )
            return Anew, dict(L10s=L10s, Lcs=Lcs, below=below)

        def body(k, carry):
            Aloc = carry
            with jax.named_scope("reduceA11"):
                panel = panel_reduce(Aloc, k)
            Anew, _ = body_core(k, Aloc, panel)
            return Anew

        def body_la(k, carry):
            # software-pipelined body (see lu.distributed.body_la): the
            # panel for step k rides the carry; step k+1's panel comes from
            # a separately-updated column slab of the PRE-update matrix, so
            # its reduce has no data dependence on the trailing GEMMs and
            # can overlap them on a mesh. Slab math mirrors the segment
            # updates operand-for-operand (bitwise-identical results).
            Aloc, panel = carry
            Anew, art = body_core(k, Aloc, panel)
            kn = k + 1
            i0 = jnp.zeros((), jnp.int32)

            def compute_next(_):
                with jax.named_scope("reduceA11"):
                    lj1 = ((kn // Py) * v).astype(jnp.int32)
                    slab = lax.dynamic_slice(Aloc, (i0, lj1), (Ml, v))
                    upd = blas.gemm(
                        art["L10s"],
                        lax.dynamic_slice(art["Lcs"], (lj1, i0),
                                          (v, nlayr)).conj().T,
                        precision=precision, backend=backend)
                    slab = slab - jnp.where(art["below"][:, None], upd,
                                            jnp.zeros((), dtype))
                    yo1 = (kn % Py).astype(jnp.int32)
                    return lax.psum(
                        jnp.where(y == yo1, slab, jnp.zeros((), dtype)),
                        (AXIS_Y, AXIS_Z)).astype(cdtype)

            panel_next = lax.cond(kn < k_end, compute_next,
                                  lambda _: panel, 0)
            return Anew, panel_next

        if lookahead:
            with jax.named_scope("reduceA11"):
                panel0 = panel_reduce(Aloc, k0)
            Aloc, _ = lax.fori_loop(k0, k_end, body_la, (Aloc, panel0))
        else:
            Aloc = lax.fori_loop(k0, k_end, body, Aloc)
        Aout = lax.psum(Aloc, AXIS_Z)
        return Aout[None, None]

    shard_spec = P(AXIS_X, AXIS_Y, None, None)
    if resumable:
        in_specs, out_specs = (shard_spec, P(), P()), shard_spec
    else:
        in_specs, out_specs = shard_spec, shard_spec
    fn = shard_map(device_fn, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs)
    return jax.jit(fn, donate_argnums=(0,) if donate else ())


def build_program(geom: CholeskyGeometry, mesh, precision=None,
                  backend: str | None = None, donate: bool = False,
                  resumable: bool = False, lookahead: bool = False,
                  segs: tuple = (8, 8)):
    """The jitted distributed-Cholesky program (cached per config) — the
    single point resolving trace-time defaults and the CPU donate guard;
    `cholesky_factor_distributed` goes through here. Direct use is for
    callers needing compile artifacts — e.g. the miniapp's `--profile`
    per-phase device table (see `lu.distributed.build_program`)."""
    precision = blas.matmul_precision() if precision is None else precision
    backend = blas.get_backend() if backend is None else backend
    if donate and next(iter(mesh.devices.flat)).platform == "cpu":
        donate = False  # CPU PJRT has no buffer donation (warns per call)
    if len(segs) != 2 or segs[0] < 1 or segs[1] < 1:
        raise ValueError(
            f"segs must be two positive segment counts, got {segs!r} "
            "(non-positive counts would silently skip trailing updates)")
    return _build(geom, mesh_cache_key(mesh), precision, backend, donate,
                  resumable, lookahead, tuple(segs))


def cholesky_factor_steps(shards, geom: CholeskyGeometry, mesh,
                          k0: int, k1: int, precision=None,
                          backend: str | None = None, donate: bool = False,
                          segs: tuple = (8, 8)):
    """Factor supersteps [k0, k1) only — checkpoint/restart for Cholesky
    (no pivot state to carry, unlike `lu.distributed.lu_factor_steps`):
    feed each call's output shards into the next; after the last call the
    lower triangle holds L as `cholesky_factor_distributed` computes it —
    bit-identically when Pz == 1; with Pz > 1 the checkpoint consolidates
    the 2.5D z-partial sums, so a resumed run is numerically equivalent
    but re-associates f32 additions (same caveat as `lu_factor_steps`).
    `segs` matches `cholesky_factor_distributed` so a resumed run keeps
    the tuned segmentation of the run it resumes (segmentation is
    math-invariant; only performance differs).
    """
    if not (0 <= k0 < k1 <= geom.Kappa):
        raise ValueError(f"step range [{k0}, {k1}) outside [0, {geom.Kappa})")
    # traced step bounds: one compiled program serves every segment
    fn = build_program(geom, mesh, precision=precision, backend=backend,
                       donate=donate, resumable=True, segs=segs)
    return fn(shards, jnp.int32(k0), jnp.int32(k1))


def cholesky_factor_distributed(shards, geom: CholeskyGeometry, mesh,
                                precision=None, backend: str | None = None,
                                donate: bool = False,
                                lookahead: bool = False,
                                segs: tuple = (8, 8)):
    """Factor block-cyclic shards of an SPD matrix; returns factored shards
    (lower triangle = L, upper triangle unspecified). `donate=True`
    aliases the input into the output — without it the superstep loop
    cannot update in place (an immutable input forces a full-buffer copy
    per step, measured ~6 ms/step at N=16384 on a v5e). `segs` = (row,
    col) trailing-update segment counts (see `lu.distributed`)."""
    from conflux_tpu.geometry import check_shards

    shards = jnp.asarray(shards)
    check_shards(shards, geom)
    fn = build_program(geom, mesh, precision=precision, backend=backend,
                       donate=donate, lookahead=lookahead, segs=segs)
    return fn(shards)


def cholesky_distributed_host(A: np.ndarray, grid: Grid3, v: int, mesh=None,
                              precision=None, backend: str | None = None,
                              segs: tuple = (8, 8)):
    """Scatter an SPD matrix, factor on the mesh, gather L back.

    Role of the reference's initialize/parallelCholesky/finalize sequence
    (`Cholesky.h:19-23`). Returns (L (N, N) lower-triangular, geom).
    """
    if A.shape[0] != A.shape[1]:
        raise ValueError(f"matrix must be square, got {A.shape}")
    geom = CholeskyGeometry.create(A.shape[0], v, grid)
    if mesh is None:
        mesh = make_mesh(grid)
    if geom.N != A.shape[0]:
        Ap = np.eye(geom.N, dtype=A.dtype)
        Ap[: A.shape[0], : A.shape[0]] = A
        A = Ap
    shards = geom.scatter(A)
    out = cholesky_factor_distributed(
        jnp.asarray(shards), geom, mesh, precision=precision, backend=backend,
        segs=segs,
    )
    L = np.tril(geom.gather(np.asarray(out)))
    return L, geom
