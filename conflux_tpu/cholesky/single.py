"""Single-device blocked right-looking Cholesky.

The reference's per-iteration phases (`Cholesky.cpp:743-784`: dpotrf ->
dtrsm -> dgemm low-rank update) collapsed onto one chip as an unrolled
jittable XLA program. Exact shapes per step — true 1/3 N^3 flops.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from conflux_tpu.ops import blas


def cholesky_blocked(A: jax.Array, v: int, precision=None, backend: str | None = None):
    """Lower Cholesky factor of SPD A (N x N, N a multiple of v).

    Returns L (N, N) lower triangular with the strict upper triangle zeroed.
    """
    N = A.shape[0]
    if A.shape[0] != A.shape[1]:
        raise ValueError(f"matrix must be square, got {A.shape}")
    if N % v:
        raise ValueError(f"N={N} not a multiple of tile size {v}")
    precision = blas.matmul_precision() if precision is None else precision
    backend = blas.get_backend() if backend is None else backend
    return _cholesky_blocked(A, v, precision, backend)


@functools.partial(jax.jit, static_argnames=("v", "precision", "backend"))
def _cholesky_blocked(A: jax.Array, v: int, precision, backend: str):
    N = A.shape[0]
    n_steps = N // v

    cdtype = blas.compute_dtype(A.dtype)
    for k in range(n_steps):
        off = k * v
        # (1) choleskyA00 (reference `Cholesky.cpp:188-194`); panel math in
        # the compute dtype (f32 when storage is bf16)
        L00 = blas.potrf(A[off : off + v, off : off + v].astype(cdtype))
        A = A.at[off : off + v, off : off + v].set(L00.astype(A.dtype))
        if off + v < N:
            # (2) A10 panel: X L00^H = A10 (reference `Cholesky.cpp:449-452`;
            # ^H == ^T for real dtypes throughout)
            L10 = blas.trsm_right_lower_t(
                L00, A[off + v :, off : off + v].astype(cdtype)
            ).astype(A.dtype)
            A = A.at[off + v :, off : off + v].set(L10)
            # (3) trailing syrk/herk update (reference `Cholesky.cpp:333-355`)
            A = A.at[off + v :, off + v :].set(
                blas.gemm(L10, L10.conj().T, c=A[off + v :, off + v :],
                          alpha=-1.0, precision=precision, backend=backend)
            )

    return jnp.tril(A)
