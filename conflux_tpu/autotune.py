"""Measured variant dispatch — pick factorization knobs from recorded
measurements instead of asking the user.

Role of the reference's hand-measured variant switch
(`src/conflux/cholesky/Cholesky.cpp:857-921`: overlapping vs
non-overlapping Cholesky chosen per (P, N) from benchmark-derived case
arms). The TPU-native recast makes the rules DATA rather than code:

- a built-in table holds every configuration measured to date, each rule
  carrying its provenance (which benchmark log it came from);
- a JSON table can extend/override it (`CONFLUX_TPU_TUNE_TABLE` env var,
  or :func:`load_table`), so a chip tuning session updates dispatch
  decisions by committing a data file, not editing code;
- lookup is most-specific-wins (device > P > dtype > bounded N-range),
  later-loaded rules beating built-ins on ties, so an override table
  needs only the rows it changes.

Honesty contract: rules exist only where measurements exist. Unmeasured
configurations fall through to broader rules (ultimately the library
defaults) and the returned provenance says so — `recommended()` never
fabricates a tuning claim. The pre-decided default-flip criteria
(docs/ROUND3.md) apply: hardware A/B results land here as new rules, not
as silent default changes.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Mapping

_VALID_ALGOS = ("lu", "cholesky", "qr")


@dataclasses.dataclass(frozen=True)
class Rule:
    """One measured dispatch rule. `None` fields match anything; `device`
    is a substring of jax's lowercased `device_kind` (e.g. 'v5e', 'cpu').
    `n_lo`/`n_hi` bound the (unpadded) matrix dimension inclusively."""

    algo: str
    knobs: Mapping[str, Any]
    device: str | tuple | None = None  # substring(s); any-of for tuples
    P: int | None = None
    n_lo: int = 0
    n_hi: int | None = None
    dtype: str | None = None
    provenance: str = ""

    def matches(self, algo: str, N: int, P: int, dtype: str,
                device_kind: str) -> bool:
        dev = ((self.device,) if isinstance(self.device, str)
               else self.device)
        return (self.algo == algo
                and (dev is None or any(d in device_kind for d in dev))
                and (self.P is None or self.P == P)
                and self.n_lo <= N <= (self.n_hi if self.n_hi is not None
                                       else N)
                and (self.dtype is None or self.dtype == dtype))

    def specificity(self) -> int:
        return ((8 if self.device is not None else 0)
                + (4 if self.P is not None else 0)
                + (2 if self.dtype is not None else 0)
                + (1 if (self.n_lo > 0 or self.n_hi is not None) else 0))


@dataclasses.dataclass(frozen=True)
class Recommendation:
    knobs: dict
    provenance: str


# Library defaults double as the weakest rule per algo: what the entry
# points do when no knob is passed. Keeping them IN the table means
# recommended() always resolves, and the provenance string says honestly
# that nothing was measured for the query. `v` is deliberately None here:
# the un-passed tile default is ADAPTIVE (Cholesky's memory heuristic,
# each miniapp's own default), so an unmeasured rule must not override it
# — None knobs never overwrite (apply_auto contract); only measured rules
# pin a tile.
_DEFAULTS = {
    "lu": dict(precision="highest", v=None, panel_chunk=None,
               segs=(16, 16), tree="pairwise", update="segments",
               lookahead=False, election="gather"),
    "cholesky": dict(precision="highest", v=None, segs=(8, 8),
                     lookahead=False),
    "qr": dict(precision="highest", v=None, csegs=8, lookahead=False,
               tree="gather"),
}

_BUILTIN_RULES: list[Rule] = [
    # ----- catch-alls: library defaults, explicitly unmeasured -----
    *[Rule(algo=a, knobs=_DEFAULTS[a],
           provenance="library defaults — no measurement matches this "
           "configuration")
      for a in _VALID_ALGOS],
    # ----- single-chip v5e LU: the only hardware-measured core -----
    Rule(algo="lu", device=("v5e", "v5 lite"), P=1,
         n_lo=8192, n_hi=32768,
         dtype="float32",
         knobs=dict(_DEFAULTS["lu"], v=1024, panel_chunk=8192),
         provenance="BENCH_r01 10,446 GFLOP/s + round-2 tune 10,749 "
         "(data/benchmarks/ tpu logs): precision=highest chunk=8192 "
         "v=1024 best of the measured matrix; tree=flat/update=block "
         "flips pending the hardware A/B (docs/ROUND3.md criteria "
         "1-2; call-count evidence in "
         "data/benchmarks/election_callcount_r4.json)"),
    # bf16 storage rides the same structure; panel math is f32 either way
    Rule(algo="lu", device=("v5e", "v5 lite"), P=1, n_lo=8192,
         dtype="bfloat16",
         knobs=dict(_DEFAULTS["lu"], v=1024, panel_chunk=8192),
         provenance="structure from the f32 v5e measurements (BENCH_r01); "
         "bf16 trailing GEMMs share the chunking — no separate bf16 "
         "tune exists yet"),
    # ----- CPU-mesh rules from the committed sweep matrix -----
    # (data/benchmarks/summary.csv, README table: best rates at tile 256
    # for LU/Cholesky, 128 for QR; lookahead measured a net LOSS with no
    # overlap-capable runtime — LU +15% / QR +87%, DESIGN §8b)
    Rule(algo="lu", device="cpu",
         knobs=dict(_DEFAULTS["lu"], v=256, lookahead=False),
         provenance="CPU-mesh sweep (data/benchmarks/, README table): "
         "tile 256 best across grids; lookahead measured +15% on the "
         "no-overlap CPU backend"),
    Rule(algo="cholesky", device="cpu",
         knobs=dict(_DEFAULTS["cholesky"], v=256, lookahead=False),
         provenance="CPU-mesh sweep (data/benchmarks/): tile 256 best "
         "across grids"),
    Rule(algo="qr", device="cpu",
         knobs=dict(_DEFAULTS["qr"], v=128, lookahead=False),
         provenance="CPU-mesh sweep (data/benchmarks/): tile 128 best; "
         "lookahead measured +87% on the no-overlap CPU backend"),
    # ----- explicitly unmeasured hardware legs (honest fall-through) ---
    Rule(algo="cholesky", device=("v5e", "v5 lite"),
         knobs=_DEFAULTS["cholesky"],
         provenance="NO hardware measurement yet for Cholesky on TPU "
         "(VERDICT r3 item 4): library defaults; the N=32768 gate is "
         "queued in scripts/chip_recover_measure.sh"),
    Rule(algo="qr", device=("v5e", "v5 lite"),
         knobs=_DEFAULTS["qr"],
         provenance="NO hardware measurement yet for QR on TPU "
         "(VERDICT r3 item 4): library defaults; the N=16384 gate is "
         "queued in scripts/chip_recover_measure.sh"),
]

_loaded_rules: list[Rule] = []
_env_table_loaded = False


def _rules() -> list[Rule]:
    global _env_table_loaded
    if not _env_table_loaded:
        _env_table_loaded = True
        path = os.environ.get("CONFLUX_TPU_TUNE_TABLE")
        if path:
            load_table(path)
    return _BUILTIN_RULES + _loaded_rules


def load_table(path: str) -> int:
    """Append rules from a JSON file (a list of Rule-shaped objects; only
    `algo` and `knobs` are required). Later rules beat built-ins on
    specificity ties, so a tuning session's table needs only the rows it
    changes. Returns the number of rules added."""
    with open(path) as f:
        raw = json.load(f)
    if not isinstance(raw, list):
        raise ValueError(f"{path}: tune table must be a JSON list of rules")
    added = []
    for i, r in enumerate(raw):
        if not isinstance(r, dict) or "algo" not in r or "knobs" not in r:
            raise ValueError(
                f"{path}[{i}]: each rule needs at least algo + knobs")
        if r["algo"] not in _VALID_ALGOS:
            raise ValueError(
                f"{path}[{i}]: unknown algo {r['algo']!r} "
                f"(want one of {_VALID_ALGOS})")
        allowed = {f.name for f in dataclasses.fields(Rule)}
        unknown = set(r) - allowed
        if unknown:
            raise ValueError(
                f"{path}[{i}]: unknown rule fields {sorted(unknown)}")
        knobs = {k: tuple(v) if isinstance(v, list) else v
                 for k, v in r["knobs"].items()}
        added.append(Rule(**{**r, "knobs": knobs}))
    _loaded_rules.extend(added)
    return len(added)


def reset_loaded_table() -> None:
    """Drop JSON-loaded rules (test hook; built-ins are immutable)."""
    global _env_table_loaded
    _loaded_rules.clear()
    _env_table_loaded = False


def detect_device_kind() -> str:
    """Lowercased device kind of device 0 ('cpu', 'tpu v5 lite', ...).
    NOTE: may initialize a jax backend — on a wedged tunnel that HANGS;
    callers in probe-sensitive paths pass device_kind explicitly."""
    import jax

    return jax.devices()[0].device_kind.lower()


def recommended(algo: str, N: int, P: int = 1, dtype: str = "float32",
                device_kind: str | None = None) -> Recommendation:
    """The measured-best knob set for (algo, N, P, dtype, device).

    `P` is the total device count (grid volume). `device_kind=None`
    detects the current backend's device 0 (see `detect_device_kind`'s
    wedge caveat). The winning rule is the most specific match; its
    provenance names the measurement (or states that none exists)."""
    if algo not in _VALID_ALGOS:
        raise ValueError(f"unknown algo {algo!r} (want {_VALID_ALGOS})")
    if N < 1 or P < 1:
        raise ValueError(f"need positive N and P, got N={N} P={P}")
    dtype = str(dtype)
    if device_kind is None:
        device_kind = detect_device_kind()
    device_kind = device_kind.lower()
    best: Rule | None = None
    best_key = (-1, -1)
    for i, rule in enumerate(_rules()):
        if rule.matches(algo, N, P, dtype, device_kind):
            key = (rule.specificity(), i)  # ties: later-loaded wins
            if key > best_key:
                best, best_key = rule, key
    assert best is not None  # the catch-all rules always match
    return Recommendation(knobs=dict(best.knobs),
                          provenance=best.provenance)
