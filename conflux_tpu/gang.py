"""Gang residency: device-resident stacked fleets for cross-session
serving (DESIGN.md §26).

The engine's original ``stack_sessions`` path bought one dispatch per
coalescing window, but paid for it per dispatch: every window re-stacked
the member sessions' factor pytrees (`batched.stack_trees` + a
``jnp.stack`` of the bases) before the vmapped solve could run, and any
session carrying pending Woodbury drift or a checked health program was
silently excluded — exactly the sessions production traffic has. That
violates the CONFLUX thesis on the hottest path: pay the flops, never
pay redundant data movement.

A :class:`SessionGang` fixes the movement half by making the stacked
state *resident*: same-``PlanKey`` non-mesh sessions adopt into a shared
stacked factor pytree (plus base/probe/drift stacks) that lives on their
pinned device. Slots are assigned at adopt and freed on close/spill/GC;
pad slots self-reference slot 0 (the same well-conditioned fill the
per-dispatch stacking used); a slot round-trips bitwise through the
existing `stack_trees`/`write_slot_tree`/`unstack_tree` contract. A
stacked solve then indexes the resident stack directly — zero
per-dispatch restacking, zero per-dispatch h2d beyond the RHS staging
the solo path pays anyway — and session mutations (``update`` /
``refactor`` / drift-refactor) re-sync their owning slot lazily through
a per-session version counter, written back with the PR 3 donation
discipline (`batched.write_slot_tree` donates the gang-owned superseded
stack, so a write-back is one row write, not a full-stack copy).

Both exclusion holes are closed here: the gang maintains a stacked
rank-bucketed Woodbury state (per-slot U/V/Y zero-padded to the gang
rank bucket, ``Cinv`` extended block-diagonally with the identity —
`update.pad_update_state`), so drifting sessions ride the same dispatch
as clean ones, and a checked gang maintains the stacked probe rows
``wA`` so the §20 Freivalds verdict fuses into the stacked program
per-slot (`update.health_spot_check_slots`, read by the factor lane's
existing `resilience.evaluate_slots` + solo-survivor machinery).

The old "gang plans must open with ``substitution='inv'``" rule is
RETIRED (DESIGN §27): the stacked programs are vmapped, and the
``'blocked'`` substitution engine (`ops.batched_trsm`) keeps every
vmapped block step a batched GEMM — ``substitution='auto'`` plans gang
at full speed with triangular-grade accuracy, the checked stacked
program fusing its Freivalds epilogue into the final block steps
(`FactorPlan._stacked_solve_health_fn`). ``'inv'`` remains an explicit
opt-in, not a gang prerequisite.

Locking (the tier layer's discipline, §23): the gang RLock orders AFTER
any session RLock — write paths that hold a session lock (tier spill,
``to_device``) may call :meth:`release`; the adopt/refresh path
(:meth:`ensure`) therefore NEVER takes a session lock while holding the
gang lock (snapshot phase B runs lock-free between two gang-locked
phases). Holding the gang RLock across the stacked dispatch is legal
(the session-RLock-across-dispatch precedent) and is what makes the
donating write-backs safe against in-flight snapshots.
"""

from __future__ import annotations

import threading
import weakref

import jax.numpy as jnp

from conflux_tpu.batched import (
    grow_stack_tree,
    stack_trees,
    write_slot_tree,
)
from conflux_tpu.update import pad_update_state, rank_bucket


class SessionGang:
    """One plan's device-resident stacked fleet on one lane device.

    Owned by a `DeviceLane` (one gang per (plan, lane)); the lane's
    dispatcher adopts sessions on first stacked contact, refreshes
    dirty slots (version mismatch) before dispatching, and frees slots
    when the tier layer spills a member (or a member is GC'd — slot
    reclamation rides a lock-free weakref-callback list). All stacked
    arrays are gang-OWNED: they come out of the gang's own builds and
    donating slot writes, never out of a caller's hands, which is what
    licenses `write_slot_tree`'s buffer donation.
    """

    def __init__(self, plan, device):
        self.plan = plan
        self.device = device
        # the gang RLock: every attribute below is guarded by it; it
        # may be held across the stacked dispatch (RLock, gang.py-born
        # — the lockcheck dispatch rule only forbids engine.py plain
        # Locks) so donating writes serialize with dispatch snapshots
        self._lock = threading.RLock()
        self.cap = 0                    # guarded-by: _lock
        self._slots: list = []          # guarded-by: _lock (weakref|None)
        self._vers: list = []           # guarded-by: _lock (applied ver)
        self._free: list = []           # guarded-by: _lock
        self._by_id: dict = {}          # guarded-by: _lock (id -> slot)
        self._cancelled: set = set()    # guarded-by: _lock
        # per-slot drift occupancy: current rank bucket (0 = clean) and
        # the drifted slot's DriftPolicy.refine (sweeps uniformity)
        self._upd_kb: list = []         # guarded-by: _lock
        self._upd_refine: list = []     # guarded-by: _lock
        # stacked device state (immutable jax arrays, refs swapped
        # under the lock; in-flight dispatches hold their own refs)
        self._F = None                  # guarded-by: _lock
        self._A0 = None                 # guarded-by: _lock
        self._wA = None                 # guarded-by: _lock
        self._KB = 0                    # guarded-by: _lock
        self._Up = None                 # guarded-by: _lock
        self._Vp = None                 # guarded-by: _lock
        self._Y = None                  # guarded-by: _lock
        self._Cinv = None               # guarded-by: _lock
        self._checked = False           # guarded-by: _lock
        # GC-freed slots: (slot, id) appended by weakref callbacks
        # WITHOUT any lock (list.append is GIL-atomic; callbacks must
        # never block on gang state), drained under the lock
        self._dead: list = []
        # counters (read by engine.stats/counters)
        self.adopts = 0                 # guarded-by: _lock
        self.releases = 0               # guarded-by: _lock
        self.refreshes = 0              # guarded-by: _lock
        self.rebuilds = 0               # guarded-by: _lock

    # ------------------------------------------------------------------ #
    # membership bookkeeping
    # ------------------------------------------------------------------ #

    @property
    def members(self) -> int:
        with self._lock:
            self._drain_dead_locked()
            return len(self._by_id)

    def slot_of(self, session):
        """The session's slot, or None (not a member)."""
        with self._lock:
            return self._by_id.get(id(session))

    def _make_ref(self, session, slot: int):
        dead = self._dead
        sid = id(session)

        def cb(_ref, dead=dead, slot=slot, sid=sid):
            # GC context: append only — never touch gang state or locks
            dead.append((slot, sid))

        return weakref.ref(session, cb)

    # requires-lock: _lock
    def _drain_dead_locked(self) -> None:
        while self._dead:
            try:
                slot, sid = self._dead.pop()
            except IndexError:  # pragma: no cover — racing GC append
                break
            # id() reuse guard: only free when the id still maps to the
            # slot the dead session held
            if self._by_id.get(sid) == slot:
                del self._by_id[sid]
                self._free_slot_locked(slot)
                self.releases += 1

    # requires-lock: _lock
    def _free_slot_locked(self, slot: int) -> None:
        self._slots[slot] = None
        self._vers[slot] = -1
        self._upd_kb[slot] = 0
        self._upd_refine[slot] = 0
        self._free.append(slot)
        if not self._by_id:
            self._reset_locked()

    # requires-lock: _lock
    def _reset_locked(self) -> None:
        """Empty gang: drop every stacked array (frees the device
        memory) and return to the unbuilt state."""
        self.cap = 0
        self._slots = []
        self._vers = []
        self._free = []
        self._upd_kb = []
        self._upd_refine = []
        self._F = self._A0 = self._wA = None
        self._Up = self._Vp = self._Y = self._Cinv = None
        self._KB = 0

    def release(self, session) -> None:
        """Free the session's slot (tier spill, `to_device`, engine
        teardown). The CALLER must hold the session's RLock — release
        is the one gang entry point reached from under a session lock,
        which is why `ensure` never nests the locks the other way. A
        release that races a still-pending adoption cancels it. The
        freed slot's stale stack contents are numerically inert (slots
        never interact; a later adopt overwrites them)."""
        sid = id(session)
        with self._lock:
            self._drain_dead_locked()
            slot = self._by_id.pop(sid, None)
            if slot is None:
                self._cancelled.add(sid)
            else:
                self._free_slot_locked(slot)
                self.releases += 1
        session._gang = None
        session._gang_slot = None

    # ------------------------------------------------------------------ #
    # adopt / refresh (the dispatcher's pre-dispatch sync)
    # ------------------------------------------------------------------ #

    def _snap(self, session, checked: bool) -> dict:
        """Snapshot one session's resident state under ITS lock (no
        gang lock held — phase B). Marks tentative membership so a
        concurrent spill's release cancels the pending adoption."""
        with session._lock:
            session._ensure_resident()
            session._gang = self
            probe = session._probe_row() if checked else None
            u = session._upd
            upd = None
            if u is not None:
                upd = (u["kb"], u["Up"], u["Vp"], u["Y"], u["Cinv"],
                       int(session.policy.refine))
            return {"session": session, "ver": session._gang_ver,
                    "F": session._factors, "A0": session._A0,
                    "probe": probe, "upd": upd}

    def ensure(self, sessions, max_stack: int, checked: bool):
        """Adopt any non-member `sessions` (capacity permitting),
        refresh dirty members (version mismatch — a member mutated
        since its slot was written), and upgrade the gang to checked
        residency when the engine's health policy asks for it. Returns
        ``(admitted, excluded)``: admitted maps id(session) -> slot for
        every requested session that is a member after the call;
        excluded maps id(session) -> reason ('stack_cap' | 'error')
        for the rest. Never takes a session lock while holding the
        gang lock (see module docstring)."""
        # ---- phase A (gang lock): plan the work -----------------------
        with self._lock:
            self._drain_dead_locked()
            nmem = len(self._by_id)
            space = max(0, int(max_stack) - nmem)
            news, excluded = [], {}
            seen = set()
            for s in sessions:
                sid = id(s)
                if sid in seen:
                    continue
                seen.add(sid)
                if sid in self._by_id:
                    continue
                if space > 0:
                    news.append(s)
                    space -= 1
                else:
                    excluded[sid] = "stack_cap"
            total = nmem + len(news)
            rebuild = total >= 2 and (
                self.cap == 0
                or (checked and not self._checked)
                or self.cap > 2 * rank_bucket(max(2, total)))
            if checked:
                self._checked = True
            use_checked = self._checked
            dirty = []
            if not rebuild:
                for s in sessions:
                    slot = self._by_id.get(id(s))
                    if slot is not None \
                            and self._vers[slot] != s._gang_ver:
                        dirty.append(s)
            live = []
            if rebuild:
                for ref in self._slots:
                    s = None if ref is None else ref()
                    if s is not None:
                        live.append(s)
        # ---- phase B (no gang lock): snapshot under session locks -----
        need = (live + news) if rebuild else (news + dirty)
        snaps: dict[int, dict] = {}
        for s in need:
            sid = id(s)
            if sid in snaps:
                continue
            try:
                snaps[sid] = self._snap(s, use_checked)
            except Exception:  # noqa: BLE001 — adoption is best-effort
                excluded[sid] = "error"
        # ---- phase C (gang lock): apply -------------------------------
        with self._lock:
            self._drain_dead_locked()
            for sid in list(snaps):
                if sid in self._cancelled:
                    self._cancelled.discard(sid)
                    snaps.pop(sid)
            if rebuild:
                order = [snaps[id(s)] for s in (live + news)
                         if id(s) in snaps]
                if len(order) >= 2:
                    self._install_build_locked(order)
                # sessions that failed their snapshot mid-rebuild are
                # no longer members (their state never made the stack)
                for s in live:
                    if id(s) not in snaps and id(s) in self._by_id:
                        del self._by_id[id(s)]
            else:
                for s in news:
                    snap = snaps.get(id(s))
                    if snap is None:
                        continue
                    if self.cap == 0:
                        # a lone adoptee cannot build a stack (co-
                        # adoptees failed their snapshots): report it
                        # unadmitted; the engine dispatches it solo
                        excluded.setdefault(id(s), "singleton")
                        continue
                    self._adopt_one_locked(snap)
                for s in dirty:
                    snap = snaps.get(id(s))
                    if snap is None:
                        continue
                    slot = self._by_id.get(id(s))
                    if slot is not None:
                        self._write_slot_locked(slot, snap)
                        self.refreshes += 1
            admitted = {}
            for s in sessions:
                slot = self._by_id.get(id(s))
                if slot is not None:
                    admitted[id(s)] = slot
                    s._gang_slot = slot
                elif id(s) not in excluded:
                    excluded[id(s)] = "error"
            return admitted, excluded

    # requires-lock: _lock
    def _install_build_locked(self, snaps: list) -> None:
        """(Re)build every stacked array from scratch: first adoption
        of a pair, a checked upgrade (the probe stack must cover every
        member), or a compaction after the live set shrank well below
        the bucket. Pad slots self-reference slot 0."""
        n = len(snaps)
        cap = rank_bucket(max(2, n))
        pads = cap - n
        self._F = stack_trees([s["F"] for s in snaps]
                              + [snaps[0]["F"]] * pads)
        self._A0 = jnp.stack([s["A0"] for s in snaps]
                             + [snaps[0]["A0"]] * pads)
        if self._checked:
            self._wA = jnp.stack([s["probe"] for s in snaps]
                                 + [snaps[0]["probe"]] * pads)
        else:
            self._wA = None
        self.cap = cap
        self._by_id = {}
        self._slots = [None] * cap
        self._vers = [-1] * cap
        self._free = list(range(n, cap))[::-1]
        self._upd_kb = [0] * cap
        self._upd_refine = [0] * cap
        self._KB = 0
        self._Up = self._Vp = self._Y = self._Cinv = None
        kbs = [s["upd"][0] for s in snaps if s["upd"] is not None]
        if kbs:
            self._alloc_drift_locked(max(kbs),
                                     next(s["upd"] for s in snaps
                                          if s["upd"] is not None))
        for i, snap in enumerate(snaps):
            session = snap["session"]
            self._by_id[id(session)] = i
            self._slots[i] = self._make_ref(session, i)
            self._vers[i] = snap["ver"]
            if self._KB:
                self._write_drift_locked(i, snap["upd"])
            elif snap["upd"] is not None:  # pragma: no cover — allocated above
                raise AssertionError("drift stack missing")
            self.adopts += 1
        self.rebuilds += 1

    # requires-lock: _lock
    def _adopt_one_locked(self, snap: dict) -> None:
        """Adopt one session into a free slot (growing the bucket when
        none is free) — the steady-state adopt: one donated row write
        per stacked component, no rebuild."""
        session = snap["session"]
        if not self._free:
            new_cap = rank_bucket(self.cap + 1)
            self._grow_locked(new_cap)
        slot = self._free.pop()
        self._by_id[id(session)] = slot
        self._slots[slot] = self._make_ref(session, slot)
        self._write_slot_locked(slot, snap)
        self.adopts += 1

    # requires-lock: _lock
    def _grow_locked(self, new_cap: int) -> None:
        self._F = grow_stack_tree(self._F, new_cap)
        self._A0 = grow_stack_tree(self._A0, new_cap)
        if self._wA is not None:
            self._wA = grow_stack_tree(self._wA, new_cap)
        if self._KB:
            self._Up = grow_stack_tree(self._Up, new_cap, fill="zero")
            self._Vp = grow_stack_tree(self._Vp, new_cap, fill="zero")
            self._Y = grow_stack_tree(self._Y, new_cap, fill="zero")
            self._Cinv = grow_stack_tree(self._Cinv, new_cap)
        self._free.extend(range(self.cap, new_cap)[::-1])
        self._slots += [None] * (new_cap - self.cap)
        self._vers += [-1] * (new_cap - self.cap)
        self._upd_kb += [0] * (new_cap - self.cap)
        self._upd_refine += [0] * (new_cap - self.cap)
        self.cap = new_cap

    # requires-lock: _lock
    def _write_slot_locked(self, slot: int, snap: dict) -> None:
        """Write one session's state into its slot — donated row writes
        into the gang-owned stacks (adopt and dirty-refresh share
        this). Bitwise: the slot reads back exactly the session's
        resident bits (`write_slot_tree`'s contract)."""
        self._F = write_slot_tree(self._F, snap["F"], slot)
        self._A0 = write_slot_tree(self._A0, snap["A0"], slot)
        if self._wA is not None:
            probe = snap["probe"]
            if probe is None:  # pragma: no cover — snap matches checked
                raise AssertionError("checked gang snap without probe")
            self._wA = write_slot_tree(self._wA, probe, slot)
        u = snap["upd"]
        if u is not None and u[0] > self._KB:
            if self._KB == 0:
                self._alloc_drift_locked(u[0], u)
            else:
                self._repad_drift_locked(u[0])
        if self._KB:
            self._write_drift_locked(slot, u)
        self._vers[slot] = snap["ver"]

    # requires-lock: _lock
    def _alloc_drift_locked(self, kb: int, template: tuple) -> None:
        """First drifted member: allocate the stacked Woodbury state at
        rank bucket kb — zero U/V/Y (inert) and identity Cinv rows, in
        the template's dtypes (Y/Cinv ride the plan's compute dtype,
        which only a real capacitance output names authoritatively)."""
        _kb, Up, Vp, Y, Cinv, _r = template
        n = Up.shape[-2]
        cap = self.cap
        self._Up = jnp.zeros((cap, n, kb), Up.dtype)
        self._Vp = jnp.zeros((cap, n, kb), Vp.dtype)
        self._Y = jnp.zeros((cap, n, kb), Y.dtype)
        eye = jnp.zeros((cap, kb, kb), Cinv.dtype)
        idx = jnp.arange(kb)
        self._Cinv = eye.at[:, idx, idx].set(1.0)
        self._KB = kb

    # requires-lock: _lock
    def _repad_drift_locked(self, kb2: int) -> None:
        """Grow the gang rank bucket: zero-pad U/V/Y columns, extend
        Cinv block-diagonally with the identity (inert for every
        existing slot — the `pad_update_state` algebra applied to the
        whole stack at once). The bucket is sticky until the gang
        rebuilds: shrinking on every refactor would thrash the pad."""
        kb = self._KB
        pad = [(0, 0), (0, 0), (0, kb2 - kb)]
        self._Up = jnp.pad(self._Up, pad)
        self._Vp = jnp.pad(self._Vp, pad)
        self._Y = jnp.pad(self._Y, pad)
        C = jnp.zeros((self.cap, kb2, kb2), self._Cinv.dtype)
        C = C.at[:, :kb, :kb].set(self._Cinv)
        idx = jnp.arange(kb, kb2)
        self._Cinv = C.at[:, idx, idx].set(1.0)
        self._KB = kb2

    # requires-lock: _lock
    def _write_drift_locked(self, slot: int, upd) -> None:
        kb = self._KB
        if upd is None:
            up = jnp.zeros(self._Up.shape[1:], self._Up.dtype)
            vp = jnp.zeros(self._Vp.shape[1:], self._Vp.dtype)
            y = jnp.zeros(self._Y.shape[1:], self._Y.dtype)
            ci = jnp.eye(kb, dtype=self._Cinv.dtype)
            self._upd_kb[slot] = 0
            self._upd_refine[slot] = 0
        else:
            k0, Up, Vp, Y, Cinv, refine = upd
            up, vp, y, ci = pad_update_state(Up, Vp, Y, Cinv, kb)
            self._upd_kb[slot] = k0
            self._upd_refine[slot] = refine
        self._Up = write_slot_tree(self._Up, up, slot)
        self._Vp = write_slot_tree(self._Vp, vp, slot)
        self._Y = write_slot_tree(self._Y, y, slot)
        self._Cinv = write_slot_tree(self._Cinv, ci, slot)

    # ------------------------------------------------------------------ #
    # dispatch-side reads
    # ------------------------------------------------------------------ #

    # requires-lock: _lock
    def prepare(self, sessions) -> dict:
        """Consistent dispatch snapshot (refs only, no device work) for
        the given request-carrying sessions. The CALLER holds the gang
        lock across this AND the dispatch itself, so a concurrent
        adopt's donating write can never invalidate the refs mid-
        enqueue. Raises KeyError when a session lost its slot since
        `ensure` (a racing spill) — the engine routes that through the
        solo survivor path, which revives and answers."""
        slots = {}
        for s in sessions:
            slots[id(s)] = self._by_id[id(s)]
        drifted = [(k, r) for k, r in zip(self._upd_kb, self._upd_refine)
                   if k]
        kb = self._KB if drifted else 0
        sweeps = self.plan.key.refine
        if drifted:
            sweeps += max(r for _k, r in drifted)
        return {"cap": self.cap, "slots": slots, "F": self._F,
                "A0": self._A0, "wA": self._wA, "kb": kb,
                "sweeps": sweeps, "Up": self._Up, "Vp": self._Vp,
                "Y": self._Y, "Cinv": self._Cinv,
                "checked": self._checked}

    def stats(self) -> dict:
        with self._lock:
            self._drain_dead_locked()
            return {"members": len(self._by_id), "cap": self.cap,
                    "rank_bucket": self._KB,
                    "checked": self._checked, "adopts": self.adopts,
                    "releases": self.releases,
                    "refreshes": self.refreshes,
                    "rebuilds": self.rebuilds}
