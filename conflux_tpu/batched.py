"""Batched factorization/solve: many independent systems, one program.

The serving workload (ROADMAP north star) is *many medium problems at high
throughput*, not one giant factorization: requests arrive as batches of
same-shape (N, N) systems. Factoring them one `solvers.solve` call at a
time serializes B factorizations on one device and pays per-call dispatch
overhead B times. Here the blocked single-device paths (`lu/single.py`,
`cholesky/single.py`, the `solvers` substitutions) are `vmap`-ed over a
leading batch axis and the batch is sharded across the mesh as data
parallelism — B/P whole problems per device, ONE compiled program for the
whole fleet. All underlying tile kernels (`lax.linalg.lu`, `cholesky`,
`triangular_solve`, the masked gathers/scatters) carry batching rules, so
the vmap costs no generality; the XLA partitioner never communicates
because the batch axis is the only sharded axis.

Ragged batches are handled by padding: when B is not a multiple of the
mesh size the batch is padded with copies of element 0 (well-conditioned
by construction — identity padding would be equally valid but a copy
reuses an array we already hold) and the results sliced back.

The plan/session layer (`conflux_tpu.serve`) builds on these entry points
and adds program caching + device-resident factors; use these directly for
one-shot batched calls.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from conflux_tpu.ops import blas
from conflux_tpu.parallel.mesh import lookup_mesh, mesh_cache_key

AXIS_B = "b"  # the data-parallel batch axis of `batch_mesh`


def batch_mesh(devices=None) -> jax.sharding.Mesh:
    """A flat 1D mesh over all (or the given) devices, axis name 'b'.

    The serving counterpart of `make_mesh`: factorization model
    parallelism uses the ('x', 'y', 'z') grid; batched serving shards
    independent problems over one axis instead.
    """
    devs = np.asarray(jax.devices() if devices is None else devices)
    return jax.sharding.Mesh(devs.reshape(-1), (AXIS_B,))


def _batch_spec(mesh, ndim: int):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P(AXIS_B, *([None] * (ndim - 1))))


def _shard_batch(arrs, mesh):
    """Reshard (B, ...) arrays onto the batch mesh (device_put reshards
    committed arrays; jit's in_shardings would reject them instead)."""
    if mesh is None:
        return arrs
    return tuple(
        jax.device_put(a, _batch_spec(mesh, a.ndim)) for a in arrs)


def _pad_batch(arrs, B: int, nshards: int, fill: str = "first"):
    """Pad every (B, ...) array to the next multiple of nshards; returns
    (padded_arrs, Bp). `fill='first'` pads with copies of element 0
    (well-conditioned because it is a real problem we already hold) —
    the data-parallel sharding pad. `fill='eye'` pads square (B, N, N)
    batches with identity matrices instead — the factor lane's pad: an
    identity slot is well-conditioned by CONSTRUCTION, so a poisoned
    element 0 can never leak into the padding (the engine's host-side
    staging buffer mirrors this fill in numpy)."""
    Bp = nshards * (-(-B // nshards))
    if Bp == B:
        return arrs, B
    out = []
    for a in arrs:
        if fill == "eye":
            if a.ndim < 2 or a.shape[-1] != a.shape[-2]:
                raise ValueError(
                    f"fill='eye' pads square trailing dims, got {a.shape}")
            one = jnp.eye(a.shape[-1], dtype=a.dtype)
            pad = jnp.broadcast_to(one, (Bp - B,) + a.shape[1:])
        else:
            pad = jnp.broadcast_to(a[:1], (Bp - B,) + a.shape[1:])
        out.append(jnp.concatenate([a, pad], axis=0))
    return out, Bp


def put_tree(tree, device):
    """`jax.device_put` a pytree onto `device`, preserving leaf ALIASING:
    leaves that are the same buffer in (arrive as one object) leave as
    one object on the target device. A plain tree-mapped device_put
    copies an aliased leaf once per appearance — a `SolveSession` whose
    `_A` IS its `_A0` would come back holding two device buffers, and
    the session's identity-deduplicated `nbytes` accounting (and the
    tier manager's byte caps built on it) would double-count the base.
    `device=None` is the identity (no transfer, no copy) — the
    single-lane engine's path stays byte-for-byte untouched."""
    if device is None:
        return tree
    seen: dict[int, object] = {}

    def _put(leaf):
        if leaf is None:
            return None
        got = seen.get(id(leaf))
        if got is None:
            got = jax.device_put(leaf, device)
            seen[id(leaf)] = got
        return got

    return jax.tree_util.tree_map(_put, tree)


def shard_host_tree(tree, mesh):
    """`jax.device_put` a pytree of HOST (numpy) leaves back onto the
    batch mesh, leaf-wise batch-sharded — the tier/checkpoint revival
    primitive for MESH sessions (`conflux_tpu.tier`, DESIGN §32). Every
    session-state leaf is batch-axis-leading (2D perm rows, 3D factor
    stacks, 4D diagonal-block-inverse stacks), so
    ``_batch_spec(mesh, leaf.ndim)`` reshards any of them. Bitwise: a
    host->device scatter moves bytes, never computes (asserted in
    tests/test_mesh_lane.py). Aliased leaves transfer ONCE (the same
    dedup contract as :func:`put_tree`); None leaves stay None;
    `mesh=None` falls back to plain `jnp.asarray` (default-device
    revival — the pre-mesh path, byte-identical)."""
    seen: dict[int, object] = {}

    def _put(leaf):
        if leaf is None:
            return None
        got = seen.get(id(leaf))
        if got is None:
            if mesh is None:
                got = jnp.asarray(leaf)
            else:
                a = np.asarray(leaf)
                got = jax.device_put(a, _batch_spec(mesh, a.ndim))
            seen[id(leaf)] = got
        return got

    return jax.tree_util.tree_map(_put, tree,
                                  is_leaf=lambda x: x is None)


def stack_trees(trees):
    """Stack identical-structure pytrees along a new leading axis.

    The serve engine's cross-session coalescing primitive: S sessions of
    one single-system plan stack their factor pytrees into a (S, ...)
    batch and ride ONE vmapped substitution dispatch
    (`FactorPlan._stacked_solve_fn`). None leaves must agree across trees
    (they stay None)."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *trees)


def stack_host_trees(trees):
    """Stack identical-structure pytrees of HOST (numpy) leaves along a
    new leading axis and transfer the stack — one h2d per leaf POSITION
    instead of one per (tree, leaf).

    The tier layer's batched-revival primitive (`conflux_tpu.tier`):
    reviving S spilled sessions of one plan naively pays S x L small
    host->device transfers (L = leaves per state pytree, each eating
    XLA-CPU's fixed per-op cost); stacking in numpy first (memcpy, no
    device work) turns that into L transfers of S-times-larger arrays,
    then :func:`unstack_tree` hands each session its slot back as lazy
    device indexing. Values are bitwise the per-leaf transfer's — a
    memcpy and a slice never touch the payload bits (asserted in
    tests/test_tier.py). None leaves must agree across trees (stay
    None)."""
    def one(*xs):
        if xs[0] is None:
            return None
        return jnp.asarray(np.stack([np.asarray(x) for x in xs]))

    return jax.tree_util.tree_map(one, *trees,
                                  is_leaf=lambda x: x is None)


@functools.lru_cache(maxsize=512)
def _slot_writer(shape: tuple, dtype_name: str, donate: bool):
    """One compiled slot-write program per (stack shape, dtype): write a
    per-slot array into row `i` of a stacked array. The slot index is a
    traced argument, so every slot of a bucket shares the program. With
    `donate` the superseded stack buffer is handed to XLA (the output
    replaces it in place) — the gang layer's write-back discipline: the
    gang OWNS its stacks (they come out of its own builds/writes), so
    donation never invalidates a caller's array, and a stacked update
    costs one row write instead of a full-stack copy."""
    fn = lambda S, x, i: lax.dynamic_update_index_in_dim(S, x, i, 0)  # noqa: E731
    return jax.jit(fn, donate_argnums=(0,) if donate else ())


def write_slot_tree(stack, sub, i: int, donate: bool = True):
    """Write per-slot pytree `sub` into slot `i` of stacked pytree
    `stack`, leafwise — the gang layer's slot write-back primitive
    (`conflux_tpu.gang.SessionGang`). Bitwise: a written slot
    round-trips through :func:`unstack_tree` carrying exactly the bits
    of `sub` (a dynamic-update-slice is pure data movement), the same
    contract `stack_trees`/`unstack_tree` already pin. None leaves must
    agree (stay None). `donate=True` donates each superseded stack leaf
    (see `_slot_writer`) — only pass stacks the caller owns."""
    def one(S, x):
        if S is None:
            return None
        return _slot_writer(tuple(S.shape), S.dtype.name, donate)(S, x, i)

    return jax.tree_util.tree_map(one, stack, sub,
                                  is_leaf=lambda x: x is None)


def grow_stack_tree(stack, cap: int, fill: str = "first"):
    """Grow a stacked pytree's leading axis to `cap` slots (a no-op when
    already there). `fill='first'` pads with copies of slot 0 — the gang
    pad rule (pad slots self-reference slot 0, exactly what the engine's
    per-dispatch stacking repeated); `fill='zero'` pads with zeros (the
    gang's drift-state pad: zero U/V columns are Woodbury-inert). Slots
    0..old-1 keep their bits (a concatenate moves, never computes)."""
    def one(S):
        if S is None:
            return None
        n = S.shape[0]
        if n >= cap:
            return S
        if fill == "zero":
            pad = jnp.zeros((cap - n,) + S.shape[1:], S.dtype)
        else:
            pad = jnp.broadcast_to(S[:1], (cap - n,) + S.shape[1:])
        return jnp.concatenate([S, pad], axis=0)

    return jax.tree_util.tree_map(one, stack,
                                  is_leaf=lambda x: x is None)


def unstack_tree(tree, B: int):
    """Split the first `B` slots of a stacked pytree back into a list of
    per-slot trees — the inverse of :func:`stack_trees` (bitwise: slot i
    of the stack IS tree i, no arithmetic happens), asserted as a
    round-trip property in tests/test_factor_lane.py.

    The factor lane's slice-out primitive: one coalesced batched factor
    dispatch produces a (bb, ...)-stacked factor pytree, and each
    request's `SolveSession` takes slot i DEVICE-side — the slices are
    lazy device indexing of arrays that already exist, so no factor data
    ever crosses the host boundary. `B` may be smaller than the leading
    axis (the engine slices only the live slots and leaves the identity
    padding untouched)."""
    return [jax.tree_util.tree_map(lambda l, i=i: l[i], tree)
            for i in range(B)]


def _check_batched_square(A, what: str = "A") -> None:
    if A.ndim != 3 or A.shape[1] != A.shape[2]:
        raise ValueError(
            f"{what} must be a (B, N, N) batch of square systems, got "
            f"{A.shape}")


def _rhs_3d(b, B: int, N: int):
    """Normalize a batched rhs to (B, N, k); returns (b3, squeeze)."""
    b = jnp.asarray(b)
    if b.ndim == 2:
        if b.shape != (B, N):
            raise ValueError(f"rhs {b.shape} does not match batch ({B}, {N})")
        return b[:, :, None], True
    if b.ndim == 3:
        if b.shape[:2] != (B, N):
            raise ValueError(
                f"rhs {b.shape} does not match batch ({B}, {N}, k)")
        return b, False
    raise ValueError(f"rhs must be (B, N) or (B, N, k), got {b.shape}")


# --------------------------------------------------------------------------- #
# Compiled-program builders (one per shape/config, shared by all callers)
# --------------------------------------------------------------------------- #


@functools.lru_cache(maxsize=32)
def _build_lu_factor(B: int, M: int, N: int, dtype_name: str, v: int,
                     precision, backend: str, panel_algo: str, mesh_key):
    from conflux_tpu.lu.single import _lu_factor_blocked

    fn = jax.vmap(
        lambda A: _lu_factor_blocked(A, v, precision, backend, panel_algo))
    if mesh_key is None:
        return jax.jit(fn)
    mesh = lookup_mesh(mesh_key)
    return jax.jit(
        fn, out_shardings=(_batch_spec(mesh, 3), _batch_spec(mesh, 2)))


@functools.lru_cache(maxsize=32)
def _build_cholesky_factor(B: int, N: int, dtype_name: str, v: int,
                           precision, backend: str, mesh_key):
    from conflux_tpu.cholesky.single import _cholesky_blocked

    fn = jax.vmap(lambda A: _cholesky_blocked(A, v, precision, backend))
    if mesh_key is None:
        return jax.jit(fn)
    mesh = lookup_mesh(mesh_key)
    return jax.jit(fn, out_shardings=_batch_spec(mesh, 3))


@functools.lru_cache(maxsize=32)
def _build_lu_solve(B: int, N: int, k: int, dtype_name: str, mesh_key):
    from conflux_tpu.solvers import lu_solve

    fn = jax.vmap(lu_solve)
    if mesh_key is None:
        return jax.jit(fn)
    mesh = lookup_mesh(mesh_key)
    return jax.jit(fn, out_shardings=_batch_spec(mesh, 3))


@functools.lru_cache(maxsize=32)
def _build_cholesky_solve(B: int, N: int, k: int, dtype_name: str, mesh_key):
    from conflux_tpu.solvers import cholesky_solve

    fn = jax.vmap(cholesky_solve)
    if mesh_key is None:
        return jax.jit(fn)
    mesh = lookup_mesh(mesh_key)
    return jax.jit(fn, out_shardings=_batch_spec(mesh, 3))


def _resolve(precision, backend):
    precision = blas.matmul_precision() if precision is None else precision
    backend = blas.get_backend() if backend is None else backend
    return precision, backend


def _mesh_key(mesh):
    return None if mesh is None else mesh_cache_key(mesh)


# --------------------------------------------------------------------------- #
# Public batched entry points
# --------------------------------------------------------------------------- #


def _pallas_factor_eligible(A, mesh, backend: str) -> bool:
    """Whether a batched factor call routes to the batch-blocked Pallas
    kernels (DESIGN §29): opt-in via `backend='pallas'`, single-device
    only (the kernel grid owns the batch axis — a mesh wants the vmapped
    body so the partitioner can shard it), f32/f64 systems (the
    batch-grid kernel's verified dtypes; f64 is interpret-only)."""
    return (backend == "pallas" and mesh is None
            and A.dtype in (jnp.float32, jnp.float64))


def lu_factor_batched(A, v: int, *, mesh=None, precision=None,
                      backend: str | None = None):
    """Pivoted LU of a (B, N, N) batch: returns (LU (B, N, N), perm (B, N))
    with A[i][perm[i]] == L_i @ U_i (the `lu_factor_blocked` contract per
    element). With a `batch_mesh`, the batch is sharded over its devices.
    `backend='pallas'` (mesh-less, f32/f64) runs the batch-blocked Pallas
    factor kernel (`ops.pallas_factor`) instead of vmapping the blocked
    single-system body — the batch axis lives in the kernel grid."""
    A = jnp.asarray(A)
    _check_batched_square(A)
    B, N = A.shape[0], A.shape[1]
    if N % v:
        raise ValueError(f"N={N} not a multiple of tile size v={v}")
    precision, backend = _resolve(precision, backend)
    if _pallas_factor_eligible(A, mesh, backend):
        return blas.batched_lu_factor(A, backend="pallas")
    key = _mesh_key(mesh)
    nsh = 1 if mesh is None else mesh.devices.size
    (Ap,), Bp = _pad_batch((A,), B, nsh)
    (Ap,) = _shard_batch((Ap,), mesh)
    fn = _build_lu_factor(Bp, N, N, A.dtype.name, v, precision, backend,
                          blas.get_panel_algo(), key)
    LU, perm = fn(Ap)
    return LU[:B], perm[:B]


def cholesky_factor_batched(A, v: int, *, mesh=None, precision=None,
                            backend: str | None = None):
    """Lower Cholesky factors of a (B, N, N) SPD batch: returns L
    (B, N, N), strictly-upper parts zeroed. `backend='pallas'`
    (mesh-less, f32/f64) runs the batch-blocked Pallas kernel, see
    :func:`lu_factor_batched`."""
    A = jnp.asarray(A)
    _check_batched_square(A)
    B, N = A.shape[0], A.shape[1]
    if N % v:
        raise ValueError(f"N={N} not a multiple of tile size v={v}")
    precision, backend = _resolve(precision, backend)
    if _pallas_factor_eligible(A, mesh, backend):
        return blas.batched_cholesky_factor(A, backend="pallas")
    key = _mesh_key(mesh)
    nsh = 1 if mesh is None else mesh.devices.size
    (Ap,), Bp = _pad_batch((A,), B, nsh)
    (Ap,) = _shard_batch((Ap,), mesh)
    fn = _build_cholesky_factor(Bp, N, A.dtype.name, v, precision, backend,
                                key)
    return fn(Ap)[:B]


def lu_solve_batched(LU, perm, b, *, mesh=None):
    """Batched substitution through packed LU factors: b is (B, N) or
    (B, N, k); returns x of b's shape."""
    LU = jnp.asarray(LU)
    _check_batched_square(LU, "LU")
    B, N = LU.shape[0], LU.shape[1]
    b3, squeeze = _rhs_3d(b, B, N)
    key = _mesh_key(mesh)
    nsh = 1 if mesh is None else mesh.devices.size
    (LUp, permp, bp), Bp = _pad_batch(
        (LU, jnp.asarray(perm), b3), B, nsh)
    LUp, permp, bp = _shard_batch((LUp, permp, bp), mesh)
    fn = _build_lu_solve(Bp, N, b3.shape[2], LU.dtype.name, key)
    x = fn(LUp, permp, bp)[:B]
    return x[:, :, 0] if squeeze else x


def cholesky_solve_batched(L, b, *, mesh=None):
    """Batched substitution through lower Cholesky factors."""
    L = jnp.asarray(L)
    _check_batched_square(L, "L")
    B, N = L.shape[0], L.shape[1]
    b3, squeeze = _rhs_3d(b, B, N)
    key = _mesh_key(mesh)
    nsh = 1 if mesh is None else mesh.devices.size
    (Lp, bp), Bp = _pad_batch((L, b3), B, nsh)
    Lp, bp = _shard_batch((Lp, bp), mesh)
    fn = _build_cholesky_solve(Bp, N, b3.shape[2], L.dtype.name, key)
    x = fn(Lp, bp)[:B]
    return x[:, :, 0] if squeeze else x


def _batched_corr(spd: bool, substitution: str, precision, backend: str,
                  Af, v: int, panel_algo: str):
    """Factor ONE system of the vmapped one-shot bodies and return its
    substitution closure. `substitution='blocked'` routes through the
    blocked-trsm engine (`ops.batched_trsm`, DESIGN §27) — under the
    callers' vmap every block step is a batched GEMM, sidestepping
    XLA's serial batched small-rhs TriangularSolve; 'trsm' keeps the
    classic substitutions (the historical bits)."""
    from conflux_tpu.cholesky.single import _cholesky_blocked
    from conflux_tpu.lu.single import _lu_factor_blocked
    from conflux_tpu.ops.batched_trsm import (
        blocked_solve,
        diag_block_inverses,
    )
    from conflux_tpu.solvers import cholesky_solve, lu_solve

    cdtype = blas.compute_dtype(Af.dtype)
    if spd:
        L = _cholesky_blocked(Af, v, precision, backend)
        if substitution != "blocked":
            return lambda r: cholesky_solve(L, r)
        Lc = L.astype(cdtype)
        Dl = diag_block_inverses(Lc, lower=True)
        Du = jnp.swapaxes(Dl.conj(), -1, -2)

        def corr(r):
            y = blocked_solve(Lc, Dl, r.astype(cdtype), lower=True)
            return blocked_solve(Lc.conj().T, Du, y, lower=False)

        return corr
    LUf, perm = _lu_factor_blocked(Af, v, precision, backend, panel_algo)
    if substitution != "blocked":
        return lambda r: lu_solve(LUf, perm, r)
    LUc = LUf.astype(cdtype)
    Dl = diag_block_inverses(LUc, lower=True, unit_diagonal=True)
    Du = diag_block_inverses(LUc, lower=False)

    def corr(r):
        y = blocked_solve(LUc, Dl, r.astype(cdtype)[perm], lower=True)
        return blocked_solve(LUc, Du, y, lower=False)

    return corr


@functools.lru_cache(maxsize=32)
def _build_solve(B: int, N: int, k: int, dtype_name: str,
                 fdtype_name: str, v: int, refine: int, spd: bool,
                 precision, backend: str, panel_algo: str, mesh_key,
                 substitution: str = "trsm"):
    """One compiled program for the whole batched pipeline: factor (in the
    factor dtype) + substitution + `refine` classic-IR sweeps, vmapped and
    batch-sharded. Keeping factor and solve in a single program lets XLA
    fuse the dtype casts and skip materializing intermediates the solve
    does not need."""
    fdtype = jnp.dtype(fdtype_name)

    def one(A, b2):
        Af = A.astype(fdtype)
        solve_corr = _batched_corr(spd, substitution, precision, backend,
                                   Af, v, panel_algo)
        cdtype = blas.compute_dtype(A.dtype)
        Ac = A.astype(cdtype)
        bc = b2.astype(cdtype)
        x = solve_corr(b2).astype(cdtype)
        for _ in range(refine):
            r = bc - jnp.matmul(Ac, x, precision=lax.Precision.HIGHEST)
            x = x + solve_corr(r).astype(cdtype)
        return x

    fn = jax.vmap(one)
    if mesh_key is None:
        return jax.jit(fn)
    mesh = lookup_mesh(mesh_key)
    return jax.jit(fn, out_shardings=_batch_spec(mesh, 3))


@functools.lru_cache(maxsize=32)
def _build_solve_updated(B: int, N: int, k: int, nrhs: int, dtype_name: str,
                         fdtype_name: str, v: int, refine: int, spd: bool,
                         precision, backend: str, panel_algo: str, mesh_key,
                         substitution: str = "trsm"):
    """One compiled program for a fleet of drifting systems: factor each
    base A[i], then solve (A[i] + U[i] V[i]^H) x[i] = b[i] through the
    Woodbury capacitance correction — vmapped and batch-sharded like
    `_build_solve`, so B rank-k drifts update together without any
    per-element dispatch."""
    from conflux_tpu.update import woodbury_solve

    fdtype = jnp.dtype(fdtype_name)

    def one(A, U, V, b2):
        Af = A.astype(fdtype)
        base = _batched_corr(spd, substitution, precision, backend,
                             Af, v, panel_algo)
        return woodbury_solve(base, A if refine else None, U, V, b2,
                              refine=refine)

    fn = jax.vmap(one)
    if mesh_key is None:
        return jax.jit(fn)
    mesh = lookup_mesh(mesh_key)
    return jax.jit(fn, out_shardings=_batch_spec(mesh, 3))


def solve_updated_batched(A, U, V, b, *, v: int = 256, factor_dtype=None,
                          refine: int = 0, spd: bool = False, mesh=None,
                          precision=None, backend: str | None = None,
                          substitution: str = "trsm"):
    """Solve B drifted systems (A[i] + U[i] V[i]^H) x[i] = b[i] in one
    program — the batched counterpart of `solvers.solve_updated` for
    fleets whose systems drift by a low-rank correction together. A is
    (B, N, N), U/V are (B, N, k) with k << N, b is (B, N) or (B, N, nrhs);
    only the BASE matrices are factored (O(N^3) each), the corrections
    ride k x k capacitance systems. With a `batch_mesh` the batch is
    data-parallel across its devices; `spd` refers to the base matrices;
    `substitution` as in :func:`solve_batched`.
    """
    if substitution not in ("trsm", "blocked"):
        raise ValueError(
            f"unknown substitution {substitution!r} (trsm|blocked)")
    A = jnp.asarray(A)
    _check_batched_square(A)
    B, N = A.shape[0], A.shape[1]
    U, V = jnp.asarray(U), jnp.asarray(V)
    if U.shape != V.shape or U.ndim != 3 or U.shape[:2] != (B, N):
        raise ValueError(
            f"update factors must both be ({B}, {N}, k), got {U.shape} "
            f"and {V.shape}")
    v = min(v, N)
    if N % v:
        raise ValueError(
            f"N={N} not a multiple of tile size v={v}; pre-pad the batch "
            "with an identity extension (cf. solvers.solve)")
    b3, squeeze = _rhs_3d(b, B, N)
    fdtype = A.dtype if factor_dtype is None else jnp.dtype(factor_dtype)
    precision, backend = _resolve(precision, backend)
    key = _mesh_key(mesh)
    nsh = 1 if mesh is None else mesh.devices.size
    (Ap, Up, Vp, bp), Bp = _pad_batch((A, U, V, b3), B, nsh)
    Ap, Up, Vp, bp = _shard_batch((Ap, Up, Vp, bp), mesh)
    fn = _build_solve_updated(Bp, N, U.shape[-1], b3.shape[2], A.dtype.name,
                              fdtype.name, v, refine, spd, precision,
                              backend, blas.get_panel_algo(), key,
                              substitution)
    x = fn(Ap, Up, Vp, bp)[:B]
    return x[:, :, 0] if squeeze else x


def solve_batched(A, b, *, v: int = 256, factor_dtype=None, refine: int = 0,
                  spd: bool = False, mesh=None, precision=None,
                  backend: str | None = None, substitution: str = "trsm"):
    """Solve B independent systems A[i] x[i] = b[i] in one program.

    The batched counterpart of `solvers.solve` (same `factor_dtype` /
    `refine` HPL-MxP recipe, same `spd` Cholesky switch): A is (B, N, N),
    b is (B, N) or (B, N, k); returns x of b's shape. With a `batch_mesh`
    the batch rides data-parallel across its devices.
    `substitution='blocked'` substitutes through the blocked-trsm
    engine (`ops.batched_trsm`, DESIGN §27 — GEMM steps instead of
    XLA's serial batched trsm; the serve layer's default); 'trsm'
    (default here) keeps this one-shot entry's historical bits.
    """
    if substitution not in ("trsm", "blocked"):
        raise ValueError(
            f"unknown substitution {substitution!r} (trsm|blocked)")
    A = jnp.asarray(A)
    _check_batched_square(A)
    B, N = A.shape[0], A.shape[1]
    v = min(v, N)
    if N % v:
        raise ValueError(
            f"N={N} not a multiple of tile size v={v}; pre-pad the batch "
            "with an identity extension (cf. solvers.solve)")
    b3, squeeze = _rhs_3d(b, B, N)
    fdtype = A.dtype if factor_dtype is None else jnp.dtype(factor_dtype)
    precision, backend = _resolve(precision, backend)
    key = _mesh_key(mesh)
    nsh = 1 if mesh is None else mesh.devices.size
    (Ap, bp), Bp = _pad_batch((A, b3), B, nsh)
    Ap, bp = _shard_batch((Ap, bp), mesh)
    fn = _build_solve(Bp, N, b3.shape[2], A.dtype.name, fdtype.name, v,
                      refine, spd, precision, backend,
                      blas.get_panel_algo(), key, substitution)
    x = fn(Ap, bp)[:B]
    return x[:, :, 0] if squeeze else x
