"""Scoped-region profiler — the role of the vendored semiprof
(`libs/semiprof/include/semiprof/semiprof.hpp:38-52`) and the PE/PL/PP/PC
macro shims (`src/conflux/lu/profiler.hpp`, `cholesky/CholeskyProfiler.h`).

`region(name)` is both a context manager and a decorator; it wraps the body
in `jax.named_scope` (so regions show up in XLA/`jax.profiler` traces under
the same names) and accumulates host-side wall time and call counts.
`report()` prints a semiprof-style table sorted by total time; `clear()`
resets. Region names follow the reference's step vocabulary
(`step0_reduce`, `step1_pivoting`, ..., `conflux_opt.hpp:635,777,1346`).

For on-device timing of jitted code use `trace(logdir)` which forwards to
`jax.profiler.trace` (XPlane output readable in TensorBoard/XProf).
"""

from __future__ import annotations

import contextlib
import functools
import time
from collections import defaultdict

import jax

_times: dict[str, float] = defaultdict(float)
_counts: dict[str, int] = defaultdict(int)
_enabled = True


def enable(on: bool = True) -> None:
    """Compile-time switch analog (reference CONFLUX_WITH_PROFILING)."""
    global _enabled
    _enabled = on


@contextlib.contextmanager
def region(name: str):
    """Profiled named scope: `with profiler.region('step1_pivoting'): ...`"""
    if not _enabled:
        with jax.named_scope(name):
            yield
        return
    t0 = time.perf_counter()
    with jax.named_scope(name):
        yield
    _times[name] += time.perf_counter() - t0
    _counts[name] += 1


def profiled(name: str):
    """Decorator form of :func:`region`."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with region(name):
                return fn(*args, **kwargs)

        return wrapper

    return deco


def report() -> str:
    """semiprof-style table (reference README.md:120-165 output shape)."""
    lines = [f"{'REGION':<32}{'CALLS':>8}{'THREAD':>12}{'WALL':>12}{'%':>8}"]
    total = sum(_times.values()) or 1.0
    for name, t in sorted(_times.items(), key=lambda kv: -kv[1]):
        lines.append(
            f"{name:<32}{_counts[name]:>8}{t:>12.3f}{t:>12.3f}{100 * t / total:>8.1f}"
        )
    out = "\n".join(lines)
    print(out)
    return out


def clear() -> None:
    _times.clear()
    _counts.clear()


def timings() -> dict[str, tuple[int, float]]:
    return {k: (_counts[k], _times[k]) for k in _times}


def trace(logdir: str):
    """Device-level tracing: `with profiler.trace('/tmp/trace'): ...`"""
    return jax.profiler.trace(logdir)
