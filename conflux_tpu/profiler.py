"""Scoped-region profiler — the role of the vendored semiprof
(`libs/semiprof/include/semiprof/semiprof.hpp:38-52`) and the PE/PL/PP/PC
macro shims (`src/conflux/lu/profiler.hpp`, `cholesky/CholeskyProfiler.h`).

`region(name)` is both a context manager and a decorator; it wraps the body
in `jax.named_scope` (so regions show up in XLA/`jax.profiler` traces under
the same names) and accumulates host-side wall time and call counts.
`report()` prints a semiprof-style table sorted by total time; `clear()`
resets. Region names follow the reference's step vocabulary
(`step0_reduce`, `step1_pivoting`, ..., `conflux_opt.hpp:635,777,1346`).

For on-device timing of jitted code use `trace(logdir)` which forwards to
`jax.profiler.trace` (XPlane output readable in TensorBoard/XProf).
"""

from __future__ import annotations

import contextlib
import functools
import threading
import time
from collections import defaultdict

import jax

# the region tables are written from every serve-engine worker thread
# (dispatcher, drain, watchdog) plus the caller: the += below is a
# read-modify-write, so unlocked it silently loses updates (a conflint
# CFX-LOCK finding; regression test in tests/test_analysis.py)
_PROF_LOCK = threading.Lock()
_times: dict[str, float] = defaultdict(float)    # guarded-by: _PROF_LOCK
_counts: dict[str, int] = defaultdict(int)       # guarded-by: _PROF_LOCK
_enabled = True

# set by conflux_tpu.analysis.lockcheck while a watch() is active: the
# hook observes which locks are held when a dispatch region is entered.
# One attribute read per region when inactive.
_dispatch_hook = None


def enable(on: bool = True) -> None:
    """Compile-time switch analog (reference CONFLUX_WITH_PROFILING)."""
    global _enabled
    _enabled = on


@contextlib.contextmanager
def region(name: str):
    """Profiled named scope: `with profiler.region('step1_pivoting'): ...`"""
    hook = _dispatch_hook
    if hook is not None:
        hook(name)
    if not _enabled:
        with jax.named_scope(name):
            yield
        return
    t0 = time.perf_counter()
    with jax.named_scope(name):
        yield
    dt = time.perf_counter() - t0
    with _PROF_LOCK:
        _times[name] += dt
        _counts[name] += 1


def profiled(name: str):
    """Decorator form of :func:`region`."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with region(name):
                return fn(*args, **kwargs)

        return wrapper

    return deco


def _snapshot() -> tuple[dict, dict]:
    """Consistent copy of the region tables (readers never iterate the
    live dicts while a worker thread is inserting)."""
    with _PROF_LOCK:
        return dict(_times), dict(_counts)


def report() -> str:
    """semiprof-style table (reference README.md:120-165 output shape)."""
    times, counts = _snapshot()
    lines = [f"{'REGION':<32}{'CALLS':>8}{'THREAD':>12}{'WALL':>12}{'%':>8}"]
    total = sum(times.values()) or 1.0
    for name, t in sorted(times.items(), key=lambda kv: -kv[1]):
        lines.append(
            f"{name:<32}{counts[name]:>8}{t:>12.3f}{t:>12.3f}{100 * t / total:>8.1f}"
        )
    out = "\n".join(lines)
    print(out)
    return out


def clear() -> None:
    with _PROF_LOCK:
        _times.clear()
        _counts.clear()
    # the resilience and tier outcome counters are global like the
    # region tables, so they reset together (engine counters and the
    # ResidentSet gauges live on their objects and survive — see
    # serve_stats)
    from conflux_tpu import resilience, tier

    resilience.clear_health()
    tier.clear_tier()


def timings() -> dict[str, tuple[int, float]]:
    times, counts = _snapshot()
    return {k: (counts[k], times[k]) for k in times}


def trace(logdir: str):
    """Device-level tracing: `with profiler.trace('/tmp/trace'): ...`"""
    return jax.profiler.trace(logdir)


# --------------------------------------------------------------------------- #
# Serving-phase counters (the amortization view)
# --------------------------------------------------------------------------- #

# the serve layer (conflux_tpu/serve.py) wraps its call sites in
# region("serve.<phase>"), so bench/ops read amortization ratios here
# without instrumenting anything themselves
SERVE_PHASES = ("factor", "solve", "update", "refactor")

# live ServeEngines (conflux_tpu/engine.py) register here (weakly — an
# engine dies with its owner) so serve_stats() can fold queue/coalescing/
# latency counters in next to the per-phase wall times. Unlocked, two
# concurrent _live_engines() calls could both .remove() the same dead
# ref (ValueError) — another conflint CFX-LOCK find.
_ENGINE_REFS: list = []  # guarded-by: _PROF_LOCK


def register_engine(engine) -> None:
    """Called by ServeEngine.__init__; weak so engines are collectable."""
    import weakref

    ref = weakref.ref(engine)
    with _PROF_LOCK:
        _ENGINE_REFS.append(ref)


def _live_engines() -> list:
    """Snapshot the live engines, pruning dead refs. Only the registry
    walk holds the lock — callers talk to the engines (their own locks)
    outside it, so profiler-lock -> engine-lock never nests."""
    alive = []
    with _PROF_LOCK:
        dead = []
        for ref in _ENGINE_REFS:
            e = ref()
            (alive if e is not None else dead).append(e if e is not None
                                                      else ref)
        for ref in dead:
            _ENGINE_REFS.remove(ref)
    return alive


def engine_stats() -> dict:
    """Aggregate ServeEngine counters across live engines: queue depth
    high-water mark (max), batches dispatched / requests / sheds (sums),
    mean coalesced batch size (request-weighted), and p50/p95/p99 request
    latency over the engines' merged rolling windows — plus the factor
    lane's cold-start counters (factor batches, mean coalesced factor
    batch size, pad-waste ratio, session-open latency percentiles),
    merged the same way. Zeroes when no engine is alive."""
    engines = _live_engines()
    out = {"engines": len(engines), "requests": 0, "completed": 0,
           "shed": 0, "batches": 0, "queue_peak": 0,
           "coalesced_mean": 0.0, "latency_p50_ms": 0.0,
           "latency_p95_ms": 0.0, "latency_p99_ms": 0.0,
           "factor_requests": 0, "factor_batches": 0,
           "factor_coalesced_mean": 0.0, "factor_pad_waste": 0.0,
           "factor_latency_p50_ms": 0.0, "factor_latency_p95_ms": 0.0,
           "factor_latency_p99_ms": 0.0}
    coalesced = 0
    fcoalesced = fslots = fpad = 0
    samples: list = []
    fsamples: list = []
    for e in engines:
        s = e.stats()
        out["requests"] += s["requests"]
        out["completed"] += s["completed"]
        out["shed"] += s["shed"]
        out["batches"] += s["batches"]
        out["queue_peak"] = max(out["queue_peak"], s["queue_peak"])
        coalesced += s["coalesced_requests"]
        out["factor_requests"] += s["factor_requests"]
        out["factor_batches"] += s["factor_batches"]
        fcoalesced += s["factor_coalesced_requests"]
        fslots += s["factor_slots"]
        fpad += s["factor_pad_slots"]
        samples.extend(e.latency_samples())
        fsamples.extend(e.factor_latency_samples())
    if out["batches"]:
        out["coalesced_mean"] = coalesced / out["batches"]
    if out["factor_batches"]:
        out["factor_coalesced_mean"] = fcoalesced / out["factor_batches"]
    if fslots:
        out["factor_pad_waste"] = fpad / fslots
    if samples or fsamples:
        from conflux_tpu.engine import _percentile

        for xs, prefix in ((samples, "latency"),
                           (fsamples, "factor_latency")):
            if not xs:
                continue
            xs.sort()
            for pct in (50, 95, 99):
                out[f"{prefix}_p{pct}_ms"] = 1e3 * _percentile(xs, pct)
    return out


def serve_stats() -> dict:
    """Per-phase serving counters from the `serve.*` regions.

    Returns {phase: {'count', 'wall_s'}} for factor / solve / update /
    refactor plus two derived amortization ratios: 'solves_per_factor'
    (how many substitutions each O(N^3) factorization amortized over —
    the serving win) and 'updates_per_refactor' (how many O(N^2 k)
    refreshes each drift-policy refactorization amortized over). Phases
    never entered report zero; `clear()` resets alongside everything
    else. An 'engine' sub-dict carries the ServeEngine counters
    (:func:`engine_stats`) — those live on the engines themselves, so
    `clear()` does not reset them. A 'health' sub-dict carries the
    resilience outcome counters (`conflux_tpu.resilience.health_stats`:
    guard trips, staging isolations, survivor re-dispatches, escalation
    rungs, deadline evictions, quarantine transitions, watchdog trips,
    injected faults) — global like the region tables, so `clear()`
    resets them too. Reliability and throughput read off ONE surface.
    """
    times, counts = _snapshot()
    out: dict = {}
    for ph in SERVE_PHASES:
        key = f"serve.{ph}"
        out[ph] = {"count": counts.get(key, 0),
                   "wall_s": times.get(key, 0.0)}
    factors = out["factor"]["count"] + out["refactor"]["count"]
    out["solves_per_factor"] = (out["solve"]["count"] / factors
                                if factors else 0.0)
    refac = out["refactor"]["count"]
    out["updates_per_refactor"] = (out["update"]["count"] / refac
                                   if refac else float("inf")
                                   if out["update"]["count"] else 0.0)
    out["engine"] = engine_stats()
    from conflux_tpu import resilience, tier

    out["health"] = resilience.health_stats()
    # the tier sub-dict: spill/revive counters + fault-in latency
    # percentiles (global, reset by clear()) and the per-tier
    # population/byte gauges merged across live ResidentSets (live on
    # the managers, surviving clear() like engine counters)
    out["tier"] = tier.tier_stats()
    return out


# --------------------------------------------------------------------------- #
# Device-side per-phase timing (the reference's per-step semiprof table)
# --------------------------------------------------------------------------- #

# LU loop scopes (step0_reduce .. step7_writes) + Cholesky loop scopes
# (reference vocabulary: reduceA11/choleskyA00/updateA10/scatterA11/computeA11)
_PHASE_RE = r"(step\d+_[a-z0-9]+|(?:reduce|cholesky|update|compute|scatter)A\d\d)"

# optimized-HLO "op token -> op_name metadata" line shape; shared with
# scripts/step_profile.py's --top-other listing so the two parsers cannot
# drift apart across jax versions
OP_NAME_RE = r"%([\w.-]+) = .*?metadata=\{[^}]*?op_name=\"([^\"]*)\""


def op_name_map(hlo_text: str) -> dict[str, str]:
    """HLO op token -> op_name metadata string (empty-metadata ops absent)."""
    import re

    return dict(re.findall(OP_NAME_RE, hlo_text))


def _scope_map(hlo_text: str, phase_re: str) -> dict[str, str]:
    """HLO op token -> phase name, from optimized-HLO `op_name` metadata.

    The factorization is one jitted program, so host-side `region` timing
    can never split the hot loop (the judge's round-1 finding). The phases
    ARE visible on the device though: every `jax.named_scope` lands in the
    compiled executable's per-op `metadata={op_name="..."}`, and the XPlane
    trace records each op's device duration. Joining the two recovers a true
    per-phase device-time table from the production program — no staged
    sub-jits, no scheduling perturbation.
    """
    import re

    phase = re.compile(phase_re)
    out: dict[str, str] = {}
    for tok, op_name in op_name_map(hlo_text).items():
        m = phase.search(op_name)
        if m:
            out[tok] = m.group(1)
    return out


def _trace_durations(trace_dir: str) -> dict[str, float]:
    """HLO op token -> total device time (ms) from the newest xplane.pb."""
    import glob
    import os

    files = sorted(
        glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True),
        key=os.path.getmtime,
    )
    if not files:
        raise FileNotFoundError(f"no *.xplane.pb under {trace_dir}")
    from tensorflow.tsl.profiler.protobuf import xplane_pb2  # vendored proto

    xs = xplane_pb2.XSpace()
    with open(files[-1], "rb") as f:
        xs.ParseFromString(f.read())
    durs: dict[str, float] = defaultdict(float)
    for plane in xs.planes:
        if not plane.name.startswith("/device:"):
            continue
        for line in plane.lines:
            # 'XLA Modules' spans whole executables and 'Async XLA Ops'
            # overlaps compute (DMA) — only the serial op line is the
            # device's actual timeline
            if line.name != "XLA Ops":
                continue
            # the op timeline is hierarchical: while/cond events span their
            # body ops, so raw duration sums double-count. Credit each op
            # its SELF time (duration minus directly nested events).
            evs = []
            for ev in line.events:
                name = plane.event_metadata[ev.metadata_id].name
                tok = name[1:].split(" ", 1)[0] if name.startswith("%") else name
                evs.append((ev.offset_ps, ev.offset_ps + ev.duration_ps, tok))
            evs.sort(key=lambda e: (e[0], -(e[1] - e[0])))
            self_ps: list[float] = [e[1] - e[0] for e in evs]
            stack: list[int] = []  # indices of currently open events
            for i, (off, end, _tok) in enumerate(evs):
                while stack and evs[stack[-1]][1] <= off:
                    stack.pop()
                if stack:  # nested: take my span out of my parent's self
                    self_ps[stack[-1]] -= end - off
                stack.append(i)
            for (_off, _end, tok), s in zip(evs, self_ps):
                durs[tok] += s / 1e9
    if not durs:
        raise ValueError(
            "trace has no device op events (CPU runs have no device "
            "plane; the phase table needs a TPU execution)")
    return dict(durs)


def phase_table(trace_dir: str, hlo_text: str,
                phase_re: str = _PHASE_RE) -> dict[str, tuple[float, int]]:
    """Per-phase device time {phase: (ms, ops)} for a traced jitted program.

    `hlo_text` is `fn.lower(*args).compile().as_text()` of the same program
    that ran under :func:`trace`. Ops whose scope matches no phase are
    aggregated under '(other)'. Prints the reference-shaped table
    (README.md:120-165) and returns the mapping.
    """
    scope = _scope_map(hlo_text, phase_re)
    durs = _trace_durations(trace_dir)
    agg: dict[str, tuple[float, int]] = defaultdict(lambda: (0.0, 0))
    for tok, ms in durs.items():
        ph = scope.get(tok, "(other)")
        t, n = agg[ph]
        agg[ph] = (t + ms, n + 1)
    total = sum(t for t, _ in agg.values()) or 1.0
    lines = [f"{'PHASE':<24}{'OPS':>8}{'DEVICE ms':>14}{'%':>8}"]
    for ph, (t, n) in sorted(agg.items(), key=lambda kv: -kv[1][0]):
        lines.append(f"{ph:<24}{n:>8}{t:>14.3f}{100 * t / total:>8.1f}")
    print("\n".join(lines))
    return dict(agg)
