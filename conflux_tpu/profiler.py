"""Scoped-region profiler — the role of the vendored semiprof
(`libs/semiprof/include/semiprof/semiprof.hpp:38-52`) and the PE/PL/PP/PC
macro shims (`src/conflux/lu/profiler.hpp`, `cholesky/CholeskyProfiler.h`).

`region(name)` is both a context manager and a decorator; it wraps the body
in `jax.named_scope` (so regions show up in XLA/`jax.profiler` traces under
the same names) and accumulates host-side wall time and call counts.
`report()` prints a semiprof-style table sorted by total time; `clear()`
resets. Region names follow the reference's step vocabulary
(`step0_reduce`, `step1_pivoting`, ..., `conflux_opt.hpp:635,777,1346`).

For on-device timing of jitted code use `trace(logdir)` which forwards to
`jax.profiler.trace` (XPlane output readable in TensorBoard/XProf).
"""

from __future__ import annotations

import contextlib
import functools
import threading
import time
from collections import defaultdict

import jax

# the region tables are written from every serve-engine worker thread
# (dispatcher, drain, watchdog) plus the caller: the += below is a
# read-modify-write, so unlocked it silently loses updates (a conflint
# CFX-LOCK finding; regression test in tests/test_analysis.py)
_PROF_LOCK = threading.Lock()
_times: dict[str, float] = defaultdict(float)    # guarded-by: _PROF_LOCK
_counts: dict[str, int] = defaultdict(int)       # guarded-by: _PROF_LOCK
_enabled = True

# set by conflux_tpu.analysis.lockcheck while a watch() is active: the
# hook observes which locks are held when a dispatch region is entered.
# One attribute read per region when inactive.
_dispatch_hook = None


def enable(on: bool = True) -> None:
    """Compile-time switch analog (reference CONFLUX_WITH_PROFILING)."""
    global _enabled
    _enabled = on


# ---------------------------------------------------------------------- #
# XLA compile counter (the per-DEVICE zero-compile gate's instrument)
# ---------------------------------------------------------------------- #
#
# One jitted program traces ONCE per shape signature but compiles one
# executable PER DEVICE it dispatches on — the plan-level trace counters
# (`FactorPlan.trace_counts`) therefore cannot see a cold lane paying a
# first-dispatch compile on its own device. jax's monitoring stream
# reports every backend compile; counting it here gives tests and
# benches the exact "zero compiles after prewarm, on every lane" gate.

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_compiles = 0  # guarded-by: _PROF_LOCK


def _count_compile(event, duration, **kwargs) -> None:
    global _compiles
    if event == _COMPILE_EVENT:
        with _PROF_LOCK:
            _compiles += 1


try:  # private jax surface: degrade to a frozen counter if it moves
    from jax._src import monitoring as _jax_monitoring

    _jax_monitoring.register_event_duration_secs_listener(_count_compile)
except Exception:  # noqa: BLE001 — the counter is observability only
    _jax_monitoring = None


def compile_count() -> int:
    """Total XLA backend compiles this process has paid (all devices,
    all programs — monotone; window it by differencing). 0 forever when
    the jax monitoring hook is unavailable."""
    with _PROF_LOCK:
        return _compiles


@contextlib.contextmanager
def region(name: str):
    """Profiled named scope: `with profiler.region('step1_pivoting'): ...`"""
    hook = _dispatch_hook
    if hook is not None:
        hook(name)
    if not _enabled:
        with jax.named_scope(name):
            yield
        return
    t0 = time.perf_counter()
    with jax.named_scope(name):
        yield
    dt = time.perf_counter() - t0
    with _PROF_LOCK:
        _times[name] += dt
        _counts[name] += 1


def profiled(name: str):
    """Decorator form of :func:`region`."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with region(name):
                return fn(*args, **kwargs)

        return wrapper

    return deco


def _snapshot() -> tuple[dict, dict]:
    """Consistent copy of the region tables (readers never iterate the
    live dicts while a worker thread is inserting)."""
    with _PROF_LOCK:
        return dict(_times), dict(_counts)


def report() -> str:
    """semiprof-style table (reference README.md:120-165 output shape)."""
    times, counts = _snapshot()
    lines = [f"{'REGION':<32}{'CALLS':>8}{'THREAD':>12}{'WALL':>12}{'%':>8}"]
    total = sum(times.values()) or 1.0
    for name, t in sorted(times.items(), key=lambda kv: -kv[1]):
        lines.append(
            f"{name:<32}{counts[name]:>8}{t:>12.3f}{t:>12.3f}{100 * t / total:>8.1f}"
        )
    out = "\n".join(lines)
    print(out)
    return out


def clear() -> None:
    with _PROF_LOCK:
        _times.clear()
        _counts.clear()
    # the resilience and tier outcome counters are global like the
    # region tables, so they reset together (engine counters and the
    # ResidentSet gauges live on their objects and survive — see
    # serve_stats)
    from conflux_tpu import resilience, tier

    resilience.clear_health()
    tier.clear_tier()


def timings() -> dict[str, tuple[int, float]]:
    times, counts = _snapshot()
    return {k: (counts[k], times[k]) for k in times}


def trace(logdir: str):
    """Device-level tracing: `with profiler.trace('/tmp/trace'): ...`"""
    return jax.profiler.trace(logdir)


# --------------------------------------------------------------------------- #
# Serving-phase counters (the amortization view)
# --------------------------------------------------------------------------- #

# the serve layer (conflux_tpu/serve.py) wraps its call sites in
# region("serve.<phase>"), so bench/ops read amortization ratios here
# without instrumenting anything themselves
SERVE_PHASES = ("factor", "solve", "update", "refactor")

# live ServeEngines (conflux_tpu/engine.py) register here (weakly — an
# engine dies with its owner) so serve_stats() can fold queue/coalescing/
# latency counters in next to the per-phase wall times. Unlocked, two
# concurrent _live_engines() calls could both .remove() the same dead
# ref (ValueError) — another conflint CFX-LOCK find.
_ENGINE_REFS: list = []  # guarded-by: _PROF_LOCK


def register_engine(engine) -> None:
    """Called by ServeEngine.__init__; weak so engines are collectable."""
    import weakref

    ref = weakref.ref(engine)
    with _PROF_LOCK:
        _ENGINE_REFS.append(ref)


def _live_engines() -> list:
    """Snapshot the live engines, pruning dead refs. Only the registry
    walk holds the lock — callers talk to the engines (their own locks)
    outside it, so profiler-lock -> engine-lock never nests."""
    alive = []
    with _PROF_LOCK:
        dead = []
        for ref in _ENGINE_REFS:
            e = ref()
            (alive if e is not None else dead).append(e if e is not None
                                                      else ref)
        for ref in dead:
            _ENGINE_REFS.remove(ref)
    return alive


def engine_stats() -> dict:
    """Aggregate ServeEngine counters across live engines: queue depth
    high-water mark (max), batches dispatched / requests / sheds (sums),
    mean coalesced batch size (request-weighted), and p50/p95/p99 request
    latency over the engines' merged rolling windows — plus the factor
    lane's cold-start counters (factor batches, mean coalesced factor
    batch size, pad-waste ratio, session-open latency percentiles),
    merged the same way. Zeroes when no engine is alive."""
    engines = _live_engines()
    out = {"engines": len(engines), "requests": 0, "completed": 0,
           "shed": 0, "batches": 0, "queue_peak": 0,
           "coalesced_mean": 0.0, "latency_p50_ms": 0.0,
           "latency_p95_ms": 0.0, "latency_p99_ms": 0.0,
           "factor_requests": 0, "factor_batches": 0,
           "factor_coalesced_mean": 0.0, "factor_pad_waste": 0.0,
           "factor_latency_p50_ms": 0.0, "factor_latency_p95_ms": 0.0,
           "factor_latency_p99_ms": 0.0,
           "lanes": 0, "lane_batches_max": 0, "lane_batches_min": 0,
           "lane_occupancy_max": 0.0, "lane_sheds": 0,
           "gang_batches": 0, "gang_coalesced_mean": 0.0,
           "gang_sessions": 0, "gang_opportunity": 0,
           "stack_exclusions": {}}
    coalesced = 0
    fcoalesced = fslots = fpad = 0
    gcoalesced = 0
    samples: list = []
    fsamples: list = []
    for e in engines:
        s = e.stats()
        out["requests"] += s["requests"]
        out["completed"] += s["completed"]
        out["shed"] += s["shed"]
        out["batches"] += s["batches"]
        out["queue_peak"] = max(out["queue_peak"], s["queue_peak"])
        coalesced += s["coalesced_requests"]
        out["factor_requests"] += s["factor_requests"]
        out["factor_batches"] += s["factor_batches"]
        fcoalesced += s["factor_coalesced_requests"]
        fslots += s["factor_slots"]
        fpad += s["factor_pad_slots"]
        samples.extend(e.latency_samples())
        fsamples.extend(e.factor_latency_samples())
        # gang-stacked serving (PR 10): stacked dispatch counters, gang
        # population, and the per-reason exclusion trace, fleet-merged
        out["gang_batches"] += s.get("gang_batches", 0)
        gcoalesced += s.get("gang_coalesced_requests", 0)
        out["gang_sessions"] += s.get("gang", {}).get("sessions", 0)
        out["gang_opportunity"] += s.get("gang_opportunity", 0)
        for k, v in s.get("stack_exclusions", {}).items():
            out["stack_exclusions"][k] = \
                out["stack_exclusions"].get(k, 0) + v
        # per-lane fleet view (PR 9): lane count and the dispatch-balance
        # extremes across every engine's lanes — the one-glance answer
        # to "is one device starving while another drowns"
        for ln in s.get("lanes", ()):
            out["lanes"] += 1
            b = ln.get("batches", 0) + ln.get("factor_batches", 0)
            out["lane_batches_max"] = max(out["lane_batches_max"], b)
            out["lane_batches_min"] = (b if out["lanes"] == 1
                                       else min(out["lane_batches_min"], b))
            out["lane_occupancy_max"] = max(out["lane_occupancy_max"],
                                            ln.get("occupancy", 0.0))
            out["lane_sheds"] += ln.get("sheds", 0)
    if out["batches"]:
        out["coalesced_mean"] = coalesced / out["batches"]
    if out["gang_batches"]:
        out["gang_coalesced_mean"] = gcoalesced / out["gang_batches"]
    if out["factor_batches"]:
        out["factor_coalesced_mean"] = fcoalesced / out["factor_batches"]
    if fslots:
        out["factor_pad_waste"] = fpad / fslots
    if samples or fsamples:
        from conflux_tpu.engine import _percentile

        for xs, prefix in ((samples, "latency"),
                           (fsamples, "factor_latency")):
            if not xs:
                continue
            xs.sort()
            for pct in (50, 95, 99):
                out[f"{prefix}_p{pct}_ms"] = 1e3 * _percentile(xs, pct)
    return out


def qos_stats() -> dict:
    """Merged multi-tenant QoS rows across live engines (DESIGN §30):
    per-class counters summed, per-class latency percentiles and SLO
    attainment recomputed over the engines' merged per-class rolling
    windows, and the per-tenant ledger totals. Engines that never saw
    classified traffic contribute nothing; with none, the dict is
    empty shells — the `serve_stats()['qos']` surface."""
    engines = _live_engines()
    out: dict = {"engines": 0, "classes": {}, "tenants": {}}
    samples: dict = {}
    for e in engines:
        q = e.counters().get("qos")
        if not q:
            continue
        out["engines"] += 1
        for k, row in q["classes"].items():
            dst = out["classes"].setdefault(k, {
                "tenant": row["tenant"], "tier": row["tier"],
                "priority": row["priority"], "weight": row["weight"],
                "slo_ms": row["slo_ms"], "requests": 0,
                "completed": 0, "failed": 0, "throttled": 0})
            for c in ("requests", "completed", "failed", "throttled"):
                dst[c] += row[c]
        for t, row in q["tenants"].items():
            dst = out["tenants"].setdefault(t, {
                "weight": row["weight"], "pending": 0, "admitted": 0,
                "throttled": 0})
            dst["pending"] += row["pending"]
            dst["admitted"] += row["admitted"]
            dst["throttled"] += row["throttled"]
        for k, xs in e.qos_latency_samples().items():
            samples.setdefault(k, []).extend(xs)
    if samples:
        from conflux_tpu.engine import _percentile

        for k, xs in samples.items():
            row = out["classes"].get(k)
            if row is None or not xs:
                continue
            xs.sort()
            row["latency_samples"] = len(xs)
            for pct in (50, 95, 99):
                row[f"latency_p{pct}_ms"] = 1e3 * _percentile(xs, pct)
            slo_ms = row.get("slo_ms")
            if slo_ms is not None:
                within = sum(1 for x in xs if 1e3 * x <= slo_ms)
                row["slo_attainment_pct"] = round(
                    100.0 * within / len(xs), 2)
    return out


def serve_stats() -> dict:
    """Per-phase serving counters from the `serve.*` regions.

    Returns {phase: {'count', 'wall_s'}} for factor / solve / update /
    refactor plus two derived amortization ratios: 'solves_per_factor'
    (how many substitutions each O(N^3) factorization amortized over —
    the serving win) and 'updates_per_refactor' (how many O(N^2 k)
    refreshes each drift-policy refactorization amortized over). Phases
    never entered report zero; `clear()` resets alongside everything
    else. An 'engine' sub-dict carries the ServeEngine counters
    (:func:`engine_stats`) — those live on the engines themselves, so
    `clear()` does not reset them. A 'health' sub-dict carries the
    resilience outcome counters (`conflux_tpu.resilience.health_stats`:
    guard trips, staging isolations, survivor re-dispatches, escalation
    rungs, deadline evictions, quarantine transitions, watchdog trips,
    injected faults) — global like the region tables, so `clear()`
    resets them too. Reliability and throughput read off ONE surface.
    """
    times, counts = _snapshot()
    out: dict = {}
    for ph in SERVE_PHASES:
        key = f"serve.{ph}"
        out[ph] = {"count": counts.get(key, 0),
                   "wall_s": times.get(key, 0.0)}
    factors = out["factor"]["count"] + out["refactor"]["count"]
    out["solves_per_factor"] = (out["solve"]["count"] / factors
                                if factors else 0.0)
    refac = out["refactor"]["count"]
    out["updates_per_refactor"] = (out["update"]["count"] / refac
                                   if refac else float("inf")
                                   if out["update"]["count"] else 0.0)
    out["engine"] = engine_stats()
    from conflux_tpu import resilience, tier

    out["health"] = resilience.health_stats()
    # the tier sub-dict: spill/revive counters + fault-in latency
    # percentiles (global, reset by clear()) and the per-tier
    # population/byte gauges merged across live ResidentSets (live on
    # the managers, surviving clear() like engine counters)
    out["tier"] = tier.tier_stats()
    # the fabric sub-dict: host census + fail-over/migration gauges
    # merged across live ServeFabric fronts (DESIGN §28); like engine
    # counters these live on the fabrics and survive clear(). The
    # fabric EVENT counters (host_unavailable, heartbeat_misses,
    # hosts_died, sessions_failed_over, ...) ride the 'health' dict
    from conflux_tpu import fabric

    out["fabric"] = fabric.fabric_stats()
    # the qos sub-dict: per-class/per-tenant counters, percentiles and
    # SLO attainment merged across live engines (DESIGN §30); like
    # engine counters these live on the engines and survive clear().
    # The THROTTLE event counters (tenant_throttled, per-class
    # tenant_throttled[t/tier] / engine_saturated[t/tier]) ride the
    # 'health' dict
    out["qos"] = qos_stats()
    return out


# --------------------------------------------------------------------------- #
# Windowed telemetry (rolling deltas — the adaptive controller's input)
# --------------------------------------------------------------------------- #

# engine.stats() keys that are monotone counters (windowed by
# differencing); everything else in the engine dict is a gauge or a
# derived ratio and passes through / is recomputed over the window
_ENGINE_COUNTERS = (
    "requests", "completed", "failed", "shed", "batches",
    "coalesced_requests", "width_capped", "factor_requests",
    "factor_batches", "factor_coalesced_requests", "factor_slots",
    "factor_pad_slots", "gang_batches", "gang_coalesced_requests",
    "gang_opportunity",
)
# the extra per-class counters a qos_class=-scoped StatsWindow windows
# (sourced from counters()['qos']['classes'][key], DESIGN §30)
_QOS_WINDOW_COUNTERS = (
    "qos_requests", "qos_completed", "qos_failed", "qos_throttled",
)
# tier.tier_stats() keys that are NOT counters: per-manager population/
# byte gauges and the latency percentiles (recomputed cumulatively)
_TIER_GAUGES = frozenset({
    "managed_sessions", "resident_sessions", "host_sessions",
    "disk_sessions", "corrupt_sessions", "device_bytes",
    "device_bytes_high_water", "resident_high_water", "host_bytes",
    "disk_bytes", "fault_in_p50_ms", "fault_in_p95_ms",
    "fault_in_p99_ms",
})


def _diff(cur: dict, prev: dict, keys=None) -> dict:
    """Per-key counter deltas with reset detection — the `clear()`
    contract: a counter that went BACKWARDS mid-window was reset, so
    the window reports the post-clear count (everything that landed
    after the reset) instead of a negative. Counts that landed between
    the previous window and the reset are lost with the reset itself —
    window continuity cannot survive a cumulative reset, but the delta
    stays non-negative and cumulative consumers (serve_stats) are
    untouched either way."""
    if keys is None:
        keys = [k for k, v in cur.items() if isinstance(v, (int, float))]
    out = {}
    for k in keys:
        c, p = cur.get(k, 0), prev.get(k, 0)
        out[k] = c - p if c >= p else c
    return out


class StatsWindow:
    """Rolling-window deltas of the serving telemetry.

    Construction snapshots the cumulative counters; each `delta()` call
    returns what changed since the PREVIOUS `delta()` (or construction)
    and advances the window. Counters are differenced (clamped at zero
    across `clear()` — see `_diff`); population/byte gauges pass
    through; latency percentiles are recomputed over ONLY the samples
    that completed inside the window, via per-engine sample-sequence
    tokens (`ServeEngine.latency_window`), not the engines' cumulative
    rolling windows. Nothing here is destructive: any number of windows
    coexist with each other and with every cumulative consumer.

    `engine=None` windows the merged `serve_stats()` surface across all
    live engines; passing a specific engine windows that engine's own
    counters (what `conflux_tpu.control.AdaptiveController` consumes).

    `qos_class=` ('tenant/tier', DESIGN §30) scopes the LATENCY half of
    the window to one QoS class: samples come from the engines'
    per-class rings (`ServeEngine.qos_latency_window` — so the
    percentiles are the class's own tail, not the blended one) and the
    delta grows `qos_requests`/`qos_completed`/`qos_failed`/
    `qos_throttled` counters for the class; the engine-wide counters
    still ride along. Any number of class windows coexist with each
    other, with the controller's own window, and with every cumulative
    consumer — the §24 non-destructive contract, per class.
    """

    def __init__(self, engine=None, qos_class: str | None = None):
        import weakref

        self._engine = None if engine is None else weakref.ref(engine)
        self._qos_class = qos_class
        # per-engine latency sample-sequence tokens, weakly keyed so a
        # dead engine drops its token with itself
        self._tokens: "weakref.WeakKeyDictionary" = \
            weakref.WeakKeyDictionary()
        self._prev: dict | None = None
        self._t_prev = time.perf_counter()
        self.delta()  # prime the baseline snapshot

    def _engines(self) -> list:
        if self._engine is not None:
            e = self._engine()
            return [] if e is None else [e]
        return _live_engines()

    def _snapshot(self) -> tuple[dict, list, list]:
        """(cumulative snapshot, window latency samples, window factor
        latency samples)."""
        engines = self._engines()
        eng = {k: 0 for k in _ENGINE_COUNTERS}
        if self._qos_class is not None:
            eng.update({k: 0 for k in _QOS_WINDOW_COUNTERS})
        eng["pending"] = 0
        bucket_hits: dict[int, int] = {}
        fbucket_hits: dict[int, int] = {}
        lats: list = []
        flats: list = []
        for e in engines:
            # counters() skips stats()'s percentile sorts — the window
            # computes its own percentiles from the token-windowed
            # samples below, so the cumulative ones would be wasted work
            s = e.counters() if hasattr(e, "counters") else e.stats()
            for k in _ENGINE_COUNTERS:
                eng[k] += s.get(k, 0)
            eng["pending"] += s["pending"]
            for w, n in s.get("bucket_hits", {}).items():
                bucket_hits[w] = bucket_hits.get(w, 0) + n
            for bb, n in s.get("factor_bucket_hits", {}).items():
                fbucket_hits[bb] = fbucket_hits.get(bb, 0) + n
            tok, ftok = self._tokens.get(e, (None, None))
            if self._qos_class is None:
                tok, new = e.latency_window(tok)
            else:
                # the class's OWN ring: this window's percentiles are
                # the class tail, not the engine-blended one
                tok, new = e.qos_latency_window(self._qos_class, tok)
                row = (s.get("qos") or {}).get("classes", {}).get(
                    self._qos_class, {})
                for c in ("requests", "completed", "failed",
                          "throttled"):
                    eng[f"qos_{c}"] += row.get(c, 0)
            ftok, fnew = e.factor_latency_window(ftok)
            self._tokens[e] = (tok, ftok)
            lats.extend(new)
            flats.extend(fnew)
        times, counts = _snapshot()
        cur = {
            "engine": eng,
            "bucket_hits": bucket_hits,
            "factor_bucket_hits": fbucket_hits,
            "phases": {ph: {"count": counts.get(f"serve.{ph}", 0),
                            "wall_s": times.get(f"serve.{ph}", 0.0)}
                       for ph in SERVE_PHASES},
        }
        from conflux_tpu import resilience, tier

        cur["health"] = resilience.health_stats()
        t = tier.tier_stats()
        cur["tier"] = {k: v for k, v in t.items()
                       if k not in _TIER_GAUGES}
        cur["tier_gauges"] = {k: t[k] for k in _TIER_GAUGES if k in t}
        return cur, lats, flats

    def delta(self) -> dict:
        """The windowed telemetry since the last call; advances the
        window."""
        now = time.perf_counter()
        cur, lats, flats = self._snapshot()
        prev = self._prev
        if prev is None:
            prev = {"engine": {}, "bucket_hits": {},
                    "factor_bucket_hits": {},
                    "phases": {ph: {} for ph in SERVE_PHASES},
                    "health": {}, "tier": {}}
        dt = max(1e-9, now - self._t_prev)
        keys = (_ENGINE_COUNTERS if self._qos_class is None
                else _ENGINE_COUNTERS + _QOS_WINDOW_COUNTERS)
        eng = _diff(cur["engine"], prev["engine"], keys)
        eng["pending"] = cur["engine"]["pending"]
        # queue growth over the window: admissions minus resolutions.
        # Positive = the backlog is building (arrivals outpace drain)
        eng["backlog_delta"] = (eng["requests"] - eng["completed"]
                                - eng["failed"])
        eng["arrival_per_s"] = eng["requests"] / dt
        eng["drain_per_s"] = eng["completed"] / dt
        eng["coalesced_mean"] = (eng["coalesced_requests"] / eng["batches"]
                                 if eng["batches"] else 0.0)
        eng["factor_coalesced_mean"] = (
            eng["factor_coalesced_requests"] / eng["factor_batches"]
            if eng["factor_batches"] else 0.0)
        lats.sort()
        flats.sort()
        from conflux_tpu.engine import _percentile

        for xs, prefix in ((lats, "latency"), (flats, "factor_latency")):
            for pct in (50, 95, 99):
                eng[f"{prefix}_p{pct}_ms"] = 1e3 * _percentile(xs, pct)
        eng["latency_samples"] = len(lats)
        eng["factor_latency_samples"] = len(flats)
        out = {
            "seconds": dt,
            "engine": eng,
            "bucket_hits": _diff(cur["bucket_hits"],
                                 prev["bucket_hits"]),
            "factor_bucket_hits": _diff(cur["factor_bucket_hits"],
                                        prev["factor_bucket_hits"]),
            "phases": {ph: _diff(cur["phases"][ph],
                                 prev["phases"].get(ph, {}),
                                 ("count", "wall_s"))
                       for ph in SERVE_PHASES},
            "health": _diff(cur["health"], prev["health"]),
            "tier": _diff(cur["tier"], prev["tier"]),
            "tier_gauges": cur.get("tier_gauges", {}),
        }
        self._prev = cur
        self._t_prev = now
        return out


class CounterWindow:
    """Reset-aware rolling deltas over an arbitrary monotone-counter
    dict — the cross-process sibling of :class:`StatsWindow`.

    StatsWindow reads THIS process's profiler/engine globals; a serve
    fabric front (`conflux_tpu.fabric`, DESIGN §28) cannot — each
    engine host is its own process, and its counters arrive serialized
    in heartbeat payloads. The front keeps one CounterWindow per host
    and `feed()`s it each payload: numeric keys are differenced with
    the same reset-clamp `_diff` applies (a host that restarted or
    `clear()`ed reports its post-reset counts, never negative deltas),
    non-numeric keys pass through untouched, and the returned dict
    carries `seconds` (wall span of the window) so callers derive
    rates. Thread-safe: feed() is atomic under the window's lock (the
    heartbeat thread writes, stats readers may race it)."""

    def __init__(self):
        import threading

        self._lock = threading.Lock()
        self._prev: dict | None = None   # guarded-by: _lock
        self._t_prev = time.perf_counter()  # guarded-by: _lock

    def feed(self, counters: dict, t: float | None = None) -> dict:
        now = time.perf_counter() if t is None else t
        num = {k: v for k, v in counters.items()
               if isinstance(v, (int, float)) and not isinstance(v, bool)}
        with self._lock:
            prev = self._prev if self._prev is not None else {}
            dt = max(1e-9, now - self._t_prev)
            out = _diff(num, prev)
            out.update({k: v for k, v in counters.items() if k not in num})
            out["seconds"] = dt
            self._prev = num
            self._t_prev = now
        return out


# --------------------------------------------------------------------------- #
# Device-side per-phase timing (the reference's per-step semiprof table)
# --------------------------------------------------------------------------- #

# LU loop scopes (step0_reduce .. step7_writes) + Cholesky loop scopes
# (reference vocabulary: reduceA11/choleskyA00/updateA10/scatterA11/computeA11)
_PHASE_RE = r"(step\d+_[a-z0-9]+|(?:reduce|cholesky|update|compute|scatter)A\d\d)"

# optimized-HLO "op token -> op_name metadata" line shape; shared with
# scripts/step_profile.py's --top-other listing so the two parsers cannot
# drift apart across jax versions
OP_NAME_RE = r"%([\w.-]+) = .*?metadata=\{[^}]*?op_name=\"([^\"]*)\""


def op_name_map(hlo_text: str) -> dict[str, str]:
    """HLO op token -> op_name metadata string (empty-metadata ops absent)."""
    import re

    return dict(re.findall(OP_NAME_RE, hlo_text))


def _scope_map(hlo_text: str, phase_re: str) -> dict[str, str]:
    """HLO op token -> phase name, from optimized-HLO `op_name` metadata.

    The factorization is one jitted program, so host-side `region` timing
    can never split the hot loop (the judge's round-1 finding). The phases
    ARE visible on the device though: every `jax.named_scope` lands in the
    compiled executable's per-op `metadata={op_name="..."}`, and the XPlane
    trace records each op's device duration. Joining the two recovers a true
    per-phase device-time table from the production program — no staged
    sub-jits, no scheduling perturbation.
    """
    import re

    phase = re.compile(phase_re)
    out: dict[str, str] = {}
    for tok, op_name in op_name_map(hlo_text).items():
        m = phase.search(op_name)
        if m:
            out[tok] = m.group(1)
    return out


def _trace_durations(trace_dir: str) -> dict[str, float]:
    """HLO op token -> total device time (ms) from the newest xplane.pb."""
    import glob
    import os

    files = sorted(
        glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True),
        key=os.path.getmtime,
    )
    if not files:
        raise FileNotFoundError(f"no *.xplane.pb under {trace_dir}")
    from tensorflow.tsl.profiler.protobuf import xplane_pb2  # vendored proto

    xs = xplane_pb2.XSpace()
    with open(files[-1], "rb") as f:
        xs.ParseFromString(f.read())
    durs: dict[str, float] = defaultdict(float)
    for plane in xs.planes:
        if not plane.name.startswith("/device:"):
            continue
        for line in plane.lines:
            # 'XLA Modules' spans whole executables and 'Async XLA Ops'
            # overlaps compute (DMA) — only the serial op line is the
            # device's actual timeline
            if line.name != "XLA Ops":
                continue
            # the op timeline is hierarchical: while/cond events span their
            # body ops, so raw duration sums double-count. Credit each op
            # its SELF time (duration minus directly nested events).
            evs = []
            for ev in line.events:
                name = plane.event_metadata[ev.metadata_id].name
                tok = name[1:].split(" ", 1)[0] if name.startswith("%") else name
                evs.append((ev.offset_ps, ev.offset_ps + ev.duration_ps, tok))
            evs.sort(key=lambda e: (e[0], -(e[1] - e[0])))
            self_ps: list[float] = [e[1] - e[0] for e in evs]
            stack: list[int] = []  # indices of currently open events
            for i, (off, end, _tok) in enumerate(evs):
                while stack and evs[stack[-1]][1] <= off:
                    stack.pop()
                if stack:  # nested: take my span out of my parent's self
                    self_ps[stack[-1]] -= end - off
                stack.append(i)
            for (_off, _end, tok), s in zip(evs, self_ps):
                durs[tok] += s / 1e9
    if not durs:
        raise ValueError(
            "trace has no device op events (CPU runs have no device "
            "plane; the phase table needs a TPU execution)")
    return dict(durs)


def phase_table(trace_dir: str, hlo_text: str,
                phase_re: str = _PHASE_RE) -> dict[str, tuple[float, int]]:
    """Per-phase device time {phase: (ms, ops)} for a traced jitted program.

    `hlo_text` is `fn.lower(*args).compile().as_text()` of the same program
    that ran under :func:`trace`. Ops whose scope matches no phase are
    aggregated under '(other)'. Prints the reference-shaped table
    (README.md:120-165) and returns the mapping.
    """
    scope = _scope_map(hlo_text, phase_re)
    durs = _trace_durations(trace_dir)
    agg: dict[str, tuple[float, int]] = defaultdict(lambda: (0.0, 0))
    for tok, ms in durs.items():
        ph = scope.get(tok, "(other)")
        t, n = agg[ph]
        agg[ph] = (t + ms, n + 1)
    total = sum(t for t, _ in agg.values()) or 1.0
    lines = [f"{'PHASE':<24}{'OPS':>8}{'DEVICE ms':>14}{'%':>8}"]
    for ph, (t, n) in sorted(agg.items(), key=lambda kv: -kv[1][0]):
        lines.append(f"{ph:<24}{n:>8}{t:>14.3f}{100 * t / total:>8.1f}")
    print("\n".join(lines))
    return dict(agg)
