"""LU factorization with (tournament) pivoting — the CONFLUX side."""

from conflux_tpu.lu.single import lu_factor_blocked

__all__ = ["lu_factor_blocked"]
