"""Distributed LU with tournament pivoting over the (x, y, z) mesh.

TPU-native re-design of the reference's `LU_rep` superstep loop
(`conflux_opt.hpp:343-1827`). The reference is host-orchestrated SPMD: each
MPI rank owns block-cyclic tiles, physically compacts pivot rows upward
(`push_pivots_up`, `conflux_opt.hpp:176-218`), and moves panels with
Reduce/Iscatterv/Sendrecv. Here the whole factorization is ONE jitted
`shard_map` program with a `lax.fori_loop` over supersteps and static
shapes throughout:

 - the matrix lives in *currently-pivoted global row order* (LAPACK getrf
   layout): after step k, global positions < k*v hold frozen factor rows
   and positions >= k*v are active. Each step performs LAPACK-style row
   swaps — elected pivot rows move into the step's diagonal block, the
   displaced occupants move to the vacated slots — expressed as two
   (v, Nl) psums plus value-level scatters. This is the TPU answer to the
   reference's `push_pivots_up` row compaction (P6): because eliminated
   rows now occupy a tile-aligned *prefix* of every device's local rows,
   row liveness (like column liveness) is monotone in the local tile
   index, and the hot ops shrink with k instead of paying full-height
   masked work every superstep;
 - rotating owner roles (P5) -> `axis_index` comparisons inside the loop;
 - the z-layer 2.5D replication (P3) -> each device holds a *partial sum*
   shard; sum over the z axis is the true matrix. Panel reads are `psum`s
   over ('y','z'); factor writes land on layer z==0 only;
 - tournament pivoting (P4) -> chunked CALU nomination per x-rank,
   `all_gather` over 'x' + the same chunked reduction tree elects winners
   (every LU call height-bounded by max(chunk, 2v), the role of the
   reference's log-depth butterfly), computed identically on every device
   so the result needs no broadcast;
 - the trailing update (step 6) runs on each device's nlayr = v/Pz slab of
   the panel, so z layers share the O(N^2 v) GEMM flops exactly like the
   reference's 2.5D scheme. The update is cut into row x column segments
   (ragged, tile-aligned); segments with no live rows or columns are
   skipped via `lax.cond`, keeping total GEMM/TRSM work near the true
   2/3 N^3 / P.

Per superstep: 5 collectives (panel psum over (y,z), nominee all_gather
over x, pivot-row psum over (x,z), displaced-row psum over (x,z), small
bookkeeping psums), two chunked tournament factorizations, two TRSMs over
live segments, and the segmented trailing GEMMs.

Factors come back in *pivoted row order* together with `perm` (M,), the
original row index at each global position: A[perm] == L @ U.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from conflux_tpu.geometry import Grid3, LUGeometry, ragged_segments
from conflux_tpu.ops import blas
from conflux_tpu.parallel.mesh import (
    AXIS_X,
    AXIS_Y,
    AXIS_Z,
    butterfly_allreduce,
    lookup_mesh,
    make_mesh,
    mesh_cache_key,
    pvary,
    shard_map,
)

_GRI_SENTINEL = np.iinfo(np.int32).max

# The default nomination chunk is blas.single_call_rows(v): unlike the
# batched ceiling (blas.batched_call_rows — batch x height shares one
# scoped VMEM budget), the chunk_live nomination runs each chunk as a
# separate cond'd call, so the full single-call height is VMEM-safe and
# measured faster (10.5 vs 9.8 TFLOP/s at N=32768/v=1024 on a v5e, where
# the derived values pin to 8192/4096).


@functools.lru_cache(maxsize=32)
def _build(geom: LUGeometry, mesh_key, precision, backend: str,
           panel_chunk: int, donate: bool = False, resumable: bool = False,
           lookahead: bool = False, election: str = "gather",
           segs: tuple = (16, 16), tree: str = "pairwise",
           update: str = "segments"):
    """resumable=True builds the checkpoint/restart form: factor supersteps
    [k0, k1) given as TRACED scalars — one compile serves every segment of
    a checkpointed run — with the row-origin state as an explicit
    input/output (`lu_factor_steps`). lookahead=True builds the
    software-pipelined loop (panel + election carried one step ahead; see
    body_la)."""
    mesh = lookup_mesh(mesh_key)
    v = geom.v
    Px, Py, Pz = geom.grid.Px, geom.grid.Py, geom.grid.Pz
    Ml, Nl = geom.Ml, geom.Nl
    nlayr = geom.nlayr
    n_steps = geom.n_steps
    Mcap = geom.M  # positions are < Mcap; sentinel values exceed it
    v_pad = Pz * nlayr  # inner dim padded so every z layer gets a full slab
    # trailing-update segmentation: row and column liveness are both
    # monotone in local tile index (rows because of the LAPACK-order swaps,
    # columns because tile lt has global id lt*P + coord), so the live
    # region is a contiguous (row-suffix x col-suffix) block; ragged
    # segments + lax.cond skip dead blocks, bounding flop overshoot at one
    # segment of width/height per superstep. `segs` = (row, col) segment
    # counts: finer cuts overshoot (avg half a segment of dead rows/cols
    # ride every GEMM) at the cost of more cond/DUS ops per step.
    row_segs = ragged_segments(geom.Mtl, v, segs[0])
    col_segs = ragged_segments(geom.Ntl, v, segs[1])

    def device_fn(blk, orig_blk=None, k0=0, k_end=n_steps):
        x = lax.axis_index(AXIS_X)
        y = lax.axis_index(AXIS_Y)
        z = lax.axis_index(AXIS_Z)
        dtype = blk.dtype
        cdtype = blas.compute_dtype(dtype)

        # z-partial invariant: sum over z == true matrix; data enters on
        # z=0. A resumed state round-trips through the same line: outputs
        # are z-replicated, so taking layer 0 restores the invariant.
        Aloc = jnp.where(z == 0, blk[0, 0], jnp.zeros((), dtype))

        lr = jnp.arange(Ml, dtype=jnp.int32)
        rtile = (lr // v) * Px + x  # global row-tile at each local row
        gp = rtile * v + (lr % v)  # global POSITION of each local row
        lc = jnp.arange(Nl, dtype=jnp.int32)
        ctile = (lc // v) * Py + y  # global col-tile id per local col

        # original row id currently at each local position (rows start in
        # original order, so position == original id at step 0); resumed
        # runs carry it in as explicit state
        orig0 = gp if orig_blk is None else orig_blk[0]

        def loc_of(pos):
            """Local row index of a (v,) vector of global positions; Ml
            (out of range -> scatter/gather drop) when not owned in x or
            when the entry is a sentinel."""
            tile = pos // v
            owned = (tile % Px == x) & (pos < Mcap)
            return jnp.where(owned, (tile // Px) * v + pos % v, Ml)

        def panel_reduce(Aloc, k):
            """Panel column k: z-reduce + y-broadcast in one psum (ref
            step 0)."""
            j_owner = k % Py
            lj = jnp.asarray((k // Py) * v, jnp.int32)  # k may be a py int
            panel_loc = lax.dynamic_slice(
                Aloc, (jnp.zeros((), jnp.int32), lj), (Ml, v))
            return lax.psum(
                jnp.where(y == j_owner, panel_loc, jnp.zeros((), dtype)),
                (AXIS_Y, AXIS_Z),
            ).astype(cdtype)

        def elect(panel, k):
            """Tournament pivoting over x (ref step 1): candidates are
            identified by their global position; the nomination and the
            cross-x election both run the chunked CALU tournament, so every
            LU call is height-bounded by max(panel_chunk, 2v) — the
            reference butterfly's role (`conflux_opt.hpp:220-336`)."""
            live = gp >= k * v
            cand = jnp.where(live[:, None], panel, jnp.zeros((), cdtype))
            pos_m = jnp.where(live, gp, _GRI_SENTINEL)
            # dead rows form a tile-aligned prefix (LAPACK-order layout),
            # so whole chunks die as k advances: a chunk is live iff its
            # last row's position is still active (the position of a local
            # row is a closed form, so this is a scalar compare per chunk,
            # not a gather)
            c_h, nch = blas.chunk_layout(Ml, v, panel_chunk)

            def pos_of_local(r):  # python-int local row -> global position
                return ((r // v) * Px + x) * v + (r % v)

            chunk_live = jnp.stack([
                pos_of_local(min((i + 1) * c_h, Ml) - 1) >= k * v
                for i in range(nch)
            ])
            if Px == 1:
                # single x-rank: the local nomination IS the election
                lu00, top = blas.tournament_winners(
                    cand, chunk=panel_chunk, chunk_live=chunk_live,
                    tree=tree)
                wpos = jnp.take(pos_m, top, mode="fill",
                                fill_value=_GRI_SENTINEL)
                return lu00, wpos
            _, top = blas.tournament_winners(
                cand, chunk=panel_chunk, chunk_live=chunk_live, tree=tree)
            nom = jnp.take(cand, top, axis=0, mode="fill",
                           fill_value=0)
            nid = jnp.take(pos_m, top, mode="fill",
                           fill_value=_GRI_SENTINEL)
            if election == "butterfly":
                # the reference's hypercube exchange
                # (`conflux_opt.hpp:220-336`, partner at
                # `conflux_opt.cpp:59-72`): log2(Px) ppermute rounds,
                # each reducing a pair-ordered (2v, v) stack — only v
                # rows ever cross the interconnect per round, vs the
                # all_gather's Px*v. The ordering/replication invariant
                # lives in `butterfly_allreduce`, which also handles
                # non-power-of-two Px (overflow-rank fold/unfold — the
                # SPMD form of the reference's odd-grid compensating
                # sends, `conflux_opt.hpp:266-280`).
                # lu00 rides the tuple so the final round's packed
                # factor comes out replicated with the winners.
                # ZERO-FILL CONTRACT (butterfly_allreduce): on odd-Px
                # folds every rank runs this reducer, and off-subcube
                # lanes receive ppermute's zero fill — an all-zero
                # stack and ids=0. tournament_winners on zeros is
                # well-defined garbage (getrf of 0 = 0, finite, no
                # NaN/Inf), and the garbage lanes are discarded by the
                # coordinate selects inside butterfly_allreduce. Keep
                # it that way: never gather by the received ids or
                # branch on the values here — only select-by-winner on
                # the local stack (tests/test_ops.py pins this with
                # the real reducers at odd Px).
                def reduce_pair(top, bot):
                    stack = jnp.concatenate([top[0], bot[0]], axis=0)
                    ids = jnp.concatenate([top[1], bot[1]])
                    lu00_, wid = blas.tournament_winners(
                        stack, chunk=min(panel_chunk,
                                         blas.batched_call_rows(v, cdtype)))
                    return (jnp.take(stack, wid, axis=0, mode="fill",
                                     fill_value=0),
                            jnp.take(ids, wid, mode="fill",
                                     fill_value=_GRI_SENTINEL),
                            lu00_)

                nom, nid, lu00 = butterfly_allreduce(
                    (nom, nid, jnp.zeros((v, v), cdtype)), Px, AXIS_X,
                    reduce_pair)
                return lu00, nid
            blks = lax.all_gather(nom, AXIS_X)  # (Px, v, v)
            poss = lax.all_gather(nid, AXIS_X)  # (Px, v)
            flat = blks.reshape(Px * v, v)
            # the election tournament is batched (no liveness
            # structure), so its chunk stays within the batched
            # VMEM-safe bound
            lu00, wid = blas.tournament_winners(
                flat, chunk=min(panel_chunk,
                                blas.batched_call_rows(v, cdtype)),
                tree=tree)
            # winners' positions in pivot order — replicated on
            # every device, no broadcast needed
            wpos = jnp.take(poss.reshape(Px * v), wid, mode="fill",
                            fill_value=_GRI_SENTINEL)
            return lu00, wpos

        def body_core(k, Aloc, orig, panel, lu00, wpos):
            j_owner = k % Py
            lj = ((k // Py) * v).astype(jnp.int32)
            i_owner = k % Px
            li = ((k // Px) * v).astype(jnp.int32)
            i0 = jnp.zeros((), jnp.int32)
            z0 = z == 0
            U00 = jnp.triu(lu00)
            L00 = blas.unit_lower(lu00)

            # ---- LAPACK-style row swaps (ref push_pivots_up, step 2) ----- #
            # winners move into the step's diagonal block (positions
            # k*v..(k+1)*v); the non-winner occupants move to the slots
            # vacated by external winners (i-th displaced occupant -> i-th
            # vacated position, both ascending — a canonical matching).
            # The writes are a (Ml,)-indexed row scatter. XLA lowers it to
            # a serial per-row loop (~10 ms/step at v=1024) — a round-2
            # attempt to fold the writes into the step-6 segments as
            # gather+selects was REVERTED: on a real v5e it was ~30%
            # slower and silently produced garbage factors at N=32768
            # (residual 29 vs 2.9e-05; correct on CPU at every tested
            # size and on TPU at N<=16384, valid perm, bounded factor
            # magnitudes — an XLA TPU miscompile at 4 GiB operands is the
            # best available explanation; see docs/DESIGN.md §14).
            with jax.named_scope("step2_pivotrows"):
                slots = k * v + jnp.arange(v, dtype=jnp.int32)
                occ_is_winner = (wpos[None, :] == slots[:, None]).any(1)
                is_ext = wpos >= (k + 1) * v
                # ascending order of the external winners' positions by
                # comparison ranking — a (v, v) compare + tiny scatter; a
                # jnp.sort here costs ~13 ms/step on TPU (bitonic)
                both = is_ext[None, :] & is_ext[:, None]
                rank = jnp.sum(both & (wpos[None, :] < wpos[:, None]),
                               axis=1).astype(jnp.int32)
                ext_sorted = jnp.full((v,), _GRI_SENTINEL, jnp.int32).at[
                    jnp.where(is_ext, rank, v)
                ].set(wpos, mode="drop")
                disp_rank = jnp.cumsum((~occ_is_winner).astype(jnp.int32)) - 1
                dest_disp = jnp.where(~occ_is_winner, ext_sorted[disp_rank],
                                      _GRI_SENTINEL)

                # winners' full rows + ids, reduced over (x, z) (ref step 3)
                wloc = loc_of(wpos)
                Prows = lax.psum(
                    jnp.take(Aloc, wloc, axis=0, mode="fill", fill_value=0),
                    (AXIS_X, AXIS_Z))  # (v, Nl)
                worig = lax.psum(
                    jnp.take(orig, wloc, mode="fill", fill_value=0), AXIS_X)
                # displaced occupants' full rows + ids + panel rows
                own_d = x == i_owner
                Drows = lax.psum(
                    jnp.where(own_d,
                              lax.dynamic_slice(Aloc, (li, i0), (v, Nl)),
                              jnp.zeros((), dtype)),
                    (AXIS_X, AXIS_Z))  # (v, Nl)
                dorig = lax.psum(
                    jnp.where(own_d, lax.dynamic_slice(orig, (li,), (v,)), 0),
                    AXIS_X)
                diag_panel = lax.psum(
                    jnp.where(own_d,
                              lax.dynamic_slice(panel, (li, i0), (v, v)),
                              jnp.zeros((), cdtype)),
                    AXIS_X)  # (v, v)

                # swap writes: vacated positions get the displaced rows now
                # (they stay active and take the trailing update); diagonal
                # rows are fully rewritten after the GEMM. Swapped rows
                # carry their z-summed value on layer 0, zeros elsewhere.
                didx = loc_of(dest_disp)
                disp_vals = jnp.where(z0, Drows.astype(dtype),
                                      jnp.zeros((), dtype))
                # XLA's per-row scatter loop (~10 ms/step at v=1024,
                # N=32768 — the "other" phase-table bucket). A pipelined
                # Pallas row-DMA alternative existed rounds 3-4 behind
                # swap='dma' but was deleted unadopted per the
                # pre-decided criterion (docs/ROUND3.md #3: hardware A/B
                # or deletion — the chip never recovered to run it; see
                # docs/ROUND4.md); git history has the kernel.
                Aloc = Aloc.at[didx].set(disp_vals, mode="drop")
                orig = jnp.where(
                    own_d, lax.dynamic_update_slice(orig, worig, (li,)), orig)
                orig = orig.at[didx].set(dorig, mode="drop")
                # the panel after the swap, for the L10 solve. Only the
                # displaced rows matter: the diagonal rows (winners) are
                # masked out of the TRSM by row_live, so their panel values
                # are never written back here.
                panel_post = panel.at[didx].set(diag_panel, mode="drop")

            # ---- L10 for the live row suffix (ref step 4 TRSM) ----------- #
            row_live = rtile > k  # whole tiles: diag tile k is done now
            # segment liveness as SCALAR tile-index compares: liveness is
            # monotone in the local tile index (LAPACK-order rows,
            # block-cyclic columns), so "any row/col of the segment live"
            # == "its last row/col's tile is still trailing" — a bool
            # vector .any() here costs ~1 ms/step in reduce fusions
            def seg_r_live(rhi):
                return ((rhi - 1) // v) * Px + x > k

            def seg_c_live(chi):
                return ((chi - 1) // v) * Py + y > k

            with jax.named_scope("step4_dtrsm"):
                pieces = []
                for rlo, rhi in row_segs:
                    rm = row_live[rlo:rhi]
                    pieces.append(lax.cond(
                        seg_r_live(rhi),
                        lambda p, m: blas.trsm_right_upper(
                            U00, jnp.where(m[:, None], p,
                                           jnp.zeros((), cdtype))),
                        lambda p, m: jnp.zeros_like(p),
                        panel_post[rlo:rhi], rm,
                    ))
                L10 = (jnp.concatenate(pieces, axis=0)
                       if len(pieces) > 1 else pieces[0])  # (Ml, v)

            # ---- U01 on the live column suffix (ref step 5 TRSM) --------- #
            col_trail = ctile > k  # (Nl,)
            Prows_c = Prows.astype(cdtype)
            with jax.named_scope("step5_dtrsm"):
                pieces = []
                for clo, chi in col_segs:
                    pieces.append(lax.cond(
                        seg_c_live(chi),
                        lambda p: blas.trsm_left_lower_unit(L00, p),
                        # pvary matches the solve branch's varying axes
                        # (L00 varies over x) for the cond output type
                        lambda p: pvary(p, (AXIS_X,)),
                        Prows_c[:, clo:chi],
                    ))
                U01 = (jnp.concatenate(pieces, axis=1)
                       if len(pieces) > 1 else pieces[0])  # (v, Nl)

            # ---- trailing update on this layer's slab (ref step 6) ------- #
            # GEMM rides the storage dtype (bf16 fast path when selected);
            # the (row-suffix x col-suffix) live block is covered by
            # row x col segments, dead blocks skipped via lax.cond
            L10p = jnp.pad(L10.astype(dtype), ((0, 0), (0, v_pad - v)))
            U01p = jnp.pad(U01.astype(dtype), ((0, v_pad - v), (0, 0)))
            zoff = (z * nlayr).astype(jnp.int32)
            L10s = lax.dynamic_slice(L10p, (i0, zoff), (Ml, nlayr))
            U01s = lax.dynamic_slice(U01p, (zoff, i0), (nlayr, Nl))

            with jax.named_scope("step6_dgemm"):
                if update == "block":
                    # ONE live-suffix block per step instead of the
                    # row x col segment lattice: a lax.switch over the
                    # (row, col) segment-boundary pair containing the
                    # first live row/col selects a branch with STATIC
                    # slice offsets — one slice + one GEMM + one mask +
                    # one DUS, eliminating the per-segment cond/DUS/
                    # select overhead (~9 ms/step of the N=32768 phase
                    # table). Flop overshoot identical to the segment
                    # scheme at the same `segs` (up to one segment of
                    # dead rows/cols rides the GEMM, masked out of the
                    # subtract). Composition note: under lookahead the
                    # carried slab GEMM mirrors operands but not the
                    # wide GEMM's SHAPE, so block+lookahead is value-
                    # equivalent (same pivots, f32-noise factors), not
                    # bitwise like segments+lookahead.
                    def br(args, ri=0, cj=0):
                        A, L10s_, U01s_ = args
                        a = lax.slice(A, (ri, cj), (Ml, Nl))
                        upd = blas.gemm(L10s_[ri:], U01s_[:, cj:],
                                        precision=precision,
                                        backend=backend)
                        keep = (row_live[ri:, None]
                                & col_trail[None, cj:])
                        new = a - jnp.where(keep, upd,
                                            jnp.zeros((), dtype))
                        return lax.dynamic_update_slice(A, new, (ri, cj))

                    branches = [
                        functools.partial(br, ri=rlo, cj=clo)
                        for rlo, _ in row_segs for clo, _ in col_segs
                    ]
                    # first live local row: tiles with rtile <= k are
                    # dead (LAPACK-order prefix)
                    ndead_t = jnp.where(x <= k, (k - x) // Px + 1, 0)
                    first_live = ndead_t * v
                    # first trailing local col: tiles with ctile > k
                    lt0 = jnp.where(y > k, 0, (k - y) // Py + 1)
                    first_col = lt0 * v
                    # index of the segment CONTAINING the boundary =
                    # (# starts <= boundary) - 1; a fully-dead axis
                    # clamps to the last segment (its mask is all-False)
                    ri_idx = sum(
                        (jnp.asarray(rlo) <= first_live).astype(jnp.int32)
                        for rlo, _ in row_segs) - 1
                    cj_idx = sum(
                        (jnp.asarray(clo) <= first_col).astype(jnp.int32)
                        for clo, _ in col_segs) - 1
                    Anew = lax.switch(ri_idx * len(col_segs) + cj_idx,
                                      branches, (Aloc, L10s, U01s))
                else:
                    # in-place cond'd DUS per live segment: a slice->
                    # concat formulation materializes the full local
                    # matrix every step (~26 ms/step of pure copies at
                    # N=32768)
                    Anew = Aloc
                    for rlo, rhi in row_segs:
                        rm = row_live[rlo:rhi]
                        for clo, chi in col_segs:
                            cm = col_trail[clo:chi]

                            def seg_update(A, rlo=rlo, rhi=rhi, clo=clo,
                                           chi=chi, rm=rm, cm=cm):
                                a_seg = lax.slice(A, (rlo, clo), (rhi, chi))
                                upd = blas.gemm(
                                    L10s[rlo:rhi], U01s[:, clo:chi],
                                    precision=precision, backend=backend)
                                keep = rm[:, None] & cm[None, :]
                                new = a_seg - jnp.where(keep, upd,
                                                        jnp.zeros((), dtype))
                                return lax.dynamic_update_slice(A, new,
                                                                (rlo, clo))

                            Anew = lax.cond(
                                seg_r_live(rhi) & seg_c_live(chi),
                                seg_update, lambda A: A, Anew)

            # ---- factor writes (z==0 carries factors, z!=0 zeroed) ------- #
            # diagonal block rows: leading columns keep the winners' frozen
            # L prefix (they ride along in Prows), trailing columns take
            # U01; the panel tile itself is overwritten with packed lu00 by
            # the panel-column write below
            with jax.named_scope("step7_writes"):
                drow_vals = jnp.where(col_trail[None, :], U01.astype(dtype),
                                      Prows.astype(dtype))
                Anew = jnp.where(
                    own_d,
                    lax.dynamic_update_slice(
                        Anew, jnp.where(z0, drow_vals, jnp.zeros((), dtype)),
                        (li, i0)),
                    Anew)
                # panel column: packed LU00 on the diagonal rows, L10 on live
                # rows, untouched on frozen rows; zeroed on z != 0 layers
                pcol_cur = lax.dynamic_slice(Anew, (i0, lj), (Ml, v))
                pcol_new = jnp.where(row_live[:, None], L10.astype(dtype),
                                     pcol_cur)
                pcol_new = jnp.where(
                    own_d,
                    lax.dynamic_update_slice(pcol_new, lu00.astype(dtype),
                                             (li, i0)),
                    pcol_new)
                pcol_new = jnp.where(z0, pcol_new, jnp.zeros((), dtype))
                Anew = jnp.where(
                    y == j_owner,
                    lax.dynamic_update_slice(Anew, pcol_new, (i0, lj)),
                    Anew,
                )
            # A_sw = the post-swap, pre-update matrix: the lookahead body
            # recomputes next step's panel slab from it
            art = dict(A_sw=Aloc, L10s=L10s, U01s=U01s, U01=U01,
                       row_live=row_live, own_d=own_d, li=li, z0=z0)
            return Anew, orig, art

        def body(k, carry):
            Aloc, orig = carry
            with jax.named_scope("step0_reduce"):
                panel = panel_reduce(Aloc, k)
            with jax.named_scope("step1_pivoting"):
                lu00, wpos = elect(panel, k)
            Anew, orig, _ = body_core(k, Aloc, orig, panel, lu00, wpos)
            return Anew, orig

        def body_la(k, carry):
            # software-pipelined (lookahead) body: the panel and election
            # for step k arrive in the carry; step k+1's panel is computed
            # from a separately-updated column slab of the PRE-update
            # matrix, so its election collectives have no data dependence
            # on the trailing GEMMs and XLA's scheduler can overlap them on
            # a mesh (the reference's P8 MPI_Waitany overlap). Slab math
            # mirrors the segment updates operand-for-operand, so carried
            # panels are bitwise identical to recomputed ones.
            Aloc, orig, panel, lu00, wpos = carry
            Anew, orig, art = body_core(k, Aloc, orig, panel, lu00, wpos)
            kn = k + 1
            i0 = jnp.zeros((), jnp.int32)

            def compute_next(_):
                with jax.named_scope("step0_reduce"):
                    j1 = kn % Py
                    lj1 = ((kn // Py) * v).astype(jnp.int32)
                    slab = lax.dynamic_slice(art["A_sw"], (i0, lj1), (Ml, v))
                    upd = blas.gemm(art["L10s"],
                                    lax.dynamic_slice(art["U01s"],
                                                      (i0, lj1),
                                                      (nlayr, v)),
                                    precision=precision, backend=backend)
                    slab = slab - jnp.where(art["row_live"][:, None], upd,
                                            jnp.zeros((), dtype))
                    u01_slab = lax.dynamic_slice(art["U01"], (i0, lj1),
                                                 (v, v)).astype(dtype)
                    slab = jnp.where(
                        art["own_d"],
                        lax.dynamic_update_slice(
                            slab, jnp.where(art["z0"], u01_slab,
                                            jnp.zeros((), dtype)),
                            (art["li"], i0)),
                        slab)
                    panel_next = lax.psum(
                        jnp.where(y == j1, slab, jnp.zeros((), dtype)),
                        (AXIS_Y, AXIS_Z)).astype(cdtype)
                with jax.named_scope("step1_pivoting"):
                    lu00n, wposn = elect(panel_next, kn)
                return panel_next, lu00n, wposn

            # the last iteration has no next step: skip the dangling
            # election (a whole superstep's collectives + tournament)
            panel_next, lu00n, wposn = lax.cond(
                kn < k_end, compute_next, lambda _: (panel, lu00, wpos), 0)
            return Anew, orig, panel_next, lu00n, wposn

        if lookahead:
            with jax.named_scope("step0_reduce"):
                panel0 = panel_reduce(Aloc, k0)
            with jax.named_scope("step1_pivoting"):
                lu000, wpos0 = elect(panel0, k0)
            Aloc, orig, _, _, _ = lax.fori_loop(
                k0, k_end, body_la, (Aloc, orig0, panel0, lu000, wpos0))
        else:
            Aloc, orig = lax.fori_loop(k0, k_end, body, (Aloc, orig0))
        # all factors live on layer 0; psum makes the output z-replicated
        Aout = lax.psum(Aloc, AXIS_Z)
        # assemble the permutation: original row id at every global position
        perm = jnp.zeros((Mcap,), jnp.int32).at[gp].set(orig)
        perm = lax.psum(perm, AXIS_X)
        # identical on every device already; pmax re-establishes replication
        # for the out_spec
        perm = lax.pmax(perm, (AXIS_Y, AXIS_Z))
        if orig_blk is None:
            return Aout[None, None], perm
        # resumable form: the row-origin state rides along (replicated over
        # y/z by pmax — every y/z holds the same x-row's state)
        orig_out = lax.pmax(orig, (AXIS_Y, AXIS_Z))
        return Aout[None, None], orig_out[None], perm

    shard_spec = P(AXIS_X, AXIS_Y, None, None)
    if resumable:
        in_specs = (shard_spec, P(AXIS_X, None), P(), P())
        out_specs = (shard_spec, P(AXIS_X, None), P())
    else:
        in_specs, out_specs = shard_spec, (shard_spec, P())
    fn = shard_map(device_fn, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs)
    return jax.jit(fn, donate_argnums=(0,) if donate else ())



def build_program(geom: LUGeometry, mesh, precision=None,
                  backend: str | None = None, panel_chunk: int | None = None,
                  donate: bool = False, resumable: bool = False,
                  lookahead: bool = False, election: str = "gather",
                  segs: tuple = (16, 16), tree: str = "pairwise",
                  update: str = "segments", dtype=None):
    """The jitted distributed-LU program itself (cached per config).

    The single point resolving the trace-time defaults (precision/backend/
    panel_chunk, CPU donate guard); `lu_factor_distributed` goes through
    here too. Direct use is for callers that need the compile artifacts —
    e.g. the miniapp's `--profile`, which joins an XPlane trace with the
    optimized HLO's named-scope metadata (`profiler.phase_table`) to print
    the per-phase device-time table. Such callers should pass the input
    `dtype` they will run with: the panel_chunk default and the
    tree='flat' VMEM guard resolve chunk ceilings with its compute dtype
    (f64 halves the safe call heights vs the f32 default), so a
    dtype-blind build can cache a different program than the one the
    entry points time — or pass a flat-stack height the chip cannot
    compile.
    """
    precision = blas.matmul_precision() if precision is None else precision
    backend = blas.get_backend() if backend is None else backend
    cdtype = blas.compute_dtype(jnp.dtype(dtype)) if dtype is not None \
        else jnp.float32
    if panel_chunk is None:
        panel_chunk = blas.single_call_rows(geom.v, cdtype)
    if donate and next(iter(mesh.devices.flat)).platform == "cpu":
        donate = False  # CPU PJRT has no buffer donation (warns per call)
    if election not in ("gather", "butterfly"):
        raise ValueError(f"unknown election {election!r} (gather|butterfly)")
    if len(segs) != 2 or segs[0] < 1 or segs[1] < 1:
        raise ValueError(
            f"segs must be two positive segment counts, got {segs!r} "
            "(non-positive counts would silently skip trailing updates)")
    if tree not in ("pairwise", "flat"):
        raise ValueError(f"unknown tree {tree!r} (pairwise|flat)")
    if tree == "flat":
        # the flat election is ONE (nch*v, v) LU custom call per
        # tournament; keep every such stack within the derived
        # single-call VMEM-safe height (blas.single_call_rows — v5e pin
        # 8192 rows at v=1024; 16384 fails to compile). Two tournaments
        # can go flat: the local nomination over Ml rows, and (gather
        # election, Px > 1) the cross-x election over the Px*v nominee
        # panel, whose chunk is additionally capped at the batched bound
        # (the elect() call site). Butterfly's pair reductions are 2v
        # tall — single-chunk at any legal v, never a flat stack.
        v = geom.v
        stacks = []
        _, nch = blas.chunk_layout(geom.Ml, v, panel_chunk)
        if nch > 1:
            stacks.append(nch * v)
        if geom.grid.Px > 1 and election == "gather":
            # same compute-dtype ceiling the traced election uses (the
            # elect() call site) — an f32-blind guard would admit f64
            # flat stacks twice the true single-call-safe height
            _, nch2 = blas.chunk_layout(
                geom.grid.Px * v, v,
                min(panel_chunk, blas.batched_call_rows(v, cdtype)))
            if nch2 > 1:
                stacks.append(nch2 * v)
        if stacks and max(stacks) > blas.single_call_rows(v, cdtype):
            raise ValueError(
                f"tree='flat' would stack {max(stacks)} nominee rows of "
                f"width {v} in one LU call (> the "
                f"{blas.single_call_rows(v, cdtype)}-row VMEM-safe height "
                f"for {jnp.dtype(cdtype).name}); "
                "raise panel_chunk or use tree='pairwise'")
    if update not in ("segments", "block"):
        raise ValueError(f"unknown update {update!r} (segments|block)")
    return _build(geom, mesh_cache_key(mesh), precision, backend,
                  panel_chunk, donate, resumable, lookahead, election,
                  tuple(segs), tree, update)


def lu_factor_distributed(shards, geom: LUGeometry, mesh,
                          precision=None, backend: str | None = None,
                          panel_chunk: int | None = None,
                          donate: bool = False, lookahead: bool = False,
                          election: str = "gather", segs: tuple = (16, 16),
                          tree: str = "pairwise",
                          update: str = "segments"):
    """Factor block-cyclic shards (Px, Py, Ml, Nl) in place on a mesh.

    Returns (shards_out, perm): shards_out holds the packed factors in
    *pivoted row order* (LAPACK getrf layout — global position p holds the
    factor row of original row perm[p], so gathered(shards_out) == the
    packed LU of A[perm]); perm is (M,) int32, replicated. Rows eliminated
    at step k occupy positions k*v..(k+1)*v, so perm[:n_steps*v] reshaped
    to (n_steps, v) is the elimination record (the old `pivots` output).

    Rank-deficient inputs: supersteps whose candidates are exactly zero
    elect no valid rows, leaving that block's perm entries unspecified and
    its factor rows garbage (the getrf `info > 0` situation); everything
    eliminated before the degeneracy is correct and frozen.

    `panel_chunk` bounds the height of every LU call inside the pivot
    election (default: `blas.single_call_rows(v)` — the derived
    single-call VMEM-safe height, 8192 on a v5e at v=1024, safe for the
    unbatched cond'd nomination calls; the batched election stack is
    additionally capped at `blas.batched_call_rows(v)`).
    `donate=True` aliases the input shards into the output (the caller's
    array is invalidated) — at N=32768 f32 on a 16 GB chip this saves the
    4 GB that makes the difference between fitting and OOM.
    `lookahead=True` selects the software-pipelined loop: the next step's
    panel reduce + pivot election are dataflow-independent of the current
    trailing GEMMs, letting XLA overlap the election collectives with
    compute on a mesh (P8; bitwise-identical results, ~one extra
    (Ml, v)-slab GEMM per superstep of redundant work).
    `tree` shapes the election's reduction ('pairwise' binary tree vs
    'flat' single stacked LU — fewer sequential latency-bound custom
    calls; see `ops.blas.tournament_winners`). Both are valid CALU
    elections; pivot choices can differ on ties, so results are
    comparable by residual, not bitwise.
    (An experimental `swap='dma'` Pallas row-DMA alternative to the XLA
    displacement scatter existed rounds 3-4; it was deleted unadopted
    per the pre-decided hardware-A/B-or-delete criterion when the chip
    stayed unreachable — docs/ROUND4.md. Git history has the kernel and
    its staged probe protocol.)
    """
    from conflux_tpu.geometry import check_shards

    shards = jnp.asarray(shards)
    check_shards(shards, geom)
    # the default panel_chunk resolves inside build_program from the
    # compute dtype — ONE resolution point, so a retune cannot
    # desynchronize the entry paths from the --profile build
    fn = build_program(geom, mesh, precision=precision, backend=backend,
                       panel_chunk=panel_chunk, donate=donate,
                       lookahead=lookahead, election=election,
                       segs=segs, tree=tree, update=update,
                       dtype=shards.dtype)
    return fn(shards)


def lu_factor_steps(shards, geom: LUGeometry, mesh, k0: int, k1: int,
                    orig=None, precision=None, backend: str | None = None,
                    panel_chunk: int | None = None, donate: bool = False,
                    election: str = "gather", segs: tuple = (16, 16),
                    tree: str = "pairwise", update: str = "segments"):
    """Factor supersteps [k0, k1) only — the checkpoint/restart primitive.

    The reference has no notion of resuming a partial factorization
    (SURVEY §5: any rank failure kills the job and the work); here the
    mid-factorization state is first-class because the matrix lives in
    LAPACK-order positions: after k steps, global positions < k*v hold
    frozen factor rows and the rest is the updated trailing problem.

    State = (shards, orig): `orig` is the (Px, Ml) row-origin map
    (original row id at each local position). Pass orig=None when k0 == 0
    (rows start in original order); feed each call's outputs to the next.
    Both arrays are plain host-saveable values (`io.save_matrix` works on
    gathered shards), so a long factorization can checkpoint every few
    supersteps and restart after preemption — run the same call sequence
    with the loaded state.

    Returns (shards_out, orig_out, perm). perm is only the FINAL
    permutation once k1 == geom.n_steps; at intermediate k1 its entries
    beyond position k1*v still name unfactored rows.

    Bitwise caveat: the state output consolidates the 2.5D z-partial sums
    into one z-replicated copy (that is what makes the checkpoint compact
    — one matrix, not Pz layers). With Pz > 1 a resumed run therefore
    re-associates those sums and is numerically equivalent to, but not
    bit-identical with, the uninterrupted factorization (f32 rounding at
    partial-sum granularity). Pz == 1 round-trips exactly.
    """
    if not (0 <= k0 < k1 <= geom.n_steps):
        raise ValueError(
            f"step range [{k0}, {k1}) outside [0, {geom.n_steps})")
    if orig is None:
        if k0 != 0:
            raise ValueError("resuming at k0 > 0 requires the orig state "
                             "returned by the previous lu_factor_steps call")
        # rows start in original order: origin == global row index (the
        # same gri map the geometry exposes)
        orig = jnp.asarray(geom.global_row_index(), jnp.int32)
    # the step bounds are traced scalars: every segment of a checkpointed
    # run reuses ONE compiled program. `segs` rides through so a resumed
    # run keeps the tuned segmentation (math-invariant, perf-only);
    # `tree` rides through because trees may elect different winners on
    # ties — a resume must keep the uninterrupted run's pivot bracket.
    # The default chunk resolves inside build_program with the same
    # compute dtype as lu_factor_distributed's: a dtype-blind default
    # here would chunk a resumed f64 run differently from the run it
    # resumes (different nomination bracket -> different pivots).
    fn = build_program(geom, mesh, precision=precision, backend=backend,
                       panel_chunk=panel_chunk, donate=donate,
                       resumable=True, election=election, segs=segs,
                       tree=tree, update=update,
                       dtype=jnp.asarray(shards).dtype)
    return fn(shards, orig, jnp.int32(k0), jnp.int32(k1))


def lu_distributed_host(A: np.ndarray, grid: Grid3, v: int, mesh=None,
                        precision=None, backend: str | None = None,
                        panel_chunk: int | None = None,
                        segs: tuple = (16, 16), tree: str = "pairwise",
                        update: str = "segments"):
    """Host-level convenience: scatter a global matrix, factor on the mesh,
    gather back. Returns (LU_packed (M, N) in original row order, perm (M,)).

    The role of the reference's `lu_params` + `LU_rep` + validation-gather
    pipeline (`examples/conflux_miniapp.cpp:92-167`) in one call.
    """
    geom = LUGeometry.create(A.shape[0], A.shape[1], v, grid)
    if mesh is None:
        mesh = make_mesh(grid)
    shards = geom.scatter(A)
    # the device shards are a single-use temp: donate them so the jitted
    # program aliases input into output (frees a full matrix of HBM)
    out, perm = lu_factor_distributed(
        jnp.asarray(shards), geom, mesh, precision=precision, backend=backend,
        panel_chunk=panel_chunk, donate=True, segs=segs, tree=tree,
        update=update,
    )
    perm = np.asarray(perm)
    LUp = geom.gather(np.asarray(out))  # factors in pivoted order
    LU = np.empty_like(LUp)
    LU[perm] = LUp  # back to original row order (LU[perm] == L@U packing)
    return LU, perm, geom


def full_permutation(pivots: np.ndarray, M: int) -> np.ndarray:
    """Elimination order -> row permutation of length M.

    pivots is (n_steps, v) global row indices; rows never chosen (only when
    M > N) are appended in ascending order as pure-L rows.
    """
    order = pivots.reshape(-1)
    if order.size < M:
        rest = np.setdiff1d(np.arange(M), order)
        order = np.concatenate([order, rest])
    return order
