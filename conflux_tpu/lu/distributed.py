"""Distributed LU with tournament pivoting over the (x, y, z) mesh.

TPU-native re-design of the reference's `LU_rep` superstep loop
(`conflux_opt.hpp:343-1827`). The reference is host-orchestrated SPMD: each
MPI rank owns block-cyclic tiles, physically compacts pivot rows upward
(`push_pivots_up`, `conflux_opt.hpp:176-218`), and moves panels with
Reduce/Iscatterv/Sendrecv. Here the whole factorization is ONE jitted
`shard_map` program with a `lax.fori_loop` over supersteps; all shapes are
static, rows never move, and pivoting is *value-level*:

 - "active rows" (reference P6 row compaction) -> a boolean `done` mask;
 - rotating owner roles (P5) -> `axis_index` comparisons inside the loop;
 - the z-layer 2.5D replication (P3) -> each device holds a *partial sum*
   shard; sum over the z axis is the true matrix. Panel reads are `psum`s
   over ('y','z'); factor writes land on layer z==0 only;
 - tournament pivoting (P4) -> local panel LU selects v candidate rows,
   `all_gather` over 'x' + one stacked LU elects the winners (the butterfly's
   fixed point, computed identically on every device so no broadcast of the
   result is needed);
 - pivot-row reduction + distribution (reference steps 2-3, Igatherv/Isend
   mesh) -> one `psum` over ('x','z') of a v-row gather;
 - the trailing update (step 6) runs on each device's nlayr = v/Pz slab of
   the panel, so z layers share the O(N^2 v) GEMM flops exactly like the
   reference's 2.5D scheme.

Per superstep: 3 collectives (panel psum, candidate all_gather, pivot-row
psum), two small duplicated factorizations (local panel LU, stacked LU), two
duplicated v-row TRSMs, and (Ml x nlayr) @ (nlayr x seg) MXU GEMMs over the
live column segments — the local width is cut into up to 8 segments and
fully-factored segments are skipped via `lax.cond`, keeping total GEMM work
near the true 2/3 N^3 / P instead of the 3x a full-width masked update
would spend.

Factors are stored LAPACK-packed *in original row positions*; `pivots` gives
the global row index factored at each (step, slot), from which the row
permutation is reconstructed (see `full_permutation`).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from conflux_tpu.geometry import Grid3, LUGeometry, ragged_segments
from conflux_tpu.ops import blas
from conflux_tpu.parallel.mesh import (
    AXIS_X,
    AXIS_Y,
    AXIS_Z,
    lookup_mesh,
    make_mesh,
    mesh_cache_key,
)

_GRI_SENTINEL = np.iinfo(np.int32).max


@functools.lru_cache(maxsize=32)
def _build(geom: LUGeometry, mesh_key, precision, backend: str,
           panel_chunk: int, donate: bool = False):
    mesh = lookup_mesh(mesh_key)
    v = geom.v
    Px, Py, Pz = geom.grid.Px, geom.grid.Py, geom.grid.Pz
    Ml, Nl = geom.Ml, geom.Nl
    nlayr = geom.nlayr
    n_steps = geom.n_steps
    v_pad = Pz * nlayr  # inner dim padded so every z layer gets a full slab
    # trailing-update segmentation: up to 8 ragged segments bound the flop
    # overshoot at one segment width per superstep
    seg_bounds = ragged_segments(geom.Ntl, v, 8)

    def device_fn(blk):
        x = lax.axis_index(AXIS_X)
        y = lax.axis_index(AXIS_Y)
        z = lax.axis_index(AXIS_Z)
        dtype = blk.dtype

        # z-partial invariant: sum over z == true matrix; data enters on z=0
        Aloc = jnp.where(z == 0, blk[0, 0], jnp.zeros((), dtype))

        lr = jnp.arange(Ml, dtype=jnp.int32)
        gri = ((lr // v) * Px + x) * v + (lr % v)  # global row id per local row
        lc = jnp.arange(Nl, dtype=jnp.int32)
        ctile = (lc // v) * Py + y  # global col-tile id per local col

        done0 = lax.pcast(jnp.zeros((Ml,), bool), (AXIS_X, AXIS_Y, AXIS_Z), to='varying')
        piv0 = lax.pcast(jnp.zeros((n_steps, v), jnp.int32), (AXIS_X, AXIS_Y, AXIS_Z), to='varying')

        def body(k, carry):
            Aloc, done, pivrec = carry
            j_owner = k % Py
            lj = (k // Py) * v  # local col offset of panel tile on owner

            # ---- panel: z-reduce + y-broadcast in one psum (ref step 0) --- #
            with jax.named_scope("step0_reduce"):
                i0 = jnp.zeros((), jnp.int32)
                lj = lj.astype(jnp.int32)
                panel_loc = lax.dynamic_slice(Aloc, (i0, lj), (Ml, v))
                panel = lax.psum(
                    jnp.where(y == j_owner, panel_loc, jnp.zeros((), dtype)),
                    (AXIS_Y, AXIS_Z),
                )

            # ---- tournament pivoting over x (ref step 1) ------------------ #
            # panel math runs in the compute dtype (f32 when storage is bf16)
            with jax.named_scope("step1_pivoting"):
                cdtype = blas.compute_dtype(dtype)
                panel = panel.astype(cdtype)
                cand = jnp.where(done[:, None], jnp.zeros((), cdtype), panel)
                gri_m = jnp.where(done, _GRI_SENTINEL, gri)
                # local nomination: chunked tournament (CALU) — every LU call
                # is height-bounded by max(panel_chunk, 2v), never the raw
                # (Ml, v), which overflows the TPU LU custom call's scoped
                # VMEM once Ml reaches ~16384 (see ops/blas._PANEL_CHUNK)
                _, top = blas.tournament_winners(cand, chunk=panel_chunk)
                nom = jnp.take(cand, top, axis=0, mode="fill", fill_value=0)
                nid = jnp.take(gri_m, top, mode="fill",
                               fill_value=_GRI_SENTINEL)
                blks = lax.all_gather(nom, AXIS_X)  # (Px, v, v)
                gris = lax.all_gather(nid, AXIS_X)  # (Px, v)
                # election: the same chunked reduction tree over the Px·v
                # gathered nominees (log-depth stacks of (2v, v) LUs, the
                # role of the reference butterfly `tournament_rounds`,
                # conflux_opt.hpp:220-336) — computed identically on every
                # device, so the result needs no broadcast
                lu00, wid = blas.tournament_winners(
                    blks.reshape(Px * v, v), chunk=panel_chunk
                )
                gpiv = jnp.take(gris.reshape(Px * v), wid, mode="fill",
                                fill_value=_GRI_SENTINEL)
                U00 = jnp.triu(lu00)
                L00 = blas.unit_lower(lu00)

            # ---- pivot masks (ref g2lnoTile/analyze_pivots) --------------- #
            with jax.named_scope("step2_pivotrows"):
                match = gri[:, None] == gpiv[None, :]  # (Ml, v)
                is_piv = match.any(axis=1)
                done_new = done | is_piv

            # ---- L10 for all still-active rows (ref step 4 TRSM) ---------- #
            with jax.named_scope("step4_dtrsm"):
                act_panel = jnp.where(done_new[:, None], jnp.zeros((), cdtype), panel)
                L10 = blas.trsm_right_upper(U00, act_panel)  # (Ml, v)

            # ---- pivot rows: gather + reduce over (x, z) (ref steps 2-3) -- #
            with jax.named_scope("step3_distribute"):
                owned = match.any(axis=0)  # (v,) is pivot q local?
                li = jnp.argmax(match, axis=0)  # (v,) its local row
                prow_part = jnp.where(owned[:, None], Aloc[li], jnp.zeros((), dtype))
                Prows = lax.psum(prow_part, (AXIS_X, AXIS_Z))  # (v, Nl)
            with jax.named_scope("step5_dtrsm"):
                U01 = blas.trsm_left_lower_unit(L00, Prows.astype(cdtype))  # ref step 5

            # ---- trailing update on this layer's slab (ref step 6) -------- #
            # GEMM rides the storage dtype (bf16 fast path when selected)
            L10p = jnp.pad(L10.astype(dtype), ((0, 0), (0, v_pad - v)))
            U01p = jnp.pad(U01.astype(dtype), ((0, v_pad - v), (0, 0)))
            L10s = lax.dynamic_slice(L10p, (i0, (z * nlayr).astype(jnp.int32)), (Ml, nlayr))
            U01s = lax.dynamic_slice(U01p, ((z * nlayr).astype(jnp.int32), i0), (nlayr, Nl))
            col_trail = ctile > k  # (Nl,)
            # Static shapes force a full-local-width GEMM every superstep,
            # which would spend 3x the optimal 2/3 N^3/P flops. Local column
            # tiles finish in ascending local order (tile lt has global tile
            # id lt*Py + y), so the live region is a contiguous suffix: cut
            # the width into segments and skip fully-finished ones with
            # lax.cond — flop waste drops to <= segw extra columns per step.
            def seg_update(a_seg, u_seg, m_seg):
                upd = blas.gemm(L10s, u_seg, precision=precision, backend=backend)
                return a_seg - jnp.where(m_seg[None, :], upd, jnp.zeros((), dtype))

            with jax.named_scope("step6_dgemm"):
                pieces = []
                for lo, hi in seg_bounds:
                    sl = slice(lo, hi)
                    pieces.append(lax.cond(
                        col_trail[sl].any(), seg_update, lambda a, u, mm: a,
                        Aloc[:, sl], U01s[:, sl], col_trail[sl],
                    ))
                Anew = (jnp.concatenate(pieces, axis=1)
                        if len(pieces) > 1 else pieces[0])

            # ---- factor writes (z==0 carries factors, z!=0 zeroed) -------- #
            # v-row scatters, not (Ml, Nl) gathers/selects: `U01[piv_pos]`
            # materializes a full-matrix temp per step, which OOMs HBM at
            # N=32768 on one chip (2 x 4 GB temps); scattering the v pivot
            # rows in place costs (v, Nl) instead
            z0 = z == 0
            li_safe = jnp.where(owned, li, Ml)  # unowned slots drop
            cur_rows = jnp.take(Anew, li_safe, axis=0, mode="fill",
                                fill_value=0)  # (v, Nl)
            urow = jnp.where(z0, U01.astype(dtype), jnp.zeros((), dtype))
            new_rows = jnp.where(col_trail[None, :], urow, cur_rows)
            Anew = Anew.at[li_safe].set(new_rows, mode="drop")
            # panel column: packed LU00 on pivot rows, L10 on active rows,
            # untouched on earlier-done rows
            pcol_cur = lax.dynamic_slice(Anew, (i0, lj), (Ml, v))
            pcol_new = jnp.where(done[:, None], pcol_cur, L10.astype(dtype))
            pcol_new = pcol_new.at[li_safe].set(lu00.astype(dtype),
                                                mode="drop")
            pcol_new = jnp.where(z0, pcol_new, jnp.zeros((), dtype))
            Anew = jnp.where(
                y == j_owner,
                lax.dynamic_update_slice(Anew, pcol_new, (i0, lj)),
                Anew,
            )

            pivrec = lax.dynamic_update_slice(
                pivrec, gpiv.astype(jnp.int32)[None], (jnp.asarray(k, jnp.int32), i0)
            )
            return Anew, done_new, pivrec

        Aloc, done, pivrec = lax.fori_loop(0, n_steps, body, (Aloc, done0, piv0))
        # all factors live on layer 0; psum makes the output z-replicated
        Aout = lax.psum(Aloc, AXIS_Z)
        # pivrec is numerically identical on every device (it comes from
        # collectives); pmax re-establishes replication for the out_spec
        pivrec = lax.pmax(pivrec, (AXIS_X, AXIS_Y, AXIS_Z))
        return Aout[None, None], pivrec

    fn = jax.shard_map(
        device_fn,
        mesh=mesh,
        in_specs=P(AXIS_X, AXIS_Y, None, None),
        out_specs=(P(AXIS_X, AXIS_Y, None, None), P()),
    )
    return jax.jit(fn, donate_argnums=(0,) if donate else ())



def lu_factor_distributed(shards, geom: LUGeometry, mesh,
                          precision=None, backend: str | None = None,
                          panel_chunk: int | None = None,
                          donate: bool = False):
    """Factor block-cyclic shards (Px, Py, Ml, Nl) in place on a mesh.

    Returns (shards_out, pivots) where pivots is (n_steps, v) global row
    indices in elimination order. `panel_chunk` bounds the height of every
    LU call inside the pivot election (default: ops/blas's measured TPU
    VMEM-safe chunk). `donate=True` aliases the input shards into the
    output (the caller's array is invalidated) — at N=32768 f32 on a 16 GB
    chip this saves the 4 GB that makes the difference between fitting and
    OOM.
    """
    precision = blas.matmul_precision() if precision is None else precision
    backend = blas.get_backend() if backend is None else backend
    if panel_chunk is None:
        panel_chunk = blas._PANEL_CHUNK
    if donate and next(iter(mesh.devices.flat)).platform == "cpu":
        donate = False  # CPU PJRT has no buffer donation (warns per call)
    fn = _build(geom, mesh_cache_key(mesh), precision, backend, panel_chunk,
                donate)
    return fn(shards)


def lu_distributed_host(A: np.ndarray, grid: Grid3, v: int, mesh=None,
                        precision=None, backend: str | None = None,
                        panel_chunk: int | None = None):
    """Host-level convenience: scatter a global matrix, factor on the mesh,
    gather back. Returns (LU_packed (M, N) in original row order, perm (M,)).

    The role of the reference's `lu_params` + `LU_rep` + validation-gather
    pipeline (`examples/conflux_miniapp.cpp:92-167`) in one call.
    """
    geom = LUGeometry.create(A.shape[0], A.shape[1], v, grid)
    if mesh is None:
        mesh = make_mesh(grid)
    shards = geom.scatter(A)
    # the device shards are a single-use temp: donate them so the jitted
    # program aliases input into output (frees a full matrix of HBM)
    out, pivots = lu_factor_distributed(
        jnp.asarray(shards), geom, mesh, precision=precision, backend=backend,
        panel_chunk=panel_chunk, donate=True,
    )
    LU = geom.gather(np.asarray(out))
    perm = full_permutation(np.asarray(pivots), geom.M)
    return LU, perm, geom


def full_permutation(pivots: np.ndarray, M: int) -> np.ndarray:
    """Elimination order -> row permutation of length M.

    pivots is (n_steps, v) global row indices; rows never chosen (only when
    M > N) are appended in ascending order as pure-L rows.
    """
    order = pivots.reshape(-1)
    if order.size < M:
        rest = np.setdiff1d(np.arange(M), order)
        order = np.concatenate([order, rest])
    return order
