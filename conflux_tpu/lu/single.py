"""Single-device blocked right-looking LU with partial pivoting.

This is the minimum end-to-end slice (SURVEY.md §7 step 2): the same
superstep structure as the reference's `LU_rep` (`conflux_opt.hpp:343-1827`)
collapsed onto a 1x1x1 grid — panel factorization, row pivoting, two TRSMs,
trailing GEMM — expressed as one jittable XLA program. Tiles stay HBM-resident
for the whole factorization; each superstep's trailing update is a single
large MXU matmul.

The number of supersteps Nt = N/v is a static Python value, so the loop
unrolls at trace time with *exact* shapes (no masking overhead): total flops
are the true 2/3 N^3. For very large Nt (where the unrolled program gets
expensive to compile) run the distributed implementation on a 1x1x1 grid —
it is a single `fori_loop` body with static-shape masking, compiling in
O(1) steps (see conflux_tpu/lu/distributed.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from conflux_tpu.ops import blas
from conflux_tpu.ops.permute import swap_minimal_perm

# Largest M that uses swap-minimal row placement (see the strategy comment
# inside _lu_factor_blocked); module-level so tests can exercise both paths.
_SWAP_SCATTER_MAX = 16384


def lu_factor_blocked(A: jax.Array, v: int, precision=None, backend: str | None = None):
    """Factor A (M x N, M >= N, both multiples of v) as P A = L U.

    Returns (LU, perm):
      LU   — (M, N) packed factors: strictly-lower part of column-block k
             holds L, upper part holds U (LAPACK getrf layout).
      perm — (M,) row indices such that A[perm, :] == L @ U.
    """
    M, N = A.shape
    if M % v or N % v:
        raise ValueError(f"shape {A.shape} not a multiple of tile size {v}")
    if M < N:
        raise ValueError("lu_factor_blocked requires M >= N")
    # resolve config outside jit so it lands in the jit cache key
    precision = blas.matmul_precision() if precision is None else precision
    backend = blas.get_backend() if backend is None else backend
    return _lu_factor_blocked(A, v, precision, backend, blas.get_panel_algo())


@functools.partial(
    jax.jit, static_argnames=("v", "precision", "backend", "panel_algo")
)
def _lu_factor_blocked(A: jax.Array, v: int, precision, backend: str,
                       panel_algo: str = "auto"):
    M, N = A.shape
    n_steps = N // v

    perm = jnp.arange(M)

    cdtype = blas.compute_dtype(A.dtype)
    # Row placement strategy. LAPACK semantics move at most 2v rows per
    # superstep, so scattering just the changed slots (swap-minimal) avoids
    # the O(m*N) trailing-block gather — measured 374 -> 343 ms at N=16384
    # on a v5e. Above that size the dynamic-index row scatter's lowering and
    # aliasing copies cost more than the gathers they replace (2330 vs
    # 2247 ms at N=32768, plus worker OOM crashes at v=2048), so large
    # problems keep the full-gather formulation.
    swap_minimal = M <= _SWAP_SCATTER_MAX
    for k in range(n_steps):
        off = k * v
        m = M - off
        # --- pivot election (reference step 1) ---------------------------- #
        # panel math in the compute dtype (f32 when storage is bf16)
        panel = A[off:, off : off + v].astype(cdtype)
        if swap_minimal:
            lu00, gpiv = blas.panel_winners(panel, algo=panel_algo)
            sperm = swap_minimal_perm(gpiv, m)
            nsel = min(2 * v, m)
            moved = jnp.argsort(jnp.where(sperm != jnp.arange(m), 0, 1),
                                stable=True)[:nsel]
            # gather straight from A with absolute row ids (slicing A[off:]
            # first materializes a full trailing-block copy)
            A = A.at[off + moved, :].set(A[off + sperm[moved], :])
            perm = perm.at[off:].set(perm[off:][sperm])
            A = A.at[off : off + v, off : off + v].set(lu00.astype(A.dtype))
            U00 = jnp.triu(lu00)
            if m > v:
                # --- L10 TRSM (reference step 4) -------------------------- #
                L10 = blas.trsm_right_upper(
                    U00, A[off + v :, off : off + v].astype(cdtype)
                )
                A = A.at[off + v :, off : off + v].set(L10.astype(A.dtype))
        else:
            lu_panel, pperm = blas.panel_lu(panel, algo=panel_algo)
            lu00 = lu_panel[:v]
            A = A.at[off:, :].set(A[off:, :][pperm])
            perm = perm.at[off:].set(perm[off:][pperm])
            A = A.at[off:, off : off + v].set(lu_panel.astype(A.dtype))
            L10 = lu_panel[v:, :]
        if off + v < N:
            # --- A01 TRSM (reference step 5) ------------------------------ #
            L00 = blas.unit_lower(lu00)
            A01 = blas.trsm_left_lower_unit(
                L00, A[off : off + v, off + v :].astype(cdtype)
            ).astype(A.dtype)
            A = A.at[off : off + v, off + v :].set(A01)
            # --- trailing GEMM (reference step 6, the hot op) ------------- #
            A = A.at[off + v :, off + v :].set(
                blas.gemm(L10.astype(A.dtype), A01,
                          c=A[off + v :, off + v :], alpha=-1.0,
                          precision=precision, backend=backend)
            )

    return A, perm


def unpack_lu(LU: jax.Array):
    """Split packed factors into (L (M, N) unit-lower, U (N, N) upper)."""
    M, N = LU.shape
    L = jnp.tril(LU, -1)[:, :N]
    L = L.at[:N, :].add(jnp.eye(N, dtype=LU.dtype))
    U = jnp.triu(LU[:N, :])
    return L, U
