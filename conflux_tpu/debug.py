"""Debug-build assertions — the reference's -DDEBUG in-situ checks.

The reference guards its superstep loop with NaN/Inf scans
(`has_valid_data`, `memory_utils.hpp:37-49`, used at
`conflux_opt.hpp:592-601`), post-tournament non-zero-pivot asserts
(`conflux_opt.hpp:793-800`), and a global row-count conservation check via
MPI_Allgather (`conflux_opt.hpp:980-1000`). Here the same checks are
host-side helpers over gathered results plus a jit-compatible checify layer.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp


def has_valid_data(x) -> bool:
    """NaN/Inf-free scan (reference `memory_utils.hpp:37-49`)."""
    return bool(np.isfinite(np.asarray(x)).all())


def assert_valid(x, what: str = "buffer") -> None:
    if not has_valid_data(x):
        bad = int((~np.isfinite(np.asarray(x))).sum())
        raise FloatingPointError(f"{what} contains {bad} non-finite values")


def assert_nonzero_pivots(LU, what: str = "LU") -> None:
    """Post-factorization zero-pivot check (reference
    `conflux_opt.hpp:793-800`)."""
    d = np.abs(np.diag(np.asarray(LU)))
    if (d == 0).any():
        k = int(np.argmin(d != 0))
        raise ZeroDivisionError(f"{what}: zero pivot at position {k}")


def assert_pivot_conservation(pivots, M: int) -> None:
    """Every row is eliminated exactly once (the row-count conservation
    check, reference `conflux_opt.hpp:980-1000`)."""
    p = np.asarray(pivots).reshape(-1)
    uniq = np.unique(p)
    if uniq.size != p.size:
        raise AssertionError(f"duplicate pivot rows: {p.size - uniq.size}")
    if p.min() < 0 or p.max() >= M:
        raise AssertionError(f"pivot row out of range [0, {M}): {p.min()}..{p.max()}")


def checked_isfinite(x: jax.Array, what: str) -> jax.Array:
    """jit-compatible in-graph check: returns x, raising at runtime via
    jax.debug callbacks when non-finite values appear (debug builds only)."""
    def _cb(ok):
        if not bool(ok):
            raise FloatingPointError(f"{what}: non-finite values inside jit")

    ok = jnp.isfinite(x).all()
    jax.debug.callback(_cb, ok)
    return x
