"""Shared CLI plumbing for the miniapps."""

from __future__ import annotations

import argparse
import time

import numpy as np

import jax


def add_common_args(p: argparse.ArgumentParser) -> None:
    p.add_argument(
        "--platform", default=None, choices=["cpu", "tpu", "axon"],
        help="force a JAX platform (default: whatever the environment gives); "
        "'cpu' also enables --devices simulated host devices",
    )
    p.add_argument(
        "--devices", type=int, default=8,
        help="simulated device count when --platform cpu (default 8)",
    )
    p.add_argument(
        "--dtype", default="float32", choices=["float32", "float64", "bfloat16"],
        help="element type (float64 requires a CPU platform: the TPU LU "
        "custom call is f32-only)",
    )
    p.add_argument("--profile", action="store_true", help="print region timings")


def positive_int(text: str) -> int:
    """argparse type for counts that must be >= 1."""
    try:
        n = int(text)
    except ValueError:
        n = 0
    if n < 1:
        raise argparse.ArgumentTypeError(
            f"expected a positive integer, got {text!r}")
    return n


def segs_arg(text: str) -> tuple[int, int]:
    """argparse type for --segs RxC (e.g. '16x16'): two positive ints."""
    r, sep, c = text.lower().partition("x")
    try:
        segs = (int(r), int(c))
    except ValueError:
        segs = None
    if not sep or segs is None or segs[0] < 1 or segs[1] < 1:
        raise argparse.ArgumentTypeError(
            f"expected RxC with positive integers (e.g. 16x16), got {text!r}")
    return segs


def setup_platform(args) -> None:
    """Must run before any JAX backend initializes."""
    from conflux_tpu import cache

    # persistent XLA compile cache (conflux_tpu.cache): at-scale programs
    # cost minutes of compile; every CLI process shares the warmed cache
    cache.enable_persistent_cache()
    if args.platform == "cpu":
        import os

        os.environ["XLA_FLAGS"] = (
            f"--xla_force_host_platform_device_count={args.devices} "
            + os.environ.get("XLA_FLAGS", "")
        )
        jax.config.update("jax_platforms", "cpu")
    elif args.platform in ("tpu", "axon"):
        pass  # the environment default
    if args.dtype == "float64" or getattr(args, "refine", None) is not None:
        # --refine computes its residuals in f64 (O(N^2) work only;
        # software-emulated on TPU) — the HPL-MxP recipe's high half
        jax.config.update("jax_enable_x64", True)


def np_dtype(name: str):
    return {"float32": np.float32, "float64": np.float64, "bfloat16": np.float32}[name]


def sync(x) -> float:
    """Block until x is truly materialized (through-tunnel safe) and return
    a checksum — `block_until_ready` alone does not guarantee completion on
    tunneled platforms. A reduction (not ravel/indexing) so it works on
    arrays sharded over a mesh."""
    return float(jax.numpy.sum(x))


def refine_report(solve_fn, A_host, out_dtype, sweeps: int) -> float:
    """Shared --refine epilogue of the miniapps: solve A x = 1 with
    `sweeps` classic-IR rounds (f64 residuals — the HPL-MxP recipe),
    print the `_solve_residual_` line, return the relative residual.
    The residual is measured against the matrix actually factored, in
    its own dtype; corrections ride the factors' compute dtype."""
    import jax.numpy as jnp

    from conflux_tpu import solvers
    from conflux_tpu.ops import blas

    n = A_host.shape[0]
    b = jnp.ones((n,), A_host.dtype)
    Adev = jnp.asarray(A_host)
    corr_dtype = blas.compute_dtype(jnp.dtype(out_dtype))
    x = solvers.refine_classic(solve_fn, Adev, b, sweeps, jnp.float64,
                               corr_dtype)
    r = solvers._residual_strips(Adev, x, b.astype(jnp.float64),
                                 jnp.float64)
    rel = float(jnp.linalg.norm(r) / jnp.linalg.norm(b.astype(jnp.float64)))
    flag = "PASS" if rel <= 1e-6 else "----"
    print(f"_solve_residual_ refine={sweeps} rel={rel:.3e} [{flag} <=1e-6]")
    return rel


class WallTimer:
    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.ms = (time.perf_counter() - self.t0) * 1e3


def phase_profile(program, dev) -> None:
    """Per-phase device-time table for a distributed hot loop (the
    reference's per-step semiprof table, `README.md:120-165`): one extra
    run under `jax.profiler.trace`, joined with the compiled program's
    named-scope metadata by `profiler.phase_table`."""
    import tempfile

    from conflux_tpu import profiler

    comp = program.lower(dev).compile()
    with tempfile.TemporaryDirectory(prefix="conflux-phases-") as trace_dir:
        with profiler.trace(trace_dir):
            out = comp(dev)
            sync(out[0] if isinstance(out, tuple) else out)
        try:
            profiler.phase_table(trace_dir, comp.as_text())
        except (ImportError, FileNotFoundError, ValueError) as e:
            # CPU runs have no device plane; the proto reader needs the
            # baked tensorflow package — the host-region report still prints
            print(f"(no device phase table: {e})")


def add_experiment_type_arg(p) -> None:
    """The reference's -t vocabulary (`examples/conflux_miniapp.cpp:63-66`)."""
    p.add_argument(
        "-t", "--type", default="weak", choices=["weak", "strong"],
        help="experiment type: sets the reported N_base (reference "
        "convention: N / int(sqrt(P)) for weak scaling, N for strong)",
    )


def result_line(algo: str, N: int, P: int, grid, exp_type: str,
                ms: float, v: int, dtype: str) -> str:
    """Reference line shape (`examples/conflux_miniapp.cpp:136-165`):
    `_result_ <algo>,<impl>,<N>,<N_base>,<P>,<grid>,time,<weak|strong>,<ms>,<v>`
    with N_base = N // int(sqrt(P)) under weak scaling (the reference
    truncates the sqrt — NOT a rounded float division), and the dtype
    appended as an 11th field fixed-width parsers ignore."""
    import math

    n_base = N // math.isqrt(P) if exp_type == "weak" else N
    return (f"_result_ {algo},conflux_tpu,{N},{n_base},{P},"
            f"{grid},time,{exp_type},{ms:.3f},{v},{dtype}")


def add_auto_arg(p: argparse.ArgumentParser) -> None:
    p.add_argument(
        "--auto", action="store_true",
        help="resolve tuning knobs you did not pass from the measured "
        "dispatch table (conflux_tpu.autotune — the role of the "
        "reference's hand-measured variant switch, Cholesky.cpp:857-921); "
        "prints the applied knobs and the measurement they came from. Any "
        "explicitly passed flag pins its knob, even at the library "
        "default value",
    )


def apply_auto(args, algo: str, N: int, P: int, dtype: str,
               flag_knobs: dict) -> None:
    """--auto resolution: for every (args attribute -> (knob name, library
    default)) in `flag_knobs`, an un-passed flag is replaced by the
    measured recommendation's knob (None knobs never overwrite).

    Auto-eligible flags are declared with a `default=None` SENTINEL, so
    "un-passed" is detected as `is None` — an explicitly passed flag
    always pins its knob, even when the passed value equals the library
    default (ADVICE r4 #1: `--auto --election gather` must run gather).
    Callers must follow apply_auto with resolve_knob_defaults(), which
    fills any attribute still None with its library default.

    With an empty `flag_knobs` (a mode with nothing auto-tunable) the
    dispatch table is not consulted and a distinct line says so
    (ADVICE r4 #4 — "(all knobs pinned)" would misreport).

    Prints `_auto_` lines (knobs + provenance) in the miniapp protocol
    style: one space-free key=value token per knob (tuples in the RxC
    grammar), so whitespace-splitting sweep parsers stay correct."""
    if not flag_knobs:
        print("_auto_ (no auto-tunable knobs for this mode)")
        return
    from conflux_tpu import autotune

    rec = autotune.recommended(algo, N, P=P, dtype=str(dtype))

    def fmt(v):
        # one token vocabulary for sweep parsers: tuples in the RxC
        # grammar, bools as on/off (the tune-log grammar and
        # apply_flip_criteria vocabulary — a Python bool repr here would
        # hand parsers a second spelling of the same knob state; bool
        # check first, bool is an int subclass)
        if isinstance(v, bool):
            return "on" if v else "off"
        return "x".join(map(str, v)) if isinstance(v, tuple) else v

    applied = []
    for attr, (knob, _default) in flag_knobs.items():
        if getattr(args, attr) is None and rec.knobs.get(knob) is not None:
            setattr(args, attr, rec.knobs[knob])
            applied.append(f"{attr}={fmt(rec.knobs[knob])}")
    print(f"_auto_ {' '.join(applied) if applied else '(all knobs pinned)'}")
    print(f"_auto_provenance_ {rec.provenance}")


def resolve_knob_defaults(args, flag_knobs: dict) -> None:
    """Fill every auto-eligible attribute still at its None sentinel with
    its library default — run after apply_auto (or instead of it when
    --auto is off). Kept separate so apply_auto can tell "un-passed"
    from "explicitly passed at the default value"."""
    for attr, (_knob, default) in flag_knobs.items():
        if getattr(args, attr) is None:
            setattr(args, attr, default)
