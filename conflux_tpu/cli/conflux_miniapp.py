"""LU miniapp — the role of `examples/conflux_miniapp.cpp`.

Same CLI vocabulary (-N, -b, --p_grid, -r) and the same machine-parsable
result protocol (`examples/conflux_miniapp.cpp:119,156-165`):

    _result_ lu,conflux_tpu,<N>,<N_base>,<P>,<PxxPyxPz>,time,<type>,<ms>,<v>

plus an optional --validate residual check (the CONFLUX_WITH_VALIDATION
equivalent, computed directly instead of via ScaLAPACK pdgemm).

Examples:
    python -m conflux_tpu.cli.conflux_miniapp -N 2048 -b 128 -r 2
    python -m conflux_tpu.cli.conflux_miniapp -N 512 -b 64 --p_grid 2,2,2 \
        --platform cpu --devices 8 --dtype float64 --validate
"""

from __future__ import annotations

import argparse

import numpy as np

from conflux_tpu.cli.common import (
    WallTimer,
    add_auto_arg,
    add_common_args,
    add_experiment_type_arg,
    apply_auto,
    np_dtype,
    resolve_knob_defaults,
    result_line,
    segs_arg,
    setup_platform,
    sync,
)


def parse_args(argv=None):
    p = argparse.ArgumentParser("conflux_miniapp", description=__doc__)
    p.add_argument("-M", type=int, default=None, help="rows (default: N)")
    p.add_argument("-N", type=int, default=2048, help="matrix dimension")
    p.add_argument("-b", "--block_size", type=int, default=None,
                   help="tile size v (default 128; un-passed = "
                   "auto-eligible under --auto)")
    p.add_argument(
        "--p_grid", default=None,
        help="Px,Py,Pz (default: auto-pick over all available devices)",
    )
    p.add_argument("-r", "--n_rep", type=int, default=2, help="timed repetitions")
    p.add_argument(
        "-l", "--print_limit", type=int, default=30,
        help="print the input matrix and packed factors when max(M, N) is "
        "below this limit (the reference's debug aid, "
        "`examples/conflux_miniapp.cpp:57,86`)",
    )
    p.add_argument("--validate", action="store_true", help="residual ||PA-LU||_F check")
    p.add_argument(
        "--lookahead", action="store_true", default=None,
        help="software-pipelined loop: overlap the next step's pivot "
        "election with the trailing update (multi-chip meshes; P8)",
    )
    p.add_argument(
        "--election", default=None, choices=["gather", "butterfly"],
        help="cross-x pivot election (default gather): one all_gather "
        "tournament, or the reference's log2(Px) ppermute hypercube "
        "(any Px; odd grids fold their overflow ranks with two extra "
        "rounds)",
    )
    p.add_argument(
        "--segs", default=None, metavar="RxC", type=segs_arg,
        help="trailing-update row x col segment counts, e.g. 16x16 "
        "(default: tuned library value); finer cuts dead-region flop "
        "overshoot at the cost of more per-step conds",
    )
    p.add_argument(
        "--tree", default=None, choices=["pairwise", "flat"],
        help="pivot election reduction (default pairwise): pairwise "
        "binary tree, or one stacked LU call (fewer sequential "
        "latency-bound custom calls)",
    )
    p.add_argument(
        "--update", default=None, choices=["segments", "block"],
        help="trailing-update partitioning (default segments): cond'd "
        "segment lattice, or one switch-selected live-suffix block per "
        "step",
    )
    p.add_argument(
        "--refine", type=int, default=None, metavar="K",
        help="after factoring, solve A x = 1 with K iterative-refinement "
        "sweeps (f64 residual — the HPL-MxP recipe; pairs with --dtype "
        "bfloat16 for the fast-factor path) and report the solve residual",
    )
    add_auto_arg(p)
    add_experiment_type_arg(p)
    add_common_args(p)
    return p.parse_args(argv)


def main(argv=None) -> int:
    args = parse_args(argv)
    setup_platform(args)

    import jax
    import jax.numpy as jnp

    from conflux_tpu import profiler
    from conflux_tpu.geometry import Grid3, LUGeometry, choose_grid
    from conflux_tpu.lu.distributed import lu_factor_distributed
    from conflux_tpu.parallel.mesh import make_mesh
    from conflux_tpu.validation import (
        lu_residual,
        lu_residual_distributed,
        make_test_matrix,
    )

    M = args.M or args.N
    n_devices = len(jax.devices())
    grid = Grid3.parse(args.p_grid) if args.p_grid else choose_grid(n_devices, M, args.N)
    if grid.P > n_devices:
        raise SystemExit(f"grid {grid} needs {grid.P} devices, have {n_devices}")

    # auto-eligible knobs: parser sentinel None = un-passed (an explicit
    # flag always pins its knob, even at the library default value)
    knob_map = {
        "block_size": ("v", 128),
        "election": ("election", "gather"),
        "segs": ("segs", None),
        "tree": ("tree", "pairwise"),
        "update": ("update", "segments"),
        "lookahead": ("lookahead", False),
    }
    if args.auto:
        apply_auto(args, "lu", args.N, grid.P, args.dtype, knob_map)
    resolve_knob_defaults(args, knob_map)

    dtype = np_dtype(args.dtype)
    geom = LUGeometry.create(M, args.N, args.block_size, grid)
    if args.refine is not None:
        # fail in milliseconds, not after the timed O(N^3) factor reps
        if args.refine < 0:
            raise SystemExit("--refine needs a sweep count >= 0")
        if geom.M != geom.N:
            raise SystemExit("--refine needs a square system")

    # Dedicated single-device path: exact shrinking shapes per superstep
    # (true 2/3 N^3 flops) instead of the masked fixed-shape distributed
    # program. It unrolls the superstep loop at trace time, so cap the step
    # count — beyond that the distributed program on a 1x1x1 mesh compiles
    # in O(1) (see conflux_tpu/lu/single.py docstring).
    single = grid.P == 1 and geom.n_steps <= 64
    mesh = None if single else make_mesh(grid, devices=jax.devices()[: grid.P])
    seg_kw = {} if args.segs is None else {"segs": args.segs}
    with profiler.region("init_matrix"):
        A = make_test_matrix(geom.M, geom.N, dtype=dtype)
        dev = jnp.asarray(A) if single else jnp.asarray(geom.scatter(A))
        if args.dtype == "bfloat16":
            dev = dev.astype(jnp.bfloat16)
        sync(dev)

    times = []
    for rep in range(args.n_rep + 1):  # rep 0 is the mandatory warm-up
        with WallTimer() as t:
            with profiler.region("lu_factorization"):
                if single:
                    from conflux_tpu.lu.single import lu_factor_blocked

                    out, perm_dev = lu_factor_blocked(dev, v=geom.v)
                else:
                    out, perm_dev = lu_factor_distributed(
                        dev, geom, mesh, lookahead=args.lookahead,
                        election=args.election, tree=args.tree,
                        update=args.update, **seg_kw)
                sync(out)
        if rep > 0:
            times.append(t.ms)

    for ms in times:
        print(result_line("lu", geom.N, grid.P, grid, args.type, ms, geom.v,
                          args.dtype))

    if max(geom.M, geom.N) < args.print_limit:
        # the reference's print_full_matrices debug aid
        np.set_printoptions(precision=4, suppress=True, linewidth=200)
        print("input matrix:")
        print(np.asarray(A))
        LUp = (np.asarray(out) if single
               else geom.gather(np.asarray(out)))
        print("packed LU factors (pivoted row order):")
        print(LUp)
        print("perm:", np.asarray(perm_dev).tolist())

    if args.validate:
        with profiler.region("validation"):
            if single:
                LU_perm = np.asarray(out)
                perm = np.asarray(perm_dev)
                res = lu_residual(np.asarray(A, np.float64), LU_perm, perm)
            else:
                # gather-free on-mesh oracle (the reference's ScaLAPACK
                # pdgemm validation role): nothing (M, N)-sized leaves the
                # mesh; `dev` still holds the original shards (the timed
                # runs do not donate them)
                res = lu_residual_distributed(dev, out, perm_dev, geom, mesh)
        print(f"_residual_ {res:.3e}")

    if args.refine is not None:
        # HPL-MxP demonstration on the factors just computed: solve
        # A x = 1 and refine with f64 residuals (O(N^2) per sweep). The
        # reference's accuracy story is all-f64 factors
        # (`src/conflux/lu/blas.cpp:15-123`); the TPU-native answer is
        # cheap factors + refinement to the same <=1e-6 solve bar.
        from conflux_tpu import solvers
        from conflux_tpu.cli.common import refine_report

        with profiler.region("refine_solve"):
            if single:
                def solve(r):
                    return solvers.lu_solve(out, perm_dev, r)
            else:
                def solve(r):
                    return solvers.lu_solve_distributed(
                        out, perm_dev, geom, mesh, r)
            refine_report(solve, A, jnp.asarray(out).dtype, args.refine)

    if args.profile:
        if not single:
            from conflux_tpu.cli.common import phase_profile
            from conflux_tpu.lu.distributed import build_program

            # dtype rides along so the profiled program IS the cached one
            # just timed (the panel_chunk default + flat-tree guard are
            # compute-dtype-resolved; a dtype-blind build would profile a
            # different program under --dtype float64)
            phase_profile(
                build_program(geom, mesh, lookahead=args.lookahead,
                              election=args.election, tree=args.tree,
                              update=args.update,
                              dtype=dtype, **seg_kw), dev)
        profiler.report()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
