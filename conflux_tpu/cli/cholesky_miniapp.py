"""Cholesky miniapp — the role of `examples/cholesky_miniapp.cpp`.

Same CLI vocabulary (--dim, --tile, --grid, --run) and a printTimings-style
report (`examples/cholesky_miniapp.cpp:34-50`), plus the `_result_` line
protocol for machine parsing.

Examples:
    python -m conflux_tpu.cli.cholesky_miniapp --dim 2048 --tile 128 --run 2
    python -m conflux_tpu.cli.cholesky_miniapp --dim 512 --tile 64 \
        --grid 2,2,2 --platform cpu --validate
"""

from __future__ import annotations

import argparse

import numpy as np

from conflux_tpu.cli.common import (
    WallTimer,
    add_auto_arg,
    add_common_args,
    add_experiment_type_arg,
    apply_auto,
    np_dtype,
    resolve_knob_defaults,
    result_line,
    segs_arg,
    setup_platform,
    sync,
)


def parse_args(argv=None):
    p = argparse.ArgumentParser("cholesky_miniapp", description=__doc__)
    p.add_argument("--dim", type=int, default=2048, help="matrix dimension N")
    p.add_argument("--tile", type=int, default=None, help="tile size v (default: heuristic)")
    p.add_argument("--grid", default=None, help="Px,Py,Pz (default: auto)")
    p.add_argument("--run", type=int, default=2, help="timed repetitions")
    p.add_argument("--validate", action="store_true", help="residual ||A-LL^T||_F check")
    p.add_argument(
        "--refine", type=int, default=None, metavar="K",
        help="after factoring, solve A x = 1 with K iterative-refinement "
        "sweeps (f64 residual — the HPL-MxP recipe; pairs with --dtype "
        "bfloat16) and report the solve residual",
    )
    p.add_argument(
        "--lookahead", action="store_true", default=None,
        help="software-pipelined loop: overlap the next panel reduce "
        "with the trailing update (multi-chip meshes; P8)",
    )
    p.add_argument(
        "--segs", default=None, metavar="RxC", type=segs_arg,
        help="trailing-update row x col segment counts, e.g. 8x8 "
        "(default: tuned library value)",
    )
    add_auto_arg(p)
    add_experiment_type_arg(p)
    add_common_args(p)
    return p.parse_args(argv)


def main(argv=None) -> int:
    args = parse_args(argv)
    setup_platform(args)

    import jax
    import jax.numpy as jnp

    from conflux_tpu import profiler
    from conflux_tpu.cholesky.distributed import cholesky_factor_distributed
    from conflux_tpu.geometry import (
        CholeskyGeometry,
        Grid3,
        choose_cholesky_grid,
        choose_cholesky_tile,
    )
    from conflux_tpu.parallel.mesh import make_mesh
    from conflux_tpu.validation import (
        cholesky_residual,
        cholesky_residual_distributed,
        make_spd_matrix,
    )

    n_devices = len(jax.devices())
    grid = Grid3.parse(args.grid) if args.grid else choose_cholesky_grid(n_devices)
    if grid.P > n_devices:
        raise SystemExit(f"grid {grid} needs {grid.P} devices, have {n_devices}")
    knob_map = {
        "tile": ("v", None),
        "segs": ("segs", None),
        "lookahead": ("lookahead", False),
    }
    if args.auto:
        apply_auto(args, "cholesky", args.dim, grid.P, args.dtype, knob_map)
    resolve_knob_defaults(args, knob_map)
    v = args.tile or choose_cholesky_tile(args.dim, grid.P)

    dtype = np_dtype(args.dtype)
    geom = CholeskyGeometry.create(args.dim, v, grid)
    if args.refine is not None and args.refine < 0:
        # fail in milliseconds, not after the timed O(N^3) factor reps
        raise SystemExit("--refine needs a sweep count >= 0")

    # dedicated single-device path (true 1/3 N^3 flops); it unrolls Kappa
    # supersteps at trace time, so fall back to the distributed program (O(1)
    # compile on a 1x1x1 mesh) for very deep factorizations
    single = grid.P == 1 and geom.Kappa <= 64
    mesh = None if single else make_mesh(grid, devices=jax.devices()[: grid.P])
    seg_kw = {} if args.segs is None else {"segs": args.segs}
    with profiler.region("init_matrix"):
        A = make_spd_matrix(geom.N, dtype=dtype)
        dev = jnp.asarray(A) if single else jnp.asarray(geom.scatter(A))
        if args.dtype == "bfloat16":
            dev = dev.astype(jnp.bfloat16)
        sync(dev)

    times = []
    for rep in range(args.run + 1):
        with WallTimer() as t:
            with profiler.region("cholesky_factorization"):
                if single:
                    from conflux_tpu.cholesky.single import cholesky_blocked

                    out = cholesky_blocked(dev, v=geom.v)
                else:
                    out = cholesky_factor_distributed(
                        dev, geom, mesh, lookahead=args.lookahead, **seg_kw)
                sync(out)
        if rep > 0:
            times.append(t.ms)

    # printTimings-style block (reference cholesky_miniapp.cpp:34-50)
    print("==========================================")
    print("    PROBLEM PARAMETERS:")
    print(f"    Matrix dimension: {geom.N} (requested {args.dim})")
    print(f"    Tile size: {geom.v}")
    print(f"    Grid: {grid} on {grid.P} devices")
    print(f"    Runs: {len(times)}")
    print("    TIMINGS [ms]:")
    for ms in times:
        print(f"       {ms:.3f}")
    print("==========================================")
    # our extension (the reference cholesky_miniapp prints only the
    # timings block) — same field shape as the LU line for one parser
    for ms in times:
        print(result_line("cholesky", geom.N, grid.P, grid, args.type, ms,
                          geom.v, args.dtype))

    if args.validate:
        with profiler.region("validation"):
            if single:
                res = cholesky_residual(np.asarray(A, np.float64),
                                        np.asarray(out))
            else:
                # gather-free on-mesh oracle (pdgemm validation role):
                # nothing (N, N)-sized leaves the mesh
                res = cholesky_residual_distributed(dev, out, geom, mesh)
        print(f"_residual_ {res:.3e}")

    if args.refine is not None:
        from conflux_tpu import solvers
        from conflux_tpu.cli.common import refine_report

        with profiler.region("refine_solve"):
            if single:
                def solve(r):
                    return solvers.cholesky_solve(out, r)
            else:
                def solve(r):
                    return solvers.cholesky_solve_distributed(
                        out, geom, mesh, r)
            refine_report(solve, A, jnp.asarray(out).dtype, args.refine)

    if args.profile:
        if not single:
            from conflux_tpu.cholesky.distributed import build_program
            from conflux_tpu.cli.common import phase_profile

            phase_profile(
                build_program(geom, mesh, lookahead=args.lookahead,
                              **seg_kw), dev)
        profiler.report()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
