"""Command-line miniapps mirroring the reference drivers in `examples/`."""
