"""QR miniapp — driver for the third factorization family.

The reference ships miniapps only for its two cores (LU/Cholesky); this
driver extends the same harness vocabulary (`examples/conflux_miniapp.cpp`
flag shapes, `_result_` protocol, warm-up + timed reps) to the QR family
so the sweep/collect tooling covers all three.

Modes:
  - tall (`--cols` < rows, default): distributed TSQR or CholeskyQR2 on
    x-block rows (`--algo`);
  - general block-cyclic (`--full`): `qr_factor_distributed` on the
    (Px, Py, Pz) mesh, same superstep shape as the LU/Cholesky loops.

Examples:
    python -m conflux_tpu.cli.qr_miniapp -M 8192 --cols 256 -r 2
    python -m conflux_tpu.cli.qr_miniapp -M 1024 --cols 1024 --full \
        --p_grid 2,2,1 --platform cpu --devices 4 --validate
"""

from __future__ import annotations

import argparse

import numpy as np

from conflux_tpu.cli.common import (
    WallTimer,
    add_common_args,
    add_experiment_type_arg,
    np_dtype,
    positive_int,
    result_line,
    setup_platform,
    sync,
)


def parse_args(argv=None):
    p = argparse.ArgumentParser("qr_miniapp", description=__doc__)
    p.add_argument("-M", type=int, default=8192, help="rows")
    p.add_argument("--cols", type=int, default=256, help="columns (<= rows)")
    p.add_argument("-b", "--block", type=int, default=None,
                   help="panel width v for --full (default 256)")
    p.add_argument("--p_grid", default=None, help="Px,Py,Pz (default: auto)")
    p.add_argument("--algo", default="tsqr", choices=["tsqr", "cholesky"],
                   help="tall-mode election (QR tree vs Gram/CholeskyQR2)")
    p.add_argument("--tree", default=None, choices=["gather", "butterfly"],
                   help="tsqr cross-x reduction (default gather): one all_gather, or the "
                   "log2(Px) ppermute hypercube (any Px; odd grids fold "
                   "their overflow ranks with two extra rounds)")
    p.add_argument("--full", action="store_true",
                   help="general block-cyclic QR on the (x, y, z) mesh")
    p.add_argument("--lookahead", action="store_true", default=None,
                   help="software-pipelined --full loop: overlap the next "
                   "panel's election with the trailing update (P8; "
                   "value-equivalent results — bitwise-verified on CPU "
                   "only)")
    p.add_argument("--csegs", type=positive_int, default=None, metavar="C",
                   help="trailing-update column segment count for --full "
                   "(default: tuned library value)")
    p.add_argument("-r", "--run", type=int, default=2, help="timed reps")
    p.add_argument("--validate", action="store_true",
                   help="orthogonality + reconstruction residuals")
    from conflux_tpu.cli.common import add_auto_arg

    add_auto_arg(p)
    add_experiment_type_arg(p)
    add_common_args(p)
    return p.parse_args(argv)


def main(argv=None) -> int:
    args = parse_args(argv)
    setup_platform(args)

    import jax
    import jax.numpy as jnp

    from conflux_tpu import profiler
    from conflux_tpu.geometry import Grid3, LUGeometry, choose_grid
    from conflux_tpu.parallel.mesh import make_mesh

    if args.cols > args.M:
        raise SystemExit(f"--cols {args.cols} > rows {args.M}: QR needs M >= n")
    if args.tree not in (None, "gather") and (args.full or args.algo != "tsqr"):
        raise SystemExit(
            "--tree applies to the tall tsqr mode only (the Gram and "
            "block-cyclic paths have no cross-x R tree)")
    if args.lookahead and not args.full:
        raise SystemExit(
            "--lookahead applies to the --full block-cyclic loop only "
            "(the tall-skinny paths have no superstep loop to pipeline)")
    n_devices = len(jax.devices())
    dtype = np_dtype(args.dtype)
    rng = np.random.default_rng(42)

    # single source of truth for the auto-eligible knobs and their
    # library defaults; --auto consults a mode-gated SUBSET
    # (block/csegs/lookahead are read only by the --full loop; the
    # cross-x tree only by the tall tsqr mode — applying a knob its
    # mode rejects, or never reads, would bypass the arg validation
    # above or misreport an applied knob). apply_auto itself reports
    # the empty-subset case as "no auto-tunable knobs for this mode"
    # rather than "(all knobs pinned)".
    knob_map = {"block": ("v", None), "csegs": ("csegs", None),
                "lookahead": ("lookahead", False),
                "tree": ("tree", "gather")}
    if args.full:
        mode_knobs = {k: knob_map[k]
                      for k in ("block", "csegs", "lookahead")}
    elif args.algo == "tsqr":
        mode_knobs = {"tree": knob_map["tree"]}
    else:
        mode_knobs = {}
    if args.auto:
        from conflux_tpu.cli.common import apply_auto

        P = Grid3.parse(args.p_grid).P if args.p_grid else n_devices
        apply_auto(args, "qr", args.M, P, args.dtype, mode_knobs)
    from conflux_tpu.cli.common import resolve_knob_defaults

    # resolve the FULL sentinel set (not just this mode's): every
    # un-passed auto-eligible flag must leave parse with its library
    # default regardless of mode
    resolve_knob_defaults(args, knob_map)

    if args.full:
        from conflux_tpu.qr.distributed import qr_factor_distributed

        seg_kw = {} if args.csegs is None else {"csegs": args.csegs}
        seg_kw["lookahead"] = args.lookahead

        v = args.block or 256
        grid = (Grid3.parse(args.p_grid) if args.p_grid
                else choose_grid(n_devices, args.M, args.cols))
        if grid.P > n_devices:
            raise SystemExit(f"grid {grid} needs {grid.P} devices, have {n_devices}")
        geom = LUGeometry.create(args.M, args.cols, v, grid)
        if geom.M < geom.N:
            raise SystemExit(
                f"after grid padding the problem is {geom.M}x{geom.N} "
                f"(requested {args.M}x{args.cols}, tile {v}, grid {grid}): "
                "QR needs M >= N — raise -M or shrink the y axis")
        mesh = make_mesh(grid, devices=jax.devices()[: grid.P])
        with profiler.region("init_matrix"):
            A = rng.standard_normal((geom.M, geom.N)).astype(dtype)
            dev = jnp.asarray(geom.scatter(A))
            sync(dev)
        algo_name, N_rep, vrep = "qr", geom.N, v

        def factor():
            return qr_factor_distributed(dev, geom, mesh, **seg_kw)

    else:
        from conflux_tpu.qr.distributed import (
            cholesky_qr2_distributed,
            tsqr_distributed,
        )

        if args.p_grid:
            g = Grid3.parse(args.p_grid)
            if (g.Py, g.Pz) != (1, 1):
                raise SystemExit(
                    f"tall mode distributes rows over 'x' only; grid {g} "
                    "has Py/Pz > 1 (use --full for the 2.5D mesh)")
            Px = g.Px
        else:
            Px = n_devices
        if Px > n_devices:
            raise SystemExit(f"Px={Px} needs {Px} devices, have {n_devices}")
        grid = Grid3(Px, 1, 1)
        mesh = make_mesh(grid, devices=jax.devices()[:Px])
        Ml = -(-args.M // Px)
        with profiler.region("init_matrix"):
            # rows pad with ZEROS to a Px multiple (qr_distributed_host's
            # convention: zero rows leave R unchanged), so the factored
            # problem is exactly the requested one
            A = np.zeros((Px * Ml, args.cols), dtype)
            A[: args.M] = rng.standard_normal((args.M, args.cols))
            dev = jnp.asarray(A.reshape(Px, Ml, args.cols))
            sync(dev)
        if Px * Ml != args.M:
            print(f"rows padded {args.M} -> {Px * Ml} (zero rows)")
        # N field = row count (the quantity a tall-QR sweep scales);
        # the tile field carries the column count
        algo_name, N_rep, vrep = f"qr-{args.algo}", Px * Ml, args.cols

        def factor():
            if args.algo == "tsqr":
                return tsqr_distributed(dev, mesh, tree=args.tree)
            return cholesky_qr2_distributed(dev, mesh)

    times = []
    for rep in range(args.run + 1):
        with WallTimer() as t:
            with profiler.region("qr_factorization"):
                Qout, Rout = factor()
                sync(Qout)
        if rep > 0:
            times.append(t.ms)

    for ms in times:
        print(result_line(algo_name, N_rep, grid.P, grid, args.type, ms,
                          vrep, args.dtype))

    if args.validate:
        with profiler.region("validation"):
            if args.full:
                # gather-free on-mesh oracle (pdgemm validation role):
                # nothing (M, N)-sized leaves the mesh
                from conflux_tpu.validation import qr_residual_distributed

                rec, orth = qr_residual_distributed(dev, Qout, Rout,
                                                    geom, mesh)
            else:
                Q = np.asarray(Qout).reshape(-1, args.cols)
                R = np.asarray(Rout)
                n = Q.shape[1]
                orth = np.linalg.norm(Q.T @ Q - np.eye(n)) / np.sqrt(n)
                rec = (np.linalg.norm(Q @ R - A.reshape(Q.shape[0], -1))
                       / max(np.linalg.norm(A), 1e-30))
        print(f"_residual_ orth={orth:.3e} reconstruction={rec:.3e}")

    if args.profile:
        if args.full:
            # per-phase device table of the one-jit loop (qr_* scopes),
            # same machinery as the LU/Cholesky miniapps
            from conflux_tpu.cli.common import phase_profile
            from conflux_tpu.qr.distributed import build_program

            # dtype rides along so the chunk default resolves like the
            # timed run's (see lu miniapp --profile note)
            phase_profile(build_program(geom, mesh, dtype=dtype, **seg_kw),
                          dev)
        profiler.report()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
