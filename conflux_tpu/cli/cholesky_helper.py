"""Offline SPD matrix generator / result comparator — the role of the
reference's `examples/cholesky_helper.cpp` (binary input/result files for
very large N, produced once and reused across benchmark runs) and of
`python/compare_res.py` (norm-based comparison of a computed result against
a reference result file).

Subcommands:
    generate  write input_N.bin (SPD, deterministic) and optionally
              result_N.bin (its lower Cholesky factor, host LAPACK)
    compare   relative Frobenius distance between two matrix files
    factor    read an input file, factor it on the current JAX platform,
              write the lower factor — produces the file `compare` consumes

Files are written in the framework's binary format (`conflux_tpu.io`):
int64 header (M, N, dtype code) + row-major data. READING also accepts the
reference helper's raw headerless format (dim*dim float64, detected by exact
file size — `examples/cholesky_helper.cpp` writes these), so `factor` and
`compare` consume reference-produced input_N.bin / result_N.bin directly.

Examples:
    python -m conflux_tpu.cli.cholesky_helper generate --dim 4096 \
        --out /tmp/input_4096.bin --result /tmp/result_4096.bin
    python -m conflux_tpu.cli.cholesky_helper factor /tmp/input_4096.bin \
        /tmp/mine_4096.bin --tile 256
    python -m conflux_tpu.cli.cholesky_helper compare /tmp/mine_4096.bin \
        /tmp/result_4096.bin --tol 1e-5
"""

from __future__ import annotations

import argparse

import numpy as np

from conflux_tpu.cli.common import add_common_args, np_dtype, setup_platform
from conflux_tpu.io import load_matrix_auto, save_matrix
from conflux_tpu.validation import make_spd_matrix


def parse_args(argv=None):
    p = argparse.ArgumentParser("cholesky_helper", description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)

    g = sub.add_parser("generate", help="write a deterministic SPD input file")
    g.add_argument("--dim", type=int, required=True)
    g.add_argument("--seed", type=int, default=7)
    g.add_argument("--out", required=True, help="input matrix path")
    g.add_argument("--result", default=None,
                   help="also write the reference lower factor here (host LAPACK)")
    g.add_argument("--stream", action="store_true",
                   help="tile-strip streaming writer: the matrix never exists "
                   "in RAM (very large N; uses the tile-replicated SPD "
                   "construction, incompatible with --result)")
    g.add_argument("--tile", type=int, default=256,
                   help="strip height for --stream (default 256)")
    add_common_args(g)

    c = sub.add_parser("compare", help="relative Frobenius distance of two files")
    c.add_argument("a")
    c.add_argument("b")
    c.add_argument("--tol", type=float, default=None,
                   help="exit 1 if the distance exceeds this")
    c.add_argument("--lower", action="store_true",
                   help="compare only the lower triangles (factor files)")

    f = sub.add_parser("factor", help="factor an input file on this platform")
    f.add_argument("infile")
    f.add_argument("outfile")
    f.add_argument("--tile", type=int, default=None)
    f.add_argument("--grid", default=None, help="Px,Py,Pz (default: auto)")
    add_common_args(f)
    return p.parse_args(argv)


def _generate(args) -> int:
    setup_platform(args)
    dtype = np_dtype(args.dtype)
    if args.stream:
        if args.result:
            raise SystemExit("--stream cannot also write --result "
                             "(the factor would need the full matrix)")
        from conflux_tpu.io import generate_spd_file

        generate_spd_file(args.out, args.dim, v=args.tile, seed=args.seed,
                          dtype=dtype)
        print(f"wrote {args.out}: SPD {args.dim}x{args.dim} "
              f"{np.dtype(dtype).name} (streamed)")
        return 0
    A = make_spd_matrix(args.dim, seed=args.seed, dtype=dtype)
    save_matrix(args.out, A)
    print(f"wrote {args.out}: SPD {args.dim}x{args.dim} {np.dtype(dtype).name}")
    if args.result:
        import scipy.linalg

        L = scipy.linalg.cholesky(A.astype(np.float64), lower=True)
        save_matrix(args.result, L.astype(dtype))
        print(f"wrote {args.result}: reference lower factor")
    return 0


def _compare(args) -> int:
    A = load_matrix_auto(args.a).astype(np.float64)
    B = load_matrix_auto(args.b).astype(np.float64)
    if A.shape != B.shape:
        print(f"shape mismatch: {A.shape} vs {B.shape}")
        return 1
    if args.lower:
        A, B = np.tril(A), np.tril(B)
    dist = float(np.linalg.norm(A - B) / max(np.linalg.norm(B), 1e-30))
    print(f"_compare_ {args.a},{args.b},{dist:.6e}")
    if args.tol is not None and dist > args.tol:
        print(f"FAIL: {dist:.3e} > tol {args.tol:.3e}")
        return 1
    return 0


def _factor(args) -> int:
    setup_platform(args)

    import jax
    import jax.numpy as jnp

    from conflux_tpu.cholesky.distributed import cholesky_factor_distributed
    from conflux_tpu.geometry import (
        CholeskyGeometry,
        Grid3,
        choose_cholesky_grid,
        choose_cholesky_tile,
    )
    from conflux_tpu.parallel.mesh import make_mesh

    A = load_matrix_auto(args.infile)
    N = A.shape[0]
    n_devices = len(jax.devices())
    grid = Grid3.parse(args.grid) if args.grid else choose_cholesky_grid(n_devices)
    v = args.tile or choose_cholesky_tile(N, grid.P)
    geom = CholeskyGeometry.create(N, v, grid)

    if grid.P == 1 and geom.N == N and geom.Kappa <= 64:
        from conflux_tpu.cholesky.single import cholesky_blocked

        L = np.asarray(cholesky_blocked(jnp.asarray(A), v=geom.v))
    else:
        mesh = make_mesh(grid, devices=jax.devices()[: grid.P])
        shards = jnp.asarray(geom.scatter(A))
        out = cholesky_factor_distributed(shards, geom, mesh)
        L = np.tril(geom.gather(np.asarray(out)))[:N, :N]
    save_matrix(args.outfile, L.astype(A.dtype))
    print(f"wrote {args.outfile}: lower factor of {args.infile} "
          f"(grid {grid}, tile {geom.v})")
    return 0


def main(argv=None) -> int:
    args = parse_args(argv)
    return {"generate": _generate, "compare": _compare, "factor": _factor}[args.cmd](args)


if __name__ == "__main__":
    raise SystemExit(main())
