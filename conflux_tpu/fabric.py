"""Multi-host serve fabric — federated engines behind one front (DESIGN §28).

One :class:`ServeFabric` federates N engine *hosts* — each a full
serve stack (ServeEngine + its session registry) living in its own
process — behind a single routing front:

- **Routing** rides the same rendezvous hash as device placement
  (`engine.rendezvous`): a session id maps to the live host whose
  (sid, host-id) weight is highest, so a host-set change remaps ONLY
  the dead host's sessions (~1/N of the fleet) instead of reshuffling
  everyone. The owners map is authoritative AFTER placement — fail-over
  and migration move entries explicitly; healthy sessions never move
  just because the live set changed.
- **Detection** is a heartbeat/lease loop with hysteresis: every
  `heartbeat_interval` the front pings each host; a miss moves
  alive → suspect (`suspect_after` misses) → dead (`dead_after`), and a
  torn transport (EOF/conn-reset — the process is demonstrably gone)
  jumps straight to dead. Per-host :class:`~conflux_tpu.resilience.
  CircuitBreaker`s shed request traffic from flapping hosts between
  heartbeats.
- **Fail-over** revives a dead host's fleet on the survivors from its
  last background checkpoint (`tier.load_fleet` with the `names=`
  subset filter — each survivor adopts exactly the records the
  rendezvous hash assigns it). Revived sessions solve BITWISE
  identically (the checkpoint contract); staleness is bounded by one
  `checkpoint_interval` of drift updates. Sessions that were never
  checkpointed are reported lost — their requests fail with a
  structured :class:`~conflux_tpu.resilience.HostUnavailable`, never
  hang.
- **Migration** hands a live session between hosts at a drain barrier:
  the source checkpoints exactly that session (`engine.checkpoint`
  waits out in-flight work), the target adopts the record, ownership
  flips, the source drops its copy. A crash before the target adopts
  leaves the session intact on the source.
- **Elastic membership** (DESIGN §34): `add_host` joins at runtime
  (adopt-on-arrival — nobody reshuffles), `remove_host` leaves via a
  ``draining`` state + per-sid migration storm (a crash mid-drain
  leaves undrained sessions on the still-live source), retired ids
  never resurrect, and `rebalance` drains induced skew at a bounded
  rate. With ``FabricPolicy.replicas`` ≥ 2, each checkpointed
  session's record is also pushed to the next K-1 hosts on its
  rendezvous-RANKED list, and fail-over becomes re-point-to-standby:
  the standby adopts from its LOCAL replica store, no cross-host
  snapshot read, with restore-from-snapshot demoted to the fallback.
  `conflux_tpu.control.FabricAutoscaler` drives grow/shrink/rebalance
  decisions behind a ``HostProvider`` callback.

Request traffic raises structured errors, never hangs:
:class:`~conflux_tpu.resilience.HostUnavailable` (dead/flapping owner,
`retry_after` riding the fleet's measured drain rate —
:class:`~conflux_tpu.control.HostLoadEstimator`) and
:class:`~conflux_tpu.resilience.FleetDegraded` (admission refused
below `min_live` live hosts).

Two host flavors share one op core (:class:`_HostCore`):
:class:`LocalHost` runs the engine in-process (deterministic tests,
lockcheck soaks, fault drills) and :class:`ProcessHost` spawns
``python -m conflux_tpu.fabric --worker`` wired over an authenticated
``multiprocessing.connection`` AF_UNIX pipe (the real fabric; see
scripts/fabric_drill.py and ``bench_engine.py --fabric``). Checkpoint
records live on a filesystem shared by front and hosts (same box or
shared mount) — the front reads a dead host's snapshot directly and
points survivors at it.

Fault injection (`resilience.FaultPlan`) covers the fabric control
plane: 'heartbeat' (delay/crash — a slow or failed probe, the
hysteresis driver), 'route' (crash/delay on the front's per-request
host call), 'migrate' (crash/delay at the hand-off barrier) and
'host_kill' (kill — a whole engine host dies). `scripts/soak.py
--fabric` drives randomized kill/revive/migrate chaos against per-
session float64 oracles.
"""

from __future__ import annotations

import dataclasses
import json
import os
import secrets
import shutil
import subprocess
import sys
import threading
import time
import weakref
import zlib
from concurrent.futures import Future, ThreadPoolExecutor
from concurrent.futures import TimeoutError as FuturesTimeout
from multiprocessing.connection import Client, Listener
from typing import Any

import numpy as np

from conflux_tpu import resilience, tier
from conflux_tpu import wire as wire_mod
from conflux_tpu import qos as qos_mod
from conflux_tpu.control import HostLoadEstimator
from conflux_tpu.profiler import CounterWindow
from conflux_tpu.resilience import (
    CircuitBreaker,
    DeadlineExceeded,
    FleetDegraded,
    HostUnavailable,
    InjectedFault,
    InjectedKill,
    MeshPlanUnsupported,
    RestoreCorrupt,
    RhsNonFinite,
    SessionQuarantined,
    SessionSpilled,
    SolveUnhealthy,
    TenantThrottled,
    WireCorrupt,
    bump,
    maybe_fault,
)
from conflux_tpu.wire import WireConfig

__all__ = [
    "FabricPolicy", "HostHandle", "LocalHost", "ProcessHost",
    "ServeFabric", "fabric_stats", "latest_checkpoint", "record_name",
    "local_fabric", "process_fabric", "worker_main",
]

# errors raised by the wire/transport layer (NOT by the remote op):
# the front maps these to HostUnavailable + breaker bookkeeping.
# TimeoutError and ConnectionError both subclass OSError.
_TRANSPORT_ERRORS = (OSError, EOFError)

_LATEST = "LATEST"


# --------------------------------------------------------------------------- #
# checkpoint record naming + generation bookkeeping
# --------------------------------------------------------------------------- #


def record_name(sid: Any) -> str:
    """Deterministic, filesystem-safe record name for a session id.

    Successive checkpoints of the same fleet reuse names, so a
    snapshot directory's population tracks the live registry; the
    CRC suffix keeps two sids that sanitize identically apart."""
    s = str(sid)
    safe = "".join(c if c.isalnum() or c in "-_." else "_" for c in s)
    return f"{safe[:48]}-{zlib.crc32(s.encode()):08x}"


def _write_latest(ckpt_dir: str, dest: str) -> None:
    """Atomically point ckpt_dir/LATEST at `dest` (a fleet snapshot
    subdir). Write-tmp-then-replace: a crash mid-checkpoint leaves
    LATEST on the previous complete snapshot."""
    tmp = os.path.join(ckpt_dir, _LATEST + ".tmp")
    with open(tmp, "w") as f:
        f.write(os.path.basename(dest))
    os.replace(tmp, os.path.join(ckpt_dir, _LATEST))


def latest_checkpoint(ckpt_dir: str) -> str | None:
    """The host's newest COMPLETE fleet snapshot dir, or None if it
    never finished one (LATEST is written only after save_fleet
    returns, so the pointer never names a half-written snapshot)."""
    p = os.path.join(ckpt_dir, _LATEST)
    try:
        with open(p) as f:
            name = f.read().strip()
    except OSError:
        return None
    dest = os.path.join(ckpt_dir, name)
    return dest if os.path.isdir(dest) else None


def _snapshot_gen(snap: str | None) -> int:
    """The fleet-NNNNNN sequence number of a snapshot dir — the
    K-replica coherence token (DESIGN §34): a standby's replica is
    trusted at fail-over only when its pushed generation is ≥ the
    corpse's latest snapshot generation, i.e. re-pointing never rolls
    a session back further than the snapshot restore would. -1 when
    the host never completed a snapshot (any replica then wins)."""
    if snap is None:
        return -1
    try:
        return int(os.path.basename(snap).split("-", 1)[1])
    except (IndexError, ValueError):
        return -1


def checkpoint_sids(snapshot: str) -> dict[Any, str]:
    """{sid: record name} for a fleet snapshot — the fail-over front's
    view of WHICH sessions a dead host's checkpoint can revive."""
    with open(os.path.join(snapshot, "fleet.json")) as f:
        fleet = json.load(f)
    return {e["sid"]: e["name"] for e in fleet["sessions"]
            if e.get("sid") is not None}


def checkpoint_manifest(snapshot: str) -> dict[Any, tuple[str, int]]:
    """{sid: (record name, record WRITE generation)} for a fleet
    snapshot — the name for adoption, the generation for the §35
    dirty gates: replica pushes skip standbys already holding the
    record's exact bytes, and fail-over's re-point gate refuses only
    genuinely stale standbys (a session unchanged since generation g
    is coherent on any standby pushed at ≥ g, however many delta
    generations have passed). Format-1 entries (no per-record gen)
    report the snapshot's own generation — the pre-§35 conservative
    gate, bitwise the old behavior."""
    with open(os.path.join(snapshot, "fleet.json")) as f:
        fleet = json.load(f)
    default = int(fleet.get("gen", _snapshot_gen(snapshot)))
    return {e["sid"]: (e["name"], int(e.get("gen", default)))
            for e in fleet["sessions"] if e.get("sid") is not None}


# --------------------------------------------------------------------------- #
# policy
# --------------------------------------------------------------------------- #


@dataclasses.dataclass
class FabricPolicy:
    """Fabric-front knobs (TUNING.md "Multi-host fabric").

    heartbeat_interval: seconds between heartbeat rounds.
    heartbeat_timeout: per-ping reply budget; an overrun is a miss.
    suspect_after / dead_after: consecutive-miss thresholds of the
        alive → suspect → dead hysteresis (dead_after > suspect_after;
        worst-case detection ≈ dead_after * (interval + timeout)).
        A torn transport skips the ladder — the process is gone.
    call_timeout: default reply budget for request-path host calls.
    checkpoint_interval: background fleet-checkpoint period per host;
        0 disables (fail-over then recovers only durable opens and
        explicit checkpoints). Bounds fail-over staleness to one
        interval of drift updates.
    checkpoint_keep: completed snapshot generations kept per host.
    checkpoint_compact_every: delta-checkpoint cadence (DESIGN §35) —
        every Nth generation is a self-contained full compaction;
        the generations between carry clean (unmutated) sessions as
        references into earlier generations, so a steady-state
        checkpoint costs O(dirty sessions), not O(fleet). <= 1 makes
        every generation full (the pre-§35 behavior). Disk held
        grows with the reference chain: worst case
        checkpoint_keep + checkpoint_compact_every generations.
    durable_open: checkpoint the owning host synchronously after every
        `open` — every admitted session is recoverable from birth (the
        soak's session-count conservation oracle). Costs one fleet
        snapshot per open; high-churn deployments turn it off and
        lean on the background interval.
    min_live: below this many live hosts, `open` refuses with
        :class:`FleetDegraded` (solves on live owners still run).
    breaker_threshold / breaker_cooldown: per-host CircuitBreaker —
        transport failures on the REQUEST path trip it; a tripped
        host sheds with HostUnavailable until its cooldown probe.
    retry_floor / retry_ceil: clamp on retry_after hints
        (:class:`~conflux_tpu.control.HostLoadEstimator`).
    replicas: K-replica placement (DESIGN §34). 1 (default) is the
        pre-§34 fabric: fail-over restores from the dead host's own
        snapshot. K ≥ 2 pushes each checkpointed session's record to
        the next K-1 hosts on its rendezvous-RANKED candidate list
        (`engine.rendezvous_ranked`), so fail-over re-points to a
        standby that adopts from its LOCAL replica record — no
        cross-host snapshot read; restore-from-snapshot demotes to
        the fallback for sids whose live standbys are stale or gone.
    """

    heartbeat_interval: float = 0.25
    heartbeat_timeout: float = 2.0
    suspect_after: int = 2
    dead_after: int = 4
    call_timeout: float = 120.0
    checkpoint_interval: float = 0.0
    checkpoint_keep: int = 2
    checkpoint_compact_every: int = 8
    durable_open: bool = True
    min_live: int = 1
    breaker_threshold: int = 3
    breaker_cooldown: float = 5.0
    retry_floor: float = 0.05
    retry_ceil: float = 5.0
    replicas: int = 1

    def __post_init__(self):
        if self.replicas < 1:
            raise ValueError("replicas must be >= 1")
        if self.heartbeat_interval <= 0 or self.heartbeat_timeout <= 0:
            raise ValueError("heartbeat_interval and heartbeat_timeout "
                             "must be > 0")
        if not (1 <= self.suspect_after < self.dead_after):
            raise ValueError("need 1 <= suspect_after < dead_after "
                             f"(got {self.suspect_after}, "
                             f"{self.dead_after})")
        if self.min_live < 1:
            raise ValueError("min_live must be >= 1")
        if self.checkpoint_interval < 0 or self.checkpoint_keep < 1:
            raise ValueError("checkpoint_interval must be >= 0 and "
                             "checkpoint_keep >= 1")
        if self.checkpoint_compact_every < 0:
            raise ValueError("checkpoint_compact_every must be >= 0")


# --------------------------------------------------------------------------- #
# the host op core — shared by LocalHost and the worker process
# --------------------------------------------------------------------------- #


class _HostCore:
    """One engine host's op surface: a ServeEngine plus the sid →
    session registry, with the checkpoint/adopt/migrate rails the
    fabric's robustness story rides. `LocalHost` calls it in-process;
    `worker_main` wraps it behind the wire loop."""

    def __init__(self, host_id: str, ckpt_dir: str, engine) -> None:
        self.host_id = str(host_id)
        self.ckpt_dir = ckpt_dir
        os.makedirs(ckpt_dir, exist_ok=True)
        self.eng = engine
        self.ckpt_keep = 2
        # delta-checkpoint cadence (DESIGN §35): every Nth generation
        # is a self-contained compaction; the rest carry clean
        # sessions as references into earlier generations. <=1 means
        # every generation is full (the pre-§35 behavior).
        self.ckpt_compact_every = 8
        self._lock = threading.Lock()
        self._registry: dict = {}  # guarded-by: _lock — sid -> session
        self._ckpt_seq = 0         # guarded-by: _lock
        # standby records this host holds for OTHER hosts' sessions
        # (DESIGN §34 K-replica placement): name -> generation. Seeded
        # from disk so a restarted worker still answers adopt_replica
        # for records a previous incarnation accepted.
        self._replicas: dict[str, int] = {}  # guarded-by: _lock
        rep_root = os.path.join(ckpt_dir, "replicas")
        if os.path.isdir(rep_root):
            for name in os.listdir(rep_root):
                try:
                    with open(os.path.join(rep_root, name,
                                           "fleet.json")) as f:
                        self._replicas[name] = int(
                            json.load(f).get("gen", 0))
                except (OSError, ValueError, KeyError):
                    continue  # half-written leftover; replaced on push

    # -- telemetry ----------------------------------------------------- #

    def ping(self) -> dict:
        """Heartbeat payload: a cheap counter snapshot (the front's
        CounterWindow diffs it into rates) + the session census."""
        c = self.eng.counters()
        with self._lock:
            n = len(self._registry)
            nrep = len(self._replicas)
        counters = {"pending": c["pending"],
                    "solves": c["completed"],
                    "requests": c["requests"],
                    "failed": c["failed"],
                    "shed": c["shed"]}
        # per-tier drain counters ride as FLAT keys: CounterWindow on
        # the front differences numeric keys only, so the front sees
        # per-class drain rates without a payload schema change
        qc = c.get("qos")
        if qc is not None:
            tiers: dict[str, int] = {}
            for row in qc.get("classes", {}).values():
                t = row.get("tier")
                tiers[t] = tiers.get(t, 0) + int(row.get("completed", 0))
            for t, done in sorted(tiers.items()):
                counters[f"qos_{t}_solves"] = done
        return {"host_id": self.host_id, "sessions": n,
                "replicas": nrep, "counters": counters}

    def stats(self) -> dict:
        with self._lock:
            sids = sorted(str(s) for s in self._registry)
            seq = self._ckpt_seq
        return {"host_id": self.host_id, "sids": sids,
                "checkpoints": seq, "engine": self.eng.counters()}

    # -- session lifecycle --------------------------------------------- #

    def open(self, sid: Any, spec: dict, A: np.ndarray,
             policy: dict | None = None) -> Any:
        """Factor A under the EXACT plan `spec` describes and register
        the session. The plan rebuilds from its wire spec
        (`serve.plan_from_spec`) so every host compiles the same
        program family — the bitwise hand-off contract."""
        from conflux_tpu.serve import plan_from_spec
        from conflux_tpu.update import DriftPolicy

        with self._lock:
            if sid in self._registry:
                raise ValueError(f"sid {sid!r} already open on host "
                                 f"{self.host_id}")
        plan = plan_from_spec(spec)
        pol = DriftPolicy(**policy) if policy is not None else None
        s = self.eng.factor(plan, A, sid=sid, policy=pol)
        with self._lock:
            self._registry[sid] = s
        return sid

    def _session(self, sid: Any):
        with self._lock:
            s = self._registry.get(sid)
        if s is None:
            raise KeyError(f"host {self.host_id} has no session "
                           f"{sid!r}")
        return s

    def solve_async(self, sid: Any, b: np.ndarray,
                    qos=None) -> Future:
        return self.eng.submit(self._session(sid), b, qos=qos)

    def update(self, sid: Any, U: np.ndarray, V: np.ndarray,
               replace: bool = False) -> bool:
        self._session(sid).update(U, V, replace=replace)
        return True

    def drop(self, sid: Any) -> bool:
        """Forget a session (the migration source's final step)."""
        with self._lock:
            return self._registry.pop(sid, None) is not None

    # -- checkpoint / adopt / migrate rails ---------------------------- #

    def checkpoint(self) -> str:
        """Snapshot the whole registry at the engine's drain barrier
        into a fresh generation dir, flip LATEST, prune old
        generations. Returns the snapshot dir.

        Incremental (DESIGN §35): against the previous LATEST, clean
        sessions (dirty clock unchanged since their last record) are
        carried as single-hop references instead of re-serialized, so
        a steady-state generation costs O(dirty) d2h/IO. Every
        `ckpt_compact_every`-th generation is a full compaction
        (byte-identical local copies, no d2h) so reference chains stay
        bounded and pruning can retire old generations."""
        with self._lock:
            items = sorted(self._registry.items(), key=lambda kv: str(kv[0]))
            seq = self._ckpt_seq
            self._ckpt_seq += 1
        base = latest_checkpoint(self.ckpt_dir)
        every = int(self.ckpt_compact_every)
        full = base is None or every <= 1 or (seq % every == 0)
        dest = os.path.join(self.ckpt_dir, f"fleet-{seq:06d}")
        self.eng.checkpoint(dest, sessions=[s for _, s in items],
                            names=[record_name(sid) for sid, _ in items],
                            base=base, gen=seq, full=full)
        _write_latest(self.ckpt_dir, dest)
        self._prune()
        return dest

    def _prune(self) -> None:
        # reference-aware (DESIGN §35): a delta generation's carried
        # records physically live in OLDER generation dirs. Keep the
        # newest `ckpt_keep` generations plus every generation a kept
        # fleet.json references, so pruning never strands a record a
        # restorable snapshot still needs.
        keep = self.ckpt_keep
        gens = sorted(d for d in os.listdir(self.ckpt_dir)
                      if d.startswith("fleet-"))
        kept = set(gens[-keep:])
        frontier = sorted(kept)
        while frontier:
            d = frontier.pop()
            try:
                with open(os.path.join(self.ckpt_dir, d,
                                       "fleet.json")) as f:
                    entries = json.load(f)["sessions"]
            except (OSError, ValueError, KeyError):
                continue  # unreadable gen: keeps nothing extra
            for e in entries:
                parts = os.path.normpath(e.get("dir", "")).split(os.sep)
                if (len(parts) >= 2 and parts[0] == ".."
                        and parts[1].startswith("fleet-")
                        and parts[1] not in kept):
                    kept.add(parts[1])
                    frontier.append(parts[1])
        for d in gens:
            if d not in kept:
                shutil.rmtree(os.path.join(self.ckpt_dir, d),
                              ignore_errors=True)

    def adopt(self, src: str, names: list[str]) -> list:
        """Restore a `names` subset of another host's snapshot into
        this host's registry (fail-over / migration target half).
        Returns the adopted sids."""
        sessions = tier.load_fleet(src, names=names)
        with self._lock:
            for s in sessions:
                self._registry[s.sid] = s
        return [s.sid for s in sessions]

    def migrate_out(self, sid: Any, dest: str) -> str:
        """Checkpoint exactly `sid` to `dest` at the engine's drain
        barrier (in-flight solves finish first; nothing else moves).
        The session STAYS registered — the front drops it only after
        the target adopts, so a crash mid-hand-off loses nothing."""
        s = self._session(sid)
        name = record_name(sid)
        self.eng.checkpoint(dest, sessions=[s], names=[name])
        return name

    # -- K-replica standby store (DESIGN §34) -------------------------- #

    def replicate(self, src: str, names: list[str], gen: int) -> list:
        """Accept standby copies of another host's checkpoint records.

        For each `name`, copy its record dir out of the snapshot `src`
        into this host's local `replicas/<name>/` store as a
        one-session fleet (loadable by `tier.load_fleet` without
        touching `src` again — the whole point: fail-over re-points
        here with zero cross-host reads). The swap is
        copy-aside-then-rename, so a crash mid-push leaves either the
        previous complete replica or a `.tmp` leftover the seeding
        scan skips — never a half record. Generations are monotone:
        a stale push (gen older than what this host already holds) is
        skipped, not applied, so out-of-order rounds cannot roll a
        standby backward. Returns the names actually (re)written."""
        with open(os.path.join(src, "fleet.json")) as f:
            entries = {e["name"]: e for e in json.load(f)["sessions"]}
        rep_root = os.path.join(self.ckpt_dir, "replicas")
        os.makedirs(rep_root, exist_ok=True)
        done: list[str] = []
        for name in names:
            e = entries.get(name)
            if e is None:
                raise KeyError(f"snapshot {src} has no record {name!r}")
            with self._lock:
                if self._replicas.get(name, -1) >= gen:
                    continue
            tmp = os.path.join(rep_root, f"{name}.tmp")
            shutil.rmtree(tmp, ignore_errors=True)
            os.makedirs(tmp)
            # resolve through delta references (a carried entry's dir
            # points into an older generation — DESIGN §35) and store
            # the record under its own name, so the replica fleet is
            # self-contained whatever the source entry's shape
            shutil.copytree(os.path.normpath(
                os.path.join(src, e["dir"])),
                os.path.join(tmp, name))
            e = {**e, "dir": name}
            with open(os.path.join(tmp, "fleet.json"), "w") as f:
                json.dump({"format": 1, "gen": int(gen),
                           "sessions": [e]}, f)
            final = os.path.join(rep_root, name)
            old = final + ".old"
            shutil.rmtree(old, ignore_errors=True)
            if os.path.isdir(final):
                os.rename(final, old)
            os.rename(tmp, final)
            shutil.rmtree(old, ignore_errors=True)
            with self._lock:
                self._replicas[name] = int(gen)
            done.append(name)
        return done

    def adopt_replica(self, items: list) -> dict:
        """Fail-over re-point (DESIGN §34): restore sessions from this
        host's LOCAL replica store — no cross-host snapshot read.
        `items` is [[sid, name], ...]; each present replica loads via
        the same `tier.load_fleet` rail as snapshot adoption (bitwise
        contract intact) and registers. Missing/corrupt replicas are
        reported, not raised — the front falls back to the snapshot
        path for exactly those sids."""
        rep_root = os.path.join(self.ckpt_dir, "replicas")
        adopted: list = []
        missing: list = []
        for sid, name in items:
            path = os.path.join(rep_root, name)
            try:
                sessions = tier.load_fleet(path, names=[name])
            except (OSError, KeyError, ValueError, RestoreCorrupt):
                missing.append(sid)
                continue
            with self._lock:
                for s in sessions:
                    self._registry[s.sid] = s
                gen = self._replicas.pop(name, 0)
            shutil.rmtree(path, ignore_errors=True)
            adopted.append([sid, gen])
        return {"adopted": adopted, "missing": missing}

    def drop_replica(self, names: list[str]) -> int:
        """Retire standby records this host no longer ranks for
        (placement moved, session closed, or the primary itself now
        lives here). Best-effort hygiene — a leftover replica is
        harmless (generation-gated) but wastes disk."""
        rep_root = os.path.join(self.ckpt_dir, "replicas")
        n = 0
        for name in names:
            with self._lock:
                had = self._replicas.pop(name, None)
            shutil.rmtree(os.path.join(rep_root, name),
                          ignore_errors=True)
            if had is not None:
                n += 1
        return n

    def wipe(self) -> None:
        """Drop the whole registry (LocalHost.kill: a dead process's
        un-checkpointed state is simply gone)."""
        with self._lock:
            self._registry.clear()
            self._replicas.clear()

    def close(self) -> bool:
        self.eng.close()
        return True


# --------------------------------------------------------------------------- #
# host handles
# --------------------------------------------------------------------------- #


class HostHandle:
    """The front's view of one engine host. Implementations raise
    transport-shaped errors (ConnectionError/TimeoutError/EOFError)
    when the host is unreachable — the front maps those to
    HostUnavailable + breaker/heartbeat bookkeeping, while structured
    per-request errors (EngineSaturated, SolveUnhealthy, ...) pass
    through untouched."""

    host_id: str
    ckpt_dir: str

    def start(self) -> None:
        raise NotImplementedError

    def ping(self, timeout: float | None = None) -> dict:
        raise NotImplementedError

    def open(self, sid, spec, A, policy=None,
             timeout: float | None = None):
        raise NotImplementedError

    def solve(self, sid, b, timeout: float | None = None, qos=None):
        raise NotImplementedError

    def update(self, sid, U, V, replace: bool = False,
               timeout: float | None = None):
        raise NotImplementedError

    def checkpoint(self, timeout: float | None = None) -> str:
        raise NotImplementedError

    def adopt(self, src, names, timeout: float | None = None) -> list:
        raise NotImplementedError

    def migrate_out(self, sid, dest,
                    timeout: float | None = None) -> str:
        raise NotImplementedError

    def replicate(self, src, names, gen,
                  timeout: float | None = None) -> list:
        raise NotImplementedError

    def adopt_replica(self, items,
                      timeout: float | None = None) -> dict:
        raise NotImplementedError

    def drop_replica(self, names,
                     timeout: float | None = None) -> int:
        raise NotImplementedError

    def drop(self, sid, timeout: float | None = None) -> bool:
        raise NotImplementedError

    def stats(self, timeout: float | None = None) -> dict:
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError

    def kill(self) -> None:
        """Abrupt host death (tests/soak/bench drills)."""
        raise NotImplementedError


class LocalHost(HostHandle):
    """In-process host: the engine runs on this process's threads.

    Deterministic and cheap — the unit tests, the lockcheck soaks and
    the fault drills run the whole fabric in one process. `kill()`
    simulates abrupt process death: the registry is gone and every
    subsequent call raises ConnectionError (transport-shaped), so the
    front exercises the same detection/fail-over path as a real dead
    worker."""

    def __init__(self, host_id: str, ckpt_dir: str, *,
                 engine=None, engine_kwargs: dict | None = None):
        self.host_id = str(host_id)
        self.ckpt_dir = ckpt_dir
        self._engine = engine
        self._engine_kwargs = dict(engine_kwargs or {})
        self._core: _HostCore | None = None
        self._killed = threading.Event()

    def start(self) -> None:
        if self._core is not None:
            return
        eng = self._engine
        if eng is None:
            from conflux_tpu.engine import ServeEngine

            eng = ServeEngine(**self._engine_kwargs)
        self._core = _HostCore(self.host_id, self.ckpt_dir, eng)

    @property
    def core(self) -> _HostCore:
        if self._core is None:
            raise RuntimeError(f"host {self.host_id} not started")
        return self._core

    def _alive_core(self) -> _HostCore:
        if self._killed.is_set():
            raise ConnectionError(f"host {self.host_id} is dead")
        return self.core

    def ping(self, timeout: float | None = None) -> dict:
        return self._alive_core().ping()

    def _engine_op(self, op, *args):
        """Run one engine-backed core op, mapping EngineClosed during
        a concurrent kill() to the transport shape (a real dead worker
        would have torn the pipe mid-call)."""
        from conflux_tpu.engine import EngineClosed

        core = self._alive_core()
        try:
            return op(core, *args)
        except EngineClosed as e:
            if self._killed.is_set():  # killed mid-flight
                raise ConnectionError(
                    f"host {self.host_id} died mid-call") from e
            raise

    def open(self, sid, spec, A, policy=None,
             timeout: float | None = None):
        return self._engine_op(
            lambda c: c.open(sid, spec, A, policy))

    def solve(self, sid, b, timeout: float | None = None, qos=None):
        from conflux_tpu.engine import EngineClosed

        fut = self._alive_core().solve_async(sid, b, qos=qos)
        try:
            return fut.result(timeout)
        except EngineClosed as e:
            if self._killed.is_set():  # killed mid-flight
                raise ConnectionError(
                    f"host {self.host_id} died mid-solve") from e
            raise

    def update(self, sid, U, V, replace: bool = False,
               timeout: float | None = None):
        return self._alive_core().update(sid, U, V, replace)

    def checkpoint(self, timeout: float | None = None) -> str:
        return self._engine_op(lambda c: c.checkpoint())

    def adopt(self, src, names, timeout: float | None = None) -> list:
        return self._alive_core().adopt(src, names)

    def migrate_out(self, sid, dest,
                    timeout: float | None = None) -> str:
        return self._engine_op(lambda c: c.migrate_out(sid, dest))

    def replicate(self, src, names, gen,
                  timeout: float | None = None) -> list:
        return self._alive_core().replicate(src, names, gen)

    def adopt_replica(self, items,
                      timeout: float | None = None) -> dict:
        return self._engine_op(lambda c: c.adopt_replica(items))

    def drop_replica(self, names,
                     timeout: float | None = None) -> int:
        return self._alive_core().drop_replica(names)

    def drop(self, sid, timeout: float | None = None) -> bool:
        return self._alive_core().drop(sid)

    def stats(self, timeout: float | None = None) -> dict:
        return self._alive_core().stats()

    def close(self) -> None:
        if self._core is not None and not self._killed.is_set():
            self._core.close()

    def kill(self) -> None:
        if self._killed.is_set():
            return
        self._killed.set()
        # abrupt: un-checkpointed registry state is gone; the engine's
        # close answers whatever already reached a lane, mirroring
        # requests that raced a real process death
        if self._core is not None:
            self._core.eng.close(timeout=2.0)
            self._core.wipe()


# --------------------------------------------------------------------------- #
# wire codec (ProcessHost <-> worker)
# --------------------------------------------------------------------------- #


def _encode_exc(e: BaseException) -> dict:
    extra: dict = {}
    for k in ("retry_after", "evidence", "live", "total", "host",
              "surface", "tenant", "qos_class", "kind"):
        v = getattr(e, k, None)
        if v is not None:
            extra[k] = v
    return {"ok": False, "etype": type(e).__name__,
            "emsg": str(e), "extra": extra}


_WIRE_TYPES: dict[str, Any] = {
    "EngineSaturated": lambda m, x: _mk_engine_exc(
        "EngineSaturated", m, x.get("retry_after", 0.0),
        tenant=x.get("tenant"), qos_class=x.get("qos_class")),
    "EngineClosed": lambda m, x: _mk_engine_exc("EngineClosed", m),
    "TenantThrottled": lambda m, x: TenantThrottled(
        m, retry_after=x.get("retry_after", 0.0),
        tenant=x.get("tenant"), qos_class=x.get("qos_class")),
    "SessionQuarantined": lambda m, x: SessionQuarantined(
        m, retry_after=x.get("retry_after", 0.0)),
    "SessionSpilled": lambda m, x: SessionSpilled(
        m, retry_after=x.get("retry_after", 0.0)),
    "SolveUnhealthy": lambda m, x: SolveUnhealthy(
        m, x.get("evidence") or {}),
    "RestoreCorrupt": lambda m, x: RestoreCorrupt(m, x.get("evidence")),
    "RhsNonFinite": lambda m, x: RhsNonFinite(m),
    "DeadlineExceeded": lambda m, x: DeadlineExceeded(m),
    "MeshPlanUnsupported": lambda m, x: MeshPlanUnsupported(
        m, x.get("surface", "")),
    "HostUnavailable": lambda m, x: HostUnavailable(
        m, retry_after=x.get("retry_after", 0.0), host=x.get("host")),
    "FleetDegraded": lambda m, x: FleetDegraded(
        m, retry_after=x.get("retry_after", 0.0),
        live=x.get("live", 0), total=x.get("total", 0)),
    "KeyError": lambda m, x: KeyError(m),
    "ValueError": lambda m, x: ValueError(m),
    # a corrupt REQUEST record detected worker-side comes back as a
    # per-item error frame: rehydrate it ConnectionError-shaped with
    # its kind/host intact. Deliberately NOT host death (the
    # asymmetry with reply-side corruption): the front wrote that
    # record and its frame-mates validated fine, so the channel
    # itself is still trusted — only reply-side corruption (decode)
    # condemns the host, because there the front can no longer trust
    # anything it reads out of the reply ring.
    "WireCorrupt": lambda m, x: WireCorrupt(
        m, kind=x.get("kind", "torn_segment"), host=x.get("host")),
}


def _mk_engine_exc(name: str, msg: str, retry_after: float | None = None,
                   **attrs):
    from conflux_tpu import engine as _eng

    cls = getattr(_eng, name)
    if retry_after is None:
        return cls(msg)
    return cls(msg, retry_after=retry_after,
               **{k: v for k, v in attrs.items() if v is not None})


def _raise_wire(reply: dict) -> None:
    et = reply.get("etype", "RuntimeError")
    em = reply.get("emsg", "")
    build = _WIRE_TYPES.get(et)
    if build is not None:
        raise build(em, reply.get("extra") or {})
    raise RuntimeError(f"remote {et}: {em}")


class ProcessHost(HostHandle):
    """An engine host in its own worker process.

    `start()` opens an authenticated AF_UNIX listener under the host's
    checkpoint dir, spawns ``python -m conflux_tpu.fabric --worker``
    (authkey via the CONFLUX_FABRIC_KEY env var — never on the command
    line) and accepts the worker's connection. Requests are
    id-matched: a sender lock serializes writes, a receiver thread
    resolves reply futures, and a torn pipe fails every pending future
    with ConnectionError — an in-flight request on a dying host gets a
    structured error, never a hang.

    With ``wire="shm"`` (the default) solve payloads ride the
    zero-copy shared-memory wire (DESIGN §31, `conflux_tpu.wire`):
    the RHS is staged straight into a per-host request ring and only
    a descriptor crosses the pipe, batched with its frame-mates; the
    answer comes back through the reply ring the same way. Non-array
    ops, oversized payloads and a worker whose reply ring backs up
    all fall back to the pickle wire transparently; ``wire="pickle"``
    is the escape hatch that turns the rings off entirely. A corrupt
    REPLY record (:class:`~conflux_tpu.resilience.WireCorrupt` —
    torn/stale/overrun) means the payload channel can no longer be
    trusted: the worker is killed and every pending request fails
    structurally, exactly like a torn pipe. A corrupt REQUEST record
    detected worker-side fails only its own item (rehydrated
    front-side as WireCorrupt, kind/host intact) — the asymmetry is
    deliberate, see the `_WIRE_TYPES` entry."""

    def __init__(self, host_id: str, ckpt_dir: str, *,
                 engine_kwargs: dict | None = None,
                 start_timeout: float = 180.0,
                 call_timeout: float = 120.0,
                 env: dict | None = None,
                 wire: str = "shm",
                 wire_config: WireConfig | None = None):
        if wire not in ("shm", "pickle"):
            raise ValueError(f"wire must be 'shm' or 'pickle', "
                             f"got {wire!r}")
        self.host_id = str(host_id)
        self.ckpt_dir = ckpt_dir
        self._engine_kwargs = dict(engine_kwargs or {})
        self._start_timeout = float(start_timeout)
        self._call_timeout = float(call_timeout)
        self._env = env
        self._wire_mode = wire
        self._wire_cfg = (wire_config if wire_config is not None
                          else WireConfig())
        self._wire: wire_mod.WireClient | None = None
        self._proc: subprocess.Popen | None = None
        self._conn = None
        self._listener = None
        self._send_lock = threading.Lock()
        self._pending: dict[int, Future] = {}  # guarded-by: _send_lock
        self._next_id = 0                      # guarded-by: _send_lock
        self._dead: Exception | None = None    # guarded-by: _send_lock
        self._recv_thread: threading.Thread | None = None

    # -- lifecycle ----------------------------------------------------- #

    def start(self) -> None:
        if self._conn is not None:
            return
        os.makedirs(self.ckpt_dir, exist_ok=True)
        sock = os.path.join(self.ckpt_dir, "rpc.sock")
        if os.path.exists(sock):
            os.unlink(sock)
        key = secrets.token_bytes(16)
        self._listener = Listener(sock, family="AF_UNIX", authkey=key)
        env = dict(os.environ if self._env is None else self._env)
        env["CONFLUX_FABRIC_KEY"] = key.hex()
        cmd = [sys.executable, "-m", "conflux_tpu.fabric", "--worker",
               "--host-id", self.host_id, "--connect", sock,
               "--ckpt-dir", self.ckpt_dir,
               "--engine-json", json.dumps(self._engine_kwargs)]
        req_ring = rep_ring = None
        if self._wire_mode == "shm":
            # the FRONT creates (and always unlinks) the segments, so
            # a SIGKILLed worker can never leak /dev/shm entries
            rq_name, rp_name = wire_mod.segment_names(self.host_id)
            req_ring = wire_mod.Ring.create(
                rq_name, self._wire_cfg.ring_bytes, reclaim="local")
            rep_ring = wire_mod.Ring.create(
                rp_name, self._wire_cfg.ring_bytes, reclaim="shared")
            cmd += ["--wire-json", json.dumps(
                {"req": rq_name, "rep": rp_name,
                 "cfg": self._wire_cfg.to_json()})]
        self._log_path = os.path.join(self.ckpt_dir, "worker.log")
        self._log = open(self._log_path, "ab")
        self._proc = subprocess.Popen(cmd, env=env, stdout=self._log,
                                      stderr=subprocess.STDOUT)
        box: list = []

        def accept():
            try:
                box.append(self._listener.accept())
            except Exception as e:  # noqa: BLE001 — reported below
                box.append(e)

        t = threading.Thread(target=accept, daemon=True,
                             name=f"fabric-accept-{self.host_id}")
        t.start()
        t.join(self._start_timeout)
        if not box or isinstance(box[0], Exception):
            self._proc.kill()
            if req_ring is not None:
                req_ring.close()
                rep_ring.close()
            tail = b""
            try:
                with open(self._log_path, "rb") as f:
                    tail = f.read()[-2000:]
            except OSError:
                pass
            raise RuntimeError(
                f"fabric worker {self.host_id} failed to connect within "
                f"{self._start_timeout}s: {box[0] if box else 'timeout'}"
                f"\n--- worker log tail ---\n{tail.decode(errors='replace')}")
        self._conn = box[0]
        if req_ring is not None:
            self._wire = wire_mod.WireClient(
                req_ring, rep_ring, self._wire_send,
                host_id=self.host_id, config=self._wire_cfg,
                on_send_error=self._wire_send_failed)
        self._recv_thread = threading.Thread(
            target=self._recv_loop, daemon=True,
            name=f"fabric-recv-{self.host_id}")
        self._recv_thread.start()

    def _wire_send(self, frame: dict) -> None:
        """Control-frame send for the wire pump — serialized with the
        direct _call sends on the one pipe."""
        with self._send_lock:
            if self._dead is not None:
                raise OSError(f"host {self.host_id} is dead")
            self._conn.send(frame)

    # futures-owner
    def _wire_send_failed(self, items: list, exc: Exception) -> None:
        """The wire pump's frame never left: fail exactly its mids
        (the pipe itself is torn, so _recv_loop's _fail follows)."""
        with self._send_lock:
            futs = [self._pending.pop(it["id"], None) for it in items]
        e = ConnectionError(
            f"host {self.host_id} wire send failed: {exc!r}")
        for fut in futs:
            if fut is not None:
                fut.set_exception(e)

    # futures-owner
    def _recv_loop(self) -> None:
        try:
            while True:
                msg = self._conn.recv()
                if msg.get("op") == "reply_many":
                    self._wire_replies(msg)
                    continue
                with self._send_lock:
                    fut = self._pending.pop(msg.get("id"), None)
                if fut is not None:
                    fut.set_result(msg)
        except (EOFError, OSError) as e:
            self._fail(ConnectionError(
                f"host {self.host_id} connection lost: {e!r}"))

    # futures-owner
    def _wire_replies(self, msg: dict) -> None:
        """One batched reply frame off the shm wire: decode validates
        every ring record — a torn/stale/overrun record condemns the
        whole payload channel (DESIGN §31 fault table)."""
        try:
            pairs = self._wire.decode(msg["items"])
        except WireCorrupt as e:
            self._wire_dead(e)
            return
        with self._send_lock:
            futs = [(self._pending.pop(mid, None), reply)
                    for mid, reply in pairs]
        for fut, reply in futs:
            if fut is not None:
                fut.set_result(reply)

    def _wire_dead(self, exc: WireCorrupt) -> None:
        """A corrupt shm record ⇒ instant structural death: kill the
        worker (its view of the rings is no longer trustworthy), fail
        every pending request NOW (WireCorrupt is ConnectionError-
        shaped, so the front maps it like any torn transport), and
        let the heartbeat's torn-pipe detection drive fail-over."""
        if self._wire is not None:
            self._wire.fail(exc)
        if self._proc is not None:
            try:
                self._proc.kill()
            except OSError:
                pass
        self._fail(exc)

    def _fail(self, exc: Exception) -> None:
        """Mark the transport dead and fail every pending reply future
        — no request ever hangs on a torn pipe. The shm wire client
        (when present) is failed FIRST, outside `_send_lock` (it has
        its own lock; never nest the two): a torn pipe means no reply
        will ever drain the rings again, so ring-backpressure retry
        loops and the send pump must observe the death instead of
        pacing forever against a permanently full ring."""
        w = self._wire
        if w is not None:
            w.fail(exc)
        with self._send_lock:
            if self._dead is None:
                self._dead = exc
            stranded = list(self._pending.values())
            self._pending.clear()
        for fut in stranded:
            fut.set_exception(exc)

    # -- request plumbing ---------------------------------------------- #

    def _deadline(self, timeout: float | None) -> float:
        """ONE timeout rule for every op: an explicit per-op timeout
        wins, else the handle's call_timeout — the pickle wire, the
        shm wire and ping all resolve through here, so the two knobs
        compose identically everywhere."""
        return self._call_timeout if timeout is None else float(timeout)

    def _await(self, fut: Future, mid: int, timeout: float | None):
        """Wait out one reply future. A timeout pops the pending entry
        (no leak) and raises the BUILTIN TimeoutError: on Python 3.10
        ``concurrent.futures.TimeoutError`` is a distinct class that
        is NOT an OSError, so re-raising it raw would slip past
        _TRANSPORT_ERRORS and reach the caller unstructured instead of
        mapping to HostUnavailable."""
        secs = self._deadline(timeout)
        try:
            reply = fut.result(secs)
        except FuturesTimeout as e:
            with self._send_lock:
                self._pending.pop(mid, None)
            raise TimeoutError(
                f"host {self.host_id} op timed out after "
                f"{secs:g}s") from e
        if reply.get("ok"):
            return reply.get("value")
        _raise_wire(reply)

    def _call(self, op: str, timeout: float | None = None, **kw):
        fut: Future = Future()
        with self._send_lock:
            if self._dead is not None:
                raise ConnectionError(
                    f"host {self.host_id} is dead: {self._dead}")
            if self._conn is None:
                raise ConnectionError(
                    f"host {self.host_id} not started")
            mid = self._next_id
            self._next_id += 1
            self._pending[mid] = fut
            try:
                self._conn.send({"id": mid, "op": op, **kw})
            except (OSError, ValueError) as e:
                self._pending.pop(mid, None)
                raise ConnectionError(
                    f"host {self.host_id} send failed: {e!r}") from e
        return self._await(fut, mid, timeout)

    # hot-path (one ring memcpy + one outbox append per request)
    def _call_wire(self, op: str, sid, b: np.ndarray,
                   timeout: float | None, qos) -> Any:
        """A payload op over the shm wire: register the mid, stage the
        RHS into the request ring, let the pump batch the descriptor
        out. Ring backpressure maps to HostUnavailable with the ring's
        measured-drain retry hint — never a blocking wait."""
        fut: Future = Future()
        with self._send_lock:
            if self._dead is not None:
                raise ConnectionError(
                    f"host {self.host_id} is dead: {self._dead}")
            mid = self._next_id
            self._next_id += 1
            self._pending[mid] = fut
        try:
            self._wire.submit(mid, sid, b, qos=qos, op=op)
        except wire_mod.RingFull as e:
            with self._send_lock:
                self._pending.pop(mid, None)
            raise HostUnavailable(
                f"host {self.host_id} wire backpressure: {e} "
                f"(retry in ~{e.retry_after * 1e3:.0f}ms at the "
                f"measured drain rate)",
                retry_after=e.retry_after, host=self.host_id) from e
        except ConnectionError:
            with self._send_lock:
                self._pending.pop(mid, None)
            raise
        return self._await(fut, mid, timeout)

    # -- op surface ---------------------------------------------------- #

    def ping(self, timeout: float | None = None) -> dict:
        out = self._call("ping", timeout=timeout)
        w = self._wire
        if w is not None and isinstance(out, dict):
            # ring occupancy rides the heartbeat as a GAUGE — the
            # front-side client sees both rings, no worker round-trip
            st = w.stats()
            frac = max(st["req_used"] / max(1, st["req_cap"]),
                       st["rep_used"] / max(1, st["rep_cap"]))
            out.setdefault("counters", {})["wire_used_frac"] = round(
                frac, 4)
            out["wire"] = st
        return out

    def open(self, sid, spec, A, policy=None,
             timeout: float | None = None):
        return self._call("open", timeout=timeout, sid=sid, spec=spec,
                          A=np.asarray(A), policy=policy)

    def solve(self, sid, b, timeout: float | None = None, qos=None):
        w = self._wire
        if w is not None:
            b2 = np.asarray(b)
            if b2.dtype != object and w.payload_fits(b2.nbytes):
                return self._call_wire(
                    "solve", sid, b2, timeout,
                    None if qos is None else qos.to_wire())
        return self._call("solve", timeout=timeout, sid=sid,
                          b=np.asarray(b),
                          qos=None if qos is None else qos.to_wire())

    def echo(self, b, timeout: float | None = None):
        """RPC-layer microbench op (``bench_engine.py --wire``): the
        payload round-trips through whichever wire this host runs,
        engine bypassed — isolates transport cost from solve cost."""
        w = self._wire
        if w is not None:
            b2 = np.asarray(b)
            if b2.dtype != object and w.payload_fits(b2.nbytes):
                return self._call_wire("echo", None, b2, timeout, None)
        return self._call("echo", timeout=timeout, b=np.asarray(b))

    def echo_many(self, payloads, timeout: float | None = None):
        """Pipelined batch echo (``bench_engine.py --wire``): submit
        EVERY payload before awaiting any reply, so the measured cost
        is the wire itself, not one round-trip latency per request.
        On the shm wire the burst goes through
        :meth:`WireClient.submit_many` — N payloads, one lock, a
        handful of ``solve_many`` frames — honouring ring
        backpressure with the measured-drain retry hint; on the
        pickle wire it is one ``Connection.send`` per payload (that
        per-request serialization IS the baseline being measured).
        Returns the echoed arrays in submission order."""
        arrs = [np.asarray(b) for b in payloads]
        w = self._wire
        pend: list[tuple[int, Future]] = []
        if w is not None and all(
                a.dtype != object and w.payload_fits(a.nbytes)
                for a in arrs):
            with self._send_lock:
                if self._dead is not None:
                    raise ConnectionError(
                        f"host {self.host_id} is dead: {self._dead}")
                for a in arrs:
                    mid = self._next_id
                    self._next_id += 1
                    fut: Future = Future()
                    self._pending[mid] = fut
                    pend.append((mid, fut))
            entries = [(mid, None, a, None, "echo")
                       for (mid, _f), a in zip(pend, arrs)]
            sent = 0
            secs = self._deadline(timeout)
            give_up = time.perf_counter() + secs
            try:
                while sent < len(entries):
                    try:
                        sent += w.submit_many(entries[sent:])
                    except wire_mod.RingFull as e:
                        # bounded, measured-drain pacing: the ring is
                        # full because replies are still in flight —
                        # they free records as they land. Re-check
                        # death each lap (a torn pipe means no reply
                        # will EVER free a record) and bound the total
                        # pacing by the op timeout: never spin forever
                        with self._send_lock:
                            dead = self._dead
                        if dead is not None:
                            raise ConnectionError(
                                f"host {self.host_id} died while "
                                f"pacing a full ring: {dead}") from dead
                        if time.perf_counter() >= give_up:
                            with self._send_lock:
                                for mid, _f in pend[sent:]:
                                    self._pending.pop(mid, None)
                            raise TimeoutError(
                                f"host {self.host_id} request ring "
                                f"stayed full past the {secs:g}s op "
                                f"timeout") from e
                        time.sleep(min(0.05, max(1e-4, e.retry_after)))
            except ConnectionError:
                with self._send_lock:
                    for mid, _f in pend[sent:]:
                        self._pending.pop(mid, None)
                raise
        else:
            with self._send_lock:
                if self._dead is not None:
                    raise ConnectionError(
                        f"host {self.host_id} is dead: {self._dead}")
                for a in arrs:
                    mid = self._next_id
                    self._next_id += 1
                    fut = Future()
                    self._pending[mid] = fut
                    try:
                        self._conn.send({"id": mid, "op": "echo",
                                         "b": a})
                    except (OSError, ValueError) as e:
                        self._pending.pop(mid, None)
                        raise ConnectionError(
                            f"host {self.host_id} send failed: "
                            f"{e!r}") from e
                    pend.append((mid, fut))
        return [self._await(f, m, timeout) for m, f in pend]

    def debug_wire(self, mode: str) -> None:
        """Fire-and-forget drill trigger (scripts/fabric_drill.py):
        ask the worker to emit a deliberately corrupt wire reply. No
        reply is waited for — the corruption's detection IS the
        response."""
        with self._send_lock:
            if self._conn is not None and self._dead is None:
                self._conn.send({"id": -2, "op": "_debug_wire",
                                 "mode": mode})

    def update(self, sid, U, V, replace: bool = False,
               timeout: float | None = None):
        return self._call("update", timeout=timeout, sid=sid,
                          U=np.asarray(U), V=np.asarray(V),
                          replace=replace)

    def checkpoint(self, timeout: float | None = None) -> str:
        return self._call("checkpoint", timeout=timeout)

    def adopt(self, src, names, timeout: float | None = None) -> list:
        return self._call("adopt", timeout=timeout, src=src,
                          names=list(names))

    def migrate_out(self, sid, dest,
                    timeout: float | None = None) -> str:
        return self._call("migrate_out", timeout=timeout, sid=sid,
                          dest=dest)

    def replicate(self, src, names, gen,
                  timeout: float | None = None) -> list:
        return self._call("replicate", timeout=timeout, src=src,
                          names=list(names), gen=int(gen))

    def adopt_replica(self, items,
                      timeout: float | None = None) -> dict:
        return self._call("adopt_replica", timeout=timeout,
                          items=[[s, n] for s, n in items])

    def drop_replica(self, names,
                     timeout: float | None = None) -> int:
        return self._call("drop_replica", timeout=timeout,
                          names=list(names))

    def drop(self, sid, timeout: float | None = None) -> bool:
        return self._call("drop", timeout=timeout, sid=sid)

    def stats(self, timeout: float | None = None) -> dict:
        return self._call("stats", timeout=timeout)

    def close(self) -> None:
        if self._proc is None:
            return
        try:
            self._call("close", timeout=30.0)
        except (ConnectionError, EOFError, OSError):
            pass
        self._teardown()

    def kill(self) -> None:
        """Hard-kill the worker process (drills). The torn pipe fails
        every in-flight request with ConnectionError."""
        if self._proc is None:
            return
        try:
            with self._send_lock:
                if self._dead is None and self._conn is not None:
                    self._conn.send({"id": -1, "op": "kill"})
        except (OSError, ValueError):
            pass
        try:
            self._proc.wait(timeout=2.0)
        except subprocess.TimeoutExpired:
            self._proc.kill()
        self._fail(ConnectionError(f"host {self.host_id} killed"))
        self._teardown(wait=False)

    def _teardown(self, wait: bool = True) -> None:
        if self._wire is not None:
            # closes the pump and UNLINKS both segments (the front
            # created them) — /dev/shm stays clean even when the
            # worker was SIGKILLed mid-write
            self._wire.close()
            self._wire = None
        if self._conn is not None:
            try:
                self._conn.close()
            except OSError:
                pass
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        if self._proc is not None and wait:
            try:
                self._proc.wait(timeout=30.0)
            except subprocess.TimeoutExpired:
                self._proc.kill()
        if getattr(self, "_log", None) is not None:
            self._log.close()
        self._fail(ConnectionError(f"host {self.host_id} closed"))


# --------------------------------------------------------------------------- #
# the worker process
# --------------------------------------------------------------------------- #


def _send_locked(conn, lock, payload: dict) -> None:
    with lock:
        conn.send(payload)


def worker_main(argv=None) -> int:
    """``python -m conflux_tpu.fabric --worker`` — one engine host.

    Connects BACK to the front's listener (authkey from the
    CONFLUX_FABRIC_KEY env var), builds its ServeEngine, then serves
    ops. The recv loop stays responsive while heavy ops run: `solve`
    rides the engine's own async submit (reply from the future's done
    callback — coalescing is preserved), and barrier ops
    (open/checkpoint/adopt/migrate_out/update) run on a small op pool
    so a long checkpoint cannot starve heartbeat replies. EOF on the
    pipe (front gone) closes the engine and exits cleanly; the 'kill'
    op exits abruptly (drills)."""
    import argparse

    ap = argparse.ArgumentParser(prog="python -m conflux_tpu.fabric")
    ap.add_argument("--worker", action="store_true", required=True)
    ap.add_argument("--host-id", required=True)
    ap.add_argument("--connect", required=True,
                    help="front's AF_UNIX listener path")
    ap.add_argument("--ckpt-dir", required=True)
    ap.add_argument("--engine-json", default="{}")
    ap.add_argument("--wire-json", default=None,
                    help="shm wire spec: segment names + WireConfig")
    args = ap.parse_args(argv)

    key = bytes.fromhex(os.environ["CONFLUX_FABRIC_KEY"])
    conn = Client(args.connect, family="AF_UNIX", authkey=key)
    send_lock = threading.Lock()

    from conflux_tpu.engine import ServeEngine

    eng = ServeEngine(**json.loads(args.engine_json))
    core = _HostCore(args.host_id, args.ckpt_dir, eng)
    pool = ThreadPoolExecutor(max_workers=2,
                              thread_name_prefix="fabric-op")

    wire_srv: wire_mod.WireServer | None = None
    if args.wire_json is not None:
        spec = json.loads(args.wire_json)
        cfg = WireConfig.from_json(spec["cfg"])
        # ATTACH only — the front owns creation and unlink, so a
        # worker death (even SIGKILL) can never leak /dev/shm entries
        req_ring = wire_mod.Ring.attach(spec["req"], reclaim="local")
        rep_ring = wire_mod.Ring.attach(spec["rep"], reclaim="shared")
        wire_srv = wire_mod.WireServer(
            req_ring, rep_ring,
            lambda frame: _send_locked(conn, send_lock, frame),
            host_id=args.host_id, config=cfg, encode_exc=_encode_exc)

    def _wire_submit_many(batch):
        """[(sid, b_view, qos_dict)] -> aligned futures. Session and
        qos resolution fail PER ITEM (a bad sid must not poison its
        frame-mates); the survivors ride the engine's single-lock
        batched admission."""
        futs: list[Future | None] = [None] * len(batch)
        live = []
        for i, (sid, b, q) in enumerate(batch):
            try:
                s = core._session(sid)
                qc = None if q is None else qos_mod.class_from_wire(q)
            except Exception as e:
                f: Future = Future()
                f.set_exception(e)
                futs[i] = f
            else:
                live.append((i, s, b, qc))
        if live:
            engine_futs = eng.submit_many(
                [(s, b, qc) for _, s, b, qc in live])
            for (i, _s, _b, _qc), f in zip(live, engine_futs):
                futs[i] = f
        return futs

    def reply_solve(fut: Future, mid: int) -> None:
        try:
            val = fut.result()
        # conflint: disable=CFX-EXCEPT worker op boundary: every failure (kills included) is wired back to the front
        except BaseException as e:
            payload = {"id": mid, **_encode_exc(e)}
        else:
            payload = {"id": mid, "ok": True, "value": val}
        try:
            _send_locked(conn, send_lock, payload)
        except (OSError, ValueError):
            pass  # front is gone; EOF will land on the recv loop

    def run_op(mid: int, op: str, kw: dict) -> None:
        try:
            if op == "ping":
                val: Any = core.ping()
            elif op == "open":
                val = core.open(kw["sid"], kw["spec"], kw["A"],
                                kw.get("policy"))
            elif op == "update":
                val = core.update(kw["sid"], kw["U"], kw["V"],
                                  kw.get("replace", False))
            elif op == "checkpoint":
                val = core.checkpoint()
            elif op == "adopt":
                val = core.adopt(kw["src"], kw["names"])
            elif op == "migrate_out":
                val = core.migrate_out(kw["sid"], kw["dest"])
            elif op == "replicate":
                val = core.replicate(kw["src"], kw["names"], kw["gen"])
            elif op == "adopt_replica":
                val = core.adopt_replica(kw["items"])
            elif op == "drop_replica":
                val = core.drop_replica(kw["names"])
            elif op == "drop":
                val = core.drop(kw["sid"])
            elif op == "stats":
                val = core.stats()
            elif op == "echo":
                val = kw["b"]  # RPC microbench: transport cost only
            else:
                raise ValueError(f"unknown fabric op {op!r}")
        # conflint: disable=CFX-EXCEPT worker op boundary: every failure (kills included) is wired back to the front
        except BaseException as e:
            payload = {"id": mid, **_encode_exc(e)}
        else:
            payload = {"id": mid, "ok": True, "value": val}
        try:
            _send_locked(conn, send_lock, payload)
        except (OSError, ValueError):
            pass

    try:
        while True:
            try:
                msg = conn.recv()
            except (EOFError, OSError):
                break
            op = msg.get("op")
            mid = msg.get("id")
            if op == "kill":
                os._exit(1)
            if op == "close":
                _send_locked(conn, send_lock,
                             {"id": mid, "ok": True, "value": True})
                break
            if op == "solve_many":
                # the zero-copy wire's batched solve frame: descriptor
                # -> shm view -> single-lock engine admission; replies
                # ride the reply ring via the server's pump
                if wire_srv is not None:
                    wire_srv.handle(msg, _wire_submit_many)
                continue
            if op == "_debug_wire":
                # drill hook: emit a deliberately corrupt wire reply
                # (fire-and-forget — detection is the response)
                if wire_srv is not None:
                    if msg.get("mode") == "die_mid_write":
                        wire_srv.debug_partial_write()
                        os._exit(1)
                    wire_srv.debug_corrupt(msg.get("mode",
                                                   "torn_reply"))
                continue
            if op == "solve":
                try:
                    q = msg.get("qos")
                    fut = core.solve_async(
                        msg["sid"], msg["b"],
                        qos=None if q is None
                        else qos_mod.class_from_wire(q))
                # conflint: disable=CFX-EXCEPT worker op boundary: admission failures are wired back to the front
                except BaseException as e:
                    _send_locked(conn, send_lock,
                                 {"id": mid, **_encode_exc(e)})
                else:
                    fut.add_done_callback(
                        lambda f, mid=mid: reply_solve(f, mid))
                continue
            if op == "ping":
                run_op(mid, op, msg)  # inline: must outrun the op pool
                continue
            pool.submit(run_op, mid, op, dict(msg))
    finally:
        pool.shutdown(wait=False)
        eng.close()
        if wire_srv is not None:
            wire_srv.close()  # detach only; the front unlinks
        try:
            conn.close()
        except OSError:
            pass
    return 0


# --------------------------------------------------------------------------- #
# the fabric front
# --------------------------------------------------------------------------- #

_FABRICS: "weakref.WeakSet[ServeFabric]" = weakref.WeakSet()


class ServeFabric:
    """The routing front over a fleet of engine hosts (DESIGN §28).

    Construct with started-or-not :class:`HostHandle`s, call
    :meth:`start`, then `open`/`solve`/`update` by session id. The
    heartbeat, background-checkpoint and fail-over machinery runs on
    two daemon threads; `close()` stops them and the hosts.
    """

    def __init__(self, hosts, *, policy: FabricPolicy | None = None,
                 fault_plan=None, root: str | None = None):
        handles = list(hosts)
        if not handles:
            raise ValueError("a fabric needs at least one host")
        ids = [h.host_id for h in handles]
        if len(set(ids)) != len(ids):
            raise ValueError(f"duplicate host ids: {ids}")
        self.policy = policy if policy is not None else FabricPolicy()
        self._hosts: dict[str, HostHandle] = {h.host_id: h
                                              for h in handles}
        if root is None:
            import tempfile

            root = tempfile.mkdtemp(prefix="conflux-fabric-")
        self.root = root
        os.makedirs(root, exist_ok=True)
        self._faults = fault_plan
        self._lock = threading.Lock()
        self._state = {h: "alive" for h in self._hosts}  # guarded-by: _lock
        self._misses = {h: 0 for h in self._hosts}       # guarded-by: _lock
        self._owners: dict[Any, str] = {}                # guarded-by: _lock
        # inverted ownership index + capacity pricing (DESIGN §35):
        # `_owned[hid]` mirrors `_owners` per host, `_sid_cost` is the
        # session's qos.request_cost weight fixed at admission, and
        # `_host_cost[hid]` the per-host sum — so fail-over, drain,
        # replica pushes and the rebalancer read a host's load in
        # O(owned)/O(hosts) instead of scanning the fleet-wide map,
        # and a large-N mesh tenant weighs as the capacity it
        # actually consumes (ISSUE 20 satellite).
        self._owned: dict[str, set] = {}                 # guarded-by: _lock
        self._sid_cost: dict[Any, float] = {}            # guarded-by: _lock
        self._host_cost: dict[str, float] = {}           # guarded-by: _lock
        self._lost: dict[Any, str] = {}                  # guarded-by: _lock
        self._recoveries: list[dict] = []                # guarded-by: _lock
        self._mig_seq = 0                                # guarded-by: _lock
        self._ckpt_rounds = 0                            # guarded-by: _lock
        self._closed_sids = 0                            # guarded-by: _lock
        self._admitted_sids = 0                          # guarded-by: _lock
        # elastic membership (DESIGN §34). _reserved: ids an in-flight
        # add_host claimed in its first critical section (the TOCTOU
        # fix — a racing duplicate add fails BEFORE starting a second
        # worker). _retired: ids that died or were removed; they never
        # resurrect — a returning/zombie process must come back under
        # a fresh identity or stale routing state could alias it.
        # _failing: hosts whose fail-over is in flight (remove_host of
        # a corpse waits this out instead of yanking the handle the
        # fail-over is still reading).
        self._reserved: set[str] = set()                 # guarded-by: _lock
        self._retired: set[str] = set()                  # guarded-by: _lock
        self._failing: set[str] = set()                  # guarded-by: _lock
        # sid -> {standby host id: replica generation} (K-replica
        # placement; generations are the primary's fleet-NNNNNN seq,
        # the coherence token fail-over's re-point gate checks)
        self._replicas: dict[Any, dict[str, int]] = {}   # guarded-by: _lock
        self._breakers = {h: CircuitBreaker(self.policy.breaker_threshold,
                                            self.policy.breaker_cooldown)
                          for h in self._hosts}
        self._windows = {h: CounterWindow() for h in self._hosts}
        self.load = HostLoadEstimator(floor=self.policy.retry_floor,
                                      ceil=self.policy.retry_ceil)
        self._stop = threading.Event()
        self._hb_thread: threading.Thread | None = None
        self._ckpt_thread: threading.Thread | None = None
        self._closed = False
        _FABRICS.add(self)

    # -- lifecycle ----------------------------------------------------- #

    def start(self) -> "ServeFabric":
        for h in self._hosts.values():
            h.start()
            if isinstance(h, LocalHost):
                h.core.ckpt_keep = self.policy.checkpoint_keep
                h.core.ckpt_compact_every = \
                    self.policy.checkpoint_compact_every
        self._hb_thread = threading.Thread(
            target=self._hb_loop, daemon=True, name="fabric-heartbeat")
        self._hb_thread.start()
        if self.policy.checkpoint_interval > 0:
            self._ckpt_thread = threading.Thread(
                target=self._ckpt_loop, daemon=True, name="fabric-ckpt")
            self._ckpt_thread.start()
        return self

    def close(self) -> None:
        self._closed = True
        self._stop.set()
        for t in (self._hb_thread, self._ckpt_thread):
            if t is not None:
                t.join(timeout=10.0)
        for h in self._hosts.values():
            try:
                h.close()
            except (ConnectionError, EOFError, OSError):
                pass

    def __enter__(self) -> "ServeFabric":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # -- host census --------------------------------------------------- #

    def _live(self) -> list[str]:
        """Hosts eligible for NEW placement and fail-over adoption:
        alive or suspect (a suspect host still answers most traffic;
        only DEATH moves sessions — the hysteresis half of the
        no-reshuffle story). Draining hosts (scale-in in progress,
        DESIGN §34) are excluded — they keep serving the sessions
        they still own but take nothing new."""
        with self._lock:
            return sorted(h for h, s in self._state.items()
                          if s not in ("dead", "draining"))

    def _alive(self) -> list[str]:
        with self._lock:
            return sorted(h for h, s in self._state.items()
                          if s == "alive")

    def host_state(self, host_id: str) -> str:
        with self._lock:
            return self._state[host_id]

    def owner_of(self, sid) -> str | None:
        with self._lock:
            return self._owners.get(sid)

    # requires-lock: _lock
    def _own(self, sid, hid: str) -> None:
        """Single writer for the ownership map: keeps `_owned` and the
        per-host cost gauge in lockstep with `_owners` (DESIGN §35) —
        every ownership change MUST route through here or `_disown`."""
        old = self._owners.get(sid)
        c = self._sid_cost.get(sid, 1.0)
        if old is not None:
            s = self._owned.get(old)
            if s is not None:
                s.discard(sid)
            self._host_cost[old] = self._host_cost.get(old, 0.0) - c
        self._owners[sid] = hid
        self._owned.setdefault(hid, set()).add(sid)
        self._host_cost[hid] = self._host_cost.get(hid, 0.0) + c

    # requires-lock: _lock
    def _disown(self, sid) -> None:
        """Retire a session from the ownership map + index (close,
        loss, voided admission). Drops its cost entry — a re-admission
        re-prices at open."""
        hid = self._owners.pop(sid, None)
        c = self._sid_cost.pop(sid, 1.0)
        if hid is None:
            return
        s = self._owned.get(hid)
        if s is not None:
            s.discard(sid)
        self._host_cost[hid] = self._host_cost.get(hid, 0.0) - c

    def owner_census(self) -> dict[str, int]:
        """{host id: owned-session count} — the autoscaler's memory
        axis and the rebalancer's skew input. O(hosts) off the
        inverted index, not O(fleet)."""
        with self._lock:
            return {h: len(s) for h, s in self._owned.items() if s}

    def taken_ids(self) -> set[str]:
        """Every host id that would be refused by :meth:`add_host` —
        present, reserved by an in-flight add, or permanently retired.
        The autoscaler mints fresh ids against this set."""
        with self._lock:
            return set(self._hosts) | self._reserved | self._retired

    def add_host(self, handle: HostHandle) -> None:
        """Grow the live set at runtime (scale-out, DESIGN §34). New
        sessions HRW over the enlarged set; existing owners do not
        move — scale-out is adopt-on-arrival, with :meth:`rebalance`
        draining induced skew deliberately.

        The id is RESERVED in the first critical section, so two
        concurrent add_host calls with the same id race on the
        reservation, not on `handle.start()` — exactly one starts a
        worker, the loser fails before owning any resource (the old
        check-then-insert TOCTOU leaked a started handle). Retired
        ids (died or removed) are refused permanently: a dead host's
        identity never resurrects."""
        hid = handle.host_id
        with self._lock:
            if hid in self._hosts or hid in self._reserved:
                raise ValueError(f"host id {hid!r} already present")
            if hid in self._retired:
                raise ValueError(
                    f"host id {hid!r} is retired (it died or was "
                    "removed) — dead ids never resurrect; rejoin "
                    "under a fresh id")
            self._reserved.add(hid)
        try:
            handle.start()
            self._breakers[hid] = CircuitBreaker(
                self.policy.breaker_threshold,
                self.policy.breaker_cooldown)
            self._windows[hid] = CounterWindow()
        except BaseException:
            with self._lock:
                self._reserved.discard(hid)
            raise
        if isinstance(handle, LocalHost):
            handle.core.ckpt_keep = self.policy.checkpoint_keep
        with self._lock:
            self._reserved.discard(hid)
            self._hosts[hid] = handle
            self._state[hid] = "alive"
            self._misses[hid] = 0
        bump("fabric_hosts_added")

    def remove_host(self, host_id: str, *, drain: bool = True) -> list:
        """Leave the fleet at runtime (scale-in, DESIGN §34).

        A live host first moves to the ``draining`` state — it keeps
        serving the sessions it owns but is excluded from new
        placement, fail-over adoption and migration targets — then a
        drain-barrier migration storm rides the §28 :meth:`migrate`
        path once per owned sid (HRW remaps only the departing host's
        sessions; nobody else reshuffles). Only when the host owns
        nothing is it retired: handle closed, id permanently refused
        by :meth:`add_host`. A crash (of this caller or a migration
        target) mid-drain leaves every undrained session owned by the
        still-live source, which returns to ``alive`` — scale-in is
        abandoned, not half-applied — and the partial storm raises
        :class:`HostUnavailable` with a retry hint.

        Removing an already-dead host is pure bookkeeping: it waits
        out any in-flight fail-over reading the corpse's snapshot,
        then retires the entry. Returns the sids migrated off."""
        with self._lock:
            if host_id not in self._hosts:
                raise KeyError(f"unknown host {host_id!r}")
            st = self._state[host_id]
            if st == "draining":
                raise ValueError(f"host {host_id!r} is already "
                                 "draining")
        if st == "dead":
            # fail-over (heartbeat thread) may still be reading the
            # corpse's checkpoint via self._hosts[hid] — wait it out
            deadline = time.monotonic() + self.policy.call_timeout
            while time.monotonic() < deadline:
                with self._lock:
                    busy = host_id in self._failing
                if not busy:
                    break
                time.sleep(0.01)
            self._retire(host_id, close=False)
            return []
        if len(self._live()) - 1 < self.policy.min_live:
            raise FleetDegraded(
                f"removing {host_id} would leave "
                f"{len(self._live()) - 1} live hosts, below min_live="
                f"{self.policy.min_live}",
                retry_after=self._retry_hint(),
                live=len(self._live()), total=len(self._hosts))
        with self._lock:
            self._state[host_id] = "draining"
        moved: list = []
        if drain:
            with self._lock:
                owned = sorted(self._owned.get(host_id) or (), key=str)
            for sid in owned:
                try:
                    self.migrate(sid)
                # conflint: disable=CFX-EXCEPT an injected kill fails ONE drain migration; the storm's partial-result accounting below abandons the scale-in and the monitor owns the death
                except (HostUnavailable, FleetDegraded, InjectedFault,
                        InjectedKill):
                    continue  # undrained: stays on the live source
                moved.append(sid)
        with self._lock:
            undrained = sorted(self._owned.get(host_id) or (), key=str)
            died = self._state.get(host_id) == "dead"
        if undrained and not died:
            # put the host back in service; the caller retries
            with self._lock:
                if self._state.get(host_id) == "draining":
                    self._state[host_id] = "alive"
            if moved:
                bump("fabric_drain_migrations", len(moved))
            raise HostUnavailable(
                f"drain of {host_id} incomplete: {len(undrained)} "
                f"session(s) still owned (moved {len(moved)}) — host "
                "stays in service; retry",
                retry_after=self._retry_hint(len(undrained)),
                host=host_id)
        if died:
            # the host died mid-drain: its heartbeat fail-over has
            # (or will) re-home the rest; retire bookkeeping only
            self._retire(host_id, close=False)
        else:
            self._retire(host_id, close=True)
        if moved:
            bump("fabric_drain_migrations", len(moved))
        bump("fabric_hosts_removed")
        return moved

    def _retire(self, hid: str, *, close: bool) -> None:
        """Purge a host's entry and permanently retire its id."""
        with self._lock:
            handle = self._hosts.pop(hid, None)
            self._state.pop(hid, None)
            self._misses.pop(hid, None)
            self._retired.add(hid)
            for reps in self._replicas.values():
                reps.pop(hid, None)
        self._breakers.pop(hid, None)
        self._windows.pop(hid, None)
        self.load.forget(hid)
        if close and handle is not None:
            try:
                handle.close()
            except (ConnectionError, EOFError, OSError):
                pass

    def _pick_target(self, exclude: "set[str] | tuple" = (), *,
                     require_wire_headroom: bool = False) -> str | None:
        """THE migration-target picker — migrate, the drain storm and
        background rebalancing all route through here so placement
        policy lives in one place. Alive hosts only (suspect and
        draining hosts take nothing new), wire-congestion aware:
        hosts whose shm ring is ≥ 90% full are avoided, and with
        `require_wire_headroom` (the rebalancer — a HOT-host fix must
        not aim at a host about to shed RingFull) they are refused
        outright. Returns None when no candidate qualifies."""
        cands = [h for h in self._alive() if h not in exclude]
        if not cands:
            return None
        clear = [h for h in cands if self.load.wire_frac(h) < 0.9]
        if require_wire_headroom:
            return self.load.least_loaded(clear) if clear else None
        return self.load.least_loaded(clear or cands)

    # -- admission + request routing ----------------------------------- #

    def _fault_plan(self):
        return (self._faults if self._faults is not None
                else resilience.active_faults())

    def _retry_hint(self, backlog: int = 1) -> float:
        return self.load.retry_after(backlog, self._alive() or None)

    def open(self, sid, plan_or_spec, A, *, policy: dict | None = None,
             timeout: float | None = None):
        """Admit a session: place it on the rendezvous-chosen live
        host, factor there, optionally checkpoint it durable
        (`durable_open`). Refuses with :class:`FleetDegraded` below
        `min_live` live hosts and ValueError on a duplicate sid."""
        from conflux_tpu.engine import rendezvous
        from conflux_tpu.serve import FactorPlan, plan_spec

        spec = (plan_spec(plan_or_spec)
                if isinstance(plan_or_spec, FactorPlan)
                else dict(plan_or_spec))
        with self._lock:
            if sid in self._owners:
                raise ValueError(f"sid {sid!r} already open on host "
                                 f"{self._owners[sid]}")
            # reopening a lost sid is legal: the loss accounting is
            # resolved so the census identity admitted == open + lost
            # + closed stays EXACT across re-admission
            if self._lost.pop(sid, None) is not None:
                self._admitted_sids -= 1
            total = len(self._hosts)
        live = self._live()
        if len(live) < self.policy.min_live:
            raise FleetDegraded(
                f"{len(live)}/{total} hosts live, below min_live="
                f"{self.policy.min_live} — admission refused",
                retry_after=self._retry_hint(), live=len(live),
                total=total)
        hid = rendezvous(sid, live)
        self._route_fault(hid)
        host = self._hosts[hid]
        try:
            host.open(sid, spec, A, policy,
                      timeout=timeout if timeout is not None
                      else self.policy.call_timeout)
        except _TRANSPORT_ERRORS as e:
            self._note_request_failure(hid)
            raise HostUnavailable(
                f"host {hid} unreachable during open: {e}",
                retry_after=self._retry_hint(), host=hid) from e
        with self._lock:
            # price the tenant once at admission: the rebalancer and
            # autoscaler weigh this session by the capacity its shape
            # actually consumes (qos.request_cost, DESIGN §32)
            self._sid_cost[sid] = qos_mod.request_cost(
                tuple(spec["shape"]))
            self._own(sid, hid)
        if self.policy.durable_open:
            snap = self._checkpoint_host(hid)
            if snap is not None:
                # K-replica placement (DESIGN §34): the admission
                # snapshot's records land on the next K-1 ranked
                # hosts, so this session is re-pointable from birth
                self._push_replicas(hid, snap)
            else:
                # the host died inside the admission window: the
                # session is NOT durable, so the admission is void —
                # undo it and tell the caller to retry (the next open
                # lands on a survivor). Without this, a kill racing
                # durable_open admits a session that the very next
                # fail-over must declare lost.
                with self._lock:
                    self._disown(sid)
                try:
                    host.drop(sid, timeout=self.policy.call_timeout)
                except _TRANSPORT_ERRORS:
                    pass
                raise HostUnavailable(
                    f"host {hid} died before admission of {sid!r} "
                    "became durable — retry",
                    retry_after=self._retry_hint(), host=hid)
        with self._lock:
            self._admitted_sids += 1
        return sid

    def _route_fault(self, hid: str) -> None:
        try:
            maybe_fault(self._fault_plan(), "route")
        # conflint: disable=CFX-EXCEPT injected transport fault mapped to a structured HostUnavailable
        except (InjectedFault, InjectedKill) as e:
            self._note_request_failure(hid)
            raise HostUnavailable(
                f"host {hid} unreachable (injected route fault)",
                retry_after=self._retry_hint(), host=hid) from e

    def _resolve(self, sid) -> tuple[str, HostHandle]:
        """Route a request: owner lookup + state/breaker gates.
        Structured failures only — never a hang, never a stale pick."""
        with self._lock:
            lost = self._lost.get(sid)
            hid = self._owners.get(sid)
            st = None if hid is None else self._state[hid]
        if lost is not None:
            raise HostUnavailable(
                f"session {sid!r} was lost: {lost}", retry_after=0.0)
        if hid is None:
            raise KeyError(f"unknown sid {sid!r} — open it first")
        if st == "dead":
            raise HostUnavailable(
                f"host {hid} is dead; fail-over for {sid!r} is in "
                "flight", retry_after=self._retry_hint(), host=hid)
        br = self._breakers.get(hid)
        if br is None:  # retired mid-request
            raise HostUnavailable(
                f"host {hid} left the fleet; routing for {sid!r} is "
                "settling", retry_after=self._retry_hint(), host=hid)
        ok, cool = br.allow()
        if not ok:
            raise HostUnavailable(
                f"host {hid} circuit open (repeated transport "
                f"failures); probe in ~{cool:.2f}s",
                retry_after=max(cool, self._retry_hint()), host=hid)
        return hid, self._hosts[hid]

    def _note_request_failure(self, hid: str) -> None:
        br = self._breakers.get(hid)
        if br is not None:
            br.record_failure()

    def solve(self, sid, b, timeout: float | None = None, qos=None):
        """One routed solve. Transport failure on the owning host maps
        to :class:`HostUnavailable` with a measured-drain retry hint;
        the host's own structured errors (including per-tenant
        ``TenantThrottled``, attrs intact) pass through untouched.
        ``qos`` is a :class:`conflux_tpu.qos.QosClass` classifying the
        request on the OWNING host's engine — each host runs its own
        fair-share ledger over the tenants it actually serves."""
        if qos is not None and not isinstance(qos, qos_mod.QosClass):
            raise TypeError(f"qos must be a QosClass or None, got "
                            f"{type(qos).__name__}")
        hid, host = self._resolve(sid)
        self._route_fault(hid)
        try:
            out = host.solve(sid, b,
                             timeout=timeout if timeout is not None
                             else self.policy.call_timeout, qos=qos)
        except _TRANSPORT_ERRORS as e:
            self._note_request_failure(hid)
            raise HostUnavailable(
                f"host {hid} unreachable during solve({sid!r}): {e}",
                retry_after=self._retry_hint(), host=hid) from e
        br = self._breakers.get(hid)
        if br is not None:
            br.record_success()
        return out

    def update(self, sid, U, V, *, replace: bool = False,
               timeout: float | None = None):
        hid, host = self._resolve(sid)
        self._route_fault(hid)
        try:
            out = host.update(sid, U, V, replace=replace,
                              timeout=timeout if timeout is not None
                              else self.policy.call_timeout)
        except _TRANSPORT_ERRORS as e:
            self._note_request_failure(hid)
            raise HostUnavailable(
                f"host {hid} unreachable during update({sid!r}): {e}",
                retry_after=self._retry_hint(), host=hid) from e
        br = self._breakers.get(hid)
        if br is not None:
            br.record_success()
        return out

    def close_session(self, sid, timeout: float | None = None) -> bool:
        """Deliberately retire a session fleet-wide: drop the owner's
        live copy, the ownership entry and every standby replica. The
        load-recede half of elasticity (DESIGN §34) — admitted work
        must be able to END for utilization to fall and the
        autoscaler's scale-in lane to ever fire. The sid becomes
        reusable; the census conserves as
        admitted == open + lost + closed (`stats()['closed_sessions']`,
        the soak's conservation oracle)."""
        hid, host = self._resolve(sid)
        try:
            host.drop(sid, timeout=timeout if timeout is not None
                      else self.policy.call_timeout)
        except _TRANSPORT_ERRORS as e:
            self._note_request_failure(hid)
            raise HostUnavailable(
                f"host {hid} unreachable during close({sid!r}): {e}",
                retry_after=self._retry_hint(), host=hid) from e
        with self._lock:
            self._disown(sid)
            reps = self._replicas.pop(sid, None) or {}
            self._closed_sids += 1
        name = record_name(sid)
        for h in sorted(reps):
            handle = self._hosts.get(h)
            if handle is None:
                continue
            try:
                handle.drop_replica([name],
                                    timeout=self.policy.call_timeout)
            except _TRANSPORT_ERRORS:
                pass  # hygiene only; the generation gate covers it
        bump("fabric_sessions_closed")
        return True

    # -- heartbeat / detection ----------------------------------------- #

    def _hb_loop(self) -> None:
        while not self._stop.wait(self.policy.heartbeat_interval):
            try:
                self._hb_round()
            except Exception:  # noqa: BLE001 — the loop must survive
                bump("fabric_hb_errors")

    def _hb_round(self) -> None:
        plan = self._fault_plan()
        if plan is not None:
            s = plan.fire("host_kill", kinds=("kill",))
            if s is not None:
                victims = self._alive()
                if victims:
                    try:
                        self._hosts[victims[0]].kill()
                    except (ConnectionError, EOFError, OSError):
                        pass
        for hid in sorted(self._hosts):
            if self._closed:
                return
            with self._lock:
                # .get: a concurrent remove_host may retire entries
                # mid-round — a vanished host simply isn't probed
                if self._state.get(hid, "dead") == "dead":
                    continue
            self._probe(hid, plan)

    def _probe(self, hid: str, plan) -> None:
        host = self._hosts.get(hid)
        if host is None:
            return  # retired mid-round
        torn = False
        try:
            maybe_fault(plan, "heartbeat")
            payload = host.ping(timeout=self.policy.heartbeat_timeout)
        except (ConnectionError, EOFError, BrokenPipeError) as e:
            torn, payload = True, None
            del e
        # conflint: disable=CFX-EXCEPT an injected heartbeat kill IS the miss being counted
        except (InjectedFault, InjectedKill, OSError):
            payload = None  # includes TimeoutError: a miss, not a tear
        if payload is not None:
            with self._lock:
                if hid not in self._state:
                    return  # retired mid-probe
                self._misses[hid] = 0
                if self._state[hid] == "suspect":
                    self._state[hid] = "alive"
            win = self._windows.get(hid)
            if win is None:
                return
            counters = dict(payload.get("counters") or {})
            delta = win.feed(counters)
            # pending and wire occupancy are gauges: re-inject the raw
            # values after the window differences the payload
            delta["pending"] = counters.get("pending", 0)
            if "wire_used_frac" in counters:
                delta["wire_used_frac"] = counters["wire_used_frac"]
            self.load.feed(hid, delta)
            return
        bump("heartbeat_misses")
        with self._lock:
            if hid not in self._state:
                return  # retired mid-probe
            self._misses[hid] += 1
            m = self._misses[hid]
            st = self._state[hid]
        if torn or m >= self.policy.dead_after:
            self._declare_dead(hid)
        elif m >= self.policy.suspect_after and st == "alive":
            with self._lock:
                self._state[hid] = "suspect"
            bump("hosts_suspected")

    def _declare_dead(self, hid: str) -> None:
        with self._lock:
            if self._state.get(hid, "dead") == "dead":
                return
            self._state[hid] = "dead"
            # claimed under the SAME lock acquisition that flips the
            # state: remove_host of a corpse waits out _failing, so
            # there must be no window where the state reads dead but
            # the claim isn't visible yet
            self._failing.add(hid)
        bump("hosts_died")
        self.load.forget(hid)
        try:
            self._failover(hid)
        finally:
            with self._lock:
                self._failing.discard(hid)

    # -- fail-over ------------------------------------------------------ #

    def _failover(self, hid: str) -> None:
        """Re-home a dead host's sessions onto the survivors.

        Two rails, re-point first (DESIGN §34): a sid whose live
        standby holds a replica record at a generation ≥ the corpse's
        latest snapshot generation is RE-POINTED — the standby adopts
        from its own local replica store, zero cross-host reads (with
        K ≥ 2 this is the whole fleet's fast path). Everything else
        falls back to the §28 snapshot restore: read the corpse's
        last complete checkpoint, group by rendezvous, one adopt RPC
        per target. The generation gate is the coherence rule — a
        standby whose push failed last round is STALE relative to the
        durable snapshot and re-pointing to it would roll the session
        back further than the documented one-interval bound, so it is
        refused, not trusted. Sids with no record anywhere are
        declared lost with a structured reason."""
        from conflux_tpu.engine import rendezvous

        t0 = time.perf_counter()
        with self._lock:
            owned = sorted(self._owned.get(hid) or (), key=str)
            reps = {sid: dict(self._replicas.get(sid, {}))
                    for sid in owned}
        handle = self._hosts.get(hid)
        snap = (latest_checkpoint(handle.ckpt_dir)
                if handle is not None else None)
        snap_gen = _snapshot_gen(snap)
        manifest = checkpoint_manifest(snap) if snap is not None else {}
        have = {sid: nm for sid, (nm, _g) in manifest.items()}
        adopted: dict[Any, str] = {}
        repointed: dict[Any, str] = {}
        lost: dict[Any, str] = {}

        # rail 1: re-point to live standbys holding coherent replicas.
        # The coherence bar is the RECORD's write generation (§35): a
        # session clean since generation g is current on any standby
        # pushed at ≥ g — delta checkpoints and skipped clean pushes
        # never widen the staleness bound. Sids absent from the
        # snapshot fall back to the snapshot-generation bar.
        live_set = set(self._live())
        groups_rp: dict[str, list] = {}
        for sid in owned:
            ent = manifest.get(sid)
            need = ent[1] if ent is not None else snap_gen
            cands = sorted(
                ((g, h) for h, g in reps[sid].items()
                 if h in live_set and g >= need),
                reverse=True)
            if cands:
                groups_rp.setdefault(cands[0][1], []).append(sid)
        for tgt, sids in sorted(groups_rp.items()):
            try:
                out = self._hosts[tgt].adopt_replica(
                    [[s, record_name(s)] for s in sids],
                    timeout=self.policy.call_timeout)
            except _TRANSPORT_ERRORS:
                # standby dying too — its sids ride the snapshot rail
                self._note_request_failure(tgt)
                continue
            for s, _gen in out.get("adopted", []):
                repointed[s] = tgt
                adopted[s] = tgt
        if repointed:
            with self._lock:
                for s, tgt in repointed.items():
                    self._replicas.get(s, {}).pop(tgt, None)
            bump("fabric_replica_repoints", len(repointed))

        # rail 2: §28 snapshot restore for everything not re-pointed
        for sid in owned:
            if sid not in adopted and sid not in have:
                lost[sid] = (f"host {hid} died before {sid!r} was "
                             "ever checkpointed")
        excluded: set[str] = set()
        remaining = [sid for sid in owned
                     if sid in have and sid not in adopted]
        if remaining:
            bump("fabric_snapshot_restores", len(remaining))
        while remaining:
            live = [h for h in self._live() if h not in excluded]
            if not live:
                for sid in remaining:
                    lost[sid] = (f"host {hid} died and no live host "
                                 f"could adopt {sid!r}")
                break
            groups: dict[str, list] = {}
            for sid in remaining:
                groups.setdefault(rendezvous(sid, live), []).append(sid)
            remaining = []
            for tgt, sids in sorted(groups.items()):
                try:
                    self._hosts[tgt].adopt(
                        snap, [have[s] for s in sids],
                        timeout=self.policy.call_timeout)
                except _TRANSPORT_ERRORS:
                    # the target is dying too: exclude it and re-home
                    # its share on the next pass (its own heartbeat
                    # death will run its own fail-over)
                    self._note_request_failure(tgt)
                    excluded.add(tgt)
                    remaining.extend(sids)
                else:
                    for s in sids:
                        adopted[s] = tgt
        with self._lock:
            for sid, tgt in adopted.items():
                self._own(sid, tgt)
            for sid, why in lost.items():
                self._disown(sid)
                self._replicas.pop(sid, None)
                self._lost[sid] = why
            dt = time.perf_counter() - t0
            self._recoveries.append(
                {"host": hid, "seconds": dt, "adopted": len(adopted),
                 "repointed": len(repointed), "lost": len(lost),
                 "snapshot": os.path.basename(snap) if snap else None})
        bump("host_failovers")
        if adopted:
            bump("sessions_failed_over", len(adopted))
        if adopted and self.policy.durable_open:
            # re-adoption is re-admission: fold the moved sessions
            # into each adopter's own fleet snapshot NOW (and re-seed
            # their standbys) — otherwise an adopter death inside one
            # checkpoint interval would lose the very sessions this
            # fail-over just saved. After the recovery record: the
            # measured recovery time is the adopt, not the re-arm.
            for tgt in sorted(set(adopted.values())):
                snap2 = self._checkpoint_host(tgt)
                if snap2 is not None:
                    self._push_replicas(tgt, snap2)

    # -- migration ------------------------------------------------------ #

    def migrate(self, sid, target: str | None = None) -> str:
        """Hand a live session to another host at a drain barrier.

        Order is crash-safe: (1) the source checkpoints exactly this
        session (its engine drains in-flight work first), (2) the
        target adopts the record, (3) ownership flips, (4) the source
        drops its copy. A failure at or before (2) leaves the session
        intact and owned by the source. Migrated sessions solve
        BITWISE identically (the checkpoint contract). Returns the
        target host id."""
        hid, src = self._resolve(sid)
        if target is None:
            target = self._pick_target(exclude={hid})
            if target is None:
                raise FleetDegraded(
                    f"no live migration target for {sid!r} "
                    f"(source {hid})",
                    retry_after=self._retry_hint(),
                    live=len(self._alive()), total=len(self._hosts))
        elif target == hid:
            raise ValueError(f"migrate target equals source {hid!r}")
        elif self.host_state(target) != "alive":
            raise HostUnavailable(
                f"migrate target {target} is "
                f"{self.host_state(target)}",
                retry_after=self._retry_hint(), host=target)
        with self._lock:
            seq = self._mig_seq
            self._mig_seq += 1
        dest = os.path.join(self.root, "migrate", f"m{seq:06d}")
        try:
            name = src.migrate_out(sid, dest,
                                   timeout=self.policy.call_timeout)
        except _TRANSPORT_ERRORS as e:
            self._note_request_failure(hid)
            raise HostUnavailable(
                f"migration source {hid} unreachable: {e}",
                retry_after=self._retry_hint(), host=hid) from e
        # the hand-off barrier: a crash HERE (record written, target
        # not yet adopting) leaves the session intact on the source
        maybe_fault(self._fault_plan(), "migrate")
        try:
            self._hosts[target].adopt(dest, [name],
                                      timeout=self.policy.call_timeout)
        except _TRANSPORT_ERRORS as e:
            self._note_request_failure(target)
            raise HostUnavailable(
                f"migration target {target} unreachable — {sid!r} "
                f"stays on {hid}", retry_after=self._retry_hint(),
                host=target) from e
        with self._lock:
            self._own(sid, target)
        try:
            src.drop(sid, timeout=self.policy.call_timeout)
        except _TRANSPORT_ERRORS:
            pass  # source copy is unreachable garbage; fail-over skips
            # moved sids because ownership already flipped
        if self.policy.durable_open:
            # migration is re-admission on the target: fold the moved
            # session into the target's own fleet snapshot NOW, or a
            # target death inside one checkpoint interval loses it
            snap = self._checkpoint_host(target)
            if snap is not None:
                # re-seed the moved session's standbys for the new
                # primary (and retire standbys the new ranking drops)
                self._push_replicas(target, snap)
        bump("sessions_migrated")
        return target

    def rebalance(self, *, max_moves: int = 2, ratio: float = 2.0,
                  floor: int = 4) -> list:
        """One bounded background-rebalancing pass (DESIGN §34).

        Skew detector + corrective storm: find the hottest alive host
        by owned CAPACITY COST (each session weighted by its
        `qos.request_cost` at admission — one large-N mesh tenant
        counts as the capacity it actually consumes, ISSUE 20
        satellite; with uniform shapes this reduces exactly to the
        former session-count greed). When the hot host carries more
        than `ratio` × the alive-host mean cost (and at least `floor`
        sessions — tiny fleets are never 'skewed'), live-migrate up to
        `max_moves` of its costliest sessions through
        :meth:`_pick_target` with the wire-headroom requirement (a
        hot-host fix must not aim at a ≥90% full wire). Everything
        else preserves the no-reshuffle contract: only the hot host's
        sids move, at a bounded rate, each over the §28 crash-safe
        migrate barrier. Returns the sids moved. The
        :class:`~conflux_tpu.control.FabricAutoscaler` calls this
        every tick; it is also a public one-shot knob. Census reads
        ride the inverted `_owned` index — O(hosts + hot-host owned),
        not O(fleet) (DESIGN §35)."""
        alive = self._alive()
        if len(alive) < 2:
            return []
        with self._lock:
            counts = {h: len(self._owned.get(h) or ()) for h in alive}
            costs = {h: self._host_cost.get(h, 0.0) for h in alive}
            hot = max(alive, key=lambda h: (costs[h], counts[h], h))
            # costliest first; str(sid) tie-break keeps uniform-cost
            # fleets on the former deterministic victim order
            victims = sorted(
                self._owned.get(hot) or (),
                key=lambda s: (-self._sid_cost.get(s, 1.0), str(s)))
            vcost = {s: self._sid_cost.get(s, 1.0) for s in victims}
        mean = sum(costs.values()) / len(alive)
        if counts[hot] < floor or costs[hot] <= ratio * max(mean, 1e-9):
            return []
        excess = costs[hot] - mean
        moved_cost = 0.0
        moved: list = []
        for sid in victims:
            if len(moved) >= int(max_moves):
                break
            if moved and moved_cost >= excess:
                break  # enough capacity moved to reach the mean
            tgt = self._pick_target(exclude={hot},
                                    require_wire_headroom=True)
            if tgt is None:
                break  # nobody has headroom: try again next tick
            try:
                self.migrate(sid, target=tgt)
            # conflint: disable=CFX-EXCEPT an injected kill ends THIS rebalance tick (best-effort background bleed); the monitor owns the death
            except (HostUnavailable, FleetDegraded, ValueError,
                    KeyError, InjectedFault, InjectedKill):
                break
            moved.append(sid)
            moved_cost += vcost[sid]
        if moved:
            bump("fabric_rebalance_migrations", len(moved))
        return moved

    # -- checkpointing -------------------------------------------------- #

    def _ckpt_loop(self) -> None:
        while not self._stop.wait(self.policy.checkpoint_interval):
            try:
                self.checkpoint_all()
            except Exception:  # noqa: BLE001 — the loop must survive
                bump("fabric_ckpt_errors")

    def checkpoint_all(self) -> dict[str, str | None]:
        """One background-checkpoint round: snapshot every alive
        host's fleet (each at its own drain barrier). Returns
        {host_id: snapshot dir | None} (None: host unreachable —
        its heartbeat will deal with it)."""
        out: dict[str, str | None] = {}
        for hid in self._alive():
            snap = self._checkpoint_host(hid)
            out[hid] = snap
            if snap is not None:
                self._push_replicas(hid, snap)
        with self._lock:
            self._ckpt_rounds += 1
        return out

    def _push_replicas(self, hid: str, snap: str) -> None:
        """Seed/refresh standby replicas off one host snapshot
        (DESIGN §34 K-replica placement, no-op at K=1).

        For every sid the host owns, the next K-1 hosts on its
        rendezvous-RANKED candidate list (owner excluded) receive a
        local copy of its record, batched one `replicate` RPC per
        standby, all tagged with the snapshot's generation — the
        coherence token `_failover`'s re-point gate checks. Two §35
        scale rails: the push set is DIRTY-ONLY (a standby whose last
        accepted generation is ≥ the record's write generation already
        holds those exact bytes — clean sessions cost zero wire), and
        the per-standby RPCs dispatch CONCURRENTLY, mirroring how
        fail-over batches `adopt_replica` — a push round costs one
        slowest-standby round trip, not the sum. Standbys the new
        ranking drops (membership changed, session migrated) get a
        best-effort `drop_replica`. Push failures are counted, never
        fatal: the session stays durable via the primary snapshot,
        and the stale standby is exactly what the generation gate
        exists to refuse."""
        if self.policy.replicas <= 1:
            return
        from conflux_tpu.engine import rendezvous_ranked

        gen = _snapshot_gen(snap)
        with self._lock:
            owned = sorted(self._owned.get(hid) or (), key=str)
        if not owned:
            return
        try:
            manifest = checkpoint_manifest(snap)
        except (OSError, ValueError, KeyError):
            return
        cands = [h for h in self._live() if h != hid]
        groups: dict[str, list] = {}
        stale: dict[str, list] = {}
        for sid in owned:
            ent = manifest.get(sid)
            if ent is None:
                continue
            name, egen = ent
            standbys = rendezvous_ranked(
                sid, cands, k=self.policy.replicas - 1)
            with self._lock:
                cur = self._replicas.setdefault(sid, {})
                drop = [h for h in cur if h not in standbys]
                for h in drop:
                    cur.pop(h, None)
                known = {h: cur.get(h, -1) for h in standbys}
            for h in drop:
                stale.setdefault(h, []).append(name)
            for tgt in standbys:
                if known[tgt] >= egen:
                    continue  # standby already holds these exact bytes
                groups.setdefault(tgt, []).append((sid, name))
        # fault injection stays on the caller thread in sorted-target
        # order (deterministic under test fault plans); only the real
        # RPCs fan out
        jobs: list = []
        for tgt, pairs in sorted(groups.items()):
            if self._hosts.get(tgt) is None:
                continue
            try:
                maybe_fault(self._fault_plan(), "replicate")
            # conflint: disable=CFX-EXCEPT injected replicate fault: the standby simply stays a generation stale
            except (InjectedFault, InjectedKill):
                bump("fabric_replica_push_failures", len(pairs))
                continue
            jobs.append((tgt, pairs))

        def _push_one(tgt: str, pairs: list) -> None:
            handle = self._hosts.get(tgt)
            if handle is None:
                return
            try:
                handle.replicate(snap, [n for _, n in pairs], gen,
                                 timeout=self.policy.call_timeout)
            except _TRANSPORT_ERRORS:
                self._note_request_failure(tgt)
                bump("fabric_replica_push_failures", len(pairs))
                return
            with self._lock:
                for sid, _n in pairs:
                    self._replicas.setdefault(sid, {})[tgt] = gen
            bump("fabric_replica_pushes", len(pairs))

        if len(jobs) <= 1:
            for tgt, pairs in jobs:
                _push_one(tgt, pairs)
        else:
            with ThreadPoolExecutor(
                    max_workers=min(8, len(jobs)),
                    thread_name_prefix="fabric-replica-push") as ex:
                for f in [ex.submit(_push_one, t, p) for t, p in jobs]:
                    f.result()
        for tgt, names in sorted(stale.items()):
            handle = self._hosts.get(tgt)
            if handle is None:
                continue
            try:
                handle.drop_replica(names,
                                    timeout=self.policy.call_timeout)
            except _TRANSPORT_ERRORS:
                pass  # hygiene only; the generation gate covers it

    def _checkpoint_host(self, hid: str) -> str | None:
        try:
            return self._hosts[hid].checkpoint(
                timeout=self.policy.call_timeout)
        except _TRANSPORT_ERRORS:
            self._note_request_failure(hid)
            return None

    # -- observability -------------------------------------------------- #

    def session_count(self) -> int:
        with self._lock:
            return len(self._owners)

    def stats(self) -> dict:
        """Fabric census: per-host state/misses/sessions/breaker, the
        owners and lost totals, recovery log tail and the per-host
        load estimates — merged into `profiler.serve_stats()['fabric']`
        via :func:`fabric_stats`."""
        with self._lock:
            hosts = {hid: {"state": self._state[hid],
                           "misses": self._misses[hid],
                           "sessions": len(self._owned.get(hid) or ()),
                           "cost": round(
                               self._host_cost.get(hid, 0.0), 3),
                           "breaker": self._breakers[hid].state}
                     for hid in sorted(self._hosts)}
            recoveries = list(self._recoveries[-8:])
            out = {"hosts": hosts,
                   "sessions": len(self._owners),
                   "admitted_sessions": self._admitted_sids,
                   "lost_sessions": len(self._lost),
                   "closed_sessions": self._closed_sids,
                   "retired_hosts": len(self._retired),
                   "replicated_sessions": sum(
                       1 for m in self._replicas.values() if m),
                   "checkpoint_rounds": self._ckpt_rounds,
                   "recoveries": recoveries}
        out["recovery_s_max"] = max(
            (r["seconds"] for r in recoveries), default=0.0)
        out["load"] = self.load.stats()
        return out


def fabric_stats() -> dict:
    """Aggregate census over every live fabric front — the 'fabric'
    sub-dict of :func:`conflux_tpu.profiler.serve_stats`. Gauges live
    on the fabrics (surviving `profiler.clear()`); the EVENT counters
    (host_unavailable, heartbeat_misses, hosts_died,
    sessions_failed_over, ...) ride `resilience.health_stats` in the
    'health' sub-dict."""
    fabs = [f for f in list(_FABRICS) if not f._closed]
    out = {"fabrics": len(fabs), "hosts": 0, "hosts_alive": 0,
           "hosts_suspect": 0, "hosts_dead": 0, "hosts_draining": 0,
           "sessions": 0, "lost_sessions": 0, "recovery_s_max": 0.0}
    for f in fabs:
        s = f.stats()
        out["hosts"] += len(s["hosts"])
        for row in s["hosts"].values():
            out[f"hosts_{row['state']}"] += 1
        out["sessions"] += s["sessions"]
        out["lost_sessions"] += s["lost_sessions"]
        out["recovery_s_max"] = max(out["recovery_s_max"],
                                    s["recovery_s_max"])
    return out


# --------------------------------------------------------------------------- #
# convenience constructors
# --------------------------------------------------------------------------- #


def local_fabric(n: int, root: str, *,
                 engine_kwargs: dict | None = None,
                 policy: FabricPolicy | None = None,
                 fault_plan=None) -> ServeFabric:
    """An n-host single-process fabric (tests, soak, lockcheck)."""
    hosts = [LocalHost(f"h{i}", os.path.join(root, f"h{i}"),
                       engine_kwargs=engine_kwargs) for i in range(n)]
    return ServeFabric(hosts, policy=policy, fault_plan=fault_plan,
                       root=root)


def process_fabric(n: int, root: str, *,
                   engine_kwargs: dict | None = None,
                   policy: FabricPolicy | None = None,
                   fault_plan=None,
                   start_timeout: float = 180.0,
                   wire: str = "shm",
                   wire_config: WireConfig | None = None) -> ServeFabric:
    """An n-host fabric with one worker process per host (the real
    deployment shape; scripts/fabric_drill.py and the --fabric
    bench). ``wire`` picks the payload transport (DESIGN §31):
    'shm' (default) stages solve payloads through per-host
    shared-memory rings; 'pickle' is the pre-§31 escape hatch."""
    hosts = [ProcessHost(f"h{i}", os.path.join(root, f"h{i}"),
                         engine_kwargs=engine_kwargs,
                         start_timeout=start_timeout,
                         wire=wire, wire_config=wire_config)
             for i in range(n)]
    return ServeFabric(hosts, policy=policy, fault_plan=fault_plan,
                       root=root)


if __name__ == "__main__":
    sys.exit(worker_main())
