"""Native (C++) acceleration for host-side layout work.

The reference's redistribution layer is native (COSTA, a C++ library wired
in through `src/conflux/lu/layout.cpp`); this package is its counterpart:
an OpenMP C++ scatter/gather for the block-cyclic device layout, loaded via
ctypes (no pybind11 in this environment). Build on demand with

    python -m conflux_tpu.native.build

`available()` reports whether the shared library is loadable; the pure-
NumPy paths in `conflux_tpu.geometry` are used as fallback when it is not.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

_LIB = None
_TRIED = False

_SO_PATH = os.path.join(os.path.dirname(__file__), "libconflux_layout.so")


def _load():
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    if os.path.exists(_SO_PATH):
        try:
            lib = ctypes.CDLL(_SO_PATH)
            for name in ("conflux_scatter_f32", "conflux_scatter_f64",
                         "conflux_gather_f32", "conflux_gather_f64"):
                fn = getattr(lib, name)
                fn.restype = None
                ptr = ctypes.c_float if name.endswith("f32") else ctypes.c_double
                fn.argtypes = [ctypes.POINTER(ptr), ctypes.POINTER(ptr)] + [ctypes.c_int64] * 5
            lib.conflux_native_nthreads.restype = ctypes.c_int
            _LIB = lib
        except (OSError, AttributeError):
            # unloadable or stale .so (e.g. built before a symbol was added):
            # fall back to the pure-NumPy paths
            _LIB = None
    return _LIB


def available() -> bool:
    return _load() is not None


def nthreads() -> int:
    lib = _load()
    return lib.conflux_native_nthreads() if lib else 0


def _ptr(a: np.ndarray):
    ct = ctypes.c_float if a.dtype == np.float32 else ctypes.c_double
    return a.ctypes.data_as(ctypes.POINTER(ct))


def scatter(A: np.ndarray, v: int, Px: int, Py: int) -> np.ndarray | None:
    """(M, N) row-major -> (Px, Py, Ml, Nl) shards; None if not applicable."""
    lib = _load()
    if lib is None or A.dtype not in (np.float32, np.float64):
        return None
    M, N = A.shape
    if M % (v * Px) or N % (v * Py):
        return None
    A = np.ascontiguousarray(A)
    Ml, Nl = M // Px, N // Py
    out = np.empty((Px, Py, Ml, Nl), dtype=A.dtype)
    fn = lib.conflux_scatter_f32 if A.dtype == np.float32 else lib.conflux_scatter_f64
    fn(_ptr(A), _ptr(out), M, N, v, Px, Py)
    return out


def gather(shards: np.ndarray, v: int, Px: int, Py: int) -> np.ndarray | None:
    lib = _load()
    if lib is None or shards.dtype not in (np.float32, np.float64):
        return None
    _, _, Ml, Nl = shards.shape
    if Ml % v or Nl % v:
        return None
    M, N = Ml * Px, Nl * Py
    shards = np.ascontiguousarray(shards)
    out = np.empty((M, N), dtype=shards.dtype)
    fn = lib.conflux_gather_f32 if shards.dtype == np.float32 else lib.conflux_gather_f64
    fn(_ptr(shards), _ptr(out), M, N, v, Px, Py)
    return out
