"""Native (C++) acceleration for host-side layout work.

The reference's redistribution layer is native (COSTA, a C++ library wired
in through `src/conflux/lu/layout.cpp`); this package is its counterpart:
an OpenMP C++ scatter/gather for the block-cyclic device layout, loaded via
ctypes (no pybind11 in this environment). Build on demand with

    python -m conflux_tpu.native.build

`available()` reports whether the shared library is loadable; the pure-
NumPy paths in `conflux_tpu.geometry` are used as fallback when it is not.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

_LIB = None
_TRIED = False
_FILE_OK = False
_TILES_OK = False

_SO_PATH = os.path.join(os.path.dirname(__file__), "libconflux_layout.so")


def _load():
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    if os.path.exists(_SO_PATH):
        try:
            lib = ctypes.CDLL(_SO_PATH)
            for name in ("conflux_scatter_f32", "conflux_scatter_f64",
                         "conflux_gather_f32", "conflux_gather_f64"):
                fn = getattr(lib, name)
                fn.restype = None
                ptr = ctypes.c_float if name.endswith("f32") else ctypes.c_double
                fn.argtypes = [ctypes.POINTER(ptr), ctypes.POINTER(ptr)] + [ctypes.c_int64] * 5
            lib.conflux_native_nthreads.restype = ctypes.c_int
            _LIB = lib
        except (OSError, AttributeError):
            # unloadable or stale .so (e.g. built before a symbol was added):
            # fall back to the pure-NumPy paths
            _LIB = None
            return _LIB
        # file IO symbols are newer: a stale .so keeps the in-memory fast
        # paths and only loses the streaming ones
        global _FILE_OK
        try:
            for name in ("conflux_file_scatter_f32", "conflux_file_scatter_f64",
                         "conflux_file_gather_f32", "conflux_file_gather_f64"):
                fn = getattr(lib, name)
                fn.restype = ctypes.c_int
                ptr = ctypes.c_float if name.endswith("f32") else ctypes.c_double
                fn.argtypes = [ctypes.c_char_p, ctypes.POINTER(ptr)] + [ctypes.c_int64] * 6
            _FILE_OK = True
        except AttributeError:
            import warnings

            warnings.warn(
                "stale libconflux_layout.so lacks the streaming file IO "
                "symbols; rebuild with `python -m conflux_tpu.native.build`",
                stacklevel=2,
            )
            _FILE_OK = False
        # tile-pack symbols are newer still (round 3): same stale-.so
        # degradation story as the file IO block
        global _TILES_OK
        try:
            for name in ("conflux_bc_to_tiles_f32", "conflux_bc_to_tiles_f64",
                         "conflux_tiles_to_bc_f32", "conflux_tiles_to_bc_f64"):
                fn = getattr(lib, name)
                fn.restype = None
                ptr = (ctypes.c_float if name.endswith("f32")
                       else ctypes.c_double)
                fn.argtypes = ([ctypes.POINTER(ptr), ctypes.POINTER(ptr)]
                               + [ctypes.c_int64] * 5)
            _TILES_OK = True
        except AttributeError:
            import warnings

            warnings.warn(
                "stale libconflux_layout.so lacks the tile-pack symbols; "
                "rebuild with `python -m conflux_tpu.native.build`",
                stacklevel=2,
            )
            _TILES_OK = False
    return _LIB


def available() -> bool:
    return _load() is not None


def nthreads() -> int:
    lib = _load()
    return lib.conflux_native_nthreads() if lib else 0


def _ptr(a: np.ndarray):
    ct = ctypes.c_float if a.dtype == np.float32 else ctypes.c_double
    return a.ctypes.data_as(ctypes.POINTER(ct))


def scatter(A: np.ndarray, v: int, Px: int, Py: int) -> np.ndarray | None:
    """(M, N) row-major -> (Px, Py, Ml, Nl) shards; None if not applicable."""
    lib = _load()
    if lib is None or A.dtype not in (np.float32, np.float64):
        return None
    M, N = A.shape
    if M % (v * Px) or N % (v * Py):
        return None
    A = np.ascontiguousarray(A)
    Ml, Nl = M // Px, N // Py
    out = np.empty((Px, Py, Ml, Nl), dtype=A.dtype)
    fn = lib.conflux_scatter_f32 if A.dtype == np.float32 else lib.conflux_scatter_f64
    fn(_ptr(A), _ptr(out), M, N, v, Px, Py)
    return out


def file_scatter(path: str, header: int, M: int, N: int, v: int, Px: int,
                 Py: int, dtype) -> np.ndarray | None:
    """Stream a row-major on-disk matrix (after `header` bytes) straight into
    (Px, Py, Ml, Nl) shards via mmap — the global matrix is never
    materialized in memory. None if the native engine can't handle it."""
    lib = _load()
    dtype = np.dtype(dtype)
    if lib is None or not _FILE_OK or dtype not in (np.float32, np.float64):
        return None
    if M % (v * Px) or N % (v * Py):
        return None
    out = np.empty((Px, Py, M // Px, N // Py), dtype=dtype)
    fn = (lib.conflux_file_scatter_f32 if dtype == np.float32
          else lib.conflux_file_scatter_f64)
    rc = fn(path.encode(), _ptr(out), header, M, N, v, Px, Py)
    if rc != 0:
        raise OSError(f"native file_scatter({path!r}) failed with code {rc}")
    return out


def file_gather(path: str, shards: np.ndarray, header: int, v: int, Px: int,
                Py: int) -> bool:
    """Stream shards into an on-disk row-major matrix after `header` bytes.
    The file must exist with the header already written; it is grown to the
    full size. Returns False if the native engine can't handle it."""
    lib = _load()
    if lib is None or not _FILE_OK or shards.dtype not in (np.float32, np.float64):
        return False
    if shards.ndim != 4 or shards.shape[:2] != (Px, Py):
        raise ValueError(f"shards shape {shards.shape} does not match grid "
                         f"({Px}, {Py}, Ml, Nl)")
    _, _, Ml, Nl = shards.shape
    if Ml % v or Nl % v:
        return False
    shards = np.ascontiguousarray(shards)
    fn = (lib.conflux_file_gather_f32 if shards.dtype == np.float32
          else lib.conflux_file_gather_f64)
    rc = fn(path.encode(), _ptr(shards), header, Ml * Px, Nl * Py, v, Px, Py)
    if rc != 0:
        raise OSError(f"native file_gather({path!r}) failed with code {rc}")
    return True


def gather(shards: np.ndarray, v: int, Px: int, Py: int) -> np.ndarray | None:
    lib = _load()
    if lib is None or shards.dtype not in (np.float32, np.float64):
        return None
    if shards.ndim != 4 or shards.shape[:2] != (Px, Py):
        raise ValueError(f"shards shape {shards.shape} does not match grid "
                         f"({Px}, {Py}, Ml, Nl)")
    _, _, Ml, Nl = shards.shape
    if Ml % v or Nl % v:
        return None
    M, N = Ml * Px, Nl * Py
    shards = np.ascontiguousarray(shards)
    out = np.empty((M, N), dtype=shards.dtype)
    fn = lib.conflux_gather_f32 if shards.dtype == np.float32 else lib.conflux_gather_f64
    fn(_ptr(shards), _ptr(out), M, N, v, Px, Py)
    return out


def bc_to_tiles(shards: np.ndarray, v: int, Px: int, Py: int
                ) -> np.ndarray | None:
    """(Px, Py, Ml, Nl) block-cyclic shards -> (Mt*Nt, v, v) tiles packed
    in global (ti, tj) row-major order. Owner-agnostic: the custom-layout
    transform slices per-owner VIEWS of the result, so one native kernel
    serves every `costa::custom_layout` owner array. None when the
    native engine can't handle it (fallback to the Python walk)."""
    lib = _load()
    if lib is None or not _TILES_OK \
            or shards.dtype not in (np.float32, np.float64):
        return None
    if shards.ndim != 4 or shards.shape[:2] != (Px, Py):
        raise ValueError(f"shards shape {shards.shape} does not match grid "
                         f"({Px}, {Py}, Ml, Nl)")
    _, _, Ml, Nl = shards.shape
    if Ml % v or Nl % v:
        return None
    M, N = Ml * Px, Nl * Py
    shards = np.ascontiguousarray(shards)
    out = np.empty(((M // v) * (N // v), v, v), dtype=shards.dtype)
    fn = (lib.conflux_bc_to_tiles_f32 if shards.dtype == np.float32
          else lib.conflux_bc_to_tiles_f64)
    fn(_ptr(shards), _ptr(out), M, N, v, Px, Py)
    return out


def tiles_to_bc(tiles: np.ndarray, M: int, N: int, v: int, Px: int, Py: int
                ) -> np.ndarray | None:
    """Inverse of :func:`bc_to_tiles`: (Mt*Nt, v, v) packed tiles ->
    (Px, Py, Ml, Nl) block-cyclic shards. None when not applicable."""
    lib = _load()
    if lib is None or not _TILES_OK \
            or tiles.dtype not in (np.float32, np.float64):
        return None
    if M % (v * Px) or N % (v * Py):
        return None
    if tiles.shape != ((M // v) * (N // v), v, v):
        raise ValueError(f"tiles shape {tiles.shape} does not match "
                         f"{M}x{N} at tile {v}")
    tiles = np.ascontiguousarray(tiles)
    out = np.empty((Px, Py, M // Px, N // Py), dtype=tiles.dtype)
    fn = (lib.conflux_tiles_to_bc_f32 if tiles.dtype == np.float32
          else lib.conflux_tiles_to_bc_f64)
    fn(_ptr(tiles), _ptr(out), M, N, v, Px, Py)
    return out
