// Native layout engine: block-cyclic scatter/gather between a row-major
// global matrix and per-device shard buffers.
//
// This is the TPU build's counterpart of the reference's native
// redistribution layer (COSTA, consumed through src/conflux/lu/layout.cpp):
// the host-side half of moving matrices between user layout and the
// framework's block-cyclic device layout. The hot path is a strided tile
// copy, memory-bandwidth-bound, parallelized over tiles with OpenMP
// (kernels in tile_copy.hpp, shared with the streaming IO engine).
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this environment).

#include <cstdint>

#include "tile_copy.hpp"

extern "C" {

void conflux_scatter_f32(const float* A, float* shards, int64_t M, int64_t N,
                         int64_t v, int64_t Px, int64_t Py) {
  conflux_native::scatter_impl(A, shards, M, N, v, Px, Py);
}
void conflux_scatter_f64(const double* A, double* shards, int64_t M, int64_t N,
                         int64_t v, int64_t Px, int64_t Py) {
  conflux_native::scatter_impl(A, shards, M, N, v, Px, Py);
}
void conflux_gather_f32(const float* shards, float* A, int64_t M, int64_t N,
                        int64_t v, int64_t Px, int64_t Py) {
  conflux_native::gather_impl(shards, A, M, N, v, Px, Py);
}
void conflux_gather_f64(const double* shards, double* A, int64_t M, int64_t N,
                        int64_t v, int64_t Px, int64_t Py) {
  conflux_native::gather_impl(shards, A, M, N, v, Px, Py);
}

int conflux_native_nthreads() {
#if defined(_OPENMP)
  return omp_get_max_threads();
#else
  return 1;
#endif
}

}  // extern "C"

extern "C" {

void conflux_bc_to_tiles_f32(const float* shards, float* tiles, int64_t M,
                             int64_t N, int64_t v, int64_t Px, int64_t Py) {
  conflux_native::bc_to_tiles_impl(shards, tiles, M, N, v, Px, Py);
}
void conflux_bc_to_tiles_f64(const double* shards, double* tiles, int64_t M,
                             int64_t N, int64_t v, int64_t Px, int64_t Py) {
  conflux_native::bc_to_tiles_impl(shards, tiles, M, N, v, Px, Py);
}
void conflux_tiles_to_bc_f32(const float* tiles, float* shards, int64_t M,
                             int64_t N, int64_t v, int64_t Px, int64_t Py) {
  conflux_native::tiles_to_bc_impl(tiles, shards, M, N, v, Px, Py);
}
void conflux_tiles_to_bc_f64(const double* tiles, double* shards, int64_t M,
                             int64_t N, int64_t v, int64_t Px, int64_t Py) {
  conflux_native::tiles_to_bc_impl(tiles, shards, M, N, v, Px, Py);
}

}  // extern "C"
