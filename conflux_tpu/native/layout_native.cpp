// Native layout engine: block-cyclic scatter/gather between a row-major
// global matrix and per-device shard buffers.
//
// This is the TPU build's counterpart of the reference's native
// redistribution layer (COSTA, consumed through src/conflux/lu/layout.cpp):
// the host-side half of moving matrices between user layout and the
// framework's block-cyclic device layout. The hot path is a strided tile
// copy, memory-bandwidth-bound, parallelized over tiles with OpenMP.
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this environment).
//
// Layout convention (matches conflux_tpu.geometry.LUGeometry.scatter):
//   global tile (ti, tj) of size v x v lives on device (ti % Px, tj % Py)
//   at local tile slot (ti / Px, tj / Py); shards is one contiguous buffer
//   of shape (Px, Py, Ml, Nl) with Ml = Mt/Px*v, Nl = Nt/Py*v.

#include <cstdint>
#include <cstring>

#if defined(_OPENMP)
#include <omp.h>
#endif

namespace {

template <typename T>
void scatter_impl(const T* A, T* shards, int64_t M, int64_t N, int64_t v,
                  int64_t Px, int64_t Py) {
  const int64_t Mt = M / v, Nt = N / v;
  const int64_t Ml = (Mt / Px) * v, Nl = (Nt / Py) * v;
#pragma omp parallel for collapse(2) schedule(static)
  for (int64_t ti = 0; ti < Mt; ++ti) {
    for (int64_t tj = 0; tj < Nt; ++tj) {
      const int64_t px = ti % Px, py = tj % Py;
      const int64_t lt = ti / Px, lj = tj / Py;
      const T* src = A + ti * v * N + tj * v;
      T* dst = shards + ((px * Py + py) * Ml + lt * v) * Nl + lj * v;
      for (int64_t r = 0; r < v; ++r) {
        std::memcpy(dst + r * Nl, src + r * N, sizeof(T) * v);
      }
    }
  }
}

template <typename T>
void gather_impl(const T* shards, T* A, int64_t M, int64_t N, int64_t v,
                 int64_t Px, int64_t Py) {
  const int64_t Mt = M / v, Nt = N / v;
  const int64_t Ml = (Mt / Px) * v, Nl = (Nt / Py) * v;
#pragma omp parallel for collapse(2) schedule(static)
  for (int64_t ti = 0; ti < Mt; ++ti) {
    for (int64_t tj = 0; tj < Nt; ++tj) {
      const int64_t px = ti % Px, py = tj % Py;
      const int64_t lt = ti / Px, lj = tj / Py;
      T* dst = A + ti * v * N + tj * v;
      const T* src = shards + ((px * Py + py) * Ml + lt * v) * Nl + lj * v;
      for (int64_t r = 0; r < v; ++r) {
        std::memcpy(dst + r * N, src + r * Nl, sizeof(T) * v);
      }
    }
  }
}

}  // namespace

extern "C" {

void conflux_scatter_f32(const float* A, float* shards, int64_t M, int64_t N,
                         int64_t v, int64_t Px, int64_t Py) {
  scatter_impl(A, shards, M, N, v, Px, Py);
}
void conflux_scatter_f64(const double* A, double* shards, int64_t M, int64_t N,
                         int64_t v, int64_t Px, int64_t Py) {
  scatter_impl(A, shards, M, N, v, Px, Py);
}
void conflux_gather_f32(const float* shards, float* A, int64_t M, int64_t N,
                        int64_t v, int64_t Px, int64_t Py) {
  gather_impl(shards, A, M, N, v, Px, Py);
}
void conflux_gather_f64(const double* shards, double* A, int64_t M, int64_t N,
                        int64_t v, int64_t Px, int64_t Py) {
  gather_impl(shards, A, M, N, v, Px, Py);
}

int conflux_native_nthreads() {
#if defined(_OPENMP)
  return omp_get_max_threads();
#else
  return 1;
#endif
}

}  // extern "C"
