// Native streaming IO engine: move matrices between binary files and
// block-cyclic shard buffers WITHOUT materializing the global matrix.
//
// Role of the reference's MPI-IO layer (`src/conflux/cholesky/CholeskyIO.cpp`
// :185-375 file parse + tile scatter, :384-501 MPI_File_write_at dumps): on a
// TPU host there is one filesystem instead of a rank-collective file view, so
// the equivalent is an mmap'd window over the file with the same OpenMP tile
// copy the in-memory layout engine uses. For matrices larger than host RAM
// the page cache streams tiles in and out; only the shard buffers are real
// allocations.
//
// Plain C ABI for ctypes (no pybind11 in this environment). Return codes:
// 0 ok, -1 open failed, -2 file too short, -3 mmap failed, -4 resize failed.

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cstdint>

#include "tile_copy.hpp"

namespace {

template <typename T>
int file_scatter(const char* path, T* shards, int64_t header, int64_t M,
                 int64_t N, int64_t v, int64_t Px, int64_t Py) {
  const int fd = open(path, O_RDONLY);
  if (fd < 0) return -1;
  const size_t len = size_t(header) + size_t(M) * N * sizeof(T);
  struct stat st;
  if (fstat(fd, &st) != 0 || size_t(st.st_size) < len) {
    close(fd);
    return -2;
  }
  void* map = mmap(nullptr, len, PROT_READ, MAP_PRIVATE, fd, 0);
  close(fd);
  if (map == MAP_FAILED) return -3;
  const T* A = reinterpret_cast<const T*>(static_cast<const char*>(map) + header);
  conflux_native::scatter_impl(A, shards, M, N, v, Px, Py);
  munmap(map, len);
  return 0;
}

template <typename T>
int file_gather(const char* path, const T* shards, int64_t header, int64_t M,
                int64_t N, int64_t v, int64_t Px, int64_t Py) {
  // file must already exist with the header written (Python owns the format)
  const int fd = open(path, O_RDWR);
  if (fd < 0) return -1;
  const size_t len = size_t(header) + size_t(M) * N * sizeof(T);
  if (ftruncate(fd, off_t(len)) != 0) {
    close(fd);
    return -4;
  }
  void* map = mmap(nullptr, len, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (map == MAP_FAILED) return -3;
  T* A = reinterpret_cast<T*>(static_cast<char*>(map) + header);
  conflux_native::gather_impl(shards, A, M, N, v, Px, Py);
  munmap(map, len);  // MAP_SHARED: kernel flushes dirtied pages
  return 0;
}

}  // namespace

extern "C" {

int conflux_file_scatter_f32(const char* path, float* shards, int64_t header,
                             int64_t M, int64_t N, int64_t v, int64_t Px,
                             int64_t Py) {
  return file_scatter(path, shards, header, M, N, v, Px, Py);
}
int conflux_file_scatter_f64(const char* path, double* shards, int64_t header,
                             int64_t M, int64_t N, int64_t v, int64_t Px,
                             int64_t Py) {
  return file_scatter(path, shards, header, M, N, v, Px, Py);
}
int conflux_file_gather_f32(const char* path, const float* shards,
                            int64_t header, int64_t M, int64_t N, int64_t v,
                            int64_t Px, int64_t Py) {
  return file_gather(path, shards, header, M, N, v, Px, Py);
}
int conflux_file_gather_f64(const char* path, const double* shards,
                            int64_t header, int64_t M, int64_t N, int64_t v,
                            int64_t Px, int64_t Py) {
  return file_gather(path, shards, header, M, N, v, Px, Py);
}

}  // extern "C"
