// Shared tile-copy kernels for the native host runtime: block-cyclic
// scatter/gather between a row-major global matrix view and per-device
// shard buffers. Used by the in-memory layout engine (layout_native.cpp)
// and the mmap streaming IO engine (io_native.cpp).
//
// Layout convention (matches conflux_tpu.geometry.LUGeometry.scatter):
//   global tile (ti, tj) of size v x v lives on device (ti % Px, tj % Py)
//   at local tile slot (ti / Px, tj / Py); shards is one contiguous buffer
//   of shape (Px, Py, Ml, Nl) with Ml = Mt/Px*v, Nl = Nt/Py*v.

#pragma once

#include <cstdint>
#include <cstring>

#if defined(_OPENMP)
#include <omp.h>
#endif

namespace conflux_native {

template <typename T>
void scatter_impl(const T* A, T* shards, int64_t M, int64_t N, int64_t v,
                  int64_t Px, int64_t Py) {
  const int64_t Mt = M / v, Nt = N / v;
  const int64_t Ml = (Mt / Px) * v, Nl = (Nt / Py) * v;
#pragma omp parallel for collapse(2) schedule(static)
  for (int64_t ti = 0; ti < Mt; ++ti) {
    for (int64_t tj = 0; tj < Nt; ++tj) {
      const int64_t px = ti % Px, py = tj % Py;
      const int64_t lt = ti / Px, lj = tj / Py;
      const T* src = A + ti * v * N + tj * v;
      T* dst = shards + ((px * Py + py) * Ml + lt * v) * Nl + lj * v;
      for (int64_t r = 0; r < v; ++r) {
        std::memcpy(dst + r * Nl, src + r * N, sizeof(T) * v);
      }
    }
  }
}

template <typename T>
void gather_impl(const T* shards, T* A, int64_t M, int64_t N, int64_t v,
                 int64_t Px, int64_t Py) {
  const int64_t Mt = M / v, Nt = N / v;
  const int64_t Ml = (Mt / Px) * v, Nl = (Nt / Py) * v;
#pragma omp parallel for collapse(2) schedule(static)
  for (int64_t ti = 0; ti < Mt; ++ti) {
    for (int64_t tj = 0; tj < Nt; ++tj) {
      const int64_t px = ti % Px, py = tj % Py;
      const int64_t lt = ti / Px, lj = tj / Py;
      T* dst = A + ti * v * N + tj * v;
      const T* src = shards + ((px * Py + py) * Ml + lt * v) * Nl + lj * v;
      for (int64_t r = 0; r < v; ++r) {
        std::memcpy(dst + r * N, src + r * Nl, sizeof(T) * v);
      }
    }
  }
}

}  // namespace conflux_native

namespace conflux_native {

// Block-cyclic shard buffer (Px, Py, Ml, Nl) <-> tiles packed in global
// (ti, tj) row-major order, each tile (v, v) contiguous. Owner-agnostic:
// the custom-layout (costa::custom_layout) transform slices per-owner
// VIEWS of the packed buffer on the Python side, so one kernel serves
// every owner array.

template <typename T>
void bc_to_tiles_impl(const T* shards, T* tiles, int64_t M, int64_t N,
                      int64_t v, int64_t Px, int64_t Py) {
  const int64_t Mt = M / v, Nt = N / v;
  const int64_t Ml = (Mt / Px) * v, Nl = (Nt / Py) * v;
#if defined(_OPENMP)
#pragma omp parallel for collapse(2) schedule(static)
#endif
  for (int64_t ti = 0; ti < Mt; ++ti) {
    for (int64_t tj = 0; tj < Nt; ++tj) {
      const int64_t px = ti % Px, py = tj % Py;
      const int64_t lt = ti / Px, lj = tj / Py;
      const T* src = shards + ((px * Py + py) * Ml + lt * v) * Nl + lj * v;
      T* dst = tiles + (ti * Nt + tj) * v * v;
      for (int64_t r = 0; r < v; ++r) {
        std::memcpy(dst + r * v, src + r * Nl, sizeof(T) * v);
      }
    }
  }
}

template <typename T>
void tiles_to_bc_impl(const T* tiles, T* shards, int64_t M, int64_t N,
                      int64_t v, int64_t Px, int64_t Py) {
  const int64_t Mt = M / v, Nt = N / v;
  const int64_t Ml = (Mt / Px) * v, Nl = (Nt / Py) * v;
#if defined(_OPENMP)
#pragma omp parallel for collapse(2) schedule(static)
#endif
  for (int64_t ti = 0; ti < Mt; ++ti) {
    for (int64_t tj = 0; tj < Nt; ++tj) {
      const int64_t px = ti % Px, py = tj % Py;
      const int64_t lt = ti / Px, lj = tj / Py;
      const T* src = tiles + (ti * Nt + tj) * v * v;
      T* dst = shards + ((px * Py + py) * Ml + lt * v) * Nl + lj * v;
      for (int64_t r = 0; r < v; ++r) {
        std::memcpy(dst + r * Nl, src + r * v, sizeof(T) * v);
      }
    }
  }
}

}  // namespace conflux_native
