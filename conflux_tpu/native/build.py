"""Build the native layout library: `python -m conflux_tpu.native.build`."""

from __future__ import annotations

import os
import shutil
import subprocess
import sys


def build(verbose: bool = True) -> str:
    here = os.path.dirname(__file__)
    srcs = [os.path.join(here, "layout_native.cpp"),
            os.path.join(here, "io_native.cpp")]
    out = os.path.join(here, "libconflux_layout.so")
    cxx = os.environ.get("CXX") or shutil.which("g++") or shutil.which("c++")
    if cxx is None:
        raise RuntimeError("no C++ compiler found (set CXX)")
    cmd = [cxx, "-O3", "-march=native", "-fopenmp", "-shared", "-fPIC",
           "-std=c++17", *srcs, "-o", out]
    if verbose:
        print(" ".join(cmd))
    subprocess.run(cmd, check=True)
    return out


if __name__ == "__main__":
    # support direct-path invocation (python conflux_tpu/native/build.py)
    # as well as the documented -m form
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    path = build()
    from conflux_tpu import native

    native._TRIED = False  # force re-probe
    ok = native.available()
    print(f"built {path}; loadable={ok}; omp threads={native.nthreads()}")
    sys.exit(0 if ok else 1)
