"""Persistent XLA compilation cache, promoted from bench.py into the library.

The at-scale programs cost minutes of compile each (the N=32768 LU is
4-6 min per config) and every process historically re-paid that cost:
bench.py carried a private `_enable_compile_cache` while the CLIs, the
serve layer, and the tuning scripts compiled from scratch. This module is
the single switch-on point: the serve path (`conflux_tpu.serve`) and the
miniapp CLIs call :func:`enable_persistent_cache` at startup so cold-start
compiles amortize across processes — a second process hitting the same
(geometry, knobs) config deserializes the executable in seconds.

The cache location resolves, in order:

1. an explicit `path=` argument,
2. `$CONFLUX_TPU_CACHE_DIR`,
3. `~/.cache/conflux_tpu/xla` (created on demand).

Enabling is idempotent and *guarded*: on a backend/jax combination without
persistent-cache support the call degrades to a no-op instead of raising —
a missing cache only costs compile time, never correctness.
"""

from __future__ import annotations

import os

_ENABLED_AT: str | None = None


def default_cache_dir() -> str:
    """The resolved default cache directory (no filesystem side effects)."""
    env = os.environ.get("CONFLUX_TPU_CACHE_DIR")
    if env:
        return env
    return os.path.join(os.path.expanduser("~"), ".cache", "conflux_tpu",
                        "xla")


def enable_persistent_cache(path: str | None = None, *,
                            min_compile_secs: float = 10.0) -> str | None:
    """Point jax's persistent compilation cache at a durable directory.

    `min_compile_secs` filters trivial programs out of the cache (the
    default 10 s keeps every at-scale factorization but skips the
    sub-second host utilities); the min-entry-size filter is zeroed so the
    time threshold is the only admission rule — bench.py measured small
    serialized executables for multi-minute compiles, and the byte filter
    silently dropped them.

    Returns the cache directory actually enabled, or None when the
    environment does not support it. Safe to call many times (first call
    wins; later calls with a different path are ignored rather than
    re-pointing a live cache).
    """
    global _ENABLED_AT
    if _ENABLED_AT is not None:
        return _ENABLED_AT
    cache = path or default_cache_dir()
    try:
        import jax

        os.makedirs(cache, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          min_compile_secs)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:
        return None
    _ENABLED_AT = cache
    return cache


def cache_enabled() -> bool:
    return _ENABLED_AT is not None


def cache_dir() -> str | None:
    """The directory of the live persistent cache, or None when disabled.

    The serve engine reports this in its startup/bench metadata so an
    operator can tell whether prewarmed compiles will survive the process
    (a cold replica deserializes instead of re-paying the compile)."""
    return _ENABLED_AT
