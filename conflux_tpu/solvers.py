"""Direct solvers on the factorizations, with mixed-precision refinement.

The reference stops at the factorization (its miniapps benchmark `LU_rep` /
`parallelCholesky` and validate residuals; there is no solve API). On TPU a
solver is where mixed precision pays: the MXU's native bf16 pass is ~6x the
f32-accurate (HIGHEST) rate, so the HPL-MxP recipe — factor in bf16, then
recover accuracy with a few iterative-refinement sweeps whose residuals are
computed in f32 — turns the cheap factorization into an f32-grade solution.

    x = solve(A, b)                       # f32 factors, direct
    x = solve(A, b, factor_dtype=jnp.bfloat16, refine=3)   # HPL-MxP mode

`lu_solve` / `cholesky_solve` are the plain triangular-substitution halves,
usable with factors from `lu_factor_blocked` / `cholesky_blocked`.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from conflux_tpu.ops import blas


def _as_2d(b: jax.Array) -> tuple[jax.Array, bool]:
    if b.ndim == 1:
        return b[:, None], True
    return b, False


def lu_solve(LU: jax.Array, perm: jax.Array, b: jax.Array) -> jax.Array:
    """Solve A x = b given packed LU factors with A[perm] == L @ U
    (the contract of `lu_factor_blocked`, square A). b is (N,) or (N, k)."""
    M, N = LU.shape
    if M != N:
        raise ValueError(
            f"lu_solve needs square factors, got {LU.shape} (an M > N "
            "factorization has no unique solve)"
        )
    if b.shape[0] != N:
        raise ValueError(f"b has {b.shape[0]} rows, factors need {N}")
    cdtype = blas.compute_dtype(LU.dtype)
    Lu = LU.astype(cdtype)
    b2, squeeze = _as_2d(b.astype(cdtype))
    # TPU triangular_solve lowers to blocked inversion + matmuls, which at
    # default precision are single bf16 passes — pin the accurate path
    with jax.default_matmul_precision("highest"):
        y = blas.trsm_left_lower_unit(Lu, b2[perm])
        x = blas.trsm_left_upper(Lu, y)
    return x[:, 0] if squeeze else x


def cholesky_solve(L: jax.Array, b: jax.Array) -> jax.Array:
    """Solve A x = b given the lower Cholesky factor L (A = L L^T)."""
    if b.shape[0] != L.shape[0]:
        raise ValueError(f"b has {b.shape[0]} rows, factor needs {L.shape[0]}")
    cdtype = blas.compute_dtype(L.dtype)
    Lc = L.astype(cdtype)
    b2, squeeze = _as_2d(b.astype(cdtype))
    with jax.default_matmul_precision("highest"):
        y = blas.trsm_left_lower(Lc, b2)
        x = blas.trsm_left_lower_t(Lc, y)
    return x[:, 0] if squeeze else x


def solve(A: jax.Array, b: jax.Array, *, v: int = 256,
          factor_dtype=None, refine: int = 0, spd: bool = False) -> jax.Array:
    """Solve A x = b by blocked factorization + optional refinement.

    factor_dtype: dtype the factorization runs in (default: A's dtype).
    Passing jnp.bfloat16 rides the MXU's fast single-pass path — ~6x the
    f32-accurate rate — and `refine` iterative-refinement sweeps (2-3 is
    typical) restore the solution to working-precision accuracy (the
    HPL-MxP trade). Convergence requires the classic IR condition
    cond(A) * err(factors) < 1: with bf16 factors that means reasonably
    well-conditioned (e.g. diagonally dominant) systems; for harder systems
    keep f32 factors or wrap the low-precision solve in GMRES as HPL-MxP
    does at scale.
    refine: number of refinement sweeps; each computes r = b - A x at
    HIGHEST precision in A's dtype and solves for the correction with the
    low-precision factors.
    spd: use Cholesky instead of LU (A must be SPD).
    """
    N = A.shape[0]
    if N % v:  # largest divisor of N not exceeding the requested tile size
        v = max(d for d in range(1, min(v, N) + 1) if N % d == 0)
    fdtype = A.dtype if factor_dtype is None else factor_dtype
    Af = A.astype(fdtype)
    if spd:
        from conflux_tpu.cholesky.single import cholesky_blocked

        L = cholesky_blocked(Af, v=v)
        solve_corr = lambda r: cholesky_solve(L, r)
    else:
        from conflux_tpu.lu.single import lu_factor_blocked

        LU, perm = lu_factor_blocked(Af, v=v)
        solve_corr = lambda r: lu_solve(LU, perm, r)

    cdtype = blas.compute_dtype(A.dtype)
    Ac = A.astype(cdtype)
    bc = b.astype(cdtype)
    x = solve_corr(b).astype(cdtype)
    for _ in range(refine):
        r = bc - jnp.matmul(Ac, x, precision=lax.Precision.HIGHEST)
        x = x + solve_corr(r).astype(cdtype)
    return x
