"""Direct solvers on the factorizations, with mixed-precision refinement.

The reference stops at the factorization (its miniapps benchmark `LU_rep` /
`parallelCholesky` and validate residuals; there is no solve API). On TPU a
solver is where mixed precision pays: the MXU's native bf16 pass is ~6x the
f32-accurate (HIGHEST) rate, so the HPL-MxP recipe — factor in bf16, then
recover accuracy with a few iterative-refinement sweeps whose residuals are
computed in f32 — turns the cheap factorization into an f32-grade solution.

    x = solve(A, b)                       # f32 factors, direct
    x = solve(A, b, factor_dtype=jnp.bfloat16, refine=3)   # HPL-MxP mode

`lu_solve` / `cholesky_solve` are the plain triangular-substitution halves,
usable with factors from `lu_factor_blocked` / `cholesky_blocked`.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from conflux_tpu.ops import blas
from conflux_tpu.parallel.mesh import mesh_cache_key, shard_map


def _as_2d(b: jax.Array) -> tuple[jax.Array, bool]:
    if b.ndim == 1:
        return b[:, None], True
    return b, False


def lu_solve(LU: jax.Array, perm: jax.Array, b: jax.Array) -> jax.Array:
    """Solve A x = b given packed LU factors with A[perm] == L @ U
    (the contract of `lu_factor_blocked`, square A). b is (N,) or (N, k)."""
    M, N = LU.shape
    if M != N:
        raise ValueError(
            f"lu_solve needs square factors, got {LU.shape} (an M > N "
            "factorization has no unique solve)"
        )
    if b.shape[0] != N:
        raise ValueError(f"b has {b.shape[0]} rows, factors need {N}")
    cdtype = blas.compute_dtype(LU.dtype)
    Lu = LU.astype(cdtype)
    b2, squeeze = _as_2d(b.astype(cdtype))
    # TPU triangular_solve lowers to blocked inversion + matmuls, which at
    # default precision are single bf16 passes — pin the accurate path
    with jax.default_matmul_precision("highest"):
        y = blas.trsm_left_lower_unit(Lu, b2[perm])
        x = blas.trsm_left_upper(Lu, y)
    return x[:, 0] if squeeze else x


def cholesky_solve(L: jax.Array, b: jax.Array) -> jax.Array:
    """Solve A x = b given the lower Cholesky factor L (A = L L^T)."""
    if b.shape[0] != L.shape[0]:
        raise ValueError(f"b has {b.shape[0]} rows, factor needs {L.shape[0]}")
    cdtype = blas.compute_dtype(L.dtype)
    Lc = L.astype(cdtype)
    b2, squeeze = _as_2d(b.astype(cdtype))
    with jax.default_matmul_precision("highest"):
        y = blas.trsm_left_lower(Lc, b2)
        x = blas.trsm_left_lower_t(Lc, y)
    return x[:, 0] if squeeze else x


def _check_solve_rhs(geom, b) -> None:
    """Both mesh solves read b by padded global position: a shorter rhs
    would be silently clamp-read in the padded tiles and the solution
    returned at padded length — reject instead (pad A and b with an
    identity extension first, like `solve` does)."""
    n = geom.N
    rows = np.shape(b)[0] if np.ndim(b) else 0  # list rhs is fine
    if rows != n:
        raise ValueError(
            f"rhs has {rows} rows, the (padded) factorization needs "
            f"{n}; pad the system identity-extended before factoring")


def _diag_tile_rows(Aloc, k, x_, gcol, v, Px, Nl, dtype):
    """Shared by the LU and Cholesky mesh solves: step k's diagonal row
    tile — (v, Nl) local columns via a masked psum over 'x', and the
    (v, v) diagonal block via an index scatter + psum over 'y'."""
    from conflux_tpu.parallel.mesh import AXIS_X, AXIS_Y

    li = ((k // Px) * v).astype(jnp.int32)
    part = jnp.where(
        x_ == k % Px,
        lax.dynamic_slice(Aloc, (li, jnp.zeros((), jnp.int32)), (v, Nl)),
        jnp.zeros((), dtype))
    rows = lax.psum(part, AXIS_X)  # (v, Nl): my cols of those rows
    idx = jnp.where((gcol >= k * v) & (gcol < (k + 1) * v), gcol - k * v, v)
    diag = jnp.zeros((v, v), dtype).at[:, idx].add(
        jnp.where(idx[None, :] < v, rows, 0.0), mode="drop")
    diag = lax.psum(diag, AXIS_Y)
    return rows, diag


def lu_solve_distributed(shards, perm, geom, mesh, b) -> jax.Array:
    """Solve A x = b on the mesh, from `lu_factor_distributed`'s outputs.

    The factors are block-cyclic in *pivoted row order* (LAPACK layout), so
    the solve is plain block forward/back substitution over tile steps: per
    step, the diagonal tile's v rows are assembled with one masked psum
    over 'x', each device dots them against its already-solved column
    entries, and a psum over 'y' completes the inner products. O(N^2/P)
    flops over 2*n_steps latency-bound steps — triangular solves are
    sequential by nature; the reference has no distributed solve at all.

    b may be (N,) or (N, nrhs) — multi-RHS runs all columns through each
    substitution step at once (LAPACK getrs semantics). Returns x of b's
    shape, replicated.
    """
    _check_solve_rhs(geom, b)
    b2, squeeze = _as_2d(jnp.asarray(b, blas.compute_dtype(shards.dtype)))
    fn = _build_lu_solve(geom, mesh_cache_key(mesh))
    x = fn(shards, jnp.asarray(perm, jnp.int32), b2)
    return x[:, 0] if squeeze else x


@functools.lru_cache(maxsize=16)
def _build_lu_solve(geom, mesh_key):
    from jax.sharding import PartitionSpec as P

    from conflux_tpu.parallel.mesh import (
        AXIS_X, AXIS_Y, AXIS_Z, lookup_mesh,
    )

    mesh = lookup_mesh(mesh_key)
    if geom.M != geom.N:
        raise ValueError("distributed solve needs a square factorization")
    v, Px, Py = geom.v, geom.grid.Px, geom.grid.Py
    Ml, Nl, n = geom.Ml, geom.Nl, geom.n_steps

    def device_fn(blk, perm, b):
        x_ = lax.axis_index(AXIS_X)
        y_ = lax.axis_index(AXIS_Y)
        dtype = blas.compute_dtype(blk.dtype)
        Aloc = blk[0, 0].astype(dtype)  # z-replicated factors, pivoted order
        bp = b.astype(dtype)[perm]  # rhs in pivoted row order

        lc = jnp.arange(Nl, dtype=jnp.int32)
        gcol = ((lc // v) * Py + y_) * v + (lc % v)

        nrhs = bp.shape[1]
        i0 = jnp.zeros((), jnp.int32)

        def fwd(k, yv):
            rows, diag = _diag_tile_rows(Aloc, k, x_, gcol, v, Px, Nl, dtype)
            solved = gcol < k * v
            s = jnp.matmul(rows, jnp.where(solved[:, None], yv[gcol], 0.0),
                           precision=lax.Precision.HIGHEST)
            s = lax.psum(s, AXIS_Y)  # (v, nrhs)
            kv = jnp.asarray(k * v, jnp.int32)
            bk = lax.dynamic_slice(bp, (kv, i0), (v, nrhs))
            yk = blas.trsm_left_lower_unit(blas.unit_lower(diag), bk - s)
            return lax.dynamic_update_slice(yv, yk, (kv, i0))

        yv = lax.fori_loop(0, n, fwd, jnp.zeros((geom.N, nrhs), dtype))

        def bwd(i, xv):
            k = n - 1 - i
            rows, diag = _diag_tile_rows(Aloc, k, x_, gcol, v, Px, Nl, dtype)
            ahead = gcol >= (k + 1) * v
            s = jnp.matmul(rows, jnp.where(ahead[:, None], xv[gcol], 0.0),
                           precision=lax.Precision.HIGHEST)
            s = lax.psum(s, AXIS_Y)
            kv = jnp.asarray(k * v, jnp.int32)
            yk = lax.dynamic_slice(yv, (kv, i0), (v, nrhs))
            xk = blas.trsm_left_upper(jnp.triu(diag), yk - s)
            return lax.dynamic_update_slice(xv, xk, (kv, i0))

        xv = lax.fori_loop(0, n, bwd, jnp.zeros((geom.N, nrhs), dtype))
        # replicated by construction (pure collectives); replicate (a
        # complex-safe pmax) satisfies the
        # out_spec's replication check
        from conflux_tpu.parallel.mesh import replicate
        return replicate(xv, (AXIS_X, AXIS_Y, AXIS_Z))

    fn = shard_map(
        device_fn,
        mesh=mesh,
        in_specs=(P(AXIS_X, AXIS_Y, None, None), P(), P()),
        out_specs=P(),
    )
    return jax.jit(fn)


def cholesky_solve_distributed(shards, geom, mesh, b) -> jax.Array:
    """Solve A x = b on the mesh from `cholesky_factor_distributed` shards
    (lower triangle = L): block forward substitution with L, then block
    back substitution with L^T. Mirrors `lu_solve_distributed` (which the
    reference lacks entirely); no permutation is involved since Cholesky
    does not pivot.

    b may be (N,) or (N, nrhs) (LAPACK potrs semantics). Returns x of
    b's shape, replicated.
    """
    _check_solve_rhs(geom, b)
    b2, squeeze = _as_2d(jnp.asarray(b, blas.compute_dtype(shards.dtype)))
    fn = _build_cholesky_solve(geom, mesh_cache_key(mesh))
    x = fn(shards, b2)
    return x[:, 0] if squeeze else x


@functools.lru_cache(maxsize=16)
def _build_cholesky_solve(geom, mesh_key):
    from jax.sharding import PartitionSpec as P

    from conflux_tpu.parallel.mesh import (
        AXIS_X, AXIS_Y, AXIS_Z, lookup_mesh,
    )

    mesh = lookup_mesh(mesh_key)
    v, Px, Py = geom.v, geom.grid.Px, geom.grid.Py
    Ml, Nl, n = geom.Ml, geom.Nl, geom.Kappa

    def device_fn(blk, b):
        x_ = lax.axis_index(AXIS_X)
        y_ = lax.axis_index(AXIS_Y)
        dtype = blas.compute_dtype(blk.dtype)
        Aloc = blk[0, 0].astype(dtype)  # z-replicated factors, lower = L
        b = b.astype(dtype)

        lr = jnp.arange(Ml, dtype=jnp.int32)
        grow = ((lr // v) * Px + x_) * v + (lr % v)
        lc = jnp.arange(Nl, dtype=jnp.int32)
        gcol = ((lc // v) * Py + y_) * v + (lc % v)

        nrhs = b.shape[1]
        i0 = jnp.zeros((), jnp.int32)

        def fwd(k, yv):
            rows, diag = _diag_tile_rows(Aloc, k, x_, gcol, v, Px, Nl, dtype)
            solved = gcol < k * v
            s = jnp.matmul(rows, jnp.where(solved[:, None], yv[gcol], 0.0),
                           precision=lax.Precision.HIGHEST)
            s = lax.psum(s, AXIS_Y)
            kv = jnp.asarray(k * v, jnp.int32)
            bk = lax.dynamic_slice(b, (kv, i0), (v, nrhs))
            yk = blas.trsm_left_lower(jnp.tril(diag), bk - s)
            return lax.dynamic_update_slice(yv, yk, (kv, i0))

        yv = lax.fori_loop(0, n, fwd, jnp.zeros((geom.N, nrhs), dtype))

        def bwd(i, xv):
            k = n - 1 - i
            # column tile k of L: my rows of those v columns
            lj = ((k // Py) * v).astype(jnp.int32)
            cols = lax.psum(
                jnp.where(y_ == k % Py,
                          lax.dynamic_slice(Aloc, (jnp.zeros((), jnp.int32), lj),
                                            (Ml, v)),
                          jnp.zeros((), dtype)), AXIS_Y)
            ahead = grow >= (k + 1) * v
            # conj().T: the back sweep applies L^H for complex dtypes
            s = jnp.matmul(cols.conj().T,
                           jnp.where(ahead[:, None], xv[grow], 0.0),
                           precision=lax.Precision.HIGHEST)
            s = lax.psum(s, AXIS_X)  # (v, nrhs)
            idx = jnp.where((grow >= k * v) & (grow < (k + 1) * v),
                            grow - k * v, v)
            diag = jnp.zeros((v, v), dtype).at[idx].add(
                jnp.where(idx[:, None] < v, cols, 0.0), mode="drop")
            diag = lax.psum(diag, AXIS_X)
            kv = jnp.asarray(k * v, jnp.int32)
            yk = lax.dynamic_slice(yv, (kv, i0), (v, nrhs))
            xk = blas.trsm_left_lower_t(jnp.tril(diag), yk - s)
            return lax.dynamic_update_slice(xv, xk, (kv, i0))

        xv = lax.fori_loop(0, n, bwd, jnp.zeros((geom.N, nrhs), dtype))
        from conflux_tpu.parallel.mesh import replicate
        return replicate(xv, (AXIS_X, AXIS_Y, AXIS_Z))

    fn = shard_map(
        device_fn,
        mesh=mesh,
        in_specs=(P(AXIS_X, AXIS_Y, None, None), P()),
        out_specs=P(),
    )
    return jax.jit(fn)


def solve_distributed(A, b, *, grid=None, v: int = 1024, mesh=None,
                      refine: int = 0, factor_dtype=None,
                      residual_dtype=None, panel_chunk: int | None = None,
                      precision=None, segs: tuple = (16, 16),
                      tree: str = "pairwise", ir: str = "classic",
                      tol: float = 1e-6, restart: int = 16,
                      max_restarts: int = 12):
    """Factor + solve + iterative refinement on a device mesh.

    The at-scale solve path: the factorization is the distributed program
    (O(1) compile in the superstep count, unlike the unrolled single-device
    path whose trace grows with N/v), the triangular solves run on the
    mesh, and each refinement sweep computes r = b - A x in
    `residual_dtype` (default: float64 when x64 is enabled, else the
    compute dtype).

    Accuracy: with f32 factors the attainable relative residual is floored
    by the *residual computation* precision — an f32 residual stalls near
    eps_f32 * ||A|| * ||x|| / ||b|| (~4e-5 at N=16384 on the standard test
    matrix), while an f64 residual (software-emulated on TPU, but only
    O(N^2) work per sweep, cast strip-wise so no (N, N) f64 buffer exists)
    reaches <= 1e-6 in 2 sweeps — the BASELINE.md acceptance bar. This is
    the HPL-MxP recipe (low-precision O(N^3), high-precision O(N^2)); with
    factor_dtype=bfloat16 the factorization itself rides the fast MXU path
    and a few more sweeps recover the same bar. `precision` reaches the
    trailing GEMMs the same way (lax.Precision.HIGH = bf16x3 passes on f32
    storage — the measured fast path on v5e — vs the default HIGHEST);
    `segs`/`tree` pass through to the factorization untouched.

    A must be the original matrix, (N, N); device placement recommended at
    scale (a host A costs a full transfer). Returns x (N,) in the
    residual/accumulation dtype.
    """
    from conflux_tpu.geometry import LUGeometry, choose_grid
    from conflux_tpu.lu.distributed import lu_factor_distributed
    from conflux_tpu.parallel.mesh import make_mesh

    N = A.shape[0]
    if A.shape[0] != A.shape[1]:
        raise ValueError("solve_distributed needs a square A")
    if ir not in ("classic", "gmres"):
        # before the O(N^3) factorization: a typo must fail in
        # microseconds, not after a multi-minute factor
        raise ValueError(f"unknown ir {ir!r} (classic|gmres)")
    if grid is None:
        grid = choose_grid(jax.device_count(), N, N)
    geom = LUGeometry.create(N, N, v, grid)
    if (geom.M, geom.N) != (N, N):
        raise ValueError(
            f"N={N} must be a multiple of v*Px and v*Py (got padding to "
            f"{geom.M}x{geom.N}); pre-pad with an identity extension")
    if mesh is None:
        mesh = make_mesh(grid)

    fdtype = A.dtype if factor_dtype is None else factor_dtype
    cdtype = blas.compute_dtype(A.dtype)
    if residual_dtype is None:
        residual_dtype = (jnp.float64 if jax.config.jax_enable_x64
                          else cdtype)

    shards = _build_scatter(geom, mesh_cache_key(mesh),
                            jnp.dtype(fdtype).name)(jnp.asarray(A))
    out, perm = lu_factor_distributed(shards, geom, mesh,
                                      panel_chunk=panel_chunk, donate=True,
                                      precision=precision, segs=segs,
                                      tree=tree)

    if ir == "gmres":
        b_r = jnp.asarray(b, residual_dtype)
        # GMRES-IR: the factors precondition FGMRES instead of driving a
        # Richardson iteration — converges where classic IR diverges
        # (cond(A)·eps_factor ~ 1, the bf16/bf16x3 factor regime). This
        # is the actual HPL-MxP algorithm; `refine` is ignored here
        # (tol/restart/max_restarts govern). The callables come from a
        # geometry-keyed cache and the data rides fgmres's `args`, so
        # repeated solves at one geometry share one compiled cycle.
        matvec, precond = _gmres_ops(geom, mesh_cache_key(mesh),
                                     jnp.dtype(residual_dtype).name)
        x, info = fgmres(
            matvec, precond, b_r, args=(jnp.asarray(A), out, perm),
            tol=tol, restart=restart, max_restarts=max_restarts,
            rdtype=residual_dtype)
        if info["residual"] > tol:
            import warnings

            warnings.warn(
                f"GMRES-IR stalled at residual {info['residual']:.3e} "
                f"(> tol {tol:.1e}) after {info['restarts']} restarts "
                "— raise max_restarts/restart or improve the factors",
                RuntimeWarning, stacklevel=2)
        return x
    return refine_classic(
        lambda r: lu_solve_distributed(out, perm, geom, mesh, r),
        A, b, refine, residual_dtype, cdtype)


def refine_classic(solve_fn, A, b, sweeps: int, rdtype, corr_dtype):
    """Classic (Richardson) iterative refinement: x0 = solve(b), then
    `sweeps` rounds of x += solve(b - A x). The single implementation of
    the numerically delicate discipline shared by `solve_distributed`,
    the miniapp's --refine and the bench: x and b stay in the high
    (residual) precision `rdtype` — a b downcast would make IR converge
    to A x = low(b) instead — and only the corrections ride the
    low-precision factors through `solve_fn` (input cast to
    `corr_dtype`)."""
    b_r = jnp.asarray(b, rdtype)
    x = solve_fn(jnp.asarray(b, corr_dtype)).astype(rdtype)
    for _ in range(sweeps):
        r = _residual_strips(A, x, b_r, rdtype)
        x = x + solve_fn(r.astype(corr_dtype)).astype(rdtype)
    return x


def fgmres(matvec, precond, b, *, args=(), x0=None, tol: float = 1e-6,
           restart: int = 16, max_restarts: int = 12, rdtype=None):
    """Flexible GMRES with right preconditioning — the GMRES-IR engine.

    Solves A x = b where `matvec(x, *args)` applies A (accumulate in
    `rdtype`) and `precond(r, *args)` applies an approximate inverse
    (typically a low-precision LU solve: the HPL-MxP recipe — classic
    iterative
    refinement is a Richardson iteration that DIVERGES once
    cond(A)·eps_factor approaches 1, e.g. bf16 factors on a
    cond ~1e3 matrix; FGMRES with the same factors as preconditioner
    converges whenever the preconditioned spectrum clusters).

    TPU-native structure: each restart cycle is ONE jitted program — the
    full Arnoldi process with masked reorthogonalized Gram-Schmidt (CGS2) runs
    device-resident (`lax.fori_loop` over the basis; H and the Krylov
    bases V, Z are fixed-shape carries), so a cycle costs zero host
    round-trips; the only readback per cycle is the small H matrix and
    residual norm for the host-side least-squares update. The basis is
    flexible (Z stores preconditioned vectors), so `precond` may itself
    be any jit-traceable approximate solve.

    `args` rides through to both callables AS JIT ARGUMENTS — pass the
    factors/matrix here (not via closure) so the compiled cycle is
    reused across calls with different data: callers that pass the same
    (matvec, precond, restart, rdtype) identities share one compile.

    Returns (x, info) with info = {'restarts', 'residual'} — residual is
    ||b - A x|| / ||b|| measured with `matvec` at the end.
    """
    if rdtype is None:
        rdtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    b_r = jnp.asarray(b, rdtype)
    N = b_r.shape[0]
    m = int(restart)
    if m < 1:
        raise ValueError(f"restart must be >= 1, got {restart}")
    cycle = _fgmres_cycle(matvec, precond, m, jnp.dtype(rdtype).name)

    x = (jnp.zeros((N,), rdtype) if x0 is None
         else jnp.asarray(x0, rdtype))
    done_restarts = 0
    bnorm = float(jnp.sqrt(jnp.sum(b_r * b_r)))
    if bnorm == 0:
        return x, {"restarts": 0, "residual": 0.0}
    for k in range(max_restarts):
        beta, H, Z = cycle(x, b_r, *args)
        beta_f = float(beta)
        done_restarts = k + 1
        if beta_f / bnorm <= tol:
            break
        # small (m+1, m) least squares on host; breakdown columns (zero
        # subdiagonal) are harmless — lstsq handles the rank
        Hh = np.asarray(H, np.float64)
        e1 = np.zeros(m + 1)
        e1[0] = beta_f
        y, *_ = np.linalg.lstsq(Hh, e1, rcond=None)
        x = x + Z.T @ jnp.asarray(y, rdtype)
        # projected residual estimate: stop next cycle from launching if
        # this one already converged
        if np.linalg.norm(e1 - Hh @ y) / bnorm <= tol:
            break
    r = b_r - matvec(x, *args).astype(rdtype)
    rel = float(jnp.sqrt(jnp.sum(r * r))) / bnorm
    return x, {"restarts": done_restarts, "residual": rel}


@functools.lru_cache(maxsize=16)
def _fgmres_cycle(matvec, precond, m: int, rdtype_name: str):
    """One compiled Arnoldi cycle per (matvec, precond, restart, dtype):
    repeat fgmres calls with the SAME callables (e.g. a bench loop, or
    the restart loop itself) reuse the compiled program instead of
    re-jitting a fresh closure every call. Callers that pass fresh
    lambdas each time simply fall back to one compile per call."""
    rdtype = jnp.dtype(rdtype_name)

    @jax.jit
    def cycle(x, b_r, *args):
        N = b_r.shape[0]
        r = b_r - matvec(x, *args).astype(rdtype)
        beta = jnp.sqrt(jnp.sum(r * r))
        V = jnp.zeros((m + 1, N), rdtype).at[0].set(
            r / jnp.where(beta > 0, beta, 1))
        Z = jnp.zeros((m, N), rdtype)
        H = jnp.zeros((m + 1, m), rdtype)

        def arnoldi(j, carry):
            V, Z, H = carry
            z = precond(V[j], *args).astype(rdtype)
            w = matvec(z, *args).astype(rdtype)
            # masked classical Gram-Schmidt with reorthogonalization
            # (CGS2): two batched projection passes against the whole
            # basis — rows > j are zero so their coefficients vanish and
            # the loop body stays fixed-shape for the one-compile cycle.
            # Single-pass CGS loses orthogonality at O(eps*kappa^2) on
            # ill-conditioned preconditioned operators (exactly the weak-
            # factor regime GMRES-IR exists for); CGS2 restores it at the
            # cost of two extra (m+1, N) GEMVs, and unlike true MGS stays
            # batched (no serial per-column dependence).
            mask = jnp.arange(m + 1) <= j
            h = jnp.where(mask, V @ w, 0)  # (m+1,)
            w = w - V.T @ h
            h2 = jnp.where(mask, V @ w, 0)
            w = w - V.T @ h2
            h = h + h2
            hn = jnp.sqrt(jnp.sum(w * w))
            V = V.at[j + 1].set(w / jnp.where(hn > 0, hn, 1))
            H = H.at[:, j].set(h).at[j + 1, j].set(hn)
            Z = Z.at[j].set(z)
            return V, Z, H

        V, Z, H = lax.fori_loop(0, m, arnoldi, (V, Z, H))
        return beta, H, Z

    return cycle


@functools.lru_cache(maxsize=16)
def _build_scatter(geom, mesh_key, dtype_name: str):
    """Jitted device-side scatter with a sharded output: (M, N) -> block-
    cyclic (Px, Py, Ml, Nl) placed directly with the mesh sharding — no
    single-device staging of the scattered array, no host round trip (the
    host `geom.scatter` costs a full transfer at scale). The factor-dtype
    cast happens inside the same program for the same reason. The layout
    math is `LUGeometry.scatter_blocks`, the single source of the tile
    convention."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from conflux_tpu.parallel.mesh import AXIS_X, AXIS_Y, lookup_mesh

    mesh = lookup_mesh(mesh_key)
    sharding = NamedSharding(mesh, P(AXIS_X, AXIS_Y, None, None))
    return jax.jit(
        lambda A: geom.scatter_blocks(A.astype(dtype_name)),
        out_shardings=sharding,
    )


@functools.partial(jax.jit, static_argnames=("rdtype",))
def _residual_strips(A, x, b, rdtype):
    """r = b - A x with the matvec accumulated in `rdtype`, casting A one
    row-strip at a time (a full (N, N) float64 copy would double the
    matrix footprint — 8 GB at N=32768)."""
    N = A.shape[0]
    strip = max(1, min(4096, N))
    xr = x.astype(rdtype)
    pieces = [
        b[i : i + strip].astype(rdtype)
        - A[i : i + strip].astype(rdtype) @ xr
        for i in range(0, N, strip)
    ]
    return jnp.concatenate(pieces) if len(pieces) > 1 else pieces[0]


@functools.lru_cache(maxsize=16)
def _gmres_ops(geom, mesh_key, rdtype_name: str):
    """(matvec, precond) pair for GMRES-IR at one geometry: stable
    function identities (the fgmres cycle-compile cache key) with the
    matrix/factors as runtime arguments."""
    from conflux_tpu.parallel.mesh import lookup_mesh

    mesh = lookup_mesh(mesh_key)
    rdtype = jnp.dtype(rdtype_name)

    def matvec(x, A, shards, perm):
        return _matvec_strips(A, x, rdtype)

    def precond(r, A, shards, perm):
        return lu_solve_distributed(
            shards, perm, geom, mesh,
            r.astype(blas.compute_dtype(shards.dtype)))

    return matvec, precond


def _matvec_strips(A, x, rdtype):
    """A @ x accumulated in `rdtype` with strip-wise casts (same HBM
    discipline as `_residual_strips`); traceable inside fgmres's jitted
    cycle."""
    N = A.shape[0]
    strip = max(1, min(4096, N))
    xr = x.astype(rdtype)
    pieces = [A[i : i + strip].astype(rdtype) @ xr
              for i in range(0, N, strip)]
    return jnp.concatenate(pieces) if len(pieces) > 1 else pieces[0]


def solve(A: jax.Array, b: jax.Array, *, v: int = 256,
          factor_dtype=None, refine: int = 0, spd: bool = False) -> jax.Array:
    """Solve A x = b by blocked factorization + optional refinement.

    factor_dtype: dtype the factorization runs in (default: A's dtype).
    Passing jnp.bfloat16 rides the MXU's fast single-pass path — ~6x the
    f32-accurate rate — and `refine` iterative-refinement sweeps (2-3 is
    typical) restore the solution to working-precision accuracy (the
    HPL-MxP trade). Convergence requires the classic IR condition
    cond(A) * err(factors) < 1: with bf16 factors that means reasonably
    well-conditioned (e.g. diagonally dominant) systems; for harder systems
    keep f32 factors or wrap the low-precision solve in GMRES as HPL-MxP
    does at scale.
    refine: number of refinement sweeps; each computes r = b - A x at
    HIGHEST precision in A's dtype and solves for the correction with the
    low-precision factors.
    spd: use Cholesky instead of LU (A must be SPD).
    """
    N = A.shape[0]
    v = min(v, N)
    pad = (-N) % v
    if pad:
        # Pad to the next multiple of v with an identity-extended diagonal
        # (the same trick LUGeometry.scatter uses): the extra rows/cols are
        # decoupled unit pivots, so factors and solution are unchanged and
        # the blocked loops keep a bounded number of supersteps (a divisor
        # fallback here can degenerate to v=1 for prime N, unrolling N
        # supersteps at trace time).
        Np = N + pad
        Ap = jnp.zeros((Np, Np), A.dtype)
        Ap = Ap.at[:N, :N].set(A)
        Ap = Ap.at[jnp.arange(N, Np), jnp.arange(N, Np)].set(1)
        A = Ap
        b2, squeezed = _as_2d(b)
        b = jnp.pad(b2, ((0, pad), (0, 0)))
        if squeezed:
            b = b[:, 0]
    fdtype = A.dtype if factor_dtype is None else factor_dtype
    Af = A.astype(fdtype)
    if spd:
        from conflux_tpu.cholesky.single import cholesky_blocked

        L = cholesky_blocked(Af, v=v)
        solve_corr = lambda r: cholesky_solve(L, r)
    else:
        from conflux_tpu.lu.single import lu_factor_blocked

        LU, perm = lu_factor_blocked(Af, v=v)
        solve_corr = lambda r: lu_solve(LU, perm, r)

    cdtype = blas.compute_dtype(A.dtype)
    Ac = A.astype(cdtype)
    bc = b.astype(cdtype)
    x = solve_corr(b).astype(cdtype)
    for _ in range(refine):
        r = bc - jnp.matmul(Ac, x, precision=lax.Precision.HIGHEST)
        x = x + solve_corr(r).astype(cdtype)
    return x[:N] if pad else x


def solve_updated(A, U, V, b, *, v: int = 256, factor_dtype=None,
                  refine: int = 0, spd: bool = False) -> jax.Array:
    """Solve (A + U V^H) x = b through the factors of A alone.

    The one-shot Sherman-Morrison-Woodbury entry point (the serving form
    is `SolveSession.update`, see `conflux_tpu.update`): A is factored
    once — O(N^3), same `v`/`factor_dtype`/`spd` recipe as :func:`solve`
    — and the rank-k correction rides a k x k capacitance system, so
    solving against MANY drifted variants of one A costs O(N^2 k) each
    instead of a refactorization. U, V are (N, k) with k << N; `refine`
    sweeps compute residuals against the DRIFTED matrix and correct
    through the same Woodbury apply (the classic-IR backstop). `spd`
    refers to A — the drifted matrix need not stay symmetric.
    """
    from conflux_tpu.update import woodbury_solve

    N = A.shape[0]
    if A.shape[0] != A.shape[1]:
        raise ValueError("solve_updated needs a square A")
    if U.shape != V.shape or U.ndim != 2 or U.shape[0] != N:
        raise ValueError(
            f"update factors must both be ({N}, k), got {U.shape} and "
            f"{V.shape}")
    v = min(v, N)
    pad = (-N) % v
    b2, squeeze = _as_2d(jnp.asarray(b))
    if pad:
        # identity-extended A (cf. solve); zero-row U/V leave the
        # extension's unit pivots untouched
        Np = N + pad
        Ap = jnp.zeros((Np, Np), A.dtype).at[:N, :N].set(A)
        A = Ap.at[jnp.arange(N, Np), jnp.arange(N, Np)].set(1)
        U = jnp.pad(jnp.asarray(U), ((0, pad), (0, 0)))
        V = jnp.pad(jnp.asarray(V), ((0, pad), (0, 0)))
        b2 = jnp.pad(b2, ((0, pad), (0, 0)))
    fdtype = A.dtype if factor_dtype is None else factor_dtype
    Af = A.astype(fdtype)
    if spd:
        from conflux_tpu.cholesky.single import cholesky_blocked

        L = cholesky_blocked(Af, v=v)
        base = lambda r: cholesky_solve(L, r)
    else:
        from conflux_tpu.lu.single import lu_factor_blocked

        LU, perm = lu_factor_blocked(Af, v=v)
        base = lambda r: lu_solve(LU, perm, r)
    x = woodbury_solve(base, A if refine else None, U, V, b2, refine=refine)
    if pad:
        x = x[:N]
    return x[:, 0] if squeeze else x


def lstsq(A: jax.Array, b: jax.Array, chunk: int | None = None,
          passes: int = 2, factor_dtype=None, refine: int = 0) -> jax.Array:
    """Least-squares min_x ||A x - b|| for tall full-rank A (M >= n).

    QR route (`qr.single.tall_qr`): x = R^{-1} (Q^T b). Completes the
    solver family (LU for square, Cholesky for SPD, QR for overdetermined)
    — the reference has no solve API at all; see the module docstring.

    `factor_dtype`/`refine` extend the HPL-MxP recipe to least squares:
    factor in a cheap dtype (e.g. bf16), then `refine` sweeps of
    r = b - A x in the accurate dtype with the correction solved through
    the same cheap factors (for consistent systems / small residuals this
    recovers the accurate-dtype solution like the square-solve IR path;
    genuinely inconsistent systems are limited by the normal-equations
    conditioning as usual).
    """
    M, n = A.shape
    if b.shape[0] != M:
        raise ValueError(f"b has {b.shape[0]} rows, A has {M}")
    from conflux_tpu.qr.single import tall_qr

    Af = A.astype(factor_dtype) if factor_dtype is not None else A
    Q, R = tall_qr(Af, chunk=chunk, passes=passes)
    cdtype = blas.compute_dtype(A.dtype)
    Qc, Rc = Q.astype(cdtype), R.astype(cdtype)
    b2, squeeze = _as_2d(b.astype(cdtype))

    def solve_ls(rhs):
        with jax.default_matmul_precision("highest"):
            c = jnp.matmul(Qc.conj().T, rhs,
                           precision=lax.Precision.HIGHEST)
            return blas.trsm_left_upper(Rc, c)

    x = solve_ls(b2)
    if refine:
        Ac = A.astype(cdtype)
        for _ in range(refine):
            r = b2 - jnp.matmul(Ac, x, precision=lax.Precision.HIGHEST)
            x = x + solve_ls(r)
    return x[:, 0] if squeeze else x


@functools.lru_cache(maxsize=32)
def _build_qtb(mesh_key, cdtype_name: str):
    """Compiled c = psum_x(Q_loc^T b_loc) program, cached per mesh/dtype
    (the shapes are traced; rebuilding the shard_map closure per call
    would force a recompile every invocation)."""
    from jax.sharding import PartitionSpec as P

    from conflux_tpu.parallel.mesh import AXIS_X, lookup_mesh

    mesh = lookup_mesh(mesh_key)
    cdtype = jnp.dtype(cdtype_name)

    from conflux_tpu.parallel.mesh import replicate

    def device_fn(qblk, bblk):
        c = lax.psum(
            jnp.matmul(qblk[0].astype(cdtype).conj().T, bblk[0],
                       precision=lax.Precision.HIGHEST), AXIS_X)
        return replicate(c, tuple(mesh.axis_names))

    return jax.jit(shard_map(
        device_fn, mesh=mesh,
        in_specs=(P(AXIS_X, None, None), P(AXIS_X, None, None)),
        out_specs=P()))


def lstsq_distributed(shards, mesh, b, algo: str = "tsqr",
                      chunk: int | None = None, passes: int = 2):
    """Distributed least squares on x-sharded rows: min_x ||A x - b||.

    shards is (Px, Ml, n) block-row shards (the `qr.distributed` layout),
    b is (M,) or (M, k) with M = Px*Ml. TSQR (or CholeskyQR2) gives
    (Q_shards, R); c = Q^T b is one (n, k) psum over 'x' — the only
    communication beyond the factorization's R reduction — then
    x = R^{-1} c, replicated.
    """
    from conflux_tpu.qr.distributed import (
        cholesky_qr2_distributed,
        tsqr_distributed,
    )

    shards = jnp.asarray(shards)
    Px, Ml, n = shards.shape
    cdtype = blas.compute_dtype(shards.dtype)
    b2, squeeze = _as_2d(jnp.asarray(b, cdtype))
    if b2.shape[0] != Px * Ml:
        # before the factorization: the error should be free
        raise ValueError(f"b has {b2.shape[0]} rows, shards hold {Px * Ml}")
    if algo == "tsqr":
        Qs, R = tsqr_distributed(shards, mesh, chunk=chunk, passes=passes)
    elif algo == "cholesky":
        Qs, R = cholesky_qr2_distributed(shards, mesh, passes=passes)
    else:
        raise ValueError(f"unknown algo {algo!r} (tsqr|cholesky)")
    bs = b2.reshape(Px, Ml, -1)
    c = _build_qtb(mesh_cache_key(mesh), cdtype.name)(Qs, bs)
    with jax.default_matmul_precision("highest"):
        x = blas.trsm_left_upper(jnp.asarray(R, cdtype), c)
    return x[:, 0] if squeeze else x


def lu_solve_transposed(LU: jax.Array, perm: jax.Array,
                        b: jax.Array) -> jax.Array:
    """Solve A^T x = b from the packed LU factors of A (getrs 'T' path:
    A[perm] = L U, so A^T = U^T L^T P and x = P^T (L^T \\ (U^T \\ b)))."""
    N = LU.shape[0]
    if LU.shape[0] != LU.shape[1] or b.shape[0] != N:
        raise ValueError(f"square factors and matching rhs required, "
                         f"got {LU.shape} and {b.shape}")
    cdtype = blas.compute_dtype(LU.dtype)
    Lu = LU.astype(cdtype)
    b2, squeeze = _as_2d(b.astype(cdtype))
    with jax.default_matmul_precision("highest"):
        y = blas.trsm_left_upper_t(Lu, b2)
        z = blas.trsm_left_lower_unit_t(Lu, y)
    x = jnp.zeros_like(z).at[perm].set(z)  # apply P^T
    return x[:, 0] if squeeze else x


def slogdet_from_lu(LU, perm):
    """(sign, log|det|) from packed LU factors (LAPACK getrf->det recipe:
    det = sign(perm) * prod(diag U)), np.linalg.slogdet conventions:
    sign is 0 for an exactly singular matrix, complex for complex input.
    Host-side; perm parity by cycle count."""
    d = np.asarray(jnp.diagonal(jnp.asarray(LU)))
    p = np.asarray(perm)
    n = p.shape[0]
    seen = np.zeros(n, dtype=bool)
    transpositions = 0
    for i in range(n):
        if seen[i]:
            continue
        j, clen = i, 0
        while not seen[j]:
            seen[j] = True
            j = p[j]
            clen += 1
        transpositions += clen - 1
    sign = -1.0 if transpositions % 2 else 1.0
    if (d == 0).any():
        # np convention: zero sign, complex-typed for complex input
        return (0j if np.iscomplexobj(d) else 0.0), float("-inf")
    if np.iscomplexobj(d):
        ang = np.angle(d).sum()
        sign = sign * np.exp(1j * ang)
    else:
        neg = int((d < 0).sum())
        sign = sign * (-1.0 if neg % 2 else 1.0)
    logabs = float(np.log(np.abs(d)).sum())
    return sign, logabs


def cond_estimate_1(A, LU, perm, iters: int = 5) -> float:
    """1-norm condition estimate from the factors (the `gecon` role):
    ||A||_1 * est(||A^{-1}||_1) via Hager's power iteration on A^{-1}
    (each step is one solve + one transpose solve through the factors —
    O(iters * N^2) after the O(N^3) factorization)."""
    A = jnp.asarray(A)
    n = A.shape[0]
    anorm = float(jnp.abs(A).sum(axis=0).max())
    x = jnp.full((n,), 1.0 / n, blas.compute_dtype(A.dtype))
    est = 0.0
    iters = max(1, iters)
    for it in range(iters):
        y = lu_solve(LU, perm, x)                      # y = A^{-1} x
        est_new = float(jnp.abs(y).sum())
        if est_new <= est:  # converged: skip the dead solve pair
            break
        est = est_new
        if it == iters - 1:  # count exit: the x update has no consumer
            break
        xi = jnp.sign(jnp.where(y == 0, 1.0, y))
        z = lu_solve_transposed(LU, perm, xi)          # z = A^{-T} xi
        j = int(jnp.argmax(jnp.abs(z)))
        x = jnp.zeros((n,), x.dtype).at[j].set(1.0)
    return anorm * est


def inv_from_lu(LU: jax.Array, perm: jax.Array) -> jax.Array:
    """A^{-1} from packed LU factors (the `getri` role): solve with the
    identity as RHS — N simultaneous columns through the same blocked
    substitutions, so the MXU sees (N, N) triangular solves, not N
    vector solves."""
    N = LU.shape[0]
    if LU.shape[0] != LU.shape[1]:
        raise ValueError(f"inverse needs square factors, got {LU.shape}")
    return lu_solve(LU, perm, jnp.eye(N, dtype=LU.dtype))


def qr_lstsq_distributed(Q_shards, R_shards, geom, mesh, b) -> jax.Array:
    """Least squares min_x ||A x - b|| on the mesh from the BLOCK-CYCLIC
    QR factors (`qr.qr_factor_distributed` outputs) — the general-matrix
    counterpart of `lstsq_distributed`'s tall x-sharded form, completing
    the distributed-solver matrix (LU square / Cholesky SPD / QR
    overdetermined).

    c = Q^H b is one (Nl, k) partial per device + psums; then R x = c is
    block back substitution over R's own block-cyclic geometry (the
    `lu_solve_distributed` machinery on the upper factor). b is (M,) or
    (M, k) at the PADDED geometry size; x comes back (N,) or (N, k),
    replicated.
    """
    from conflux_tpu.geometry import check_shards
    from conflux_tpu.qr.distributed import r_geometry

    M = geom.M
    rows = np.shape(b)[0] if np.ndim(b) else 0
    if rows != M:
        raise ValueError(
            f"rhs has {rows} rows, the (padded) factorization needs {M}")
    Q_shards = jnp.asarray(Q_shards)
    R_shards = jnp.asarray(R_shards)
    check_shards(Q_shards, geom, "Q_shards")
    check_shards(R_shards, r_geometry(geom), "R_shards")
    b2, squeeze = _as_2d(jnp.asarray(b, blas.compute_dtype(Q_shards.dtype)))
    fn = _build_qr_lstsq(geom, mesh_cache_key(mesh))
    x = fn(Q_shards, R_shards, b2)
    return x[:, 0] if squeeze else x


@functools.lru_cache(maxsize=16)
def _build_qr_lstsq(geom, mesh_key):
    from jax.sharding import PartitionSpec as P

    from conflux_tpu.parallel.mesh import (
        AXIS_X, AXIS_Y, AXIS_Z, lookup_mesh,
    )

    mesh = lookup_mesh(mesh_key)
    v, Px, Py = geom.v, geom.grid.Px, geom.grid.Py
    Ml, Nl = geom.Ml, geom.Nl
    n = geom.Nt  # R row tiles to substitute

    def device_fn(Qblk, Rblk, b):
        x_ = lax.axis_index(AXIS_X)
        y_ = lax.axis_index(AXIS_Y)
        dtype = blas.compute_dtype(Qblk.dtype)
        Qloc = Qblk[0, 0].astype(dtype)
        Rloc = Rblk[0, 0].astype(dtype)
        b = b.astype(dtype)

        lr = jnp.arange(Ml, dtype=jnp.int32)
        grow = ((lr // v) * Px + x_) * v + (lr % v)
        lc = jnp.arange(Nl, dtype=jnp.int32)
        gcol = ((lc // v) * Py + y_) * v + (lc % v)
        nrhs = b.shape[1]
        i0 = jnp.zeros((), jnp.int32)

        # ---- c = Q^H b: local rows contribute, psum over 'x' ---------- #
        part = jnp.matmul(Qloc.conj().T, b[grow],
                          precision=lax.Precision.HIGHEST)  # (Nl, k)
        part = lax.psum(part, AXIS_X)
        # assemble replicated (N, k): each y owns disjoint global cols
        cv = lax.psum(
            jnp.zeros((geom.N, nrhs), dtype).at[gcol].set(part), AXIS_Y)

        # ---- back substitution R x = c over R's geometry -------------- #
        def bwd(i, xv):
            k = n - 1 - i
            rows, diag = _diag_tile_rows(Rloc, k, x_, gcol, v, Px, Nl,
                                         dtype)
            ahead = gcol >= (k + 1) * v
            s = jnp.matmul(rows, jnp.where(ahead[:, None], xv[gcol], 0.0),
                           precision=lax.Precision.HIGHEST)
            s = lax.psum(s, AXIS_Y)
            kv = jnp.asarray(k * v, jnp.int32)
            ck = lax.dynamic_slice(cv, (kv, i0), (v, nrhs))
            xk = blas.trsm_left_upper(jnp.triu(diag), ck - s)
            return lax.dynamic_update_slice(xv, xk, (kv, i0))

        xv = lax.fori_loop(0, n, bwd, jnp.zeros((geom.N, nrhs), dtype))
        from conflux_tpu.parallel.mesh import replicate

        return replicate(xv, (AXIS_X, AXIS_Y, AXIS_Z))

    fn = shard_map(
        device_fn,
        mesh=mesh,
        in_specs=(P(AXIS_X, AXIS_Y, None, None),
                  P(AXIS_X, AXIS_Y, None, None), P()),
        out_specs=P(),
    )
    return jax.jit(fn)
