"""Problem geometry: 3D grids, padding, and block-cyclic tile maps.

TPU-native equivalent of the reference's problem/grid setup layer
(`src/conflux/lu/lu_params.hpp:21-138` — grid auto-selection, padding to
tile-grid multiples, local tile counts — and the Cholesky geometry in
`src/conflux/cholesky/CholeskyProperties.cpp:71-235`). Pure host-side Python:
no communication happens here. The chosen (Px, Py, Pz) maps 1:1 onto a
`jax.sharding.Mesh` with axis names ('x', 'y', 'z').

Tile distribution is 2D block-cyclic over the (x, y) plane: tile (i, j) of the
global tile grid lives on mesh coordinate (i mod Px, j mod Py) at local tile
slot (i // Px, j // Py). The z axis does not own distinct tiles — it carries
2.5D *replicated partial sums* of the trailing matrix (reference P3 strategy,
`SURVEY.md` §2.4).
"""

from __future__ import annotations

import dataclasses
import math

import numpy as np


# --------------------------------------------------------------------------- #
# Grids
# --------------------------------------------------------------------------- #


@dataclasses.dataclass(frozen=True)
class Grid3:
    """A 3D processor/device grid (Px, Py, Pz) — mesh axes ('x', 'y', 'z')."""

    Px: int
    Py: int
    Pz: int

    @property
    def P(self) -> int:
        return self.Px * self.Py * self.Pz

    def __post_init__(self):
        if self.Px < 1 or self.Py < 1 or self.Pz < 1:
            raise ValueError(f"grid dims must be >= 1, got {self}")

    def __str__(self) -> str:
        return f"{self.Px}x{self.Py}x{self.Pz}"

    @classmethod
    def parse(cls, s: str) -> "Grid3":
        """Parse 'Px,Py,Pz' or 'PxxPyxPz' CLI syntax."""
        sep = "," if "," in s else "x"
        parts = [int(t) for t in s.split(sep)]
        if len(parts) != 3:
            raise ValueError(f"expected 3 grid dims, got {s!r}")
        return cls(*parts)


def _isqrt(n: int) -> int:
    return int(math.isqrt(n))


def _best_grid(P: int, target_ratio: float) -> Grid3:
    """Exhaustive search over factor triples of P.

    Considers every (Px, Py, Pz) with Px*Py*Pz == P and Px >= Py >= Pz (the
    z axis carries 2.5D replication, so Pz larger than the 2D grid sides is
    never useful), and minimizes
        |log((Px/Py) / target_ratio)| + 0.35 * ln(Pz)
    i.e. match the matrix aspect ratio in the 2D plane, with a mild penalty
    on replication depth. Unlike the reference's closed-form heuristic
    (`lu_params.hpp:21-47`) this always uses *all* P devices; on the
    published experiment grids (BASELINE.md) it reproduces the reference's
    choices exactly (2x2x1, 2x2x2, 4x4x1, 4x4x2, ..., 32x32x1).
    """
    if P < 1:
        raise ValueError("P must be >= 1")
    best = None
    best_key = None
    for Pz in range(1, P + 1):
        if P % Pz:
            continue
        Q = P // Pz
        for Py in range(1, Q + 1):
            if Q % Py:
                continue
            Px = Q // Py
            if not (Px >= Py >= Pz):
                continue
            score = abs(math.log((Px / Py) / target_ratio)) + 0.35 * math.log(Pz)
            key = (score, Pz, Px)
            if best_key is None or key < best_key:
                best_key, best = key, Grid3(Px, Py, Pz)
    assert best is not None  # (P, 1, 1) always qualifies
    return best


def choose_grid(P: int, M: int, N: int) -> Grid3:
    """Pick (Px, Py, Pz) for an LU factorization of an M x N matrix on P
    devices (role of the reference auto-pick, `lu_params.hpp:21-47`)."""
    ratio = max(M, N) / max(1, min(M, N))
    return _best_grid(P, ratio)


def choose_cholesky_grid(P: int) -> Grid3:
    """Pick (Px, Py, Pz) for Cholesky on P devices (role of the reference
    driver's grid pick, `Cholesky.cpp:76-114`, generalized to any P)."""
    return _best_grid(P, 1.0)


# --------------------------------------------------------------------------- #
# Block-cyclic index math
# --------------------------------------------------------------------------- #


def tile_owner(t: int, Pdim: int) -> int:
    """Mesh coordinate along one axis owning global tile index t."""
    return t % Pdim


def tile_local(t: int, Pdim: int) -> int:
    """Local tile slot of global tile t on its owner."""
    return t // Pdim


def tile_global(p: int, lt: int, Pdim: int) -> int:
    """Global tile index of local slot lt on mesh coordinate p."""
    return lt * Pdim + p


def row_owner(r: int, v: int, Pdim: int) -> int:
    """Mesh x-coordinate owning global row r (tile size v)."""
    return (r // v) % Pdim


def row_local(r: int, v: int, Pdim: int) -> int:
    """Local row index of global row r on its owner."""
    return (r // v) // Pdim * v + r % v


def row_global(p: int, lr: int, v: int, Pdim: int) -> int:
    """Global row index of local row lr on mesh coordinate p."""
    return (lr // v * Pdim + p) * v + lr % v


def local_row_indices(p: int, Ml: int, v: int, Pdim: int) -> np.ndarray:
    """Global row indices (length Ml) owned by x-coordinate p, in local order."""
    lr = np.arange(Ml)
    return (lr // v * Pdim + p) * v + lr % v


def ragged_segments(n_tiles: int, v: int, max_seg: int) -> list[tuple[int, int]]:
    """Ceil-divide n_tiles tiles of width v into at most max_seg contiguous
    (start, stop) element ranges, the last one ragged. Used by the
    distributed trailing updates to skip fully-factored column/row blocks."""
    n = min(max_seg, n_tiles)
    per = -(-n_tiles // n)
    return [(g * per * v, min((g + 1) * per, n_tiles) * v)
            for g in range(n) if g * per < n_tiles]


def check_shards(shards, geom, what: str = "shards") -> None:
    """Reject mis-shaped shard arrays with a geometry-aware message (a
    wrong shape otherwise surfaces as a cryptic shard_map mismatch deep
    inside the jitted program)."""
    shape = tuple(shards.shape) if hasattr(shards, "shape") else None
    Ml = getattr(geom, "Ml")
    Nl = getattr(geom, "Nl")
    want = (geom.grid.Px, geom.grid.Py, Ml, Nl)
    if shape != want:
        raise ValueError(
            f"{what} shape {shape} does not match the geometry's "
            f"block-cyclic layout {want} (grid {geom.grid}, "
            f"local {Ml}x{Nl}); build shards with geom.scatter or "
            f"distribute_shards")


# --------------------------------------------------------------------------- #
# LU geometry
# --------------------------------------------------------------------------- #


@dataclasses.dataclass(frozen=True)
class LUGeometry:
    """All derived sizes for a distributed LU problem.

    Equivalent role to the reference's `lu_params` container
    (`lu_params.hpp:49-138`), minus the communicators (which on TPU are just
    named mesh axes) and the matrix storage (owned by the algorithm).
    """

    M: int  # padded global rows
    N: int  # padded global cols
    Mbase: int  # requested rows before padding
    Nbase: int  # requested cols before padding
    v: int  # tile size
    grid: Grid3

    @classmethod
    def create(cls, M: int, N: int, v: int, grid: Grid3) -> "LUGeometry":
        """Pad M, N up to multiples of v*Px / v*Py (reference `lu_params.hpp:67-71`)."""
        if v < 1:
            raise ValueError("tile size v must be >= 1")
        Mp = v * grid.Px * math.ceil(M / (v * grid.Px))
        Np = v * grid.Py * math.ceil(N / (v * grid.Py))
        return cls(M=Mp, N=Np, Mbase=M, Nbase=N, v=v, grid=grid)

    # Tile counts
    @property
    def Mt(self) -> int:
        return self.M // self.v

    @property
    def Nt(self) -> int:
        return self.N // self.v

    # Local tile counts per device (block-cyclic, exact by construction)
    @property
    def Mtl(self) -> int:
        return self.Mt // self.grid.Px

    @property
    def Ntl(self) -> int:
        return self.Nt // self.grid.Py

    # Local matrix extents
    @property
    def Ml(self) -> int:
        return self.Mtl * self.v

    @property
    def Nl(self) -> int:
        return self.Ntl * self.v

    @property
    def nlayr(self) -> int:
        """Columns of each z-layer's slab of a v-wide panel (2.5D split)."""
        return -(-self.v // self.grid.Pz)

    @property
    def n_steps(self) -> int:
        """Number of supersteps = number of v-wide panels to factor."""
        return min(self.Mt, self.Nt)

    # ---------------- host-side scatter/gather ---------------- #

    def scatter(self, A: np.ndarray) -> np.ndarray:
        """Distribute a global (M, N) matrix into per-device block-cyclic shards.

        Returns an array of shape (Px, Py, Ml, Nl): shard [pi, pj] holds the
        tiles {(i, j) : i mod Px == pi, j mod Py == pj} in local tile order.
        The z axis is not represented — layer 0 owns initial data, other
        layers start at zero (2.5D convention, reference `python/conflux.py`
        initial distribution).
        """
        M, N, v = self.M, self.N, self.v
        Px, Py = self.grid.Px, self.grid.Py
        if A.shape != (M, N):
            padded = np.zeros((M, N), dtype=A.dtype)
            padded[: A.shape[0], : A.shape[1]] = A
            # identity on the padding diagonal keeps padded LU well-posed
            for d in range(min(A.shape[0], A.shape[1]), min(M, N)):
                padded[d, d] = 1.0
            A = padded
        from conflux_tpu import native

        fast = native.scatter(A, v, Px, Py)
        if fast is not None:
            return fast
        return np.ascontiguousarray(self.scatter_blocks(A))

    def scatter_blocks(self, A):
        """Pure reshape/transpose core of :meth:`scatter` — the single
        source of the block-cyclic layout convention (tile index
        i = lt*Px + px). Works on numpy and jax arrays alike, so it can run
        inside jit for device-side scattering (no exact-shape check or
        padding here; `A` must already be (M, N))."""
        Px, Py, v = self.grid.Px, self.grid.Py, self.v
        # (M, N) -> (Mtl, Px, v, Ntl, Py, v) -> (Px, Py, Ml, Nl)
        T = A.reshape(self.Mtl, Px, v, self.Ntl, Py, v)
        return T.transpose(1, 4, 0, 2, 3, 5).reshape(Px, Py, self.Ml, self.Nl)

    def gather(self, shards: np.ndarray) -> np.ndarray:
        """Inverse of :meth:`scatter`: (Px, Py, Ml, Nl) -> (M, N)."""
        Px, Py, v = self.grid.Px, self.grid.Py, self.v
        from conflux_tpu import native

        fast = native.gather(np.asarray(shards), v, Px, Py)
        if fast is not None:
            return fast
        T = shards.reshape(Px, Py, self.Mtl, v, self.Ntl, v)
        T = np.transpose(T, (2, 0, 3, 4, 1, 5))  # (Mtl, Px, v, Ntl, Py, v)
        return np.ascontiguousarray(T.reshape(self.M, self.N))

    def global_row_index(self) -> np.ndarray:
        """(Px, Ml) array: global row index of each local row per x-coordinate.

        TPU equivalent of the reference's `gri` global-row-index tracking
        (`conflux_opt.hpp:427-440`) — here a static map, since rows never
        physically move (pivoting is value-level masking, not compaction).
        """
        return np.stack(
            [local_row_indices(p, self.Ml, self.v, self.grid.Px) for p in range(self.grid.Px)]
        )


# --------------------------------------------------------------------------- #
# Cholesky geometry
# --------------------------------------------------------------------------- #


def choose_cholesky_tile(N: int, P: int, *, itemsize: int = 4,
                         hbm_bytes: int = 16 << 30) -> int:
    """Tile-size heuristic for Cholesky.

    The reference derives v from a memory ratio: it grows the tile until
    the per-rank tile buffers reach a target fraction of the rank's memory
    (`Cholesky.cpp:116-134`). Same principle here, with TPU constants: the
    per-device working set is the local matrix share (~N^2/P elements) plus
    the step's panel slab (Ml x v) and its z-replicated copies, so v is
    grown while (a) the panel slab stays under ~1/8 of the local share —
    keeping the working set within HBM headroom — and (b) at least two
    tile columns per device axis remain (the loop needs >= 2 supersteps to
    pipeline). v is further capped at 1024: the potrf/LU panel custom
    calls overflow scoped VMEM on tall tiles (see ops/blas.py), and 1024
    measured fastest on a v5e for the GEMM-dominated regime anyway.
    """
    if N <= 0:
        return max(1, N)
    px = max(1, _isqrt(P))
    local_share = max(1, N * N // max(1, P)) * itemsize
    if local_share > hbm_bytes:
        # out-of-memory configs still get a well-formed answer; the caller's
        # scatter will fail with a clear message if it truly cannot fit
        local_share = hbm_bytes
    v = 128
    while v * 2 <= 1024:
        nv = v * 2
        ml = -(-N // (nv * px)) * nv  # local panel height at tile nv
        if N // (nv * px) < 2:  # (b) keep >= 2 tile cols per device
            break
        if ml * nv * itemsize * 8 > local_share:  # (a) slab <= 1/8 share
            break
        v = nv
    return min(v, max(1, N))


@dataclasses.dataclass(frozen=True)
class CholeskyGeometry:
    """Derived sizes for distributed Cholesky (reference `CholeskyProperties`)."""

    N: int
    Nbase: int
    v: int
    grid: Grid3

    @classmethod
    def create(cls, N: int, v: int, grid: Grid3) -> "CholeskyGeometry":
        lcm = v * grid.Px * grid.Py // math.gcd(grid.Px, grid.Py)
        Np = lcm * math.ceil(N / lcm)
        return cls(N=Np, Nbase=N, v=v, grid=grid)

    @property
    def Kappa(self) -> int:
        """Number of tile columns = supersteps (reference calls this Kappa)."""
        return self.N // self.v

    @property
    def Mtl(self) -> int:
        return self.Kappa // self.grid.Px

    @property
    def Ntl(self) -> int:
        return self.Kappa // self.grid.Py

    @property
    def Ml(self) -> int:
        return self.Mtl * self.v

    @property
    def Nl(self) -> int:
        return self.Ntl * self.v

    @property
    def nlayr(self) -> int:
        return -(-self.v // self.grid.Pz)

    def scatter(self, A: np.ndarray) -> np.ndarray:
        return LUGeometry.create(self.N, self.N, self.v, self.grid).scatter(A)

    def gather(self, shards: np.ndarray) -> np.ndarray:
        return LUGeometry.create(self.N, self.N, self.v, self.grid).gather(shards)
