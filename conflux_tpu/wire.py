"""Zero-copy fabric wire: shm payload rings + batched control plane
(DESIGN §31).

The §28 fabric's ``ProcessHost`` originally pickled every RHS and every
solution over its AF_UNIX pipe — four buffer copies and one pipe
round-trip per request, paid per message. This module re-applies the
paper's communication discipline (move bytes once, in bulk — PAPER.md
§1) and the §19 staging lesson (host-stage in numpy, batch the
boundary crossing) at the RPC layer:

- **Payload rings** (:class:`Ring`): one ``multiprocessing.
  shared_memory`` segment per direction per host. The front stages a
  numpy RHS directly into a ring-allocated record (ONE memcpy) and
  ships only a compact descriptor (offset, size, generation, dtype,
  shape) over the control pipe; the worker maps a numpy view onto the
  same bytes and feeds it straight to the engine — the next copy is
  the h2d staging the engine pays anyway. Results come back the same
  way through the reply ring.
- **Generation tags**: every record carries its allocation generation
  in a header AND a footer; the descriptor carries it too. A reader
  whose record fails the check — a SIGKILL mid-write left the footer
  unwritten (torn), a stale descriptor points at a recycled slot, or
  the descriptor names bytes outside the segment (overrun) — raises a
  structured :class:`~conflux_tpu.resilience.WireCorrupt` instantly.
  The payload channel can no longer be trusted, so the front treats it
  exactly like a torn pipe: instant structural death, never a hang.
- **Cursor reclaim, no scanning**: records are bump-allocated off a
  monotonic u64 write cursor (wrap = skip the tail). The request ring
  is reclaimed entirely by the front (records freed when their reply
  lands, out-of-order safe); the reply ring's read cursor is the one
  shared word — the front advances it in the segment header after
  copying a reply out, the worker reads it when sizing free space. A
  torn cursor read can at worst mis-size an allocation, and the
  generation check turns that into a structured error, not silent
  corruption.
- **Backpressure, never a blocking wait**: a full request ring raises
  :class:`RingFull` with a ``retry_after`` sized from the ring's own
  measured drain rate (bytes freed per second, EMA) — the fabric front
  maps it to ``HostUnavailable(retry_after=)``. The worker's reply
  side may briefly wait for the front to drain, then falls back to
  shipping the value inline on the control frame (pickle) so progress
  is never gated on ring space.
- **Batched control plane**: descriptors ride ``solve_many`` /
  ``reply_many`` frames. Both pumps batch opportunistically — while
  one frame is in flight on the pipe, every submission that arrives
  queues into the next frame — so the per-message pipe overhead
  amortizes across the coalescing window instead of being paid per
  request (zero added latency when idle; ``batch_window_s`` can
  stretch the window deliberately).

Fault sites (`resilience.FaultPlan`): ``ring_full`` forces an
allocation refusal, ``torn_segment`` / ``stale_generation`` force the
reader-side integrity trips — `scripts/soak.py --fabric` drives them
through real shared segments via :class:`InProcWire`.

``ProcessHost(wire="pickle")`` is the escape hatch: the pre-§31 wire,
byte-identical behavior, no segments.
"""

from __future__ import annotations

import dataclasses
import secrets
import struct
import threading
import time
from collections import deque
from concurrent.futures import Future
from multiprocessing import shared_memory
from typing import Any, Callable

import numpy as np

from conflux_tpu import resilience
from conflux_tpu.resilience import WireCorrupt, bump

__all__ = ["Ring", "RingFull", "WireConfig", "WireClient", "WireServer",
           "InProcWire", "WireCorrupt"]

_MAGIC = 0x43465857        # "CFXW"
_VERSION = 1
_ALIGN = 64                # record spans round up to cache lines
_CTRL = 64                 # segment control header bytes
# control header: magic u32, version u32, capacity u64, R u64, W u64
_CTRL_FMT = struct.Struct("<IIQQQ")
_CTRL_R_OFF = 16           # byte offset of the shared read cursor
_CTRL_W_OFF = 24
# record header: magic u32, generation u32, payload bytes u64, span u64
_HDR = struct.Struct("<IIQQ")
# record footer: generation u32, ~generation u32 — written LAST, so a
# writer killed mid-copy leaves a detectable tear
_FTR = struct.Struct("<II")
_U64 = struct.Struct("<Q")


def _round_up(n: int, a: int) -> int:
    return (n + a - 1) // a * a


def _fire(plan, site: str) -> bool:
    """True when the installed/explicit FaultPlan fires `site` (wire
    faults use the generic 'crash' kind). One None check in
    production."""
    p = plan if plan is not None else resilience.active_faults()
    if p is None:
        return False
    return p.fire(site, kinds=("crash",)) is not None


class RingFull(RuntimeError):
    """A ring allocation was refused — the segment holds `needed` more
    bytes than `capacity` minus what is still in flight. NEVER a
    blocking wait on the hot path: `retry_after` is sized from the
    ring's measured drain rate (bytes freed per second), so a retrying
    caller lands as space actually frees up. The fabric front maps
    this to ``HostUnavailable(retry_after=)``."""

    def __init__(self, msg: str, retry_after: float = 0.0,
                 needed: int = 0, capacity: int = 0):
        super().__init__(msg)
        self.retry_after = retry_after
        self.needed = needed
        self.capacity = capacity
        bump("wire_ring_full")


@dataclasses.dataclass
class WireConfig:
    """Knobs for one host's shm wire (TUNING.md "Zero-copy wire").

    ring_bytes: capacity of EACH payload ring (request + reply).
    max_payload_frac: payloads larger than this fraction of the ring
        ride the pickle wire instead (a single huge RHS must not be
        able to wedge the ring).
    batch_window_s: deliberate control-frame coalescing window on top
        of the opportunistic batching (0 = opportunistic only — zero
        added latency when idle).
    reply_wait_s: how long the worker's reply pump may wait for ring
        space before falling back to an inline (pickle) value — bounds
        the reply path, never a hang.
    max_frame_items: cap on descriptors per control frame. A burst
        bigger than this is sliced into consecutive frames so the
        worker starts draining the FIRST slice while the front is
        still staging the rest — unbounded frames collapse the
        pipeline into lockstep phases (stage-all, serve-all,
        decode-all).
    """

    ring_bytes: int = 8 << 20
    max_payload_frac: float = 0.25
    batch_window_s: float = 0.0
    reply_wait_s: float = 0.25
    max_frame_items: int = 64

    def __post_init__(self):
        if self.ring_bytes < 4096:
            raise ValueError("ring_bytes must be >= 4096")
        if not (0.0 < self.max_payload_frac <= 1.0):
            raise ValueError("max_payload_frac must be in (0, 1]")
        if self.batch_window_s < 0 or self.reply_wait_s < 0:
            raise ValueError("windows must be >= 0")
        if self.max_frame_items < 1:
            raise ValueError("max_frame_items must be >= 1")

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, d: dict) -> "WireConfig":
        return cls(**d)


class Ring:
    """One shared-memory payload ring (single writer, single reader).

    NOT internally locked: the owning endpoint serializes access (the
    WireClient guards its request ring with its own lock; the reply
    ring's writer and reader each live on exactly one thread).
    `reclaim='local'` keeps the free list on the writer (out-of-order
    frees — the request ring); `reclaim='shared'` trusts the segment's
    shared read cursor, advanced by the reader via :meth:`release`
    (FIFO — the reply ring)."""

    def __init__(self, shm: shared_memory.SharedMemory, capacity: int,
                 *, created: bool, reclaim: str = "local"):
        self._shm = shm
        # alias, not a new export: shm.buf returns its one stored
        # memoryview, and the hot paths touch it several times per
        # record — skip the property walk
        self._buf = shm.buf
        self.name = shm.name
        self.capacity = capacity
        self._created = created
        self._reclaim = reclaim
        self._closed = False
        self._unlinked = False
        # writer state (meaningful on the writing side only)
        self._w = 0                      # monotonic byte cursor
        self._gen = 0
        self._free_floor = 0             # all records before this freed
        self._inflight: deque = deque()  # [start, span, freed]
        self._by_start: dict[int, list] = {}
        # reader state (reply ring): last released cursor, monotonic
        self._released = 0

    # -- lifecycle ------------------------------------------------------ #

    @classmethod
    def create(cls, name: str, capacity: int,
               reclaim: str = "local") -> "Ring":
        shm = shared_memory.SharedMemory(
            name=name, create=True, size=_CTRL + capacity)
        _CTRL_FMT.pack_into(shm.buf, 0, _MAGIC, _VERSION, capacity, 0, 0)
        return cls(shm, capacity, created=True, reclaim=reclaim)

    @classmethod
    def attach(cls, name: str, reclaim: str = "local") -> "Ring":
        shm = shared_memory.SharedMemory(name=name, create=False)
        # Python <= 3.12 registers ATTACHED segments with this
        # process's resource tracker, whose exit-time cleanup would
        # unlink a segment the creator still owns — unregister; the
        # creating side keeps its registration as the leak backstop.
        try:
            from multiprocessing import resource_tracker

            resource_tracker.unregister(shm._name, "shared_memory")
        except Exception:  # noqa: BLE001 — tracker layout varies
            pass
        magic, ver, cap, _r, _w = _CTRL_FMT.unpack_from(shm.buf, 0)
        if magic != _MAGIC or ver != _VERSION:
            shm.close()
            raise WireCorrupt(
                f"segment {name} is not a conflux wire ring "
                f"(magic {magic:#x} ver {ver})", kind="overrun")
        return cls(shm, cap, created=False, reclaim=reclaim)

    def close(self, unlink: bool | None = None) -> None:
        """Detach (and, for the creator by default, unlink) the
        segment. Never raises — teardown runs on corpse-cleanup
        paths. Detach and unlink are tracked separately so a shared
        Ring (the loopback harness) unlinks even when a detach-only
        close landed first."""
        if unlink is None:
            unlink = self._created
        if not self._closed:
            self._closed = True
            try:
                self._shm.close()
            except BufferError:
                # a payload view is still exported (a solve racing
                # shutdown); the fd stays open until the view dies,
                # but the NAME must go away now
                pass
            except OSError:
                pass
        if unlink and not self._unlinked:
            self._unlinked = True
            try:
                self._shm.unlink()
            except (FileNotFoundError, OSError):
                pass

    # -- shared cursors -------------------------------------------------- #

    def _shared_r(self) -> int:
        return _U64.unpack_from(self._buf, _CTRL_R_OFF)[0]

    def _set_shared_r(self, v: int) -> None:
        _U64.pack_into(self._buf, _CTRL_R_OFF, v)

    def _shared_w(self) -> int:
        return _U64.unpack_from(self._buf, _CTRL_W_OFF)[0]

    def _set_shared_w(self, v: int) -> None:
        _U64.pack_into(self._buf, _CTRL_W_OFF, v)

    def used_bytes(self) -> int:
        """In-flight bytes (either side — reads the shared mirrors)."""
        try:
            return max(0, self._shared_w() - self._shared_r())
        except (ValueError, OSError):
            return 0

    # -- writer side ----------------------------------------------------- #

    def _floor(self) -> int:
        return (self._free_floor if self._reclaim == "local"
                else self._shared_r())

    def stage(self, arr: np.ndarray) -> dict:
        """Allocate a record and copy `arr` into it (the ONE host-side
        copy of the send path). Returns the descriptor the control
        frame ships: offset/size/generation/cursor/span/dtype/shape.
        Raises :class:`RingFull` (retry_after=0 — the owning endpoint
        enriches it with the measured drain hint)."""
        arr = np.ascontiguousarray(arr)
        size = int(arr.nbytes)
        rec = _HDR.size + _round_up(size + _FTR.size, _ALIGN)
        cap = self.capacity
        pos = self._w % cap
        skip = cap - pos if pos + rec > cap else 0
        span = skip + rec
        if span > cap - (self._w - self._floor()):
            raise RingFull(
                f"ring {self.name} full: need {span} bytes, "
                f"{cap - (self._w - self._floor())} free of {cap}",
                needed=span, capacity=cap)
        off = 0 if skip else pos
        base = _CTRL + off
        self._gen = gen = (self._gen % 0xFFFFFFFF) + 1
        buf = self._buf
        _HDR.pack_into(buf, base, _MAGIC, gen, size, span)
        if size:
            dst = np.ndarray(arr.shape, arr.dtype, buffer=buf,
                             offset=base + _HDR.size)
            np.copyto(dst, arr)
            del dst
        _FTR.pack_into(buf, base + _HDR.size + size,
                       gen, gen ^ 0xFFFFFFFF)
        start = self._w
        self._w = start + span
        self._set_shared_w(self._w)
        if self._reclaim == "local":
            ent = [start, span, False]
            self._inflight.append(ent)
            self._by_start[start] = ent
        return {"o": off, "n": size, "g": gen, "c": start, "p": span,
                "t": arr.dtype.str, "s": tuple(arr.shape)}

    def free(self, desc: dict) -> int:
        """Reclaim a staged record (local mode; out-of-order safe —
        the floor advances over the contiguous freed prefix). Returns
        the bytes actually reclaimed by this call."""
        ent = self._by_start.pop(desc["c"], None)
        if ent is None:
            return 0
        ent[2] = True
        freed = 0
        while self._inflight and self._inflight[0][2]:
            start, span, _ = self._inflight.popleft()
            self._free_floor = start + span
            freed += span
        if freed:
            self._set_shared_r(self._free_floor)
        return freed

    # -- reader side ----------------------------------------------------- #

    def read(self, desc: dict, *, copy: bool,
             fault_plan=None, host: str | None = None) -> np.ndarray:
        """Map a descriptor back to its payload, VALIDATED: magic,
        descriptor-vs-header generation (stale slot), footer
        generation (torn write), bounds (overrun). `copy=False`
        returns a live view into the segment — the caller must hold
        the record allocated until done with it."""
        off, size, gen = desc["o"], desc["n"], desc["g"]
        if (off < 0 or size < 0
                or off + _HDR.size + size + _FTR.size > self.capacity):
            raise WireCorrupt(
                f"descriptor names bytes outside ring {self.name} "
                f"(off={off} size={size} cap={self.capacity})",
                kind="overrun", host=host)
        base = _CTRL + off
        buf = self._buf
        magic, hgen, hsize, _span = _HDR.unpack_from(buf, base)
        if _fire(fault_plan, "stale_generation"):
            hgen = gen + 1  # injected: descriptor outlived its slot
        if magic != _MAGIC or hgen != gen or hsize != size:
            raise WireCorrupt(
                f"stale record in ring {self.name}: descriptor "
                f"gen={gen} size={size}, header gen={hgen} "
                f"size={hsize} (slot recycled under a live "
                "descriptor)", kind="stale_generation", host=host)
        fgen, finv = _FTR.unpack_from(buf, base + _HDR.size + size)
        if _fire(fault_plan, "torn_segment"):
            fgen = 0  # injected: writer died before the footer landed
        if fgen != gen or finv != gen ^ 0xFFFFFFFF:
            raise WireCorrupt(
                f"torn record in ring {self.name}: footer gen={fgen} "
                f"!= {gen} — the writer died mid-copy",
                kind="torn_segment", host=host)
        view = np.ndarray(desc["s"], np.dtype(desc["t"]), buffer=buf,
                          offset=base + _HDR.size)
        return view.copy() if copy else view

    def release(self, desc: dict) -> None:
        """Reader-side acknowledge (shared mode): advance the shared
        read cursor past this record. Replies are decoded in frame
        order, so the cursor is monotonic by construction."""
        end = desc["c"] + desc["p"]
        if end > self._released:
            self._released = end
            self._set_shared_r(end)


# --------------------------------------------------------------------------- #
# endpoints
# --------------------------------------------------------------------------- #


class WireClient:
    """The front half of one host's shm wire.

    Owns the request ring (stage on submit, free when the reply
    lands), decodes reply frames against the reply ring, and runs the
    send pump that batches descriptors into ``solve_many`` control
    frames. Future bookkeeping stays with the owner (ProcessHost's
    pending map / InProcWire) — the client only moves bytes and
    descriptors."""

    def __init__(self, req: Ring, rep: Ring,
                 send: Callable[[dict], None], *,
                 host_id: str = "?",
                 config: WireConfig | None = None,
                 fault_plan=None,
                 on_send_error: Callable[[list, Exception], None]
                 | None = None):
        self.host_id = host_id
        self.config = config if config is not None else WireConfig()
        self._req = req
        self._rep = rep
        self._send = send
        self._faults = fault_plan
        self._on_send_error = on_send_error
        self._lock = threading.Lock()
        self._have = threading.Condition(self._lock)
        self._outbox: list[dict] = []        # guarded-by: _lock
        self._by_mid: dict[int, dict] = {}   # guarded-by: _lock
        self._dead: Exception | None = None  # guarded-by: _lock
        # measured drain: bytes freed per second, EMA (retry hints)
        self._drain_ema = 0.0                # guarded-by: _lock
        self._drain_t0 = time.perf_counter()  # guarded-by: _lock
        self._drain_bytes = 0                # guarded-by: _lock
        self.staged = 0                      # guarded-by: _lock
        self.frames = 0                      # guarded-by: _lock
        self.replies = 0                     # guarded-by: _lock
        self._pump = threading.Thread(
            target=self._send_loop, daemon=True,
            name=f"wire-send-{host_id}")
        self._pump.start()

    # -- submit path ----------------------------------------------------- #

    def payload_fits(self, nbytes: int) -> bool:
        return nbytes <= self.config.max_payload_frac * self._req.capacity

    def submit(self, mid: int, sid, arr: np.ndarray, qos=None,
               op: str = "solve") -> None:
        """Stage one request payload and enqueue its descriptor for
        the next control frame. Raises :class:`RingFull` (with a
        measured-drain retry hint) or ConnectionError (wire dead)."""
        with self._lock:
            if self._dead is not None:
                raise ConnectionError(
                    f"wire to host {self.host_id} is dead: "
                    f"{self._dead}")
            if _fire(self._faults, "ring_full"):
                raise RingFull(
                    f"ring {self._req.name} full (injected)",
                    retry_after=self._retry_hint_locked(1),
                    needed=int(arr.nbytes), capacity=self._req.capacity)
            try:
                desc = self._req.stage(arr)
            except RingFull as e:
                e.retry_after = self._retry_hint_locked(e.needed)
                raise
            self._by_mid[mid] = desc
            item = {"id": mid, "sid": sid, "d": desc}
            if qos is not None:
                item["q"] = qos
            if op != "solve":
                item["op"] = op
            self._outbox.append(item)
            self.staged += 1
            self._have.notify()

    def submit_many(self, entries: list) -> int:
        """Stage a BURST of requests under one lock acquisition —
        `entries` is [(mid, sid, arr, qos, op)]. Returns how many of
        the leading entries were staged; a short count means the ring
        filled mid-burst and the caller resubmits the tail after the
        drain hint. Raises :class:`RingFull` only when NOTHING could
        be staged (enriched with the measured-drain retry hint) and
        ConnectionError when the wire is dead. This is the front half
        of the batched control plane: N payloads, one lock, one pump
        wakeup, (opportunistically) one ``solve_many`` frame."""
        staged = 0
        with self._lock:
            if self._dead is not None:
                raise ConnectionError(
                    f"wire to host {self.host_id} is dead: "
                    f"{self._dead}")
            if _fire(self._faults, "ring_full"):
                raise RingFull(
                    f"ring {self._req.name} full (injected)",
                    retry_after=self._retry_hint_locked(1),
                    needed=int(entries[0][2].nbytes),
                    capacity=self._req.capacity)
            for mid, sid, arr, qos, op in entries:
                try:
                    desc = self._req.stage(arr)
                except RingFull as e:
                    if staged == 0:
                        e.retry_after = self._retry_hint_locked(
                            e.needed)
                        raise
                    break
                self._by_mid[mid] = desc
                item = {"id": mid, "sid": sid, "d": desc}
                if qos is not None:
                    item["q"] = qos
                if op != "solve":
                    item["op"] = op
                self._outbox.append(item)
                staged += 1
            self.staged += staged
            if staged:
                self._have.notify()
        return staged

    # requires-lock: _lock
    def _retry_hint_locked(self, needed: int) -> float:
        rate = self._drain_ema
        if rate <= 0.0:
            return 0.01
        return min(1.0, max(1e-4, needed / rate))

    # requires-lock: _lock
    def _note_drain_locked(self, nbytes: int) -> None:
        self._drain_bytes += nbytes
        now = time.perf_counter()
        dt = now - self._drain_t0
        if dt >= 0.05:
            rate = self._drain_bytes / dt
            self._drain_ema = (rate if self._drain_ema == 0.0
                               else 0.3 * rate + 0.7 * self._drain_ema)
            self._drain_t0 = now
            self._drain_bytes = 0

    def _send_loop(self) -> None:
        window = self.config.batch_window_s
        cap_items = self.config.max_frame_items
        while True:
            with self._lock:
                while not self._outbox and self._dead is None:
                    self._have.wait()
                if self._dead is not None:
                    return
                items = self._outbox[:cap_items]
                del self._outbox[:cap_items]
            if window > 0.0 and len(items) < cap_items:
                # deliberate coalescing on top of the opportunistic
                # batching: widen this frame's window
                time.sleep(window)
                with self._lock:
                    take = cap_items - len(items)
                    items.extend(self._outbox[:take])
                    del self._outbox[:take]
            try:
                self._send({"op": "solve_many", "items": items})
                with self._lock:
                    self.frames += 1
            except (OSError, ValueError) as e:
                if self._on_send_error is not None:
                    self._on_send_error(items, e)
                self.fail(ConnectionError(
                    f"wire send to host {self.host_id} failed: {e!r}"))
                return

    # -- reply path (recv thread only) ------------------------------------ #

    def decode(self, items: list) -> list[tuple[int, dict]]:
        """Decode one ``reply_many`` frame into [(mid, reply-dict)]
        pairs shaped exactly like the pickle wire's replies. Frees the
        matching request records and releases the reply records.
        Raises :class:`WireCorrupt` on a torn/stale/overrun reply —
        the owner must then declare the host structurally dead."""
        out: list[tuple[int, dict]] = []
        with self._lock:
            # one lock for the whole frame: pop + free every matching
            # request record, then read the reply payloads unlocked
            # (the reply ring's reader side is this thread only)
            for it in items:
                req_desc = self._by_mid.pop(it["id"], None)
                if req_desc is not None:
                    self._note_drain_locked(self._req.free(req_desc))
            self.replies += len(items)
        for it in items:
            mid = it["id"]
            d = it.get("d")
            if d is None:
                if it.get("ok"):
                    # inline (pickle-fallback) value: same reply shape
                    # as a ring-borne one
                    out.append((mid, {"id": mid, "ok": True,
                                      "value": it.get("v")}))
                else:
                    out.append((mid, it))  # structured error frame
                continue
            try:
                arr = self._rep.read(d, copy=True,
                                     fault_plan=self._faults,
                                     host=self.host_id)
            except WireCorrupt:
                raise
            finally:
                # even a torn record's span must not wedge the cursor
                self._rep.release(d)
            out.append((mid, {"id": mid, "ok": True, "value": arr}))
        return out

    # NOTE: there is deliberately no per-mid "forget" — an abandoned
    # (timed-out) request's ring record is reclaimed by its LATE reply
    # (decode frees unconditionally), so forgetting the mid early
    # would leak the record until the wire dies.

    # -- lifecycle / telemetry -------------------------------------------- #

    def fail(self, exc: Exception) -> None:
        with self._lock:
            if self._dead is None:
                self._dead = exc
            self._outbox = []
            self._by_mid.clear()
            self._have.notify_all()

    def close(self) -> None:
        self.fail(ConnectionError(
            f"wire to host {self.host_id} closed"))
        self._pump.join(timeout=5.0)
        self._req.close()
        self._rep.close()

    def stats(self) -> dict:
        with self._lock:
            staged, frames, replies = self.staged, self.frames, \
                self.replies
            drain = self._drain_ema
        return {"req_used": self._req.used_bytes(),
                "req_cap": self._req.capacity,
                "rep_used": self._rep.used_bytes(),
                "rep_cap": self._rep.capacity,
                "staged": staged, "frames": frames,
                "replies": replies,
                "drain_bytes_per_s": round(drain, 1)}


class WireServer:
    """The worker half: maps request descriptors to payload views,
    feeds them to a caller-supplied batched submit, and runs the reply
    pump that stages results into the reply ring (bounded wait, inline
    pickle fallback — progress is never gated on ring space) and
    batches reply descriptors into ``reply_many`` frames."""

    def __init__(self, req: Ring, rep: Ring,
                 send: Callable[[dict], None], *,
                 host_id: str = "?",
                 config: WireConfig | None = None,
                 encode_exc: Callable[[BaseException], dict]
                 | None = None,
                 fault_plan=None):
        self.host_id = host_id
        self.config = config if config is not None else WireConfig()
        self._req = req
        self._rep = rep
        self._send = send
        self._faults = fault_plan
        self._encode_exc = encode_exc or (lambda e: {
            "ok": False, "etype": type(e).__name__, "emsg": str(e),
            "extra": {}})
        self._lock = threading.Lock()
        self._have = threading.Condition(self._lock)
        # the reply ring has TWO staging threads (the recv thread's
        # inline echo path and the reply pump) — writer-side cursor
        # state is serialized here, never held across a drain wait
        self._rep_lock = threading.Lock()
        self._outbox: list[tuple[int, Any, BaseException | None]] = []  # guarded-by: _lock
        self._stop = False                   # guarded-by: _lock
        self.fallbacks = 0                   # guarded-by: _lock
        self._pump = threading.Thread(
            target=self._reply_loop, daemon=True,
            name=f"wire-reply-{host_id}")
        self._pump.start()

    # -- request path (recv thread) --------------------------------------- #

    def handle(self, msg: dict,
               submit_many: Callable[[list], list[Future]]) -> None:
        """One ``solve_many`` frame. `submit_many` takes
        [(sid, b_view, qos_dict)] and returns aligned futures (per-item
        failures set ON the futures). Views stay valid until the reply
        is staged — the front frees a request record only when its
        reply lands, and replies are staged only after completion."""
        batch: list[tuple[int, Any, Any, Any]] = []
        inline: list[dict] = []
        for it in msg["items"]:
            mid = it["id"]
            try:
                view = self._req.read(it["d"], copy=False,
                                      fault_plan=self._faults,
                                      host=self.host_id)
            # conflint: disable=CFX-EXCEPT wire op boundary: a corrupt request record fails ITS item structurally
            except BaseException as e:
                inline.append({"id": mid, **self._encode_exc(e)})
                continue
            if it.get("op") == "echo":
                # the wire microbench: payload straight back out,
                # engine bypassed — isolates the RPC layer. The reply
                # is staged and framed INLINE (this thread): an echo
                # is complete the moment it is read, so routing it
                # through the reply pump would buy a thread hop and a
                # per-item lock for nothing.
                inline.append(self._encode_reply(mid, view))
                continue
            batch.append((mid, it["sid"], view, it.get("q")))
        if inline:
            try:
                self._send({"op": "reply_many", "items": inline})
            except (OSError, ValueError):
                return  # front is gone; the recv loop sees the EOF
        if not batch:
            return
        futs = submit_many([(sid, b, q) for _, sid, b, q in batch])
        for (mid, _sid, _b, _q), fut in zip(batch, futs):
            fut.add_done_callback(
                lambda f, mid=mid: self._done(mid, f))

    def _done(self, mid: int, fut: Future) -> None:
        try:
            val = fut.result()
        # conflint: disable=CFX-EXCEPT wire op boundary: every failure (kills included) is wired back to the front
        except BaseException as e:
            self.reply(mid, exc=e)
        else:
            self.reply(mid, value=val)

    def reply(self, mid: int, value: Any = None,
              exc: BaseException | None = None) -> None:
        with self._lock:
            if self._stop:
                return
            self._outbox.append((mid, value, exc))
            self._have.notify()

    def debug_corrupt(self, mode: str = "torn_reply",
                      mid: int = -999) -> None:
        """Drill hook (scripts/fabric_drill.py, tests): emit one reply
        whose ring record is deliberately corrupted — 'torn_reply'
        zeroes the footer (a writer killed mid-copy), 'stale_reply'
        bumps the header generation past the descriptor's (a recycled
        slot). The front's decode must raise WireCorrupt and declare
        the host structurally dead. Assumes a quiescent wire (the
        reply ring's writer cursor is pump-owned in production)."""
        arr = np.zeros(64, np.float32)
        desc = self._rep.stage(arr)
        base = _CTRL + desc["o"]
        buf = self._rep._shm.buf
        if mode == "stale_reply":
            _HDR.pack_into(buf, base, _MAGIC, desc["g"] + 1,
                           desc["n"], desc["p"])
        else:
            _FTR.pack_into(buf, base + _HDR.size + desc["n"], 0, 0)
        self._send({"op": "reply_many",
                    "items": [{"id": mid, "ok": True, "d": desc}]})

    def debug_partial_write(self) -> None:
        """Drill hook: leave the reply ring exactly as a SIGKILL
        mid-copy would — a header landed at the write head, the
        payload and footer never did (the caller dies right after)."""
        rep = self._rep
        base = _CTRL + rep._w % rep.capacity
        rep._gen += 1
        _HDR.pack_into(rep._shm.buf, base, _MAGIC, rep._gen,
                       1 << 20, 0)

    # -- reply pump -------------------------------------------------------- #

    def _stage_reply(self, arr: np.ndarray) -> dict | None:
        """Reply-ring allocation with a BOUNDED wait for the front to
        drain; None = fall back to an inline value."""
        deadline = time.perf_counter() + self.config.reply_wait_s
        while True:
            try:
                with self._rep_lock:
                    return self._rep.stage(arr)
            except RingFull:
                if time.perf_counter() >= deadline:
                    return None
                time.sleep(0.001)

    def _encode_reply(self, mid: int, val: Any) -> dict:
        """One successful reply → its frame item: ring-staged
        descriptor when the payload fits (bounded wait for drain),
        inline pickled value otherwise — progress is never gated on
        ring space."""
        arr = val if isinstance(val, np.ndarray) else None
        if (arr is not None and arr.dtype != object
                and arr.nbytes <= self.config.max_payload_frac
                * self._rep.capacity):
            desc = self._stage_reply(arr)
            if desc is not None:
                return {"id": mid, "ok": True, "d": desc}
            with self._lock:
                self.fallbacks += 1
            bump("wire_pickle_fallbacks")
        return {"id": mid, "ok": True, "v": val}

    def _reply_loop(self) -> None:
        while True:
            with self._lock:
                while not self._outbox and not self._stop:
                    self._have.wait()
                if self._stop and not self._outbox:
                    return
                pending = self._outbox
                self._outbox = []
            items = []
            for mid, val, exc in pending:
                if exc is not None:
                    items.append({"id": mid,
                                  **self._encode_exc(exc)})
                else:
                    items.append(self._encode_reply(mid, val))
            try:
                self._send({"op": "reply_many", "items": items})
            except (OSError, ValueError):
                return  # front is gone; the recv loop sees the EOF

    # -- lifecycle / telemetry --------------------------------------------- #

    def close(self) -> None:
        with self._lock:
            self._stop = True
            self._have.notify_all()
        self._pump.join(timeout=5.0)
        # the worker only ATTACHED: detach, never unlink — the front
        # owns the segment names (and unlinks them even if this
        # process is SIGKILLed before getting here)
        self._req.close(unlink=False)
        self._rep.close(unlink=False)

    def stats(self) -> dict:
        with self._lock:
            fallbacks = self.fallbacks
        return {"rep_used": self._rep.used_bytes(),
                "rep_cap": self._rep.capacity,
                "fallbacks": fallbacks}


# --------------------------------------------------------------------------- #
# segment naming + in-process loopback (tests, soak)
# --------------------------------------------------------------------------- #


def segment_names(host_id: str) -> tuple[str, str]:
    """(request, reply) segment names for one host — unique per
    start(), filesystem-visible under /dev/shm for leak audits. The
    host-id slice is capped at 10 chars so the full name stays <= 27:
    macOS limits POSIX shm names to 31 bytes (PSHMNAMLEN) including
    the leading '/' the stdlib prepends, and a longer host id must
    not make Ring.create fail there — the random token, not the id,
    carries uniqueness."""
    tok = secrets.token_hex(4)
    safe = "".join(c if c.isalnum() or c in "-_" else "_"
                   for c in str(host_id))[:10]
    return (f"cfxw-{safe}-{tok}-rq", f"cfxw-{safe}-{tok}-rp")


class InProcWire:
    """A single-process loopback of the whole wire — REAL shared
    segments, real generation/backpressure protocol, control frames
    crossing on in-process queues. `submit_many([(sid, b, qos)])`
    is supplied by the caller (an engine hook, or an echo). Used by
    the wire unit tests and the `scripts/soak.py --fabric` wire
    hammer; the ProcessHost path wires the same two endpoint classes
    across the pipe instead."""

    def __init__(self, submit_many: Callable[[list], list[Future]], *,
                 config: WireConfig | None = None,
                 fault_plan=None, host_id: str = "loop"):
        cfg = config if config is not None else WireConfig()
        rq_name, rp_name = segment_names(host_id)
        self._req = Ring.create(rq_name, cfg.ring_bytes,
                                reclaim="local")
        self._rep = Ring.create(rp_name, cfg.ring_bytes,
                                reclaim="shared")
        self._submit_many = submit_many
        self._lock = threading.Lock()
        self._pending: dict[int, Future] = {}  # guarded-by: _lock
        self._next = 0                         # guarded-by: _lock
        self._dead: Exception | None = None    # guarded-by: _lock
        self.server = WireServer(self._req, self._rep,
                                 self._on_reply_frame, host_id=host_id,
                                 config=cfg)
        self.client = WireClient(self._req, self._rep,
                                 self._on_request_frame,
                                 host_id=host_id, config=cfg,
                                 fault_plan=fault_plan,
                                 on_send_error=self._on_send_error)

    # frames "cross the pipe": request frames run the server handler
    # on the client pump thread, reply frames decode on the server's
    # reply pump thread — same thread topology as the process wire
    def _on_request_frame(self, msg: dict) -> None:
        self.server.handle(msg, self._submit_many)

    def _on_reply_frame(self, msg: dict) -> None:
        try:
            pairs = self.client.decode(msg["items"])
        except WireCorrupt as e:
            self.fail(e)
            return
        for mid, reply in pairs:
            with self._lock:
                fut = self._pending.pop(mid, None)
            if fut is None:
                continue
            if reply.get("ok"):
                fut.set_result(reply.get("value", reply.get("v")))
            else:
                fut.set_exception(RuntimeError(
                    f"remote {reply.get('etype')}: "
                    f"{reply.get('emsg')}"))

    def _on_send_error(self, items: list, exc: Exception) -> None:
        self.fail(ConnectionError(f"loopback send failed: {exc!r}"))

    def solve(self, sid, b, qos=None, op: str = "solve") -> Future:
        fut: Future = Future()
        with self._lock:
            if self._dead is not None:
                raise ConnectionError(f"wire dead: {self._dead}")
            mid = self._next
            self._next += 1
            self._pending[mid] = fut
        try:
            self.client.submit(mid, sid, np.asarray(b), qos=qos, op=op)
        except BaseException:
            with self._lock:
                self._pending.pop(mid, None)
            raise
        return fut

    def fail(self, exc: Exception) -> None:
        """Instant structural death: every pending future fails NOW —
        the never-hang contract of the process wire, in-process."""
        with self._lock:
            if self._dead is None:
                self._dead = exc
            stranded = list(self._pending.values())
            self._pending.clear()
        self.client.fail(exc)
        for fut in stranded:
            if not fut.done():
                fut.set_exception(exc)

    def stats(self) -> dict:
        out = self.client.stats()
        out.update(self.server.stats())
        return out

    def close(self) -> None:
        self.fail(ConnectionError("wire closed"))
        self.server.close()
        self.client.close()
