"""Device mesh and collectives — the communication backend.

TPU-native replacement for the reference's MPI-3 layer (SURVEY.md §2.4 C1):
the 3D Cartesian communicator and its five sub-communicators become named
axes of one `jax.sharding.Mesh`, and every MPI exchange becomes an XLA
collective over a subset of axis names, riding ICI within a slice and DCN
across slices.
"""

from conflux_tpu.parallel.mesh import (
    AXIS_X,
    AXIS_Y,
    AXIS_Z,
    make_mesh,
    comm,
)

__all__ = ["AXIS_X", "AXIS_Y", "AXIS_Z", "make_mesh", "comm"]
