"""The 3D device mesh and the six communicator patterns.

The reference builds one 3D Cartesian communicator plus five sub-communicators
(`lu_params.hpp:84-108`): lu (xyz), jk (yz), ik (xz), ij (xy), k (z), i (x).
On TPU these are not objects — they are *names*: collectives take mesh axis
names, and a "sub-communicator" is just a subset of axes. `comm` maps the
reference's communicator vocabulary onto axis-name tuples so algorithm code
can speak in the same terms the reference does.

| reference comm | axes      | used for                                        |
|----------------|-----------|-------------------------------------------------|
| `lu_comm`      | x, y, z   | whole-grid ops                                  |
| `jk_comm`      | y, z      | panel broadcast / A10 slab scatter              |
| `ik_comm`      | x, z      | pivot-row reduce + distribute / A01 slab scatter|
| `ij_comm`      | x, y      | validation-layout assembly                      |
| `k_comm`       | z         | 2.5D partial-sum reduction                      |
| `i_comm`       | x         | tournament pivoting butterfly                   |
"""

from __future__ import annotations

import numpy as np

import jax

from conflux_tpu.geometry import Grid3

AXIS_X = "x"  # row dimension of the tile grid (Px)
AXIS_Y = "y"  # column dimension of the tile grid (Py)
AXIS_Z = "z"  # 2.5D replication depth (Pz)

# jax-version shim: `jax.shard_map` graduated from
# `jax.experimental.shard_map.shard_map` only in newer jax releases; this
# environment ships 0.4.37 where only the experimental spelling exists.
# Every shard_map program in the package routes through this name.
try:
    shard_map = jax.shard_map
except AttributeError:  # jax <= 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map_exp

    def shard_map(f, *, mesh, in_specs, out_specs, **kwargs):
        # check_rep=False: the legacy replication tracker cannot follow
        # fori_loop carries that start replicated and turn varying inside
        # the body (jax's own error message recommends exactly this
        # workaround); the algorithms re-establish replication explicitly
        # via `replicate` before any out_spec that claims it, so the
        # check adds nothing here.
        kwargs.setdefault("check_rep", False)
        return _shard_map_exp(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, **kwargs)


def pvary(val, axes):
    """Mark a literal as varying over mesh `axes` — `lax.pcast`'s
    "varying manual axes" vocabulary, needed so `lax.cond` branch output
    types match mask-dependent compute branches on new jax. Old jax
    (<= 0.4.x, the experimental shard_map) has no pcast: its check_rep
    machinery inserts the equivalent rewrites itself, so this is an
    identity there."""
    from jax import lax

    if hasattr(lax, "pcast"):
        for ax in axes:
            val = lax.pcast(val, ax, to="varying")
    return val

comm = {
    "lu": (AXIS_X, AXIS_Y, AXIS_Z),
    "jk": (AXIS_Y, AXIS_Z),
    "ik": (AXIS_X, AXIS_Z),
    "ij": (AXIS_X, AXIS_Y),
    "k": (AXIS_Z,),
    "i": (AXIS_X,),
}


_MESH_REGISTRY: dict = {}


def mesh_cache_key(mesh: jax.sharding.Mesh):
    """Hashable identity for a mesh, and register it for `lookup_mesh`.

    Compiled program builders are lru_cached on geometry + this key; keying
    by (device ids, axis names) means two equivalent Mesh objects share one
    compiled program, and the registry holds one canonical mesh per key
    (bounded by the number of distinct device layouts, so no growth over
    repeated calls).
    """
    key = (tuple(d.id for d in mesh.devices.flat), mesh.axis_names)
    _MESH_REGISTRY[key] = mesh
    return key


def lookup_mesh(key) -> jax.sharding.Mesh:
    return _MESH_REGISTRY[key]


def initialize_multihost(coordinator_address: str | None = None,
                         num_processes: int | None = None,
                         process_id: int | None = None, **kwargs) -> None:
    """Bring up the multi-host runtime — the role of `MPI_Init`
    (`examples/conflux_miniapp.cpp:90`) for TPU pods.

    Call once per host process before any mesh/array work;
    `jax.distributed.initialize` discovers the coordinator automatically on
    Cloud TPU (all arguments optional there). After this, `jax.devices()`
    spans every host's chips and `make_mesh` builds pod-wide meshes; the
    collectives in the factorization loops ride ICI within a slice and DCN
    across slices without code changes.
    """
    jax.distributed.initialize(coordinator_address, num_processes,
                               process_id, **kwargs)


def distribute_shards(shards, mesh: jax.sharding.Mesh, *,
                      shape: tuple | None = None, dtype=None,
                      spec=None) -> jax.Array:
    """Build the (Px, Py, Ml, Nl) device-sharded global array from host data.

    Two forms:

    - `shards` is the full (Px, Py, Ml, Nl) host array: single-host
      convenience, equivalent to a device_put with the block-cyclic sharding
      (every process must hold the whole thing — fine on one host).
    - `shards` is a callable `(px, py) -> (Ml, Nl) ndarray` and
      `shape`/`dtype` give the global spec: it is invoked only for the
      shards owned by THIS process's addressable devices, so on a multi-host
      pod no host ever materializes the global matrix — the role of the
      reference's per-rank `InitMatrix` fill (`lu_params.hpp:141-376`).

    `spec` overrides the default block-cyclic (x, y, None, None)
    partitioning — e.g. PartitionSpec('x', None, None) for the QR
    family's (Px, Ml, n) row-block shards; the callable then takes one
    coordinate per sharded dimension.
    """
    from jax.sharding import PartitionSpec

    if spec is None:
        spec = PartitionSpec(AXIS_X, AXIS_Y, None, None)
    sharding = jax.sharding.NamedSharding(mesh, spec)
    # dims carrying a mesh axis must be leading index dims (size == axis
    # extent), so a shard's slice start IS its mesh coordinate — true for
    # both supported layouts: block-cyclic (Px, Py, Ml, Nl) and the QR
    # family's row-block (Px, Ml, n)
    sharded_dims = [i for i, ax in enumerate(spec) if ax is not None]
    if callable(shards):
        if shape is None or dtype is None:
            raise ValueError("callable form requires shape= and dtype=")

        def cb(idx):
            coords = tuple(idx[i].start or 0 for i in sharded_dims)
            blk = np.asarray(shards(*coords), dtype=dtype)
            return blk[(None,) * len(sharded_dims)]

        return jax.make_array_from_callback(tuple(shape), sharding, cb)
    shards = np.asarray(shards)
    return jax.make_array_from_callback(
        shards.shape, sharding, lambda idx: shards[idx]
    )


def replicate(val, axes):
    """Re-establish replication over mesh axes for an already-identical
    value (the out_spec replication proof, see the LU loop's perm
    output). pmax is the cheapest identity-preserving collective but has
    no complex reduction on any backend, so complex values ride as their
    real/imag parts."""
    import jax.numpy as jnp
    from jax import lax

    if jnp.issubdtype(val.dtype, jnp.complexfloating):
        return lax.complex(lax.pmax(val.real, axes),
                           lax.pmax(val.imag, axes)).astype(val.dtype)
    return lax.pmax(val, axes)


def butterfly_allreduce(vals: tuple, Px: int, axis: str, reduce_pair):
    """Hypercube all-reduce over a mesh axis (the reference's tournament
    butterfly shape, `conflux_opt.hpp:220-336`): each round, partners
    exchange `vals` via ppermute and `reduce_pair(top, bot)` combines
    the two tuples into the next `vals`.

    The correctness-critical invariant lives here ONCE: the pair is
    ordered by the LOWER coordinate, so both partners reduce the
    bit-identical inputs and the result converges replicated across the
    axis without a broadcast (tie-stable for order-dependent reducers
    like the CALU tournament).

    Non-power-of-two Px is handled the way the reference patches odd
    grids with compensating sends (`conflux_opt.hpp:266-280`, partner
    math `conflux_opt.cpp:59-72`), recast for SPMD: with p the largest
    power of two <= Px and r = Px - p, a pre-round folds each overflow
    rank p+i into rank i (i < r), the log2(p) butterfly runs over the
    [0, p) subcube, and a post-round sends the replicated result back to
    the overflow ranks — 2 extra ppermute rounds total, still only one
    `vals` payload per rank per round. All ranks execute every round
    (SPMD); the off-subcube reductions operate on ppermute's zero fill
    and are discarded by coordinate selects, so reducers must tolerate
    (not crash on) all-zero inputs — true of the CALU/TSQR reducers,
    whose zero-stack factorizations are well-defined garbage.
    """
    import jax.numpy as jnp
    from jax import lax

    x = lax.axis_index(axis)
    p = 1 << (Px.bit_length() - 1)  # largest power of two <= Px
    r = Px - p
    if r:
        # fold: overflow rank p+i's contribution joins rank i's, ordered
        # by the lower coordinate (rank i's own vals first)
        perm = [(p + i, i) for i in range(r)]
        recv = tuple(lax.ppermute(v, axis, perm) for v in vals)
        folded = tuple(reduce_pair(vals, recv))
        vals = tuple(jnp.where(x < r, f, v)
                     for f, v in zip(folded, vals))
    for rnd in range(p.bit_length() - 1):
        bit = 1 << rnd
        perm = [(i, i ^ bit) for i in range(p)]
        others = tuple(lax.ppermute(v, axis, perm) for v in vals)
        low_first = (x & bit) == 0
        top = tuple(jnp.where(low_first, a, b)
                    for a, b in zip(vals, others))
        bot = tuple(jnp.where(low_first, b, a)
                    for a, b in zip(vals, others))
        vals = tuple(reduce_pair(top, bot))
    if r:
        # unfold: the subcube result is replicated over [0, p); hand the
        # overflow ranks their copy
        perm = [(i, p + i) for i in range(r)]
        recv = tuple(lax.ppermute(v, axis, perm) for v in vals)
        vals = tuple(jnp.where(x >= p, o, v)
                     for o, v in zip(recv, vals))
    return vals


def make_mesh(grid: Grid3, devices=None) -> jax.sharding.Mesh:
    """Build the ('x', 'y', 'z') mesh for a Grid3.

    On real hardware, axis order matters for ICI locality: jax.make_mesh
    chooses a device assignment that keeps the fastest-varying axes on
    physically adjacent chips. For tests, pass an explicit device list.
    """
    if devices is None:
        return jax.make_mesh((grid.Px, grid.Py, grid.Pz), (AXIS_X, AXIS_Y, AXIS_Z))
    devs = np.asarray(devices).reshape(grid.Px, grid.Py, grid.Pz)
    return jax.sharding.Mesh(devs, (AXIS_X, AXIS_Y, AXIS_Z))
