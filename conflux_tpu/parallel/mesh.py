"""The 3D device mesh and the six communicator patterns.

The reference builds one 3D Cartesian communicator plus five sub-communicators
(`lu_params.hpp:84-108`): lu (xyz), jk (yz), ik (xz), ij (xy), k (z), i (x).
On TPU these are not objects — they are *names*: collectives take mesh axis
names, and a "sub-communicator" is just a subset of axes. `comm` maps the
reference's communicator vocabulary onto axis-name tuples so algorithm code
can speak in the same terms the reference does.

| reference comm | axes      | used for                                        |
|----------------|-----------|-------------------------------------------------|
| `lu_comm`      | x, y, z   | whole-grid ops                                  |
| `jk_comm`      | y, z      | panel broadcast / A10 slab scatter              |
| `ik_comm`      | x, z      | pivot-row reduce + distribute / A01 slab scatter|
| `ij_comm`      | x, y      | validation-layout assembly                      |
| `k_comm`       | z         | 2.5D partial-sum reduction                      |
| `i_comm`       | x         | tournament pivoting butterfly                   |
"""

from __future__ import annotations

import numpy as np

import jax

from conflux_tpu.geometry import Grid3

AXIS_X = "x"  # row dimension of the tile grid (Px)
AXIS_Y = "y"  # column dimension of the tile grid (Py)
AXIS_Z = "z"  # 2.5D replication depth (Pz)

comm = {
    "lu": (AXIS_X, AXIS_Y, AXIS_Z),
    "jk": (AXIS_Y, AXIS_Z),
    "ik": (AXIS_X, AXIS_Z),
    "ij": (AXIS_X, AXIS_Y),
    "k": (AXIS_Z,),
    "i": (AXIS_X,),
}


_MESH_REGISTRY: dict = {}


def mesh_cache_key(mesh: jax.sharding.Mesh):
    """Hashable identity for a mesh, and register it for `lookup_mesh`.

    Compiled program builders are lru_cached on geometry + this key; keying
    by (device ids, axis names) means two equivalent Mesh objects share one
    compiled program, and the registry holds one canonical mesh per key
    (bounded by the number of distinct device layouts, so no growth over
    repeated calls).
    """
    key = (tuple(d.id for d in mesh.devices.flat), mesh.axis_names)
    _MESH_REGISTRY[key] = mesh
    return key


def lookup_mesh(key) -> jax.sharding.Mesh:
    return _MESH_REGISTRY[key]


def make_mesh(grid: Grid3, devices=None) -> jax.sharding.Mesh:
    """Build the ('x', 'y', 'z') mesh for a Grid3.

    On real hardware, axis order matters for ICI locality: jax.make_mesh
    chooses a device assignment that keeps the fastest-varying axes on
    physically adjacent chips. For tests, pass an explicit device list.
    """
    if devices is None:
        return jax.make_mesh((grid.Px, grid.Py, grid.Pz), (AXIS_X, AXIS_Y, AXIS_Z))
    devs = np.asarray(devices).reshape(grid.Px, grid.Py, grid.Pz)
    return jax.sharding.Mesh(devs, (AXIS_X, AXIS_Y, AXIS_Z))
