"""Incremental low-rank factor refresh: Sherman-Morrison-Woodbury solves.

PR 1 made repeated solves cheap (device-resident factors, one compiled
program per traffic shape), but any CHANGE to a served matrix still cost a
full O(N^3) refactorization. Serving traffic whose systems drift by a
rank-k correction between requests (streaming updates, a few changed
rows/columns, trust-region model tweaks) wants the Woodbury identity
instead: with A1 = A0 + U V^H (U, V of shape (N, k), k << N),

    A1^{-1} b = A0^{-1} b - A0^{-1} U (I_k + V^H A0^{-1} U)^{-1} V^H A0^{-1} b

so a refreshed solve is the BASE substitution (already compiled and
device-resident in the session) plus O(N k) extra GEMM work through the
k x k *capacitance* matrix C = I + V^H A0^{-1} U — an O(N^2 k) refresh +
O(N^2) solves where the refactor path pays O(N^3) per drift.

This module holds the traceable math (capacitance assembly, the corrected
apply, the one-shot functional solve) and the host-side
:class:`DriftPolicy` that decides when the correction has stopped paying
for itself and the session should pay for one true refactorization through
the existing `FactorPlan` factor program instead. The serving surface —
``SolveSession.update(U, V)`` / bucketed compiled programs / refactor
plumbing — lives in `conflux_tpu.serve`; one-shot entry points are
`solvers.solve_updated` and `batched.solve_updated_batched`.

When NOT to use the refresh path (also DESIGN.md §18): accumulated rank k
growing toward N (the correction costs O(N^2 k) per solve — past k ~ N/8
a refactor is cheaper and more accurate), and ill-conditioned capacitance
(cond(C) large means A0 + U V^H is near-singular *relative to the base
factors* and the correction amplifies rounding; the policy refactors on
both triggers, and `refine` backstop sweeps hold the residual in between).
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
from jax import lax

from conflux_tpu.ops import blas

_HI = lax.Precision.HIGHEST


def rank_bucket(k: int) -> int:
    """Next power of two >= k: the compiled-program bucket for update rank
    (and RHS width — `serve` pads to the bucket and slices back), so a
    traffic mix of ranks/widths compiles O(log) programs, not O(distinct)."""
    if k < 1:
        raise ValueError(f"bucket needs a positive size, got {k}")
    return 1 << (int(k) - 1).bit_length()


@dataclasses.dataclass(frozen=True)
class DriftPolicy:
    """When does the Woodbury correction stop paying for itself?

    max_rank: accumulated-rank cap; once total update rank exceeds it the
        session refactors (None -> max(8, N // 8): past ~N/8 the O(N^2 k)
        correction approaches the amortized O(N^3) refactor and accuracy
        degrades with every stacked correction).
    cond_limit: 1-norm condition cap on the k x k capacitance matrix; a
        large cond(C) means the drifted system is near-singular relative
        to the base factors and the correction amplifies rounding —
        refactor instead (non-finite estimates also trigger).
    refine: iterative-refinement backstop sweeps ADDED to the plan's own
        `refine` on updated solves only — the residual r = b - A1 x is
        computed against the *drifted* matrix (A0 x + U (V^H x)) and the
        correction rides the same Woodbury apply, the serve layer's
        existing refinement-loop discipline.
    """

    max_rank: int | None = None
    cond_limit: float = 1e6
    refine: int = 0

    def resolved_max_rank(self, n: int) -> int:
        if self.max_rank is not None:
            return int(self.max_rank)
        return max(8, n // 8)


def capacitance(base_apply, U, V):
    """Assemble the Woodbury correction state against the base factors.

    base_apply(r) must apply A0^{-1} (the session's substitution); U, V are
    (N, k) — zero-padded columns are harmless (they contribute an identity
    block to C, see below). Returns (Y, Cinv, cond1):

      Y    = A0^{-1} U                      (N, k)
      C    = I_k + V^H Y                    (k, k) capacitance
      Cinv = C^{-1}                         (k, k), dense — k is small, and
             an explicit inverse makes every later solve two GEMMs (the
             same trade as the serve layer's 'inv' substitution engine)
      cond1 = ||C||_1 ||C^{-1}||_1          the drift policy's trigger

    Traceable (jit/vmap-safe): the policy decision on cond1 happens on the
    host in the serve layer, not here.
    """
    Y = base_apply(U.astype(jnp.result_type(U.dtype, jnp.float32)))
    cdtype = Y.dtype
    Vc = V.astype(cdtype)
    k = U.shape[-1]
    C = jnp.eye(k, dtype=cdtype) + jnp.matmul(Vc.conj().T, Y, precision=_HI)
    Cinv = jnp.linalg.inv(C)
    norm1 = lambda M: jnp.max(jnp.sum(jnp.abs(M), axis=-2), axis=-1)
    cond1 = norm1(C) * norm1(Cinv)
    return Y, Cinv, cond1


def woodbury_apply(base_apply, Y, Cinv, V, b):
    """A1^{-1} b through the base factors + capacitance state:
    z - Y (Cinv (V^H z)) with z = A0^{-1} b. b is (N, nrhs)."""
    z = base_apply(b)
    Vc = V.astype(z.dtype)
    w = jnp.matmul(Vc.conj().T, z, precision=_HI)
    return z - jnp.matmul(Y.astype(z.dtype),
                          jnp.matmul(Cinv.astype(z.dtype), w, precision=_HI),
                          precision=_HI)


def updated_matvec(A0, U, V, x):
    """(A0 + U V^H) x without materializing the drifted matrix — the
    residual matvec of the refinement backstop, O(N^2 + N k) per column."""
    cdtype = x.dtype
    ax = jnp.matmul(A0.astype(cdtype), x, precision=_HI)
    w = jnp.matmul(V.astype(cdtype).conj().T, x, precision=_HI)
    return ax + jnp.matmul(U.astype(cdtype), w, precision=_HI)


def woodbury_solve(base_apply, A0, U, V, b, refine: int = 0):
    """One-shot functional form: solve (A0 + U V^H) x = b given a base
    substitution `base_apply` (r -> A0^{-1} r). `refine` sweeps compute the
    residual against the DRIFTED matrix and correct through the same
    Woodbury apply. A0 is only consumed when refine > 0 (pass None
    otherwise). Traceable; b is (N, nrhs)."""
    Y, Cinv, _ = capacitance(base_apply, U, V)
    x = woodbury_apply(base_apply, Y, Cinv, V, b)
    cdtype = x.dtype
    bc = b.astype(cdtype)
    for _ in range(refine):
        r = bc - updated_matvec(A0, U, V, x)
        x = x + woodbury_apply(base_apply, Y, Cinv, V, r).astype(cdtype)
    return x


def probe_vector(n: int):
    """The resilience layer's fixed Rademacher probe w (host numpy,
    float32 +-1, deterministic): E[(w . r)^2] = ||r||^2 exactly, so the
    projected residual below estimates the true one at the same relative
    scale. One fixed w per size keeps every checked program and every
    cached wA consistent."""
    import numpy as _np

    rng = _np.random.default_rng(0xC0FFEE)
    return rng.choice(_np.float32([-1.0, 1.0]), size=n)


def probe_row(w, A0):
    """wA = w^T A0 — the session-resident half of the Freivalds-style
    residual check, paid ONCE per base matrix (O(N^2), amortized like
    the factors; `SolveSession` caches it and invalidates on refactor).
    Traceable; per-system."""
    cdtype = blas.compute_dtype(A0.dtype)
    return jnp.matmul(w.astype(cdtype), A0.astype(cdtype), precision=_HI)


def probe_lstsq(w, A0):
    """(u, uA) — the least-squares analog of :func:`probe_row`, paid
    once per base matrix of a QR-backed session (`serve` kind='qr').

    A square session's Freivalds check projects the residual b - A x
    through a fixed Rademacher w. For min||Ax - b|| that residual is
    NOT small — it is the orthogonal complement of b — so the probe
    must live in range(A0) instead: u = A0 w (normalized to the
    Rademacher scale ||u|| = sqrt(M)) and uA = u^T A0. At the true LS
    solution the residual is orthogonal to range(A0), so
    u . (b - A0 x) = u . b - uA . x vanishes, and
    :func:`health_spot_check` works VERBATIM with (u, uA) in the
    (w, wA) slots — same formula, same (2,) verdict, same escalation
    plumbing. Systemic garbage (corrupt R, a non-orthogonal Q) shows
    up as an O(1) relative error in uA . x. Traceable; per-system."""
    cdtype = blas.compute_dtype(A0.dtype)
    u = jnp.matmul(A0.astype(cdtype), w.astype(cdtype), precision=_HI)
    m = A0.shape[-2]
    scale = jnp.sqrt(jnp.asarray(float(m), cdtype))
    u = u * (scale / (jnp.sqrt(jnp.sum(jnp.abs(u) ** 2))
                      + jnp.finfo(cdtype).tiny))
    uA = jnp.matmul(u, A0.astype(cdtype), precision=_HI)
    return u, uA


def health_spot_check(w, wA, x, b, Up=None, Vp=None):
    """Fused finite/projected-residual health verdict for one solve —
    the resilience layer's output guard (`conflux_tpu.resilience`),
    fused into the checked solve programs so the clean path pays no
    extra dispatch. Returns a (2,) float32 verdict
    [finite_flag, residual]:

      finite_flag — 1.0 iff EVERY element of x is finite. RHS columns
          are independent through the substitution, so a NaN/Inf column
          corrupts only its own answer column: the all-element finite
          check IS the per-column guard.
      residual    — |w . (b0 - A x0)| / ||b0|| on column 0, computed
          Freivalds-style through the precomputed probe row wA = w^T A0
          (:func:`probe_row`): w.b0 - wA.x0 costs two O(N) dots where
          the true residual matvec costs O(N^2) — which is comparable to
          the solve itself, and the clean-path overhead gate (<5%,
          BENCH_RESILIENCE.json) forbids that (XLA CPU also runs skinny
          batched matvecs far off peak, the §17 trsm lesson). With the
          Rademacher w the projection estimates ||r||/||b|| at the same
          relative scale; systemic garbage (factor corruption, an
          ill-conditioned SMW correction) is an O(1) relative error in
          essentially every component, so it cannot hide from the
          projection except on a measure-zero set. A tripwire for
          catastrophic failures, not an accuracy certificate — `refine`
          sweeps are the accuracy tool.

    Up/Vp (the session's padded drift factors) extend the projection to
    the DRIFTED matrix: w^T A1 = wA + (w^T Up) Vp^H, two more O(N k)
    dots; zero-padded columns are inert.

    Batch-generic and deliberately op-lean: XLA CPU charges microseconds
    of fixed overhead PER OP next to tiny dispatches, so the verdict is
    built from a handful of batched reductions on the whole (B, N, w)
    block — never per-vmap-lane — and the finite flag rides one
    summation (NaN/Inf poisons the accumulator; an overflow false
    positive merely triggers one escalation whose exact re-check then
    passes). Traceable; call OUTSIDE any vmap."""
    cdtype = x[..., 0].dtype
    finite = jnp.isfinite(jnp.sum(x))
    x0 = x[..., 0].astype(cdtype)                       # (..., N)
    b0 = b[..., 0].astype(cdtype)
    wc = w.astype(cdtype)
    ax = jnp.sum(wA.astype(cdtype) * x0, axis=-1)       # (...,)
    if Up is not None:
        wU = jnp.sum(wc[:, None] * Up.astype(cdtype), axis=-2)
        vx = jnp.sum(Vp.astype(cdtype).conj()
                     * x0[..., :, None], axis=-2)       # (..., k)
        ax = ax + jnp.sum(wU * vx, axis=-1)
    num = jnp.abs(jnp.sum(wc * b0, axis=-1) - ax)
    den = (jnp.sqrt(jnp.sum(jnp.abs(b0) ** 2, axis=-1))
           + jnp.finfo(cdtype).tiny)
    return jnp.stack([finite.astype(jnp.float32),
                      jnp.max(num / den).astype(jnp.float32)])


def health_spot_check_slots(w, wA, x, b, Up=None, Vp=None):
    """Per-slot fused health verdict for a STACKED (gang) solve — the
    cross-session analog of :func:`health_spot_check`, returning a
    (2, S) float32 block instead of a (2,) scalar pair: row 0 the
    per-slot finite flags, row 1 the per-slot projected residuals.
    Slot i's verdict depends only on slot i's factors/RHS (the vmapped
    solve never mixes slots), so one sick session can never contaminate
    its gang-mates' evidence — the same blast-radius-isolation shape as
    the factor lane's per-slot verdict (`FactorPlan._factor_health_fn`),
    read host-side by the same `resilience.evaluate_slots`.

    x is (S, N, w), wA is (S, N), b is (S, N, w); Up/Vp (S, N, kb)
    extend the projection to each slot's drifted matrix (zero-padded
    columns inert — a clean slot carries zero U/V). Idle gang slots
    (zero RHS columns) evaluate finite with residual 0. Deliberately
    op-lean: a handful of batched reductions OUTSIDE the vmap (the
    XLA-CPU fixed-op-cost rule, §20) — per-slot sums, never
    per-element ops. Traceable; single-system plans only."""
    cdtype = x[..., 0].dtype
    xs = jnp.sum(x, axis=tuple(range(1, x.ndim)))            # (S,)
    finite = jnp.isfinite(xs)
    x0 = x[..., 0].astype(cdtype)                            # (S, N)
    b0 = b[..., 0].astype(cdtype)
    wc = w.astype(cdtype)
    ax = jnp.sum(wA.astype(cdtype) * x0, axis=-1)            # (S,)
    if Up is not None:
        wU = jnp.sum(wc[None, :, None] * Up.astype(cdtype),
                     axis=-2)                                # (S, kb)
        vx = jnp.sum(Vp.astype(cdtype).conj()
                     * x0[..., :, None], axis=-2)            # (S, kb)
        ax = ax + jnp.sum(wU * vx, axis=-1)
    num = jnp.abs(jnp.sum(wc * b0, axis=-1) - ax)
    den = (jnp.sqrt(jnp.sum(jnp.abs(b0) ** 2, axis=-1))
           + jnp.finfo(cdtype).tiny)
    return jnp.stack([finite.astype(jnp.float32),
                      (num / den).astype(jnp.float32)])


def health_verdict_from_stats(w, xsum, wAx, b):
    """Assemble the :func:`health_spot_check` verdict from IN-LOOP
    accumulators instead of a pass over x — the blocked substitution
    engine's fused probe epilogue (DESIGN §27): `xsum` is sum(x) (the
    finite accumulator) and `wAx` is wA . x[:, 0], both accumulated per
    block inside `ops.batched_trsm.blocked_solve_probe`'s final solve,
    so the verdict here costs only the two O(N) b-side dots. Leading
    batch axes of xsum/wAx/b (batched plans) max-reduce like the
    unfused check. Returns the same (2,) float32
    [finite_flag, residual] verdict; traceable, call OUTSIDE vmap."""
    cdtype = wAx.dtype
    finite = jnp.isfinite(jnp.sum(xsum))
    b0 = b[..., 0].astype(cdtype)
    wc = w.astype(cdtype)
    num = jnp.abs(jnp.sum(wc * b0, axis=-1) - wAx)
    den = (jnp.sqrt(jnp.sum(jnp.abs(b0) ** 2, axis=-1))
           + jnp.finfo(cdtype).tiny)
    return jnp.stack([finite.astype(jnp.float32),
                      jnp.max(num / den).astype(jnp.float32)])


def health_verdict_from_stats_slots(w, xsum, wAx, b):
    """Per-slot fused verdict from in-loop accumulators — the stacked
    (gang) analog of :func:`health_verdict_from_stats`, mirroring
    :func:`health_spot_check_slots`'s (2, S) contract: xsum/wAx are
    (S,) per-slot accumulators out of the vmapped blocked probe solve,
    b is (S, N, w). Slot i's verdict still depends only on slot i's
    accumulators and RHS (blast-radius isolation); idle pad slots
    (zero RHS) evaluate finite with residual 0. Traceable."""
    cdtype = wAx.dtype
    finite = jnp.isfinite(xsum)
    b0 = b[..., 0].astype(cdtype)
    wc = w.astype(cdtype)
    num = jnp.abs(jnp.sum(wc * b0, axis=-1) - wAx)
    den = (jnp.sqrt(jnp.sum(jnp.abs(b0) ** 2, axis=-1))
           + jnp.finfo(cdtype).tiny)
    return jnp.stack([finite.astype(jnp.float32),
                      (num / den).astype(jnp.float32)])


def pad_update_state(Up, Vp, Y, Cinv, kb: int):
    """Zero-pad one session's Woodbury state from its own rank bucket
    k0 = Up.shape[-1] up to the gang bucket `kb` — what lets sessions
    at DIFFERENT drift ranks share one stacked rank-bucketed Woodbury
    dispatch. U/V/Y gain zero columns (inert: a zero column contributes
    nothing to V^H z or to Y @ (...)); Cinv extends block-diagonally
    with the identity — exactly the capacitance :func:`capacitance`
    would have produced from the zero-padded U/V (C = I + V^H Y is
    block-diag [C_k0, I], so its inverse is [Cinv_k0, I]), built here
    by construction instead of re-inverting. The padded slot's
    correction therefore equals the unpadded one up to reduction
    order (allclose, the gang contract for drifted slots)."""
    k0 = Up.shape[-1]
    if k0 == kb:
        return Up, Vp, Y, Cinv
    if k0 > kb:
        raise ValueError(f"cannot pad rank {k0} down to bucket {kb}")
    pad = [(0, 0)] * (Up.ndim - 1) + [(0, kb - k0)]
    Up2 = jnp.pad(Up, pad)
    Vp2 = jnp.pad(Vp, pad)
    Y2 = jnp.pad(Y, pad)
    C2 = jnp.eye(kb, dtype=Cinv.dtype).at[:k0, :k0].set(Cinv)
    return Up2, Vp2, Y2, C2


def zero_update_state(n: int, kb: int, dtype, factor_dtype=None):
    """The Woodbury state of an UNdrifted gang slot at rank bucket kb:
    zero U/V/Y and an identity capacitance inverse. Riding the stacked
    Woodbury program with this state reproduces the plain substitution
    (the correction term is exactly zero — Y is the zero matrix), so a
    mixed clean/drifted gang dispatches ONE program. Y/Cinv take the
    compute dtype of `factor_dtype` (default `dtype`) — the dtype a
    real :func:`capacitance` output carries, so a prewarmed program
    signature matches live drift traffic."""
    cdtype = blas.compute_dtype(jnp.dtype(factor_dtype or dtype))
    z = jnp.zeros((n, kb), jnp.dtype(dtype))
    return z, z, jnp.zeros((n, kb), cdtype), jnp.eye(kb, dtype=cdtype)


def apply_update(A0, U, V):
    """Materialize the drifted matrix A0 + U V^H in A0's dtype — the
    refactor path's input (and the bench's full-refactor oracle).
    Batch-safe: leading axes of A0/U/V broadcast through the matmul."""
    cdtype = blas.compute_dtype(A0.dtype)
    Vh = jnp.swapaxes(V.astype(cdtype).conj(), -1, -2)
    return (A0.astype(cdtype)
            + jnp.matmul(U.astype(cdtype), Vh,
                         precision=_HI)).astype(A0.dtype)
