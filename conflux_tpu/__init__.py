"""conflux_tpu — a TPU-native communication-optimal dense linear algebra framework.

A from-scratch rebuild of the capabilities of eth-cscs/conflux (CONFLUX
distributed LU with tournament pivoting, CONFCHOX distributed Cholesky) on
JAX/XLA/Pallas. The reference's 2.5D/3D MPI process grid becomes a named
`jax.sharding.Mesh` over ('x', 'y', 'z'); its block-cyclic tile distribution,
butterfly tournament pivoting, and z-replicated trailing updates become
`shard_map` programs built on `psum` / `ppermute` / `all_gather` collectives;
its CBLAS/LAPACKE tile kernels become XLA ops and Pallas kernels.

Reference layer map: /root/reference (see SURVEY.md). This package is an
independent TPU-first design, not a translation.
"""

from conflux_tpu.geometry import (
    Grid3,
    LUGeometry,
    CholeskyGeometry,
    choose_grid,
    choose_cholesky_grid,
)

__version__ = "0.1.0"

__all__ = [
    "Grid3",
    "LUGeometry",
    "CholeskyGeometry",
    "choose_grid",
    "choose_cholesky_grid",
]
