"""conflux_tpu — a TPU-native communication-optimal dense linear algebra framework.

A from-scratch rebuild of the capabilities of eth-cscs/conflux (CONFLUX
distributed LU with tournament pivoting, CONFCHOX distributed Cholesky) on
JAX/XLA/Pallas. The reference's 2.5D/3D MPI process grid becomes a named
`jax.sharding.Mesh` over ('x', 'y', 'z'); its block-cyclic tile distribution,
butterfly tournament pivoting, and z-replicated trailing updates become
`shard_map` programs built on `psum` / `ppermute` / `all_gather` collectives;
its CBLAS/LAPACKE tile kernels become XLA ops and Pallas kernels.

Reference layer map: /root/reference (see SURVEY.md). This package is an
independent TPU-first design, not a translation.
"""

from conflux_tpu.geometry import (
    Grid3,
    LUGeometry,
    CholeskyGeometry,
    choose_grid,
    choose_cholesky_grid,
)


def __getattr__(name):
    # lazy top-level API: keep `import conflux_tpu` light (no jax import)
    _lazy = {
        "lu_factor_blocked": ("conflux_tpu.lu.single", "lu_factor_blocked"),
        "lu_distributed_host": ("conflux_tpu.lu.distributed", "lu_distributed_host"),
        "cholesky_blocked": ("conflux_tpu.cholesky.single", "cholesky_blocked"),
        "cholesky_distributed_host": (
            "conflux_tpu.cholesky.distributed", "cholesky_distributed_host"),
        "lu_factor_distributed": (
            "conflux_tpu.lu.distributed", "lu_factor_distributed"),
        "lu_factor_steps": ("conflux_tpu.lu.distributed", "lu_factor_steps"),
        "cholesky_factor_distributed": (
            "conflux_tpu.cholesky.distributed", "cholesky_factor_distributed"),
        "cholesky_factor_steps": (
            "conflux_tpu.cholesky.distributed", "cholesky_factor_steps"),
        "lu_solve_distributed": (
            "conflux_tpu.solvers", "lu_solve_distributed"),
        "cholesky_solve_distributed": (
            "conflux_tpu.solvers", "cholesky_solve_distributed"),
        "solve_distributed": ("conflux_tpu.solvers", "solve_distributed"),
        "distribute_shards": (
            "conflux_tpu.parallel.mesh", "distribute_shards"),
        "solve": ("conflux_tpu.solvers", "solve"),
        "lu_solve": ("conflux_tpu.solvers", "lu_solve"),
        "cholesky_solve": ("conflux_tpu.solvers", "cholesky_solve"),
        "lstsq": ("conflux_tpu.solvers", "lstsq"),
        "lu_solve_transposed": ("conflux_tpu.solvers", "lu_solve_transposed"),
        "slogdet_from_lu": ("conflux_tpu.solvers", "slogdet_from_lu"),
        "cond_estimate_1": ("conflux_tpu.solvers", "cond_estimate_1"),
        "inv_from_lu": ("conflux_tpu.solvers", "inv_from_lu"),
        "lstsq_distributed": ("conflux_tpu.solvers", "lstsq_distributed"),
        "qr_lstsq_distributed": ("conflux_tpu.solvers", "qr_lstsq_distributed"),
        "make_mesh": ("conflux_tpu.parallel.mesh", "make_mesh"),
        "initialize_multihost": ("conflux_tpu.parallel.mesh", "initialize_multihost"),
        "qr_factor_blocked": ("conflux_tpu.qr.single", "qr_factor_blocked"),
        "tall_qr": ("conflux_tpu.qr.single", "tall_qr"),
        "tsqr_distributed": ("conflux_tpu.qr.distributed", "tsqr_distributed"),
        "qr_factor_distributed": (
            "conflux_tpu.qr.distributed", "qr_factor_distributed"),
        "qr_factor_steps": (
            "conflux_tpu.qr.distributed", "qr_factor_steps"),
        "cholesky_qr2_distributed": (
            "conflux_tpu.qr.distributed", "cholesky_qr2_distributed"),
        "qr_distributed_host": (
            "conflux_tpu.qr.distributed", "qr_distributed_host"),
        # serving / batched layer (ISSUE 1)
        "lu_factor_batched": ("conflux_tpu.batched", "lu_factor_batched"),
        "cholesky_factor_batched": (
            "conflux_tpu.batched", "cholesky_factor_batched"),
        "lu_solve_batched": ("conflux_tpu.batched", "lu_solve_batched"),
        "cholesky_solve_batched": (
            "conflux_tpu.batched", "cholesky_solve_batched"),
        "solve_batched": ("conflux_tpu.batched", "solve_batched"),
        "batch_mesh": ("conflux_tpu.batched", "batch_mesh"),
        "FactorPlan": ("conflux_tpu.serve", "FactorPlan"),
        "SolveSession": ("conflux_tpu.serve", "SolveSession"),
        "enable_persistent_cache": (
            "conflux_tpu.cache", "enable_persistent_cache"),
        # incremental low-rank refresh (ISSUE 2)
        "solve_updated": ("conflux_tpu.solvers", "solve_updated"),
        "solve_updated_batched": (
            "conflux_tpu.batched", "solve_updated_batched"),
        "DriftPolicy": ("conflux_tpu.update", "DriftPolicy"),
        # async serve engine (ISSUE 3)
        "ServeEngine": ("conflux_tpu.engine", "ServeEngine"),
        "EngineSaturated": ("conflux_tpu.engine", "EngineSaturated"),
        "EngineClosed": ("conflux_tpu.engine", "EngineClosed"),
        # serve-path resilience (ISSUE 4)
        "HealthPolicy": ("conflux_tpu.resilience", "HealthPolicy"),
        "FaultPlan": ("conflux_tpu.resilience", "FaultPlan"),
        "FaultSpec": ("conflux_tpu.resilience", "FaultSpec"),
        "SolveUnhealthy": ("conflux_tpu.resilience", "SolveUnhealthy"),
        "DeadlineExceeded": ("conflux_tpu.resilience", "DeadlineExceeded"),
        "SessionQuarantined": (
            "conflux_tpu.resilience", "SessionQuarantined"),
        "RhsNonFinite": ("conflux_tpu.resilience", "RhsNonFinite"),
        # factor lane / coalesced cold-start (ISSUE 5)
        "stack_trees": ("conflux_tpu.batched", "stack_trees"),
        "unstack_tree": ("conflux_tpu.batched", "unstack_tree"),
        # adaptive serve-engine control loop (ISSUE 8)
        "AdaptiveController": ("conflux_tpu.control", "AdaptiveController"),
        "ControlLimits": ("conflux_tpu.control", "ControlLimits"),
        "StatsWindow": ("conflux_tpu.profiler", "StatsWindow"),
        # mesh-sharded serve fleet (ISSUE 9)
        "DeviceLane": ("conflux_tpu.engine", "DeviceLane"),
        "place_session": ("conflux_tpu.engine", "place_session"),
        "MeshPlanUnsupported": (
            "conflux_tpu.resilience", "MeshPlanUnsupported"),
        # gang-resident session stacking (ISSUE 10)
        "SessionGang": ("conflux_tpu.gang", "SessionGang"),
        "write_slot_tree": ("conflux_tpu.batched", "write_slot_tree"),
        "grow_stack_tree": ("conflux_tpu.batched", "grow_stack_tree"),
        # multi-host serve fabric (ISSUE 13)
        "ServeFabric": ("conflux_tpu.fabric", "ServeFabric"),
        "FabricPolicy": ("conflux_tpu.fabric", "FabricPolicy"),
        "LocalHost": ("conflux_tpu.fabric", "LocalHost"),
        "ProcessHost": ("conflux_tpu.fabric", "ProcessHost"),
        "HostUnavailable": ("conflux_tpu.resilience", "HostUnavailable"),
        "FleetDegraded": ("conflux_tpu.resilience", "FleetDegraded"),
        "HostLoadEstimator": ("conflux_tpu.control", "HostLoadEstimator"),
        "CounterWindow": ("conflux_tpu.profiler", "CounterWindow"),
        "QosClass": ("conflux_tpu.qos", "QosClass"),
        "FairShareLedger": ("conflux_tpu.qos", "FairShareLedger"),
        "TenantThrottled": ("conflux_tpu.resilience", "TenantThrottled"),
        # elastic fabric (ISSUE 19)
        "FabricAutoscaler": ("conflux_tpu.control", "FabricAutoscaler"),
        "AutoscalePolicy": ("conflux_tpu.control", "AutoscalePolicy"),
        "rendezvous_ranked": ("conflux_tpu.engine", "rendezvous_ranked"),
    }
    if name in _lazy:
        import importlib

        mod, attr = _lazy[name]
        return getattr(importlib.import_module(mod), attr)
    raise AttributeError(f"module 'conflux_tpu' has no attribute {name!r}")


__version__ = "0.1.0"

__all__ = [
    "Grid3",
    "LUGeometry",
    "CholeskyGeometry",
    "choose_grid",
    "choose_cholesky_grid",
    "lu_factor_blocked",
    "lu_distributed_host",
    "cholesky_blocked",
    "cholesky_distributed_host",
    "solve",
    "lu_solve",
    "cholesky_solve",
    "lstsq",
    "lu_solve_transposed",
    "slogdet_from_lu",
    "cond_estimate_1",
    "inv_from_lu",
    "lstsq_distributed",
    "qr_lstsq_distributed",
    "lu_factor_distributed",
    "lu_factor_steps",
    "cholesky_factor_distributed",
    "cholesky_factor_steps",
    "lu_solve_distributed",
    "cholesky_solve_distributed",
    "solve_distributed",
    "distribute_shards",
    "make_mesh",
    "initialize_multihost",
    "qr_factor_blocked",
    "tall_qr",
    "tsqr_distributed",
    "qr_factor_distributed",
    "qr_factor_steps",
    "cholesky_qr2_distributed",
    "qr_distributed_host",
    "lu_factor_batched",
    "cholesky_factor_batched",
    "lu_solve_batched",
    "cholesky_solve_batched",
    "solve_batched",
    "batch_mesh",
    "FactorPlan",
    "SolveSession",
    "enable_persistent_cache",
    "solve_updated",
    "solve_updated_batched",
    "DriftPolicy",
    "ServeEngine",
    "EngineSaturated",
    "EngineClosed",
    "HealthPolicy",
    "FaultPlan",
    "FaultSpec",
    "SolveUnhealthy",
    "DeadlineExceeded",
    "SessionQuarantined",
    "RhsNonFinite",
    "stack_trees",
    "unstack_tree",
    "AdaptiveController",
    "ControlLimits",
    "StatsWindow",
    "DeviceLane",
    "place_session",
    "MeshPlanUnsupported",
    "SessionGang",
    "write_slot_tree",
    "grow_stack_tree",
    "ServeFabric",
    "FabricPolicy",
    "LocalHost",
    "ProcessHost",
    "HostUnavailable",
    "FleetDegraded",
    "HostLoadEstimator",
    "CounterWindow",
    "QosClass",
    "FairShareLedger",
    "TenantThrottled",
    "FabricAutoscaler",
    "AutoscalePolicy",
    "rendezvous_ranked",
]
