"""Async serve engine: request coalescing, double-buffered dispatch,
plan prewarming, and admission control.

The plan/session layer (`conflux_tpu.serve`) makes a *single* session
fast — compile once per traffic shape, factor once per matrix,
substitution-only solves — but every entry point is synchronous and
per-request: a fleet of sessions under open-loop traffic still dispatches
one device program per request, leaves the device idle between host
round-trips, and pays a compile stall on the first request of every new
bucket. The same trade that drives the 2.5D algorithms (a little extra
buffering/replication for far fewer, larger device operations) applies at
the request level, and :class:`ServeEngine` makes it:

- **Coalescing** — requests arriving within a ``max_batch_delay`` window
  are grouped and merged along the axes the compiled programs already
  bucket. Requests against the SAME session concatenate their RHS columns
  into one wider substitution: columns are independent through every
  substitution/GEMM/IR step, so single-system answers are bitwise the
  per-request ones (the bucket-padding argument of `SolveSession.solve`,
  asserted in tests/test_engine.py); batched plans' vmapped GEMM kernel
  changes shape with the coalesced width, so their coalesced answers are
  allclose, bitwise only within a bucket. With ``stack_sessions=True``,
  requests against DIFFERENT sessions of one single-system plan
  additionally stack their factor pytrees on a new leading axis and ride
  one vmapped dispatch (`FactorPlan._stacked_solve_fn`) — allclose to,
  but not bitwise, the per-session programs, so it is opt-in.

- **Double-buffered async dispatch** — a dispatcher thread stages and
  dispatches batch i+1 while a drain thread waits on batch i: the
  dispatched-batch queue is bounded at two entries, so host staging
  overlaps device compute without unbounded in-flight growth, and the hot
  path never calls ``block_until_ready`` (JAX async dispatch carries the
  results; only the drain thread blocks).

- **Prewarming + admission control** — :meth:`ServeEngine.prewarm`
  compiles the declared traffic buckets (widths, stack sizes) before
  traffic lands, so p99 never eats a compile (the persistent XLA cache is
  switched on, so even cold processes deserialize); a bounded pending
  count sheds (``on_full='reject'``, the default, raising
  :class:`EngineSaturated`) or backpressures (``on_full='block'``)
  instead of collapsing into unbounded latency.

Sessions mutate under ``update``/refactor; the engine only ever calls
``session.solve``. Do not call ``session.update`` while requests against
that session are in flight — drain first (``engine.close()`` or wait on
the outstanding futures).

    engine = ServeEngine(max_batch_delay=0.002)
    engine.prewarm(session, widths=(1, 2, 4))
    futs = [engine.submit(session, b) for b in rhs]     # non-blocking
    xs = [f.result() for f in futs]                     # coalesced device work
    print(engine.stats())                               # p50/p95/p99, batches
    engine.close()                                      # drains in flight
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from concurrent.futures import Future
from queue import Empty, Queue
from typing import Any

import numpy as np

import jax.numpy as jnp

from conflux_tpu import profiler
from conflux_tpu.batched import _shard_batch, stack_trees
from conflux_tpu.update import rank_bucket


class EngineSaturated(RuntimeError):
    """submit() refused: the bounded pending set is full (shed policy)."""


class EngineClosed(RuntimeError):
    """submit() after close()."""


@dataclasses.dataclass
class _Request:
    session: Any          # the SolveSession the answer comes from
    b2: Any               # HOST RHS normalized to a trailing width axis
    width: int            # pre-coalescing column count
    squeeze: bool         # drop the width axis in the result
    future: Future        # resolved by the drain thread
    t_submit: float       # perf_counter at admission (latency clock)
    carried: bool = False  # deferred once already — never defer again


def _normalize_rhs(session, b):
    """Mirror `SolveSession._rhs` on the HOST: returns (b2, squeeze) with
    b2 a numpy array carrying an explicit trailing width axis. Staying in
    numpy keeps admission free of device work — the dispatcher memcpys
    requests into one bucket-width staging buffer per batch, so the
    device sees ONE transfer and ONE prewarmed program regardless of how
    many requests coalesced (a per-batch `concatenate` of varying widths
    would be a fresh XLA compile per width combination)."""
    plan = session.plan
    b = np.asarray(b)
    if plan.batched:
        want = (plan.B, plan.N)
        if b.ndim == 2:
            if b.shape != want:
                raise ValueError(f"rhs {b.shape}, session needs {want}")
            return b[:, :, None], True
        if b.ndim != 3 or b.shape[:2] != want:
            raise ValueError(
                f"rhs {b.shape}, session needs {want} (+ rhs axis)")
        return b, False
    if b.ndim == 1:
        if b.shape[0] != plan.N:
            raise ValueError(f"rhs {b.shape}, session needs ({plan.N},)")
        return b[:, None], True
    if b.ndim != 2 or b.shape[0] != plan.N:
        raise ValueError(f"rhs {b.shape}, session needs ({plan.N}, k)")
    return b, False


_STOP = object()


def _percentile(sorted_vals, pct: float) -> float:
    """Nearest-rank percentile of an ascending list (0.0 when empty)."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(pct / 100.0 * len(sorted_vals) + 0.5)) - 1))
    return sorted_vals[idx]


class ServeEngine:
    """A thread-safe request queue in front of a fleet of SolveSessions.

    Knobs (the latency/throughput dial, DESIGN.md §19):

    max_batch_delay: how long the dispatcher holds the first request of a
        batch while more arrive to coalesce with it. 0 disables the wait
        (requests still coalesce when they are already queued — the burst
        shape); larger trades p50 latency for wider device dispatches.
    max_pending: admission bound on un-answered requests (queued plus in
        flight). `on_full` picks the policy at the bound: 'reject' (shed:
        submit raises :class:`EngineSaturated`) or 'block' (backpressure
        the submitter).
    max_coalesce_width: cap on coalesced RHS columns per dispatch — also
        the widest bucket `prewarm` needs to cover for a compile-free
        steady state.
    stack_sessions / max_stack: opt-in cross-session stacking for
        single-system plans (see module docstring).
    latency_window: how many completed-request latencies the percentile
        window keeps.
    """

    def __init__(self, *, max_batch_delay: float = 0.002,
                 max_pending: int = 1024, on_full: str = "reject",
                 max_coalesce_width: int = 32,
                 stack_sessions: bool = False, max_stack: int = 8,
                 latency_window: int = 8192,
                 persistent_cache: bool = True):
        if on_full not in ("reject", "block"):
            raise ValueError(f"unknown on_full {on_full!r} (reject|block)")
        if max_pending < 1 or max_coalesce_width < 1 or max_stack < 1:
            raise ValueError("max_pending, max_coalesce_width and "
                             "max_stack must be >= 1")
        if persistent_cache:
            from conflux_tpu import cache

            cache.enable_persistent_cache()
        self.max_batch_delay = float(max_batch_delay)
        self.max_pending = int(max_pending)
        self.on_full = on_full
        self.max_coalesce_width = int(max_coalesce_width)
        self.stack_sessions = bool(stack_sessions)
        self.max_stack = int(max_stack)

        self._inq: Queue = Queue()
        # bounded at 2: the double buffer. The dispatcher stages/dispatches
        # batch i+1 while the drain thread waits on batch i; a third batch
        # blocks the dispatcher instead of growing in-flight device work.
        self._outq: Queue = Queue(maxsize=2)
        self._lock = threading.Lock()
        self._not_full = threading.Condition(self._lock)
        self._closed = False
        self._pending = 0
        self._queue_peak = 0
        self._requests = 0
        self._completed = 0
        self._failed = 0
        self._sheds = 0
        self._batches = 0
        self._coalesced_requests = 0
        self._latencies: deque = deque(maxlen=int(latency_window))

        profiler.register_engine(self)
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, name="serve-engine-dispatch",
            daemon=True)
        self._drainer = threading.Thread(
            target=self._drain_loop, name="serve-engine-drain", daemon=True)
        self._dispatcher.start()
        self._drainer.start()

    # ------------------------------------------------------------------ #
    # client surface
    # ------------------------------------------------------------------ #

    def submit(self, session, b) -> Future:
        """Enqueue one solve against `session`; returns a Future whose
        result is a HOST (numpy) array with the shape and values
        `session.solve(b)` would have returned. A served answer crosses
        the host boundary anyway, so the engine pays it once per
        coalesced batch (one contiguous device->host copy on the drain
        thread) instead of per request — the per-request scatter is then
        numpy views, zero extra device dispatches. Raises
        :class:`EngineSaturated` at the pending bound under the 'reject'
        policy; blocks under 'block'."""
        if self._closed:
            raise EngineClosed("submit() on a closed ServeEngine")
        b2, squeeze = _normalize_rhs(session, b)
        req = _Request(session, b2, int(b2.shape[-1]), squeeze, Future(),
                       time.perf_counter())
        with self._lock:
            if self._closed:
                raise EngineClosed("submit() on a closed ServeEngine")
            if self._pending >= self.max_pending:
                if self.on_full == "reject":
                    self._sheds += 1
                    raise EngineSaturated(
                        f"{self._pending} pending requests >= max_pending="
                        f"{self.max_pending} (shed policy 'reject')")
                while self._pending >= self.max_pending \
                        and not self._closed:
                    self._not_full.wait()
                if self._closed:
                    raise EngineClosed("engine closed while blocked")
            self._pending += 1
            self._requests += 1
            if self._pending > self._queue_peak:
                self._queue_peak = self._pending
        self._inq.put(req)
        return req.future

    def solve(self, session, b, timeout: float | None = None):
        """Blocking convenience: ``submit(session, b).result(timeout)``."""
        return self.submit(session, b).result(timeout)

    def close(self, timeout: float | None = None) -> None:
        """Stop admission, drain every in-flight request, join the
        workers. Queued requests are answered, not dropped; idempotent."""
        with self._lock:
            already = self._closed
            self._closed = True
            self._not_full.notify_all()
        if not already:
            self._inq.put(_STOP)
        self._dispatcher.join(timeout)
        self._drainer.join(timeout)

    def __enter__(self) -> "ServeEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------------------ #
    # prewarming
    # ------------------------------------------------------------------ #

    def prewarm(self, session, widths=(1,), stacks=(), wait: bool = True):
        """Compile the session's solve programs for the declared traffic
        before it lands: `widths` are RHS widths (rounded up to
        power-of-two buckets — include the coalesced widths you expect;
        `max_coalesce_width` covers the worst case), `stacks` are
        cross-session stack sizes (single-system plans only). Runs the
        programs once on zero RHS through the plan's own cached builders,
        so steady-state traffic observes zero compiles (asserted via
        `plan.trace_counts` in tests and bench_engine). `wait=False`
        compiles on a background thread (the engine-start pattern) and
        returns the Thread."""

        def run():
            with profiler.region("engine.prewarm"):
                for wb in sorted({rank_bucket(w) for w in widths}):
                    self._prewarm_width(session, wb)
                    for s in stacks:
                        self._prewarm_stack(session, rank_bucket(s), wb)

        if wait:
            run()
            return None
        t = threading.Thread(target=run, name="serve-engine-prewarm",
                             daemon=True)
        t.start()
        return t

    def _prewarm_width(self, session, wb: int) -> None:
        plan = session.plan
        shape = ((plan.B, plan.N, wb) if plan.batched else (plan.N, wb))
        b2 = jnp.zeros(shape, jnp.dtype(plan.key.dtype))
        if plan.mesh is not None:
            (b2,) = _shard_batch((b2,), plan.mesh)
        plan._solve_fn(wb)(session._factors, session._A,
                           b2).block_until_ready()

    def _prewarm_stack(self, session, sb: int, wb: int) -> None:
        plan = session.plan
        if plan.batched:
            raise ValueError(
                "stacks= prewarming applies to single-system plans only")
        F = stack_trees([session._factors] * sb)
        A = None if session._A is None else jnp.stack([session._A] * sb)
        b = jnp.zeros((sb, plan.N, wb), jnp.dtype(plan.key.dtype))
        plan._stacked_solve_fn(sb, wb)(F, A, b).block_until_ready()

    # ------------------------------------------------------------------ #
    # dispatcher: collect a window, coalesce, dispatch async
    # ------------------------------------------------------------------ #

    def _dispatch_loop(self) -> None:
        stop = False
        carry: list = []  # small remainder chunks deferred to this round
        while not stop:
            if carry:
                try:
                    first = self._inq.get(timeout=self.max_batch_delay)
                except Empty:
                    first = None  # window spent waiting on the carry
            else:
                first = self._inq.get()
            batch = list(carry)
            carry = []
            collect = True
            if first is _STOP:
                stop = True
                collect = False
            elif first is None:
                collect = False
            else:
                batch.append(first)
            if collect:
                deadline = time.perf_counter() + self.max_batch_delay
                while True:
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        # the window is over, but anything ALREADY queued
                        # still coalesces (the burst shape: a backlog
                        # should never dispatch one request at a time)
                        try:
                            r = self._inq.get_nowait()
                        except Empty:
                            break
                    else:
                        try:
                            r = self._inq.get(timeout=remaining)
                        except Empty:
                            break
                    if r is _STOP:
                        stop = True
                        break
                    batch.append(r)
                    if len(batch) >= self.max_pending:
                        break
            if batch:
                carry = self._dispatch(
                    batch,
                    may_defer=not stop and not self._inq.empty())
        if carry:
            self._dispatch(carry, may_defer=False)
        self._outq.put(_STOP)

    def _dispatch(self, batch, may_defer: bool = False) -> list:
        """Group a window's requests and dispatch each group as one
        device program (async — nothing here blocks on device work).
        With `may_defer` (more traffic already queued), each session's
        small remainder chunk is handed back once to ride the next
        window instead of wasting a whole dispatch on a sliver."""
        groups: dict[int, list[_Request]] = {}
        order = []
        for r in batch:
            key = id(r.session)
            if key not in groups:
                groups[key] = []
                order.append(r.session)
            groups[key].append(r)
        deferred: list = []
        stackable: dict[int, list] = {}
        plan_order = []
        for session in order:
            reqs = groups[id(session)]
            if (self.stack_sessions and not session.plan.batched
                    and session._upd is None):
                pk = id(session.plan)
                if pk not in stackable:
                    stackable[pk] = []
                    plan_order.append(session.plan)
                stackable[pk].append((session, reqs))
            else:
                deferred += self._dispatch_session(session, reqs,
                                                   may_defer)
        for plan in plan_order:
            entries = stackable[id(plan)]
            if len(entries) == 1:
                deferred += self._dispatch_session(*entries[0], may_defer)
            else:
                self._dispatch_stacked(plan, entries)
        return deferred

    def _dispatch_session(self, session, reqs,
                          may_defer: bool = False) -> list:
        """Per-session coalescing: concatenate RHS columns up to the
        width cap and run each chunk through `session.solve` (which
        already buckets, pads, shards, and counts). Returns the deferred
        remainder (at most one small chunk, each request deferred at most
        once — the latency cost is bounded by one extra window)."""
        chunks: list[list[_Request]] = []
        chunk: list[_Request] = []
        width = 0
        for r in reqs:
            if chunk and width + r.width > self.max_coalesce_width:
                chunks.append(chunk)
                chunk, width = [], 0
            chunk.append(r)
            width += r.width
        deferred: list = []
        if chunk:
            if (may_defer and width <= self.max_coalesce_width // 2
                    and not any(r.carried for r in chunk)):
                for r in chunk:
                    r.carried = True
                deferred = chunk
            else:
                chunks.append(chunk)
        for c in chunks:
            self._run_chunk(session, c)
        return deferred

    def _stage(self, reqs):
        """Host-stage a session chunk: memcpy every request's columns
        into ONE bucket-width buffer (zero-padded — exactly the padding
        `SolveSession.solve` would add, so answers stay bitwise). A numpy
        buffer keeps staging off the device and, crucially, off the
        compiler: the device sees one transfer of one already-bucketed
        shape, never a fresh concatenate signature. Returns (buf, spec)
        with spec the (request, stack-slot, column-offset) scatter plan
        for the drain thread."""
        W = sum(r.width for r in reqs)
        wb = rank_bucket(W)
        lead = reqs[0].b2.shape[:-1]
        buf = np.zeros(lead + (wb,), reqs[0].b2.dtype)
        spec = []
        lo = 0
        for r in reqs:
            buf[..., lo:lo + r.width] = r.b2
            spec.append((r, None, lo))
            lo += r.width
        return buf, spec

    def _run_chunk(self, session, reqs) -> None:
        try:
            buf, spec = self._stage(reqs)
            x = session.solve(buf)
        except Exception as e:  # noqa: BLE001 — engine must survive
            self._fail(reqs, e)
            return
        with self._lock:
            self._batches += 1
            self._coalesced_requests += len(reqs)
        self._outq.put((spec, x))

    def _dispatch_stacked(self, plan, entries) -> None:
        """Cross-session coalescing for single-system plans: per-session
        RHS concat first (width-capped; overflow falls back to per-session
        dispatch), then up to `max_stack` sessions stack factors along a
        new leading axis into one vmapped dispatch."""
        ready = []
        for session, reqs in entries:
            chunk: list[_Request] = []
            width = 0
            rest: list[_Request] = []
            for r in reqs:
                if not rest and (not chunk or width + r.width
                                 <= self.max_coalesce_width):
                    chunk.append(r)
                    width += r.width
                else:
                    rest.append(r)
            ready.append((session, chunk, width))
            if rest:
                self._dispatch_session(session, rest)
        for i in range(0, len(ready), self.max_stack):
            part = ready[i:i + self.max_stack]
            if len(part) == 1:
                self._run_chunk(part[0][0], part[0][1])
            else:
                self._run_stack(plan, part)

    def _run_stack(self, plan, part) -> None:
        reqs_all = [r for _, reqs, _ in part for r in reqs]
        try:
            wb = rank_bucket(max(w for _, _, w in part))
            sb = rank_bucket(len(part))
            # host-stage the whole stack in one (sb, N, wb) buffer; the
            # pad slots repeat session 0's factors against zero columns
            buf = np.zeros((sb, plan.N, wb),
                           part[0][1][0].b2.dtype)
            spec = []
            factors, As = [], []
            for si, (session, reqs, _w) in enumerate(part):
                lo = 0
                for r in reqs:
                    buf[si, :, lo:lo + r.width] = r.b2
                    spec.append((r, si, lo))
                    lo += r.width
                factors.append(session._factors)
                As.append(session._A)
            while len(factors) < sb:
                factors.append(factors[0])
                As.append(As[0])
            F = stack_trees(factors)
            A = None if As[0] is None else jnp.stack(As)
            with profiler.region("serve.solve"):
                X = plan._stacked_solve_fn(sb, wb)(F, A, buf)
        except Exception as e:  # noqa: BLE001
            self._fail(reqs_all, e)
            return
        for session, _reqs, _w in part:
            session.solves += 1
        with self._lock:
            self._batches += 1
            self._coalesced_requests += len(reqs_all)
        self._outq.put((spec, X))

    def _fail(self, reqs, exc: Exception) -> None:
        with self._lock:
            self._pending -= len(reqs)
            self._failed += len(reqs)
            self._not_full.notify_all()
        for r in reqs:
            r.future.set_exception(exc)

    # ------------------------------------------------------------------ #
    # drain: the only thread that blocks on device work
    # ------------------------------------------------------------------ #

    def _drain_loop(self) -> None:
        import numpy as np

        while True:
            item = self._outq.get()
            if item is _STOP:
                break
            spec, block_on = item
            try:
                # ONE blocking device->host copy per coalesced batch; the
                # per-request scatter is numpy views of it, so answering N
                # requests costs zero extra device dispatches
                xh = np.asarray(block_on)
            except Exception as e:  # noqa: BLE001
                self._fail([r for r, _si, _lo in spec], e)
                continue
            now = time.perf_counter()
            with self._lock:
                for r, _si, _lo in spec:
                    self._latencies.append(now - r.t_submit)
                self._pending -= len(spec)
                self._completed += len(spec)
                self._not_full.notify_all()
            for r, si, lo in spec:
                xs = (xh[..., lo:lo + r.width] if si is None
                      else xh[si, :, lo:lo + r.width])
                if r.squeeze:
                    xs = xs[..., 0]
                r.future.set_result(xs)

    # ------------------------------------------------------------------ #
    # observability (merged into profiler.serve_stats()['engine'])
    # ------------------------------------------------------------------ #

    def stats(self) -> dict:
        """Engine counters: queue depth high-water mark, batches
        dispatched, mean coalesced batch size, shed count, and
        p50/p95/p99 request latency over the rolling window."""
        with self._lock:
            lats = sorted(self._latencies)
            batches = self._batches
            return {
                "pending": self._pending,
                "queue_peak": self._queue_peak,
                "requests": self._requests,
                "completed": self._completed,
                "failed": self._failed,
                "shed": self._sheds,
                "batches": batches,
                "coalesced_requests": self._coalesced_requests,
                "coalesced_mean": (self._coalesced_requests / batches
                                   if batches else 0.0),
                "latency_p50_ms": 1e3 * _percentile(lats, 50),
                "latency_p95_ms": 1e3 * _percentile(lats, 95),
                "latency_p99_ms": 1e3 * _percentile(lats, 99),
            }

    def latency_samples(self) -> list:
        """The rolling latency window in seconds (profiler merges these
        across engines for fleet-wide percentiles)."""
        with self._lock:
            return list(self._latencies)
